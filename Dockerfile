# chunkflow-tpu worker image.
# Parity target: reference Dockerfile + docker/ (ubuntu + python + CUDA
# torch); here the accelerator stack is JAX/TPU, which needs no CUDA base —
# TPU runtime libraries are injected by the TPU VM host.
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make cmake ninja-build \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/chunkflow-tpu
COPY pyproject.toml ./
COPY chunkflow_tpu ./chunkflow_tpu

# TPU wheels: libtpu comes from the TPU VM; jax[tpu] resolves the rest
RUN pip install --no-cache-dir "jax[tpu]" -f \
        https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir .

# build the native host-side kernels (cc3d / watershed / surface-nets)
RUN python -c "from chunkflow_tpu import native; native.build()"

ENTRYPOINT ["python", "-m", "chunkflow_tpu.flow.cli"]
