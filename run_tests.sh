#!/bin/bash
# CI entry point: graftlint gate, then the test suite on a clean 8-device
# virtual CPU mesh.
# PALLAS_AXON_POOL_IPS must be unset: with it set, the TPU-tunnel site hook
# intercepts every jax init, slowing CPU tests ~20x and wedging the
# single-client tunnel if tests run concurrently with TPU work.
set -u
cd "$(dirname "$0")"

# --- static analysis gate -------------------------------------------------
# graftlint (tools/graftlint, docs/linting.md) fails only on findings NOT
# grandfathered in tools/graftlint/baseline.json. Skip with
# CHUNKFLOW_SKIP_LINT=1 (e.g. when iterating on a single test).
if [ "${CHUNKFLOW_SKIP_LINT:-0}" != "1" ]; then
    echo "== graftlint gate =="
    python -m tools.graftlint || exit 1
fi

# --- tests ----------------------------------------------------------------
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/ "$@"
