#!/bin/bash
# CI entry point: graftlint gate, then the test suite on a clean 8-device
# virtual CPU mesh. Prints per-stage wall time so tier-1 latency creep is
# visible in every CI log.
# PALLAS_AXON_POOL_IPS must be unset: with it set, the TPU-tunnel site hook
# intercepts every jax init, slowing CPU tests ~20x and wedging the
# single-client tunnel if tests run concurrently with TPU work.
set -u
cd "$(dirname "$0")"

stage_start=$SECONDS
stage_time() {
    echo "== stage '$1' took $((SECONDS - stage_start))s =="
    stage_start=$SECONDS
}

# --- baseline guard -------------------------------------------------------
# The graftlint baseline was emptied in PR 2 (all GL005 donate_argnums
# findings fixed) and has stayed empty through the GL010-series
# concurrency rules (ISSUE 10) and the GL020-series Pallas kernel rules
# (ISSUE 16): any entry reappearing — for ANY rule, and a GL010+/GL020+
# key especially, since every real concurrency or kernel-soundness hit
# was fixed or inline-annotated, never grandfathered — means someone
# re-grandfathered a finding instead of fixing it. Fail loudly
# (docs/linting.md).
python - <<'EOF' || exit 1
import json, sys
with open("tools/graftlint/baseline.json") as f:
    findings = json.load(f).get("findings", {})
if findings:
    concurrency = [k for k in findings if "::GL01" in k]
    pallas = [k for k in findings if "::GL02" in k]
    print(
        f"graftlint baseline is not empty ({len(findings)} grandfathered "
        f"finding(s), {len(concurrency)} from the GL010-series, "
        f"{len(pallas)} from the GL020-series); fix the "
        "findings instead of re-grandfathering them (docs/linting.md)",
        file=sys.stderr,
    )
    sys.exit(1)
EOF
stage_time "baseline guard"

# --- static analysis gate -------------------------------------------------
# graftlint (tools/graftlint, docs/linting.md) fails on any finding not in
# the (empty) baseline; --stats prints the per-rule-family hit counts so
# the CI log shows which families (jit vs concurrency) carry weight.
# Warm runs are served from .graftlint_cache/ (content-hash keyed). Skip
# with CHUNKFLOW_SKIP_LINT=1 (e.g. when iterating on a single test).
if [ "${CHUNKFLOW_SKIP_LINT:-0}" != "1" ]; then
    echo "== graftlint gate =="
    python -m tools.graftlint --stats || exit 1
    stage_time "graftlint"
fi

# --- tests ----------------------------------------------------------------
# CHUNKFLOW_LOCKSMITH defaults ON for the suite (tests/conftest.py): every
# Lock/Condition the codebase creates is proxied and lock-order cycles
# raise in place, so the chaos/acceptance tests double as concurrency
# tests (docs/linting.md "Concurrency lint"). CHUNKFLOW_LOCKSMITH=0
# switches the sanitizer off wholesale.
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    CHUNKFLOW_LOCKSMITH="${CHUNKFLOW_LOCKSMITH:-1}" \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/ "$@"
rc=$?
stage_time "pytest"

# --- locksmith overhead gate ------------------------------------------------
# Sanitizer-on vs -off wall time over the e2e_overlap scheduled workload
# (docs/observability.md "Locksmith"). The JSON line reports the <5%
# target as gate_pass; the process only fails past 25% (a pathological
# proxy-hot-path regression), so shared-box noise cannot redden CI. The
# run also proves the full scheduled path is lock-order clean (a
# violation raises and fails the stage).
echo "== locksmith overhead gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py locksmith_overhead --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "locksmith overhead gate"

# --- kernelcheck overhead gate ----------------------------------------------
# Kernel-sanitizer-on vs -off wall time over the interpret-mode Pallas
# parity legs (docs/linting.md "Runtime kernel sanitizer"). The JSON
# line reports the <5% target as gate_pass; the process only fails past
# 25% (the sanitizer landed work somewhere hot), so shared-box noise
# cannot redden CI. The on leg also proves a clean workload raises no
# violation (the tier-1 no-false-positives contract).
echo "== kernelcheck overhead gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py kernelcheck_overhead --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "kernelcheck overhead gate"

# --- telemetry overhead gate ----------------------------------------------
# Telemetry-on vs -off wall time on the pipeline_overlap workload
# (docs/observability.md). The JSON line reports the <2% target as
# gate_pass; the process only fails past 10% (gross regression — a lock
# on the hot path, per-event fsync), so shared-box noise cannot redden CI.
echo "== telemetry overhead gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py telemetry_overhead --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "telemetry overhead gate"

# --- pipeline overlap gate --------------------------------------------------
# Serial vs double-buffered executor on the synthetic chunk workload
# (docs/performance.md). The in-suite copy of this ratio gate is marked
# slow/bench (it flips under full-suite load on a 1-core box — ISSUE 7
# satellite); this standalone run, on a quiet interpreter, is the gate
# of record. The run itself raises on bit-divergence.
echo "== pipeline overlap gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py pipeline_overlap --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "pipeline overlap gate"

# --- e2e overlap gate ------------------------------------------------------
# Serial vs adaptive-scheduler wall time over the full task lifecycle
# (load → compute → post → write, docs/performance.md "Adaptive
# scheduler"). Reports the >=1.4x target as gate_pass (asserted
# best-of-3 in tests/test_bench.py); the process only fails below 1.1x.
echo "== e2e overlap gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py e2e_overlap --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "e2e overlap gate"

# --- resilience overhead gate ----------------------------------------------
# Fault-tolerance layer on-vs-off over the e2e_overlap workload
# (docs/fault_tolerance.md): supervised claims + completion ledger +
# lease heartbeat must cost < 3% wall-clock (reported as gate_pass);
# the process only fails past 15% (a lock/fsync landed on the per-task
# hot path), so shared-box noise cannot redden CI.
echo "== resilience overhead gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py resilience_overhead --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "resilience overhead gate"

# --- export overhead gate ---------------------------------------------------
# Live /metrics exporter on-vs-off over the e2e_overlap workload, scraped
# continuously while tasks flow (docs/observability.md "Fleet view"):
# serving registry snapshots must cost < 2% wall-clock (reported as
# gate_pass); the process only fails past 10% (a lock landed on the
# per-task hot path), so shared-box noise cannot redden CI.
echo "== export overhead gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py export_overhead --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "export overhead gate"

# --- fleet chaos smoke ------------------------------------------------------
# A REAL multi-process fleet (parallel/fleet.py) drains a small volume
# while one worker is SIGKILLed mid-run and one spot-drill preemption
# fires (docs/fault_tolerance.md "Running a fleet"). Binary gate: the
# run either converges — every task committed exactly once, queue
# clean — or the process exits nonzero.
echo "== fleet chaos smoke =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py fleet_smoke --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "fleet chaos smoke"

# --- trace export overhead gate ----------------------------------------------
# Perfetto/Chrome-trace exporter (tools/trace_export.py) pinned on a
# large synthetic multi-worker stream with injected clock skew
# (docs/observability.md "Timeline view"). The run raises unless the
# exported trace validates clean and every cross-worker flow survives;
# reports the >=50k events/s soft floor as gate_pass; the process only
# fails below 5k events/s (an algorithmic regression, not box noise).
# The fleet chaos smoke above already round-trips its REAL acceptance
# JSONL through the same exporter + validator.
echo "== trace export overhead gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py trace_export_overhead --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "trace export overhead gate"

# --- serving throughput gate -------------------------------------------------
# Packed cross-request batching vs sequential per-chunk execution on many
# small concurrent requests (docs/serving.md). Reports the >=1.3x target
# as gate_pass (asserted slow-marked in tests/test_bench.py); the process
# only fails below 1.1x. The run itself raises on any bit-divergence
# between the packed and per-chunk paths.
echo "== serving throughput gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py serving_throughput --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "serving throughput gate"

# --- storage throughput gate -------------------------------------------------
# Serial uncached reads vs concurrent block reads + hot block cache on an
# overlapping-halo cutout grid (docs/storage.md). Reports the >=1.3x
# target as gate_pass (asserted slow-marked in tests/test_bench.py); the
# process only fails below 1.1x. The run itself raises on any
# bit-divergence between the serial, concurrent and cached legs.
echo "== storage throughput gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py storage_throughput --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "storage throughput gate"

# --- segmentation stitch gate -------------------------------------------------
# Stitched map->reduce->map whole-volume labeling vs one monolithic pass
# against latency-charged storage (docs/segmentation.md). Reports the
# >=1.3x target as gate_pass (asserted best-of-3 in tests/test_bench.py);
# the process only fails below 1.1x. The run itself raises unless the
# stitched output is label-isomorphic to the monolithic labeling.
echo "== segmentation stitch gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py segmentation_stitch --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "segmentation stitch gate"

# --- slo overhead gate --------------------------------------------------------
# Time-series sampler + burn-rate evaluator on-vs-off over the e2e
# scheduled workload (docs/observability.md "SLO view"): the SLO plane
# must cost < 2% wall-clock on top of plain telemetry (reported as
# gate_pass); the process only fails past 10% (sampling work landed on
# the per-task hot path), so shared-box noise cannot redden CI. The on
# leg also asserts the plane actually sampled and that a healthy
# workload fires no alert.
echo "== slo overhead gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py slo_overhead --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "slo overhead gate"

# --- multichip overlap gate ---------------------------------------------------
# Unified sharded engine (CHUNKFLOW_MESH=data=8) vs the single-device
# reference path on 8 simulated host devices (docs/multichip.md). The
# run asserts bitwise identity between the legs and that the sharded
# program landed in the roofline ledger; reports the >=1.3x target as
# gate_pass (asserted slow-marked in tests/test_bench.py); the process
# only fails below 1.1x.
echo "== multichip overlap gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py multichip_overlap --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "multichip overlap gate"

# --- sharded replay gate ------------------------------------------------------
# Sharded blend replay (per-slab rosters + ppermute fringe exchange)
# vs replicated replay on the same 8-device spatial mesh, blend-
# dominated identity proxy (docs/multichip.md "Sharded blend replay").
# The run asserts bitwise identity of BOTH legs against the
# single-device reference and that the sharded program landed in the
# roofline ledger; reports the >=1.3x target as gate_pass (asserted
# slow-marked in tests/test_bench.py); the process only fails below
# 1.1x.
echo "== sharded replay gate =="
env -u PALLAS_AXON_POOL_IPS -u CHUNKFLOW_SHARD_REPLAY JAX_PLATFORMS=cpu \
    python bench.py multichip_sharded_replay --ledger \
    || rc=$((rc == 0 ? 1 : rc))
stage_time "sharded replay gate"

# --- fused blend gate ---------------------------------------------------------
# Fused blend data movement (weighting + aligned-window placement + RMW in
# one pass) vs the separate-leg structure it replaced, as compiled XLA
# proxies of both structures (docs/performance.md "The fused Pallas blend
# kernel"). The run asserts bit-identity across both proxies, the XLA
# scatter reference AND the real fused Pallas kernel in interpret mode,
# and that both legs carry roofline rows in programs.json; reports the
# >=1.2x target as gate_pass (asserted slow-marked in tests/test_bench.py);
# the process only fails below 1.1x.
echo "== fused blend gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py blend_fused --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "fused blend gate"

# Device-resident front half (raw chunk uploaded once, convert+gather on
# device) vs the host gather+convert+re-upload structure it replaced
# (docs/performance.md "The device-resident front half"). The run asserts
# bit-identity across both legs AND the real Pallas gather kernel in
# interpret mode, and that both legs carry roofline rows in
# programs.json; reports the >=1.2x target as gate_pass (asserted
# slow-marked in tests/test_bench.py); the process only fails below 1.1x.
echo "== front half gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py front_half --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "front half gate"

# Fused patch pipeline (ISSUE 17): the per-bucket serving structure with
# device-resident weighted stacks (one upload, donated on-device overlay,
# one scatter) vs the host round-trip structure it replaced (per-batch
# download, host stack, wholesale re-upload), as compiled proxies of both
# structures (docs/performance.md "The fused patch pipeline"). The run
# asserts bit-identity across both proxies AND the composed real Pallas
# kernels (gather -> forward -> fused blend) in interpret mode, and that
# both legs carry roofline rows in programs.json; reports the >=1.2x
# target as gate_pass (asserted slow-marked in tests/test_bench.py); the
# process only fails below 1.1x.
echo "== fused pipeline gate =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py fused_pipeline --ledger || rc=$((rc == 0 ? 1 : rc))
stage_time "fused pipeline gate"

# --- bench regression ledger ------------------------------------------------
# Every gate above appended its measurement (commit-stamped) to
# telemetry/bench_ledger.jsonl; compare diffs this run against the
# rolling median of prior FRESH rows (cached: rows loudly refused as
# baselines). Soft gate on this load-sensitive 1-core box: compare
# itself exits nonzero only on a >25% fresh-vs-fresh regression of a
# throughput/speedup metric (docs/observability.md "Device program
# view" — bench-ledger cookbook).
echo "== bench regression ledger compare =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python bench.py compare || rc=$((rc == 0 ? 1 : rc))
stage_time "bench ledger compare"
exit $rc
