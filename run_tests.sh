#!/bin/bash
# Run the test suite on a clean 8-device virtual CPU mesh.
# PALLAS_AXON_POOL_IPS must be unset: with it set, the TPU-tunnel site hook
# intercepts every jax init, slowing CPU tests ~20x and wedging the
# single-client tunnel if tests run concurrently with TPU work.
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/ "$@"
