import numpy as np
import pytest

ts = pytest.importorskip("tensorstore")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.volume.precomputed import (
    PrecomputedVolume,
    load_chunk_or_volume,
)


@pytest.fixture
def vol(tmp_path):
    return PrecomputedVolume.create(
        str(tmp_path / "vol"),
        volume_size=(64, 64, 64),
        voxel_size=(40, 4, 4),
        voxel_offset=(0, 0, 0),
        dtype="uint8",
        block_size=(32, 32, 32),
    )


def test_create_metadata(vol):
    assert vol.num_mips == 1
    assert vol.dtype == np.uint8
    assert vol.voxel_size(0) == Cartesian(40, 4, 4)
    assert vol.volume_size(0) == Cartesian(64, 64, 64)
    assert vol.block_size(0) == Cartesian(32, 32, 32)


def test_save_cutout_roundtrip(vol):
    chunk = Chunk.create((64, 64, 64), dtype=np.uint8, voxel_size=(40, 4, 4))
    vol.save(chunk)
    out = vol.cutout(BoundingBox((0, 0, 0), (64, 64, 64)))
    np.testing.assert_array_equal(np.asarray(out.array), np.asarray(chunk.array))
    assert out.voxel_size == Cartesian(40, 4, 4)

    # windowed read keeps global coordinates
    window = BoundingBox((10, 20, 30), (20, 40, 50))
    sub = vol.cutout(window)
    assert sub.voxel_offset == window.start
    np.testing.assert_array_equal(
        np.asarray(sub.array), np.asarray(chunk.cutout(window).array)
    )


def test_zyx_xyz_transpose_is_correct(vol):
    """An asymmetric pattern must land transposed in xyz storage."""
    arr = np.zeros((64, 64, 64), dtype=np.uint8)
    arr[1, 2, 3] = 77  # z=1, y=2, x=3
    vol.save(Chunk(arr, voxel_size=(40, 4, 4)))
    store = vol._store(0)
    raw = store[3, 2, 1, 0].read().result()  # x, y, z, channel
    assert int(raw) == 77


def test_has_all_blocks(vol):
    chunk = Chunk.create((32, 32, 32), dtype=np.uint8, voxel_size=(40, 4, 4))
    bbox = BoundingBox((0, 0, 0), (32, 32, 32))
    assert not vol.has_all_blocks(bbox)
    vol.save(chunk)
    assert vol.has_all_blocks(bbox)
    assert not vol.has_all_blocks(BoundingBox((0, 0, 0), (64, 64, 64)))


def test_multichannel_volume(tmp_path):
    rng = np.random.default_rng(0)
    aff = Chunk(rng.random((3, 16, 16, 16)).astype(np.float32))
    vol = PrecomputedVolume.from_chunk(
        aff, str(tmp_path / "aff"), block_size=(8, 8, 8)
    )
    assert vol.num_channels == 3
    out = vol.cutout(BoundingBox((0, 0, 0), (16, 16, 16)))
    assert out.shape == (3, 16, 16, 16)
    np.testing.assert_allclose(np.asarray(out.array), np.asarray(aff.array))


def test_mip_pyramid_metadata(tmp_path):
    vol = PrecomputedVolume.create(
        str(tmp_path / "pyr"),
        volume_size=(64, 64, 64),
        voxel_size=(40, 4, 4),
        num_mips=3,
        downsample_factor=(1, 2, 2),
    )
    assert vol.num_mips == 3
    assert vol.voxel_size(1) == Cartesian(40, 8, 8)
    assert vol.volume_size(2) == Cartesian(64, 16, 16)


def test_load_chunk_or_volume(tmp_path, vol):
    chunk = Chunk.create((8, 8, 8))
    h5 = str(tmp_path / "c.h5")
    chunk.to_h5(h5)
    loaded = load_chunk_or_volume(h5)
    assert isinstance(loaded, Chunk)
    v = load_chunk_or_volume(vol.path)
    assert isinstance(v, PrecomputedVolume)


def test_volume_reference_api_surface(tmp_path):
    """Reference drop-in spellings (reference volume.py:74-121):
    from_numpy, bounding_box/bbox/start/stop/shape, block boxes,
    physical box."""
    pytest.importorskip("tensorstore")
    import numpy as np

    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    arr = np.arange(8 * 16 * 16, dtype=np.uint32).reshape(8, 16, 16)
    vol = PrecomputedVolume.from_numpy(
        arr, str(tmp_path / "v"), block_size=(8, 8, 8)
    )
    # reference shape includes the channel dim (volume.py:137)
    assert tuple(vol.shape) == (1, 8, 16, 16)
    assert vol.bounding_box == vol.bbox
    assert tuple(vol.start) == (0, 0, 0) and tuple(vol.stop) == (8, 16, 16)
    blocks = vol.block_bounding_boxes
    assert len(blocks) == 4
    assert all(vol.bounding_box.contains(b) for b in blocks)
    assert tuple(vol.physical_bounding_box.voxel_size) == tuple(vol.voxel_size(0))
    back = np.asarray(vol.cutout(vol.bounding_box).array)
    assert (back == arr).all()


def test_save_dtype_auto_convert(tmp_path):
    """Reference _auto_convert_dtype semantics (save_precomputed.py:84-102):
    float [0,1] chunks scale to full-range uint8 volumes (x255, truncating)
    and uint8 chunks scale down into float volumes (/255)."""
    pytest.importorskip("tensorstore")
    import numpy as np

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "u8vol"
    vol = PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    rng = np.random.default_rng(0)
    data = rng.random((8, 16, 16)).astype(np.float32)
    from chunkflow_tpu.core.bbox import BoundingBox

    vol.save(Chunk(data))
    back = vol.cutout(BoundingBox.from_delta((0, 0, 0), (8, 16, 16)))
    want = (data * 255.0).astype(np.uint8)
    np.testing.assert_array_equal(np.asarray(back.array), want)

    froot = tmp_path / "f32vol"
    fvol = PrecomputedVolume.create(
        str(froot), volume_size=(8, 16, 16), dtype="float32",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    u8 = (data * 255).astype(np.uint8)
    fvol.save(Chunk(u8))
    fback = fvol.cutout(BoundingBox.from_delta((0, 0, 0), (8, 16, 16)))
    np.testing.assert_allclose(
        np.asarray(fback.array), u8.astype(np.float32) / 255.0, atol=1e-6)


def test_save_async_future_and_barrier(tmp_path):
    """wait=False returns a write future; data is durable after
    .result() and matches the sync path."""
    import numpy as np

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "avol"
    vol = PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="float32",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    rng = np.random.default_rng(1)
    data = rng.random((8, 16, 16)).astype(np.float32)
    future = vol.save(Chunk(data), wait=False)
    assert future is not None
    future.result()
    back = vol.cutout(BoundingBox.from_delta((0, 0, 0), (8, 16, 16)))
    np.testing.assert_allclose(np.asarray(back.array), data, atol=1e-6)


# ---------------------------------------------------------------------------
# ISSUE 11: the storage plane under PrecomputedVolume
# ---------------------------------------------------------------------------
@pytest.fixture(autouse=True)
def _fresh_storage_plane():
    from chunkflow_tpu.volume import storage

    storage.reset_shared_cache()
    yield
    storage.reset_shared_cache()


@pytest.mark.parametrize("dtype,channels", [
    ("uint8", 1), ("uint16", 1), ("float32", 1), ("float32", 3),
])
def test_concurrent_cutout_bit_identical_to_serial(
        tmp_path, monkeypatch, dtype, channels):
    """Acceptance: concurrent cached cutouts are bit-identical to the
    serial uncached reference read, on ragged grids including the
    channel dim, for uint8/uint16/float32."""
    rng = np.random.default_rng(3)
    shape = (channels, 24, 40, 56) if channels > 1 else (24, 40, 56)
    arr = (rng.random(shape) * 200).astype(dtype) + 1
    vol = PrecomputedVolume.from_chunk(
        Chunk(arr.astype(dtype)), str(tmp_path / "v"),
        block_size=(16, 16, 16),
    )
    windows = [
        BoundingBox((0, 0, 0), (24, 40, 56)),   # whole (ragged blocks)
        BoundingBox((3, 5, 7), (21, 39, 55)),   # nothing aligned
        BoundingBox((16, 16, 16), (24, 32, 32)),
        BoundingBox((23, 39, 55), (24, 40, 56)),  # trailing voxel
    ]
    for window in windows:
        monkeypatch.setenv("CHUNKFLOW_STORAGE", "serial")
        ref = vol.cutout(window)
        monkeypatch.setenv("CHUNKFLOW_STORAGE", "concurrent")
        cold = vol.cutout(window)
        hot = vol.cutout(window)  # cache-served repeat
        np.testing.assert_array_equal(
            np.asarray(cold.array), np.asarray(ref.array))
        np.testing.assert_array_equal(
            np.asarray(hot.array), np.asarray(ref.array))
        assert cold.dtype == np.dtype(dtype)


def test_read_after_write_through_cache(tmp_path):
    """Acceptance: read-after-write through the cache returns the
    written bytes — for aligned writes even if storage is later poked
    out-of-band (the blocks are cache-served write-through)."""
    from chunkflow_tpu.volume.storage import shared_cache

    vol = PrecomputedVolume.create(
        str(tmp_path / "v"), volume_size=(32, 32, 32), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(16, 16, 16),
    )
    rng = np.random.default_rng(4)
    data = rng.integers(1, 255, size=(32, 32, 32), dtype=np.uint8)
    vol.save(Chunk(data))
    assert shared_cache() is not None and len(shared_cache()) > 0
    # poke storage behind the cache's back: the aligned write must be
    # cache-served, proving read-after-write comes from the written bytes
    vol._store(0)[0:32, 0:32, 0:32, 0:1].write(
        np.zeros((32, 32, 32, 1), dtype=np.uint8)).result()
    out = vol.cutout(BoundingBox((0, 0, 0), (32, 32, 32)))
    np.testing.assert_array_equal(np.asarray(out.array), data)
    # an UNALIGNED overwrite invalidates: the next read sees storage
    patch = np.full((8, 8, 8), 9, dtype=np.uint8)
    vol.save(Chunk(patch, voxel_offset=(4, 4, 4)))
    out = vol.cutout(BoundingBox((4, 4, 4), (12, 12, 12)))
    np.testing.assert_array_equal(np.asarray(out.array), patch)


def test_save_uint16_roundtrip(tmp_path):
    """uint16 passes through the dtype auto-conversion untouched."""
    vol = PrecomputedVolume.create(
        str(tmp_path / "v16"), volume_size=(16, 16, 16), dtype="uint16",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    rng = np.random.default_rng(5)
    data = rng.integers(0, 65535, size=(16, 16, 16), dtype=np.uint16)
    vol.save(Chunk(data))
    out = vol.cutout(BoundingBox((0, 0, 0), (16, 16, 16)))
    assert out.dtype == np.uint16
    np.testing.assert_array_equal(np.asarray(out.array), data)


def test_save_float_clip_path_roundtrip(tmp_path):
    """The float->uint8 clip path (reference latent-bug fix): values
    outside [0,1] clip instead of wrapping on the truncating astype."""
    vol = PrecomputedVolume.create(
        str(tmp_path / "vclip"), volume_size=(8, 8, 8), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    data = np.array([-0.5, 0.0, 0.25, 0.999, 1.0, 1.5, 100.0, 0.5],
                    dtype=np.float32).reshape(1, 1, 8)
    full = np.tile(data, (8, 8, 1))
    vol.save(Chunk(full))
    out = vol.cutout(BoundingBox((0, 0, 0), (8, 8, 8)))
    want = (np.clip(full, 0.0, 1.0) * 255.0).astype(np.uint8)
    np.testing.assert_array_equal(np.asarray(out.array), want)


def test_strict_read_through_concurrent_path(tmp_path):
    """fill_missing=False must stay strict through the new concurrent
    cutout path: raise while any covering block is absent, then read
    bit-identically to serial once all blocks exist."""
    vol = PrecomputedVolume.create(
        str(tmp_path / "vs"), volume_size=(32, 32, 32), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(16, 16, 16),
    )
    chunk = Chunk.create((16, 32, 32), dtype=np.uint8)
    vol.save(Chunk(np.asarray(chunk.array)))  # top half only
    with pytest.raises(FileNotFoundError):
        vol.cutout(BoundingBox((0, 0, 0), (32, 32, 32)),
                   fill_missing=False)
    ok = vol.cutout(BoundingBox((0, 0, 0), (16, 32, 32)),
                    fill_missing=False)
    np.testing.assert_array_equal(
        np.asarray(ok.array), np.asarray(chunk.array))


def test_kv_handle_opened_once_and_cached(tmp_path, vol):
    """Satellite: info/read_json/has_all_blocks share ONE cached KV
    handle instead of reopening a store per call."""
    kv_first = vol.kv
    assert vol.info is not None
    assert vol.read_json("nope.json") is None
    vol.has_all_blocks(BoundingBox((0, 0, 0), (32, 32, 32)))
    assert vol.kv is kv_first


def test_has_all_blocks_remote_path_is_batched(tmp_path):
    """Satellite: the remote existence check goes through the batched
    TensorStoreKV.exists_many (key listing), not per-name full-value
    downloads — forced here by installing the remote KV plane over the
    file root."""
    from chunkflow_tpu.volume.storage import TensorStoreKV

    vol = PrecomputedVolume.create(
        str(tmp_path / "vr"), volume_size=(32, 32, 32), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(16, 16, 16),
    )
    vol.save(Chunk.create((16, 32, 32), dtype=np.uint8))
    vol._kv = TensorStoreKV(vol.kvstore)  # the remote code path
    assert vol.has_all_blocks(BoundingBox((0, 0, 0), (16, 32, 32)))
    assert not vol.has_all_blocks(BoundingBox((0, 0, 0), (32, 32, 32)))
