import numpy as np
import pytest

ts = pytest.importorskip("tensorstore")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.volume.precomputed import (
    PrecomputedVolume,
    load_chunk_or_volume,
)


@pytest.fixture
def vol(tmp_path):
    return PrecomputedVolume.create(
        str(tmp_path / "vol"),
        volume_size=(64, 64, 64),
        voxel_size=(40, 4, 4),
        voxel_offset=(0, 0, 0),
        dtype="uint8",
        block_size=(32, 32, 32),
    )


def test_create_metadata(vol):
    assert vol.num_mips == 1
    assert vol.dtype == np.uint8
    assert vol.voxel_size(0) == Cartesian(40, 4, 4)
    assert vol.volume_size(0) == Cartesian(64, 64, 64)
    assert vol.block_size(0) == Cartesian(32, 32, 32)


def test_save_cutout_roundtrip(vol):
    chunk = Chunk.create((64, 64, 64), dtype=np.uint8, voxel_size=(40, 4, 4))
    vol.save(chunk)
    out = vol.cutout(BoundingBox((0, 0, 0), (64, 64, 64)))
    np.testing.assert_array_equal(np.asarray(out.array), np.asarray(chunk.array))
    assert out.voxel_size == Cartesian(40, 4, 4)

    # windowed read keeps global coordinates
    window = BoundingBox((10, 20, 30), (20, 40, 50))
    sub = vol.cutout(window)
    assert sub.voxel_offset == window.start
    np.testing.assert_array_equal(
        np.asarray(sub.array), np.asarray(chunk.cutout(window).array)
    )


def test_zyx_xyz_transpose_is_correct(vol):
    """An asymmetric pattern must land transposed in xyz storage."""
    arr = np.zeros((64, 64, 64), dtype=np.uint8)
    arr[1, 2, 3] = 77  # z=1, y=2, x=3
    vol.save(Chunk(arr, voxel_size=(40, 4, 4)))
    store = vol._store(0)
    raw = store[3, 2, 1, 0].read().result()  # x, y, z, channel
    assert int(raw) == 77


def test_has_all_blocks(vol):
    chunk = Chunk.create((32, 32, 32), dtype=np.uint8, voxel_size=(40, 4, 4))
    bbox = BoundingBox((0, 0, 0), (32, 32, 32))
    assert not vol.has_all_blocks(bbox)
    vol.save(chunk)
    assert vol.has_all_blocks(bbox)
    assert not vol.has_all_blocks(BoundingBox((0, 0, 0), (64, 64, 64)))


def test_multichannel_volume(tmp_path):
    rng = np.random.default_rng(0)
    aff = Chunk(rng.random((3, 16, 16, 16)).astype(np.float32))
    vol = PrecomputedVolume.from_chunk(
        aff, str(tmp_path / "aff"), block_size=(8, 8, 8)
    )
    assert vol.num_channels == 3
    out = vol.cutout(BoundingBox((0, 0, 0), (16, 16, 16)))
    assert out.shape == (3, 16, 16, 16)
    np.testing.assert_allclose(np.asarray(out.array), np.asarray(aff.array))


def test_mip_pyramid_metadata(tmp_path):
    vol = PrecomputedVolume.create(
        str(tmp_path / "pyr"),
        volume_size=(64, 64, 64),
        voxel_size=(40, 4, 4),
        num_mips=3,
        downsample_factor=(1, 2, 2),
    )
    assert vol.num_mips == 3
    assert vol.voxel_size(1) == Cartesian(40, 8, 8)
    assert vol.volume_size(2) == Cartesian(64, 16, 16)


def test_load_chunk_or_volume(tmp_path, vol):
    chunk = Chunk.create((8, 8, 8))
    h5 = str(tmp_path / "c.h5")
    chunk.to_h5(h5)
    loaded = load_chunk_or_volume(h5)
    assert isinstance(loaded, Chunk)
    v = load_chunk_or_volume(vol.path)
    assert isinstance(v, PrecomputedVolume)


def test_volume_reference_api_surface(tmp_path):
    """Reference drop-in spellings (reference volume.py:74-121):
    from_numpy, bounding_box/bbox/start/stop/shape, block boxes,
    physical box."""
    pytest.importorskip("tensorstore")
    import numpy as np

    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    arr = np.arange(8 * 16 * 16, dtype=np.uint32).reshape(8, 16, 16)
    vol = PrecomputedVolume.from_numpy(
        arr, str(tmp_path / "v"), block_size=(8, 8, 8)
    )
    # reference shape includes the channel dim (volume.py:137)
    assert tuple(vol.shape) == (1, 8, 16, 16)
    assert vol.bounding_box == vol.bbox
    assert tuple(vol.start) == (0, 0, 0) and tuple(vol.stop) == (8, 16, 16)
    blocks = vol.block_bounding_boxes
    assert len(blocks) == 4
    assert all(vol.bounding_box.contains(b) for b in blocks)
    assert tuple(vol.physical_bounding_box.voxel_size) == tuple(vol.voxel_size(0))
    back = np.asarray(vol.cutout(vol.bounding_box).array)
    assert (back == arr).all()


def test_save_dtype_auto_convert(tmp_path):
    """Reference _auto_convert_dtype semantics (save_precomputed.py:84-102):
    float [0,1] chunks scale to full-range uint8 volumes (x255, truncating)
    and uint8 chunks scale down into float volumes (/255)."""
    pytest.importorskip("tensorstore")
    import numpy as np

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "u8vol"
    vol = PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    rng = np.random.default_rng(0)
    data = rng.random((8, 16, 16)).astype(np.float32)
    from chunkflow_tpu.core.bbox import BoundingBox

    vol.save(Chunk(data))
    back = vol.cutout(BoundingBox.from_delta((0, 0, 0), (8, 16, 16)))
    want = (data * 255.0).astype(np.uint8)
    np.testing.assert_array_equal(np.asarray(back.array), want)

    froot = tmp_path / "f32vol"
    fvol = PrecomputedVolume.create(
        str(froot), volume_size=(8, 16, 16), dtype="float32",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    u8 = (data * 255).astype(np.uint8)
    fvol.save(Chunk(u8))
    fback = fvol.cutout(BoundingBox.from_delta((0, 0, 0), (8, 16, 16)))
    np.testing.assert_allclose(
        np.asarray(fback.array), u8.astype(np.float32) / 255.0, atol=1e-6)


def test_save_async_future_and_barrier(tmp_path):
    """wait=False returns a write future; data is durable after
    .result() and matches the sync path."""
    import numpy as np

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "avol"
    vol = PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="float32",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    rng = np.random.default_rng(1)
    data = rng.random((8, 16, 16)).astype(np.float32)
    future = vol.save(Chunk(data), wait=False)
    assert future is not None
    future.result()
    back = vol.cutout(BoundingBox.from_delta((0, 0, 0), (8, 16, 16)))
    np.testing.assert_allclose(np.asarray(back.array), data, atol=1e-6)
