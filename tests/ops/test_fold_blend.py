"""Fold (parity-class dense) overlap-add: equivalence with the scatter
path and with a direct numpy overlap-add."""
import numpy as np
import pytest

from chunkflow_tpu.ops.fold_blend import (
    fold_accumulate,
    fold_grid,
    fold_pad_shape,
)


def _numpy_overlap_add(stack, grid, stride, pout, offset, out_zyx):
    co = stack.shape[1]
    out = np.zeros((co,) + tuple(out_zyx), np.float32)
    idx = 0
    for iz in range(grid[0]):
        for iy in range(grid[1]):
            for ix in range(grid[2]):
                z0 = offset[0] + iz * stride[0]
                y0 = offset[1] + iy * stride[1]
                x0 = offset[2] + ix * stride[2]
                out[:, z0:z0 + pout[0], y0:y0 + pout[1],
                    x0:x0 + pout[2]] += np.asarray(stack[idx])
                idx += 1
    return out


@pytest.mark.parametrize(
    "grid,stride,pout",
    [
        ((3, 2, 2), (4, 12, 12), (8, 16, 16)),   # k=2 per axis
        ((2, 4, 3), (8, 6, 8), (8, 16, 16)),     # kz=1, ky=3, kx=2
        ((1, 1, 5), (4, 16, 5), (4, 16, 12)),    # heavy x overlap, kx=3
    ],
)
def test_fold_accumulate_matches_numpy(grid, stride, pout):
    rng = np.random.default_rng(0)
    n = int(np.prod(grid))
    co = 2
    stack = rng.random((n, co) + pout).astype(np.float32)
    offset = (1, 2, 3)
    out_zyx = tuple(
        offset[i] + (grid[i] - 1) * stride[i] + pout[i] for i in range(3)
    )
    got = np.asarray(
        fold_accumulate(stack, grid, stride, pout, offset, out_zyx)
    )
    want = _numpy_overlap_add(stack, grid, stride, pout, offset, out_zyx)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_fold_pad_and_grid():
    assert fold_pad_shape((64, 512, 512), (20, 256, 256), (16, 192, 192)) \
        == (68, 640, 640)
    assert fold_grid((68, 640, 640), (20, 256, 256), (16, 192, 192)) \
        == (4, 3, 3)
    # already uniform: unchanged
    assert fold_pad_shape((36, 448, 448), (20, 256, 256), (16, 192, 192)) \
        == (36, 448, 448)
    with pytest.raises(ValueError):
        fold_grid((65, 640, 640), (20, 256, 256), (16, 192, 192))


@pytest.mark.parametrize("shape", [(8, 32, 32), (8, 33, 37), (5, 17, 18)])
def test_fold_identity_oracle(shape):
    """blend='fold' reproduces the input through the full engine on
    uniform AND ragged shapes (padding + crop)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        blend="fold",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(1)
    chunk = rng.random(shape).astype(np.float32)
    out = np.asarray(inferencer(Chunk(chunk)).array)
    assert out.shape == (3,) + shape
    np.testing.assert_allclose(out[0], chunk, atol=1e-5)


def test_fold_matches_scatter_with_margin():
    """With a crop margin (pin != pout), fold and scatter agree on the
    mutually-covered interior."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    def build(blend):
        return Inferencer(
            input_patch_size=(8, 24, 24),
            output_patch_size=(4, 16, 16),
            output_patch_overlap=(2, 8, 8),
            num_output_channels=1,
            framework="identity",
            batch_size=2,
            blend=blend,
            crop_output_margin=True,
        )

    rng = np.random.default_rng(2)
    chunk = Chunk(rng.random((16, 48, 48)).astype(np.float32))
    fold = np.asarray(build("fold")(chunk.clone()).array)
    scatter = np.asarray(build("scatter")(chunk.clone()).array)
    assert fold.shape == scatter.shape
    # cropped interior: both must equal the input there (identity engine)
    np.testing.assert_allclose(fold, scatter, atol=1e-5)


def test_fold_with_tta_and_bf16_output():
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        blend="fold",
        augment=True,
        output_dtype="bfloat16",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(3)
    chunk = rng.random((8, 32, 32)).astype(np.float32)
    out = inferencer(Chunk(chunk))
    import jax.numpy as jnp

    assert out.array.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out.array, np.float32)[0], chunk, atol=0.01)


def test_fold_program_reuse_and_patch_grid():
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        blend="fold",
        crop_output_margin=False,
    )
    # both ragged shapes pad to the same uniform grid -> one program
    rng = np.random.default_rng(4)
    for shape in ((8, 30, 30), (7, 27, 32), (8, 32, 32)):
        chunk = rng.random(shape).astype(np.float32)
        out = np.asarray(inferencer(Chunk(chunk)).array)
        np.testing.assert_allclose(out[0], chunk, atol=1e-5)
    assert len(inferencer._fold_programs) == 1
    assert inferencer.patch_grid_shape((8, 32, 32)) == (3, 3, 3)


def test_fold_budget_fallback_and_sharding_conflict(monkeypatch):
    """Over-budget stacks fall back to the scatter path (no OOM), and
    fold+sharding is rejected loudly at construction."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    monkeypatch.setenv("CHUNKFLOW_BLEND_STACK_MAX_GB", "0.000001")
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        blend="fold",
        crop_output_margin=False,
    )
    assert not inferencer._use_fold((8, 32, 32))
    rng = np.random.default_rng(6)
    chunk = rng.random((8, 32, 32)).astype(np.float32)
    out = np.asarray(inferencer(Chunk(chunk)).array)
    np.testing.assert_allclose(out[0], chunk, atol=1e-5)
    assert not inferencer._fold_programs  # scatter path ran instead
    # the --patch-num assertion follows the EXECUTED (scatter) grid
    assert inferencer.patch_grid_shape((8, 32, 32)) == (3, 3, 3)

    monkeypatch.delenv("CHUNKFLOW_BLEND_STACK_MAX_GB")
    with pytest.raises(ValueError, match="single-device"):
        Inferencer(
            input_patch_size=(4, 16, 16),
            framework="identity",
            blend="fold",
            sharding="spatial",
        )


def test_fold_thinner_than_patch():
    """Chunks thinner than the input patch pad up and work under fold
    (the scatter enumerate_patches path would reject them)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        blend="fold",
        crop_output_margin=False,
    )
    assert inferencer.patch_grid_shape((3, 32, 32)) == (1, 3, 3)
    rng = np.random.default_rng(8)
    chunk = rng.random((3, 32, 32)).astype(np.float32)
    out = np.asarray(inferencer(Chunk(chunk)).array)
    assert out.shape == (1, 3, 32, 32)
    np.testing.assert_allclose(out[0], chunk, atol=1e-5)


def test_fold_thin_chunk_survives_budget_fallback(monkeypatch):
    """Thin-chunk padding holds even when the stack budget forces the
    scatter fallback (regression: enumerate_patches used to crash)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    monkeypatch.setenv("CHUNKFLOW_BLEND_STACK_MAX_GB", "0.000001")
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        blend="fold",
        crop_output_margin=False,
    )
    assert not inferencer._use_fold((4, 32, 32))
    rng = np.random.default_rng(10)
    chunk = rng.random((3, 32, 32)).astype(np.float32)
    out = np.asarray(inferencer(Chunk(chunk)).array)
    assert out.shape == (1, 3, 32, 32)
    np.testing.assert_allclose(out[0], chunk, atol=1e-5)


def test_patch_grid_shape_thin_chunk_budget_fallback(monkeypatch):
    """patch_grid_shape must not crash (and must match execution) for
    thin chunks when the budget forces the scatter fallback."""
    from chunkflow_tpu.inference.inferencer import Inferencer

    monkeypatch.setenv("CHUNKFLOW_BLEND_STACK_MAX_GB", "0.000001")
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        blend="fold",
        crop_output_margin=False,
    )
    assert inferencer.patch_grid_shape((3, 32, 32)) == (1, 3, 3)
