"""Pallas scatter-accumulate kernel (interpret mode on CPU) vs XLA path."""
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def _run_identity(monkeypatch, mode):
    monkeypatch.setenv("CHUNKFLOW_PALLAS", mode)
    # build_local_blend reads CHUNKFLOW_PALLAS when the Inferencer is built
    from chunkflow_tpu.inference.inferencer import Inferencer
    from chunkflow_tpu.chunk.base import Chunk

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=2,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    return np.asarray(inferencer(chunk).array)


def test_pallas_accumulate_matches_xla(monkeypatch):
    ref = _run_identity(monkeypatch, "0")
    got = _run_identity(monkeypatch, "interpret")
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_pallas_identity_oracle(monkeypatch):
    got = _run_identity(monkeypatch, "interpret")
    # identity oracle holds through the pallas scatter path
    from chunkflow_tpu.chunk.base import Chunk

    rng = np.random.default_rng(0)
    chunk = rng.random((8, 32, 32)).astype(np.float32)
    np.testing.assert_allclose(got[0], chunk, atol=1e-5)
    np.testing.assert_allclose(got[1], chunk, atol=1e-5)
