"""Pallas scatter-accumulate kernel (interpret mode on CPU) vs XLA path."""
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def _run_identity(monkeypatch, mode, shape=(8, 32, 32)):
    monkeypatch.setenv("CHUNKFLOW_PALLAS", mode)
    # build_local_blend reads CHUNKFLOW_PALLAS when the Inferencer is built
    from chunkflow_tpu.inference.inferencer import Inferencer
    from chunkflow_tpu.chunk.base import Chunk

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=2,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random(shape).astype(np.float32))
    return chunk, np.asarray(inferencer(chunk).array)


# (9, 35, 33) produces patch corners with no (8,128) alignment at all —
# exercises the aligned-window machinery end to end
@pytest.mark.parametrize("shape", [(8, 32, 32), (9, 35, 33)])
def test_pallas_accumulate_matches_xla(monkeypatch, shape):
    _, ref = _run_identity(monkeypatch, "0", shape)
    _, got = _run_identity(monkeypatch, "interpret", shape)
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("shape", [(8, 32, 32), (9, 35, 33)])
def test_pallas_identity_oracle(monkeypatch, shape):
    chunk, got = _run_identity(monkeypatch, "interpret", shape)
    # identity oracle holds through the pallas scatter path
    arr = np.asarray(chunk.array)
    np.testing.assert_allclose(got[0], arr, atol=1e-5)
    np.testing.assert_allclose(got[1], arr, atol=1e-5)


@pytest.mark.parametrize("mode", ["0", "interpret"])
def test_blend_stacked_optin_matches_per_batch_default(monkeypatch, mode):
    """The opt-in stacked single-accumulation (CHUNKFLOW_BLEND_STACKED=1,
    kept for hardware A/B) must agree with the per-batch default."""
    _, ref = _run_identity(monkeypatch, mode, (9, 35, 33))
    monkeypatch.setenv("CHUNKFLOW_BLEND_STACKED", "1")
    _, got = _run_identity(monkeypatch, mode, (9, 35, 33))
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("mode", ["0", "interpret"])
def test_blend_stacked_budget_fallback(monkeypatch, mode):
    """Even when opted in, an over-budget stack falls back to per-batch
    accumulation (the jumbo-chunk OOM guard) with identical results."""
    monkeypatch.setenv("CHUNKFLOW_BLEND_STACKED", "1")
    _, ref = _run_identity(monkeypatch, mode, (9, 35, 33))
    monkeypatch.setenv("CHUNKFLOW_BLEND_STACK_MAX_GB", "0.0000001")
    _, got = _run_identity(monkeypatch, mode, (9, 35, 33))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_pallas_matches_xla_blend_on_overlapping_patches(monkeypatch):
    """Dense-overlap parity: the pallas DMA kernel (interpret mode) and
    the ops/blend.py scatter-add path must agree on a fixture where every
    patch overlaps several neighbours (stride = half patch per axis)."""
    _, ref = _run_identity(monkeypatch, "0", (10, 40, 40))
    _, got = _run_identity(monkeypatch, "interpret", (10, 40, 40))
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_accumulate_patches_overlapping_windows_vs_numpy():
    """Direct kernel check with overlapping windows: sequential-grid
    accumulation order must reproduce numpy's += semantics exactly."""
    import jax.numpy as jnp

    from chunkflow_tpu.ops import pallas_blend

    rng = np.random.default_rng(7)
    co, Z, Y, X = 3, 5, 32, 40
    B, pz, py, px = 4, 3, 12, 16
    pad_y, pad_x = pallas_blend.buffer_padding((pz, py, px))
    out = np.zeros((co, Z, Y + pad_y, X + pad_x), np.float32)
    weight = np.zeros((Z, Y + pad_y, X + pad_x), np.float32)
    preds = rng.random((B, co, pz, py, px)).astype(np.float32)
    wpatches = rng.random((B, pz, py, px)).astype(np.float32)
    # stride ~ half patch: every window overlaps its neighbours in all axes
    starts = np.array(
        [[0, 0, 0], [1, 6, 8], [2, 12, 16], [1, 6, 8]], np.int32
    )

    got_out, got_w = pallas_blend.accumulate_patches(
        jnp.asarray(out), jnp.asarray(weight), jnp.asarray(preds),
        jnp.asarray(wpatches), jnp.asarray(starts), interpret=True,
    )
    exp_out, exp_w = out.copy(), weight.copy()
    for b in range(B):
        z, y, x = starts[b]
        exp_out[:, z:z + pz, y:y + py, x:x + px] += preds[b]
        exp_w[z:z + pz, y:y + py, x:x + px] += wpatches[b]
    np.testing.assert_allclose(np.asarray(got_out), exp_out, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_w), exp_w, atol=1e-5)


def test_accumulate_patches_unaligned_offsets_vs_numpy():
    """Direct kernel check: arbitrary (not 8/128-divisible) corners."""
    import jax.numpy as jnp

    from chunkflow_tpu.ops import pallas_blend

    rng = np.random.default_rng(3)
    co, Z, Y, X = 2, 6, 40, 48
    B, pz, py, px = 3, 2, 9, 11
    pad_y, pad_x = pallas_blend.buffer_padding((pz, py, px))
    out = np.zeros((co, Z, Y + pad_y, X + pad_x), np.float32)
    weight = np.zeros((Z, Y + pad_y, X + pad_x), np.float32)
    preds = rng.random((B, co, pz, py, px)).astype(np.float32)
    wpatches = rng.random((B, pz, py, px)).astype(np.float32)
    starts = np.array([[0, 1, 5], [3, 17, 30], [1, 31, 37]], np.int32)

    got_out, got_w = pallas_blend.accumulate_patches(
        jnp.asarray(out), jnp.asarray(weight), jnp.asarray(preds),
        jnp.asarray(wpatches), jnp.asarray(starts), interpret=True,
    )
    exp_out, exp_w = out.copy(), weight.copy()
    for b in range(B):
        z, y, x = starts[b]
        exp_out[:, z:z + pz, y:y + py, x:x + px] += preds[b]
        exp_w[z:z + pz, y:y + py, x:x + px] += wpatches[b]
    np.testing.assert_allclose(np.asarray(got_out), exp_out, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_w), exp_w, atol=1e-6)
