"""Fused Pallas blend kernel (interpret mode on CPU) vs the XLA scatter
path: BITWISE parity across the PR 13 matrix (ISSUE 14 acceptance) —
plain/ragged/uint8/crop-margin traffic x single-device and
``data=N``/``y=A,x=B`` meshes, plus packed-serve traffic."""
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.inference import engines
from chunkflow_tpu.inference.inferencer import Inferencer

PIN = (4, 16, 16)
OVERLAP = (2, 8, 8)


def _run_identity(monkeypatch, mode, shape=(8, 32, 32)):
    monkeypatch.setenv("CHUNKFLOW_PALLAS", mode)
    inferencer = Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=2,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random(shape).astype(np.float32))
    return chunk, np.asarray(inferencer(chunk).array)


# (9, 35, 33) produces patch corners with no (8,128) alignment at all —
# exercises the aligned-window machinery end to end
@pytest.mark.parametrize("shape", [(8, 32, 32), (9, 35, 33)])
def test_fused_bitwise_matches_xla(monkeypatch, shape):
    """The float32 fused path is BITWISE identical to the XLA scatter
    path (ISSUE 14 acceptance — tighter than the old atol=1e-5 bound:
    same weighting expressions, same ascending-patch accumulation
    order)."""
    _, ref = _run_identity(monkeypatch, "0", shape)
    _, got = _run_identity(monkeypatch, "interpret", shape)
    assert np.array_equal(got, ref)


@pytest.mark.parametrize("shape", [(8, 32, 32), (9, 35, 33)])
def test_pallas_identity_oracle(monkeypatch, shape):
    chunk, got = _run_identity(monkeypatch, "interpret", shape)
    # identity oracle holds through the fused blend path
    arr = np.asarray(chunk.array)
    np.testing.assert_allclose(got[0], arr, atol=1e-5)
    np.testing.assert_allclose(got[1], arr, atol=1e-5)


@pytest.mark.parametrize("mode", ["0", "interpret"])
def test_blend_stacked_optin_matches_per_batch_default(monkeypatch, mode):
    """The opt-in stacked single-accumulation (CHUNKFLOW_BLEND_STACKED=1,
    kept for hardware A/B) must agree with the per-batch default —
    bitwise now that both weight inside the shared accumulate step."""
    _, ref = _run_identity(monkeypatch, mode, (9, 35, 33))
    monkeypatch.setenv("CHUNKFLOW_BLEND_STACKED", "1")
    _, got = _run_identity(monkeypatch, mode, (9, 35, 33))
    assert np.array_equal(got, ref)


@pytest.mark.parametrize("mode", ["0", "interpret"])
def test_blend_stacked_budget_fallback(monkeypatch, mode):
    """Even when opted in, an over-budget stack falls back to per-batch
    accumulation (the jumbo-chunk OOM guard) with identical results."""
    monkeypatch.setenv("CHUNKFLOW_BLEND_STACKED", "1")
    _, ref = _run_identity(monkeypatch, mode, (9, 35, 33))
    monkeypatch.setenv("CHUNKFLOW_BLEND_STACK_MAX_GB", "0.0000001")
    _, got = _run_identity(monkeypatch, mode, (9, 35, 33))
    assert np.array_equal(got, ref)


def test_fused_bitwise_on_overlapping_patches(monkeypatch):
    """Dense-overlap parity: the fused kernel (interpret mode) and the
    ops/blend.py scatter-add path must agree BITWISE on a fixture where
    every patch overlaps several neighbours (stride = half patch)."""
    _, ref = _run_identity(monkeypatch, "0", (10, 40, 40))
    _, got = _run_identity(monkeypatch, "interpret", (10, 40, 40))
    assert np.array_equal(got, ref)


# ---------------------------------------------------------------------------
# direct kernel checks
# ---------------------------------------------------------------------------
def _kernel_fixture(seed, co=3, Z=5, Y=32, X=40, B=4, pz=3, py=12, px=16):
    from chunkflow_tpu.ops import pallas_blend

    rng = np.random.default_rng(seed)
    pad_y, pad_x = pallas_blend.buffer_padding((pz, py, px))
    out = np.zeros((co, Z, Y + pad_y, X + pad_x), np.float32)
    weight = np.zeros((Z, Y + pad_y, X + pad_x), np.float32)
    preds = rng.standard_normal((B, co, pz, py, px)).astype(np.float32)
    bump = (rng.random((pz, py, px)) * 5 + 1).astype(np.float32)
    valid = np.ones((B,), np.float32)
    valid[-1] = 0.0  # one batch-padding row
    return out, weight, preds, bump, valid


def test_fused_kernel_overlapping_windows_vs_numpy():
    """Direct kernel check with overlapping windows: weighting +
    placement + sequential-grid accumulation must reproduce numpy's
    ``+= (preds*bump)*valid`` semantics bitwise."""
    import jax.numpy as jnp

    from chunkflow_tpu.ops import pallas_blend

    out, weight, preds, bump, valid = _kernel_fixture(7)
    B, co, pz, py, px = preds.shape
    # stride ~ half patch: every window overlaps its neighbours; a
    # duplicate corner exercises the in-order accumulation
    starts = np.array(
        [[0, 0, 0], [1, 6, 8], [2, 12, 16], [1, 6, 8]], np.int32
    )
    got_out, got_w = pallas_blend.fused_accumulate_patches(
        jnp.asarray(out), jnp.asarray(weight), jnp.asarray(preds),
        jnp.asarray(valid), jnp.asarray(bump), jnp.asarray(starts),
        interpret=True,
    )
    exp_out, exp_w = out.copy(), weight.copy()
    for b in range(B):
        z, y, x = starts[b]
        exp_out[:, z:z + pz, y:y + py, x:x + px] += \
            (preds[b] * bump[None]) * valid[b]
        exp_w[z:z + pz, y:y + py, x:x + px] += bump * valid[b]
    assert np.array_equal(np.asarray(got_out), exp_out)
    assert np.array_equal(np.asarray(got_w), exp_w)


def test_fused_kernel_pre_weighted_vs_numpy():
    """The pre-weighted flavor (the serving replay / sharded-engine
    stacks): rows added as-is, weight contributions bump*valid."""
    import jax.numpy as jnp

    from chunkflow_tpu.ops import pallas_blend

    out, weight, preds, bump, valid = _kernel_fixture(
        3, co=2, Z=6, Y=40, X=48, B=3, pz=2, py=9, px=11)
    B, co, pz, py, px = preds.shape
    starts = np.array([[0, 1, 5], [3, 17, 30], [1, 31, 37]], np.int32)
    wstack = (preds * bump[None, None]) * valid[:, None, None, None, None]
    got_out, got_w = pallas_blend.fused_accumulate_patches(
        jnp.asarray(out), jnp.asarray(weight), jnp.asarray(wstack),
        jnp.asarray(valid), jnp.asarray(bump), jnp.asarray(starts),
        pre_weighted=True, interpret=True,
    )
    exp_out, exp_w = out.copy(), weight.copy()
    for b in range(B):
        z, y, x = starts[b]
        exp_out[:, z:z + pz, y:y + py, x:x + px] += wstack[b]
        exp_w[z:z + pz, y:y + py, x:x + px] += bump * valid[b]
    assert np.array_equal(np.asarray(got_out), exp_out)
    assert np.array_equal(np.asarray(got_w), exp_w)


# ---------------------------------------------------------------------------
# pallas_mode: typo warning (ISSUE 14 satellite)
# ---------------------------------------------------------------------------
def test_pallas_mode_warns_once_on_typo(monkeypatch, capsys):
    """A mistyped opt-in (CHUNKFLOW_PALLAS=ture) must not silently run
    the slow path: one stderr warning per unrecognized value, then
    quiet; recognized values never warn."""
    from chunkflow_tpu.ops import pallas_blend

    monkeypatch.setattr(pallas_blend, "_WARNED_VALUES", set())
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "ture")
    assert pallas_blend.pallas_mode() == "off"
    err = capsys.readouterr().err
    assert "ture" in err and "not a recognized value" in err
    # second call with the same typo: silent (warned once)
    assert pallas_blend.pallas_mode() == "off"
    assert capsys.readouterr().err == ""
    # a DIFFERENT typo warns again
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "yes please")
    assert pallas_blend.pallas_mode() == "off"
    assert "not a recognized value" in capsys.readouterr().err
    # recognized values never warn
    for value, expected in [("0", "off"), ("off", "off"), ("", "off"),
                            ("1", "on"), ("force", "on"),
                            ("interpret", "interpret")]:
        monkeypatch.setenv("CHUNKFLOW_PALLAS", value)
        assert pallas_blend.pallas_mode() == expected
    assert capsys.readouterr().err == ""


# ---------------------------------------------------------------------------
# the ISSUE 14 parity matrix: fused vs XLA scatter, bitwise, across
# traffic classes, meshes, and packed-serve traffic
# ---------------------------------------------------------------------------
def _traffic_chunk(traffic: str, seed: int):
    rng = np.random.default_rng(seed)
    if traffic == "ragged":
        return Chunk(rng.random((6, 37, 45)).astype(np.float32))
    if traffic == "uint8":
        return Chunk(rng.integers(0, 256, (8, 40, 48), dtype=np.uint8))
    return Chunk(rng.random((8, 40, 48)).astype(np.float32))


def _matrix_inferencer(crop: bool, mesh=None):
    if crop:
        engine = engines.create_identity_engine(
            input_patch_size=PIN, output_patch_size=(2, 8, 8),
            num_input_channels=1, num_output_channels=3,
        )
        return Inferencer(
            input_patch_size=PIN,
            output_patch_size=(2, 8, 8),
            output_patch_overlap=(1, 4, 4),
            num_output_channels=3,
            framework="prebuilt",
            batch_size=2,
            engine=engine,
            mesh=mesh,
            crop_output_margin=True,
        )
    engine = engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=PIN,
        num_input_channels=1, num_output_channels=3,
    )
    return Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=3,
        framework="prebuilt",
        batch_size=2,
        engine=engine,
        mesh=mesh,
        crop_output_margin=False,
    )


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 virtual devices (tests/conftest.py)")
@pytest.mark.parametrize("mesh", [None, "data=2", "y=2,x=2"])
@pytest.mark.parametrize(
    "traffic", ["plain", "ragged", "uint8", "crop_margin"]
)
def test_fused_parity_matrix(monkeypatch, mesh, traffic):
    """ISSUE 14 acceptance: the float32 fused path is BITWISE identical
    to the XLA scatter path in interpret mode across the PR 13 parity
    matrix — every traffic class, single-device AND both mesh kinds
    (the fused kernel runs inside the sharded replay too)."""
    crop = traffic == "crop_margin"
    chunk = _traffic_chunk(traffic, seed=abs(hash(traffic)) % 2**31)
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "0")
    ref = np.asarray(_matrix_inferencer(crop, mesh=mesh)(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "interpret")
    got = np.asarray(_matrix_inferencer(crop, mesh=mesh)(chunk).array)
    assert got.dtype == ref.dtype and got.shape == ref.shape
    assert np.array_equal(got, ref), (
        f"fused path diverged from XLA scatter (mesh={mesh}, "
        f"traffic={traffic}; max abs diff "
        f"{np.abs(got.astype(np.float64) - ref.astype(np.float64)).max():.3e})"
    )


def test_fused_parity_packed_serve(monkeypatch):
    """Packed-serve traffic through the fused serve_scatter program is
    bitwise identical to the XLA-scatter packed path AND the per-chunk
    fused path (the serving leg of the ISSUE 14 matrix)."""
    from chunkflow_tpu.serve.packer import PatchPacker

    rng = np.random.default_rng(5)
    chunks = [
        Chunk(rng.random((4, 16, 48), dtype=np.float32),
              voxel_offset=(8 * i, 0, 0))
        for i in range(4)
    ]

    def packed(mode):
        monkeypatch.setenv("CHUNKFLOW_PALLAS", mode)
        inf = Inferencer(
            input_patch_size=PIN,
            num_output_channels=2,
            framework="identity",
            batch_size=4,
            crop_output_margin=False,
        )
        packer = PatchPacker(inf, max_wait_ms=2.0)
        try:
            handles = [packer.submit(c) for c in chunks]
            return [np.asarray(h.result(timeout=60).array)
                    for h in handles]
        finally:
            packer.close()
        # the fused key is distinct, so the packer builds the fused
        # serve_scatter program rather than reusing the XLA one

    ref = packed("0")
    got = packed("interpret")
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "interpret")
    inf = Inferencer(
        input_patch_size=PIN,
        num_output_channels=2,
        framework="identity",
        batch_size=4,
        crop_output_margin=False,
    )
    per_chunk = [np.asarray(inf(c).array) for c in chunks]
    for r, g, p in zip(ref, got, per_chunk):
        assert np.array_equal(g, r)
        assert np.array_equal(g, p)


def test_fused_key_rebuilds_on_env_flip(monkeypatch):
    """Flipping CHUNKFLOW_PALLAS mid-stream builds the fused program
    under its own cache key instead of reusing the stale XLA one (the
    CHUNKFLOW_MESH re-read convention, now for the kernel selection)."""
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "0")
    inf = Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=2,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(1)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    ref = np.asarray(inf(chunk).array)
    assert ("scatter",) in inf._programs
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "interpret")
    got = np.asarray(inf(chunk).array)
    # the interpret tag carries "+kc" while the kernelcheck sanitizer
    # is live (its hooks are part of the program identity)
    from chunkflow_tpu.testing import kernelcheck
    tag = f"fused-interpret{kernelcheck.key_suffix()}"
    assert ("scatter_fused", tag) in inf._programs
    assert np.array_equal(got, ref)
    assert inf._programs.builds == 2


def test_fused_modules_are_graftlint_clean():
    """ISSUE 14 satellite: GL001-GL014 clean over the new/changed kernel
    modules, asserted in-suite (the whole-repo gate covers them too;
    this pins the specific modules so a future baseline regeneration
    cannot quietly grandfather a finding here)."""
    from pathlib import Path

    from tools.graftlint.config import load_config
    from tools.graftlint.engine import lint_paths

    repo_root = Path(__file__).resolve().parents[2]
    config = load_config(repo_root / "pyproject.toml")
    findings, _ = lint_paths(
        [
            "chunkflow_tpu/ops/pallas_blend.py",
            "chunkflow_tpu/ops/blend.py",
            "chunkflow_tpu/inference/precision.py",
            "chunkflow_tpu/inference/inferencer.py",
            "chunkflow_tpu/inference/bump.py",
            "chunkflow_tpu/serve/packer.py",
            "chunkflow_tpu/parallel/engine.py",
            "chunkflow_tpu/core/profiling.py",
        ],
        config, repo_root=repo_root,
    )
    assert not findings, [
        f"{f.path}:{f.line}: {f.code} {f.message}" for f in findings
    ]
