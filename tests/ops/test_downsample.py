import numpy as np

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.ops.downsample import (
    downsample,
    downsample_average,
    downsample_mode,
    pyramid,
)


def test_average_downsample_exact():
    arr = np.arange(16, dtype=np.float32).reshape(1, 4, 4)
    arr = np.broadcast_to(arr, (2, 4, 4)).copy()
    chunk = Chunk(arr, voxel_offset=(0, 4, 8), voxel_size=(40, 4, 4))
    down = downsample_average(chunk, (1, 2, 2))
    assert down.shape == (2, 2, 2)
    # block (0:2, 0:2) of row-major arange(16) in 4x4: mean of 0,1,4,5 = 2.5
    assert float(np.asarray(down.array)[0, 0, 0]) == 2.5
    assert down.voxel_size == Cartesian(40, 8, 8)
    assert down.voxel_offset == Cartesian(0, 2, 4)


def test_average_downsample_uint8_rounds():
    chunk = Chunk(np.full((2, 4, 4), 3, dtype=np.uint8))
    down = downsample_average(chunk, (2, 2, 2))
    assert down.dtype == np.uint8
    assert np.all(np.asarray(down.array) == 3)


def test_mode_downsample_majority_wins():
    arr = np.zeros((2, 4, 4), dtype=np.uint32)
    arr[:, :2, :2] = 7  # 8 voxels of id 7 in first block
    arr[0, 0, 0] = 3    # minority
    seg = Chunk(arr)
    down = downsample_mode(seg, (2, 2, 2))
    assert down.shape == (1, 2, 2)
    assert np.asarray(down.array)[0, 0, 0] == 7
    assert np.asarray(down.array)[0, 1, 1] == 0


def test_downsample_dispatches_by_layer():
    seg = Chunk(np.ones((2, 2, 2), dtype=np.uint32))
    img = Chunk(np.ones((2, 2, 2), dtype=np.uint8))
    assert downsample(seg, (2, 2, 2)).dtype == np.uint32
    assert downsample(img, (2, 2, 2)).dtype == np.uint8


def test_pyramid_levels():
    chunk = Chunk(np.ones((8, 16, 16), dtype=np.uint8))
    levels = pyramid(chunk, (1, 2, 2), num_mips=3)
    assert [tuple(l.shape) for l in levels] == [
        (8, 8, 8), (8, 4, 4), (8, 2, 2)
    ]
