import numpy as np

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.ops.downsample import (
    downsample,
    downsample_average,
    downsample_mode,
    pyramid,
)


def test_average_downsample_exact():
    arr = np.arange(16, dtype=np.float32).reshape(1, 4, 4)
    arr = np.broadcast_to(arr, (2, 4, 4)).copy()
    chunk = Chunk(arr, voxel_offset=(0, 4, 8), voxel_size=(40, 4, 4))
    down = downsample_average(chunk, (1, 2, 2))
    assert down.shape == (2, 2, 2)
    # block (0:2, 0:2) of row-major arange(16) in 4x4: mean of 0,1,4,5 = 2.5
    assert float(np.asarray(down.array)[0, 0, 0]) == 2.5
    assert down.voxel_size == Cartesian(40, 8, 8)
    assert down.voxel_offset == Cartesian(0, 2, 4)


def test_average_downsample_uint8_rounds():
    chunk = Chunk(np.full((2, 4, 4), 3, dtype=np.uint8))
    down = downsample_average(chunk, (2, 2, 2))
    assert down.dtype == np.uint8
    assert np.all(np.asarray(down.array) == 3)


def test_mode_downsample_majority_wins():
    arr = np.zeros((2, 4, 4), dtype=np.uint32)
    arr[:, :2, :2] = 7  # 8 voxels of id 7 in first block
    arr[0, 0, 0] = 3    # minority
    seg = Chunk(arr)
    down = downsample_mode(seg, (2, 2, 2))
    assert down.shape == (1, 2, 2)
    assert np.asarray(down.array)[0, 0, 0] == 7
    assert np.asarray(down.array)[0, 1, 1] == 0


def test_mode_device_matches_numpy_exactly():
    """Device (XLA) mode pooling == the slow numpy reference, including
    tie-breaking by first corner in z-major order."""
    from chunkflow_tpu.core.cartesian import to_cartesian
    from chunkflow_tpu.ops.downsample import mode_pool_device, mode_pool_numpy

    rng = np.random.default_rng(7)
    for factor in ((1, 2, 2), (2, 2, 2)):
        # few labels -> lots of genuine ties to exercise tie-breaking
        arr = rng.integers(0, 4, size=(2, 8, 12, 12)).astype(np.uint32)
        fac = to_cartesian(factor)
        dev = np.asarray(mode_pool_device(arr, fac))
        ref = mode_pool_numpy(arr, fac)
        np.testing.assert_array_equal(dev, ref)


def test_mode_all_distinct_first_corner_wins():
    # 2x2 block with four distinct labels: every corner counts 1 -> the
    # z-major first corner (dz=0, dy=0, dx=0) wins in both paths
    arr = np.array([[[1, 2], [3, 4]]], dtype=np.uint32)
    seg = Chunk(arr)
    down = downsample_mode(seg, (1, 2, 2))
    assert np.asarray(down.array)[0, 0, 0] == 1


def test_mode_uint64_falls_back_to_numpy():
    import jax

    big = np.uint64(2**40 + 5)  # would truncate in 32-bit jnp
    arr = np.full((2, 2, 2), big, dtype=np.uint64)
    arr[1, 1, 1] = 0
    seg = Chunk(arr)
    down = downsample_mode(seg, (2, 2, 2))
    assert down.dtype == np.uint64
    if not jax.config.jax_enable_x64:
        assert np.asarray(down.array)[0, 0, 0] == big


def test_downsample_dispatches_by_layer():
    seg = Chunk(np.ones((2, 2, 2), dtype=np.uint32))
    img = Chunk(np.ones((2, 2, 2), dtype=np.uint8))
    assert downsample(seg, (2, 2, 2)).dtype == np.uint32
    assert downsample(img, (2, 2, 2)).dtype == np.uint8


def test_pyramid_levels():
    chunk = Chunk(np.ones((8, 16, 16), dtype=np.uint8))
    levels = pyramid(chunk, (1, 2, 2), num_mips=3)
    assert [tuple(l.shape) for l in levels] == [
        (8, 8, 8), (8, 4, 4), (8, 2, 2)
    ]
