"""Kernel window geometry, pinned as a table (ISSUE 16 satellite):
``padded_patch_shape`` / ``buffer_padding`` on the blend side and the
dtype-tiling ``gather_window`` / ``gather_buffer_padding`` table on the
gather side — including the flush-at-edge worst case the padding
exists for. The analytic cost helpers (``fused_kernel_cost`` /
``gather_kernel_cost``) are pinned against the same arithmetic so the
stamped programs.json VMEM column cannot drift from the geometry.
"""
import numpy as np
import pytest

from chunkflow_tpu.ops import pallas_blend, pallas_gather


# ---------------------------------------------------------------------------
# blend-side geometry (f32 only: the blend kernel is float32)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("py,px,expected", [
    (1, 1, (8, 128)),       # tiny patch still needs one full tile
    (8, 128, (16, 256)),    # exactly one tile + worst-case offset slack
    (12, 16, (24, 256)),
    (64, 64, (72, 256)),    # the bench geometry
    (9, 129, (16, 256)),
])
def test_padded_patch_shape(py, px, expected):
    assert pallas_blend.padded_patch_shape(py, px) == expected


def test_padded_patch_shape_covers_any_offset():
    # the window must hold a (py, px) patch at ANY in-window offset
    # (dy, dx) in [0, 8) x [0, 128) — that is its whole job
    for py, px in [(1, 1), (7, 127), (8, 128), (30, 200)]:
        py_pad, px_pad = pallas_blend.padded_patch_shape(py, px)
        assert py_pad % 8 == 0 and px_pad % 128 == 0
        assert py_pad >= py + 7 and px_pad >= px + 127


def test_buffer_padding_is_window_minus_patch():
    for pout in [(3, 12, 16), (4, 64, 64), (2, 7, 127)]:
        pad_y, pad_x = pallas_blend.buffer_padding(pout)
        py_pad, px_pad = pallas_blend.padded_patch_shape(
            pout[1], pout[2])
        assert (pad_y, pad_x) == (py_pad - pout[1], px_pad - pout[2])


def test_buffer_padding_covers_flush_at_edge():
    # worst case: a patch ENDING at the unpadded buffer edge whose
    # aligned corner rounds down by (sublane-1, lane-1) — the padded
    # buffer must still contain the full aligned window
    pout = (3, 12, 16)
    Y, X = 40, 48
    pad_y, pad_x = pallas_blend.buffer_padding(pout)
    py_pad, px_pad = pallas_blend.padded_patch_shape(pout[1], pout[2])
    y, x = Y - pout[1], X - pout[2]  # flush at the edge
    y0, x0 = (y // 8) * 8, (x // 128) * 128
    assert y0 + py_pad <= Y + pad_y
    assert x0 + px_pad <= X + pad_x


# ---------------------------------------------------------------------------
# gather-side geometry: the dtype-tiling table
# ---------------------------------------------------------------------------
GATHER_TABLE = [
    # dtype     sublane  (py, px)   expected window
    ("float32", 8,  (12, 18), (24, 256)),
    ("uint16",  16, (12, 18), (32, 256)),
    ("uint8",   32, (12, 18), (64, 256)),
    ("float32", 8,  (64, 64), (72, 256)),
    ("uint16",  16, (64, 64), (80, 256)),
    ("uint8",   32, (64, 64), (96, 256)),
    ("float32", 8,  (8, 128), (16, 256)),
    ("uint16",  16, (16, 128), (32, 256)),
    ("uint8",   32, (32, 128), (64, 256)),
]


@pytest.mark.parametrize("dtype,sub,patch,window", GATHER_TABLE)
def test_gather_window_table(dtype, sub, patch, window):
    dt = np.dtype(dtype)
    assert pallas_gather._sublane(dt) == sub
    assert pallas_gather.gather_window(*patch, dt) == window
    wy, wx = window
    assert wy % sub == 0 and wx % 128 == 0
    # covers any offset in [0, sub) x [0, 128)
    assert wy >= patch[0] + sub - 1 and wx >= patch[1] + 127


@pytest.mark.parametrize("dtype", ["uint8", "uint16", "float32"])
def test_gather_buffer_padding_covers_flush_at_edge(dtype):
    dt = np.dtype(dtype)
    pin = (3, 12, 18)
    Y, X = 50, 70
    pad_y, pad_x = pallas_gather.gather_buffer_padding(pin, dt)
    wy, wx = pallas_gather.gather_window(pin[1], pin[2], dt)
    assert (pad_y, pad_x) == (wy - pin[1], wx - pin[2])
    sub = pallas_gather._sublane(dt)
    y, x = Y - pin[1], X - pin[2]  # flush at the edge
    y0, x0 = (y // sub) * sub, (x // 128) * 128
    assert y0 + wy <= Y + pad_y
    assert x0 + wx <= X + pad_x


# ---------------------------------------------------------------------------
# the analytic cost helpers track the geometry (the stamp_cost/GL021
# arithmetic)
# ---------------------------------------------------------------------------
def test_fused_kernel_cost_tracks_geometry():
    B, co, pout = 4, 3, (3, 12, 16)
    pz, py, px = pout
    py_pad, px_pad = pallas_blend.padded_patch_shape(py, px)
    cost = pallas_blend.fused_kernel_cost(B, co, pout)
    assert cost["grid_steps"] == B * co * pz
    # GL021 model: preds tile x2 (dynamic index), bump block x1
    # (constant index), scratch window x1
    assert cost["vmem_bytes"] == (
        2 * py * px * 4 + pz * py * px * 4 + py_pad * px_pad * 4)
    assert cost["bytes_per_step"] == py * px * 4 + 4 * py_pad * px_pad * 4
    assert cost["bytes_accessed"] == (
        B * co * pz * py * px * 4
        + B * (co + 1) * pz * py_pad * px_pad * 4 * 2)
    assert cost["flops"] == B * (2 * co + 1) * pz * py * px


@pytest.mark.parametrize("dtype", ["uint8", "uint16", "float32"])
def test_gather_kernel_cost_tracks_geometry(dtype):
    dt = np.dtype(dtype)
    B, ci, pin = 5, 2, (3, 12, 18)
    pz, py, px = pin
    wy, wx = pallas_gather.gather_window(py, px, dt)
    cost = pallas_gather.gather_kernel_cost(B, ci, pin, dt)
    assert cost["grid_steps"] == B * ci * pz
    assert cost["vmem_bytes"] == 2 * py * px * 4 + wy * wx * dt.itemsize
    step = wy * wx * dt.itemsize + py * px * 4
    assert cost["bytes_per_step"] == step
    assert cost["bytes_accessed"] == B * ci * pz * step
    # int chunks pay one scale multiply per output voxel; f32 moves only
    expected_flops = B * ci * pz * py * px if dtype != "float32" else 0
    assert cost["flops"] == expected_flops


def test_kernel_costs_fit_default_vmem_budget():
    # the shipping geometries must sit far under the 16 MiB device
    # budget — the GL021 rule enforces this statically, this pins the
    # helper's arithmetic to the same conclusion
    for pout in [(4, 64, 64), (8, 32, 32)]:
        assert pallas_blend.fused_kernel_cost(
            8, 3, pout)["vmem_bytes"] < 16 * 2**20
        for dtype in ("uint8", "uint16", "float32"):
            assert pallas_gather.gather_kernel_cost(
                8, 2, pout, np.dtype(dtype))["vmem_bytes"] < 16 * 2**20
