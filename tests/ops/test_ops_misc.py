"""Unit tests for the small jax/numpy ops modules."""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.ops import filters, mask as mask_ops, remap, threshold, voting


def test_maskout_upsampled_mask():
    """Mask at a coarser mip multiplies through (reference mask.py:74-81)."""
    chunk = Chunk(np.ones((4, 8, 8), np.float32), voxel_size=(40, 4, 4))
    coarse = Chunk(
        np.ones((4, 4, 4), np.uint8), voxel_size=(40, 8, 8)
    )
    coarse[:, 0, :] = 0  # zero a y-band at coarse resolution
    out = mask_ops.maskout(chunk, coarse)
    arr = np.asarray(out.array)
    assert np.all(arr[:, 0:2, :] == 0)  # coarse band upsamples to fine 2 rows
    assert np.all(arr[:, 2:, :] == 1)


def test_maskout_inverse():
    chunk = Chunk(np.ones((2, 2, 2), np.float32))
    m = Chunk(np.zeros((2, 2, 2), np.uint8))
    out = mask_ops.maskout(chunk, m, inverse=True)
    assert np.all(np.asarray(out.array) == 1)


def test_channel_voting_argmax_plus_one():
    arr = np.zeros((3, 1, 2, 2), np.float32)
    arr[0, 0, 0, 0] = 1.0
    arr[1, 0, 0, 1] = 1.0
    arr[2, 0, 1, 0] = 1.0
    out = voting.channel_voting(Chunk(arr))
    res = np.asarray(out.array)
    assert res.shape == (1, 2, 2)
    assert res[0, 0, 0] == 1 and res[0, 0, 1] == 2 and res[0, 1, 0] == 3


def test_mask_using_last_channel():
    arr = np.zeros((2, 1, 2, 2), np.float32)
    arr[0] = 0.8
    arr[1, 0, 0, 0] = 0.9  # myelin above threshold -> zero out
    out = voting.mask_using_last_channel(Chunk(arr), threshold=0.5)
    res = np.asarray(out.array)
    assert res.shape == (1, 1, 2, 2)
    assert res[0, 0, 0, 0] == 0.0
    assert res[0, 0, 0, 1] == pytest.approx(0.8)


def test_threshold_binary():
    c = Chunk(np.asarray([[[0.2, 0.8]]], dtype=np.float32))
    out = threshold.threshold(c, 0.5)
    res = np.asarray(out.array)
    assert res.dtype == np.uint8
    assert res.tolist() == [[[0, 1]]]


def test_gaussian_filter_2d_matches_scipy():
    from scipy.ndimage import gaussian_filter

    rng = np.random.default_rng(0)
    arr = rng.random((3, 16, 16)).astype(np.float32)
    out = filters.gaussian_filter_2d(Chunk(arr.copy()), sigma=1.0)
    ref = np.stack([gaussian_filter(a, 1.0) for a in arr])
    np.testing.assert_allclose(np.asarray(out.array), ref, atol=2e-2)


def test_median_filter():
    arr = np.zeros((1, 5, 5), np.float32)
    arr[0, 2, 2] = 100.0  # salt noise removed by median
    out = filters.median_filter(Chunk(arr), size=3)
    assert np.asarray(out.array)[0, 2, 2] == 0.0


def test_renumber_and_remap_roundtrip():
    arr = np.array([[[0, 5, 5, 9]]], dtype=np.uint32)
    renum, mapping = remap.renumber(arr)
    assert set(np.unique(renum).tolist()) == {0, 1, 2}
    back = remap.remap(renum, {v: k for k, v in mapping.items()})
    np.testing.assert_array_equal(back, arr)


def test_unique_ids():
    arr = np.array([0, 3, 3, 7], dtype=np.uint32)
    ids = remap.unique_ids(arr)
    assert set(np.asarray(ids).tolist()) == {3, 7}
    ids, counts = remap.unique_ids(arr, return_counts=True)
    assert dict(zip(ids.tolist(), counts.tolist())) == {3: 2, 7: 1}


def test_gaussian_filter_2d_device_matches_scipy():
    from scipy.ndimage import gaussian_filter

    rng = np.random.default_rng(1)
    arr = rng.random((2, 12, 12)).astype(np.float32)
    dev = filters.gaussian_filter_2d(Chunk(arr).device(), sigma=1.5)
    assert dev.is_on_device
    ref = np.stack([gaussian_filter(a, 1.5, mode="reflect") for a in arr])
    np.testing.assert_allclose(np.asarray(dev.array), ref, atol=1e-4)


def test_native_renumber_remap_matches_numpy_semantics():
    """native/src/remap.cpp (fastremap-equivalent hash path) agrees with
    the numpy path on everything observable: zero preservation, compact id
    range, partition structure, and mapping roundtrips."""
    import pytest

    from chunkflow_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(3)
    arr = (rng.integers(0, 500, (32, 32, 32)) * 97).astype(np.uint64)

    out_nat, map_nat = native.renumber(arr, start_id=5)
    out_np, map_np = remap.renumber(arr, start_id=5)

    assert ((out_nat == 0) == (arr == 0)).all()
    nz = np.unique(out_nat[out_nat != 0])
    assert nz.min() == 5 and nz.size == np.unique(arr[arr != 0]).size
    assert nz.max() == 5 + nz.size - 1  # compact
    # same partition as the numpy relabeling: ids correspond 1:1
    pairs = np.unique(
        np.stack([out_nat.ravel(), out_np.ravel()]), axis=1
    )
    assert pairs.shape[1] == nz.size + 1  # bijection (+ the 0-0 pair)
    # mapping roundtrip
    back = native.remap(out_nat, {v: k for k, v in map_nat.items()})
    assert (back == arr).all()
    # preserve_missing semantics
    some = int(arr[arr != 0].flat[0])
    kept = native.remap(arr, {some: 1}, preserve_missing=True)
    dropped = native.remap(arr, {some: 1}, preserve_missing=False)
    assert (kept[arr == some] == 1).all()
    assert (kept[arr != some] == arr[arr != some]).all()
    assert (dropped[(arr != some) & (arr != 0)] == 0).all()
    assert (dropped[arr == 0] == 0).all()


def test_renumber_paths_bit_identical():
    """numpy and native renumber both use first-appearance ordering
    (fastremap semantics): outputs and mappings are bit-identical, so
    results don't change with array size or toolchain availability."""
    import pytest

    from chunkflow_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(11)
    arr = (rng.integers(0, 97, (24, 24, 24)) * 1009).astype(np.uint32)
    out_np, m_np = remap.renumber(arr)          # small -> numpy path
    out_nat, m_nat = native.renumber(arr)
    assert (out_np == out_nat).all()
    assert m_np == m_nat


def test_native_remap_overflow_guard():
    import pytest

    from chunkflow_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    arr = np.full((128,), 7, dtype=np.uint32)
    with pytest.raises(OverflowError):
        native.remap(arr, {7: 2 ** 40})
