"""Unit tests for the small jax/numpy ops modules."""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.ops import filters, mask as mask_ops, remap, threshold, voting


def test_maskout_upsampled_mask():
    """Mask at a coarser mip multiplies through (reference mask.py:74-81)."""
    chunk = Chunk(np.ones((4, 8, 8), np.float32), voxel_size=(40, 4, 4))
    coarse = Chunk(
        np.ones((4, 4, 4), np.uint8), voxel_size=(40, 8, 8)
    )
    coarse[:, 0, :] = 0  # zero a y-band at coarse resolution
    out = mask_ops.maskout(chunk, coarse)
    arr = np.asarray(out.array)
    assert np.all(arr[:, 0:2, :] == 0)  # coarse band upsamples to fine 2 rows
    assert np.all(arr[:, 2:, :] == 1)


def test_maskout_inverse():
    chunk = Chunk(np.ones((2, 2, 2), np.float32))
    m = Chunk(np.zeros((2, 2, 2), np.uint8))
    out = mask_ops.maskout(chunk, m, inverse=True)
    assert np.all(np.asarray(out.array) == 1)


def test_channel_voting_argmax_plus_one():
    arr = np.zeros((3, 1, 2, 2), np.float32)
    arr[0, 0, 0, 0] = 1.0
    arr[1, 0, 0, 1] = 1.0
    arr[2, 0, 1, 0] = 1.0
    out = voting.channel_voting(Chunk(arr))
    res = np.asarray(out.array)
    assert res.shape == (1, 2, 2)
    assert res[0, 0, 0] == 1 and res[0, 0, 1] == 2 and res[0, 1, 0] == 3


def test_mask_using_last_channel():
    arr = np.zeros((2, 1, 2, 2), np.float32)
    arr[0] = 0.8
    arr[1, 0, 0, 0] = 0.9  # myelin above threshold -> zero out
    out = voting.mask_using_last_channel(Chunk(arr), threshold=0.5)
    res = np.asarray(out.array)
    assert res.shape == (1, 1, 2, 2)
    assert res[0, 0, 0, 0] == 0.0
    assert res[0, 0, 0, 1] == pytest.approx(0.8)


def test_threshold_binary():
    c = Chunk(np.asarray([[[0.2, 0.8]]], dtype=np.float32))
    out = threshold.threshold(c, 0.5)
    res = np.asarray(out.array)
    assert res.dtype == np.uint8
    assert res.tolist() == [[[0, 1]]]


def test_gaussian_filter_2d_matches_scipy():
    from scipy.ndimage import gaussian_filter

    rng = np.random.default_rng(0)
    arr = rng.random((3, 16, 16)).astype(np.float32)
    out = filters.gaussian_filter_2d(Chunk(arr.copy()), sigma=1.0)
    ref = np.stack([gaussian_filter(a, 1.0) for a in arr])
    np.testing.assert_allclose(np.asarray(out.array), ref, atol=2e-2)


def test_median_filter():
    arr = np.zeros((1, 5, 5), np.float32)
    arr[0, 2, 2] = 100.0  # salt noise removed by median
    out = filters.median_filter(Chunk(arr), size=3)
    assert np.asarray(out.array)[0, 2, 2] == 0.0


def test_renumber_and_remap_roundtrip():
    arr = np.array([[[0, 5, 5, 9]]], dtype=np.uint32)
    renum, mapping = remap.renumber(arr)
    assert set(np.unique(renum).tolist()) == {0, 1, 2}
    back = remap.remap(renum, {v: k for k, v in mapping.items()})
    np.testing.assert_array_equal(back, arr)


def test_unique_ids():
    arr = np.array([0, 3, 3, 7], dtype=np.uint32)
    ids = remap.unique_ids(arr)
    assert set(np.asarray(ids).tolist()) == {3, 7}
    ids, counts = remap.unique_ids(arr, return_counts=True)
    assert dict(zip(ids.tolist(), counts.tolist())) == {3: 2, 7: 1}


def test_gaussian_filter_2d_device_matches_scipy():
    from scipy.ndimage import gaussian_filter

    rng = np.random.default_rng(1)
    arr = rng.random((2, 12, 12)).astype(np.float32)
    dev = filters.gaussian_filter_2d(Chunk(arr).device(), sigma=1.5)
    assert dev.is_on_device
    ref = np.stack([gaussian_filter(a, 1.5, mode="reflect") for a in arr])
    np.testing.assert_allclose(np.asarray(dev.array), ref, atol=1e-4)
