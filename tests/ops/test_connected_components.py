"""Device-side CC (label propagation) vs host labeling."""
import numpy as np
import pytest

from chunkflow_tpu.ops import connected_components as cc


def _equivalent_labelings(a: np.ndarray, b: np.ndarray) -> bool:
    """Same partition of foreground, regardless of label values."""
    fg = a > 0
    if not np.array_equal(fg, b > 0):
        return False
    pairs = {}
    for va, vb in zip(a[fg], b[fg]):
        if pairs.setdefault(va, vb) != vb:
            return False
    return len(set(pairs.values())) == len(pairs)


@pytest.mark.parametrize("connectivity", [6, 18, 26])
def test_device_cc_matches_host(connectivity):
    rng = np.random.default_rng(0)
    mask = rng.random((12, 16, 16)) > 0.7
    host = cc.label_binary(mask, connectivity=connectivity)
    dev = np.asarray(cc.label_binary_device(mask, connectivity=connectivity))
    assert _equivalent_labelings(host, dev)


def test_device_cc_two_objects():
    mask = np.zeros((4, 8, 8), bool)
    mask[1, 1:3, 1:3] = True
    mask[2, 5:7, 5:7] = True
    dev = np.asarray(cc.label_binary_device(mask, connectivity=6))
    labels = set(np.unique(dev).tolist()) - {0}
    assert len(labels) == 2
    assert (dev > 0).sum() == mask.sum()


def test_device_cc_empty():
    dev = np.asarray(cc.label_binary_device(np.zeros((4, 4, 4), bool)))
    assert dev.sum() == 0


def test_device_cc_default_connectivity_matches_cc3d_default():
    """label_binary_device defaults to 26 like the host paths."""
    rng = np.random.default_rng(3)
    mask = rng.random((6, 10, 10)) > 0.6
    host = cc.label_binary(mask, connectivity=26)
    dev = np.asarray(cc.label_binary_device(mask))
    assert _equivalent_labelings(host, dev)


def test_device_cc_stays_on_device():
    from chunkflow_tpu.chunk.base import Chunk

    chunk = Chunk(np.asarray(
        np.random.default_rng(0).random((4, 8, 8)), dtype=np.float32
    ))
    out = cc.connected_components(chunk, threshold=0.5, device=True)
    assert out.is_on_device
