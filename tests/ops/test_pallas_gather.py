"""Device-resident front half (ops/pallas_gather.py, ISSUE 15): the
device-gather legs (XLA dynamic_slice default + Pallas kernel in
interpret mode) vs the ``CHUNKFLOW_GATHER=off`` host front half —
BITWISE across the PR 13 parity matrix (plain/ragged/uint8/crop-margin x
single-device and ``data=N``/``y=A,x=B`` meshes), packed-serve traffic,
and every ``CHUNKFLOW_PRECISION``; plus the env-flip-rebuilds contract,
the warn-once env parsing, the direct kernel oracle, and the
``transfer/h2d_*`` staging-seam counters."""
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.inference import engines
from chunkflow_tpu.inference.inferencer import Inferencer

PIN = (4, 16, 16)
OVERLAP = (2, 8, 8)

GATHER_MODES = ["", "interpret"]  # device-resident legs, vs "off" ref


@pytest.fixture
def clean_telemetry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield monkeypatch
    telemetry.reset()


def _traffic_chunk(traffic: str, seed: int):
    rng = np.random.default_rng(seed)
    if traffic == "ragged":
        return Chunk(rng.random((6, 37, 45)).astype(np.float32))
    if traffic == "uint8":
        return Chunk(rng.integers(0, 256, (8, 40, 48), dtype=np.uint8))
    return Chunk(rng.random((8, 40, 48)).astype(np.float32))


def _matrix_inferencer(crop: bool, mesh=None, precision=None):
    if crop:
        engine = engines.create_identity_engine(
            input_patch_size=PIN, output_patch_size=(2, 8, 8),
            num_input_channels=1, num_output_channels=3,
        )
        return Inferencer(
            input_patch_size=PIN,
            output_patch_size=(2, 8, 8),
            output_patch_overlap=(1, 4, 4),
            num_output_channels=3,
            framework="prebuilt",
            batch_size=2,
            engine=engine,
            mesh=mesh,
            precision=precision,
            crop_output_margin=True,
        )
    engine = engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=PIN,
        num_input_channels=1, num_output_channels=3,
    )
    return Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=3,
        framework="prebuilt",
        batch_size=2,
        engine=engine,
        mesh=mesh,
        precision=precision,
        crop_output_margin=False,
    )


# ---------------------------------------------------------------------------
# the ISSUE 15 parity matrix: device gather vs host gather, bitwise
# ---------------------------------------------------------------------------
@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 virtual devices (tests/conftest.py)")
@pytest.mark.parametrize("mesh", [None, "data=2", "y=2,x=2"])
@pytest.mark.parametrize(
    "traffic", ["plain", "ragged", "uint8", "crop_margin"]
)
def test_gather_parity_matrix(monkeypatch, mesh, traffic):
    """ISSUE 15 acceptance: both device-gather legs (XLA dynamic_slice
    default AND the Pallas kernel in interpret mode) are BITWISE
    identical to the CHUNKFLOW_GATHER=off host front across every
    traffic class, single-device and both mesh kinds (the gather front
    runs inside the sharded forward too)."""
    crop = traffic == "crop_margin"
    chunk = _traffic_chunk(traffic, seed=abs(hash(traffic)) % 2**31)
    monkeypatch.setenv("CHUNKFLOW_GATHER", "off")
    ref = np.asarray(_matrix_inferencer(crop, mesh=mesh)(chunk).array)
    for mode in GATHER_MODES:
        monkeypatch.setenv("CHUNKFLOW_GATHER", mode)
        got = np.asarray(_matrix_inferencer(crop, mesh=mesh)(chunk).array)
        assert got.dtype == ref.dtype and got.shape == ref.shape
        assert np.array_equal(got, ref), (
            f"device gather diverged from host gather (mesh={mesh}, "
            f"traffic={traffic}, mode={mode or 'device'}; max abs diff "
            f"{np.abs(got.astype(np.float64) - ref.astype(np.float64)).max():.3e})"
        )


@pytest.mark.parametrize("precision", ["bfloat16", "int8"])
def test_gather_parity_at_precisions(monkeypatch, precision):
    """The bitwise device-vs-host gather contract survives every
    CHUNKFLOW_PRECISION: the front half hands the (wrapped) forward
    bitwise-equal patches, so the quantized outputs are bitwise equal
    too."""
    chunk = _traffic_chunk("uint8", seed=11)
    monkeypatch.setenv("CHUNKFLOW_GATHER", "off")
    ref = np.asarray(
        _matrix_inferencer(False, precision=precision)(chunk).array)
    for mode in GATHER_MODES:
        monkeypatch.setenv("CHUNKFLOW_GATHER", mode)
        got = np.asarray(
            _matrix_inferencer(False, precision=precision)(chunk).array)
        assert np.array_equal(got, ref), (precision, mode)


def test_gather_parity_packed_serve(monkeypatch):
    """Packed-serve traffic with the device-resident front (request
    chunk uploaded once, batches gathered on device across requests) is
    bitwise identical to the host-gather packed path AND the per-chunk
    path — for both device legs, on uint8 AND float32 ragged traffic."""
    from chunkflow_tpu.serve.packer import PatchPacker

    def chunks():
        rng = np.random.default_rng(5)
        out = []
        for i in range(4):
            if i % 2:
                out.append(Chunk(
                    rng.integers(0, 256, (4, 16, 48), dtype=np.uint8),
                    voxel_offset=(8 * i, 0, 0)))
            else:
                out.append(Chunk(
                    rng.random((4, 16, 48), dtype=np.float32),
                    voxel_offset=(8 * i, 0, 0)))
        return out

    def packed(mode):
        monkeypatch.setenv("CHUNKFLOW_GATHER", mode)
        inf = Inferencer(
            input_patch_size=PIN,
            num_output_channels=2,
            framework="identity",
            batch_size=4,
            crop_output_margin=False,
        )
        packer = PatchPacker(inf, max_wait_ms=2.0)
        try:
            handles = [packer.submit(c) for c in chunks()]
            return [np.asarray(h.result(timeout=60).array)
                    for h in handles]
        finally:
            packer.close()

    ref = packed("off")
    for mode in GATHER_MODES:
        got = packed(mode)
        monkeypatch.setenv("CHUNKFLOW_GATHER", mode)
        inf = Inferencer(
            input_patch_size=PIN,
            num_output_channels=2,
            framework="identity",
            batch_size=4,
            crop_output_margin=False,
        )
        per_chunk = [np.asarray(inf(c).array) for c in chunks()]
        for r, g, p in zip(ref, got, per_chunk):
            assert np.array_equal(g, r), (mode,)
            assert np.array_equal(g, p), (mode,)


def test_gather_key_rebuilds_on_env_flip(monkeypatch):
    """Flipping CHUNKFLOW_GATHER mid-stream builds the selected front's
    program under its own cache key instead of reusing a stale one (the
    CHUNKFLOW_PALLAS/CHUNKFLOW_MESH re-read convention) — and the
    default device leg keeps the historical ``("scatter",)`` key."""
    monkeypatch.setenv("CHUNKFLOW_GATHER", "")
    inf = Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=2,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(1)
    chunk = Chunk(rng.integers(0, 256, (8, 32, 32), dtype=np.uint8))
    ref = np.asarray(inf(chunk).array)
    assert ("scatter",) in inf._programs
    monkeypatch.setenv("CHUNKFLOW_GATHER", "off")
    got = np.asarray(inf(chunk).array)
    assert ("scatter", "gather-host") in inf._programs
    assert np.array_equal(got, ref)
    monkeypatch.setenv("CHUNKFLOW_GATHER", "interpret")
    got = np.asarray(inf(chunk).array)
    # the interpret tag carries "+kc" while the kernelcheck sanitizer
    # is live (its hooks are part of the program identity)
    from chunkflow_tpu.testing import kernelcheck
    tag = f"gather-pallas-interpret{kernelcheck.key_suffix()}"
    assert ("scatter", tag) in inf._programs
    assert np.array_equal(got, ref)
    assert inf._programs.builds == 3


# ---------------------------------------------------------------------------
# env parsing: warn once on unrecognized values (ISSUE 15 satellite)
# ---------------------------------------------------------------------------
def test_gather_mode_warns_once_on_typo(monkeypatch, capsys):
    """A mistyped CHUNKFLOW_GATHER must not silently pick a front: one
    stderr warning per unrecognized value (resolving to the default
    device leg), then quiet; recognized values never warn."""
    from chunkflow_tpu.ops import pallas_gather

    monkeypatch.setattr(pallas_gather, "_WARNED_VALUES", set())
    monkeypatch.setenv("CHUNKFLOW_GATHER", "divice")
    assert pallas_gather.gather_mode() == "device"
    err = capsys.readouterr().err
    assert "divice" in err and "not a recognized value" in err
    # second call with the same typo: silent (warned once)
    assert pallas_gather.gather_mode() == "device"
    assert capsys.readouterr().err == ""
    # a DIFFERENT typo warns again
    monkeypatch.setenv("CHUNKFLOW_GATHER", "yes please")
    assert pallas_gather.gather_mode() == "device"
    assert "not a recognized value" in capsys.readouterr().err
    # recognized values never warn
    for value, expected in [("", "device"), ("on", "device"),
                            ("device", "device"), ("xla", "device"),
                            ("0", "host"), ("off", "host"),
                            ("host", "host"), ("pallas", "pallas"),
                            ("force", "pallas"),
                            ("interpret", "interpret")]:
        monkeypatch.setenv("CHUNKFLOW_GATHER", value)
        assert pallas_gather.gather_mode() == expected
    assert capsys.readouterr().err == ""


# ---------------------------------------------------------------------------
# direct kernel checks
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", ["uint8", "uint16", "float32"])
def test_gather_kernel_vs_numpy(dtype):
    """Direct kernel oracle: window DMA + in-VMEM conversion must
    reproduce numpy's convert-then-slice bitwise — for int dtypes
    (normalized by 1/iinfo.max) and float32 (no conversion) — at starts
    with no (sublane, 128) alignment at all."""
    import jax.numpy as jnp

    from chunkflow_tpu.ops import pallas_gather

    rng = np.random.default_rng(7)
    ci, shape = 2, (9, 40, 50)
    pin = (3, 12, 18)
    if dtype == "float32":
        raw = rng.standard_normal((ci,) + shape).astype(np.float32)
        expected_full = raw
    else:
        info = np.iinfo(np.dtype(dtype))
        raw = rng.integers(0, info.max, (ci,) + shape).astype(dtype)
        expected_full = raw.astype(np.float32) * np.float32(1.0 / info.max)
    starts = np.array(
        [[0, 0, 0], [1, 7, 13], [6, 28, 32], [2, 19, 5]], np.int32
    )
    pad_y, pad_x = pallas_gather.gather_buffer_padding(pin, raw.dtype)
    padded = np.pad(raw, [(0, 0), (0, 0), (0, pad_y), (0, pad_x)])
    got = np.asarray(pallas_gather.gather_patches(
        jnp.asarray(padded), jnp.asarray(starts), pin, interpret=True))
    assert got.dtype == np.float32
    for b, (z, y, x) in enumerate(starts):
        exp = expected_full[:, z:z + pin[0], y:y + pin[1], x:x + pin[2]]
        assert np.array_equal(got[b], exp), (dtype, b)


def test_gather_window_alignment_by_dtype():
    """The aligned-window geometry follows the dtype's Mosaic tiling:
    8 sublanes for f32, 16 for 16-bit, 32 for 8-bit; lanes always
    128."""
    from chunkflow_tpu.ops import pallas_gather

    assert pallas_gather.gather_window(12, 18, np.float32) == (24, 256)
    assert pallas_gather.gather_window(12, 18, np.uint16) == (32, 256)
    assert pallas_gather.gather_window(12, 18, np.uint8) == (64, 256)
    # buffer padding covers the worst-case round-down at a flush edge
    for dt in (np.float32, np.uint16, np.uint8):
        wy, wx = pallas_gather.gather_window(12, 18, dt)
        assert pallas_gather.gather_buffer_padding((3, 12, 18), dt) == (
            wy - 12, wx - 18)


# ---------------------------------------------------------------------------
# the staging seam: transfer/h2d_* counters (ISSUE 15 satellite)
# ---------------------------------------------------------------------------
def test_h2d_counter_once_per_chunk(clean_telemetry):
    """The sync per-chunk path counts exactly one raw-chunk upload at
    the staging seam, attributed to the consuming program family in the
    profiling catalog."""
    from chunkflow_tpu.core import profiling

    clean_telemetry.setenv("CHUNKFLOW_GATHER", "")
    inf = Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=2,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(2)
    arr = rng.integers(0, 256, (8, 32, 32), dtype=np.uint8)
    inf(Chunk(arr))
    snap = telemetry.snapshot()
    assert snap["counters"]["transfer/h2d_chunks"] == 1
    assert snap["counters"]["transfer/h2d_bytes"] == arr.nbytes
    assert profiling.h2d_by_family().get("scatter") == arr.nbytes
    # the programs.json catalog carries the per-family column
    entries = {e["family"]: e for e in profiling.catalog()}
    assert entries["scatter"]["h2d_bytes"] == arr.nbytes


def test_h2d_counter_staged_chunk(clean_telemetry):
    """Pipeline-staged chunks count at Chunk.device (raw bytes, once);
    the already-resident chunk is NOT recounted at dispatch."""
    clean_telemetry.setenv("CHUNKFLOW_GATHER", "")
    inf = Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=2,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(3)
    arr = rng.integers(0, 256, (8, 32, 32), dtype=np.uint8)
    staged = inf.stage(Chunk(arr))
    assert staged.is_on_device
    inf.infer_async(staged, consume=True).array.block_until_ready()
    snap = telemetry.snapshot()
    assert snap["counters"]["transfer/h2d_chunks"] == 1
    assert snap["counters"]["transfer/h2d_bytes"] == arr.nbytes


def test_h2d_packed_serve_device_vs_host(clean_telemetry):
    """The acceptance byte contract: with the device front a request's
    chunk crosses H2D ONCE at raw size; the host front re-uploads every
    gathered patch as float32 — ~(patch/stride)^3 x more bytes, visible
    on the same counter."""
    from chunkflow_tpu.serve.packer import PatchPacker

    def run(mode):
        clean_telemetry.setenv("CHUNKFLOW_GATHER", mode)
        telemetry.reset()
        inf = Inferencer(
            input_patch_size=PIN,
            output_patch_overlap=OVERLAP,
            num_output_channels=2,
            framework="identity",
            batch_size=2,
            crop_output_margin=False,
        )
        rng = np.random.default_rng(9)
        arr = rng.integers(0, 256, (8, 32, 32), dtype=np.uint8)
        packer = PatchPacker(inf, max_wait_ms=1.0)
        try:
            out = packer.submit(Chunk(arr)).result(timeout=60)
            assert out is not None
        finally:
            packer.close()
        return arr.nbytes, telemetry.snapshot()["counters"]

    nbytes, device_counters = run("")
    assert device_counters["transfer/h2d_chunks"] == 1
    assert device_counters["transfer/h2d_bytes"] == nbytes
    _, host_counters = run("off")
    # the host front ships gathered float32 batches: strictly more
    # bytes than the raw chunk — the (patch/stride)^3 x overlap factor
    # times the 4x dtype widening
    assert host_counters["transfer/h2d_bytes"] >= 4 * nbytes
    telemetry.reset()


# ---------------------------------------------------------------------------
# graftlint pin over the ISSUE 15 modules
# ---------------------------------------------------------------------------
def test_gather_modules_are_graftlint_clean():
    """ISSUE 15 acceptance: GL001-GL014 clean over the new/changed
    front-half modules, asserted in-suite (the whole-repo gate covers
    them too; this pins the specific modules so a future baseline
    regeneration cannot quietly grandfather a finding here)."""
    from pathlib import Path

    from tools.graftlint.config import load_config
    from tools.graftlint.engine import lint_paths

    repo_root = Path(__file__).resolve().parents[2]
    config = load_config(repo_root / "pyproject.toml")
    findings, _ = lint_paths(
        [
            "chunkflow_tpu/ops/pallas_gather.py",
            "chunkflow_tpu/ops/blend.py",
            "chunkflow_tpu/inference/inferencer.py",
            "chunkflow_tpu/serve/packer.py",
            "chunkflow_tpu/serve/frontend.py",
            "chunkflow_tpu/parallel/engine.py",
            "chunkflow_tpu/chunk/base.py",
            "chunkflow_tpu/core/profiling.py",
            "chunkflow_tpu/flow/log_summary.py",
        ],
        config, repo_root=repo_root,
    )
    assert not findings, [
        f"{f.path}:{f.line}: {f.code} {f.message}" for f in findings
    ]
