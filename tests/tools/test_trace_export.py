"""Perfetto/Chrome-trace export (ISSUE 18): clock-skew normalization,
flow-event pairing, schema validation, and the loader's tolerance of
rotated generations and torn tails — all over synthetic JSONL streams,
so every invariant the CI stage asserts on the real fleet smoke is
pinned in isolation here.
"""
import json

import pytest

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.flow.log_summary import (
    load_telemetry_dir,
    trace_timeline,
    worker_clock_offsets,
)
from tools.trace_export import (
    export_chrome_trace,
    export_metrics_dir,
    validate_chrome_trace,
)


@pytest.fixture(autouse=True)
def clean_telemetry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def _write_events(path, events, torn_tail=None):
    with open(path, "w") as f:
        for event in events:
            f.write(json.dumps(event) + "\n")
        if torn_tail is not None:
            f.write(torn_tail)  # no newline: a mid-write crash


def _skewed_stream():
    """Submitter 'wa' runs on the reference clock; claimer 'wb' runs a
    clock 3 seconds BEHIND, so its raw claim stamp (t=97) lands before
    the submit it provably followed (t=100)."""
    return [
        {"kind": "task", "name": "queue/submit", "t": 100.0,
         "worker": "wa", "trace_id": "t1", "body": "bbox-1"},
        {"kind": "span", "name": "pipeline/compute", "t": 101.0,
         "dur_s": 0.5, "worker": "wa"},
        {"kind": "task", "name": "lifecycle/claimed", "t": 97.0,
         "worker": "wb", "trace_id": "t1", "body": "bbox-1"},
        {"kind": "task", "name": "lifecycle/committed", "t": 97.5,
         "worker": "wb", "trace_id": "t1", "body": "bbox-1"},
        {"kind": "gauge", "name": "device/bytes_in_use", "t": 97.2,
         "value": 2048.0, "worker": "wb"},
        {"kind": "snapshot", "t": 98.0, "worker": "wb",
         "counters": {"tasks/committed": 1.0}},
    ]


# ---------------------------------------------------------------------------
# clock-skew normalization (satellite: queue send/receive pairs)
# ---------------------------------------------------------------------------
def test_worker_clock_offsets_minimal_monotone_correction():
    offsets = worker_clock_offsets(_skewed_stream())
    # claim at 97 vs submit at 100: wb shifts forward by exactly the
    # gap (the minimal correction), wa (the reference) stays put
    assert offsets == {"wb": pytest.approx(3.0)}


def test_worker_clock_offsets_no_skew_no_offsets():
    events = _skewed_stream()
    for e in events:
        if e["worker"] == "wb":
            e["t"] += 10.0  # claim now AFTER submit: causality holds
    assert worker_clock_offsets(events) == {}


def test_trace_timeline_orders_across_skewed_clocks():
    timeline = trace_timeline(_skewed_stream(), "t1")
    assert [e["name"] for e in timeline] == [
        "queue/submit", "lifecycle/claimed", "lifecycle/committed",
    ]


# ---------------------------------------------------------------------------
# export: schema, flows, counters
# ---------------------------------------------------------------------------
def test_export_schema_valid_with_cross_worker_flow():
    trace = export_chrome_trace(_skewed_stream())
    assert validate_chrome_trace(trace) == []
    events = trace["traceEvents"]
    assert trace["otherData"]["workers"] == 2
    assert trace["otherData"]["flow_pairs"] == 1
    # two worker processes, named
    procs = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"worker wa", "worker wb"}
    # the span renders as a complete event with µs duration
    spans = [e for e in events if e.get("ph") == "X"]
    assert [s["name"] for s in spans] == ["pipeline/compute"]
    assert spans[0]["dur"] == pytest.approx(0.5e6)
    # the gauge and the snapshot counter render as counter tracks
    cats = {e["name"]: e["cat"] for e in events if e.get("ph") == "C"}
    assert cats == {"device/bytes_in_use": "gauge",
                    "tasks/committed": "cumulative"}
    # the hop renders as one paired flow: a start on wa's submit and a
    # finish on wb's claim, finish never before start
    starts = [e for e in events if e.get("ph") == "s"]
    finishes = [e for e in events if e.get("ph") == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["id"] == finishes[0]["id"]
    assert starts[0]["pid"] != finishes[0]["pid"]
    assert finishes[0]["ts"] >= starts[0]["ts"]
    assert finishes[0]["bp"] == "e"
    # timestamps are relative to the earliest event: non-negative
    assert min(e["ts"] for e in events) >= 0


def test_export_single_worker_task_needs_no_flow():
    events = [e for e in _skewed_stream() if e["worker"] == "wa"]
    events.append({"kind": "task", "name": "lifecycle/claimed",
                   "t": 100.5, "worker": "wa", "trace_id": "t1"})
    trace = export_chrome_trace(events)
    assert validate_chrome_trace(trace) == []
    assert trace["otherData"]["flow_pairs"] == 0
    assert not [e for e in trace["traceEvents"]
                if e.get("ph") in ("s", "t", "f")]


def test_validator_flags_broken_traces():
    problems = validate_chrome_trace({"traceEvents": [
        {"ph": "X", "name": "no-dur", "pid": 1, "tid": 1, "ts": 0.0},
        {"ph": "i", "name": "no-pid", "tid": 1, "ts": 0.0},
        {"ph": "s", "name": "orphan", "id": 9, "pid": 1, "tid": 1,
         "ts": 5.0},
        {"ph": "C", "name": "ctr", "cat": "cumulative", "pid": 1,
         "tid": 0, "ts": 0.0, "args": {"value": 5.0}},
        {"ph": "C", "name": "ctr", "cat": "cumulative", "pid": 1,
         "tid": 0, "ts": 1.0, "args": {"value": 3.0}},
    ]})
    assert any("non-negative dur" in p for p in problems)
    assert any("bad pid" in p for p in problems)
    assert any("flow 9" in p for p in problems)
    assert any("decreases" in p for p in problems)
    assert validate_chrome_trace({"traceEvents": None}) \
        == ["traceEvents is not a list"]


# ---------------------------------------------------------------------------
# per-chip counter tracks (ISSUE 19: shard/chip/<i>/* gauges)
# ---------------------------------------------------------------------------
def test_export_chip_gauges_render_as_per_chip_tracks():
    """``<plane>/chip/<i>/<metric>`` gauges get their own ``chip <i>``
    thread track per worker (so a mesh run shows replay-buffer bytes /
    HBM watermarks side by side per chip), while plain gauges stay on
    the global tid-0 track."""
    events = [
        {"kind": "gauge", "name": "shard/chip/0/replay_buffer_bytes",
         "t": 10.0, "value": 4096.0, "worker": "wa"},
        {"kind": "gauge", "name": "shard/chip/1/replay_buffer_bytes",
         "t": 10.0, "value": 4096.0, "worker": "wa"},
        {"kind": "gauge", "name": "device/chip/1/hbm_headroom",
         "t": 10.5, "value": 1e9, "worker": "wa"},
        {"kind": "gauge", "name": "shard/n_devices", "t": 10.0,
         "value": 2.0, "worker": "wa"},
    ]
    trace = export_chrome_trace(events)
    assert validate_chrome_trace(trace) == []
    counters = [e for e in trace["traceEvents"] if e.get("ph") == "C"]
    chip = [e for e in counters if e["cat"] == "chip_gauge"]
    plain = [e for e in counters if e["cat"] == "gauge"]
    # chip prefix stripped from the counter name, chip carried as an arg
    assert {e["name"] for e in chip} == {"shard/replay_buffer_bytes",
                                         "device/hbm_headroom"}
    assert {e["args"]["chip"] for e in chip} == {0, 1}
    # per-chip samples land on distinct non-global tracks...
    assert all(e["tid"] != 0 for e in chip)
    by_chip = {}
    for e in chip:
        by_chip.setdefault(e["args"]["chip"], set()).add(e["tid"])
    assert by_chip[0].isdisjoint(by_chip[1])
    # ...named "chip <i>" in the thread metadata
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert {"chip 0", "chip 1"} <= names
    # the plain gauge stays on the global track
    assert plain and all(e["tid"] == 0 for e in plain)


def test_validator_flags_broken_chip_tracks():
    problems = validate_chrome_trace({"traceEvents": [
        {"ph": "C", "name": "shard/replay_buffer_bytes",
         "cat": "chip_gauge", "pid": 1, "tid": 3, "ts": 0.0,
         "args": {"value": 1.0}},  # no chip arg
        {"ph": "C", "name": "shard/replay_buffer_bytes",
         "cat": "chip_gauge", "pid": 1, "tid": 4, "ts": 0.0,
         "args": {"value": 1.0, "chip": 0}},
        {"ph": "C", "name": "shard/replay_buffer_bytes",
         "cat": "chip_gauge", "pid": 1, "tid": 4, "ts": 1.0,
         "args": {"value": 1.0, "chip": 1}},  # same track, other chip
    ]})
    assert any("integer chip arg" in p for p in problems)
    assert any("mixes chips 0 and 1" in p for p in problems)


# ---------------------------------------------------------------------------
# loader round trip: rotated generations + torn tail (satellite)
# ---------------------------------------------------------------------------
def test_export_metrics_dir_rotations_and_torn_tail(tmp_path):
    events = _skewed_stream()
    wa = [e for e in events if e["worker"] == "wa"]
    wb = [e for e in events if e["worker"] == "wb"]
    # wa's stream spans three generations: .2 (oldest) -> .1 -> live,
    # and the live file ends in a torn line from a mid-write crash
    _write_events(tmp_path / "telemetry-wa.jsonl.2", wa[:1])
    _write_events(tmp_path / "telemetry-wa.jsonl.1", [])
    _write_events(tmp_path / "telemetry-wa.jsonl", wa[1:],
                  torn_tail='{"kind": "span", "name": "torn"')
    _write_events(tmp_path / "telemetry-wb.jsonl", wb)

    loaded = load_telemetry_dir(str(tmp_path))
    assert len(loaded) == len(events)  # torn tail skipped, not fatal
    assert not any(e.get("name") == "torn" for e in loaded)
    # generations load oldest-first so wa's stream stays in order
    wa_names = [e.get("name") for e in loaded
                if e.get("worker") == "wa"]
    assert wa_names == [e.get("name") for e in wa]
    # the skewed trace still reconstructs in causal order
    assert [e["name"] for e in trace_timeline(loaded, "t1")] == [
        "queue/submit", "lifecycle/claimed", "lifecycle/committed",
    ]

    out = tmp_path / "trace.json"
    stats = export_metrics_dir(str(tmp_path), str(out))
    assert stats["problems"] == []
    assert stats["events"] == len(events)
    assert stats["workers"] == 2
    assert stats["flow_pairs"] == 1
    on_disk = json.loads(out.read_text())
    assert len(on_disk["traceEvents"]) == stats["trace_events"]


def test_cli_export_trace_flag(tmp_path):
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main

    metrics = tmp_path / "metrics"
    metrics.mkdir()
    events = _skewed_stream()
    _write_events(metrics / "telemetry-wa.jsonl",
                  [e for e in events if e["worker"] == "wa"])
    _write_events(metrics / "telemetry-wb.jsonl",
                  [e for e in events if e["worker"] == "wb"])
    out = tmp_path / "trace.json"
    result = CliRunner().invoke(
        main,
        ["log-summary", "--metrics-dir", str(metrics),
         "--export-trace", str(out)],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "2 worker process(es)" in result.output
    assert "1 cross-worker flow(s)" in result.output
    assert "trace validation:" not in result.output
    trace = json.loads(out.read_text())
    assert validate_chrome_trace(trace) == []


def test_cli_export_trace_requires_metrics_dir():
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main

    result = CliRunner().invoke(
        main, ["log-summary", "--export-trace", "out.json"])
    assert result.exit_code != 0
