"""graftlint rule tests: one positive and one suppressed case per rule,
plus the traced-context analysis and baseline machinery the rules rest on.
"""
import textwrap

import pytest

from tools.graftlint.config import Config
from tools.graftlint.engine import lint_file


def run(src, path="chunkflow_tpu/ops/example.py", config=None):
    findings, suppressed = lint_file(
        path, textwrap.dedent(src), config or Config()
    )
    return findings, suppressed


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------- GL001
GL001_POSITIVE = """\
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        y = np.asarray(x)
        return y.item()
"""


def test_gl001_detects_host_sync_in_jit():
    findings, _ = run(GL001_POSITIVE)
    assert codes(findings).count("GL001") == 2  # np.asarray AND .item()
    assert all(f.context == "f" for f in findings)


def test_gl001_suppressed():
    src = GL001_POSITIVE.replace(
        "y = np.asarray(x)", "y = np.asarray(x)  # graftlint: disable=GL001"
    ).replace(
        "return y.item()", "return y.item()  # graftlint: disable=GL001"
    )
    findings, suppressed = run(src)
    assert "GL001" not in codes(findings)
    assert suppressed == 2


def test_gl001_ignores_host_code():
    # same calls OUTSIDE jit are legitimate chunk-boundary host syncs
    findings, _ = run("""\
        import numpy as np

        def host(x):
            return np.asarray(x).item()
    """)
    assert "GL001" not in codes(findings)


# ---------------------------------------------------------------- GL002
GL002_POSITIVE = """\
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        return np.exp(x) + np.sum(x)
"""


def test_gl002_detects_numpy_op_on_tracer():
    findings, _ = run(GL002_POSITIVE)
    assert codes(findings).count("GL002") == 2


def test_gl002_suppressed():
    src = GL002_POSITIVE.replace(
        "return np.exp(x) + np.sum(x)",
        "return np.exp(x) + np.sum(x)  # graftlint: disable=GL002",
    )
    findings, suppressed = run(src)
    assert "GL002" not in codes(findings)
    assert suppressed == 2


def test_gl002_allows_static_numpy():
    # dtype metadata and scalar constructors are trace-safe
    findings, _ = run("""\
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            scale = np.float32(1.0 / np.iinfo(np.uint8).max)
            return x * scale
    """)
    assert "GL002" not in codes(findings)


# ---------------------------------------------------------------- GL003
GL003_POSITIVE = """\
    import jax

    @jax.jit
    def f(x):
        y = x + 1
        if y > 0:
            return y
        return -y
"""


def test_gl003_detects_tracer_branch():
    findings, _ = run(GL003_POSITIVE)
    assert "GL003" in codes(findings)


def test_gl003_suppressed():
    src = GL003_POSITIVE.replace(
        "if y > 0:", "if y > 0:  # graftlint: disable=GL003"
    )
    findings, _ = run(src)
    assert "GL003" not in codes(findings)


def test_gl003_allows_static_shape_branch():
    findings, _ = run("""\
        import jax

        @jax.jit
        def f(x):
            if x.ndim == 3:
                x = x[None]
            if x.shape[0] > 4:
                return x[:4]
            n = x.shape[1]
            while n > 8:
                n //= 2
            return x
    """)
    assert "GL003" not in codes(findings)


# ---------------------------------------------------------------- GL004
GL004_POSITIVE = """\
    import numpy as np

    def make_weights(n):
        acc = np.zeros((n, n))
        return acc.mean()
"""


def test_gl004_detects_implicit_float64_in_scoped_path():
    findings, _ = run(GL004_POSITIVE)
    assert codes(findings).count("GL004") == 2  # zeros w/o dtype + bare mean


def test_gl004_suppressed():
    src = GL004_POSITIVE.replace(
        "acc = np.zeros((n, n))",
        "acc = np.zeros((n, n))  # graftlint: disable=GL004",
    ).replace(
        "return acc.mean()",
        "return acc.mean()  # graftlint: disable=GL004",
    )
    findings, _ = run(src)
    assert "GL004" not in codes(findings)


def test_gl004_out_of_scope_path_not_checked():
    findings, _ = run(GL004_POSITIVE, path="chunkflow_tpu/flow/example.py")
    assert "GL004" not in codes(findings)


def test_gl004_positional_dtype_accepted():
    findings, _ = run("""\
        import numpy as np

        def f(shape):
            return np.full(shape, 0.5, np.float32)
    """)
    assert "GL004" not in codes(findings)


def test_gl004_file_wide_disable():
    findings, suppressed = run(
        "# metrics accumulate in float64  # graftlint: disable-file=GL004\n"
        + textwrap.dedent(GL004_POSITIVE)
    )
    assert "GL004" not in codes(findings)
    assert suppressed == 2


# ---------------------------------------------------------------- GL005
GL005_POSITIVE = """\
    import jax

    def build_program():
        def program(chunk, params):
            return chunk * 2
        return jax.jit(program)
"""


def test_gl005_detects_missing_donation():
    findings, _ = run(GL005_POSITIVE)
    assert "GL005" in codes(findings)


def test_gl005_suppressed():
    src = GL005_POSITIVE.replace(
        "return jax.jit(program)",
        "return jax.jit(program)  # graftlint: disable=GL005",
    )
    findings, _ = run(src)
    assert "GL005" not in codes(findings)


def test_gl005_donation_satisfies():
    findings, _ = run("""\
        import jax

        def build_program():
            def program(chunk, params):
                return chunk * 2
        return_value = None

        @jax.jit
        def other(params):
            return params
    """)
    assert "GL005" not in codes(findings)
    findings, _ = run("""\
        import jax

        def build_program():
            def program(chunk, params):
                return chunk * 2
            return jax.jit(program, donate_argnums=(0,))
    """)
    assert "GL005" not in codes(findings)


def test_gl005_decorator_form():
    findings, _ = run("""\
        import functools

        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def f(chunk, n):
            return chunk * n
    """)
    assert "GL005" in codes(findings)


# ---------------------------------------------------------------- GL006
GL006_POSITIVE = """\
    import numpy as np

    def save(chunk):
        arr = np.transpose(chunk, (3, 2, 1, 0))
        return arr
"""


def test_gl006_detects_unannotated_shuffle():
    findings, _ = run(GL006_POSITIVE)
    assert "GL006" in codes(findings)


def test_gl006_suppressed():
    src = GL006_POSITIVE.replace(
        "arr = np.transpose(chunk, (3, 2, 1, 0))",
        "arr = np.transpose(chunk, (3, 2, 1, 0))  "
        "# graftlint: disable=GL006",
    )
    findings, _ = run(src)
    assert "GL006" not in codes(findings)


def test_gl006_axis_comment_satisfies():
    src = GL006_POSITIVE.replace(
        "arr = np.transpose(chunk, (3, 2, 1, 0))",
        "arr = np.transpose(chunk, (3, 2, 1, 0))  # czyx -> xyzc",
    )
    findings, _ = run(src)
    assert "GL006" not in codes(findings)


def test_gl006_named_helper_satisfies():
    findings, _ = run("""\
        def transpose_to_xyzc(chunk):
            return chunk.transpose(3, 2, 1, 0)
    """)
    assert "GL006" not in codes(findings)


# ---------------------------------------------------------------- GL007
GL007_POSITIVE = """\
    import jax
    import time
    from chunkflow_tpu.core.telemetry import span

    @jax.jit
    def f(x):
        t0 = time.perf_counter()
        with span("inference/body"):
            y = x * 2
        return y, time.perf_counter() - t0
"""


def test_gl007_detects_telemetry_in_jit():
    findings, _ = run(GL007_POSITIVE)
    # two perf_counter calls + the span call
    assert codes(findings).count("GL007") == 3


def test_gl007_suppressed():
    src = GL007_POSITIVE.replace(
        "t0 = time.perf_counter()",
        "t0 = time.perf_counter()  # graftlint: disable=GL007",
    ).replace(
        'with span("inference/body"):',
        'with span("inference/body"):  # graftlint: disable=GL007',
    ).replace(
        "return y, time.perf_counter() - t0",
        "return y, time.perf_counter() - t0  # graftlint: disable=GL007",
    )
    findings, suppressed = run(src)
    assert "GL007" not in codes(findings)
    assert suppressed == 3


def test_gl007_ignores_host_side_telemetry():
    # spans AROUND dispatch/wait are exactly the designed pattern
    findings, _ = run("""\
        import time
        from chunkflow_tpu.core import telemetry

        def drain(out):
            t0 = time.perf_counter()
            with telemetry.span("pipeline/drain"):
                host = out.host()
            telemetry.observe("pipeline/drain_s", time.perf_counter() - t0)
            return host
    """)
    assert "GL007" not in codes(findings)


def test_gl007_covers_task_context_helpers():
    """The ISSUE 6 fleet helpers (task_context, worker_id,
    current_trace_id) resolve under chunkflow_tpu.core.telemetry.* like
    every other telemetry call, so trace stamping can never leak into a
    jitted function — stamping belongs around the dispatch, not in it."""
    findings, _ = run("""\
        import jax
        from chunkflow_tpu.core import telemetry

        @jax.jit
        def f(x):
            with telemetry.task_context(telemetry.current_trace_id()):
                return x * telemetry.worker_id().__len__()
    """)
    assert codes(findings).count("GL007") == 3


def test_gl007_module_alias_and_traced_callee():
    # `telemetry.inc` via module import, inside a lax.scan callback
    findings, _ = run("""\
        from jax import lax
        from chunkflow_tpu.core import telemetry

        def step(carry, x):
            telemetry.inc("bad/under_trace")
            return carry, x

        def outer(xs):
            return lax.scan(step, None, xs)
    """)
    assert "GL007" in codes(findings)


# ------------------------------------------------- traced-context engine
def test_traced_via_lax_scan_callback():
    findings, _ = run("""\
        import numpy as np
        from jax import lax

        def step(carry, x):
            return carry, np.exp(x)

        def outer(xs):
            return lax.scan(step, None, xs)
    """)
    assert "GL002" in codes(findings)


def test_traced_via_build_closure_and_callee_propagation():
    # helper() is traced because the build_* closure calls it
    findings, _ = run("""\
        import numpy as np

        def build_blend():
            def helper(x):
                return np.square(x)

            def blend(chunk):
                return helper(chunk)
            return blend
    """)
    assert "GL002" in codes(findings)


def test_syntax_error_reports_gl000():
    findings, _ = run("def broken(:\n    pass\n")
    assert codes(findings) == ["GL000"]


def test_select_limits_rules():
    findings, _ = run(GL001_POSITIVE, config=Config(select=["GL003"]))
    assert "GL001" not in codes(findings)
    with pytest.raises(ValueError):
        run(GL001_POSITIVE, config=Config(select=["GL999"]))


# ------------------------------------------------------------- baseline
def test_baseline_roundtrip_and_diff(tmp_path):
    from tools.graftlint.baseline import (
        diff_baseline, load_baseline, write_baseline,
    )

    findings, _ = run(GL001_POSITIVE)
    assert len(findings) == 2
    path = tmp_path / "baseline.json"
    write_baseline(path, findings[:1])
    baseline = load_baseline(path)

    new, grandfathered, stale = diff_baseline(findings, baseline)
    assert grandfathered == 1 and len(new) == 1 and stale == 0

    # all fixed -> the baselined entry goes stale, nothing new
    new, grandfathered, stale = diff_baseline([], baseline)
    assert new == [] and grandfathered == 0 and stale == 1


def test_baseline_key_survives_line_shift():
    findings_a, _ = run(GL001_POSITIVE)
    findings_b, _ = run("# a new leading comment\n"
                        + textwrap.dedent(GL001_POSITIVE))
    assert [f.baseline_key for f in findings_a] == \
        [f.baseline_key for f in findings_b]
    assert [f.line for f in findings_a] != [f.line for f in findings_b]


def test_missing_baseline_is_empty(tmp_path):
    from tools.graftlint.baseline import load_baseline

    assert load_baseline(tmp_path / "nope.json") == {}


# ------------------------------------------------------------------ CLI
def test_cli_end_to_end(tmp_path, monkeypatch, capsys):
    from tools.graftlint.cli import main

    pkg = tmp_path / "chunkflow_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(textwrap.dedent(GL001_POSITIVE))
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftlint]\ninclude = ["chunkflow_tpu"]\n'
        'baseline = "baseline.json"\n'
    )
    monkeypatch.chdir(tmp_path)

    assert main([]) == 1  # new findings, no baseline yet
    assert main(["--write-baseline"]) == 0
    assert main([]) == 0  # grandfathered now
    out = capsys.readouterr().out
    assert "0 new findings" in out and "2 grandfathered" in out

    assert main(["--json", "--no-baseline"]) == 1
    import json

    payload = json.loads(capsys.readouterr().out)
    assert len(payload["new"]) == 2
    assert {f["code"] for f in payload["new"]} == {"GL001"}

    assert main(["--explain", "GL003"]) == 0
    assert "tracer" in capsys.readouterr().out.lower()
