"""The CI gate itself, as a tier-1 test: graftlint over chunkflow_tpu/
must be clean against the checked-in baseline. A failure here means a NEW
GL violation entered the codebase — fix it or (deliberately) regenerate
the baseline with `python -m tools.graftlint --write-baseline`.
"""
from pathlib import Path

from tools.graftlint.baseline import diff_baseline, load_baseline
from tools.graftlint.config import load_config
from tools.graftlint.engine import lint_paths

REPO_ROOT = Path(__file__).resolve().parents[2]


def test_graftlint_clean_against_baseline():
    config = load_config(REPO_ROOT / "pyproject.toml")
    findings, _ = lint_paths(config.include, config, repo_root=REPO_ROOT)
    baseline = load_baseline(REPO_ROOT / config.baseline)
    new, _, _ = diff_baseline(findings, baseline)
    assert not new, (
        "new graftlint findings (see docs/linting.md):\n"
        + "\n".join(f"{f.path}:{f.line}: {f.code} {f.message}" for f in new)
    )


def test_baseline_has_no_stale_entries():
    # keep the grandfather list honest: fixed findings must leave the file
    config = load_config(REPO_ROOT / "pyproject.toml")
    findings, _ = lint_paths(config.include, config, repo_root=REPO_ROOT)
    baseline = load_baseline(REPO_ROOT / config.baseline)
    _, _, stale = diff_baseline(findings, baseline)
    assert stale == 0, (
        f"{stale} baseline entries no longer match any finding; run "
        f"`python -m tools.graftlint --write-baseline` to shrink the file"
    )
