"""tools/analyze_trace.py (ISSUE 8 satellite): importable summarizer,
robust on empty/missing dirs, --json output mode — over a tiny
synthetic *.trace.json.gz fixture."""
import gzip
import json
import os

import pytest

from tools.analyze_trace import (
    categorize,
    find_trace_files,
    main,
    summarize_trace_dir,
)


def write_trace(path, events):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


@pytest.fixture
def trace_dir(tmp_path):
    """Two trace files in nested dirs, with device-pid metadata: pid 7
    is the TPU lane, pid 1 is host python frames that must be dropped
    only when no device metadata exists (here it IS present, so the
    filter is pid-based)."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "python host"}},
        {"ph": "X", "pid": 7, "name": "fusion.42", "dur": 600},
        {"ph": "X", "pid": 7, "name": "convolution.3", "dur": 300},
        {"ph": "X", "pid": 7, "name": "convolution.3", "dur": 100},
        {"ph": "X", "pid": 1, "name": "runner.py:12", "dur": 9999},
    ]
    write_trace(str(tmp_path / "a" / "host.trace.json.gz"), events)
    write_trace(
        str(tmp_path / "b" / "host.trace.json.gz"),
        [{"ph": "M", "name": "process_name", "pid": 7,
          "args": {"name": "/device:TPU:0"}},
         {"ph": "X", "pid": 7, "name": "dynamic-update-slice.1",
          "dur": 500}],
    )
    return tmp_path


def test_find_and_summarize(trace_dir):
    assert len(find_trace_files(str(trace_dir))) == 2
    summary = summarize_trace_dir(str(trace_dir), top=10)
    assert summary["files"] == 2
    # host pid 9999us excluded: 600 + 300 + 100 + 500
    assert summary["total_device_us"] == 1500
    cats = {row["category"]: row for row in summary["categories"]}
    assert cats["fusion"]["us"] == 600
    assert cats["convolution"]["us"] == 400
    assert cats["gather/slice"]["us"] == 500
    ops = {row["name"]: row for row in summary["top_ops"]}
    assert ops["convolution.3"]["count"] == 2
    assert abs(sum(r["share"] for r in summary["categories"]) - 1.0) < 1e-9


def test_categorize_rules():
    assert categorize("fusion.12") == "fusion"
    assert categorize("loop_convolution_fusion") == "convolution"
    assert categorize("all-reduce.1") == "reduce"
    assert categorize("some-op") == "other"


def test_empty_dir_warns_instead_of_crashing(tmp_path, capsys):
    rc = main([str(tmp_path / "nowhere")])
    captured = capsys.readouterr()
    assert rc == 0
    assert "warning: no *.trace.json.gz" in captured.err


def test_json_output_mode(trace_dir, capsys):
    rc = main([str(trace_dir), "--json", "--top", "2"])
    captured = capsys.readouterr()
    assert rc == 0
    summary = json.loads(captured.out)
    assert summary["files"] == 2
    assert len(summary["top_ops"]) == 2
    assert summary["total_device_us"] == 1500


def test_json_output_empty_dir(tmp_path, capsys):
    rc = main([str(tmp_path), "--json"])
    captured = capsys.readouterr()
    assert rc == 0
    assert json.loads(captured.out)["files"] == 0


def test_corrupt_trace_file_is_skipped(trace_dir):
    bad = trace_dir / "c" / "bad.trace.json.gz"
    os.makedirs(bad.parent)
    bad.write_bytes(b"not gzip at all")
    summary = summarize_trace_dir(str(trace_dir))
    assert summary["files"] == 3  # counted as present...
    assert summary["total_device_us"] == 1500  # ...but contributes nothing
