"""The seeded-defect corpus (tests/tools/fixtures/): every planted
kernel defect must be DETECTED by its plane — GL020-GL024 by the lint,
the runtime pair by the kernelcheck sanitizer — and every twin must be
quiet. This is the regression harness that keeps the detectors honest:
a refactor that stops catching a seed fails here, not in a TPU tunnel
window.
"""
from pathlib import Path

import pytest

from tools.graftlint.config import Config
from tools.graftlint.engine import lint_file

FIXTURES = Path(__file__).parent / "fixtures"

LINT_SEEDS = [
    ("gl020_unaligned_slice.py", "GL020"),
    ("gl021_vmem_overflow.py", "GL021"),
    ("gl022_unaliased_rmw.py", "GL022"),
    ("gl023_unwaited_copy.py", "GL023"),
    ("gl024_unguarded_call.py", "GL024"),
]


def lint(name):
    path = FIXTURES / name
    return lint_file(str(path), path.read_text(), Config())


@pytest.mark.parametrize("name,code", LINT_SEEDS)
def test_lint_seed_detected(name, code):
    findings, _ = lint(name)
    hits = [f.code for f in findings]
    # exactly the planted defect, nothing else: a seed that trips a
    # second rule would blur which detector the corpus pins
    assert hits == [code], (name, [(f.code, f.message) for f in findings])


@pytest.mark.parametrize("name,code", LINT_SEEDS)
def test_lint_seed_suppressed_twin_is_quiet(name, code):
    twin = name.replace(".py", "_suppressed.py")
    findings, suppressed = lint(twin)
    assert [f.code for f in findings] == [], twin
    assert suppressed == 1, twin


# ---------------------------------------------------------------------------
# runtime seeds: only the kernelcheck sanitizer sees these
# ---------------------------------------------------------------------------
@pytest.fixture
def kernelcheck_log(monkeypatch):
    from chunkflow_tpu.testing import kernelcheck

    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    kernelcheck.reset_state()
    yield kernelcheck
    kernelcheck.reset_state()


def test_runtime_seeds_are_lint_clean():
    # the whole point of the runtime pair: statically sound, only the
    # sanitizer catches them
    for name in ("rt_scratch_read_before_write.py", "rt_oob_slice.py"):
        findings, _ = lint(name)
        gl02x = [f.code for f in findings if f.code.startswith("GL02")]
        assert gl02x == [], (name, gl02x)


def test_scratch_read_before_write_detected(kernelcheck_log):
    import jax.numpy as jnp

    from tests.tools.fixtures import rt_scratch_read_before_write as fx

    x = jnp.ones((4, 16, 128), jnp.float32)
    fx.build(x, interpret=True).block_until_ready()
    kinds = [v["kind"] for v in kernelcheck_log.report()["violations"]]
    assert "scratch-canary" in kinds


def test_oob_slice_detected(kernelcheck_log):
    import jax.numpy as jnp

    from tests.tools.fixtures import rt_oob_slice as fx

    x = jnp.ones((16, 256), jnp.float32)
    fx.build(x, interpret=True).block_until_ready()
    kinds = [v["kind"] for v in kernelcheck_log.report()["violations"]]
    assert "oob-slice" in kinds


def test_runtime_seeds_silent_with_sanitizer_off(monkeypatch):
    # the strict no-op twin: CHUNKFLOW_KERNELCHECK=0 -> the defects run
    # to completion, nothing is recorded, no callback ever fires
    import jax.numpy as jnp

    from chunkflow_tpu.testing import kernelcheck
    from tests.tools.fixtures import rt_oob_slice
    from tests.tools.fixtures import rt_scratch_read_before_write

    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "0")
    kernelcheck.reset_state()
    rt_scratch_read_before_write.build(
        jnp.ones((4, 16, 128), jnp.float32), interpret=True
    ).block_until_ready()
    rt_oob_slice.build(
        jnp.ones((16, 256), jnp.float32), interpret=True
    ).block_until_ready()
    snap = kernelcheck.report()
    assert snap["violations"] == []
    assert snap["checks"] == 0


def test_scratch_seed_detected_in_raise_mode(monkeypatch):
    # default mode: the violation raises out of the host callback and
    # surfaces through the runtime instead of passing silently
    import jax.numpy as jnp

    from chunkflow_tpu.testing import kernelcheck
    from tests.tools.fixtures import rt_scratch_read_before_write as fx

    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "raise")
    kernelcheck.reset_state()
    x = jnp.ones((4, 16, 128), jnp.float32)
    with pytest.raises(Exception, match="canary|KernelCheck"):
        fx.build(x, interpret=True).block_until_ready()
    kernelcheck.reset_state()
