"""Shape-contract decorator: validation semantics + zero-cost-under-jit."""
import numpy as np
import pytest

from chunkflow_tpu.core.contracts import (
    ContractError,
    Spec,
    check_abstract,
    contract,
)

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


@contract(
    out=Spec("co", "z", "y", "x", dtype="float32"),
    weight=Spec("z", "y", "x", dtype="float32"),
)
def fake_normalize(out, weight):
    return out / weight[None]


def test_contract_accepts_matching_shapes():
    out = np.ones((2, 4, 5, 6), np.float32)
    weight = np.ones((4, 5, 6), np.float32)
    assert fake_normalize(out, weight).shape == (2, 4, 5, 6)


def test_contract_rejects_rank_mismatch():
    with pytest.raises(ContractError, match="rank"):
        fake_normalize(np.ones((4, 5, 6), np.float32),
                       np.ones((4, 5, 6), np.float32))


def test_contract_rejects_inconsistent_named_dims():
    # weight's grid disagrees with out's: 'z' bound twice
    with pytest.raises(ContractError, match="'z'"):
        fake_normalize(np.ones((2, 4, 5, 6), np.float32),
                       np.ones((9, 5, 6), np.float32))


def test_contract_rejects_wrong_dtype():
    with pytest.raises(ContractError, match="dtype"):
        fake_normalize(np.ones((2, 4, 5, 6), np.float64),
                       np.ones((4, 5, 6), np.float64))


def test_contract_exact_extent_and_result():
    @contract(starts=Spec("n", 3, dtype="int32"),
              _result=(Spec("n",), Spec("n",)))
    def split(starts):
        return starts[:, 0], starts[:, 1]

    a, b = split(np.zeros((7, 3), np.int32))
    assert a.shape == (7,)
    with pytest.raises(ContractError, match="extent 3"):
        split(np.zeros((7, 2), np.int32))

    @contract(starts=Spec("n", 3, dtype="int32"), _result=Spec("n",))
    def bad_result(starts):
        return starts  # wrong rank on purpose

    with pytest.raises(ContractError, match="result"):
        bad_result(np.zeros((7, 3), np.int32))


def test_contract_ellipsis_and_ndim_tuple():
    @contract(x=Spec(..., 3), y=Spec(ndim=(3, 4)))
    def f(x, y):
        return x

    f(np.zeros((5, 3)), np.zeros((1, 2, 3)))
    f(np.zeros((2, 9, 3)), np.zeros((1, 2, 3, 4)))
    with pytest.raises(ContractError):
        f(np.zeros((5, 4)), np.zeros((1, 2, 3)))
    with pytest.raises(ContractError, match="ndim"):
        f(np.zeros((5, 3)), np.zeros((2, 3)))


def test_contract_checks_under_jit_at_trace_time():
    calls = []

    @contract(x=Spec("a", "a", dtype="float32"))
    def square_only(x):
        calls.append(1)
        return x * 2

    jitted = jax.jit(square_only)
    jitted(jnp.ones((3, 3), jnp.float32))
    with pytest.raises(ContractError, match="'a'"):
        jitted(jnp.ones((3, 4), jnp.float32))  # non-square: new trace fails


def test_check_abstract_validates_without_execution():
    @contract(x=Spec("n", 3, dtype="int32"))
    def f(x):
        return x.sum(axis=1)

    out = check_abstract(
        f, jax.ShapeDtypeStruct((5, 3), jnp.int32)
    )
    assert out.shape == (5,)
    with pytest.raises(ContractError):
        check_abstract(f, jax.ShapeDtypeStruct((5, 2), jnp.int32))


def test_contracts_env_kill_switch(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_CONTRACTS", "0")
    # violations pass through when disabled
    fake_normalize(np.ones((4, 5, 6), np.float32),
                   np.ones((4, 5, 6), np.float32))


def test_contract_unknown_param_fails_at_decoration():
    with pytest.raises(TypeError, match="no such parameter"):
        @contract(nope=Spec(ndim=1))
        def f(x):
            return x


def test_contract_accepts_chunk_objects():
    from chunkflow_tpu.chunk.base import Chunk

    @contract(chunk=Spec(ndim=(3, 4)))
    def f(chunk):
        return chunk

    f(Chunk(np.zeros((2, 3, 4), np.float32)))
    with pytest.raises(ContractError):
        f(np.zeros((2, 2)))


def test_real_entry_point_contract_fires():
    # the fused pallas kernel declares int32 starts; float starts are the
    # classic silent-cast bug this contract exists to catch
    from chunkflow_tpu.ops.pallas_blend import (
        buffer_padding, fused_accumulate_patches,
    )

    co, Z, Y, X = 1, 2, 8, 16
    pz, py, px = 1, 4, 8
    pad_y, pad_x = buffer_padding((pz, py, px))
    out = jnp.zeros((co, Z, Y + pad_y, X + pad_x), jnp.float32)
    weight = jnp.zeros((Z, Y + pad_y, X + pad_x), jnp.float32)
    preds = jnp.ones((1, co, pz, py, px), jnp.float32)
    valid = jnp.ones((1,), jnp.float32)
    bump = jnp.ones((pz, py, px), jnp.float32)
    with pytest.raises(ContractError, match="int32"):
        fused_accumulate_patches(out, weight, preds, valid, bump,
                                 jnp.zeros((1, 3), jnp.float32),
                                 interpret=True)
