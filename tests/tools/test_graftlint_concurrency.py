"""GL010-series concurrency rule tests: one positive and one suppressed
case per rule (the established graftlint pattern), plus the thread/lock
model they rest on (tools/graftlint/threads.py).
"""
import textwrap

from tools.graftlint.config import Config
from tools.graftlint.engine import lint_file


def run(src, path="chunkflow_tpu/flow/example.py", config=None):
    findings, suppressed = lint_file(
        path, textwrap.dedent(src), config or Config()
    )
    return findings, suppressed


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------- GL010
GL010_POSITIVE = """\
    import threading

    class Worker:
        def __init__(self):
            self.lock = threading.Lock()
            self.count = 0
            self.thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            self.count += 1

        def snapshot(self):
            with self.lock:
                return self.count
"""


def test_gl010_detects_unlocked_shared_write():
    findings, _ = run(GL010_POSITIVE)
    assert codes(findings).count("GL010") == 1
    assert "self.count" in findings[0].message


def test_gl010_suppressed():
    src = GL010_POSITIVE.replace(
        "self.count += 1",
        "self.count += 1  # graftlint: disable=GL010",
    )
    findings, suppressed = run(src)
    assert "GL010" not in codes(findings)
    assert suppressed == 1


def test_gl010_locked_write_is_clean():
    src = GL010_POSITIVE.replace(
        "        self.count += 1",
        "        with self.lock:\n            self.count += 1",
    )
    findings, _ = run(src)
    assert "GL010" not in codes(findings)


def test_gl010_thread_private_state_is_clean():
    # an attribute only the thread itself touches is not shared
    findings, _ = run("""\
        import threading

        class Worker:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self.scratch = 0
                self.scratch += 1
    """)
    assert "GL010" not in codes(findings)


def test_gl010_propagates_through_local_calls():
    # _step is thread-context because the thread target calls it
    findings, _ = run("""\
        import threading

        class Worker:
            def __init__(self):
                self.total = 0
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self._step()

            def _step(self):
                self.total += 1

            def read(self):
                return self.total
    """)
    assert codes(findings).count("GL010") == 1


def test_gl010_module_global_write():
    findings, _ = run("""\
        import threading

        _COUNT = 0
        _LOCK = threading.Lock()

        def _pump():
            global _COUNT
            _COUNT += 1

        def start():
            threading.Thread(target=_pump, daemon=True).start()
    """)
    assert codes(findings).count("GL010") == 1
    findings, _ = run("""\
        import threading

        _COUNT = 0
        _LOCK = threading.Lock()

        def _pump():
            global _COUNT
            with _LOCK:
                _COUNT += 1

        def start():
            threading.Thread(target=_pump, daemon=True).start()
    """)
    assert "GL010" not in codes(findings)


# ---------------------------------------------------------------- GL011
GL011_POSITIVE = """\
    import threading

    class Pair:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def one(self):
            with self.a:
                with self.b:
                    pass

        def two(self):
            with self.b:
                with self.a:
                    pass
"""


def test_gl011_detects_inversion():
    findings, _ = run(GL011_POSITIVE)
    assert codes(findings).count("GL011") == 1  # the pair reported once


def test_gl011_suppressed():
    src = GL011_POSITIVE.replace(
        "            with self.b:\n                    pass",
        "            with self.b:  # graftlint: disable=GL011\n"
        "                    pass",
    )
    findings, _ = run(src)
    assert "GL011" not in codes(findings)


def test_gl011_consistent_order_is_clean():
    findings, _ = run("""\
        import threading

        class Pair:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def one(self):
                with self.a:
                    with self.b:
                        pass

            def two(self):
                with self.a:
                    with self.b:
                        pass
    """)
    assert "GL011" not in codes(findings)


def test_gl011_inversion_through_call():
    # two() holds b and calls helper(), which acquires a
    findings, _ = run("""\
        import threading

        class Pair:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def one(self):
                with self.a:
                    with self.b:
                        pass

            def two(self):
                with self.b:
                    self.helper()

            def helper(self):
                with self.a:
                    pass
    """)
    assert codes(findings).count("GL011") == 1


def test_gl011_condition_over_same_lock_is_one_mutex():
    # two conditions wrapping one lock are NOT a second lock: the
    # scheduler's _AdaptiveQueue shape must stay clean
    findings, _ = run("""\
        import threading

        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self._not_empty = threading.Condition(self._lock)
                self._not_full = threading.Condition(self._lock)

            def put(self):
                with self._not_full:
                    self._not_full.notify()

            def close(self):
                with self._lock:
                    self._not_empty.notify_all()
    """)
    assert "GL011" not in codes(findings)


# ---------------------------------------------------------------- GL012
GL012_POSITIVE = """\
    import threading
    import urllib.request

    class Client:
        def __init__(self):
            self._lock = threading.Lock()

        def fetch(self, url, q, thread):
            with self._lock:
                data = urllib.request.urlopen(url)
                item = q.get()
                thread.join()
            return data, item
"""


def test_gl012_detects_blocking_calls_under_lock():
    findings, _ = run(GL012_POSITIVE)
    assert codes(findings).count("GL012") == 3  # urlopen, .get(), .join()


def test_gl012_suppressed():
    src = GL012_POSITIVE.replace(
        "data = urllib.request.urlopen(url)",
        "data = urllib.request.urlopen(url)  # graftlint: disable=GL012",
    ).replace(
        "item = q.get()",
        "item = q.get()  # graftlint: disable=GL012",
    ).replace(
        "thread.join()",
        "thread.join()  # graftlint: disable=GL012",
    )
    findings, suppressed = run(src)
    assert "GL012" not in codes(findings)
    assert suppressed == 3


def test_gl012_bounded_waits_are_clean():
    findings, _ = run("""\
        import threading

        class Client:
            def __init__(self):
                self._lock = threading.Lock()

            def fetch(self, q, thread):
                with self._lock:
                    item = q.get(timeout=1.0)
                    thread.join(timeout=2.0)
                return item
    """)
    assert "GL012" not in codes(findings)


def test_gl012_condition_wait_is_exempt():
    # cv.wait releases the lock while waiting — that is the point
    findings, _ = run("""\
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()

            def get(self):
                with self._cv:
                    while True:
                        self._cv.wait(0.1)
    """)
    assert "GL012" not in codes(findings)


def test_gl012_event_wait_and_device_sync_under_lock():
    findings, _ = run("""\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = threading.Event()

            def drain(self, out):
                with self._lock:
                    self._done.wait()
                    out.block_until_ready()
    """)
    assert codes(findings).count("GL012") == 2


def test_gl012_outside_lock_is_clean():
    findings, _ = run("""\
        def fetch(q, thread):
            item = q.get()
            thread.join()
            return item
    """)
    assert "GL012" not in codes(findings)


# ---------------------------------------------------------------- GL013
GL013_POSITIVE = """\
    import threading

    def spawn():
        t = threading.Thread(target=print)
        t.start()
        return t
"""


def test_gl013_detects_leaked_thread():
    findings, _ = run(GL013_POSITIVE)
    assert codes(findings).count("GL013") == 1


def test_gl013_suppressed():
    src = GL013_POSITIVE.replace(
        "t = threading.Thread(target=print)",
        "t = threading.Thread(target=print)  # graftlint: disable=GL013",
    )
    findings, _ = run(src)
    assert "GL013" not in codes(findings)


def test_gl013_daemon_and_joined_are_clean():
    findings, _ = run("""\
        import threading

        def fire_and_forget():
            threading.Thread(target=print, daemon=True).start()

        def bounded():
            t = threading.Thread(target=print)
            t.start()
            t.join()
    """)
    assert "GL013" not in codes(findings)


def test_gl013_handle_joined_in_other_method():
    findings, _ = run("""\
        import threading

        class Pump:
            def start(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def _run(self):
                pass

            def stop(self):
                self._thread.join(timeout=5.0)
    """)
    assert "GL013" not in codes(findings)


def test_gl013_pool_joined_via_loop():
    # the LocalBackend shape: a list of threads joined in close()
    findings, _ = run("""\
        import threading

        class Pool:
            def __init__(self, n):
                self._threads = [
                    threading.Thread(target=self._run) for _ in range(n)
                ]

            def _run(self):
                pass

            def close(self):
                for t in self._threads:
                    t.join(timeout=1.0)
    """)
    assert "GL013" not in codes(findings)


def test_gl013_dropped_handle():
    findings, _ = run("""\
        import threading

        def spawn():
            threading.Thread(target=print).start()
    """)
    assert codes(findings).count("GL013") == 1


# ---------------------------------------------------------------- GL014
GL014_POSITIVE = """\
    import threading

    class Box:
        def __init__(self):
            self._cv = threading.Condition()
            self._ready = False

        def get(self):
            with self._cv:
                if not self._ready:
                    self._cv.wait()
                return self._ready
"""


def test_gl014_detects_wait_outside_loop():
    findings, _ = run(GL014_POSITIVE)
    assert codes(findings).count("GL014") == 1


def test_gl014_suppressed():
    src = GL014_POSITIVE.replace(
        "self._cv.wait()",
        "self._cv.wait()  # graftlint: disable=GL014",
    )
    findings, _ = run(src)
    assert "GL014" not in codes(findings)


def test_gl014_predicate_loop_is_clean():
    src = GL014_POSITIVE.replace(
        "if not self._ready:", "while not self._ready:"
    )
    findings, _ = run(src)
    assert "GL014" not in codes(findings)


def test_gl014_wait_for_is_clean():
    findings, _ = run("""\
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()
                self._ready = False

            def get(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._ready)
                    return self._ready
    """)
    assert "GL014" not in codes(findings)


def test_gl014_event_wait_not_flagged():
    # Event.wait is level-triggered: no predicate loop required
    findings, _ = run("""\
        import threading

        class Box:
            def __init__(self):
                self._done = threading.Event()

            def get(self):
                self._done.wait()
    """)
    assert "GL014" not in codes(findings)


# ------------------------------------------------- thread/lock model
def test_model_entries_via_submit_and_timer():
    from tools.graftlint.context import FileContext
    from tools.graftlint.threads import get_model

    src = textwrap.dedent("""\
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def pumped():
            pass

        def timed():
            pass

        def start(pool: ThreadPoolExecutor):
            pool.submit(pumped)
            threading.Timer(1.0, timed).start()
    """)
    model = get_model(FileContext("chunkflow_tpu/x.py", src))
    names = {fn.name for fn in model.thread_entries}
    assert names == {"pumped", "timed"}


def test_model_iter_held_tracks_nested_with():
    import ast

    from tools.graftlint.context import FileContext
    from tools.graftlint.threads import get_model

    src = textwrap.dedent("""\
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def f():
            with _A:
                with _B:
                    x = 1
            y = 2
    """)
    ctx = FileContext("chunkflow_tpu/x.py", src)
    model = get_model(ctx)
    fn = next(n for n in ctx.functions)
    held_at = {}
    for node, held in model.iter_held(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            held_at[node.id] = tuple(t for t, _k in held)
    assert held_at["x"] == (("mod", "_A"), ("mod", "_B"))
    assert held_at["y"] == ()
