"""graftlint result cache + SARIF output + CLI surface tests (ISSUE 10
satellites): warm runs skip re-analysis and are measurably faster, cache
keys track content/config/linter versions, --no-cache bypasses, --output
sarif emits valid SARIF 2.1.0.
"""
import json
import textwrap
import time

import pytest

from tools.graftlint.config import Config
from tools.graftlint.engine import lint_paths

#: nontrivial enough that cold analysis costs real time per file
SOURCE_TEMPLATE = """\
import threading
import numpy as np
import jax


@jax.jit
def program_{i}(x):
    return x * {i}


class Worker{i}:
    def __init__(self):
        self.lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self.lock:
            self.count += 1


def host_{i}(chunk):
    out = np.asarray(chunk)
    return out.sum(dtype=np.float32)
"""


def make_tree(tmp_path, n=40):
    pkg = tmp_path / "chunkflow_tpu" / "flow"
    pkg.mkdir(parents=True)
    for i in range(n):
        (pkg / f"mod_{i}.py").write_text(SOURCE_TEMPLATE.format(i=i))
    return tmp_path


def test_warm_run_skips_analysis_and_is_faster(tmp_path, monkeypatch):
    repo = make_tree(tmp_path)
    config = Config(cache_dir=str(tmp_path / ".graftlint_cache"))

    import tools.graftlint.engine as engine_mod

    real_lint_file = engine_mod.lint_file
    calls = {"n": 0}

    def counting_lint_file(*args, **kwargs):
        calls["n"] += 1
        return real_lint_file(*args, **kwargs)

    monkeypatch.setattr(engine_mod, "lint_file", counting_lint_file)

    t0 = time.perf_counter()
    cold, sup_cold = lint_paths(["chunkflow_tpu"], config, repo_root=repo)
    cold_s = time.perf_counter() - t0
    assert calls["n"] == 40

    t0 = time.perf_counter()
    warm, sup_warm = lint_paths(["chunkflow_tpu"], config, repo_root=repo)
    warm_s = time.perf_counter() - t0
    assert calls["n"] == 40  # zero re-analysis on the warm run
    assert warm_s < cold_s  # and measurably faster wall-clock
    assert [f.as_dict() for f in warm] == [f.as_dict() for f in cold]
    assert sup_warm == sup_cold


def test_edited_file_reanalyzed_others_cached(tmp_path, monkeypatch):
    repo = make_tree(tmp_path, n=10)
    config = Config(cache_dir=str(tmp_path / ".graftlint_cache"))
    lint_paths(["chunkflow_tpu"], config, repo_root=repo)

    import tools.graftlint.engine as engine_mod

    real_lint_file = engine_mod.lint_file
    analyzed = []

    def counting_lint_file(path, *args, **kwargs):
        analyzed.append(path)
        return real_lint_file(path, *args, **kwargs)

    monkeypatch.setattr(engine_mod, "lint_file", counting_lint_file)
    target = repo / "chunkflow_tpu" / "flow" / "mod_3.py"
    target.write_text(target.read_text() + "\nEXTRA = 1\n")
    lint_paths(["chunkflow_tpu"], config, repo_root=repo)
    assert analyzed == ["chunkflow_tpu/flow/mod_3.py"]


def test_config_change_invalidates(tmp_path, monkeypatch):
    repo = make_tree(tmp_path, n=3)
    cache_dir = str(tmp_path / ".graftlint_cache")
    lint_paths(["chunkflow_tpu"], Config(cache_dir=cache_dir),
               repo_root=repo)

    import tools.graftlint.engine as engine_mod

    real_lint_file = engine_mod.lint_file
    calls = {"n": 0}

    def counting_lint_file(*args, **kwargs):
        calls["n"] += 1
        return real_lint_file(*args, **kwargs)

    monkeypatch.setattr(engine_mod, "lint_file", counting_lint_file)
    lint_paths(["chunkflow_tpu"],
               Config(cache_dir=cache_dir, select=["GL001"]),
               repo_root=repo)
    assert calls["n"] == 3  # different select -> different keys


def test_no_cache_bypasses(tmp_path, monkeypatch):
    repo = make_tree(tmp_path, n=3)
    config = Config(cache_dir=str(tmp_path / ".graftlint_cache"))
    lint_paths(["chunkflow_tpu"], config, repo_root=repo)

    import tools.graftlint.engine as engine_mod

    real_lint_file = engine_mod.lint_file
    calls = {"n": 0}

    def counting_lint_file(*args, **kwargs):
        calls["n"] += 1
        return real_lint_file(*args, **kwargs)

    monkeypatch.setattr(engine_mod, "lint_file", counting_lint_file)
    lint_paths(["chunkflow_tpu"], config, repo_root=repo,
               use_cache=False)
    assert calls["n"] == 3

    # Config(cache_dir=None) disables too
    lint_paths(["chunkflow_tpu"], Config(cache_dir=None), repo_root=repo)
    assert calls["n"] == 6


def test_torn_cache_entry_is_a_miss(tmp_path):
    repo = make_tree(tmp_path, n=1)
    config = Config(cache_dir=str(tmp_path / ".graftlint_cache"))
    cold, _ = lint_paths(["chunkflow_tpu"], config, repo_root=repo)
    for entry in (tmp_path / ".graftlint_cache").rglob("*.json"):
        entry.write_text("{ torn")
    warm, _ = lint_paths(["chunkflow_tpu"], config, repo_root=repo)
    assert [f.as_dict() for f in warm] == [f.as_dict() for f in cold]


# ------------------------------------------------------------------ SARIF
BAD_SOURCE = """\
import jax
import numpy as np

@jax.jit
def f(x):
    return np.asarray(x).item()
"""


@pytest.fixture
def bad_repo(tmp_path, monkeypatch):
    pkg = tmp_path / "chunkflow_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(textwrap.dedent(BAD_SOURCE))
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftlint]\ninclude = ["chunkflow_tpu"]\n'
        'baseline = "baseline.json"\n'
    )
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_cli_sarif_output(bad_repo, capsys):
    from tools.graftlint.cli import main

    assert main(["--output", "sarif", "--no-cache"]) == 1
    log = json.loads(capsys.readouterr().out)
    assert log["version"] == "2.1.0"
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"GL001", "GL010", "GL011", "GL012", "GL013",
            "GL014"} <= rule_ids
    results = run["results"]
    assert results and all(r["ruleId"] == "GL001" for r in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "chunkflow_tpu/ops/bad.py"
    assert loc["region"]["startLine"] >= 1
    assert loc["region"]["startColumn"] >= 1  # SARIF columns are 1-based


def test_cli_sarif_clean_run_has_no_results(bad_repo, capsys):
    from tools.graftlint.cli import main

    (bad_repo / "chunkflow_tpu" / "ops" / "bad.py").write_text(
        "x = 1\n")
    assert main(["--output", "sarif", "--no-cache"]) == 0
    log = json.loads(capsys.readouterr().out)
    assert log["runs"][0]["results"] == []


def test_cli_stats_prints_rule_families(bad_repo, capsys):
    from tools.graftlint.cli import main

    assert main(["--stats", "--no-cache"]) == 1
    out = capsys.readouterr().out
    assert "graftlint stats:" in out
    assert "jit" in out and "concurrency" in out
    assert "GL001=2" in out


def test_cli_json_alias_still_works(bad_repo, capsys):
    from tools.graftlint.cli import main

    assert main(["--json", "--no-baseline", "--no-cache"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert {f["code"] for f in payload["new"]} == {"GL001"}
