"""Seeded-defect corpus for the Pallas kernel soundness plane (ISSUE 16).

One deliberately-broken kernel per GL020-GL024 lint rule plus two
runtime defects (a scratch read-before-write and an out-of-bounds DMA
window) only the kernelcheck sanitizer can see, each with a twin: the
lint fixtures get a ``# graftlint: disable=`` suppressed twin, the
runtime fixtures run clean with ``CHUNKFLOW_KERNELCHECK=0`` (the strict
no-op proof). tests/tools/test_kernel_corpus.py asserts every defect is
DETECTED and every twin is quiet — the corpus is the regression harness
that keeps the detectors honest.

These files sit under ``tests/`` deliberately: the repo-wide graftlint
gate's include set (``pyproject.toml``) never lints them, so the
baseline stays empty while the corpus stays red.
"""
