"""Suppressed twin of gl021_vmem_overflow (a kernel targeting a part
with a bigger budget would disable the rule and set
CHUNKFLOW_VMEM_BUDGET in CI instead)."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def pallas_mode():
    return "off"


def build(x, interpret=False):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    return pl.pallas_call(  # graftlint: disable=GL021
        kernel,
        grid=(8,),
        in_specs=[pl.BlockSpec((1024, 2048), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1024, 2048), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 2048), jnp.float32),
        interpret=interpret,
    )(x)
