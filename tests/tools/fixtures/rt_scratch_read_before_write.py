"""Runtime seed: a scratch read-before-write the lint cannot see.

The kernel DMAs only the TOP half of its scratch window but reads the
whole window — rows 8:16 carry whatever the previous grid step (or
nothing at all) left there. Statically every copy is started and
waited, every slice constant-aligned, the output write-only: the
GL020-series passes this kernel. Only kernelcheck's poison catches it:
with the scratch NaN-filled at the top of each step, the unwritten rows
surface as NaN canaries in the result (:func:`chunkflow_tpu.testing.
kernelcheck.check_result`). With the sanitizer off the defect runs
silently — the scratch carries whatever interpret/hardware happens to
leave there and nothing flags the output as wrong.
"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chunkflow_tpu.testing import kernelcheck


def pallas_mode():
    return "interpret"


def build(x, interpret=True):
    """x: [4, 16, 128] f32 -> [16, 128] f32 (last grid step's window).
    BUG: only rows 0:8 of the 16-row scratch are ever written."""
    check = kernelcheck.active(interpret)

    def kernel(x_ref, o_ref, scratch, sem):
        if check:
            kernelcheck.poison_scratch(scratch)
        copy = pltpu.make_async_copy(
            x_ref.at[pl.program_id(0), pl.ds(0, 8), pl.ds(0, 128)],
            scratch.at[pl.ds(0, 8)],
            sem,
        )
        copy.start()
        copy.wait()
        o_ref[...] = scratch[...]  # BUG: rows 8:16 never written

    out = pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((16, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((16, 128), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(x)
    if check:
        out = kernelcheck.check_result(out, "rt_scratch_rbw")
    return out
