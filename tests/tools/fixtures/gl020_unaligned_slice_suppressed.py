"""Suppressed twin of gl020_unaligned_slice: the same unhinted corner
behind an inline ``# graftlint: disable=GL020`` — the deliberate-
exception escape hatch the lint must honor."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def pallas_mode():
    return "off"


def build(x, starts, interpret=False):
    def kernel(starts_ref, x_ref, o_ref, scratch, sem):
        b = pl.program_id(0)
        y0 = starts_ref[b, 0]
        x0 = pl.multiple_of(starts_ref[b, 1], 128)
        copy = pltpu.make_async_copy(
            x_ref.at[pl.ds(y0, 8), pl.ds(x0, 128)], scratch, sem)  # graftlint: disable=GL020
        copy.start()
        copy.wait()
        o_ref[...] = scratch[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(starts.shape[0],),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec((8, 128), lambda b, s: (0, 0))],
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        interpret=interpret,
    )(starts, x)
