"""Runtime seed: an out-of-bounds DMA window the lint cannot see.

The slice corners are properly ``pl.multiple_of``-hinted (GL020
passes), but the starts TABLE is wrong at runtime: the last row's
aligned window runs 128 columns past the padded buffer edge. Interpret
mode clamps the read and the output is quietly wrong; hardware DMAs
memory the buffer does not own. Only kernelcheck's
:func:`chunkflow_tpu.testing.kernelcheck.check_bounds` assertion over
the concrete starts values catches it before the kernel runs.
"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from chunkflow_tpu.testing import kernelcheck


def pallas_mode():
    return "interpret"


def build(x, interpret=True):
    """x: [16, 256] f32 -> [2, 8, 128] f32 windows at hinted corners.
    BUG: the second start row (8, 256) puts its x-window at [256, 384)
    — past the 256-column extent."""
    check = kernelcheck.active(interpret)
    starts = jnp.array([[0, 0], [8, 256]], jnp.int32)

    def kernel(starts_ref, x_ref, o_ref, scratch, sem):
        b = pl.program_id(0)
        y0 = pl.multiple_of(starts_ref[b, 0], 8)
        x0 = pl.multiple_of(starts_ref[b, 1], 128)
        copy = pltpu.make_async_copy(
            x_ref.at[pl.ds(y0, 8), pl.ds(x0, 128)], scratch, sem)
        copy.start()
        copy.wait()
        o_ref[0] = scratch[...]

    if check:
        kernelcheck.check_bounds(starts, (8, 128), x.shape, "rt_oob")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(2,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((1, 8, 128), lambda b, s: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((2, 8, 128), jnp.float32),
        interpret=interpret,
    )(starts, x)
    if check:
        out = kernelcheck.check_result(out, "rt_oob")
    return out
