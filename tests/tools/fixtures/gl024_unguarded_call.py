"""GL024 seed: a bare pallas_call — no ``*_mode`` env selector in the
module and no ``interpret=`` threaded from a caller. A CPU box (or any
platform the author did not anticipate) hard-fails instead of falling
back to an XLA path."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def build(x):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    return pl.pallas_call(  # BUG: no selection seam
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
