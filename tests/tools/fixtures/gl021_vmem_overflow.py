"""GL021 seed: double-buffered 8 MiB block windows on both input and
output — 32 MiB of analytic VMEM against a 16 MiB device budget. The
kernel is semantically fine; it simply cannot compile on hardware."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def pallas_mode():
    return "off"


def build(x, interpret=False):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(8,),
        in_specs=[pl.BlockSpec((1024, 2048), lambda i: (i, 0))],  # BUG
        out_specs=pl.BlockSpec((1024, 2048), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((8192, 2048), jnp.float32),
        interpret=interpret,
    )(x)
