"""Suppressed twin of gl024_unguarded_call (legitimate for a
hardware-only diagnostic script that must never silently fall back;
the twin pins the suppression mechanics)."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def build(x):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    return pl.pallas_call(  # graftlint: disable=GL024
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
