"""Suppressed twin of gl022_unaliased_rmw (legitimate only for a
kernel whose output-read is provably of cells the same grid step
already wrote — which this one is not; the twin exists to pin the
suppression mechanics)."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def pallas_mode():
    return "off"


def build(x, interpret=False):
    def kernel(x_ref, o_ref):
        o_ref[...] = o_ref[...] + x_ref[...]  # graftlint: disable=GL022

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
        interpret=interpret,
    )(x)
