"""GL023 seed: a DMA that is started and never waited — the copy races
every read of its destination scratch; on hardware the read sees
whatever fraction of the transfer happened to land."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def pallas_mode():
    return "off"


def build(x, interpret=False):
    def kernel(x_ref, o_ref, scratch, sem):
        copy = pltpu.make_async_copy(x_ref, scratch, sem)
        copy.start()  # BUG: no copy.wait() before the read below
        o_ref[...] = scratch[...]

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(x)
