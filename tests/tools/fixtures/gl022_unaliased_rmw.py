"""GL022 seed: the kernel reads its output ref (an accumulate) but no
``input_output_aliases`` entry ties an input to that output — XLA hands
the kernel a FRESH buffer and the read sees undefined contents (zeros
in interpret mode, so prior contributions silently vanish)."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def pallas_mode():
    return "off"


def build(x, interpret=False):
    def kernel(x_ref, o_ref):
        o_ref[...] = o_ref[...] + x_ref[...]  # BUG: RMW, no alias

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
        interpret=interpret,
    )(x)
