"""GL020-series Pallas/Mosaic kernel soundness rule tests: one positive
and one suppressed case per rule (the established graftlint pattern),
plus the pallas_call site model they rest on (tools/graftlint/pallas.py)
and the shipping-kernel zero-findings guarantee — a lint that flags the
kernels it exists to protect would be deleted within a week.
"""
import textwrap
from pathlib import Path

from tools.graftlint.config import Config
from tools.graftlint.context import FileContext
from tools.graftlint.engine import lint_file
from tools.graftlint.pallas import get_pallas_model, vmem_budget_bytes

REPO = Path(__file__).resolve().parents[2]


def run(src, path="chunkflow_tpu/ops/example.py", config=None):
    findings, suppressed = lint_file(
        path, textwrap.dedent(src), config or Config()
    )
    return findings, suppressed


def codes(findings):
    return [f.code for f in findings]


PREAMBLE = """\
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


    def pallas_mode():
        return "off"

"""


# ---------------------------------------------------------------- GL020
GL020_POSITIVE = PREAMBLE + """\

    def build(x, starts, interpret=False):
        def kernel(starts_ref, x_ref, o_ref, scratch, sem):
            b = pl.program_id(0)
            y0 = starts_ref[b, 0]
            x0 = pl.multiple_of(starts_ref[b, 1], 128)
            copy = pltpu.make_async_copy(x_ref.at[pl.ds(y0, 8), pl.ds(x0, 128)], scratch, sem)
            copy.start()
            copy.wait()
            o_ref[...] = scratch[...]

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=[pl.BlockSpec((8, 128), lambda b, s: (0, 0))],
            scratch_shapes=[
                pltpu.VMEM((8, 128), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=interpret,
        )(starts, x)
"""


def test_gl020_detects_unhinted_dynamic_slice_corner():
    findings, _ = run(GL020_POSITIVE)
    assert codes(findings).count("GL020") == 1
    hit = [f for f in findings if f.code == "GL020"][0]
    assert "second-minor" in hit.message
    assert "multiple_of" in hit.message


def test_gl020_suppressed():
    src = GL020_POSITIVE.replace(
        "], scratch, sem)",
        "], scratch, sem)  # graftlint: disable=GL020",
    )
    findings, suppressed = run(src)
    assert "GL020" not in codes(findings)
    assert suppressed == 1


def test_gl020_hinted_corner_is_clean():
    src = GL020_POSITIVE.replace(
        "y0 = starts_ref[b, 0]",
        "y0 = pl.multiple_of(starts_ref[b, 0], 8)",
    )
    findings, _ = run(src)
    assert "GL020" not in codes(findings)


def test_gl020_accepts_unfoldable_hint_divisor():
    # the gather kernel's pattern: the sublane divisor comes from
    # _sublane(dtype) and cannot fold — the hint's PRESENCE is enforced
    src = GL020_POSITIVE.replace(
        "def build(x, starts, interpret=False):",
        "def build(x, starts, interpret=False):\n"
        "    sub = {1: 32, 2: 16}.get(x.dtype.itemsize, 8)",
    ).replace(
        "y0 = starts_ref[b, 0]",
        "y0 = pl.multiple_of(starts_ref[b, 0], sub)",
    )
    findings, _ = run(src)
    assert "GL020" not in codes(findings)


def test_gl020_ignores_non_any_refs():
    # dynamic indexing into a blocked (VMEM) ref carries no DMA-slice
    # divisibility obligation
    src = GL020_POSITIVE.replace(
        "x_ref.at[pl.ds(y0, 8), pl.ds(x0, 128)]",
        "x_ref.at[0, pl.ds(0, 128)]",
    ).replace(
        "o_ref[...] = scratch[...]",
        "o_ref[pl.ds(y0, 8), pl.ds(x0, 128)] = scratch[...]",
    )
    findings, _ = run(src)
    assert "GL020" not in codes(findings)


# ---------------------------------------------------------------- GL021
GL021_POSITIVE = PREAMBLE + """\

    def build(x, interpret=False):
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        return pl.pallas_call(
            kernel,
            grid=(8,),
            in_specs=[pl.BlockSpec((1024, 2048), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1024, 2048), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((8192, 2048), jnp.float32),
            interpret=interpret,
        )(x)
"""


def test_gl021_detects_vmem_overflow():
    # 1024x2048 f32 blocks = 8 MiB each, x2 double-buffered (dynamic
    # index map), in + out = 32 MiB against a 16 MiB budget
    findings, _ = run(GL021_POSITIVE)
    assert codes(findings).count("GL021") == 1
    assert "VMEM" in findings[0].message


def test_gl021_suppressed():
    src = GL021_POSITIVE.replace(
        "return pl.pallas_call(",
        "return pl.pallas_call(  # graftlint: disable=GL021",
    )
    findings, suppressed = run(src)
    assert "GL021" not in codes(findings)
    assert suppressed == 1


def test_gl021_fitting_blocks_are_clean():
    src = GL021_POSITIVE.replace("1024, 2048", "256, 512")
    findings, _ = run(src)
    assert "GL021" not in codes(findings)


def test_gl021_constant_index_block_not_double_buffered():
    # a constant-index (grid-resident) block counts once: 1024x2048 f32
    # = 8 MiB in + 8 MiB out = 16 MiB, exactly at budget -> clean; the
    # same blocks with dynamic index maps overflow (the positive case)
    src = GL021_POSITIVE.replace("lambda i: (i, 0)", "lambda i: (0, 0)")
    findings, _ = run(src)
    assert "GL021" not in codes(findings)


def test_gl021_env_budget_override(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_VMEM_BUDGET", str(64 * 2**20))
    assert vmem_budget_bytes() == 64 * 2**20
    findings, _ = run(GL021_POSITIVE)
    assert "GL021" not in codes(findings)
    monkeypatch.setenv("CHUNKFLOW_VMEM_BUDGET", "1024")
    src = GL021_POSITIVE.replace("1024, 2048", "256, 512")
    findings, _ = run(src)
    assert codes(findings).count("GL021") == 1


def test_gl021_symbolic_shapes_skip():
    # unfoldable block dims (the shipping kernels' py/px arguments) make
    # the block unaccountable: under-count, never guess
    src = GL021_POSITIVE.replace("(1024, 2048)", "(py, px)").replace(
        "def build(x, interpret=False):",
        "def build(x, py, px, interpret=False):",
    )
    findings, _ = run(src)
    assert "GL021" not in codes(findings)


# ---------------------------------------------------------------- GL022
GL022_POSITIVE = PREAMBLE + """\

    def build(x, interpret=False):
        def kernel(x_ref, o_ref):
            o_ref[...] = o_ref[...] + x_ref[...]

        return pl.pallas_call(
            kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((32, 128), jnp.float32),
            interpret=interpret,
        )(x)
"""


def test_gl022_detects_unaliased_rmw_output():
    findings, _ = run(GL022_POSITIVE)
    assert codes(findings).count("GL022") == 1
    assert "input_output_aliases" in findings[0].message


def test_gl022_suppressed():
    src = GL022_POSITIVE.replace(
        "o_ref[...] = o_ref[...] + x_ref[...]",
        "o_ref[...] = o_ref[...] + x_ref[...]"
        "  # graftlint: disable=GL022",
    )
    findings, suppressed = run(src)
    assert "GL022" not in codes(findings)
    assert suppressed == 1


def test_gl022_aliased_rmw_is_clean():
    src = GL022_POSITIVE.replace(
        "interpret=interpret,",
        "interpret=interpret,\n        input_output_aliases={0: 0},",
    )
    findings, _ = run(src)
    assert "GL022" not in codes(findings)


def test_gl022_write_only_output_is_clean():
    src = GL022_POSITIVE.replace(
        "o_ref[...] = o_ref[...] + x_ref[...]",
        "o_ref[...] = x_ref[...] * 2.0",
    )
    findings, _ = run(src)
    assert "GL022" not in codes(findings)


def test_gl022_async_copy_source_through_at_binding():
    # the blend kernel's shape: tile = out_ref.at[...] used as a copy
    # SOURCE is a read of the output
    src = PREAMBLE + """\

        def build(x, interpret=False):
            def kernel(x_ref, o_ref, scratch, sem):
                tile = o_ref.at[pl.ds(0, 8), pl.ds(0, 128)]
                load = pltpu.make_async_copy(tile, scratch, sem)
                load.start()
                load.wait()
                scratch[...] = scratch[...] + x_ref[...]
                store = pltpu.make_async_copy(scratch, tile, sem)
                store.start()
                store.wait()

            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                scratch_shapes=[
                    pltpu.VMEM((8, 128), jnp.float32),
                    pltpu.SemaphoreType.DMA(()),
                ],
                interpret=interpret,
            )(x)
    """
    findings, _ = run(src)
    assert codes(findings).count("GL022") == 1
    aliased = src.replace(
        "interpret=interpret,",
        "interpret=interpret,\n            input_output_aliases={0: 0},",
    )
    findings, _ = run(aliased)
    assert "GL022" not in codes(findings)


# ---------------------------------------------------------------- GL023
GL023_UNWAITED = PREAMBLE + """\

    def build(x, interpret=False):
        def kernel(x_ref, o_ref, scratch, sem):
            copy = pltpu.make_async_copy(x_ref, scratch, sem)
            copy.start()
            o_ref[...] = scratch[...]

        return pl.pallas_call(
            kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((8, 128), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
            interpret=interpret,
        )(x)
"""


def test_gl023_detects_started_unwaited_copy():
    findings, _ = run(GL023_UNWAITED)
    assert codes(findings).count("GL023") == 1
    assert "never waited" in [
        f for f in findings if f.code == "GL023"][0].message


def test_gl023_suppressed():
    src = GL023_UNWAITED.replace(
        "copy.start()",
        "copy.start()  # graftlint: disable=GL023",
    )
    findings, suppressed = run(src)
    assert "GL023" not in codes(findings)
    assert suppressed == 1


def test_gl023_waited_copy_is_clean():
    src = GL023_UNWAITED.replace(
        "copy.start()",
        "copy.start()\n        copy.wait()",
    )
    findings, _ = run(src)
    assert "GL023" not in codes(findings)


def _when_arm_kernel(first_copy_completes: bool) -> str:
    """A kernel where a when-arm starts a second copy on the same
    semaphore — legal only if the first copy already completed."""
    first = ("c1.start()\n            c1.wait()"
             if first_copy_completes else "c1.start()")
    return PREAMBLE + f"""\

    def build(x, interpret=False):
        def kernel(x_ref, o_ref, scratch, sem):
            b = pl.program_id(0)
            c1 = pltpu.make_async_copy(x_ref, scratch, sem)
            {first}

            @pl.when(b == 0)
            def _():
                c2 = pltpu.make_async_copy(x_ref, scratch, sem)
                c2.start()
                c2.wait()

            {"o_ref[...] = scratch[...]" if first_copy_completes
             else "c1.wait()"}
            {"" if first_copy_completes
             else "o_ref[...] = scratch[...]"}

        return pl.pallas_call(
            kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((8, 128), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
            interpret=interpret,
        )(x)
"""


def test_gl023_detects_semaphore_reuse_across_when_arm():
    findings, _ = run(_when_arm_kernel(first_copy_completes=False))
    assert codes(findings).count("GL023") == 1
    assert "reused" in [
        f for f in findings if f.code == "GL023"][0].message


def test_gl023_sequential_reuse_after_wait_is_clean():
    # the blend kernel's when-arm pattern: the semaphore is reused only
    # after the prior copy completed
    findings, _ = run(_when_arm_kernel(first_copy_completes=True))
    assert "GL023" not in codes(findings)


def test_gl023_detects_inline_unwaitable_start():
    src = GL023_UNWAITED.replace(
        "copy = pltpu.make_async_copy(x_ref, scratch, sem)\n"
        "        copy.start()",
        "pltpu.make_async_copy(x_ref, scratch, sem).start()",
    )
    findings, _ = run(src)
    assert codes(findings).count("GL023") == 1


# ---------------------------------------------------------------- GL024
GL024_POSITIVE = """\
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl


    def build(x):
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)
"""


def test_gl024_detects_unguarded_pallas_call():
    findings, _ = run(GL024_POSITIVE)
    assert codes(findings).count("GL024") == 1
    assert "selector" in findings[0].message


def test_gl024_suppressed():
    src = GL024_POSITIVE.replace(
        "return pl.pallas_call(",
        "return pl.pallas_call(  # graftlint: disable=GL024",
    )
    findings, suppressed = run(src)
    assert "GL024" not in codes(findings)
    assert suppressed == 1


def test_gl024_mode_selector_def_is_clean():
    src = GL024_POSITIVE.replace(
        "def build(x):",
        "def pallas_mode():\n"
        "    return \"off\"\n"
        "\n"
        "\n"
        "def build(x):",
    )
    findings, _ = run(src)
    assert "GL024" not in codes(findings)


def test_gl024_imported_mode_selector_is_clean():
    src = GL024_POSITIVE.replace(
        "import jax\n",
        "import jax\n"
        "from chunkflow_tpu.ops.pallas_blend import pallas_mode\n",
    )
    findings, _ = run(src)
    assert "GL024" not in codes(findings)


def test_gl024_dynamic_interpret_kwarg_is_clean():
    src = GL024_POSITIVE.replace(
        "def build(x):", "def build(x, interpret=False):"
    ).replace(
        "out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),",
        "out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
        "        interpret=interpret,",
    )
    findings, _ = run(src)
    assert "GL024" not in codes(findings)


def test_gl024_literal_interpret_kwarg_still_fires():
    # interpret=True hard-codes the interpreter: still no way to run
    # the compiled kernel, still no selection seam
    src = GL024_POSITIVE.replace(
        "out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),",
        "out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
        "        interpret=True,",
    )
    findings, _ = run(src)
    assert codes(findings).count("GL024") == 1


# ------------------------------------------------------------ the model
def test_model_parses_shipping_blend_kernel():
    path = REPO / "chunkflow_tpu" / "ops" / "pallas_blend.py"
    ctx = FileContext(str(path), path.read_text())
    model = get_pallas_model(ctx)
    assert model.has_mode_selector
    assert len(model.sites) == 1
    site = model.sites[0]
    assert site.num_scalar_prefetch == 3
    assert [s.any_space for s in site.in_specs] == [
        False, False, True, True]
    assert [s.any_space for s in site.out_specs] == [True, True]
    assert site.aliases == {5: 0, 6: 1}
    assert [s.kind for s in site.scratch] == ["vmem", "sem", "sem"]
    assert site.params["out_ref"] == ("out", 0)
    assert site.params["starts_ref"] == ("scalar", 0)
    assert site.params["scratch"] == ("scratch", 0)
    # the bump block's index map is constant: grid-resident, no
    # double-buffer charge
    assert site.in_specs[1].constant_index
    assert not site.in_specs[0].constant_index


def test_model_parses_shipping_gather_kernel():
    path = REPO / "chunkflow_tpu" / "ops" / "pallas_gather.py"
    ctx = FileContext(str(path), path.read_text())
    model = get_pallas_model(ctx)
    assert model.has_mode_selector
    assert len(model.sites) == 1
    site = model.sites[0]
    assert site.num_scalar_prefetch == 2
    assert [s.any_space for s in site.in_specs] == [True]
    assert site.aliases is None
    assert site.params["chunk_ref"] == ("in", 0)


def test_shipping_kernels_have_zero_pallas_findings():
    for rel in ("chunkflow_tpu/ops/pallas_blend.py",
                "chunkflow_tpu/ops/pallas_gather.py"):
        path = REPO / rel
        findings, suppressed = lint_file(
            str(path), path.read_text(), Config()
        )
        gl02x = [f for f in findings if f.code.startswith("GL02")]
        assert gl02x == [], f"{rel}: {gl02x}"
        assert suppressed == 0, rel
