"""The storage plane (chunkflow_tpu/volume/storage.py, ISSUE 11):
backend interface, block-granular hot-chunk LRU, concurrent block I/O,
the coalescing write path, and the telemetry/scheduler/observability
wiring. Everything here runs against the in-memory backend (no driver,
no disk) except the KV-plane tests, which exercise the real tensorstore
KvStore batched-existence path over a file root."""
import threading

import numpy as np
import pytest

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.volume import storage
from chunkflow_tpu.volume.storage import (
    BlockCache,
    FileKV,
    GatherFuture,
    MemoryBackend,
    TensorStoreKV,
    blockwise_cutout,
    blockwise_save,
    open_kv,
    serial_cutout,
    set_read_concurrency,
    shared_cache,
)


@pytest.fixture(autouse=True)
def _clean_state():
    telemetry.reset()
    storage.reset_shared_cache()
    storage._reset_read_concurrency()
    yield
    telemetry.reset()
    storage.reset_shared_cache()
    storage._reset_read_concurrency()


def _backend(shape=(40, 50, 60), block=(16, 16, 16), seed=0, **kw):
    rng = np.random.default_rng(seed)
    # 1..255: no all-zero block (zero blocks are deliberately uncached)
    data = rng.integers(1, 255, size=shape, dtype=np.uint8)
    return data, MemoryBackend(data.copy(), block_shape=block, **kw)


# ---------------------------------------------------------------------------
# BlockCache
# ---------------------------------------------------------------------------
def test_cache_lru_eviction_holds_byte_budget():
    cache = BlockCache(3 * 100)
    blocks = {i: np.full(100, i, dtype=np.uint8) for i in range(5)}
    for i in range(4):
        assert cache.put(("t", i), blocks[i])
    assert cache.nbytes <= 300
    assert cache.evictions == 1
    assert cache.get(("t", 0)) is None  # LRU victim
    # touching 1 protects it from the next eviction
    assert cache.get(("t", 1)) is not None
    cache.put(("t", 4), blocks[4])
    assert cache.get(("t", 1)) is not None
    assert cache.get(("t", 2)) is None


def test_cache_refuses_oversized_and_invalidates():
    cache = BlockCache(100)
    assert not cache.put(("t", 0), np.zeros(101, dtype=np.uint8))
    arr = np.ones(50, dtype=np.uint8)
    cache.put(("t", 1), arr)
    # cached blocks are frozen: a writer must go through invalidation
    with pytest.raises(ValueError):
        cache.get(("t", 1))[0] = 9
    assert cache.invalidate(("t", 1))
    assert not cache.invalidate(("t", 1))
    assert cache.nbytes == 0


def test_cache_invalidate_token_scopes_to_one_dataset():
    cache = BlockCache(1 << 20)
    cache.put(("a", (0,)), np.ones(8, dtype=np.uint8))
    cache.put(("a", (8,)), np.ones(8, dtype=np.uint8))
    cache.put(("b", (0,)), np.ones(8, dtype=np.uint8))
    assert cache.invalidate_token("a") == 2
    assert cache.get(("b", (0,))) is not None


def test_shared_cache_env_knobs(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_STORAGE_CACHE_MB", "0")
    assert shared_cache() is None
    monkeypatch.setenv("CHUNKFLOW_STORAGE_CACHE_MB", "1")
    cache = shared_cache()
    assert cache is not None and cache.max_bytes == 1 << 20
    assert shared_cache() is cache  # stable while the budget holds
    monkeypatch.setenv("CHUNKFLOW_STORAGE_CACHE_MB", "2")
    assert shared_cache() is not cache  # resized -> rebuilt


# ---------------------------------------------------------------------------
# concurrent blockwise reads
# ---------------------------------------------------------------------------
def test_blockwise_cutout_bit_identical_on_ragged_windows():
    data, backend = _backend()
    cache = BlockCache(1 << 24)
    windows = [
        ((0, 0, 0), (40, 50, 60)),    # whole volume (ragged tail blocks)
        ((3, 5, 7), (37, 49, 55)),    # interior, nothing aligned
        ((16, 16, 16), (32, 32, 32)),  # exactly one block
        ((39, 49, 59), (40, 50, 60)),  # single trailing voxel
    ]
    for lo, hi in windows:
        out = blockwise_cutout(backend, lo, hi, cache=cache)
        ref = data[tuple(slice(l, h) for l, h in zip(lo, hi))]
        np.testing.assert_array_equal(out, ref)
        np.testing.assert_array_equal(
            serial_cutout(backend, lo, hi), ref)
    backend.close()


def test_overlapping_reads_hit_the_cache():
    data, backend = _backend()
    cache = BlockCache(1 << 24)
    blockwise_cutout(backend, (0, 0, 0), (16, 16, 16), cache=cache)
    assert cache.misses == 1 and cache.hits == 0
    # the halo neighbor overlaps the same block: a hit, plus new misses
    blockwise_cutout(backend, (8, 8, 8), (24, 24, 24), cache=cache)
    assert cache.hits == 1
    assert cache.misses == 1 + 7
    # full repeat: pure hits
    misses = cache.misses
    blockwise_cutout(backend, (8, 8, 8), (24, 24, 24), cache=cache)
    assert cache.misses == misses
    backend.close()


def test_cutout_counters_flow_into_telemetry_and_metrics():
    from chunkflow_tpu.parallel.restapi import render_prometheus

    _data, backend = _backend()
    cache = BlockCache(1 << 24)
    blockwise_cutout(backend, (0, 0, 0), (32, 32, 32), cache=cache)
    blockwise_cutout(backend, (0, 0, 0), (32, 32, 32), cache=cache)
    counters = telemetry.snapshot()["counters"]
    assert counters["storage/misses"] == 8
    assert counters["storage/hits"] == 8
    assert counters["storage/block_reads"] == 8
    assert counters["storage/bytes_read"] == 8 * 16 ** 3
    text = render_prometheus()
    assert "chunkflow_storage_hits_total" in text
    assert "chunkflow_storage_misses_total" in text
    assert "chunkflow_storage_bytes_read_total" in text
    backend.close()


def test_all_zero_blocks_are_never_pinned():
    """A zero block may simply not exist yet (fill_missing rendering):
    caching it would hide a neighbor task's later write forever."""
    data = np.zeros((16, 16, 16), dtype=np.uint8)
    backend = MemoryBackend(data, block_shape=(16, 16, 16))
    cache = BlockCache(1 << 20)
    out = blockwise_cutout(backend, (0, 0, 0), (16, 16, 16), cache=cache)
    assert not out.any() and len(cache) == 0
    # the block gets written out-of-band (another worker); we must see it
    backend._array[:] = 7
    out = blockwise_cutout(backend, (0, 0, 0), (16, 16, 16), cache=cache)
    assert (out == 7).all()
    backend.close()


def test_read_concurrency_waves_stay_correct():
    data, backend = _backend()
    set_read_concurrency(2)
    out = blockwise_cutout(backend, (0, 0, 0), (40, 50, 60))
    np.testing.assert_array_equal(out, data)
    assert storage.read_concurrency() == 2
    backend.close()


def test_out_of_domain_requests_raise():
    _data, backend = _backend()
    with pytest.raises(ValueError):
        blockwise_cutout(backend, (0, 0, 0), (41, 50, 60))
    with pytest.raises(ValueError):
        serial_cutout(backend, (-1, 0, 0), (8, 8, 8))
    backend.close()


def test_cache_is_thread_safe_across_tasks():
    """The LRU is shared across tasks in a worker: hammer one cache from
    worker threads doing overlapping cutouts + invalidations (locksmith
    proxies every lock in the suite, so ordering violations raise)."""
    data, backend = _backend(shape=(32, 32, 32), block=(8, 8, 8))
    cache = BlockCache(1 << 16)  # small: force concurrent evictions
    errors = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(30):
                lo = tuple(int(v) for v in rng.integers(0, 16, size=3))
                hi = tuple(l + 16 for l in lo)
                out = blockwise_cutout(backend, lo, hi, cache=cache)
                ref = data[tuple(slice(l, h) for l, h in zip(lo, hi))]
                if not np.array_equal(out, ref):
                    errors.append((lo, hi))
                if rng.random() < 0.2:
                    cache.invalidate((backend.cache_token, lo))
        except Exception as exc:  # noqa: BLE001 — surface in main thread
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not errors, errors[:3]
    backend.close()


# ---------------------------------------------------------------------------
# the coalescing write path
# ---------------------------------------------------------------------------
def test_aligned_save_is_write_through():
    data, backend = _backend()
    cache = BlockCache(1 << 24)
    rng = np.random.default_rng(1)
    w = rng.integers(1, 255, size=(16, 32, 16), dtype=np.uint8)
    blockwise_save(backend, (16, 16, 16), w, cache=cache)
    # durable in the backend...
    np.testing.assert_array_equal(
        serial_cutout(backend, (16, 16, 16), (32, 48, 32)), w)
    # ...and read-after-write through the cache returns the written
    # bytes WITHOUT touching storage (poke the backing array to prove
    # the blocks are cache-served)
    with backend._lock:
        backend._array[16:32, 16:48, 16:32] = 0
    out = blockwise_cutout(backend, (16, 16, 16), (32, 48, 32),
                           cache=cache)
    np.testing.assert_array_equal(out, w)
    assert telemetry.snapshot()["counters"]["storage/aligned_writes"] == 1
    backend.close()


def test_trailing_clamped_blocks_count_as_aligned():
    """A write ending at the domain edge owns its (clamped) trailing
    blocks — the same clamping the storage layout itself applies."""
    data, backend = _backend()          # 40x50x60, blocks 16^3
    w = np.full((8, 2, 12), 9, dtype=np.uint8)
    blockwise_save(backend, (32, 48, 48), w)  # hi == domain stop
    counters = telemetry.snapshot()["counters"]
    assert counters["storage/aligned_writes"] == 1
    assert "storage/unaligned_writes" not in counters
    np.testing.assert_array_equal(
        serial_cutout(backend, (32, 48, 48), (40, 50, 60)), w)
    backend.close()


def test_unaligned_save_invalidates_covered_blocks():
    data, backend = _backend()
    cache = BlockCache(1 << 24)
    blockwise_cutout(backend, (16, 16, 16), (32, 32, 32), cache=cache)
    assert len(cache) == 1
    u = np.full((8, 8, 8), 77, dtype=np.uint8)
    blockwise_save(backend, (20, 20, 20), u, cache=cache)
    assert len(cache) == 0  # covered block dropped
    out = blockwise_cutout(backend, (16, 16, 16), (32, 32, 32),
                           cache=cache)
    assert (out[4:12, 4:12, 4:12] == 77).all()
    counters = telemetry.snapshot()["counters"]
    assert counters["storage/unaligned_writes"] == 1
    assert counters["storage/bytes_written"] == u.nbytes
    backend.close()


def test_save_wait_false_returns_drainable_future():
    data, backend = _backend(latency_s=0.001)
    w = np.full((16, 16, 16), 5, dtype=np.uint8)
    future = blockwise_save(backend, (0, 0, 0), w, wait=False)
    assert future is not None
    # the copy leg is already awaited: mutating the source must not
    # corrupt the committed bytes
    w[:] = 0
    future.result()
    np.testing.assert_array_equal(
        serial_cutout(backend, (0, 0, 0), (16, 16, 16)),
        np.full((16, 16, 16), 5, dtype=np.uint8))
    backend.close()


def test_gather_future_drains_all_and_raises_first():
    class Boom:
        def __init__(self, exc=None):
            self.drained = False
            self.exc = exc

        def result(self):
            self.drained = True
            if self.exc is not None:
                raise self.exc

    ok1, bad, ok2 = Boom(), Boom(RuntimeError("x")), Boom()
    gathered = GatherFuture([ok1, bad, ok2])
    with pytest.raises(RuntimeError, match="x"):
        gathered.result()
    # every member drained even though one failed (the
    # drain_pending_writes contract)
    assert ok1.drained and bad.drained and ok2.drained


# ---------------------------------------------------------------------------
# the KV plane
# ---------------------------------------------------------------------------
def test_file_kv_roundtrip_and_exists(tmp_path):
    kv = open_kv({"driver": "file", "path": str(tmp_path)})
    assert isinstance(kv, FileKV)
    assert kv.read_bytes("info") is None
    kv.write_bytes("sub/dir/blob", b"abc")
    assert kv.read_bytes("sub/dir/blob") == b"abc"
    assert kv.exists_many(["sub/dir/blob", "nope"]) == {
        "sub/dir/blob": True, "nope": False}


def test_tensorstore_kv_batched_existence(tmp_path):
    """The remote-path existence check must be a batched key listing —
    one round trip for a whole task grid's blocks, never a full-value
    download per block (ISSUE 11 satellite)."""
    pytest.importorskip("tensorstore")
    kv = TensorStoreKV({"driver": "file", "path": str(tmp_path)})
    kv.write_bytes("scale/0-16_0-16_0-16", b"\x00" * 64)
    kv.write_bytes("scale/16-32_0-16_0-16", b"\x00" * 64)
    names = ["scale/0-16_0-16_0-16", "scale/16-32_0-16_0-16",
             "scale/32-48_0-16_0-16"]
    assert kv.exists_many(names) == {
        names[0]: True, names[1]: True, names[2]: False}
    assert kv.exists_many([]) == {}
    # the handle is opened once and cached on the backend
    assert kv.kv is kv.kv


# ---------------------------------------------------------------------------
# scheduler integration: the storage depth knob
# ---------------------------------------------------------------------------
def test_depth_controller_widens_storage_on_load_stall():
    from chunkflow_tpu.flow.scheduler import DepthController

    ctl = DepthController(interval=1, min_share=0.4)
    assert ctl.depths["storage"] == storage.read_concurrency()
    before = ctl.depths["storage"]
    # a load-dominated window widens prefetch AND storage, and pushes
    # the widened parallelism to the live storage plane
    ctl.tick({"scheduler/load": 10.0})
    assert ctl.depths["storage"] == before + 1
    assert storage.read_concurrency() == before + 1
    assert ctl.depths["prefetch"] > ctl.initial["prefetch"]


def test_depth_controller_storage_knob_excluded_from_memory_model():
    from chunkflow_tpu.flow.scheduler import DepthController

    ctl = DepthController()
    assert ctl.resident_slots() == sum(
        v for k, v in ctl.depths.items() if k != "storage")


# ---------------------------------------------------------------------------
# observability: the log-summary STORAGE block + lint gate
# ---------------------------------------------------------------------------
def test_log_summary_storage_block(capsys):
    from chunkflow_tpu.flow.log_summary import (
        print_storage_block,
        summarize_telemetry,
    )

    events = [{
        "kind": "snapshot", "t": 1.0, "worker": "w1",
        "counters": {"storage/hits": 30, "storage/misses": 10,
                     "storage/bytes_read": 4096,
                     "storage/aligned_writes": 2},
        "gauges": {"storage/cache_bytes": 2 << 20},
        "hists": {},
    }]
    agg = summarize_telemetry(events)
    assert print_storage_block(agg)
    out = capsys.readouterr().out
    assert "storage/hits" in out
    assert "block cache hit rate 75%" in out
    # quiet for runs that never touched the storage plane
    assert not print_storage_block(summarize_telemetry([]))


def test_fleet_summary_reports_storage_hit_rate():
    from chunkflow_tpu.flow.log_summary import summarize_fleet

    events = [{
        "kind": "snapshot", "t": 1.0, "worker": "w1",
        "counters": {"storage/hits": 8, "storage/misses": 2},
        "gauges": {}, "hists": {},
    }]
    fleet = summarize_fleet(events)
    assert fleet["w1"]["storage_hit_rate"] == pytest.approx(0.8)


def test_storage_plane_is_graftlint_clean():
    """ISSUE 11 satellite: GL001-GL014 clean over the new/reworked
    storage-plane modules, asserted in-suite (the whole-repo gate in
    tests/tools/test_graftlint_gate.py covers them too; this pins the
    specific modules so a future baseline regeneration cannot quietly
    grandfather a concurrency finding here)."""
    from pathlib import Path

    from tools.graftlint.config import load_config
    from tools.graftlint.engine import lint_paths

    repo_root = Path(__file__).resolve().parents[1]
    config = load_config(repo_root / "pyproject.toml")
    findings, _ = lint_paths(
        [
            "chunkflow_tpu/volume/storage.py",
            "chunkflow_tpu/volume/precomputed.py",
            "chunkflow_tpu/plugins/load_tensorstore.py",
            "chunkflow_tpu/plugins/load_n5.py",
        ],
        config, repo_root=repo_root,
    )
    assert not findings, [
        f"{f.path}:{f.line}: {f.code} {f.message}" for f in findings
    ]
