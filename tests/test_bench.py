"""bench.py config-matrix smoke: every CONFIGS entry must run end-to-end
(tiny shapes, CPU mesh) so breakage surfaces in CI, not in a scarce
hardware window. The pallas config must fail loudly on a non-TPU backend
rather than silently measuring the XLA path."""
import json

import numpy as np
import pytest

import bench


@pytest.fixture()
def tiny_bench(monkeypatch):
    monkeypatch.setattr(bench, "INPUT_PATCH", (8, 32, 32))
    monkeypatch.setattr(bench, "OUTPUT_OVERLAP", (2, 8, 8))
    # keep env mutations from leaking into other tests
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "0")
    monkeypatch.delenv("CHUNKFLOW_BLEND_STACK_MAX_GB", raising=False)
    return bench


def test_all_nonpallas_configs_run(tiny_bench):
    ran = 0
    for cfg in tiny_bench.CONFIGS:
        if cfg.get("pallas", "0") not in ("0", "off", "false"):
            continue
        cfg = dict(cfg, chunk_size=(16, 64, 64), batch_size=2, iters=1)
        if cfg.get("stream"):
            cfg["stream"] = 2
        stats = tiny_bench.run_config(cfg)
        assert stats["mvox_s"] > 0, cfg
        ran += 1
    assert ran >= 5


def test_pallas_config_fails_loudly_on_cpu(tiny_bench):
    cfg = dict(
        next(
            c for c in tiny_bench.CONFIGS
            if c.get("pallas", "0") == "1"
        ),
        chunk_size=(16, 64, 64),
        batch_size=2,
    )
    # CHUNKFLOW_PALLAS=1 force-enables the kernel even off-TPU (the real
    # chip reports platform 'axon', so auto-detection can't be trusted);
    # on CPU the kernel itself then fails in the pre-measurement oracle —
    # either way the config errors instead of silently measuring XLA
    with pytest.raises((RuntimeError, ValueError)):
        tiny_bench.run_config(cfg)


@pytest.mark.bench
@pytest.mark.slow
def test_pipeline_overlap_microbench(tmp_path):
    """The double-buffered executor must beat the serial chunk loop on
    the synthetic CPU workload (ISSUE 2 acceptance: >= 1.2x) and stay
    bit-identical — run_pipeline_overlap itself raises on divergence.

    Marked slow/bench (ISSUE 7 satellite): this speedup-RATIO gate is
    load-sensitive — it flips in full tier-1 runs on the 1-core CI box
    even at commits where it passes in isolation (verified in PR 6 by
    stash-and-rerun), so tier-1 (-m 'not slow') no longer reports it as
    a false regression. Coverage is kept by run_tests.sh, which runs
    the same workload as a standalone gate after pytest.

    Measured in a FRESH SUBPROCESS under the benchmark's actual
    contract (`python bench.py pipeline_overlap` from a shell): inside
    the suite's interpreter the ratio is contaminated down to ~1.0
    (observed at the PR 2 commit as well, so suite state, not the
    executor) — chiefly by conftest.py's
    --xla_force_host_platform_device_count=8, which splits the CPU
    client 8 ways and must be scrubbed from the child env too. The
    overlap itself is deterministic; best-of-3 still guards against
    load spikes on a shared CI box."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)  # the 8-device virtual mesh (conftest.py)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "pipeline_overlap"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.2:
            break
    assert best["value"] >= 1.2, best
    assert best["metric"] == "pipeline_overlap_speedup"
    assert best["pipelined_s"] < best["serial_s"], best
    # the run's own telemetry JSONL landed where we pointed it
    assert best["cache_builds"] == 1, best  # one bucket -> one trace
    assert any(
        name.endswith(".jsonl") for name in os.listdir(tmp_path)
    ), best.get("telemetry_jsonl")


@pytest.mark.bench
@pytest.mark.slow
def test_e2e_overlap_microbench(tmp_path):
    """The adaptive scheduler must beat the serial full-lifecycle loop
    (load → compute → post → write) on the calibrated synthetic CPU
    workload (ISSUE 4 acceptance: >= 1.4x) and stay bit-identical —
    run_e2e_overlap itself raises on divergence or broken task order.

    Marked slow/bench (ISSUE 7 satellite): load-sensitive ratio gate —
    see test_pipeline_overlap_microbench; run_tests.sh runs the same
    workload as a standalone gate after pytest.

    Fresh-subprocess pattern from the pipeline_overlap gate: inside the
    suite's interpreter the ratio is contaminated by conftest's 8-device
    virtual mesh; best-of-3 guards against load spikes on a shared CI
    box."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)  # the 8-device virtual mesh (conftest.py)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "e2e_overlap"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.4:
            break
    assert best["value"] >= 1.4, best
    assert best["metric"] == "e2e_overlap_speedup"
    assert best["gate_pass"] is True, best
    assert best["scheduled_s"] < best["serial_s"], best
    # the JSON line reports the final adapted depths, and the run's own
    # telemetry JSONL (incl. the scheduler/final depths event) landed
    # where we pointed it
    assert set(best["final_depths"]) == {
        "prefetch", "ring", "inflight", "post", "write", "storage"
    }, best
    jsonls = [n for n in os.listdir(tmp_path) if n.endswith(".jsonl")]
    assert jsonls, best.get("telemetry_jsonl")
    events = []
    for name in jsonls:
        with open(os.path.join(tmp_path, name)) as f:
            events += [json.loads(line) for line in f if line.strip()]
    finals = [e for e in events
              if e.get("kind") == "depths" and e["name"] == "scheduler/final"]
    assert finals, "no scheduler/final depths event in the run's JSONL"


def test_resilience_overhead_microbench(tmp_path):
    """The fault-tolerance layer (supervised claims + completion ledger
    + lease heartbeat, ISSUE 5) must be ~free over the e2e_overlap-style
    workload: run_resilience_overhead itself raises on a broken task
    order, an undrained queue, or an incomplete ledger; the process
    hard-fails past 15% overhead. The <3% target rides the JSON line as
    gate_pass — asserted loosely here (< half the hard gate) because a
    1-core shared CI box can inflate a sub-millisecond-per-task delta.

    Fresh-subprocess pattern from the other microbench gates: conftest's
    8-device virtual mesh contaminates in-suite measurement."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "resilience_overhead"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] < best["value"]:
            best = stats
        if best["gate_pass"]:
            break
    assert best["metric"] == "resilience_overhead"
    assert best["value"] < 7.5, best  # half the 15% hard gate
    assert best["gate_pct"] == 3.0
    assert best["on_s"] > 0 and best["off_s"] > 0, best
    assert any(
        name.endswith(".jsonl") for name in os.listdir(tmp_path)
    ), best.get("telemetry_jsonl")


def test_export_overhead_microbench(tmp_path):
    """The live /metrics exporter (ISSUE 6) must be ~free over the
    e2e_overlap-style workload even while being scraped continuously:
    run_export_overhead itself raises on a broken task order or a
    missing listener; the process hard-fails past 10% overhead. The <2%
    target rides the JSON line as gate_pass — asserted loosely here
    (< half the hard gate) because a 1-core shared CI box can inflate a
    sub-millisecond-per-task delta.

    Fresh-subprocess pattern from the other microbench gates: conftest's
    8-device virtual mesh contaminates in-suite measurement."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "export_overhead"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] < best["value"]:
            best = stats
        if best["gate_pass"]:
            break
    assert best["metric"] == "export_overhead"
    assert best["value"] < 5.0, best  # half the 10% hard gate
    assert best["gate_pct"] == 2.0
    assert best["scrapes"] > 0, best  # the listener really was being hit
    assert best["on_s"] > 0 and best["off_s"] > 0, best
    assert any(
        name.endswith(".jsonl") for name in os.listdir(tmp_path)
    ), best.get("telemetry_jsonl")


def test_trace_export_overhead_shape_and_invariants():
    """The Perfetto exporter gate (ISSUE 18): run_trace_export_overhead
    raises if the synthetic trace fails validation or drops a
    cross-worker flow, so in-suite we only pin the measurement shape at
    a tiny size — absolute throughput is the CI stage's business (soft
    floor 50k events/s, hard floor 5k)."""
    stats = bench.run_trace_export_overhead(
        n_workers=3, n_tasks=40, n_spans=200, n_gauges=200,
        n_snapshots=40, repeats=1)
    assert stats["metric"] == "trace_export_overhead"
    assert stats["unit"] == "events/s"
    assert stats["value"] > 0 and stats["best_s"] > 0
    assert stats["events"] == 40 * 3 + 200 + 200 + 40
    assert stats["flow_pairs"] == 40  # every synthetic task hops
    assert stats["trace_events"] >= stats["events"]
    assert stats["gate_pct"] == 50000.0
    assert isinstance(stats["gate_pass"], bool)


def test_slo_overhead_microbench(tmp_path):
    """The SLO plane (time-series sampler + burn-rate evaluator,
    ISSUE 12) must be ~free over the e2e_overlap-style workload even at
    a 0.1 s sampling interval (100x the production default):
    run_slo_overhead itself raises when the plane fails to run, takes
    no samples, or fires an alert on the healthy workload; the process
    hard-fails past 10% overhead. The <2% target rides the JSON line as
    gate_pass — asserted loosely here (< half the hard gate) because a
    1-core shared CI box can inflate a sub-millisecond-per-task delta.

    Fresh-subprocess pattern from the other microbench gates: conftest's
    8-device virtual mesh contaminates in-suite measurement."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "slo_overhead"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] < best["value"]:
            best = stats
        if best["gate_pass"]:
            break
    assert best["metric"] == "slo_overhead"
    assert best["value"] < 5.0, best  # half the 10% hard gate
    assert best["gate_pct"] == 2.0
    assert best["on_s"] > 0 and best["off_s"] > 0, best


def test_cfg_names_unique():
    names = [bench._cfg_name(c) for c in bench.CONFIGS]
    assert len(names) == len(set(names)), names


def test_config_timeout_counts_as_tunnel_failure():
    """Rounds 1-2 regression: the dominant tunnel failure mode is a
    C-level wedge surfacing as _ConfigTimeout, which must qualify for the
    cached-on-chip fallback (VERDICT r2 weak#1)."""
    results = {
        "tpu-bfloat16-bs4-pallas0": {
            "ok": False,
            "error": "Traceback ...\n_ConfigTimeout: config exceeded "
                     "480s budget\n",
        },
    }
    assert bench._failures_look_like_dead_tunnel(results)
    # a genuine code failure must NOT be mistaken for a dead tunnel
    results["tpu-bfloat16-bs4-pallas0"]["error"] = (
        "Traceback ...\nTypeError: bad operand\n"
    )
    assert not bench._failures_look_like_dead_tunnel(results)


def test_parent_emits_cached_on_probe_failure(monkeypatch, capsys):
    """A wedged/dead tunnel at probe time must still produce ONE JSON
    line (the cached on-chip number) and rc=0."""
    monkeypatch.setattr(
        bench, "_probe_backend", lambda t: (False, "probe wedged (test)")
    )
    cached = bench._cached_hardware_result()
    if cached is None:
        pytest.skip("no committed hardware snapshots")
    rc = bench.parent_main()
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    payload = json.loads(out[-1])
    assert payload["cached"] is True
    assert payload["unit"] == "Mvoxel/s/chip"
    assert payload["value"] > 0


def test_parent_live_path_end_to_end(monkeypatch, capsys, tmp_path):
    """The full parent->probe->child->live-result chain at smoke scale:
    the one path the CPU could never finish at production geometry. The
    child is a real subprocess, so the geometry rides env overrides."""
    monkeypatch.setenv("CHUNKFLOW_BENCH_CHUNK", "16,64,64")
    monkeypatch.setenv("CHUNKFLOW_BENCH_PATCH", "8,32,32")
    monkeypatch.setenv("CHUNKFLOW_BENCH_OVERLAP", "2,8,8")
    monkeypatch.setenv("CHUNKFLOW_BENCH_VARIANT", "tpu")
    monkeypatch.setenv("CHUNKFLOW_BENCH_DTYPE", "float32")
    monkeypatch.setenv("CHUNKFLOW_BENCH_BATCH", "2")
    monkeypatch.setenv("CHUNKFLOW_BENCH_WALLCLOCK", "300")
    monkeypatch.setenv("CHUNKFLOW_BENCH_RESULTS",
                       str(tmp_path / "bench_results.json"))
    rc = bench.parent_main()
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    payload = json.loads(out[-1])
    assert payload.get("cached") is None, payload  # LIVE, not fallback
    assert payload["unit"] == "Mvoxel/s/chip"
    assert payload["value"] > 0
    assert payload["config"].startswith("tpu-float32-bs2")


def test_cached_hardware_result_shape():
    cached = bench._cached_hardware_result()
    if cached is None:
        pytest.skip("no committed hardware snapshots")
    assert cached["unit"] == "Mvoxel/s/chip"
    assert cached["cached"] is True
    assert cached["value"] > 0
    assert np.isclose(
        cached["vs_baseline"], round(cached["value"] / 1.66, 2), atol=0.01
    )
    # VERDICT r3 weak#1: a cached number is self-describing — it names
    # the commit it was measured at, in both the field and the prose note
    assert cached["measured_at_commit"] not in ("", "unknown", None)
    assert cached["measured_at_commit"] in cached["note"]


def test_cached_result_skips_nondefault_geometry(tmp_path, monkeypatch):
    """A battery row measured at a different patch/overlap geometry
    (geometry_note) must never win the cached headline: the baseline was
    measured at the default geometry."""
    snap = {
        "bench_fast_geom": {"ok": True, "commit": "c1", "platform": "axon",
                            "value": {"mvox_s": 99.0,
                                      "geometry_note": "overlap 2x32x32"}},
        "bench_default": {"ok": True, "commit": "c2", "platform": "axon",
                          "value": {"mvox_s": 2.0}},
    }
    tools = tmp_path / "tools"
    tools.mkdir()
    (tools / "tpu_validation_test.json").write_text(json.dumps(snap))
    monkeypatch.setattr(bench, "_HERE", str(tmp_path))
    cached = bench._cached_hardware_result()
    assert cached["value"] == 2.0
    assert cached["config"] == "cached:bench_default"


def test_cached_result_requires_platform_stamp(tmp_path, monkeypatch):
    """ADVICE r4: the no-stamp exemption is frozen to the two known
    round-2 snapshot filenames. An unstamped row in any OTHER
    tpu_validation*.json (e.g. a future rehearsal tool that forgets the
    stamp) must not regain 'real chip' eligibility; the same row under a
    legacy filename stays eligible."""
    snap = {"bench_a": {"ok": True, "value": {"mvox_s": 42.0}}}
    tools = tmp_path / "tools"
    tools.mkdir()
    (tools / "tpu_validation_future_tool.json").write_text(json.dumps(snap))
    monkeypatch.setattr(bench, "_HERE", str(tmp_path))
    assert bench._cached_hardware_result() is None
    (tools / "tpu_validation_oldblend.json").write_text(json.dumps(snap))
    cached = bench._cached_hardware_result()
    assert cached is not None and cached["value"] == 42.0


def test_cached_result_skips_non_tpu_platform(tmp_path, monkeypatch):
    """A battery row stamped with a CPU backend (rehearsal output saved
    under a tools/tpu_validation*.json name) must never become the cached
    'real chip' headline; rows stamped tpu/axon or unstamped (legacy
    on-chip snapshots) stay eligible."""
    snap = {
        "bench_cpu_rehearsal": {"ok": True, "platform": "cpu",
                                "value": {"mvox_s": 99.0}},
        "bench_on_chip": {"ok": True, "platform": "axon",
                          "value": {"mvox_s": 2.0}},
    }
    tools = tmp_path / "tools"
    tools.mkdir()
    (tools / "tpu_validation_test.json").write_text(json.dumps(snap))
    monkeypatch.setattr(bench, "_HERE", str(tmp_path))
    cached = bench._cached_hardware_result()
    assert cached["config"] == "cached:bench_on_chip"
    assert cached["value"] == 2.0


def test_cached_result_prefers_per_row_commit(tmp_path, monkeypatch):
    """A battery row's own commit stamp wins over file-level _meta (resume
    runs can span commits)."""
    snap = {
        "_meta": {"measured_at_commit": "filelevel0", "blend_default": "x"},
        "bench_a": {"ok": True, "commit": "rowlevel1", "platform": "axon",
                    "value": {"mvox_s": 5.0}},
    }
    tools = tmp_path / "tools"
    tools.mkdir()
    (tools / "tpu_validation_test.json").write_text(json.dumps(snap))
    monkeypatch.setattr(bench, "_HERE", str(tmp_path))
    cached = bench._cached_hardware_result()
    assert cached["measured_at_commit"] == "rowlevel1"
    assert cached["measured_config"] == "x"
    assert cached["value"] == 5.0


# ---------------------------------------------------------------------------
# bench regression ledger (ISSUE 8): --ledger append + compare semantics
# ---------------------------------------------------------------------------
def _ledger_row(metric, value, cached=False, unit="x_serial",
                commit="abc1234", config=None):
    return {"t": 0.0, "commit": commit, "metric": metric, "value": value,
            "unit": unit, "config": config, "cached": cached}


def test_ledger_append_stamps_commit_config_cached(tmp_path, monkeypatch):
    path = tmp_path / "ledger.jsonl"
    monkeypatch.setattr(bench, "_LEDGER_FILE", str(path))
    bench._append_ledger({"metric": "e2e_overlap_speedup", "value": 2.5,
                          "unit": "x_serial", "gate_pass": True})
    bench._append_ledger({
        "metric": "affinity_inference_throughput", "value": 1.79,
        "unit": "Mvoxel/s/chip", "config": "cached:bench_tpu",
        "cached": True, "measured_at_commit": "deadbee",
    })
    rows = bench.load_ledger(str(path))
    assert len(rows) == 2
    fresh, cached = rows
    assert fresh["metric"] == "e2e_overlap_speedup"
    assert fresh["cached"] is False
    assert fresh["commit"]  # stamped with the measured tree's commit
    assert fresh["gate_pass"] is True
    assert cached["cached"] is True
    # a cached row keeps the commit the chip actually measured
    assert cached["commit"] == "deadbee"


def test_ledger_flag_consumed_by_main(tmp_path, monkeypatch, capsys):
    """`bench.py compare --ledger=PATH` parses and reads that path."""
    path = tmp_path / "ledger.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps(_ledger_row("m", 2.0)) + "\n")
    monkeypatch.setattr(bench.sys, "argv",
                        ["bench.py", "compare", f"--ledger={path}"])
    assert bench.main() == 0
    assert "1 row(s)" in capsys.readouterr().out


def test_compare_flags_fresh_regression(tmp_path):
    """Acceptance: a ledger seeded with two fresh entries flags an
    injected 30% regression (hard, exit 4 through compare_main)."""
    rows = [
        _ledger_row("e2e_overlap_speedup", 2.0),
        _ledger_row("e2e_overlap_speedup", 2.1),
        _ledger_row("e2e_overlap_speedup", 1.4),  # ~32% below median 2.05
    ]
    report = bench.compare_ledger(rows, threshold_pct=25.0)
    info = report["metrics"]["e2e_overlap_speedup"]
    assert info["status"] == "regression"
    assert info["baseline"] == pytest.approx(2.05)
    assert info["delta_pct"] > 25
    assert report["regressions"]

    path = tmp_path / "ledger.jsonl"
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    assert bench.compare_main([f"--ledger={path}"]) == 4


def test_compare_within_threshold_passes(tmp_path):
    rows = [
        _ledger_row("e2e_overlap_speedup", 2.0),
        _ledger_row("e2e_overlap_speedup", 2.1),
        _ledger_row("e2e_overlap_speedup", 1.9),  # ~7%: noise
    ]
    report = bench.compare_ledger(rows)
    assert report["metrics"]["e2e_overlap_speedup"]["status"] == "ok"
    assert not report["regressions"]


def test_compare_refuses_cached_rows_as_baseline():
    """Acceptance: cached: rows (the stale 1.79 headline shape) never
    enter a baseline, loudly."""
    rows = [
        _ledger_row("affinity_inference_throughput", 1.79, cached=True,
                    unit="Mvoxel/s/chip", commit="deadbee"),
        _ledger_row("affinity_inference_throughput", 1.81, cached=True,
                    unit="Mvoxel/s/chip", commit="deadbee"),
        _ledger_row("affinity_inference_throughput", 1.20,
                    unit="Mvoxel/s/chip"),
    ]
    report = bench.compare_ledger(rows)
    info = report["metrics"]["affinity_inference_throughput"]
    # 1.20 fresh vs 1.79/1.81 cached would read as a 33% regression —
    # but cached rows measured OLD code, so there is NO baseline
    assert info["status"] == "no-baseline"
    assert info["refused_cached"] == 2
    assert not report["regressions"]
    assert any("REFUSING 2 cached row(s)" in w for w in report["warnings"])


def test_compare_refuses_cached_current_row():
    rows = [
        _ledger_row("affinity_inference_throughput", 2.0,
                    unit="Mvoxel/s/chip"),
        _ledger_row("affinity_inference_throughput", 2.0,
                    unit="Mvoxel/s/chip"),
        _ledger_row("affinity_inference_throughput", 1.79, cached=True,
                    unit="Mvoxel/s/chip", commit="deadbee"),
    ]
    report = bench.compare_ledger(rows)
    info = report["metrics"]["affinity_inference_throughput"]
    assert info["status"] == "cached-current"
    assert not report["regressions"]
    assert any("current row is cached" in w for w in report["warnings"])


def test_compare_single_fresh_baseline_warns_only():
    rows = [
        _ledger_row("e2e_overlap_speedup", 2.0),
        _ledger_row("e2e_overlap_speedup", 1.0),  # 50% down, 1 baseline
    ]
    report = bench.compare_ledger(rows)
    assert report["metrics"]["e2e_overlap_speedup"]["status"] == "warn"
    assert not report["regressions"]


def test_compare_percentage_metrics_warn_only():
    """Overhead gates (pct units) are noise-dominated on a loaded box:
    even a big relative jump warns instead of hard-failing."""
    rows = [
        _ledger_row("telemetry_overhead", 1.0,
                    unit="pct_of_untelemetered_wall"),
        _ledger_row("telemetry_overhead", 1.2,
                    unit="pct_of_untelemetered_wall"),
        _ledger_row("telemetry_overhead", 5.0,
                    unit="pct_of_untelemetered_wall"),
    ]
    report = bench.compare_ledger(rows)
    assert report["metrics"]["telemetry_overhead"]["status"] == "warn"
    assert not report["regressions"]


def test_compare_empty_ledger_is_ok(tmp_path):
    assert bench.compare_main(
        [f"--ledger={tmp_path / 'missing.jsonl'}"]) == 0


@pytest.mark.bench
@pytest.mark.slow
def test_serving_throughput_microbench(tmp_path):
    """Packed cross-request batching must beat sequential per-chunk
    execution on many small concurrent requests (ISSUE 9 acceptance:
    >= 1.3x packed-occupancy speedup) and stay bit-identical —
    run_serving_throughput itself raises on any divergence.

    Marked slow/bench like the other load-sensitive ratio gates;
    run_tests.sh runs the same workload as a standalone gate after
    fleet_smoke. Fresh-subprocess + best-of-3 pattern shared with them."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # the 8-device virtual mesh (conftest.py)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "serving_throughput"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.3:
            break
    assert best["metric"] == "serving_throughput"
    assert best["value"] >= 1.3, best
    assert best["gate_pass"] is True, best
    assert best["bit_identical"] is True, best
    # the win is occupancy by construction: the packer must actually
    # have filled its batches from cross-request traffic
    assert best["packed_occupancy"] >= 0.9, best


@pytest.mark.bench
@pytest.mark.slow
def test_storage_throughput_microbench(tmp_path):
    """The hot block cache + concurrent block reads must beat the
    historical serial whole-range read on the overlapping-halo cutout
    grid (ISSUE 11 acceptance: >= 1.3x with a hot cache) and stay
    bit-identical — run_storage_throughput itself raises on any
    divergence between the serial, concurrent and cached legs.

    Marked slow/bench like the other load-sensitive ratio gates (the
    PR 7 deflake convention); run_tests.sh runs the same workload as a
    standalone gate after serving_throughput. Fresh-subprocess +
    best-of-3 pattern shared with them."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)  # the 8-device virtual mesh (conftest.py)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "storage_throughput"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.3:
            break
    assert best["metric"] == "storage_throughput_speedup"
    assert best["value"] >= 1.3, best
    assert best["gate_pass"] is True, best
    # the win is the cache by construction: the hot pass must be pure
    # hits, and the cold pass must already hit on grid overlap
    assert best["hot_cache_misses"] == 0, best
    assert best["cold_cache_hits"] > 0, best
    assert best["concurrent_cold_s"] < best["serial_s"], best
    # the run's storage counters landed in the telemetry JSONL for
    # log-summary (the acceptance visibility criterion)
    jsonls = [n for n in os.listdir(tmp_path) if n.endswith(".jsonl")]
    assert jsonls, best.get("telemetry_jsonl")
    events = []
    for name in jsonls:
        with open(os.path.join(tmp_path, name)) as f:
            events += [json.loads(line) for line in f if line.strip()]
    snaps = [e for e in events if e.get("kind") == "snapshot"]
    assert snaps, "no snapshot event in the run's JSONL"
    counters = snaps[-1].get("counters") or {}
    assert counters.get("storage/hits", 0) > 0, counters
    assert counters.get("storage/misses", 0) > 0, counters
    assert counters.get("storage/bytes_read", 0) > 0, counters


@pytest.mark.bench
@pytest.mark.slow
def test_segmentation_stitch_microbench(tmp_path):
    """The stitched map->reduce->map labeling must beat the monolithic
    whole-volume pass against latency-charged storage (ISSUE 20
    acceptance: >= 1.3x soft / 1.1x hard) and be label-isomorphic to
    it — run_segmentation_stitch itself raises on any divergence, so
    every round the speedup counts is also an exactness round.

    Marked slow/bench like the other load-sensitive ratio gates (the
    PR 7 deflake convention); run_tests.sh runs the same workload as a
    standalone gate. Fresh-subprocess + best-of-3 pattern shared with
    them."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)  # the 8-device virtual mesh (conftest.py)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "segmentation_stitch"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.3:
            break
    assert best["metric"] == "segmentation_stitch_speedup"
    assert best["value"] >= 1.3, best
    assert best["gate_pass"] is True, best
    # the whole grid went through the tree: every chunk labeled, every
    # interior node merged (a binary tree over n leaves has n-1)
    assert best["merge_nodes"] == best["n_chunks"] - 1, best
    # the run's segment counters landed in the telemetry JSONL for
    # log-summary's SEGMENT block (the acceptance visibility criterion)
    jsonls = [n for n in os.listdir(tmp_path) if n.endswith(".jsonl")]
    assert jsonls, best.get("telemetry_jsonl")
    events = []
    for name in jsonls:
        with open(os.path.join(tmp_path, name)) as f:
            events += [json.loads(line) for line in f if line.strip()]
    snaps = [e for e in events if e.get("kind") == "snapshot"]
    assert snaps, "no snapshot event in the run's JSONL"
    counters = snaps[-1].get("counters") or {}
    assert counters.get("segment/chunks_labeled", 0) == best["n_chunks"], \
        counters
    assert counters.get("segment/edges_found", 0) > 0, counters
    assert counters.get("segment/voxels_relabeled", 0) > 0, counters


@pytest.mark.bench
@pytest.mark.slow
def test_blend_fused_microbench(tmp_path):
    """The fused blend data-movement structure must beat the
    separate-leg baseline (ISSUE 14 acceptance: >= 1.2x soft / 1.1x
    hard) with bit-identity asserted in-run across both proxy legs, the
    XLA scatter reference and the real interpret-mode Pallas kernel —
    run_blend_fused itself raises on any divergence — and the fused
    family's roofline_util must be >= the separate-leg baseline in
    programs.json on the same workload.

    Marked slow/bench like the other load-sensitive ratio gates (the
    PR 7 deflake convention); run_tests.sh runs the same workload as a
    standalone gate after the multichip gate. Fresh-subprocess +
    best-of-3 pattern shared with them."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("CHUNKFLOW_PALLAS", None)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "blend_fused"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.2 and best["roofline_ok"]:
            break
    assert best["metric"] == "blend_fused"
    assert best["value"] >= 1.2, best
    assert best["gate_pass"] is True, best
    assert best["bit_identical"] is True, best
    assert best["interpret_kernel_checked"] is True, best
    # the acceptance roofline criterion: fused family util >= the
    # separate-leg baseline on the same workload, from programs.json
    assert best["roofline_ok"] is True, best
    programs = os.path.join(tmp_path, "programs.json")
    assert os.path.exists(programs), os.listdir(tmp_path)
    with open(programs) as f:
        entries = {e["family"]: e for e in json.load(f)["programs"]}
    assert "blend_fused" in entries and "blend_sep" in entries, entries
    assert (entries["blend_fused"]["roofline_util"]
            >= entries["blend_sep"]["roofline_util"]), entries


@pytest.mark.bench
@pytest.mark.slow
def test_front_half_microbench(tmp_path):
    """The device-resident front half must beat the host
    gather+convert+re-upload structure (ISSUE 15 acceptance: >= 1.2x
    soft / 1.1x hard on the H2D/data-movement proxy) with bit-identity
    asserted in-run across both legs and the real interpret-mode Pallas
    gather kernel — run_front_half itself raises on any divergence —
    and both legs must carry roofline rows in programs.json.

    Marked slow/bench like the other load-sensitive ratio gates (the
    PR 7 deflake convention); run_tests.sh runs the same workload as a
    standalone gate after the fused-blend gate. Fresh-subprocess +
    best-of-3 pattern shared with them."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("CHUNKFLOW_GATHER", None)
    env.pop("XLA_FLAGS", None)  # the 8-device virtual mesh (conftest.py)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "front_half"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.2:
            break
    assert best["metric"] == "front_half"
    assert best["value"] >= 1.2, best
    assert best["gate_pass"] is True, best
    assert best["bit_identical"] is True, best
    assert best["interpret_kernel_checked"] is True, best
    # the per-chunk H2D contract: the device leg ships the raw chunk
    # ONCE; the host leg ships every gathered patch as float32
    assert best["h2d_bytes_dev"] < best["h2d_bytes_host"], best
    assert best["h2d_ratio"] >= 4.0, best
    programs = os.path.join(tmp_path, "programs.json")
    assert os.path.exists(programs), os.listdir(tmp_path)
    with open(programs) as f:
        entries = {e["family"]: e for e in json.load(f)["programs"]}
    assert "front_dev" in entries and "front_host" in entries, entries
    assert entries["front_dev"]["roofline_util"] is not None, entries
    assert entries["front_host"]["roofline_util"] is not None, entries


@pytest.mark.bench
@pytest.mark.slow
def test_fused_pipeline_microbench(tmp_path):
    """The one-program patch pipeline (device-resident weighted stacks,
    donated on-device overlay, one scatter) must beat the
    separate-programs serving structure it replaced (ISSUE 17
    acceptance: >= 1.2x soft / 1.1x hard) with bit-identity asserted
    in-run across both proxies AND the composed real Pallas kernels
    (gather -> forward -> fused blend, interpret mode) —
    run_fused_pipeline itself raises on any divergence — and both legs
    must carry roofline rows in programs.json with the fused leg's
    utilization at least the separate leg's (both legs stamp the same
    logical byte floor, so util ranks the structures on identical
    work).

    Marked slow/bench like the other load-sensitive ratio gates (the
    PR 7 deflake convention); run_tests.sh runs the same workload as a
    standalone gate after the front-half gate. Fresh-subprocess +
    best-of-3 pattern shared with them."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("CHUNKFLOW_FUSED_PIPELINE", None)
    env.pop("XLA_FLAGS", None)  # the 8-device virtual mesh (conftest.py)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "fused_pipeline"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.2 and best["roofline_ok"]:
            break
    assert best["metric"] == "fused_pipeline"
    assert best["value"] >= 1.2, best
    assert best["gate_pass"] is True, best
    assert best["bit_identical"] is True, best
    assert best["interpret_kernel_checked"] is True, best
    assert best["roofline_ok"] is True, best
    # the fusion's prize, itemized: the separate structure pays real
    # inter-stage stack traffic; the fused structure pays none
    assert best["hbm_intermediate_sep"] > 0, best
    assert best["hbm_intermediate_fused"] == 0, best
    programs = os.path.join(tmp_path, "programs.json")
    assert os.path.exists(programs), os.listdir(tmp_path)
    with open(programs) as f:
        entries = {e["family"]: e for e in json.load(f)["programs"]}
    assert "pipe_fused" in entries and "pipe_sep" in entries, entries
    assert (entries["pipe_fused"]["roofline_util"]
            >= entries["pipe_sep"]["roofline_util"]), entries


@pytest.mark.bench
@pytest.mark.slow
def test_multichip_overlap_microbench(tmp_path):
    """The unified sharded engine on 8 simulated host devices must beat
    the single-device reference path (ISSUE 13 acceptance: >= 1.3x)
    and stay bit-identical — run_multichip_overlap itself raises on any
    divergence between the legs, and on the sharded program missing
    from the roofline ledger.

    Marked slow/bench like the other load-sensitive ratio gates (the
    PR 7 deflake convention); run_tests.sh runs the same workload as a
    standalone gate after the slo gate. Fresh-subprocess + best-of-3
    pattern shared with them (bench.py forces its own 8-device
    XLA_FLAGS, so the conftest scrub is harmless here)."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)
    env.pop("CHUNKFLOW_MESH", None)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "multichip_overlap"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.3:
            break
    assert best["metric"] == "multichip_overlap"
    assert best["value"] >= 1.3, best
    assert best["gate_pass"] is True, best
    assert best["bit_identical"] is True, best
    assert best["in_roofline_ledger"] is True, best
    assert best["n_devices"] == 8, best
    # one sharded program build, reused across every sharded dispatch
    # (the compile-cache invariant); builds = scatter + shard families
    assert best["cache_builds"] == 2, best
    # the sharded program catalog landed in programs.json (PR 8 ledger)
    programs = os.path.join(tmp_path, "programs.json")
    assert os.path.exists(programs), os.listdir(tmp_path)
    with open(programs) as f:
        entries = json.load(f)["programs"]
    assert any(e.get("family") == "shard" for e in entries), entries


@pytest.mark.bench
@pytest.mark.slow
def test_multichip_sharded_replay_microbench(tmp_path):
    """Sharded blend replay must beat replicated replay on the same
    8-device spatial mesh (ISSUE 19 acceptance: >= 1.3x soft, 1.1x
    hard) and stay bit-identical — run_multichip_sharded_replay itself
    raises on any divergence of either leg from the single-device
    reference, and on the sharded program missing from the roofline
    ledger.

    The measured win is TOTAL replay work removed (replicated replays
    every window on every chip; sharded replays each chip's slab roster
    once), so it holds on the 1-core CI box without calibrated sleeps.
    Fresh-subprocess + best-of-3 pattern shared with the other ratio
    gates (bench.py forces its own 8-device XLA_FLAGS)."""
    import os
    import subprocess
    import sys

    bench_py = os.path.join(os.path.dirname(bench.__file__), "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHUNKFLOW_BENCH_METRICS_DIR=str(tmp_path))
    env.pop("XLA_FLAGS", None)
    env.pop("CHUNKFLOW_MESH", None)
    env.pop("CHUNKFLOW_SHARD_REPLAY", None)
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [sys.executable, bench_py, "multichip_sharded_replay"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        stats = json.loads(proc.stdout.strip().splitlines()[-1])
        if best is None or stats["value"] > best["value"]:
            best = stats
        if best["value"] >= 1.3:
            break
    assert best["metric"] == "multichip_sharded_replay"
    assert best["value"] >= 1.1, best  # hard floor
    assert best["gate_pass"] is True, best  # soft 1.3x gate
    assert best["bit_identical"] is True, best
    assert best["in_roofline_ledger"] is True, best
    assert best["n_devices"] == 8, best
    # three program builds — single reference, replicated-replay shard,
    # sharded-replay shard — each reused across every later dispatch
    # (the compile-cache invariant: the replay mode is part of the key)
    assert best["cache_builds"] == 3, best
    # the sharded program catalog landed in programs.json (PR 8 ledger)
    programs = os.path.join(tmp_path, "programs.json")
    assert os.path.exists(programs), os.listdir(tmp_path)
    with open(programs) as f:
        entries = json.load(f)["programs"]
    assert any(e.get("family") == "shard" for e in entries), entries
