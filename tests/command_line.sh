#!/bin/bash
# Shell-level CLI smoke: composed pipelines exercised exactly as a user
# types them (reference test strategy: tests/command_line.sh, ~20
# pipelines; these are our own compositions over the same surface).
# Run by tests/test_command_line.py inside a tempdir with JAX on CPU.
set -euo pipefail

CLI="python -m chunkflow_tpu.flow.cli"

echo "=== 1. task grid round trip (volume-less forms) ==="
$CLI generate-tasks -b 0-16_0-32_0-32 -c 8 16 16 --task-file tasks.txt
test "$(wc -l < tasks.txt)" -eq 8
$CLI generate-tasks -s 0 0 0 -z 16 32 32 -c 8 16 16 --bounded -f tasks.npy

echo "=== 2. h5 round trip with offset + windowed reload ==="
$CLI create-chunk -s 16 32 32 --pattern sin -t 4 8 8 save-h5 -f a.h5
$CLI load-h5 -f a.h5 -t 8 8 8 -s 8 16 16 --set-bbox save-h5 -f a_win.h5
$CLI load-h5 -f a_win.h5 save-tif -f a.tif
$CLI load-tif -f a.tif -d float32 save-npy -f a.npy

echo "=== 3. png stack round trip ==="
$CLI create-chunk -s 6 16 16 --pattern random save-pngs -o pngs
$CLI load-png -p pngs -x 40 4 4 save-h5 -f pngs.h5

echo "=== 4. identity inference oracle through the shell ==="
$CLI create-chunk -s 16 32 32 --pattern sin -o img \
     inference -i img -o out -s 8 16 16 -v 2 8 8 -c 1 -f identity -b 2 \
         --no-crop-output-margin \
     multiply -i img,img -o sq \
     save-h5 -i out -f out.h5
python - <<'PY'
import h5py, numpy as np
out = np.asarray(h5py.File("out.h5")["main"])
assert out.shape[-3:] == (16, 32, 32)
PY

echo "=== 5. plugin with args mini-language ==="
$CLI create-chunk -s 8 16 16 --pattern random \
     plugin -n median_filter -a "size=(1,3,3)" \
     save-h5 -f filtered.h5

echo "=== 6. skip logic + markers + cleanup ==="
$CLI generate-tasks -b 0-8_0-16_0-16 -c 8 16 16 \
     create-chunk -s 8 16 16 --pattern zero \
     skip-all-zero -p done_ -s .marker
test -f done_0-8_0-16_0-16.marker
$CLI generate-tasks -b 0-8_0-16_0-16 -c 8 16 16 \
     skip-task-by-file -p done_ -s .marker -m exist \
     create-chunk -s 8 16 16 \
     save-h5 --file-name-prefix should_not_exist_
test ! -f should_not_exist_0-8_0-16_0-16.h5
touch empty_stale.h5
$CLI cleanup -d . -m empty --suffix .h5
test ! -f empty_stale.h5

echo "=== 7. segmentation: cc -> renumber -> evaluate -> mesh ==="
$CLI create-chunk -s 8 24 24 --pattern sin -o img \
     threshold -i img -o seg -t 0.5 \
     connected-components -i seg -o cc \
     evaluate-segmentation -s cc -g cc --output scores.jsonl \
     mesh -i cc -o meshes --manifest
test -s scores.jsonl
test "$(ls meshes | wc -l)" -gt 0

echo "=== 8. normalize + downsample + quantize ==="
$CLI create-chunk -s 8 32 32 --dtype uint8 --pattern sin \
     normalize-contrast -l 0.01 -u 0.01 --minval 1 --maxval 255 \
     downsample --factor 1 2 2 \
     save-h5 -f down.h5
python - <<'PY'
import h5py
assert h5py.File("down.h5")["main"].shape[-2:] == (16, 16)
PY

echo "=== 9. setup-env dry run ==="
$CLI --dry-run setup-env -l file://./planvol --volume-start 0 0 0 \
     -s 64 256 256 -z 8 16 16 --output-patch-overlap 2 8 8 -r 1

echo "=== 10. queue produce/consume round trip ==="
$CLI generate-tasks -b 0-16_0-32_0-32 -c 8 16 16 -q file://queue
$CLI fetch-task-from-queue -q file://queue --retry-times 1 \
     create-chunk -s 8 16 16 --pattern sin \
     save-h5 --file-name-prefix result_ \
     delete-task-in-queue
test "$(ls result_*.h5 | wc -l)" -eq 8

echo "ALL COMMAND-LINE SMOKE TESTS PASSED"
