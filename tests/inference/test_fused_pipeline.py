"""The fused patch pipeline's contracts (CHUNKFLOW_FUSED_PIPELINE,
ISSUE 17): the f32 output of the one-program pipeline (interpret leg —
Pallas gather front + fused Pallas blend + device-resident serving
stacks, under the kernelcheck sanitizer) is BITWISE identical to the
default separate-programs path across plain/ragged/uint8/crop-margin
traffic, every mesh shape, and packed serve; the knob outranks the
per-leg selectors; the pipeline tag keys every restructured program
family; and the analytic pipeline cost composes the builders' own
arithmetic (docs/performance.md "The fused patch pipeline")."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.inference import engines
from chunkflow_tpu.inference.inferencer import Inferencer
from chunkflow_tpu.ops import blend

PIN = (4, 16, 16)
OVERLAP = (2, 8, 8)


@pytest.fixture(scope="module")
def id_engine():
    return engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=PIN,
        num_input_channels=1, num_output_channels=3,
    )


@pytest.fixture(scope="module")
def crop_engine():
    return engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=(2, 8, 8),
        num_input_channels=1, num_output_channels=3,
    )


def _inferencer(engine, crop=False, **kw):
    if crop:
        return Inferencer(
            input_patch_size=PIN,
            output_patch_size=(2, 8, 8),
            output_patch_overlap=(1, 4, 4),
            num_output_channels=3,
            framework="prebuilt",
            batch_size=2,
            engine=engine,
            crop_output_margin=True,
            **kw,
        )
    return Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=3,
        framework="prebuilt",
        batch_size=2,
        engine=engine,
        crop_output_margin=False,
        **kw,
    )


def _traffic(kind: str):
    rng = np.random.default_rng(17)
    if kind == "ragged":
        # non-divisible extents: edge snapping, batch padding rows
        return Chunk(rng.random((6, 37, 45)).astype(np.float32))
    if kind == "uint8":
        # raw integer chunk: the pipeline's gather front converts
        # in-kernel by 1/iinfo.max (IEEE-exact)
        return Chunk(
            (rng.random((8, 40, 48)) * 255).astype(np.uint8))
    return Chunk(rng.random((8, 40, 48)).astype(np.float32))


# ---------------------------------------------------------------------------
# mode resolution + key structure
# ---------------------------------------------------------------------------
def test_pipeline_mode_off_is_invisible(monkeypatch):
    """Default OFF keeps every historical cache key byte-identical:
    empty tag, empty key tuple."""
    monkeypatch.delenv("CHUNKFLOW_FUSED_PIPELINE", raising=False)
    assert blend.fused_pipeline_mode() == "off"
    assert blend.pipeline_tag() == ""
    assert blend.pipeline_key() == ()


def test_pipeline_mode_tags(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "on")
    assert blend.fused_pipeline_mode() == "on"
    assert blend.pipeline_key() == ("pipe-on",)
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpret")
    monkeypatch.delenv("CHUNKFLOW_KERNELCHECK", raising=False)
    assert blend.pipeline_key() == ("pipe-interpret",)
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    # the sanitizer's hooks are program identity on interpret legs
    assert blend.pipeline_key() == ("pipe-interpret+kc",)


def test_pipeline_typo_warns_once_and_stays_off(monkeypatch, capsys):
    """A mistyped opt-in must not force-select Mosaic kernels on a CPU
    box: warn once on stderr, resolve OFF."""
    monkeypatch.setattr(blend, "_PIPELINE_WARNED", set())
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpert")
    assert blend.fused_pipeline_mode() == "off"
    err = capsys.readouterr().err
    assert "interpert" in err
    assert blend.fused_pipeline_mode() == "off"
    assert capsys.readouterr().err == ""


def test_pipeline_outranks_per_leg_knobs(monkeypatch):
    """One knob flips the whole pipeline consistently: with the
    pipeline live, the gather and blend selectors report the pipeline's
    leg regardless of their own envs — a half-fused program (Pallas
    gather feeding an XLA scatter it was never measured against) must
    be unconstructible."""
    from chunkflow_tpu.ops import pallas_blend, pallas_gather

    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpret")
    monkeypatch.setenv("CHUNKFLOW_GATHER", "off")
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "0")
    assert pallas_gather.gather_mode() == "interpret"
    assert pallas_blend.pallas_mode() == "interpret"
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "on")
    assert pallas_gather.gather_mode() == "pallas"
    assert pallas_blend.pallas_mode() == "on"
    monkeypatch.delenv("CHUNKFLOW_FUSED_PIPELINE", raising=False)
    assert pallas_gather.gather_mode() == "host"
    assert pallas_blend.pallas_mode() == "off"


def test_pipeline_kernel_cost_composes_the_builders(monkeypatch):
    """The analytic pipeline cost is the two stage models composed:
    VMEM is the max stage footprint (sequential stages of ONE program),
    traffic and FLOPs sum, and hbm_intermediate_bytes is write+read of
    both inter-stage stacks — the exact bytes the separate-programs
    composition pays and the pipeline deletes."""
    from chunkflow_tpu.ops import pallas_blend, pallas_gather

    B, ci, co, pin, pout = 8, 1, 3, (4, 32, 64), (4, 32, 64)
    gather = pallas_gather.gather_kernel_cost(B, ci, pin, "uint8")
    fused = pallas_blend.fused_kernel_cost(B, co, pout)
    pipe = blend.pipeline_kernel_cost(B, ci, co, pin, pout, "uint8")
    assert pipe["vmem_bytes"] == max(gather["vmem_bytes"],
                                     fused["vmem_bytes"])
    assert pipe["flops"] == gather["flops"] + fused["flops"]
    assert pipe["bytes_accessed"] == (gather["bytes_accessed"]
                                      + fused["bytes_accessed"])
    pvox = int(np.prod(pin))
    assert pipe["hbm_intermediate_bytes"] == 2 * (
        B * ci * pvox * 4 + B * co * pvox * 4)


# ---------------------------------------------------------------------------
# the f32 bitwise parity matrix
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("traffic", ["plain", "ragged", "uint8"])
def test_pipeline_parity_single_device(id_engine, traffic, monkeypatch):
    """interpret pipeline == default separate-programs path, bitwise,
    on plain/ragged/uint8 traffic (f32 contract: the pipeline is a
    restructuring, not a re-rounding)."""
    chunk = _traffic(traffic)
    monkeypatch.delenv("CHUNKFLOW_FUSED_PIPELINE", raising=False)
    ref = np.asarray(_inferencer(id_engine)(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpret")
    got = np.asarray(_inferencer(id_engine)(chunk).array)
    assert got.dtype == ref.dtype and got.shape == ref.shape
    assert np.array_equal(got, ref)


def test_pipeline_parity_crop_margin(crop_engine, monkeypatch):
    """Bitwise through the crop-margin path (pout < pin, real margin
    crop after the blend)."""
    chunk = _traffic("ragged")
    monkeypatch.delenv("CHUNKFLOW_FUSED_PIPELINE", raising=False)
    ref = np.asarray(_inferencer(crop_engine, crop=True)(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpret")
    got = np.asarray(_inferencer(crop_engine, crop=True)(chunk).array)
    assert np.array_equal(got, ref)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 virtual devices (tests/conftest.py)")
@pytest.mark.parametrize("traffic", ["plain", "ragged", "uint8"])
@pytest.mark.parametrize("mesh", ["data=2", "y=2,x=2"])
def test_pipeline_parity_mesh(id_engine, mesh, traffic, monkeypatch):
    """The pipeline composes with the unified mesh engine bitwise: both
    kernel legs run inside each chip's shard program (the pipeline tag
    is part of the shard key), and mesh x pipeline equals the plain
    single-device default on every traffic kind."""
    chunk = _traffic(traffic)
    monkeypatch.delenv("CHUNKFLOW_FUSED_PIPELINE", raising=False)
    ref = np.asarray(_inferencer(id_engine)(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpret")
    got = np.asarray(_inferencer(id_engine, mesh=mesh)(chunk).array)
    assert np.array_equal(got, ref)


@pytest.mark.parametrize("dtype", ["float32", "uint8"])
def test_pipeline_parity_packed_serve(id_engine, dtype, monkeypatch):
    """Packed serve with the pipeline live (device-resident weighted
    stacks, donated overlay writeback) equals the per-chunk DEFAULT
    path bitwise — the strongest serving contract: restructured
    batching AND restructured memory residency change nothing."""
    from chunkflow_tpu.serve.packer import PatchPacker

    rng = np.random.default_rng(3)
    if dtype == "uint8":
        chunks = [
            Chunk((rng.random((4, 16, 48)) * 255).astype(np.uint8),
                  voxel_offset=(8 * i, 0, 0))
            for i in range(3)
        ]
    else:
        chunks = [
            Chunk(rng.random((4, 16, 48), dtype=np.float32),
                  voxel_offset=(8 * i, 0, 0))
            for i in range(3)
        ]
    monkeypatch.delenv("CHUNKFLOW_FUSED_PIPELINE", raising=False)
    ref_inf = Inferencer(
        input_patch_size=PIN, num_output_channels=3,
        framework="prebuilt", engine=id_engine, batch_size=4,
        crop_output_margin=False,
    )
    refs = [np.asarray(ref_inf(c).array) for c in chunks]
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpret")
    inf = Inferencer(
        input_patch_size=PIN, num_output_channels=3,
        framework="prebuilt", engine=id_engine, batch_size=4,
        crop_output_margin=False,
    )
    packer = PatchPacker(inf, max_wait_ms=2.0)
    try:
        handles = [packer.submit(c) for c in chunks]
        outs = [np.asarray(h.result(timeout=60).array) for h in handles]
    finally:
        packer.close()
    for ref, out in zip(refs, outs):
        assert np.array_equal(out, ref)


def test_pipeline_interpret_runs_sanitized(id_engine, monkeypatch):
    """The interpret leg IS a kernelcheck run: both kernels record
    checks and zero violations on clean traffic — every pipeline parity
    test above doubles as a kernel soundness run (docs/linting.md)."""
    from chunkflow_tpu.testing import kernelcheck

    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    monkeypatch.setenv("CHUNKFLOW_FUSED_PIPELINE", "interpret")
    kernelcheck.reset_state()
    _inferencer(id_engine)(_traffic("plain"))
    snap = kernelcheck.report()
    assert snap["checks"] > 0, snap
    assert snap["violations"] == [], snap
