"""Quantization-error gates for the CHUNKFLOW_PRECISION forward variants
(ISSUE 14): bf16/int8 output error against the float32 reference stays
under stated bounds on both the identity and conv engines (incl. ragged
and crop-margin traffic), float32 stays bitwise untouched, and the
packed-serve / mesh parity contracts survive at every precision."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.inference import engines
from chunkflow_tpu.inference.inferencer import Inferencer
from chunkflow_tpu.inference.precision import (
    PRECISIONS,
    resolve_precision,
    wrap_apply,
)

PIN = (4, 16, 16)
OVERLAP = (2, 8, 8)

# Stated error bounds (max abs error of normalized [0,1]-scale outputs
# vs the float32 reference; measured headroom ~2-3x on both engines):
# bf16 rounds params+activations to 8 mantissa bits; int8 is symmetric
# per-tensor W8A8 fake quantization on a 255-level grid.
MAX_ABS_ERR = {"bfloat16": 0.02, "int8": 0.05}
MEAN_ERR = {"bfloat16": 0.005, "int8": 0.01}


# ---------------------------------------------------------------------------
# spec resolution
# ---------------------------------------------------------------------------
def test_resolve_precision_defaults_and_aliases(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_PRECISION", raising=False)
    assert resolve_precision() == "float32"
    assert resolve_precision("bf16") == "bfloat16"
    assert resolve_precision("FP32") == "float32"
    assert resolve_precision("i8") == "int8"
    for p in PRECISIONS:
        assert resolve_precision(p) == p
    monkeypatch.setenv("CHUNKFLOW_PRECISION", "int8")
    assert resolve_precision() == "int8"


def test_resolve_precision_explicit_is_strict():
    with pytest.raises(ValueError, match="precision"):
        resolve_precision("float16")


def test_resolve_precision_env_typo_warns_once(monkeypatch, capsys):
    """A mistyped CHUNKFLOW_PRECISION must not silently select a
    quantized path: one stderr warning, float32 fallback."""
    from chunkflow_tpu.inference import precision as precision_mod

    monkeypatch.setattr(precision_mod, "_WARNED_VALUES", set())
    monkeypatch.setenv("CHUNKFLOW_PRECISION", "bfloat61")
    assert resolve_precision() == "float32"
    err = capsys.readouterr().err
    assert "bfloat61" in err and "not a recognized value" in err
    assert resolve_precision() == "float32"
    assert capsys.readouterr().err == ""


def test_float32_wrap_is_identity_object():
    """The float32 default returns the engine apply ITSELF — the
    bitwise guarantee of the default path is structural, not numeric."""
    def apply(params, batch):
        return batch

    assert wrap_apply(apply, "float32") is apply
    assert wrap_apply(apply, "bfloat16") is not apply


# ---------------------------------------------------------------------------
# the quantization-error suite
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def conv_engine():
    return engines.create_flax_engine(
        "", None, PIN, num_input_channels=1, num_output_channels=3,
    )


@pytest.fixture(scope="module")
def id_engine():
    return engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=PIN,
        num_input_channels=1, num_output_channels=3,
    )


@pytest.fixture(scope="module")
def crop_engine():
    return engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=(2, 8, 8),
        num_input_channels=1, num_output_channels=3,
    )


def _inferencer(engine, precision, crop=False, **kw):
    if crop:
        return Inferencer(
            input_patch_size=PIN,
            output_patch_size=(2, 8, 8),
            output_patch_overlap=(1, 4, 4),
            num_output_channels=3,
            framework="prebuilt",
            batch_size=2,
            engine=engine,
            precision=precision,
            crop_output_margin=True,
            **kw,
        )
    return Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=3,
        framework="prebuilt",
        batch_size=2,
        engine=engine,
        precision=precision,
        crop_output_margin=False,
        **kw,
    )


def _traffic(kind: str):
    rng = np.random.default_rng(17)
    if kind == "ragged":
        # non-divisible extents: edge snapping, batch padding rows
        return Chunk(rng.random((6, 37, 45)).astype(np.float32))
    return Chunk(rng.random((8, 40, 48)).astype(np.float32))


@pytest.mark.parametrize("precision", ["bfloat16", "int8"])
@pytest.mark.parametrize("engine_kind", ["identity", "conv"])
@pytest.mark.parametrize("traffic", ["plain", "ragged"])
def test_quantization_error_bounds(id_engine, conv_engine, engine_kind,
                                   precision, traffic):
    """bf16/int8 outputs stay within the stated error bounds of the
    float32 reference — the gate narrow variants must pass to land
    (ISSUE 14 acceptance: no unmeasured path ships as default)."""
    engine = id_engine if engine_kind == "identity" else conv_engine
    chunk = _traffic(traffic)
    ref = np.asarray(_inferencer(engine, "float32")(chunk).array)
    got = np.asarray(_inferencer(engine, precision)(chunk).array)
    assert got.dtype == ref.dtype and got.shape == ref.shape
    err = np.abs(got.astype(np.float64) - ref.astype(np.float64))
    scale = max(float(np.abs(ref).max()), 1.0)
    assert err.max() <= MAX_ABS_ERR[precision] * scale, (
        f"{engine_kind}/{precision}/{traffic}: max abs err "
        f"{err.max():.5f} exceeds {MAX_ABS_ERR[precision]}")
    assert err.mean() <= MEAN_ERR[precision] * scale, (
        f"{engine_kind}/{precision}/{traffic}: mean err "
        f"{err.mean():.5f} exceeds {MEAN_ERR[precision]}")
    # a narrow variant that changes NOTHING would be a wiring bug
    assert err.max() > 0.0


@pytest.mark.parametrize("precision", ["bfloat16", "int8"])
def test_quantization_error_crop_margin(crop_engine, precision):
    """The bounds hold through the crop-margin path (pout < pin, real
    (1, 4, 4) margin crop after the blend)."""
    chunk = _traffic("ragged")
    ref = np.asarray(_inferencer(crop_engine, "float32",
                                 crop=True)(chunk).array)
    got = np.asarray(_inferencer(crop_engine, precision,
                                 crop=True)(chunk).array)
    err = np.abs(got.astype(np.float64) - ref.astype(np.float64))
    assert err.max() <= MAX_ABS_ERR[precision]
    assert err.max() > 0.0


def test_uint8_quantization_contract_survives(id_engine):
    """The normalize_blend uint8 contract is unchanged: a narrow forward
    moves the uint8 result by at most one quantization level on the
    identity oracle (err*255 < 1 at the stated bf16 bound)."""
    chunk = _traffic("plain")
    ref = np.asarray(_inferencer(id_engine, "float32",
                                 output_dtype="uint8")(chunk).array)
    got = np.asarray(_inferencer(id_engine, "bfloat16",
                                 output_dtype="uint8")(chunk).array)
    assert got.dtype == np.uint8 == ref.dtype
    assert np.abs(got.astype(np.int32) - ref.astype(np.int32)).max() <= 1


def test_float32_default_bitwise_untouched(id_engine, monkeypatch):
    """Explicit float32, env-default float32 and no-spec construction
    are the SAME path bitwise (and structurally: engine.apply itself)."""
    monkeypatch.delenv("CHUNKFLOW_PRECISION", raising=False)
    chunk = _traffic("ragged")
    default = _inferencer(id_engine, None)
    assert default.precision == "float32"
    assert default._apply is id_engine.apply
    ref = np.asarray(default(chunk).array)
    explicit = np.asarray(_inferencer(id_engine, "float32")(chunk).array)
    assert np.array_equal(ref, explicit)
    monkeypatch.setenv("CHUNKFLOW_PRECISION", "bfloat16")
    via_env = _inferencer(id_engine, None)
    assert via_env.precision == "bfloat16"


# ---------------------------------------------------------------------------
# parity contracts survive at every precision
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("precision", ["bfloat16", "int8"])
def test_packed_serve_parity_survives_precision(id_engine, precision):
    """Packed-vs-per-chunk bitwise identity holds AT EVERY precision:
    the packer inherits the same wrapped forward through _forward, so
    quantization cannot diverge the two paths."""
    from chunkflow_tpu.serve.packer import PatchPacker

    rng = np.random.default_rng(3)
    chunks = [
        Chunk(rng.random((4, 16, 48), dtype=np.float32),
              voxel_offset=(8 * i, 0, 0))
        for i in range(3)
    ]
    inf = Inferencer(
        input_patch_size=PIN,
        num_output_channels=3,
        framework="prebuilt",
        engine=id_engine,
        batch_size=4,
        precision=precision,
        crop_output_margin=False,
    )
    refs = [np.asarray(inf(c).array) for c in chunks]
    packer = PatchPacker(inf, max_wait_ms=2.0)
    try:
        handles = [packer.submit(c) for c in chunks]
        outs = [np.asarray(h.result(timeout=60).array) for h in handles]
    finally:
        packer.close()
    for ref, out in zip(refs, outs):
        assert np.array_equal(out, ref)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 virtual devices (tests/conftest.py)")
@pytest.mark.parametrize("precision", ["bfloat16", "int8"])
def test_mesh_parity_survives_precision(id_engine, precision):
    """Mesh-vs-single bitwise identity holds AT EVERY precision: the
    sharded engine shards the same wrapped forward and replays the same
    accumulation."""
    chunk = _traffic("ragged")
    ref = np.asarray(_inferencer(id_engine, precision)(chunk).array)
    out = np.asarray(
        _inferencer(id_engine, precision, mesh="data=2")(chunk).array)
    assert np.array_equal(out, ref)


def test_precision_composes_with_fused_kernel(id_engine, monkeypatch):
    """bf16 forward + fused Pallas blend (interpret) equals bf16 forward
    + XLA scatter bitwise — precision quantizes the forward, the
    accumulation stays float32 either way."""
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "0")
    chunk = _traffic("ragged")
    ref = np.asarray(_inferencer(id_engine, "bfloat16")(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "interpret")
    got = np.asarray(_inferencer(id_engine, "bfloat16")(chunk).array)
    assert np.array_equal(got, ref)


# ---------------------------------------------------------------------------
# the real int8 leg (CHUNKFLOW_INT8, ISSUE 17)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("engine_kind", ["identity", "conv"])
def test_int8_real_vs_fakeint_bitwise(id_engine, conv_engine,
                                      engine_kind, monkeypatch):
    """The agreement oracle: the real integer-accumulating leg and its
    exact-f32 twin (``fakeint``) quantize onto IDENTICAL integer grids
    and dequantize with one shared expression, so their outputs are
    BITWISE equal wherever the int32 sums stay below 2^24 — true by
    construction for [0,1) activations at these patch sizes. Any
    divergence means the two legs' grids or dequant orders drifted."""
    engine = id_engine if engine_kind == "identity" else conv_engine
    chunk = _traffic("ragged")
    monkeypatch.setenv("CHUNKFLOW_INT8", "real")
    real = np.asarray(_inferencer(engine, "int8")(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_INT8", "fakeint")
    twin = np.asarray(_inferencer(engine, "int8")(chunk).array)
    assert np.array_equal(real, twin)


def test_int8_real_runs_int32_matmuls(conv_engine, monkeypatch):
    """The real leg is REAL: tracing the wrapped forward shows int8
    operands feeding ``preferred_element_type=int32`` matmuls (the MXU
    integer op), while the fake leg's jaxpr carries no int8 compute at
    all — the acceptance probe that fake-quant emulation did not ship
    under the ``real`` name."""
    from chunkflow_tpu.inference import precision as precision_mod

    batch = np.linspace(
        0.0, 1.0, int(np.prod((2, 1) + PIN)), dtype=np.float32,
    ).reshape((2, 1) + PIN)
    monkeypatch.setenv("CHUNKFLOW_INT8", "real")
    monkeypatch.setattr(precision_mod, "_INT8_WARNED", set())
    wrapped = wrap_apply(conv_engine.apply, "int8")
    text = str(jax.make_jaxpr(wrapped)(conv_engine.params, batch))
    assert "preferred_element_type=int32" in text, text[-2000:]
    assert "i8[" in text, text[-2000:]  # jaxpr spelling of int8 operands
    monkeypatch.setenv("CHUNKFLOW_INT8", "fake")
    fake = wrap_apply(conv_engine.apply, "int8")
    fake_text = str(jax.make_jaxpr(fake)(conv_engine.params, batch))
    assert "i8[" not in fake_text
    assert "preferred_element_type=int32" not in fake_text


@pytest.mark.parametrize("engine_kind", ["identity", "conv"])
@pytest.mark.parametrize("traffic", ["plain", "ragged"])
def test_int8_real_error_bounds(id_engine, conv_engine, engine_kind,
                                traffic, monkeypatch):
    """The real integer leg obeys the SAME stated int8 bounds as the
    fake-quant reference (ISSUE 17 acceptance: real int8 lands inside
    the established gates, no new error budget). The interception is
    taint-targeted — matmuls/convs touched by activation data — so the
    conv engine must actually move (err > 0) while the matmul-free
    identity engine passes through EXACTLY (the real leg quantizes
    compute, not boundaries)."""
    engine = id_engine if engine_kind == "identity" else conv_engine
    chunk = _traffic(traffic)
    ref = np.asarray(_inferencer(engine, "float32")(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_INT8", "real")
    got = np.asarray(_inferencer(engine, "int8")(chunk).array)
    err = np.abs(got.astype(np.float64) - ref.astype(np.float64))
    scale = max(float(np.abs(ref).max()), 1.0)
    assert err.max() <= MAX_ABS_ERR["int8"] * scale, err.max()
    assert err.mean() <= MEAN_ERR["int8"] * scale, err.mean()
    if engine_kind == "conv":
        assert err.max() > 0.0
    else:
        assert err.max() == 0.0


def test_packed_serve_parity_survives_real_int8(id_engine, monkeypatch):
    """Packed-vs-per-chunk bitwise identity holds with the real integer
    matmul leg live — the packer inherits the same wrapped forward, so
    the int8 grid cannot diverge the two serving paths."""
    from chunkflow_tpu.serve.packer import PatchPacker

    monkeypatch.setenv("CHUNKFLOW_INT8", "real")
    rng = np.random.default_rng(3)
    chunks = [
        Chunk(rng.random((4, 16, 48), dtype=np.float32),
              voxel_offset=(8 * i, 0, 0))
        for i in range(3)
    ]
    inf = Inferencer(
        input_patch_size=PIN,
        num_output_channels=3,
        framework="prebuilt",
        engine=id_engine,
        batch_size=4,
        precision="int8",
        crop_output_margin=False,
    )
    refs = [np.asarray(inf(c).array) for c in chunks]
    packer = PatchPacker(inf, max_wait_ms=2.0)
    try:
        handles = [packer.submit(c) for c in chunks]
        outs = [np.asarray(h.result(timeout=60).array) for h in handles]
    finally:
        packer.close()
    for ref, out in zip(refs, outs):
        assert np.array_equal(out, ref)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 virtual devices (tests/conftest.py)")
def test_mesh_parity_survives_real_int8(id_engine, monkeypatch):
    """Mesh-vs-single bitwise identity holds with the real integer
    matmul leg live — the sharded engine shards the same wrapped
    forward."""
    monkeypatch.setenv("CHUNKFLOW_INT8", "real")
    chunk = _traffic("ragged")
    ref = np.asarray(_inferencer(id_engine, "int8")(chunk).array)
    out = np.asarray(
        _inferencer(id_engine, "int8", mesh="data=2")(chunk).array)
    assert np.array_equal(out, ref)


def test_int8_real_composes_with_kernels_clean(id_engine, monkeypatch):
    """Real int8 forward + both interpret Pallas kernels + kernelcheck:
    the composition matches the XLA path bitwise AND the sanitizer
    records checks but zero violations — the clean pin that the int8
    rewrite did not perturb the kernels' soundness contracts."""
    from chunkflow_tpu.testing import kernelcheck

    monkeypatch.setenv("CHUNKFLOW_INT8", "real")
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "0")
    monkeypatch.setenv("CHUNKFLOW_GATHER", "on")
    chunk = _traffic("ragged")
    ref = np.asarray(_inferencer(id_engine, "int8")(chunk).array)
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "interpret")
    monkeypatch.setenv("CHUNKFLOW_GATHER", "interpret")
    kernelcheck.reset_state()
    got = np.asarray(_inferencer(id_engine, "int8")(chunk).array)
    snap = kernelcheck.report()
    assert np.array_equal(got, ref)
    assert snap["checks"] > 0, snap
    assert snap["violations"] == [], snap
