"""The identity oracle: identity-net through the whole fused blend path
(patch gather -> forward -> bump multiply -> scatter-add -> reciprocal
normalization) must reproduce the input exactly (up to float32).

Mirrors reference tests/flow/divid_conquer/test_inferencer.py, including the
non-aligned chunk case, plus paths the reference cannot test exactly (edges
are exact here because the weight mask normalizes the whole chunk).
"""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.inference import Inferencer


def _random_chunk(size, offset=(0, 0, 0), seed=0):
    rng = np.random.default_rng(seed)
    return Chunk(
        rng.random(size).astype(np.float32),
        voxel_offset=offset,
        voxel_size=(1, 1, 1),
    )


def _assert_identity(out, chunk, margin):
    expected = chunk.crop_margin(margin) if any(margin) else chunk
    assert out.voxel_offset == expected.voxel_offset
    assert out.shape[-3:] == expected.shape[-3:]
    got = np.asarray(out.array)
    if got.ndim == 4:
        got = got[0]
    np.testing.assert_allclose(
        got, np.asarray(expected.array), rtol=1e-4, atol=1e-5
    )


def test_identity_aligned_no_margin():
    chunk = _random_chunk((32, 32, 32))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_overlap=(8, 8, 8),
        framework="identity",
    )
    out = inferencer(chunk)
    _assert_identity(out, chunk, (0, 0, 0))


def test_identity_with_crop_margin():
    chunk = _random_chunk((32, 32, 32), offset=(10, 20, 30))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_size=(12, 12, 12),
        output_patch_overlap=(4, 4, 4),
        framework="identity",
    )
    out = inferencer(chunk)
    _assert_identity(out, chunk, (2, 2, 2))


def test_identity_nonaligned_chunk():
    # 30x44x50 does not tile with 16-patches at stride 8: edge snapping +
    # weight normalization must still give exact reconstruction
    chunk = _random_chunk((30, 44, 50))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_overlap=(8, 8, 8),
        framework="identity",
    )
    out = inferencer(chunk)
    _assert_identity(out, chunk, (0, 0, 0))


def test_identity_batched():
    chunk = _random_chunk((32, 32, 32))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_overlap=(8, 8, 8),
        framework="identity",
        batch_size=5,  # 27 patches pad to 30
    )
    out = inferencer(chunk)
    _assert_identity(out, chunk, (0, 0, 0))


def test_identity_multichannel_output():
    chunk = _random_chunk((24, 24, 24))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_overlap=(8, 8, 8),
        num_output_channels=3,
        framework="identity",
    )
    out = inferencer(chunk)
    assert out.shape[0] == 3
    assert out.is_affinity_map
    for c in range(3):
        np.testing.assert_allclose(
            np.asarray(out.array)[c],
            np.asarray(chunk.array),
            rtol=1e-4,
            atol=1e-5,
        )


def test_identity_tta():
    chunk = _random_chunk((24, 24, 24))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_overlap=(8, 8, 8),
        framework="identity",
        augment=True,
    )
    out = inferencer(chunk)
    # identity is equivariant to flips/transpose, so TTA is still identity
    _assert_identity(out, chunk, (0, 0, 0))


def test_uint8_input_normalized():
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.integers(0, 255, (24, 24, 24)).astype(np.uint8))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_overlap=(8, 8, 8),
        framework="identity",
    )
    out = inferencer(chunk)
    got = np.asarray(out.array)
    expected = np.asarray(chunk.array).astype(np.float32) / 255.0
    np.testing.assert_allclose(got.squeeze(), expected, rtol=1e-4, atol=1e-5)


def test_all_zero_short_circuit():
    chunk = Chunk(np.zeros((24, 24, 24), dtype=np.float32))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        output_patch_size=(12, 12, 12),
        framework="identity",
    )
    out = inferencer(chunk)
    assert out.all_zero()
    assert out.shape[-3:] == (20, 20, 20)
    assert out.voxel_offset == Cartesian(2, 2, 2)


def test_dry_run():
    chunk = _random_chunk((24, 24, 24))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16),
        framework="identity",
        dry_run=True,
    )
    out = inferencer(chunk)
    assert out.shape[-3:] == (24, 24, 24)
    assert out.all_zero()


def test_patch_larger_than_chunk_raises():
    chunk = _random_chunk((8, 8, 8))
    inferencer = Inferencer(
        input_patch_size=(16, 16, 16), framework="identity"
    )
    with pytest.raises(ValueError):
        inferencer(chunk)


def test_tta_requires_square_patches():
    with pytest.raises(ValueError):
        Inferencer(
            input_patch_size=(16, 32, 16), framework="identity", augment=True
        )


def test_prebuilt_engine():
    """framework='prebuilt' reuses a caller-constructed Engine (reference
    inferencer.py:209-211)."""
    from chunkflow_tpu.inference import engines
    from chunkflow_tpu.inference.inferencer import Inferencer
    from chunkflow_tpu.chunk.base import Chunk

    patch = (4, 16, 16)
    eng = engines.create_identity_engine(
        input_patch_size=patch, output_patch_size=patch,
        num_input_channels=1, num_output_channels=1,
    )
    inferencer = Inferencer(
        input_patch_size=patch,
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="prebuilt",
        engine=eng,
        batch_size=1,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    out = inferencer(chunk)
    np.testing.assert_allclose(
        np.asarray(out.array)[0], np.asarray(chunk.array), atol=1e-5
    )


@pytest.mark.parametrize("sharding", ["patch", "spatial"])
def test_inferencer_sharded_modes_match_single_device(sharding):
    """--sharding patch/spatial produce the single-device result on the
    8-device virtual mesh (identity oracle)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 128, 32)).astype(np.float32))

    def run(mode):
        inferencer = Inferencer(
            input_patch_size=(4, 16, 16),
            output_patch_overlap=(2, 8, 8),
            num_output_channels=1,
            framework="identity",
            batch_size=2,
            sharding=mode,
            crop_output_margin=False,
        )
        return np.asarray(inferencer(chunk.clone()).array)

    result = run(sharding)
    np.testing.assert_allclose(result, run("none"), atol=1e-5)
    np.testing.assert_allclose(result[0], np.asarray(chunk.array), atol=1e-5)


def test_padded_context_is_edge_replicated():
    """Bucket and fold padding feed the net EDGE-REPLICATED boundary
    context (the uniform-grid analog of the reference's edge-snapped
    patch starts), not a zero wall: a patch-mean engine over an all-ones
    ragged chunk must return exactly 1.0 everywhere — zero padding would
    drag every edge patch's mean (and the blended voxels it touches)
    below 1. The identity oracle cannot see pad mode; this engine can."""
    import jax.numpy as jnp

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.engines import Engine
    from chunkflow_tpu.inference.inferencer import Inferencer

    pin = (4, 16, 16)

    def apply(params, batch):
        m = batch.mean(axis=(1, 2, 3, 4), keepdims=True)
        return jnp.broadcast_to(m, (batch.shape[0], 1) + pin)

    eng = Engine(params=(), apply=apply,
                 num_input_channels=1, num_output_channels=1)
    for kwargs in ({"shape_bucket": (8, 32, 32)}, {"blend": "fold"}):
        inferencer = Inferencer(
            input_patch_size=pin,
            output_patch_overlap=(2, 8, 8),
            num_output_channels=1,
            framework="identity",
            engine=eng,
            batch_size=2,
            crop_output_margin=False,
            **kwargs,
        )
        out = np.asarray(
            inferencer(Chunk(np.ones((7, 30, 30), np.float32))).array
        )
        assert out.shape[-3:] == (7, 30, 30)
        np.testing.assert_allclose(out, 1.0, atol=1e-6, err_msg=str(kwargs))


def test_shape_bucketing_identity_oracle_and_program_reuse():
    """With --shape-bucket, ragged chunks pad up to the bucket quantum and
    reuse ONE compiled program; the identity oracle still holds exactly
    (identity forward copies voxels, so the PAD REGION cannot leak in;
    pad-mode sensitivity is covered by
    test_padded_context_is_edge_replicated)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
        shape_bucket=(8, 16, 16),
    )
    # the asserted grid follows the bucketed shape, not the ragged one
    assert inferencer.patch_grid_shape((5, 17, 18)) == \
        inferencer.patch_grid_shape((8, 32, 32))
    import pytest as _pytest

    with _pytest.raises(ValueError):
        Inferencer(
            input_patch_size=(4, 16, 16), framework="identity",
            shape_bucket=(0, 16, 16),
        )
    rng = np.random.default_rng(7)
    shapes = [(5, 17, 18), (7, 30, 20), (8, 32, 32)]
    for shape in shapes:
        chunk = rng.random(shape).astype(np.float32)
        out = np.asarray(inferencer(Chunk(chunk)).array)
        assert out.shape[-3:] == shape
        np.testing.assert_allclose(out[0], chunk, atol=1e-5)
    # (5,17,18) and (7,30,20) both bucket to (8,32,32): one program for all
    sizes = inferencer._program._cache_size()
    assert sizes == 1, f"expected one compiled program, got {sizes}"


def test_stream_pipelined_matches_sequential_calls():
    """stream() yields the same outputs as one __call__ per chunk, in
    order, with host-resident payloads (the D2H overlap must not reorder
    or corrupt results)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(11)
    chunks = [
        Chunk(rng.random((8, 32, 32)).astype(np.float32),
              voxel_offset=(i * 8, 0, 0))
        for i in range(3)
    ]
    streamed = list(inferencer.stream(iter(chunks)))
    assert len(streamed) == 3
    for src, out in zip(chunks, streamed):
        assert not out.is_on_device
        assert tuple(out.voxel_offset) == tuple(src.voxel_offset)
        ref = np.asarray(inferencer(src).array)
        np.testing.assert_allclose(np.asarray(out.array), ref, atol=1e-6)


def test_stream_postprocess_overlaps_and_preserves_order():
    """stream(postprocess=...) runs the host stage in a worker thread
    while the next chunk's program is in flight (VERDICT r4 #3): results
    arrive in order, each produced off the dispatch thread, and wall
    clock beats the strictly-sequential sum of load + post stages."""
    import threading
    import time

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(13)
    chunks = [
        Chunk(rng.random((8, 32, 32)).astype(np.float32),
              voxel_offset=(i * 8, 0, 0))
        for i in range(4)
    ]
    load_s = post_s = 0.15
    main_thread = threading.get_ident()
    post_threads = []

    def slow_loader():
        for c in chunks:
            time.sleep(load_s)  # simulated volume cutout
            yield c

    def postprocess(out):
        post_threads.append(threading.get_ident())
        time.sleep(post_s)  # simulated watershed/agglomeration
        return (tuple(out.voxel_offset), np.asarray(out.array)[0].copy())

    # warm the compiled program so compile time doesn't mask the overlap
    inferencer(chunks[0])
    t0 = time.perf_counter()
    results = list(inferencer.stream(slow_loader(), postprocess=postprocess))
    elapsed = time.perf_counter() - t0

    assert [r[0] for r in results] == [
        tuple(c.voxel_offset) for c in chunks
    ]
    for (_, arr), src in zip(results, chunks):
        np.testing.assert_allclose(arr, np.asarray(src.array), atol=1e-6)
    assert all(t != main_thread for t in post_threads)
    sequential_floor = len(chunks) * (load_s + post_s)
    assert elapsed < sequential_floor * 0.9, (
        f"no overlap: {elapsed:.2f}s vs sequential {sequential_floor:.2f}s"
    )


def test_stream_postprocess_propagates_errors():
    """an exception inside the worker-thread postprocess surfaces to the
    caller instead of being swallowed by the executor."""
    import pytest

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(5)
    chunks = [Chunk(rng.random((8, 32, 32)).astype(np.float32))
              for _ in range(2)]

    def explode(out):
        raise RuntimeError("post stage failed")

    with pytest.raises(RuntimeError, match="post stage failed"):
        list(inferencer.stream(iter(chunks), postprocess=explode))


def test_stream_empty_and_single():
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    assert list(inferencer.stream(iter([]))) == []
    rng = np.random.default_rng(3)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    (out,) = list(inferencer.stream(iter([chunk])))
    np.testing.assert_allclose(
        np.asarray(out.array)[0], np.asarray(chunk.array), atol=1e-6)


@pytest.mark.parametrize("sharding", ["none", "patch", "spatial", "spatial2d"])
def test_output_dtype_bfloat16_all_sharding_modes(sharding):
    """output_dtype=bfloat16 is fused into every program (single-device
    and sharded): result dtype is bf16 and the identity oracle holds at
    bf16 tolerance."""
    import jax
    import jax.numpy as jnp

    if sharding != "none" and len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 128, 32)).astype(np.float32))
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        sharding=sharding,
        output_dtype="bfloat16",
        crop_output_margin=False,
    )
    out = inferencer(chunk.clone())
    assert out.array.dtype == jnp.bfloat16, out.array.dtype
    np.testing.assert_allclose(
        np.asarray(out.array, dtype=np.float32)[0],
        np.asarray(chunk.array), atol=0.01,
    )


@pytest.mark.parametrize("blend", ["scatter", "fold"])
def test_output_dtype_uint8_reference_quantization(blend):
    """output_dtype=uint8 quantizes on device exactly like the
    reference's save-time conversion: truncating (x*255).astype(uint8)
    (reference save_precomputed.py:90-92)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        blend=blend,
        output_dtype="uint8",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(9)
    chunk = rng.random((8, 32, 32)).astype(np.float32)
    out = np.asarray(inferencer(Chunk(chunk)).array)
    assert out.dtype == np.uint8
    want = (np.clip(chunk, 0, 1) * 255.0).astype(np.uint8)
    # blend round-trip can move a value across a truncation boundary;
    # allow 1 count of slack
    assert np.abs(out[0].astype(np.int16) - want.astype(np.int16)).max() <= 1

    with pytest.raises(ValueError, match="myelin"):
        Inferencer(
            input_patch_size=(4, 16, 16),
            framework="identity",
            output_dtype="uint8",
            mask_myelin_threshold=0.3,
        )


def test_stream_composes_with_sharding():
    """Pipelined stream() over a sharded program: results match the
    synchronous sharded call, order preserved (8-device mesh)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        sharding="patch",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(12)
    chunks = [
        Chunk(rng.random((8, 32, 32)).astype(np.float32)) for _ in range(3)
    ]
    streamed = list(inferencer.stream(iter(chunks)))
    for src, out in zip(chunks, streamed):
        np.testing.assert_allclose(
            np.asarray(out.array)[0], np.asarray(src.array), atol=1e-5)
