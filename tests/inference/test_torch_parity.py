"""End-to-end .pt migration parity: a torch twin of UNet3D -> converter ->
flax UNet3D must produce the same output (MSE well under the 1e-4 parity
target from BASELINE.md)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import jax
import jax.numpy as jnp

from chunkflow_tpu.models import unet3d
from chunkflow_tpu.models.converter import torch_to_flax

FEATS = (4, 6, 8)
DOWNS = ((1, 2, 2), (2, 2, 2))


class TorchConvBlock(torch.nn.Module):
    """Definition order mirrors execution order (the converter contract)."""

    def __init__(self, cin, cout):
        super().__init__()
        self.conv1 = torch.nn.Conv3d(cin, cout, 3, padding=1)
        self.norm1 = torch.nn.InstanceNorm3d(cout, eps=1e-5, affine=True)
        self.conv2 = torch.nn.Conv3d(cout, cout, 3, padding=1)
        self.norm2 = torch.nn.InstanceNorm3d(cout, eps=1e-5, affine=True)
        self.cin = cin
        self.cout = cout

    def forward(self, x):
        r = x
        x = torch.nn.functional.elu(self.norm1(self.conv1(x)))
        x = self.norm2(self.conv2(x))
        if self.cin == self.cout:
            x = x + r
        return torch.nn.functional.elu(x)


class TorchUNet(torch.nn.Module):
    def __init__(self, cin=1, cout=3):
        super().__init__()
        self.conv_in = torch.nn.Conv3d(cin, FEATS[0], (1, 5, 5), padding=(0, 2, 2))
        self.enc0 = TorchConvBlock(FEATS[0], FEATS[0])
        self.enc1 = TorchConvBlock(FEATS[0], FEATS[1])
        self.bridge = TorchConvBlock(FEATS[1], FEATS[2])
        self.up1 = torch.nn.ConvTranspose3d(FEATS[2], FEATS[1], DOWNS[1], stride=DOWNS[1])
        self.dec1 = TorchConvBlock(FEATS[1], FEATS[1])
        self.up0 = torch.nn.ConvTranspose3d(FEATS[1], FEATS[0], DOWNS[0], stride=DOWNS[0])
        self.dec0 = TorchConvBlock(FEATS[0], FEATS[0])
        self.conv_out = torch.nn.Conv3d(FEATS[0], cout, (1, 5, 5), padding=(0, 2, 2))

    def forward(self, x):
        x = self.conv_in(x)
        s0 = self.enc0(x)
        x = torch.nn.functional.max_pool3d(s0, DOWNS[0], stride=DOWNS[0])
        s1 = self.enc1(x)
        x = torch.nn.functional.max_pool3d(s1, DOWNS[1], stride=DOWNS[1])
        x = self.bridge(x)
        x = self.up1(x) + s1
        x = self.dec1(x)
        x = self.up0(x) + s0
        x = self.dec0(x)
        return torch.sigmoid(self.conv_out(x))


def test_torch_unet_to_flax_parity(tmp_path):
    tnet = TorchUNet().eval()
    path = str(tmp_path / "weights.pt")
    torch.save(tnet.state_dict(), path)

    fnet = unet3d.UNet3D(
        in_channels=1, out_channels=3,
        feature_maps=FEATS, down_factors=DOWNS,
    )
    params = unet3d.init_or_load_params(fnet, path, (4, 16, 16), 1)

    x = np.random.default_rng(0).random((2, 4, 16, 16, 1)).astype(np.float32)
    with torch.no_grad():
        expected = tnet(torch.from_numpy(np.moveaxis(x, -1, 1))).numpy()
    got = np.asarray(fnet.apply({"params": params}, jnp.asarray(x)))
    got = np.moveaxis(got, -1, 1)
    mse = float(np.mean((got - expected) ** 2))
    assert mse < 1e-8, f"torch->flax parity MSE {mse}"
