"""Compile-cache layer (core/compile_cache.py): ragged-edge chunks that
shape-bucket into the same run geometry must trigger exactly one trace,
and the keyed program cache must count builds/hits as invariants a test
can assert (not a benchmark)."""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.compile_cache import (
    ProgramCache,
    enable_persistent_cache,
)
from chunkflow_tpu.inference import Inferencer
from chunkflow_tpu.inference.engines import Engine, create_identity_engine


def test_program_cache_counts_and_eviction():
    cache = ProgramCache(maxsize=2)
    built = []

    def make(tag):
        def build():
            built.append(tag)
            return tag
        return build

    assert cache.get("a", make("a")) == "a"
    assert cache.get("a", make("a2")) == "a"  # hit: builder not invoked
    assert cache.get("b", make("b")) == "b"
    assert (cache.builds, cache.hits) == (2, 1)
    assert built == ["a", "b"]
    cache.get("c", make("c"))  # evicts "a" (FIFO)
    assert "a" not in cache and "b" in cache and "c" in cache
    assert cache.peek("a") is None
    with pytest.raises(ValueError):
        ProgramCache(maxsize=0)


def test_retrace_watchdog_warns_past_expected_builds():
    from chunkflow_tpu.core.compile_cache import RetraceWarning

    cache = ProgramCache(expected_builds=2, label="test")
    cache.get("a", lambda: "a")
    cache.get("b", lambda: "b")
    with pytest.warns(RetraceWarning, match="expected bucket count"):
        cache.get("c", lambda: "c")
    # once per cache: a warning per retrace would swamp the log
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error", RetraceWarning)
        cache.get("d", lambda: "d")


def test_cache_counters_feed_telemetry(monkeypatch):
    from chunkflow_tpu.core import telemetry

    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    cache = ProgramCache()
    cache.get("a", lambda: "a")
    cache.get("a", lambda: "a")
    cache.get("b", lambda: "b")
    counters = telemetry.snapshot()["counters"]
    assert counters["compile_cache/builds"] == 2
    assert counters["compile_cache/hits"] == 1
    # per-instance counters stay live even with telemetry off
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    cache.get("c", lambda: "c")
    assert cache.builds == 3
    telemetry.reset()


def _counting_engine(input_patch, num_output_channels):
    """Identity engine whose apply counts TRACES: the body runs under
    jit tracing only, so the counter advances once per program
    compilation and never on cached executions."""
    inner = create_identity_engine(
        input_patch, input_patch,
        num_output_channels=num_output_channels,
    )
    traces = []

    def apply(params, batch):
        traces.append(batch.shape)
        return inner.apply(params, batch)

    return Engine(
        params=(),
        apply=apply,
        num_input_channels=1,
        num_output_channels=num_output_channels,
    ), traces


@pytest.mark.parametrize("blend", ["scatter", "fold"])
def test_same_bucket_chunks_trace_once(blend):
    """Two ragged chunks in the same shape bucket run ONE compiled
    program: the second chunk is a pure cache hit (zero traces)."""
    engine, traces = _counting_engine((4, 16, 16), 1)
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="prebuilt",
        engine=engine,
        batch_size=2,
        shape_bucket=(8, 16, 16),
        blend=blend,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    first = rng.random((5, 17, 18)).astype(np.float32)
    np.asarray(inferencer(Chunk(first)).array)
    n_traces = len(traces)
    assert n_traces >= 1
    # same bucket (8, 32, 32): bit-for-bit program reuse, no retrace
    second = rng.random((7, 30, 20)).astype(np.float32)
    out = np.asarray(inferencer(Chunk(second)).array)
    assert len(traces) == n_traces, "same-bucket chunk retraced"
    np.testing.assert_allclose(out[0], second, atol=1e-5)
    # a different bucket is a genuine new geometry: exactly one more trace
    third = rng.random((8, 40, 40)).astype(np.float32)
    np.asarray(inferencer(Chunk(third)).array)
    assert len(traces) == 2 * n_traces


def test_fold_family_shares_program_cache():
    """The fold path keys per padded shape in the shared ProgramCache:
    three ragged shapes, one bucket, one build."""
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=1,
        framework="identity",
        batch_size=2,
        blend="fold",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(2)
    for shape in ((8, 30, 30), (7, 27, 32), (8, 32, 32)):
        np.asarray(inferencer(Chunk(rng.random(shape, dtype=np.float32))).array)
    assert inferencer._programs.builds == 1
    assert inferencer._programs.hits == 2


def test_persistent_cache_enable_idempotent(tmp_path, monkeypatch):
    target = str(tmp_path / "xla_cache")
    assert enable_persistent_cache(target) == target
    assert enable_persistent_cache(target) == target  # idempotent
    import jax

    assert jax.config.jax_compilation_cache_dir == target
    monkeypatch.setenv("CHUNKFLOW_JAX_CACHE", "0")
    assert enable_persistent_cache() is None  # env kill switch
