"""MXU conv lowering parity: MxuConv / MxuConvTranspose are pure lowering
changes — identical parameter trees and (up to float reassociation)
identical numerics to nn.Conv / nn.ConvTranspose. These tests pin that on
CPU so the on-chip fwd_tpu_mxu battery step is a pure speed A/B."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chunkflow_tpu.models import unet3d


def _tree_shapes(tree):
    return jax.tree_util.tree_map(lambda a: a.shape, tree)


@pytest.mark.parametrize("kernel", [(3, 3, 3), (1, 5, 5)])
def test_mxu_conv_matches_nn_conv(kernel):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((2, 5, 8, 8, 3), dtype=np.float32))
    native = unet3d._make_conv("native", 4, kernel, jnp.float32, "c")
    mxu = unet3d._make_conv("mxu", 4, kernel, jnp.float32, "c")
    params = native.init(jax.random.PRNGKey(0), x)
    # identical parameter trees: checkpoints interchange between lowerings
    assert _tree_shapes(params) == _tree_shapes(
        mxu.init(jax.random.PRNGKey(0), x)
    )
    ref = native.apply(params, x)
    got = mxu.apply(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("factor", [(1, 2, 2), (2, 2, 2)])
def test_mxu_convtranspose_matches_nn(factor):
    import flax.linen as nn

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.random((2, 4, 6, 6, 5), dtype=np.float32))
    native = nn.ConvTranspose(3, kernel_size=factor, strides=factor)
    mxu = unet3d.MxuConvTranspose(3, factor=factor)
    params = native.init(jax.random.PRNGKey(0), x)
    assert _tree_shapes(params) == _tree_shapes(
        mxu.init(jax.random.PRNGKey(0), x)
    )
    ref = native.apply(params, x)
    got = mxu.apply(params, x)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_mxu_conv_bf16_accumulates_in_f32():
    """ADVICE r3: in bf16 mode the kz partials must accumulate in f32
    (one rounding at the end, like native Conv3D) — not partial-by-partial
    bf16 rounding. Comparative assertion: the shipped lowering's error vs
    the f32 truth must be strictly below a partial-by-partial bf16
    accumulation's error computed in-test (no platform-sensitive magic
    constant), plus a sanity bound of a few bf16 ULPs."""
    from jax import lax

    rng = np.random.default_rng(3)
    x32 = jnp.asarray(rng.random((2, 5, 8, 8, 3), dtype=np.float32))
    native32 = unet3d._make_conv("native", 4, (3, 3, 3), jnp.float32, "c")
    params = native32.init(jax.random.PRNGKey(0), x32)
    truth = np.asarray(native32.apply(params, x32), np.float32)

    mxu16 = unet3d._make_conv("mxu", 4, (3, 3, 3), jnp.bfloat16, "c")
    got = np.asarray(mxu16.apply(params, x32), np.float32)
    scale = float(np.abs(truth).max())
    err_f32acc = float(np.abs(got - truth).max())

    # the regression being guarded: round each z-partial to bf16 and sum
    # in bf16 (what the lowering did before the ADVICE fix)
    kernel = np.asarray(params["params"]["kernel"], np.float32)
    bias = np.asarray(params["params"]["bias"], np.float32)
    x16 = np.asarray(x32, np.float32)
    b, d, h, w, cin = x16.shape
    xpad = np.pad(x16, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
    acc = None
    for dz in range(3):
        y = lax.conv_general_dilated(
            jnp.asarray(xpad[:, dz:dz + d], jnp.bfloat16).reshape(
                b * d, h, w, cin),
            jnp.asarray(kernel[dz], jnp.bfloat16),
            window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # bf16 output: per-partial rounding
        acc = y if acc is None else (acc + y)
    old = np.asarray(acc, np.float32).reshape(b, d, h, w, -1) + bias
    err_partial = float(np.abs(old - truth).max())

    assert err_f32acc < err_partial, (err_f32acc, err_partial)
    assert err_f32acc < 3 * scale / 256.0  # a few bf16 ULPs of the range


def test_full_unet_mxu_lowering_parity():
    """One parameter set, both lowerings, same output — the flagship
    architecture at toy scale."""
    kwargs = dict(
        in_channels=1, out_channels=3,
        feature_maps=(8, 12, 16), down_factors=((1, 2, 2), (2, 2, 2)),
        s2d_factor=(1, 2, 2),
    )
    native = unet3d.UNet3D(conv_impl="native", **kwargs)
    mxu = unet3d.UNet3D(conv_impl="mxu", **kwargs)
    params = unet3d.init_params(native, (4, 16, 16), 1)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.random((2, 4, 16, 16, 1), dtype=np.float32))
    ref = native.apply({"params": params}, x)
    got = mxu.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_engine_variant_tpu_mxu():
    from chunkflow_tpu.inference import engines

    eng = engines.create_engine(
        "flax", input_patch_size=(4, 16, 16), num_output_channels=3,
        model_variant="tpu_mxu",
    )
    x = jnp.zeros((2, 1, 4, 16, 16), jnp.float32)
    out = eng.apply(eng.params, x)
    assert out.shape == (2, 3, 4, 16, 16)
