import numpy as np

from chunkflow_tpu.inference.bump import bump_map, normalized_patch_mask
from chunkflow_tpu.inference.patching import (
    enumerate_patches,
    pad_to_batch,
    starts_1d,
)


def test_bump_map_properties():
    bump = bump_map((8, 16, 16))
    assert bump.shape == (8, 16, 16)
    assert bump.dtype == np.float32
    assert bump.min() >= 1.0
    assert bump.max() <= 1e6 + 1
    # maximum at the center
    assert bump[4, 8, 8] == bump.max()
    # symmetric
    np.testing.assert_allclose(bump, bump[::-1, :, :], rtol=1e-5)
    np.testing.assert_allclose(bump, bump[:, ::-1, :], rtol=1e-5)


def test_normalized_mask_sums_to_one_when_tiled():
    """The reference's make_patch_mask invariant (patch_mask.py:43-46):
    masks of overlapping patches must sum to 1 in the covered interior."""
    patch = (8, 8, 8)
    overlap = (4, 4, 4)
    mask = normalized_patch_mask(patch, overlap).astype(np.float64)
    stride = tuple(p - o for p, o in zip(patch, overlap))
    # tile a 5x5x5 patch grid
    shape = tuple(4 * s + p for s, p in zip(stride, patch))
    buf = np.zeros(shape)
    for i in range(5):
        for j in range(5):
            for k in range(5):
                start = (i * stride[0], j * stride[1], k * stride[2])
                sl = tuple(slice(s, s + p) for s, p in zip(start, patch))
                buf[sl] += mask
    # interior (one patch margin in from each face) must be exactly 1
    interior = buf[
        patch[0] : -patch[0], patch[1] : -patch[1], patch[2] : -patch[2]
    ]
    np.testing.assert_allclose(interior, 1.0, atol=1e-6)


def test_starts_1d_snapping():
    assert starts_1d(32, 16, 8) == [0, 8, 16]
    assert starts_1d(30, 16, 8) == [0, 8, 14]  # last snapped flush
    assert starts_1d(16, 16, 8) == [0]
    import pytest

    with pytest.raises(ValueError):
        starts_1d(8, 16, 8)


def test_enumerate_patches_geometry():
    grid = enumerate_patches(
        (32, 32, 32),
        input_patch_size=(16, 16, 16),
        output_patch_size=(12, 12, 12),
        output_patch_overlap=(4, 4, 4),
    )
    assert grid.crop_margin == (2, 2, 2)
    # stride 8: starts [0, 8, 16] per axis
    assert grid.num_patches == 27
    np.testing.assert_array_equal(
        grid.output_starts, grid.input_starts + 2
    )
    assert grid.input_starts.max() == 16


def test_pad_to_batch():
    grid = enumerate_patches((32, 32, 32), (16, 16, 16))
    assert grid.num_patches == 8
    in_starts, out_starts, valid = pad_to_batch(grid, 3)
    assert in_starts.shape[0] == 9
    assert valid.sum() == 8
    assert valid[-1] == 0
