"""Reference-checkpoint migration: torch RSUNet -> Flax by NAME.

The round-1 converter paired tensors positionally, which only worked for
torch models defined in execution order.  These tests build a
production-shaped RSUNet (width 28/36/48/64, anisotropic (1,2,2) first
pooling) the way a reference user's model.py looks — including BatchNorm3d
with real running statistics, a ``{'state_dict': ...}`` checkpoint
wrapper, and submodules DEFINED IN REVERSE ORDER so positional pairing
cannot work — and require MSE < 1e-4 between torch eval and the converted
Flax model on CPU.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
torch_nn = torch.nn

from chunkflow_tpu.models import rsunet
from chunkflow_tpu.models.converter import torch_to_flax_by_name
from chunkflow_tpu.models.unet3d import init_params

WIDTH = (28, 36, 48, 64)
DOWN = ((1, 2, 2), (2, 2, 2), (2, 2, 2))

# A reference-style user model.py: InstantiatedModel + hooks, submodules
# declared decoder-first (reverse of execution order).
MODEL_PY = """
import torch
import torch.nn as nn


class RSBlock(nn.Module):
    def __init__(self, cin, c):
        super().__init__()
        # declaration order scrambled on purpose
        self.bn3 = nn.BatchNorm3d(c)
        self.conv3 = nn.Conv3d(c, c, (3, 3, 3), padding=(1, 1, 1))
        self.bn2 = nn.BatchNorm3d(c)
        self.conv2 = nn.Conv3d(c, c, (3, 3, 3), padding=(1, 1, 1))
        self.bn1 = nn.BatchNorm3d(c)
        self.conv1 = nn.Conv3d(cin, c, (1, 3, 3), padding=(0, 1, 1))

    def forward(self, x):
        x = torch.relu(self.bn1(self.conv1(x)))
        residual = x
        x = torch.relu(self.bn2(self.conv2(x)))
        x = torch.relu(self.bn3(self.conv3(x)) + residual)
        return x


class RSUNet(nn.Module):
    def __init__(self, width=(28, 36, 48, 64),
                 down=((1, 2, 2), (2, 2, 2), (2, 2, 2)),
                 in_channels=1, out_channels=3):
        super().__init__()
        self.down = down
        depth = len(width)
        # decoder first: positional (definition-order) pairing MUST fail
        self.out = nn.Conv3d(width[0], out_channels, 1)
        for i in range(depth - 1):
            setattr(self, f"dec{i}", RSBlock(width[i], width[i]))
            setattr(self, f"up{i}", nn.ConvTranspose3d(
                width[i + 1], width[i], down[i], stride=down[i]))
        self.bridge = RSBlock(width[-2], width[-1])
        for i in reversed(range(depth - 1)):
            setattr(self, f"enc{i}",
                    RSBlock(width[i - 1] if i > 0 else width[0], width[i]))
        self.embed = nn.Conv3d(in_channels, width[0], (1, 5, 5),
                               padding=(0, 2, 2))

    def forward(self, x):
        depth = len(self.down) + 1
        x = self.embed(x)
        skips = []
        for i in range(depth - 1):
            x = getattr(self, f"enc{i}")(x)
            skips.append(x)
            x = torch.nn.functional.max_pool3d(x, self.down[i], self.down[i])
        x = self.bridge(x)
        for i in reversed(range(depth - 1)):
            x = getattr(self, f"up{i}")(x)
            x = x + skips[i]
            x = getattr(self, f"dec{i}")(x)
        return torch.sigmoid(self.out(x))


InstantiatedModel = RSUNet()


def pre_process(input_patch):
    return torch.from_numpy(input_patch)


def post_process(net_output):
    return net_output
"""


def _torch_twin(tmp_path):
    """Instantiate the reference-style model with nontrivial BN stats."""
    from chunkflow_tpu.models.migrate import load_torch_module

    model_py = tmp_path / "model.py"
    model_py.write_text(MODEL_PY)
    module = load_torch_module(str(model_py))
    model = module.InstantiatedModel
    torch.manual_seed(0)
    for m in model.modules():
        if isinstance(m, torch_nn.BatchNorm3d):
            c = m.num_features
            m.running_mean.copy_(torch.randn(c) * 0.1)
            m.running_var.copy_(torch.rand(c) * 0.5 + 0.5)
            m.weight.data.copy_(torch.rand(c) * 0.5 + 0.75)
            m.bias.data.copy_(torch.randn(c) * 0.1)
    model.eval()
    return str(model_py), model


def _flax_model():
    return rsunet.RSUNet(in_channels=1, out_channels=3, width=WIDTH,
                         down_factors=DOWN)


def _mse(model_py, weight_path, torch_model, via="engine"):
    pin = (8, 32, 32)
    rng = np.random.default_rng(3)
    x = rng.random((2, 1) + pin, dtype=np.float32)
    with torch.no_grad():
        ref = torch_model(torch.from_numpy(x)).numpy()

    if via == "engine":
        from chunkflow_tpu.inference import engines

        engine = engines.create_flax_engine(
            model_path=model_py,
            weight_path=weight_path,
            input_patch_size=pin,
            num_input_channels=1,
            num_output_channels=3,
            model_variant="rsunet",
        )
        out = np.asarray(engine.apply(engine.params, x))
    else:
        import jax.numpy as jnp

        model = _flax_model()
        state = {k: v.detach().numpy()
                 for k, v in torch_model.state_dict().items()}
        params = torch_to_flax_by_name(
            state, init_params(model, pin, 1))
        out = np.asarray(model.apply(
            {"params": params}, jnp.moveaxis(jnp.asarray(x), 1, -1)))
        out = np.moveaxis(out, -1, 1)
    return float(((out - ref) ** 2).mean()), ref


def test_name_based_conversion_parity(tmp_path):
    model_py, model = _torch_twin(tmp_path)
    mse, ref = _mse(model_py, None, model, via="direct")
    assert ref.std() > 1e-3  # non-degenerate oracle
    assert mse < 1e-4, mse


def test_engine_migration_via_reference_contract(tmp_path):
    """model.py (InstantiatedModel) + wrapped .pt checkpoint through
    create_flax_engine — the actual user migration path."""
    model_py, model = _torch_twin(tmp_path)
    ckpt = tmp_path / "model900000.pt"
    torch.save({"state_dict": model.state_dict()}, str(ckpt))
    mse, _ = _mse(model_py, str(ckpt), model, via="engine")
    assert mse < 1e-4, mse


def test_positional_pairing_rejects_scrambled_order(tmp_path):
    """The old positional converter must NOT silently mis-pair the
    scrambled-definition-order checkpoint."""
    from chunkflow_tpu.models.converter import torch_to_flax

    _, model = _torch_twin(tmp_path)
    state = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    template = init_params(_flax_model(), (8, 32, 32), 1)
    with pytest.raises(ValueError):
        torch_to_flax(state, template)


def test_name_map_bridges_renames(tmp_path):
    _, model = _torch_twin(tmp_path)
    state = {
        k.replace("embed.", "input_conv."): v.detach().numpy()
        for k, v in model.state_dict().items()
    }
    template = init_params(_flax_model(), (8, 32, 32), 1)
    with pytest.raises(KeyError):
        torch_to_flax_by_name(state, template)
    params = torch_to_flax_by_name(
        state, template, name_map={"embed": "input_conv"})
    assert "embed" in params
