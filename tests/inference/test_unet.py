import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from chunkflow_tpu.models import unet3d
from chunkflow_tpu.models.converter import torch_to_flax


def test_unet_forward_shape():
    model = unet3d.UNet3D(
        in_channels=1,
        out_channels=3,
        feature_maps=(4, 8, 12),
        down_factors=((1, 2, 2), (2, 2, 2)),
    )
    params = unet3d.init_params(model, (4, 16, 16), 1)
    x = jnp.zeros((2, 4, 16, 16, 1))
    y = model.apply({"params": params}, x)
    assert y.shape == (2, 4, 16, 16, 3)
    # sigmoid output range
    assert float(jnp.min(y)) >= 0.0 and float(jnp.max(y)) <= 1.0


def test_unet_params_save_load(tmp_path):
    model = unet3d.UNet3D(
        in_channels=1, out_channels=1,
        feature_maps=(2, 4), down_factors=((1, 2, 2),),
    )
    params = unet3d.init_params(model, (2, 8, 8), 1)
    path = str(tmp_path / "params.msgpack")
    unet3d.save_params(params, path)
    loaded = unet3d.init_or_load_params(model, path, (2, 8, 8), 1)
    x = jnp.ones((1, 2, 8, 8, 1))
    np.testing.assert_allclose(
        np.asarray(model.apply({"params": params}, x)),
        np.asarray(model.apply({"params": loaded}, x)),
    )


def test_flax_engine_through_inferencer():
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="flax",
        batch_size=2,
    )
    # patch a small model in for test speed
    out = inferencer(chunk)
    assert out.shape == (3, 8, 32, 32)
    arr = np.asarray(out.array)
    assert np.all(arr >= 0) and np.all(arr <= 1)
    assert arr.std() > 0  # not degenerate


def test_torch_conv_conversion_numeric():
    torch = pytest.importorskip("torch")
    import flax.linen as nn

    # a 2-layer torch net and its mirrored flax net
    tnet = torch.nn.Sequential(
        torch.nn.Conv3d(2, 4, 3, padding=1),
        torch.nn.ELU(),
        torch.nn.Conv3d(4, 1, 3, padding=1),
    )

    class FNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Conv(4, (3, 3, 3), padding="SAME")(x)
            x = nn.elu(x)
            x = nn.Conv(1, (3, 3, 3), padding="SAME")(x)
            return x

    fnet = FNet()
    template = fnet.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 4, 2))
    )["params"]
    params = torch_to_flax(tnet.state_dict(), template)

    x = np.random.default_rng(0).random((1, 4, 4, 4, 2)).astype(np.float32)
    with torch.no_grad():
        # torch is channels-first
        expected = tnet(torch.from_numpy(np.moveaxis(x, -1, 1))).numpy()
    got = np.asarray(fnet.apply({"params": params}, jnp.asarray(x)))
    np.testing.assert_allclose(
        np.moveaxis(got, -1, 1), expected, rtol=1e-4, atol=1e-5
    )


def test_converter_mismatch_raises():
    import flax.linen as nn

    class FNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Conv(4, (3, 3, 3))(x)

    template = FNet().init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 4, 2))
    )["params"]
    with pytest.raises(ValueError, match="do not mirror|shape mismatch"):
        torch_to_flax({}, template)


def test_tpu_variant_bf16_through_inferencer():
    """The flagship (space-to-depth, bfloat16) runs through the fused
    program — the exact path bench.py measures, at toy sizes."""
    import numpy as np

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="flax",
        batch_size=2,
        dtype="bfloat16",
        model_variant="tpu",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    out = inferencer(chunk)
    arr = np.asarray(out.array)
    assert arr.shape == (3, 8, 32, 32)
    assert np.isfinite(arr).all()
    assert arr.std() > 0
    assert arr.dtype == np.float32


def test_tpu_s2d4_variant_through_inferencer():
    """The aggressive (1,4,4) space-to-depth variant (battery A/B
    fwd_tpu_s2d4): widths scale by sqrt(prod(s2d)) so per-voxel FLOPs at
    full resolution match the reference-class model, and the fused
    program runs it end to end."""
    import numpy as np

    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer
    from chunkflow_tpu.models import unet3d

    model = unet3d.create_tpu_optimized_model(s2d_factor=(1, 4, 4))
    assert model.feature_maps == (112, 144, 192, 256)
    assert model.s2d_factor == (1, 4, 4)
    # default stem unchanged by the refactor
    flagship = unet3d.create_tpu_optimized_model()
    assert flagship.feature_maps == (56, 72, 96, 128)

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="flax",
        batch_size=2,
        dtype="bfloat16",
        model_variant="tpu_s2d4",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    arr = np.asarray(inferencer(chunk).array)
    assert arr.shape == (3, 8, 32, 32)
    assert np.isfinite(arr).all()
    assert arr.std() > 0
