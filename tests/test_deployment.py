"""Deployment-surface validation (SURVEY L7).

A container build is impossible in this image, so the k8s manifest and
Dockerfile are validated structurally instead: the manifest must parse,
target TPU node pools, mount credentials, and run a worker command whose
CLI spelling actually exists in this package; the Dockerfile's install
steps must reference real files. This machine-checks the deployment
artifacts the same way the reference's own repo only eyeballs them.
"""
import os
import re
import shlex

import pytest

yaml = pytest.importorskip("yaml")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _manifest():
    path = os.path.join(REPO, "distributed", "kubernetes", "deploy.yml")
    with open(path) as f:
        return yaml.safe_load(f)


def test_k8s_manifest_structure():
    doc = _manifest()
    assert doc["kind"] == "Deployment"
    spec = doc["spec"]["template"]["spec"]
    # TPU node targeting, not GPUs
    selector = spec["nodeSelector"]
    assert any("tpu" in str(v).lower() for v in selector.values()), selector
    # credentials secret mounted
    assert any(
        "secret" in volume for volume in spec["volumes"]
    ), spec["volumes"]
    containers = spec["containers"]
    assert len(containers) >= 1
    container = containers[0]
    mounts = {mount["name"] for mount in container["volumeMounts"]}
    assert mounts & {volume["name"] for volume in spec["volumes"]}


def test_k8s_worker_command_uses_real_cli_spellings():
    """Every chunkflow subcommand named in the manifest's worker command
    must exist in the CLI registry — a renamed command cannot silently
    strand the deployment template."""
    from chunkflow_tpu.flow.cli import main

    doc = _manifest()
    container = doc["spec"]["template"]["spec"]["containers"][0]
    blob = " ".join(
        str(x)
        for x in (container.get("command", []) + container.get("args", []))
    )
    # the chained pipeline: everything after the entrypoint token
    tokens = shlex.split(blob.replace("\n", " "))
    assert "chunkflow" in " ".join(tokens), tokens
    known = set(main.commands.keys())
    used = [t for t in tokens if t in known]
    # a real worker pipeline: fetch + load + inference + save + ack
    assert len(used) >= 4, (used, tokens)
    for required in ("fetch-task-from-queue", "delete-task-in-queue"):
        assert required in used, (required, used)
    # no token that LOOKS like a subcommand (lowercase-with-dashes, not an
    # option, not a value) is unknown to the CLI
    candidate = re.compile(r"^[a-z][a-z0-9]*(-[a-z0-9]+)+$")
    unknown = [
        t for t in tokens
        if candidate.match(t) and not t.startswith("-") and t not in known
        and "." not in t and "/" not in t
    ]
    # allow infrastructure words that are not chunkflow commands
    allowed = {"chunkflow-tpu-worker", "read-only"}
    assert not (set(unknown) - allowed), unknown


def test_dockerfile_references_exist():
    path = os.path.join(REPO, "Dockerfile")
    with open(path) as f:
        content = f.read()
    # every COPY source must exist in the repo
    for match in re.finditer(r"^COPY\s+(\S+)\s+\S+", content, re.M):
        src = match.group(1)
        if src.startswith("--"):
            continue
        assert os.path.exists(os.path.join(REPO, src)), src
    # the image must install this package, not a placeholder
    assert "pyproject.toml" in content or "pip install" in content
