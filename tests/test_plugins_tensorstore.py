"""First tests for the tensorstore-backed load plugins
(plugins/load_tensorstore.py, plugins/load_n5.py) — de-stubbed in
ISSUE 11 to ride the storage plane: one cached dataset handle per
process, block-decomposed concurrent reads, shared hot-block LRU, and a
real voxel_size default instead of None."""
import numpy as np
import pytest

ts = pytest.importorskip("tensorstore")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.flow.plugin import load_plugin
from chunkflow_tpu.volume import storage


@pytest.fixture(autouse=True)
def _clean_state():
    telemetry.reset()
    storage.reset_shared_cache()
    storage.reset_open_backends()
    yield
    telemetry.reset()
    storage.reset_shared_cache()
    storage.reset_open_backends()


@pytest.fixture
def zarr_store(tmp_path):
    data = np.random.default_rng(0).integers(
        1, 255, size=(32, 64, 64), dtype=np.uint8)
    root = str(tmp_path / "zarr")
    dataset = ts.open({
        "driver": "zarr",
        "kvstore": {"driver": "file", "path": root},
        "metadata": {"shape": [32, 64, 64], "chunks": [16, 32, 32],
                     "dtype": "|u1"},
    }, create=True).result()
    dataset[...] = data
    return root, data


def test_load_tensorstore_reads_and_defaults_voxel_size(zarr_store):
    root, data = zarr_store
    execute = load_plugin("load_tensorstore")
    bbox = BoundingBox((4, 8, 8), (28, 56, 60))
    chunk = execute(bbox, driver="zarr", kvstore=f"file://{root}")
    assert isinstance(chunk, Chunk)
    np.testing.assert_array_equal(
        np.asarray(chunk.array), data[4:28, 8:56, 8:60])
    assert tuple(chunk.voxel_offset) == (4, 8, 8)
    # ISSUE 11 satellite: a REAL default, not None
    assert chunk.voxel_size == Cartesian(1, 1, 1)
    explicit = execute(bbox, driver="zarr", kvstore=f"file://{root}",
                       voxel_size=(40, 4, 4))
    assert explicit.voxel_size == Cartesian(40, 4, 4)


def test_load_tensorstore_cache_arg_uses_shared_lru(zarr_store):
    root, data = zarr_store
    execute = load_plugin("load_tensorstore")
    bbox = BoundingBox((0, 0, 0), (32, 64, 64))
    # uncached: two calls, two full rounds of block reads
    for _ in range(2):
        execute(bbox, driver="zarr", kvstore=f"file://{root}")
    counters = telemetry.snapshot()["counters"]
    assert counters["storage/block_reads"] == 16
    assert "storage/hits" not in counters
    telemetry.reset()
    # cache=1 opts into the shared LRU: the repeat is pure hits
    for _ in range(2):
        out = execute(bbox, driver="zarr", kvstore=f"file://{root}",
                      cache=1)
        np.testing.assert_array_equal(np.asarray(out.array), data)
    counters = telemetry.snapshot()["counters"]
    assert counters["storage/block_reads"] == 8
    assert counters["storage/hits"] == 8


def test_load_tensorstore_serial_mode_bit_identical(zarr_store,
                                                    monkeypatch):
    root, data = zarr_store
    execute = load_plugin("load_tensorstore")
    bbox = BoundingBox((3, 5, 7), (29, 55, 57))
    concurrent = execute(bbox, driver="zarr", kvstore=f"file://{root}")
    monkeypatch.setenv("CHUNKFLOW_STORAGE", "serial")
    serial = execute(bbox, driver="zarr", kvstore=f"file://{root}")
    np.testing.assert_array_equal(
        np.asarray(concurrent.array), np.asarray(serial.array))


def test_load_n5_reads_through_storage_plane(tmp_path):
    data = np.random.default_rng(1).integers(
        1, 255, size=(16, 32, 32), dtype=np.uint16)
    root = str(tmp_path / "n5")
    dataset = ts.open({
        "driver": "n5",
        "kvstore": {"driver": "file", "path": root},
        "path": "raw",
        "metadata": {"dimensions": [16, 32, 32],
                     "blockSize": [8, 16, 16],
                     "dataType": "uint16"},
    }, create=True).result()
    dataset[...] = data
    execute = load_plugin("load_n5")
    bbox = BoundingBox((2, 4, 4), (14, 30, 28))
    chunk = execute(bbox, n5_dir=root, group_path="raw", cache=1)
    np.testing.assert_array_equal(
        np.asarray(chunk.array), data[2:14, 4:30, 4:28])
    assert chunk.voxel_size == Cartesian(1, 1, 1)
    # repeat is cache-served
    telemetry.reset()
    execute(bbox, n5_dir=root, group_path="raw", cache=1)
    counters = telemetry.snapshot()["counters"]
    assert counters["storage/hits"] > 0
    assert "storage/block_reads" not in counters
