"""Shell out to tests/command_line.sh (reference test strategy §4: the
composed-pipeline smoke runs as REAL shell commands, not CliRunner)."""
import os
import subprocess
import sys

import pytest


def test_command_line_smoke(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "tests", "command_line.sh")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["PATH"] = os.path.dirname(sys.executable) + os.pathsep + env["PATH"]
    proc = subprocess.run(
        ["bash", script], cwd=tmp_path, env=env,
        capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        pytest.fail(
            f"command_line.sh failed (rc={proc.returncode})\n"
            f"--- stdout ---\n{proc.stdout[-4000:]}\n"
            f"--- stderr ---\n{proc.stderr[-4000:]}"
        )
    assert "ALL COMMAND-LINE SMOKE TESTS PASSED" in proc.stdout
