"""Pod simulation: several worker PROCESSES drain one shared file queue.

The reference's distributed design is untestable without AWS credentials
(its SQS test is skipped); SURVEY §4 calls for a multi-process pod-sim as
the improvement. Here N workers run the real CLI pipeline concurrently —
fetch-task-from-queue -> create data -> identity inference -> save-h5 ->
delete-task-in-queue — against a FileQueue, exercising visibility-timeout
leasing, ack-after-write, and write-disjointness by block alignment.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import sys
sys.path.insert(0, {repo!r})
from chunkflow_tpu.flow.cli import main

main([
    "fetch-task-from-queue", "-q", {queue!r},
    "load-h5", "--file-name", {src!r},
    "inference", "--framework", "identity",
    "--input-patch-size", "4", "16", "16",
    "--output-patch-overlap", "2", "8", "8",
    "--num-output-channels", "1",
    "--no-crop-output-margin",
    "save-h5", "--file-name-prefix", {outdir!r},
    "delete-task-in-queue",
], standalone_mode=False)
"""


@pytest.mark.parametrize("n_workers", [3])
def test_multiprocess_workers_drain_queue(tmp_path, n_workers):
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.core.bbox import BoundingBoxes
    from chunkflow_tpu.parallel.queues import open_queue

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    # the shared input volume: one h5 the workers window into per task bbox
    src = str(tmp_path / "src.h5")
    full = Chunk.create((8, 32, 32), dtype=np.float32, pattern="random")
    full.to_h5(src)

    # task grid: 4 disjoint bboxes
    bboxes = BoundingBoxes.from_manual_setup(
        chunk_size=(8, 16, 16), roi_start=(0, 0, 0), roi_stop=(8, 32, 32)
    )
    queue_spec = f"file://{tmp_path / 'queue'}"
    queue = open_queue(queue_spec)
    queue.send_messages([b.string for b in bboxes])
    assert len(queue) == 4

    outdir = str(tmp_path / "out") + "/"
    os.makedirs(outdir, exist_ok=True)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    code = WORKER.format(repo=repo, queue=queue_spec, src=src, outdir=outdir)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for _ in range(n_workers)
    ]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()

    # queue fully drained and acknowledged
    assert len(open_queue(queue_spec)) == 0
    # every task produced its output file; identity oracle holds per block
    outputs = sorted(os.listdir(outdir))
    assert len(outputs) == 4, outputs
    src_arr = np.asarray(full.array)
    for bbox in bboxes:
        path = os.path.join(outdir, f"{bbox.string}.h5")
        assert os.path.exists(path), f"missing {path}"
        chunk = Chunk.from_h5(path)
        got = np.asarray(chunk.array)
        got = got[0] if got.ndim == 4 else got
        sl = tuple(slice(int(a), int(b)) for a, b in zip(bbox.start, bbox.stop))
        np.testing.assert_allclose(got, src_arr[sl], atol=1e-5)


def test_multihost_helpers_single_process():
    """Single-process runtime: we ARE the coordinator; mesh covers devices."""
    from chunkflow_tpu.parallel import multihost

    assert multihost.is_coordinator() is True
    mesh = multihost.global_mesh()
    assert mesh.devices.size == len(__import__("jax").devices())
