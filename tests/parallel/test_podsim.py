"""Pod simulation: several worker PROCESSES drain one shared file queue.

The reference's distributed design is untestable without AWS credentials
(its SQS test is skipped); SURVEY §4 calls for a multi-process pod-sim as
the improvement. Here N workers run the real CLI pipeline concurrently —
fetch-task-from-queue -> create data -> identity inference -> save-h5 ->
delete-task-in-queue — against a FileQueue, exercising visibility-timeout
leasing, ack-after-write, and write-disjointness by block alignment.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import sys
sys.path.insert(0, {repo!r})
from chunkflow_tpu.flow.cli import main

main([
    "fetch-task-from-queue", "-q", {queue!r},
    "load-h5", "--file-name", {src!r},
    "inference", "--framework", "identity",
    "--input-patch-size", "4", "16", "16",
    "--output-patch-overlap", "2", "8", "8",
    "--num-output-channels", "1",
    "--no-crop-output-margin",
    "save-h5", "--file-name-prefix", {outdir!r},
    "delete-task-in-queue",
], standalone_mode=False)
"""


@pytest.mark.parametrize("n_workers", [3])
def test_multiprocess_workers_drain_queue(tmp_path, n_workers):
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.core.bbox import BoundingBoxes
    from chunkflow_tpu.parallel.queues import open_queue

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    # the shared input volume: one h5 the workers window into per task bbox
    src = str(tmp_path / "src.h5")
    full = Chunk.create((8, 32, 32), dtype=np.float32, pattern="random")
    full.to_h5(src)

    # task grid: 4 disjoint bboxes
    bboxes = BoundingBoxes.from_manual_setup(
        chunk_size=(8, 16, 16), roi_start=(0, 0, 0), roi_stop=(8, 32, 32)
    )
    queue_spec = f"file://{tmp_path / 'queue'}"
    queue = open_queue(queue_spec)
    queue.send_messages([b.string for b in bboxes])
    assert len(queue) == 4

    outdir = str(tmp_path / "out") + "/"
    os.makedirs(outdir, exist_ok=True)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    code = WORKER.format(repo=repo, queue=queue_spec, src=src, outdir=outdir)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for _ in range(n_workers)
    ]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()

    # queue fully drained and acknowledged
    assert len(open_queue(queue_spec)) == 0
    # every task produced its output file; identity oracle holds per block
    outputs = sorted(os.listdir(outdir))
    assert len(outputs) == 4, outputs
    src_arr = np.asarray(full.array)
    for bbox in bboxes:
        path = os.path.join(outdir, f"{bbox.string}.h5")
        assert os.path.exists(path), f"missing {path}"
        chunk = Chunk.from_h5(path)
        got = np.asarray(chunk.array)
        got = got[0] if got.ndim == 4 else got
        sl = tuple(slice(int(a), int(b)) for a, b in zip(bbox.start, bbox.stop))
        np.testing.assert_allclose(got, src_arr[sl], atol=1e-5)


def test_multihost_helpers_single_process():
    """Single-process runtime: we ARE the coordinator; mesh covers devices."""
    from chunkflow_tpu.parallel import multihost

    assert multihost.is_coordinator() is True
    mesh = multihost.global_mesh()
    assert mesh.devices.size == len(__import__("jax").devices())


CLI_WORKER_HEAD = r"""
import sys
sys.path.insert(0, {repo!r})

from chunkflow_tpu.parallel import multihost

multihost.initialize(
    coordinator_address={coord!r},
    num_processes=2,
    process_id={pid},
)
import jax

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8
"""


def _cli_worker_template(queue_spec, src, outdir):
    # custom params baked in here; {repo}/{coord}/{pid} are filled by
    # test_multihost_bringup._run_two_workers
    body = f"""
from chunkflow_tpu.flow.cli import main

main([
    "fetch-task-from-queue", "-q", {queue_spec!r}, "-r", "0",
    "load-h5", "--file-name", {src!r},
    "inference", "--framework", "identity",
    "--input-patch-size", "4", "16", "16",
    "--output-patch-overlap", "2", "8", "8",
    "--num-output-channels", "3",
    "--no-crop-output-margin",
    "--sharding", "patch",
    "save-h5", "--file-name-prefix", {outdir!r},
    "delete-task-in-queue",
], standalone_mode=False)
"""
    return CLI_WORKER_HEAD + body + '\nprint("CLIWORKER_OK", {pid})\n'


def test_crosshost_cli_task_loop_matches_single_process(tmp_path):
    """VERDICT r4 #6: the production CLI task loop over a 2-process
    jax.distributed runtime — one shared file queue, coordinator-fetch +
    broadcast task distribution, patch-sharded inference as ONE global
    program spanning both processes (8 devices), consistency guard
    active, coordinator-only writes — produces the same volume output as
    the identical pipeline in a single process at ulp tolerance (XLA
    schedules reductions per topology, and even per-rank replica copies
    can differ in the last ulp — measured in test_multihost_bringup —
    which is why only the coordinator's copy is ever published). The
    reference's deployment model (distributed/kubernetes/deploy.yml:30-44)
    has no such test anywhere."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.core.bbox import BoundingBoxes
    from chunkflow_tpu.flow.cli import main as cli_main
    from chunkflow_tpu.parallel.queues import open_queue

    from tests.parallel.test_multihost_bringup import _run_two_workers

    src = str(tmp_path / "src.h5")
    full = Chunk.create((8, 32, 32), dtype=np.float32, pattern="random")
    full.to_h5(src)

    bboxes = BoundingBoxes.from_manual_setup(
        chunk_size=(4, 32, 32), roi_start=(0, 0, 0), roi_stop=(8, 32, 32)
    )
    queue_spec = f"file://{tmp_path / 'queue'}"
    queue = open_queue(queue_spec)
    queue.send_messages([b.string for b in bboxes])
    assert len(queue) == 2

    outdir = str(tmp_path / "out_dist") + "/"
    os.makedirs(outdir, exist_ok=True)
    _run_two_workers(
        tmp_path, _cli_worker_template(queue_spec, src, outdir),
        "CLIWORKER_OK",
    )

    # queue drained; exactly one output per task (coordinator-only
    # writes: the mirror process must not have double-written)
    assert len(open_queue(queue_spec)) == 0
    outputs = sorted(os.listdir(outdir))
    assert len(outputs) == 2, outputs

    # single-process reference run of the IDENTICAL pipeline with the
    # same --sharding patch program over 8 devices (here all local).
    # XLA compiles for the actual topology, so reduction schedules — and
    # therefore the last float32 ulp — may differ between the 1-process
    # and 2-process compiles; bit-identity across topologies is not a
    # property ANY system can promise. What IS promised (and asserted):
    # ulp-level numeric parity here, and byte-identical replicated
    # output ACROSS the two processes of one runtime (the crc allgather
    # in test_multihost_bringup's WORKER)
    queue2_spec = f"file://{tmp_path / 'queue2'}"
    queue2 = open_queue(queue2_spec)
    queue2.send_messages([b.string for b in bboxes])
    outdir2 = str(tmp_path / "out_single") + "/"
    os.makedirs(outdir2, exist_ok=True)
    cli_main([
        "fetch-task-from-queue", "-q", queue2_spec, "-r", "0",
        "load-h5", "--file-name", src,
        "inference", "--framework", "identity",
        "--input-patch-size", "4", "16", "16",
        "--output-patch-overlap", "2", "8", "8",
        "--num-output-channels", "3",
        "--no-crop-output-margin",
        "--sharding", "patch",
        "save-h5", "--file-name-prefix", outdir2,
        "delete-task-in-queue",
    ], standalone_mode=False)

    assert sorted(os.listdir(outdir2)) == outputs
    src_arr = np.asarray(full.array)
    for name in outputs:
        dist = Chunk.from_h5(os.path.join(outdir, name))
        single = Chunk.from_h5(os.path.join(outdir2, name))
        a, b = np.asarray(dist.array), np.asarray(single.array)
        assert a.dtype == b.dtype
        np.testing.assert_allclose(a, b, atol=2e-6, rtol=0)
        assert tuple(dist.voxel_offset) == tuple(single.voxel_offset)
        # numeric sanity vs ground truth: identity engine must
        # reproduce the source window (float-accumulation tolerance)
        bbox = dist.bbox
        sl = tuple(slice(int(s), int(e))
                   for s, e in zip(bbox.start[-3:], bbox.stop[-3:]))
        np.testing.assert_allclose(
            a, np.broadcast_to(src_arr[sl], a.shape), atol=1e-5)
