"""ISSUE 7: the elastic, preemption-native fleet supervisor.

Two layers of coverage:

* **Controller units** (stub launcher/scraper, no subprocesses): every
  scale/evict/drill/backoff decision is exercised deterministically —
  deep queue adds a worker within one decision interval, sustained idle
  drains to min, the memory watermark and storage-bound/dead-letter
  holds gate scale-up, probe misses quarantine a worker and force-nack
  its leases, ``CHUNKFLOW_FLEET=0`` bypasses the controller.
* **Real multi-process runs** (bottom of the file): a chaos-accented
  supervised fleet over a real volume — workers SIGKILLed mid-task and
  spot-drilled while the output must stay bit-identical with exactly
  one ledger marker per bbox — plus the no-supervisor crash-recovery
  satellite (chaos ``action=kill`` self-SIGKILL, lease expiry, another
  worker completes exactly once, the trace hop reconstructs from merged
  JSONL alone).
"""
import itertools
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.parallel.fleet import (
    FleetSupervisor,
    WorkerHandle,
    fleet_disabled,
    host_available_gb,
)
from chunkflow_tpu.parallel.lifecycle import FileLedger
from chunkflow_tpu.parallel.queues import MemoryQueue, QueueBase, open_queue
from chunkflow_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _clean_state():
    telemetry.reset()
    chaos.reset()
    yield
    telemetry.reset()
    chaos.reset()


# ---------------------------------------------------------------------------
# stubs
# ---------------------------------------------------------------------------
class StubProc:
    """Popen-alike whose death is scripted: SIGTERM exits 143 (the
    graceful-preemption contract), kill() exits -9."""

    _pids = itertools.count(40000)

    def __init__(self, die_immediately=False):
        self.pid = next(self._pids)
        self.returncode = -9 if die_immediately else None
        self.signals = []

    def poll(self):
        return self.returncode

    def send_signal(self, sig):
        self.signals.append(sig)
        if sig == signal.SIGTERM and self.returncode is None:
            self.returncode = 143

    def kill(self):
        self.signals.append(signal.SIGKILL)
        if self.returncode is None:
            self.returncode = -9

    def terminate(self):
        self.send_signal(signal.SIGTERM)

    def wait(self, timeout=None):
        return self.returncode


class ScriptedQueue(QueueBase):
    """stats() plays back a script (last entry repeats); nack records."""

    def __init__(self, script):
        self.script = list(script)
        self.i = 0
        self.nacked = []

    def stats(self):
        stats = self.script[min(self.i, len(self.script) - 1)]
        self.i += 1
        return dict(stats)

    def nack(self, handle, refund=True):
        self.nacked.append(handle)
        return True


def make_supervisor(tmp_path, script=None, *, procs=None, scrape=None,
                    **kw):
    """A supervisor wired to stubs: no subprocess is ever spawned."""
    spawned = []

    def launcher(cmd, env):
        proc = (procs.pop(0) if procs else StubProc())
        spawned.append((cmd, env, proc))
        return proc

    def scraper(endpoint, timeout=1.0):
        if scrape is None:
            return {"endpoint": endpoint, "healthz": {"inflight_leases": 0},
                    "metrics": {}, "dominant_stall": None, "error": None}
        return scrape(endpoint)

    kw.setdefault("min_workers", 1)
    kw.setdefault("max_workers", 3)
    kw.setdefault("interval", 0.05)
    kw.setdefault("startup_grace", 0.0)
    kw.setdefault("mem_probe", lambda: None)
    kw.setdefault("state_path", str(tmp_path / "fleet-state.json"))
    sup = FleetSupervisor(
        "memory://fleet-stub", ["fetch-task-from-queue", "-q", "x",
                                "delete-task-in-queue"],
        launcher=launcher, scraper=scraper, **kw,
    )
    if script is not None:
        sup.queue = ScriptedQueue(script)
    sup._spawned = spawned
    return sup


DEEP = {"pending": 20, "inflight": 0, "dead": 0, "receives": 0}
IDLE = {"pending": 0, "inflight": 0, "dead": 0, "receives": 0}


# ---------------------------------------------------------------------------
# controller units
# ---------------------------------------------------------------------------
def test_deep_queue_scales_up_within_one_interval(tmp_path):
    """ISSUE 7 acceptance: deep queue -> worker added within ONE
    decision interval, one per tick, capped at max_workers."""
    sup = make_supervisor(tmp_path, [DEEP], min_workers=1, max_workers=3,
                          scale_up_backlog=4.0)
    sup.step()
    assert sup.target == 2  # min + 1 after a single interval
    assert sum(1 for w in sup.workers if w.active) == 2
    sup.step()
    sup.step()
    assert sup.target == 3  # clamped at max from then on
    assert sum(1 for w in sup.workers if w.active) == 3
    counters = telemetry.snapshot()["counters"]
    assert counters["fleet/scale_up"] == 2
    assert counters["fleet/spawns"] == 3


def test_idle_queue_drains_to_min_gracefully(tmp_path):
    """ISSUE 7 acceptance: sustained-idle queue -> drained to min via
    SIGTERM (graceful preemption), not SIGKILL."""
    sup = make_supervisor(tmp_path, [DEEP, DEEP, DEEP, IDLE],
                          min_workers=1, max_workers=3, idle_ticks=2)
    for _ in range(3):
        sup.step()
    assert sum(1 for w in sup.workers if w.active) == 3
    sup.step()  # idle tick 1: nothing happens yet
    assert sup.target == 3
    sup.step()  # idle tick 2: drain to min
    assert sup.target == 1
    assert sum(1 for w in sup.workers if w.active) == 1
    drained = [w for w in sup.workers if w.state in ("draining", "exited")]
    assert len(drained) == 2
    for w in drained:
        assert signal.SIGTERM in w.proc.signals
        assert signal.SIGKILL not in w.proc.signals
    assert telemetry.snapshot()["counters"]["fleet/scale_down"] == 1


def test_memory_watermark_gates_scale_up(tmp_path):
    sup = make_supervisor(tmp_path, [DEEP], mem_probe=lambda: 1.5,
                          mem_watermark_gb=2.0)
    for _ in range(3):
        sup.step()
    assert sup.target == 1  # deep queue, but no headroom: held at min
    counters = telemetry.snapshot()["counters"]
    assert "fleet/scale_up" not in counters
    assert counters["fleet/holds"] >= 3
    events = [e for e in _fleet_events(sup) if e["name"] == "fleet/hold"]
    assert events and events[0]["reason"] == "memory-watermark"


def test_storage_bound_fleet_holds_scale_up(tmp_path):
    """A deep queue whose workers are write-bound must NOT scale: more
    workers would only multiply pressure on the shared volume store."""
    def scrape(endpoint):
        return {"endpoint": endpoint, "healthz": {"inflight_leases": 1},
                "metrics": {},
                "dominant_stall": {"phase": "scheduler/write",
                                   "share": 0.8},
                "error": None}

    # IDLE first: the min worker spawns and is probed (its dominant
    # stall becomes known) before the queue deepens
    sup = make_supervisor(tmp_path, [IDLE, DEEP], scrape=scrape)
    for _ in range(3):
        sup.step()
    assert sup.target == 1
    assert telemetry.snapshot()["counters"]["fleet/holds"] >= 2
    holds = [e["reason"] for e in _fleet_events(sup)
             if e["name"] == "fleet/hold"]
    assert "storage-bound:scheduler/write" in holds


def test_compute_bound_fleet_does_scale(tmp_path):
    def scrape(endpoint):
        return {"endpoint": endpoint, "healthz": {"inflight_leases": 1},
                "metrics": {},
                "dominant_stall": {"phase": "pipeline/compute",
                                   "share": 0.9},
                "error": None}

    sup = make_supervisor(tmp_path, [IDLE, DEEP], scrape=scrape)
    sup.step()  # spawn the min worker
    sup.step()  # probed compute-bound + deep queue -> scale
    assert sup.target == 2


def _storage_bound_scrape(hits, misses):
    def scrape(endpoint):
        return {"endpoint": endpoint, "healthz": {"inflight_leases": 1},
                "metrics": {"chunkflow_storage_hits_total": hits,
                            "chunkflow_storage_misses_total": misses},
                "dominant_stall": {"phase": "scheduler/load",
                                   "share": 0.8},
                "error": None}
    return scrape


def test_storage_hold_qualified_cold_cache(tmp_path):
    """ISSUE 11: a storage-bound hold whose workers report a COLD block
    cache (mostly misses) is qualified ':cold-cache' — the stall is
    transient re-fetch traffic the warming LRU will absorb, not a
    reason to re-shard the volume at 3 a.m."""
    sup = make_supervisor(tmp_path, [IDLE, DEEP],
                          scrape=_storage_bound_scrape(5, 95))
    for _ in range(3):
        sup.step()
    assert sup.target == 1
    holds = [e["reason"] for e in _fleet_events(sup)
             if e["name"] == "fleet/hold"]
    assert "storage-bound:scheduler/load:cold-cache" in holds


def test_storage_hold_qualified_load_bound(tmp_path):
    """ISSUE 11: storage-bound WITH a warm cache means the shared store
    genuinely is the limit (network/volume bandwidth) — qualified
    ':load-bound' so ops know adding workers or waiting won't help."""
    sup = make_supervisor(tmp_path, [IDLE, DEEP],
                          scrape=_storage_bound_scrape(90, 10))
    for _ in range(3):
        sup.step()
    assert sup.target == 1
    holds = [e["reason"] for e in _fleet_events(sup)
             if e["name"] == "fleet/hold"]
    assert "storage-bound:scheduler/load:load-bound" in holds


def test_dead_letter_surge_holds_scale_up(tmp_path):
    """A dead-letter flood means the workload is poisoned — adding
    workers would just dead-letter faster."""
    script = [dict(DEEP, dead=0), dict(DEEP, dead=3), dict(DEEP, dead=6),
              dict(DEEP, dead=9)]
    sup = make_supervisor(tmp_path, script, dead_letter_surge=3)
    for _ in range(4):
        sup.step()
    # first tick scaled (no surge yet); after the surge no further ups
    assert sup.target == 2
    holds = [e["reason"] for e in _fleet_events(sup)
             if e["name"] == "fleet/hold"]
    assert "dead-letter-surge" in holds


def test_probe_misses_quarantine_and_force_nack(tmp_path):
    """Health probation: a worker that stops answering /healthz is
    SIGKILLed, the leases it last reported are force-nacked so the
    work reappears NOW, and a replacement is spawned."""
    MemoryQueue._registry.pop("fleet-evict", None)
    queue = MemoryQueue.open("fleet-evict", visibility_timeout=600)
    queue.send_messages(["t1", "t2"])
    h1, _ = queue.receive()
    h2, _ = queue.receive()
    assert queue.stats()["pending"] == 0

    calls = {"n": 0}

    def scrape(endpoint):
        calls["n"] += 1
        if calls["n"] == 1:  # one healthy probe reporting its leases
            return {"endpoint": endpoint,
                    "healthz": {"inflight_leases": 2,
                                "inflight_handles": [h1, h2]},
                    "metrics": {}, "dominant_stall": None, "error": None}
        return {"endpoint": endpoint, "healthz": None, "metrics": None,
                "dominant_stall": None, "error": "URLError: wedged"}

    sup = make_supervisor(tmp_path, [IDLE], scrape=scrape, probe_misses=2,
                          min_workers=1, max_workers=2)
    sup.queue = queue
    sup.step()  # spawn
    sup.step()  # healthy probe: leases reported
    assert sup.workers[0].handles == [h1, h2]
    sup.step()  # miss 1
    sup.step()  # miss 2 -> quarantined + SIGKILL
    assert sup.workers[0].state in ("quarantined", "exited")
    assert signal.SIGKILL in sup.workers[0].proc.signals
    sup.step()  # reap: force-nack + replacement
    assert sup.workers[0].state == "exited"
    assert queue.stats()["pending"] == 2  # both leases handed back NOW
    counters = telemetry.snapshot()["counters"]
    assert counters["fleet/evictions"] == 1
    assert counters["fleet/probe_failures"] >= 2
    assert counters["fleet/leases_nacked"] == 2
    assert sum(1 for w in sup.workers if w.active) == 1  # replaced
    # the force-release preserved the receive count (crash-shaped
    # handback, no refund): the next claim is delivery #2, so a task
    # that wedges every worker it lands on still walks into the
    # lifecycle crash-loop bound instead of cycling forever
    h, _ = queue.receive()
    assert queue.receive_count(h) == 2


def test_reap_flags_truncated_handle_list(tmp_path):
    """/healthz caps inflight_handles; when the cap bit, the leases
    past it were NOT force-nacked and ride out the visibility timeout
    — the supervisor must say so instead of silently breaking the
    immediate-pickup guarantee."""
    MemoryQueue._registry.pop("fleet-trunc", None)
    queue = MemoryQueue.open("fleet-trunc", visibility_timeout=600)
    queue.send_messages(["t1"])
    h1, _ = queue.receive()

    def scrape(endpoint):
        return {"endpoint": endpoint,
                "healthz": {"inflight_leases": 65,
                            "inflight_handles": [h1],
                            "inflight_handles_truncated": True},
                "metrics": {}, "dominant_stall": None, "error": None}

    sup = make_supervisor(tmp_path, [IDLE], scrape=scrape)
    sup.queue = queue
    sup.step()  # spawn
    sup.step()  # probe: truncated handle list recorded
    assert sup.workers[0].handles_truncated
    sup.workers[0].proc.kill()  # unexpected death
    sup.step()  # reap: force-nack what we know, flag the rest
    counters = telemetry.snapshot()["counters"]
    assert counters["fleet/leases_nacked"] == 1
    assert counters["fleet/handles_truncated"] == 1
    events = [e for e in _fleet_events(sup)
              if e["name"] == "fleet/handles_truncated"]
    assert events and events[0]["released"] == 1


def test_blind_drain_requires_longer_settle(tmp_path):
    """With telemetry off AND a backend that cannot report inflight,
    claimed-but-unacked tasks are invisible: the fleet must not declare
    the queue drained (and SIGTERM workers mid-compute) on the normal
    settle budget."""
    sup = make_supervisor(tmp_path, [IDLE])
    blind = {"pending": 0, "inflight": None, "dead": None,
             "receives": None}
    # sighted (backend reports inflight, or probing fills the gap):
    # the caller's settle_ticks stand
    assert sup._settle_target(IDLE, 2) == 2
    sup.probing = True
    assert sup._settle_target(blind, 2) == 2
    assert sup._drained(blind)  # probed leases say zero
    # blind: pending==0 alone is a guess — demand a longer quiet period
    sup.probing = False
    assert sup._drained(blind)
    assert sup._settle_target(blind, 2) > 2


def test_drained_counts_draining_workers_leases(tmp_path):
    """A draining worker still holds its last probed leases until it
    is reaped — _drained must not ignore them just because the worker
    no longer counts toward capacity."""
    sup = make_supervisor(tmp_path, [IDLE])
    sup.probing = True
    sup.step()  # spawn
    sup.step()  # probe live
    worker = sup.workers[0]
    worker.inflight_leases = 1
    worker.state = "draining"
    blind = {"pending": 0, "inflight": None, "dead": None,
             "receives": None}
    assert not sup._drained(blind)
    worker.inflight_leases = 0
    assert sup._drained(blind)


def test_crash_loop_backs_off_respawns(tmp_path):
    """Workers dying instantly (poisoned image / broken mount) must not
    spin the host: after crash_limit deaths inside crash_window the
    supervisor stops respawning for crash_backoff seconds."""
    procs = [StubProc(die_immediately=True) for _ in range(10)]
    sup = make_supervisor(tmp_path, [DEEP], procs=procs, min_workers=1,
                          max_workers=2, crash_limit=3, crash_window=60.0,
                          crash_backoff=3600.0)
    for _ in range(8):
        sup.step()
    counters = telemetry.snapshot()["counters"]
    assert counters["fleet/crash_backoffs"] >= 1
    assert counters["fleet/worker_deaths"] >= 3
    # respawning stopped well short of the 2-per-step it would burn
    # without probation (2 spawned on each of the first two ticks,
    # then the backoff gate holds)
    assert counters["fleet/spawns"] <= 4


def test_spot_drill_preempts_one_live_worker(tmp_path):
    sup = make_supervisor(tmp_path, [DEEP], min_workers=2, max_workers=3,
                          seed=7)
    sup.step()  # spawn 2 (+1 scale-up -> 3)
    sup.step()  # probes mark them live
    sup.request_drill()
    sup.step()
    drilled = [w for w in sup.workers if w.drill]
    assert len(drilled) == 1
    assert signal.SIGTERM in drilled[0].proc.signals
    assert telemetry.snapshot()["counters"]["fleet/drill_preemptions"] == 1
    sup.step()  # reap (exit 143 is expected) + replace
    counters = telemetry.snapshot()["counters"]
    assert "fleet/worker_deaths" not in counters  # a drill is not a crash
    assert sum(1 for w in sup.workers if w.active) == sup.target


def test_static_mode_bypasses_controller(tmp_path, monkeypatch):
    """CHUNKFLOW_FLEET=0: fixed size, no telemetry-driven decisions —
    but replace-the-dead liveness stays."""
    monkeypatch.setenv("CHUNKFLOW_FLEET", "0")
    assert fleet_disabled()
    sup = make_supervisor(tmp_path, [DEEP], min_workers=2, max_workers=4)
    assert sup.static
    for _ in range(4):
        sup.step()
    assert sup.target == 2
    assert sum(1 for w in sup.workers if w.active) == 2
    counters = telemetry.snapshot()["counters"]
    assert "fleet/scale_up" not in counters
    assert "fleet/holds" not in counters
    # liveness: SIGKILL one, it is replaced at the static size
    sup.workers[0].proc.kill()
    sup.step()
    sup.step()
    assert sum(1 for w in sup.workers if w.active) == 2


def test_state_file_reports_exit_code_and_last_seen(tmp_path):
    sup = make_supervisor(tmp_path, [IDLE], min_workers=1, max_workers=2)
    sup.step()
    sup.step()  # probe marks it live (last_seen set)
    sup.workers[0].proc.kill()  # simulated external SIGKILL
    sup.step()  # reap + replace
    state = json.loads((tmp_path / "fleet-state.json").read_text())
    assert state["queue"] == "memory://fleet-stub"
    dead = [w for w in state["workers"] if w["state"] == "exited"]
    assert len(dead) == 1
    assert dead[0]["exit_code"] == -9
    assert dead[0]["last_seen"] is not None
    assert dead[0]["endpoint"].startswith("127.0.0.1:")
    live = [w for w in state["workers"] if w["state"] != "exited"]
    assert len(live) == 1 and live[0]["exit_code"] is None


def test_worker_handle_record_shape():
    w = WorkerHandle("fleet-w001", 12345, StubProc(), ["cmd"])
    rec = w.to_record()
    assert rec["worker"] == "fleet-w001"
    assert rec["state"] == "starting"
    assert rec["exit_code"] is None


def test_bounds_validation():
    with pytest.raises(ValueError, match="min_workers"):
        FleetSupervisor("memory://x", ["delete-task-in-queue"],
                        min_workers=3, max_workers=2)


def test_host_available_gb_readable():
    gb = host_available_gb()
    if gb is None:
        pytest.skip("no /proc/meminfo on this platform")
    assert gb > 0


def _fleet_events(sup):
    """Fleet events captured in the telemetry JSONL (events only hit
    disk when a sink is configured, so route through a temp dir)."""
    return sup._events


# capture fleet events without a JSONL sink: monkeypatch-free shim —
# telemetry.event is a no-op without a sink, so record via a tiny hook
@pytest.fixture(autouse=True)
def _capture_fleet_events(monkeypatch):
    events = []
    real_event = telemetry.event

    def recording_event(kind, name, **attrs):
        if kind == "fleet":
            events.append(dict(attrs, kind=kind, name=name))
        return real_event(kind, name, **attrs)

    monkeypatch.setattr(telemetry, "event", recording_event)
    # expose on every supervisor created in the test
    real_init = FleetSupervisor.__init__

    def patched_init(self, *a, **kw):
        real_init(self, *a, **kw)
        self._events = events

    monkeypatch.setattr(FleetSupervisor, "__init__", patched_init)
    yield


# ---------------------------------------------------------------------------
# real multi-process runs
# ---------------------------------------------------------------------------
def _seed_volume(tmp_path, tag, grid=(3, 2, 2), seed=11):
    """``prod(grid)`` distinct random input chunks + a file queue
    holding their bboxes (file://, so real worker subprocesses share
    it)."""
    from chunkflow_tpu.chunk import Chunk

    in_dir = tmp_path / f"in-{tag}"
    in_dir.mkdir()
    rng = np.random.default_rng(seed)
    bodies, chunks = [], {}
    for zi, yi, xi in itertools.product(*(range(g) for g in grid)):
        off = (zi * 8, yi * 16, xi * 16)
        c = Chunk(rng.random((8, 16, 16)).astype(np.float32),
                  voxel_offset=off)
        c.to_h5(str(in_dir) + "/")
        bodies.append(c.bbox.string)
        chunks[c.bbox.string] = c
    qdir = str(tmp_path / f"q-{tag}")
    open_queue(qdir).send_messages(bodies)
    return qdir, in_dir, bodies


def _pipeline_args(in_dir, out_dir, slow_plugin=None):
    args = ["load-h5", "-f", str(in_dir) + "/"]
    if slow_plugin is not None:
        # a deterministic per-task delay: keeps the run alive long
        # enough to kill workers genuinely mid-volume on any box
        args += ["plugin", "--name", str(slow_plugin)]
    args += [
        "inference", "-s", "4", "8", "8", "-v", "1", "2", "2",
        "-c", "1", "-f", "identity", "--no-crop-output-margin",
        "--async-depth", "2",
        "save-h5", "--file-name", str(out_dir) + "/",
        "delete-task-in-queue",
    ]
    return args


def _worker_args(qdir, ledger, in_dir, out_dir, *, vis=4, retry_times=10,
                 poll=0.25, slow_plugin=None):
    # drain-session workers (parallel/fleet.py): a moderate empty-poll
    # budget so an idle worker flushes its buffered pipeline tail,
    # acks, and exits 0 — the supervisor respawns sessions while it
    # still owes the target size
    return [
        "fetch-task-from-queue", "-q", qdir, "-v", str(vis),
        "-r", str(retry_times), "--poll-interval", str(poll),
        "--max-retries", "50",
        "--lease-renew", "1.0", "--backoff-base", "0.01",
        "--backoff-cap", "0.1", "--ledger", str(ledger),
    ] + _pipeline_args(in_dir, out_dir, slow_plugin)


def _reference_outputs(tmp_path, tag, grid=(3, 2, 2), seed=11):
    """Fault-free single-process reference leg (in-process CLI)."""
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main

    qdir, in_dir, bodies = _seed_volume(
        tmp_path, f"{tag}-ref", grid=grid, seed=seed)
    out_dir = tmp_path / f"out-{tag}-ref"
    out_dir.mkdir()
    args = ["fetch-task-from-queue", "-q", qdir, "-r", "2",
            ] + _pipeline_args(in_dir, out_dir)
    result = CliRunner().invoke(main, args, catch_exceptions=False)
    assert result.exit_code == 0, result.output
    telemetry.reset()  # the reference leg's counters are not the fleet's
    return _load_outputs(out_dir), bodies


def _load_outputs(out_dir):
    import h5py

    outputs = {}
    for path in sorted(out_dir.iterdir()):
        with h5py.File(path, "r") as f:
            outputs[path.name] = np.asarray(f["main"][:])
    return outputs


def _slow_plugin(tmp_path, seconds=0.25):
    path = tmp_path / "slow_identity.py"
    path.write_text(
        "import time\n\n\n"
        f"def execute(chunk):\n    time.sleep({seconds})\n"
        "    return chunk\n"
    )
    return path


def _wait_for(cond, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out after {timeout}s waiting for {msg}")


def _committed_per_trace(metrics_dir):
    from chunkflow_tpu.flow.log_summary import load_telemetry_dir

    events = load_telemetry_dir(str(metrics_dir))
    commits = {}
    for e in events:
        if e.get("name") == "lifecycle/committed" and e.get("trace_id"):
            commits[e["trace_id"]] = commits.get(e["trace_id"], 0) + 1
    return events, commits


def test_multiprocess_sigkill_crash_recovery(tmp_path):
    """ISSUE 7 satellite: a REAL worker subprocess is SIGKILLed
    mid-task (chaos ``action=kill`` at op/save-h5 — true process death,
    nothing unwinds), its lease expires, a second worker completes the
    task exactly once, and the cross-worker hop reconstructs from the
    merged JSONL alone."""
    mdir = tmp_path / "metrics"
    reference, _ = _reference_outputs(tmp_path, "cr", grid=(2, 2, 1),
                                      seed=5)
    qdir, in_dir, bodies = _seed_volume(tmp_path, "cr", grid=(2, 2, 1),
                                        seed=5)
    out_dir = tmp_path / "out-cr"
    out_dir.mkdir()
    ledger = tmp_path / "ledger-cr"
    cli = [sys.executable, "-m", "chunkflow_tpu.flow.cli",
           "--metrics-dir", str(mdir)]
    # B's poll budget (12 x 0.5s) must outlast A's lease expiry (2s)
    args = _worker_args(qdir, ledger, in_dir, out_dir, vis=2,
                        retry_times=12, poll=0.5)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    base_env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        PYTHONPATH=root + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    base_env.pop("XLA_FLAGS", None)

    # worker A: self-SIGKILLs at its first save-h5 — mid-task by
    # construction (the task is claimed, computed, not yet written)
    env_a = dict(base_env, CHUNKFLOW_WORKER_ID="mp-a",
                 CHUNKFLOW_CHAOS="once=op/save-h5:action=kill")
    proc_a = subprocess.run(cli + args, env=env_a, capture_output=True,
                            text=True, timeout=180)
    assert proc_a.returncode in (-9, 137), (
        proc_a.returncode, proc_a.stdout[-2000:], proc_a.stderr[-2000:])
    assert len(FileLedger(str(ledger)).keys()) < len(bodies)

    # worker B: a clean worker drains the rest; the dead claim expires
    # (visibility 2s) and is janitored back to pending on B's receive
    env_b = dict(base_env, CHUNKFLOW_WORKER_ID="mp-b")
    proc_b = subprocess.run(cli + args, env=env_b, capture_output=True,
                            text=True, timeout=180)
    assert proc_b.returncode == 0, (
        proc_b.stdout[-2000:], proc_b.stderr[-2000:])

    # the volume converged bit-identically, exactly one marker per bbox
    assert sorted(FileLedger(str(ledger)).keys()) == sorted(bodies)
    outputs = _load_outputs(out_dir)
    assert sorted(outputs) == sorted(reference)
    for name in reference:
        assert np.array_equal(outputs[name], reference[name]), name
    queue = open_queue(qdir)
    assert queue.stats()["pending"] == 0
    assert queue.stats()["inflight"] == 0
    assert queue.dead_letters() == []

    # the hop reconstructs from merged JSONL alone: some trace was
    # claimed by BOTH workers (A died holding it), committed exactly
    # once — by B; and every commit fleet-wide is exactly-once
    events, commits = _committed_per_trace(mdir)
    assert len(commits) == len(bodies)
    assert set(commits.values()) == {1}

    def claim_workers(trace_id):
        return {e["worker"] for e in events
                if e.get("trace_id") == trace_id
                and e.get("name") == "lifecycle/claimed"}

    hops = [t for t in commits if {"mp-a", "mp-b"} <= claim_workers(t)]
    assert hops, "no task hopped from the SIGKILLed worker to the survivor"
    for t in hops:
        committed_by = [e["worker"] for e in events
                        if e.get("trace_id") == t
                        and e.get("name") == "lifecycle/committed"]
        assert committed_by == ["mp-b"]


def test_fleet_chaos_acceptance(tmp_path):
    """ISSUE 7 acceptance: a supervisor-managed multi-process run over
    a 16-task volume (+1 deliberate poison task) with two workers
    SIGKILLed mid-volume and one spot-drill preemption. The supervisor
    replaces them; the output is bit-identical to the fault-free
    reference, the ledger holds exactly one marker per bbox, only the
    poison task dead-letters, and the supervisor ends with the target
    worker count alive."""
    mdir = tmp_path / "metrics"
    mdir.mkdir()
    reference, _ = _reference_outputs(tmp_path, "fa", grid=(4, 2, 2))
    telemetry.configure(str(mdir))

    qdir, in_dir, bodies = _seed_volume(tmp_path, "fa", grid=(4, 2, 2))
    open_queue(qdir).send_messages(["NOT_A_BBOX"])  # the poison task
    out_dir = tmp_path / "out-fa"
    out_dir.mkdir()
    ledger_dir = tmp_path / "ledger-fa"
    slow = _slow_plugin(tmp_path, seconds=0.4)

    sup = FleetSupervisor(
        qdir,
        _worker_args(qdir, ledger_dir, in_dir, out_dir, vis=4,
                     slow_plugin=slow),
        min_workers=2, max_workers=3, interval=0.5,
        scale_up_backlog=2.0, idle_ticks=2, probe_misses=6,
        probe_timeout=2.0, startup_grace=90.0, term_grace=20.0,
        crash_limit=5, crash_window=30.0,
        metrics_dir=str(mdir), seed=3, visibility_timeout=4.0,
        worker_env={"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""},
    )
    result = {}
    # idle_ticks (2) < settle_ticks (4): the idle-drain decision fires
    # before the run declares itself drained, so the fleet is back at
    # min size when run() returns
    thread = threading.Thread(
        target=lambda: result.update(
            sup.run(max_runtime=300.0, settle_ticks=4,
                    shutdown_on_drain=False)),
        daemon=True,
    )
    ledger = FileLedger(str(ledger_dir))
    killed = []
    try:
        thread.start()

        def live_pids():
            return [w.proc.pid for w in sup.workers
                    if w.active and w.proc.poll() is None
                    and w.proc.pid not in killed]

        # first SIGKILL: mid-volume (some tasks done, most remaining)
        _wait_for(lambda: len(ledger.keys()) >= 2 and live_pids(),
                  180, "first commits + live workers")
        assert len(ledger.keys()) < len(bodies)
        victim = live_pids()[0]
        os.kill(victim, signal.SIGKILL)
        killed.append(victim)

        # second SIGKILL, later in the volume, still mid-run
        _wait_for(lambda: len(ledger.keys()) >= 6 and live_pids(),
                  180, "mid-volume commits + live workers")
        victim = live_pids()[0]
        os.kill(victim, signal.SIGKILL)
        killed.append(victim)

        # one spot-drill preemption through the SIGTERM contract
        sup.request_drill()

        thread.join(timeout=300)
        assert not thread.is_alive(), "fleet run did not converge"
    finally:
        sup.stop()
        thread.join(timeout=30)
        if not result:
            sup.shutdown()

    assert len(killed) >= 2, killed  # two workers truly SIGKILLed
    assert result["drill_preemptions"] >= 1
    assert result["worker_deaths"] >= len(killed)
    assert result["scale_ups"] >= 1  # the deep queue scaled the fleet

    # ISSUE 7 acceptance: the supervisor ends with the target worker
    # count alive (drained back to min by the idle queue)
    assert result["alive"] == sup.target
    assert sup.target == sup.min_workers

    # bit-identical convergence, exactly one ledger marker per bbox
    outputs = _load_outputs(out_dir)
    assert sorted(outputs) == sorted(reference)
    for name in reference:
        assert np.array_equal(outputs[name], reference[name]), name
    assert sorted(ledger.keys()) == sorted(bodies)

    # only the deliberate poison task dead-lettered, with its reason
    queue = open_queue(qdir)
    stats = queue.stats()
    assert stats["pending"] == 0 and stats["inflight"] == 0
    dead = queue.dead_letters()
    assert len(dead) == 1, dead
    assert dead[0]["body"] == "NOT_A_BBOX"
    assert "ValueError" in dead[0]["reason"]

    # exactly-once across the whole fleet, from merged JSONL alone
    _, commits = _committed_per_trace(mdir)
    assert len(commits) == len(bodies)
    assert set(commits.values()) == {1}

    sup.shutdown()
    assert all(not w.running for w in sup.workers)
    # the state file survives for post-mortem fleet-status
    state = json.loads((mdir / "fleet-state.json").read_text())
    assert any(w["exit_code"] not in (None, 0) for w in state["workers"])


def test_fleet_run_cli_and_fleet_status(tmp_path):
    """The operational surface: `chunkflow fleet-run` drains a volume
    end-to-end and leaves a state file that `fleet-status` renders —
    including exit codes and last-seen times for dead workers."""
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main

    mdir = tmp_path / "metrics"
    qdir, in_dir, bodies = _seed_volume(tmp_path, "cli", grid=(2, 2, 1),
                                        seed=3)
    out_dir = tmp_path / "out-cli"
    out_dir.mkdir()
    pipeline = (
        f"load-h5 -f {in_dir}/ "
        "inference -s 4 8 8 -v 1 2 2 -c 1 -f identity "
        "--no-crop-output-margin --async-depth 2 "
        f"save-h5 --file-name {out_dir}/ delete-task-in-queue"
    )
    result = CliRunner().invoke(
        main,
        ["--metrics-dir", str(mdir), "fleet-run", "-q", qdir,
         "--min-workers", "1", "--max-workers", "2",
         "--interval", "0.5", "--idle-ticks", "2",
         "-v", "10", "-r", "6", "--poll-interval", "0.25",
         "--ledger", str(tmp_path / "ledger-cli"),
         "--max-runtime", "180", "-w", pipeline],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "fleet drained:" in result.output
    assert "fleet state:" in result.output
    assert sorted(_load_outputs(out_dir)) == sorted(
        f"{b}.h5" for b in bodies)
    assert (mdir / "fleet-state.json").exists()

    # fleet-status picks the state file up via --metrics-dir and gives
    # the shut-down workers a post-mortem, not a bare "unreachable"
    status = CliRunner().invoke(
        main,
        ["--metrics-dir", str(mdir), "fleet-status", "-q", qdir],
        catch_exceptions=False,
    )
    assert status.exit_code == 0, status.output
    assert "pending=0" in status.output
    assert "exited, exit code" in status.output
    assert "last seen" in status.output


def test_fleet_status_enriches_unreachable_from_state(tmp_path):
    """Satellite: an unreachable-but-supposedly-live worker reports its
    state and last-seen age from the fleet state file."""
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main

    MemoryQueue._registry.pop("fs-enrich", None)
    MemoryQueue.open("fs-enrich")
    state = {
        "t": time.time(), "queue": "memory://fs-enrich", "static": False,
        "target": 2, "min_workers": 1, "max_workers": 3,
        "workers": [
            {"worker": "fleet-w001", "pid": 1, "port": 1,
             "endpoint": "127.0.0.1:1", "state": "live",
             "started": time.time() - 60,
             "last_seen": time.time() - 12.5, "exit_code": None,
             "inflight_leases": 1},
            {"worker": "fleet-w002", "pid": 2, "port": 2,
             "endpoint": "127.0.0.1:2", "state": "exited",
             "started": time.time() - 60,
             "last_seen": time.time() - 30.0, "exit_code": -9,
             "inflight_leases": 0},
        ],
    }
    state_path = tmp_path / "fleet-state.json"
    state_path.write_text(json.dumps(state))
    result = CliRunner().invoke(
        main,
        ["fleet-status", "-q", "memory://fs-enrich",
         "--fleet-state", str(state_path), "--timeout", "0.2"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "target=2 elastic [1..3]" in result.output
    # the live-per-state but unreachable worker: state + last-seen age
    assert "[fleet-w001]: unreachable" in result.output
    assert "state=live" in result.output and "s ago" in result.output
    # the reaped worker: exit code with signal decode, no scrape
    assert "[fleet-w002]: exited, exit code -9 (signal 9)" \
        in result.output


def test_fleet_static_mode_bit_identical_run(tmp_path, monkeypatch):
    """ISSUE 7 acceptance: CHUNKFLOW_FLEET=0 bypasses the controller
    bit-identically — a real static-size fleet drains the same volume
    to the same bytes with zero scale decisions."""
    monkeypatch.setenv("CHUNKFLOW_FLEET", "0")
    mdir = tmp_path / "metrics"
    mdir.mkdir()
    reference, _ = _reference_outputs(tmp_path, "st", grid=(2, 2, 1),
                                      seed=9)
    qdir, in_dir, bodies = _seed_volume(tmp_path, "st", grid=(2, 2, 1),
                                        seed=9)
    out_dir = tmp_path / "out-st"
    out_dir.mkdir()
    sup = FleetSupervisor(
        qdir,
        _worker_args(qdir, tmp_path / "ledger-st", in_dir, out_dir,
                     vis=30),
        min_workers=2, max_workers=4, interval=0.5, idle_ticks=3,
        startup_grace=90.0, term_grace=20.0, metrics_dir=str(mdir),
        visibility_timeout=30.0,
        worker_env={"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""},
    )
    assert sup.static
    result = sup.run(max_runtime=240.0, settle_ticks=3)
    assert result["static"] is True
    assert result["scale_ups"] == 0 and result["scale_downs"] == 0
    assert result["holds"] == 0
    assert sup.target == 2

    outputs = _load_outputs(out_dir)
    assert sorted(outputs) == sorted(reference)
    for name in reference:
        assert np.array_equal(outputs[name], reference[name]), name
    assert open_queue(qdir).stats()["pending"] == 0


# ---------------------------------------------------------------------------
# SLO annotation on scale/hold decisions (ISSUE 12 — annotation only)
# ---------------------------------------------------------------------------
def _slo_firing_scrape(firing, phase=None, share=0.0):
    metrics = {f"chunkflow_slo_{name}_firing": 1.0 for name in firing}
    metrics["chunkflow_slo_availability_burn_rate"] = 20.0

    def scrape(endpoint):
        return {"endpoint": endpoint, "healthz": {"inflight_leases": 1},
                "metrics": metrics,
                "dominant_stall": ({"phase": phase, "share": share}
                                   if phase else None),
                "slo_firing": sorted(firing), "error": None}
    return scrape


def test_hold_events_annotated_with_firing_alerts(tmp_path):
    """A scale-up HOLD while SLO alerts fire carries the firing
    objective names — annotation only (no policy change in this PR),
    but the ops timeline shows what was out of spec at the decision."""
    sup = make_supervisor(
        tmp_path, [IDLE, DEEP],
        scrape=_slo_firing_scrape({"availability", "latency"},
                                  phase="scheduler/write", share=0.8))
    for _ in range(3):
        sup.step()
    assert sup.target == 1  # storage-bound: held
    holds = [e for e in _fleet_events(sup) if e["name"] == "fleet/hold"]
    assert holds
    assert holds[-1]["slo_firing"] == ["availability", "latency"]


def test_scale_events_annotated_with_firing_alerts(tmp_path):
    sup = make_supervisor(
        tmp_path, [IDLE, DEEP],
        scrape=_slo_firing_scrape({"latency"}, phase="pipeline/compute",
                                  share=0.9))
    sup.step()  # spawn the min worker
    sup.step()  # compute-bound + deep queue -> scale up
    assert sup.target == 2
    scales = [e for e in _fleet_events(sup)
              if e["name"] == "fleet/scale"]
    assert scales and scales[-1]["direction"] == "up"
    assert scales[-1]["slo_firing"] == ["latency"]


def test_decisions_without_firing_alerts_stay_unannotated(tmp_path):
    sup = make_supervisor(tmp_path, [DEEP])
    sup.step()
    scales = [e for e in _fleet_events(sup)
              if e["name"] == "fleet/scale"]
    assert scales
    assert "slo_firing" not in scales[-1]  # no noise on clean fleets
