"""ISSUE 6 acceptance: fleet observability over a chaos-accented run.

A multi-task volume drains through the supervised lifecycle across TWO
worker identities sharing one queue, with one injected mid-write kill
(chaos at op/save-h5 — the classic worker-death-between-write-and-ack
model) and one poison task. One task's input is missing during worker
A's tenure, so its claim provably hops workers: A claims it, fails,
and B — started after the input appears — retries and commits it.

From the merged JSONL alone (no registry, no queue state) the test then
reconstructs every task's full trace — submit → claim(s) → retry hop →
commit or dead-letter — with one consistent trace_id per task across
both workers, and checks that ``log-summary --fleet`` reports
per-worker stall shares and retry counts matching each worker's live
registry counters captured at exit.
"""
import itertools

import numpy as np
import pytest
from click.testing import CliRunner

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.flow.log_summary import (
    load_telemetry_dir,
    summarize_fleet,
    trace_timeline,
)
from chunkflow_tpu.parallel.lifecycle import FileLedger
from chunkflow_tpu.parallel.queues import MemoryQueue
from chunkflow_tpu.testing import chaos

QUEUE = "memory://fleet-acceptance"


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    MemoryQueue._registry.pop("fleet-acceptance", None)
    telemetry.reset()
    chaos.reset()
    yield
    MemoryQueue._registry.pop("fleet-acceptance", None)
    telemetry.reset()
    chaos.reset()


def _seed(tmp_path):
    """8 task bboxes (7 inputs on disk, one — the hopper — deliberately
    missing) + 1 poison body, FIFO-queued hopper-first so worker A is
    guaranteed to claim and fail it."""
    from chunkflow_tpu.chunk import Chunk
    from chunkflow_tpu.parallel.queues import open_queue

    in_dir = tmp_path / "in"
    in_dir.mkdir()
    rng = np.random.default_rng(7)
    chunks, bodies = {}, []
    for zi, yi, xi in itertools.product(range(2), range(2), range(2)):
        off = (zi * 8, yi * 16, xi * 16)
        c = Chunk(rng.random((8, 16, 16)).astype(np.float32),
                  voxel_offset=off)
        bodies.append(c.bbox.string)
        chunks[c.bbox.string] = c
    hopper = bodies[0]
    for body in bodies[1:]:
        chunks[body].to_h5(str(in_dir) + "/")
    # MemoryQueue delivers FIFO: the hopper is claimed first
    queue = open_queue(QUEUE)
    queue.retry_sleep = 0.01
    queue.send_messages([hopper] + bodies[1:] + ["NOT_A_BBOX"])
    return in_dir, bodies, hopper, chunks


def _run_worker(tmp_path, worker, metrics_dir, in_dir, num=None):
    from chunkflow_tpu.flow.cli import main

    out_dir = tmp_path / "out"
    out_dir.mkdir(exist_ok=True)
    args = [
        "--metrics-dir", str(metrics_dir),
        "fetch-task-from-queue", "-q", QUEUE, "-r", "20",
        "--max-retries", "50", "--lease-renew", "0.25",
        "--backoff-base", "0.01", "--backoff-cap", "0.05",
        "--ledger", str(tmp_path / "ledger"),
    ]
    if num is not None:
        args += ["--num", str(num)]
    args += [
        "load-h5", "-f", str(in_dir) + "/",
        "inference", "-s", "4", "8", "8", "-v", "1", "2", "2",
        "-c", "1", "-f", "identity", "--no-crop-output-margin",
        "--async-depth", "2",
        "save-h5", "--file-name", str(out_dir) + "/",
        "delete-task-in-queue",
    ]
    result = CliRunner().invoke(main, args, catch_exceptions=False)
    assert result.exit_code == 0, result.output
    # capture this worker's live registry counters before anything
    # resets them — the --fleet report must agree with these
    return out_dir, dict(telemetry.snapshot()["counters"])


def test_fleet_trace_reconstruction(tmp_path, monkeypatch):
    metrics_dir = tmp_path / "metrics"
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_SNAPSHOT_EVERY", "2")

    # -- submit (the test process is the submitter worker) --------------
    telemetry.configure(str(metrics_dir))
    in_dir, bodies, hopper, chunks = _seed(tmp_path)
    telemetry.flush()

    # -- worker A: chaos mid-write kill, bounded tenure ------------------
    monkeypatch.setenv("CHUNKFLOW_WORKER_ID", "worker-a")
    chaos.configure("once=op/save-h5")
    try:
        _, counters_a = _run_worker(
            tmp_path, "worker-a", metrics_dir, in_dir, num=5)
        injected = chaos.injections()
    finally:
        chaos.reset()
    assert injected.get("op/save-h5", 0) == 1  # the injected worker kill

    # -- the hopper's input appears; worker B drains the rest ------------
    chunks[hopper].to_h5(str(in_dir) + "/")
    monkeypatch.setenv("CHUNKFLOW_WORKER_ID", "worker-b")
    out_dir, counters_b = _run_worker(
        tmp_path, "worker-b", metrics_dir, in_dir)

    # -- the run converged: every bbox written + ledgered, only the
    #    poison dead-lettered ---------------------------------------------
    from chunkflow_tpu.parallel.queues import open_queue

    queue = open_queue(QUEUE)
    assert len(queue) == 0 and not queue.invisible
    assert sorted(FileLedger(str(tmp_path / "ledger")).keys()) \
        == sorted(bodies)
    outputs = sorted(p.name for p in out_dir.iterdir())
    assert len(outputs) == 8
    dead = queue.dead_letters()
    assert len(dead) == 1
    assert dead[0]["body"] == "NOT_A_BBOX"
    assert "ValueError" in dead[0]["reason"]

    # -- reconstruct every task's trace from merged JSONL alone ----------
    events = load_telemetry_dir(str(metrics_dir))
    submits = {e["body"]: e["trace_id"] for e in events
               if e.get("name") == "queue/submit"}
    assert sorted(submits) == sorted(bodies + ["NOT_A_BBOX"])
    assert len(set(submits.values())) == 9  # one distinct trace per task

    def timeline(body):
        return trace_timeline(events, submits[body])

    for body in bodies:
        tl = timeline(body)
        names = [e["name"] for e in tl]
        assert names[0] == "queue/submit"
        assert "lifecycle/claimed" in names
        assert names.count("lifecycle/committed") == 1  # exactly-once
        assert all(e["trace_id"] == submits[body]
                   for e in tl if e.get("trace_id"))
        # commit follows the last claim in time order
        assert names.index("lifecycle/committed") \
            > names.index("lifecycle/claimed")

    # the hopper's trace spans BOTH workers: claimed + failed on A,
    # retried, re-claimed and committed on B — one trace id throughout
    tl = timeline(hopper)
    claim_workers = [e["worker"] for e in tl
                     if e["name"] == "lifecycle/claimed"]
    assert "worker-a" in claim_workers and "worker-b" in claim_workers
    assert any(e["name"] == "lifecycle/retry" for e in tl)
    committed = [e for e in tl if e["name"] == "lifecycle/committed"]
    assert [e["worker"] for e in committed] == ["worker-b"]

    # the chaos-killed save retried somewhere: at least one retry event
    # beyond the hopper's exists in the stream
    retries = [e for e in events if e.get("name") == "lifecycle/retry"]
    assert any(e["trace_id"] != submits[hopper] for e in retries)

    # the poison task's trace ends in a dead-letter with its reason
    tl = timeline("NOT_A_BBOX")
    dead_events = [e for e in tl if e["name"] == "lifecycle/dead_letter"]
    assert len(dead_events) == 1
    assert "ValueError" in dead_events[0]["reason"]
    assert not any(e["name"] == "lifecycle/committed" for e in tl)

    # -- --fleet agrees with each worker's live registry ------------------
    fleet = summarize_fleet(events)
    assert "worker-a" in fleet and "worker-b" in fleet
    for worker, counters in (("worker-a", counters_a),
                             ("worker-b", counters_b)):
        info = fleet[worker]
        assert info["retries"] == counters.get("tasks/retried", 0)
        assert info["committed"] == counters.get("tasks/committed", 0)
        assert info["ledger_skips"] == counters.get("ledger/skips", 0)
        # stall attribution present per worker, shares summing to 1
        assert info["stall"], worker
        assert sum(s["share"] for s in info["stall"].values()) \
            == pytest.approx(1.0)
    assert fleet["worker-a"]["retries"] >= 1  # chaos and/or hopper
    # every pipelined task commits exactly once fleet-wide
    assert fleet["worker-a"]["committed"] \
        + fleet["worker-b"]["committed"] == 8

    # -- and the CLI renders it -------------------------------------------
    from chunkflow_tpu.flow.cli import main

    result = CliRunner().invoke(
        main,
        ["log-summary", "--metrics-dir", str(metrics_dir), "--fleet",
         "--trace-id", submits[hopper]],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "worker worker-a:" in result.output
    assert "worker worker-b:" in result.output
    assert f"trace {submits[hopper]}:" in result.output
    assert "lifecycle/committed" in result.output

    # -- ISSUE 18 acceptance: the same chaos run exports as a
    #    schema-valid Chrome trace — one process per worker identity,
    #    the hopper's worker hop as a paired cross-worker flow ----------
    import json

    from tools.trace_export import (
        export_metrics_dir,
        validate_chrome_trace,
    )

    trace_path = tmp_path / "fleet-trace.json"
    stats = export_metrics_dir(str(metrics_dir), str(trace_path))
    assert stats["problems"] == []
    trace = json.loads(trace_path.read_text())
    assert validate_chrome_trace(trace) == []
    procs = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    # submitter + worker-a + worker-b
    assert {"worker worker-a", "worker worker-b"} <= procs
    assert len(procs) >= 2
    assert stats["flow_pairs"] >= 1
    # the hopper's flow specifically: its submit started a flow that
    # finishes on a DIFFERENT process (the claim hopped workers)
    flow_events = [e for e in trace["traceEvents"]
                   if e.get("ph") in ("s", "t", "f")
                   and e["args"]["trace_id"] == submits[hopper]]
    assert {e["ph"] for e in flow_events} >= {"s", "f"}
    start = next(e for e in flow_events if e["ph"] == "s")
    finish = [e for e in flow_events if e["ph"] == "f"][-1]
    assert start["pid"] != finish["pid"]
    assert finish["ts"] >= start["ts"]

    # the CLI flag drives the same exporter
    result = CliRunner().invoke(
        main,
        ["log-summary", "--metrics-dir", str(metrics_dir),
         "--export-trace", str(tmp_path / "cli-trace.json")],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "cross-worker flow(s)" in result.output
    assert "trace validation:" not in result.output
