"""SpatialTaskTree + GlobalIdAllocator + coordination HTTP service
(reference distributed/restapi/ prototypes, completed and testable)."""
import json
import threading
import urllib.request

import pytest

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.parallel.restapi import CoordinationService, serve
from chunkflow_tpu.parallel.task_tree import (
    DONE, READY, GlobalIdAllocator, SpatialTaskTree,
)


def test_tree_decomposition_covers_volume():
    tree = SpatialTaskTree(BoundingBox((0, 0, 0), (4, 64, 64)), (4, 32, 32))
    leaves = tree.leaf_list
    assert len(leaves) == 4
    total = sum(
        int((l.bbox.stop[0] - l.bbox.start[0])
            * (l.bbox.stop[1] - l.bbox.start[1])
            * (l.bbox.stop[2] - l.bbox.start[2]))
        for l in leaves
    )
    assert total == 4 * 64 * 64


def test_merge_scheduling_order():
    tree = SpatialTaskTree(BoundingBox((0, 0, 0), (4, 64, 32)), (4, 32, 32))
    # two leaves + one root merge
    first = tree.next_ready_task()
    second = tree.next_ready_task()
    assert first.is_leaf and second.is_leaf
    # root not runnable until children done
    assert tree.next_ready_task() is None
    first.set_state_done()
    second.set_state_done()
    merge = tree.next_ready_task()
    assert merge is tree and not merge.is_leaf
    merge.set_state_done()
    assert tree.all_done


def test_auto_propagate_matches_reference_semantics():
    tree = SpatialTaskTree(BoundingBox((0, 0, 0), (4, 64, 32)), (4, 32, 32))
    for leaf in tree.leaf_list:
        leaf.set_state_done(auto_propagate=True)
    assert tree.is_done


def test_json_roundtrip_preserves_states():
    tree = SpatialTaskTree(BoundingBox((0, 0, 0), (4, 64, 64)), (4, 32, 32))
    node = tree.next_ready_task()
    node.set_state_done()
    back = SpatialTaskTree.from_json(tree.json)
    assert back.bbox == tree.bbox
    states = [n.state for n in back.walk()]
    assert DONE in states and READY in states


def test_global_id_allocator_disjoint_ranges():
    alloc = GlobalIdAllocator(100)
    results = []

    def worker():
        for _ in range(50):
            results.append((alloc.allocate(7), 7))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = sorted(results)
    for (a, n), (b, _) in zip(spans, spans[1:]):
        assert a + n <= b, "overlapping id ranges"
    assert alloc.watermark == 100 + 4 * 50 * 7


def test_http_service_end_to_end():
    tree = SpatialTaskTree(BoundingBox((0, 0, 0), (4, 64, 32)), (4, 32, 32))
    service = CoordinationService(id_start=1000, task_tree=tree)
    server, thread = serve(service, host="127.0.0.1", port=0, background=True)
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(f"{base}/objids/5") as r:
            assert json.load(r)["base_id"] == 1000
        with urllib.request.urlopen(f"{base}/objids/5") as r:
            assert json.load(r)["base_id"] == 1005

        claimed = []
        while True:
            with urllib.request.urlopen(f"{base}/task") as r:
                if r.status == 204:
                    break
                claimed.append(json.load(r)["bbox"])
        assert len(claimed) == 2  # the two leaves
        for bbox_str in claimed:
            req = urllib.request.Request(
                f"{base}/task/{bbox_str}/done", method="POST"
            )
            with urllib.request.urlopen(req) as r:
                json.load(r)
        # now the root merge is claimable
        with urllib.request.urlopen(f"{base}/task") as r:
            assert r.status == 200
            root_bbox = json.load(r)["bbox"]
        req = urllib.request.Request(
            f"{base}/task/{root_bbox}/done", method="POST"
        )
        with urllib.request.urlopen(req) as r:
            assert json.load(r)["all_done"]
    finally:
        server.shutdown()
        thread.join(timeout=5)
