"""Spatially-sharded (halo-exchange) inference: identity oracle across
chip boundaries on the 8-device virtual CPU mesh."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.inference import engines
from chunkflow_tpu.parallel.distributed import make_mesh
from chunkflow_tpu.parallel.spatial import spatial_sharded_inference


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (see tests/conftest.py)")
    return make_mesh(8)


def test_spatial_identity_oracle(mesh):
    """Same-size patches: output must equal the input everywhere, including
    across the slab boundaries served by halo + spill exchange."""
    rng = np.random.default_rng(0)
    chunk = rng.random((8, 128, 32)).astype(np.float32)
    patch = (4, 16, 16)
    engine = engines.create_identity_engine(
        input_patch_size=patch,
        output_patch_size=patch,
        num_input_channels=1,
        num_output_channels=1,
    )
    out = spatial_sharded_inference(
        chunk,
        engine,
        input_patch_size=patch,
        output_patch_size=patch,
        output_patch_overlap=(2, 8, 8),
        batch_size=2,
        mesh=mesh,
    )
    arr = np.asarray(out)
    assert arr.shape == (1, 8, 128, 32)
    np.testing.assert_allclose(arr[0], chunk, atol=1e-5)


@pytest.mark.parametrize("y", [100, 120, 130])
def test_spatial_identity_non_divisible_y(mesh, y):
    """Arbitrary chunk heights: y is padded to an even device split and
    cropped back, so the oracle holds for shapes that don't divide by 8
    (reference decomposes arbitrary sizes, cartesian_coordinate.py:316-347)."""
    rng = np.random.default_rng(2)
    chunk = rng.random((8, y, 32)).astype(np.float32)
    patch = (4, 16, 16)
    engine = engines.create_identity_engine(
        input_patch_size=patch,
        output_patch_size=patch,
        num_input_channels=1,
        num_output_channels=1,
    )
    out = spatial_sharded_inference(
        chunk,
        engine,
        input_patch_size=patch,
        output_patch_size=patch,
        output_patch_overlap=(2, 8, 8),
        batch_size=2,
        mesh=mesh,
    )
    arr = np.asarray(out)
    assert arr.shape == (1, 8, y, 32)
    np.testing.assert_allclose(arr[0], chunk, atol=1e-5)


def test_spatial_identity_with_crop_margin(mesh):
    """Smaller output patches: interior equals input, margin is zero."""
    rng = np.random.default_rng(1)
    chunk = rng.random((8, 128, 32)).astype(np.float32)
    pin, pout = (4, 16, 16), (2, 8, 8)
    engine = engines.create_identity_engine(
        input_patch_size=pin,
        output_patch_size=pout,
        num_input_channels=1,
        num_output_channels=1,
    )
    out = spatial_sharded_inference(
        chunk,
        engine,
        input_patch_size=pin,
        output_patch_size=pout,
        output_patch_overlap=(1, 4, 4),
        batch_size=2,
        mesh=mesh,
    )
    arr = np.asarray(out)[0]
    # margin = (pin - pout)//2 = (1, 4, 4): no predictions outside it
    np.testing.assert_allclose(
        arr[1:-1, 4:-4, 4:-4], chunk[1:-1, 4:-4, 4:-4], atol=1e-5
    )
    assert np.all(arr[0] == 0) and np.all(arr[-1] == 0)
    assert np.all(arr[:, :4] == 0) and np.all(arr[:, -4:] == 0)
    assert np.all(arr[:, :, :4] == 0) and np.all(arr[:, :, -4:] == 0)
