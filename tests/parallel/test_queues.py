import os
import time

import pytest

from chunkflow_tpu.parallel.queues import (
    FileQueue,
    MemoryQueue,
    SQSQueue,
    open_queue,
    unpack_task,
)


class TestMemoryQueue:
    def test_send_receive_delete(self):
        q = MemoryQueue("t1", visibility_timeout=100)
        q.send_messages(["a", "b", "c"])
        assert len(q) == 3
        handle, body = q.receive()
        assert body in {"a", "b", "c"}
        assert len(q) == 2  # claimed message is invisible
        q.delete(handle)
        bodies = {q.receive()[1], q.receive()[1]}
        assert len(bodies) == 2
        assert q.receive() is None

    def test_visibility_timeout_requeues(self):
        q = MemoryQueue("t2", visibility_timeout=0.05)
        q.send_messages(["task"])
        handle, _ = q.receive()
        assert q.receive() is None
        time.sleep(0.1)
        handle2, body = q.receive()  # crashed-worker task reappears
        assert body == "task"
        q.delete(handle2)
        time.sleep(0.1)
        assert q.receive() is None

    def test_iteration_drains(self):
        q = MemoryQueue("t3")
        q.retry_sleep = 0.01
        q.send_messages([str(i) for i in range(5)])
        seen = []
        for handle, body in q:
            seen.append(body)
            q.delete(handle)
        assert sorted(seen) == [str(i) for i in range(5)]


class TestFileQueue:
    def test_send_receive_delete(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q.send_messages(["0-4_0-4_0-4", "4-8_0-4_0-4"])
        assert len(q) == 2
        handle, body = q.receive()
        assert body.count("_") == 2
        assert len(q) == 1
        q.delete(handle)
        assert not os.path.exists(os.path.join(q.claimed_dir, handle))

    def test_crashed_worker_task_reappears(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=0.05)
        q.send_messages(["task"])
        q.receive()  # claim without ack = simulated crash
        assert len(q) == 0
        time.sleep(0.1)
        handle, body = q.receive()
        assert body == "task"

    def test_two_workers_no_double_claim(self, tmp_path):
        q1 = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q2 = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q1.send_messages(["a", "b"])
        r1 = q1.receive()
        r2 = q2.receive()
        assert r1[1] != r2[1]
        assert q1.receive() is None


def test_open_queue_schemes(tmp_path):
    assert isinstance(open_queue("memory://x"), MemoryQueue)
    assert isinstance(open_queue(str(tmp_path / "fq")), FileQueue)
    assert isinstance(open_queue("file://" + str(tmp_path / "fq2")), FileQueue)


# ---------------------------------------------------------------------------
# lifecycle protocol: renew / nack / receive counts / dead-letter
# (parallel/lifecycle.py rides these; docs/fault_tolerance.md)
# ---------------------------------------------------------------------------
class TestMemoryQueueLifecycle:
    def test_reopen_updates_visibility_timeout(self):
        """A reopen with a different timeout reconfigures the registered
        queue instead of silently keeping the first value (regression:
        MemoryQueue.open ignored the argument on reopen)."""
        q1 = MemoryQueue.open("reopen-vt", visibility_timeout=100)
        q2 = MemoryQueue.open("reopen-vt", visibility_timeout=0.05)
        assert q2 is q1
        assert q1.visibility_timeout == 0.05
        q1.send_messages(["task"])
        q1.receive()
        time.sleep(0.1)
        assert q1.receive() is not None  # the NEW timeout governs expiry

    def test_renew_extends_lease(self):
        q = MemoryQueue("renew", visibility_timeout=0.1)
        q.send_messages(["task"])
        handle, _ = q.receive()
        time.sleep(0.06)
        q.renew(handle)  # heartbeat: another 0.1s from now
        time.sleep(0.06)
        assert q.receive() is None  # still leased
        time.sleep(0.1)
        assert q.receive() is not None  # lease finally expired

    def test_renew_custom_timeout_is_backoff(self):
        q = MemoryQueue("renew2", visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        q.renew(handle, 0.05)  # re-claim for a short backoff window
        assert q.receive() is None
        time.sleep(0.1)
        assert q.receive() is not None

    def test_nack_releases_immediately(self):
        q = MemoryQueue("nack", visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        assert q.receive() is None
        q.nack(handle)
        handle2, body = q.receive()
        assert body == "task" and handle2 == handle

    def test_receive_count_accumulates_across_redeliveries(self):
        q = MemoryQueue("counts", visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        assert q.receive_count(handle) == 1
        # a crash-shaped redelivery (lease expiry) burns an attempt...
        wire, _deadline = q.invisible[handle]
        q.invisible[handle] = (wire, 0.0)
        handle, _ = q.receive()
        assert q.receive_count(handle) == 2
        # ...but a polite nack is a handback and refunds it
        q.nack(handle)
        handle, _ = q.receive()
        assert q.receive_count(handle) == 2
        q.delete(handle)
        assert q.receive_count(handle) == 0  # budget cleared with the ack

    def test_force_release_preserves_receive_count(self):
        """Supervisor force-release of a dead worker's lease is a
        crash-shaped handback: the receive count must keep accruing so
        a poison task that kills every worker still walks into the
        crash-loop bound instead of being redelivered forever."""
        q = MemoryQueue("force", visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        assert q.receive_count(handle) == 1
        assert q.force_release([handle]) == 1
        handle, _ = q.receive()
        assert q.receive_count(handle) == 2  # delivery accrued
        # the first-party refund path still exists for preemption
        assert q.nack(handle, refund=True) is True
        handle, _ = q.receive()
        assert q.receive_count(handle) == 2

    def test_force_release_counts_only_real_releases(self):
        """A nack on an already-acked/expired handle is a no-op and
        must not inflate the released count (fleet/leases_nacked)."""
        q = MemoryQueue("force-noop", visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        q.delete(handle)
        assert q.nack(handle) is False
        assert q.force_release([handle, "ghost"]) == 0

    def test_dead_letter_and_requeue(self):
        q = MemoryQueue("dead", visibility_timeout=100)
        q.send_messages(["poison"])
        handle, _ = q.receive()
        q.dead_letter(handle, reason="boom")
        assert len(q) == 0 and q.receive() is None
        entries = q.dead_letters()
        assert len(entries) == 1
        assert entries[0]["body"] == "poison"
        assert entries[0]["reason"] == "boom"
        assert entries[0]["receives"] == 1
        assert q.requeue_dead() == 1
        assert q.dead_letters() == []
        handle, body = q.receive()
        assert body == "poison"
        assert q.receive_count(handle) == 1  # fresh retry budget


class TestFileQueueLifecycle:
    def test_renew_extends_lease(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=0.1)
        q.send_messages(["task"])
        handle, _ = q.receive()
        time.sleep(0.06)
        q.renew(handle)
        time.sleep(0.06)
        assert q.receive() is None
        time.sleep(0.1)
        assert q.receive() is not None

    def test_nack_releases_immediately(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        q.nack(handle)
        assert len(q) == 1
        assert q.receive()[1] == "task"

    def test_force_release_preserves_receive_count(self, tmp_path):
        """Same crash-loop substrate as the memory backend: a
        third-party release keeps the sidecar count."""
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        assert q.receive_count(handle) == 1
        assert q.force_release([handle]) == 1
        handle, _ = q.receive()
        assert q.receive_count(handle) == 2

    def test_nack_refund_lands_before_release(self, tmp_path,
                                              monkeypatch):
        """The refund is written while the claim file still exists, so
        no other worker can re-claim (and bump) mid-decrement — the
        old decrement-after-rename overwrote a new delivery's count
        with the stale value, silently erasing retry-budget burns."""
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        seen = {}
        real_rename = os.rename

        def spy(src, dst):
            if (os.path.dirname(src) == q.claimed_dir
                    and os.path.basename(src) == handle):
                seen["count_at_release"] = q._read_count(handle)
            return real_rename(src, dst)

        monkeypatch.setattr(os, "rename", spy)
        assert q.nack(handle) is True
        assert seen["count_at_release"] == 0  # refunded pre-visibility

    def test_nack_on_lost_claim_rolls_refund_back(self, tmp_path):
        """When the janitor (or an ack elsewhere) already took the
        claim, the handback never happened: nack reports False and the
        pre-applied refund is restored."""
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q.send_messages(["task"])
        handle, _ = q.receive()
        os.remove(os.path.join(q.claimed_dir, handle))  # claim lost
        assert q.nack(handle) is False
        assert q._read_count(handle) == 1  # the count stands

    def test_receive_count_survives_crash_requeue(self, tmp_path):
        """The sidecar count survives a janitor requeue, so retry
        accounting sees attempts that died without recording a failure
        (the crash-loop guard's substrate)."""
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=0.05)
        q.send_messages(["task"])
        handle, _ = q.receive()
        assert q.receive_count(handle) == 1
        time.sleep(0.1)  # claim expires: simulated worker death
        handle, _ = q.receive()
        assert q.receive_count(handle) == 2

    def test_dead_letter_and_requeue(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q.send_messages(["poison"])
        handle, _ = q.receive()
        q.dead_letter(handle, reason="bad bbox")
        assert q.receive() is None
        assert not os.listdir(q.claimed_dir)
        entries = q.dead_letters()
        assert len(entries) == 1
        assert entries[0]["body"] == "poison"
        assert entries[0]["reason"] == "bad bbox"
        # a second FileQueue on the same dir (another worker / the CLI)
        # sees and requeues the same dead letters
        q2 = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        assert q2.requeue_dead() == 1
        assert q2.dead_letters() == []
        assert q2.receive()[1] == "poison"

    def test_janitor_sweeps_stale_tmp_files(self, tmp_path):
        """A sender that crashes mid-send_messages leaks .tmp-* staging
        files; the janitor removes the stale ones (older than the
        visibility timeout) but never an in-progress send's fresh one."""
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=0.05)
        stale = os.path.join(q.dir, ".tmp-deadbeef")
        fresh = os.path.join(q.dir, ".tmp-inprogress")
        with open(stale, "w") as f:
            f.write("half a task")
        old = time.time() - 10
        os.utime(stale, (old, old))
        with open(fresh, "w") as f:
            f.write("being written right now")
        q._requeue_expired()
        assert not os.path.exists(stale)
        assert os.path.exists(fresh)
        assert len(q) == 0  # the torn task never becomes pending


# ---------------------------------------------------------------------------
# SQS backend against a fake client (boto3 is not in this image)
# ---------------------------------------------------------------------------
class FakeSQSClient:
    """Minimal in-memory stand-in for boto3's SQS client: enough surface
    for the batch-send retry and lifecycle paths."""

    def __init__(self, fail_batches=0, fail_ids=()):
        self.queues = {}
        self.fail_batches = fail_batches  # how many send batches report Failed
        self.fail_ids = set(fail_ids)
        self.send_batch_calls = []

    def create_queue(self, QueueName, Attributes=None):
        url = f"fake://{QueueName}"
        self.queues.setdefault(url, {"messages": [], "receives": {}})
        return {"QueueUrl": url}

    def send_message_batch(self, QueueUrl, Entries):
        self.send_batch_calls.append([e["Id"] for e in Entries])
        failed = []
        for entry in Entries:
            if self.fail_batches > 0 and entry["Id"] in self.fail_ids:
                failed.append({
                    "Id": entry["Id"], "Code": "Throttled",
                    "Message": "try later",
                })
            else:
                self.queues[QueueUrl]["messages"].append(entry["MessageBody"])
        if failed:
            self.fail_batches -= 1
        return {"Failed": failed} if failed else {}

    def send_message(self, QueueUrl, MessageBody, **_):
        self.queues[QueueUrl]["messages"].append(MessageBody)
        return {}

    def receive_message(self, QueueUrl, MaxNumberOfMessages=1, **_):
        q = self.queues[QueueUrl]
        messages = []
        for body in q["messages"][:MaxNumberOfMessages]:
            q["messages"].remove(body)
            handle = f"rh-{len(q['receives'])}-{body[:12]}"
            q["receives"][handle] = q["receives"].get(handle, 0) + 1
            q.setdefault("inflight", {})[handle] = body
            messages.append({
                "ReceiptHandle": handle, "Body": body,
                "Attributes": {
                    "ApproximateReceiveCount": str(q["receives"][handle])
                },
            })
        return {"Messages": messages} if messages else {}

    def delete_message(self, QueueUrl, ReceiptHandle):
        self.queues[QueueUrl]["receives"].pop(ReceiptHandle, None)
        self.queues[QueueUrl].get("inflight", {}).pop(ReceiptHandle, None)

    def change_message_visibility(self, QueueUrl, ReceiptHandle,
                                  VisibilityTimeout):
        self.last_visibility = (ReceiptHandle, VisibilityTimeout)
        if VisibilityTimeout == 0:
            # a real SQS nack makes the message deliverable again NOW;
            # the fake otherwise consumes on receive
            q = self.queues[QueueUrl]
            body = q.get("inflight", {}).pop(ReceiptHandle, None)
            if body is not None:
                q["messages"].append(body)

    def get_queue_attributes(self, QueueUrl, AttributeNames=()):
        q = self.queues[QueueUrl]
        return {"Attributes": {
            "ApproximateNumberOfMessages": str(len(q["messages"])),
            "ApproximateNumberOfMessagesNotVisible": str(len(q["receives"])),
        }}


class TestSQSQueue:
    def test_partial_batch_failure_retried_once(self):
        """send_message_batch can return Failed entries in a *success*
        response; dropping them silently loses tasks (regression). The
        failed subset is retried once, then the send raises."""
        client = FakeSQSClient(fail_batches=1, fail_ids={"1"})
        q = SQSQueue("jobs", client=client)
        q.send_messages(["a", "b", "c"])
        # first call sends all three, retry call resends only Id 1
        assert client.send_batch_calls == [["0", "1", "2"], ["1"]]
        # stored bodies are the traced wire envelopes; the task payloads
        # inside are intact
        assert sorted(
            unpack_task(m)[0] for m in client.queues[q.queue_url]["messages"]
        ) == ["a", "b", "c"]

    def test_partial_batch_failure_raises_after_retry(self):
        client = FakeSQSClient(fail_batches=2, fail_ids={"0"})
        q = SQSQueue("jobs2", client=client)
        with pytest.raises(IOError, match="Throttled"):
            q.send_messages(["a", "b"])

    def test_receive_count_from_attributes(self):
        client = FakeSQSClient()
        q = SQSQueue("jobs3", client=client)
        q.send_messages(["task"])
        handle, body = q.receive()
        assert body == "task"
        assert q.receive_count(handle) == 1

    def test_renew_and_nack_change_visibility(self):
        client = FakeSQSClient()
        q = SQSQueue("jobs4", client=client, visibility_timeout=300)
        q.send_messages(["task"])
        handle, _ = q.receive()
        q.renew(handle)
        assert client.last_visibility == (handle, 300)
        q.renew(handle, 25)
        assert client.last_visibility == (handle, 25)
        q.nack(handle)
        assert client.last_visibility == (handle, 0)

    def test_dead_letter_carries_reason(self):
        # NOTE: the fake consumes on receive (no visibility-restore), so
        # listing and requeueing are asserted in separate tests; real SQS
        # restores listed entries after the dead queue's short timeout
        client = FakeSQSClient()
        q = SQSQueue("jobs5", client=client)
        q.send_messages(["poison"])
        handle, _ = q.receive()
        q.dead_letter(handle, reason="boom")
        entries = q.dead_letters()
        assert len(entries) == 1
        assert entries[0]["body"] == "poison"
        assert entries[0]["reason"] == "boom"
        assert entries[0]["receives"] == 1

    def test_dead_letter_requeue(self):
        client = FakeSQSClient()
        q = SQSQueue("jobs6", client=client)
        q.send_messages(["poison"])
        handle, _ = q.receive()
        q.dead_letter(handle, reason="boom")
        assert q.requeue_dead() == 1
        handle, body = q.receive()
        assert body == "poison"


class TestMemoryQueueConcurrency:
    def test_concurrent_receive_claims_each_task_exactly_once(self):
        """Regression (concurrency plane): ``receive`` is a compound
        claim-and-make-invisible. Unlocked, two LocalBackend worker
        threads could claim the same handle (double execution) or die
        on the second ``del``; under the queue lock every task is
        claimed exactly once across racing threads."""
        import threading

        q = MemoryQueue("t-concurrent-claims", visibility_timeout=100)
        n_tasks, n_threads = 300, 8
        q.send_messages([f"task-{i}" for i in range(n_tasks)])
        claimed, errors = [], []
        claimed_lock = threading.Lock()

        def worker():
            while True:
                try:
                    item = q.receive()
                except Exception as exc:  # noqa: BLE001 — the regression
                    errors.append(exc)
                    return
                if item is None:
                    return
                with claimed_lock:
                    claimed.append(item)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, f"receive raced: {errors[:3]}"
        bodies = sorted(body for _h, body in claimed)
        assert bodies == sorted(f"task-{i}" for i in range(n_tasks))
        handles = [h for h, _b in claimed]
        assert len(set(handles)) == len(handles)  # no double-claims
