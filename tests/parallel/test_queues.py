import os
import time

from chunkflow_tpu.parallel.queues import FileQueue, MemoryQueue, open_queue


class TestMemoryQueue:
    def test_send_receive_delete(self):
        q = MemoryQueue("t1", visibility_timeout=100)
        q.send_messages(["a", "b", "c"])
        assert len(q) == 3
        handle, body = q.receive()
        assert body in {"a", "b", "c"}
        assert len(q) == 2  # claimed message is invisible
        q.delete(handle)
        bodies = {q.receive()[1], q.receive()[1]}
        assert len(bodies) == 2
        assert q.receive() is None

    def test_visibility_timeout_requeues(self):
        q = MemoryQueue("t2", visibility_timeout=0.05)
        q.send_messages(["task"])
        handle, _ = q.receive()
        assert q.receive() is None
        time.sleep(0.1)
        handle2, body = q.receive()  # crashed-worker task reappears
        assert body == "task"
        q.delete(handle2)
        time.sleep(0.1)
        assert q.receive() is None

    def test_iteration_drains(self):
        q = MemoryQueue("t3")
        q.retry_sleep = 0.01
        q.send_messages([str(i) for i in range(5)])
        seen = []
        for handle, body in q:
            seen.append(body)
            q.delete(handle)
        assert sorted(seen) == [str(i) for i in range(5)]


class TestFileQueue:
    def test_send_receive_delete(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q.send_messages(["0-4_0-4_0-4", "4-8_0-4_0-4"])
        assert len(q) == 2
        handle, body = q.receive()
        assert body.count("_") == 2
        assert len(q) == 1
        q.delete(handle)
        assert not os.path.exists(os.path.join(q.claimed_dir, handle))

    def test_crashed_worker_task_reappears(self, tmp_path):
        q = FileQueue(str(tmp_path / "q"), visibility_timeout=0.05)
        q.send_messages(["task"])
        q.receive()  # claim without ack = simulated crash
        assert len(q) == 0
        time.sleep(0.1)
        handle, body = q.receive()
        assert body == "task"

    def test_two_workers_no_double_claim(self, tmp_path):
        q1 = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q2 = FileQueue(str(tmp_path / "q"), visibility_timeout=100)
        q1.send_messages(["a", "b"])
        r1 = q1.receive()
        r2 = q2.receive()
        assert r1[1] != r2[1]
        assert q1.receive() is None


def test_open_queue_schemes(tmp_path):
    assert isinstance(open_queue("memory://x"), MemoryQueue)
    assert isinstance(open_queue(str(tmp_path / "fq")), FileQueue)
    assert isinstance(open_queue("file://" + str(tmp_path / "fq2")), FileQueue)
