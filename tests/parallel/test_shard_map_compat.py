"""The _shard_map compat shim: both import branches + the psum pin.

The shim silently maps ``check_rep -> check_vma`` on new jax (>= the
``jax.shard_map`` promotion) and falls back to the experimental API on
older jax; until ISSUE 13 neither branch had a test, and the
psum-replication assumption its docstring records ("replication checking
is off either way because the blend programs psum explicitly") was
unpinned."""
import builtins
import importlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.parallel import _shard_map


def _mesh(n):
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < n:
        pytest.skip(f"needs {n} virtual devices (tests/conftest.py)")
    return Mesh(np.asarray(devices[:n]), ("data",))


def _run_psum_program(shard_map_fn, n=4):
    """A psum program through the wrapper: per-device partial sums merge
    over the mesh and return REPLICATED (out_specs P()). This is exactly
    the shape the blend programs rely on — a psum result is replicated
    by construction, which is why the shim may disable replication
    checking without changing semantics."""
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(n)

    def device_fn(x):
        return jax.lax.psum(x.sum(), "data")

    program = jax.jit(shard_map_fn(
        device_fn, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
        check_rep=False,
    ))
    x = np.arange(4 * n, dtype=np.float32).reshape(n * 2, 2)
    out = program(x)
    np.testing.assert_allclose(float(out), float(x.sum()))
    return out


def test_new_api_branch_maps_check_rep():
    """On this jax the shim must have bound the NEW ``jax.shard_map``
    (when present) and its wrapper must accept the legacy ``check_rep``
    kwarg — the silent check_rep->check_vma mapping the shim exists
    for. On an older jax the module IS the experimental function; both
    branches run the psum program either way."""
    has_new = hasattr(jax, "shard_map")
    if has_new:
        # the wrapper is our def, not the raw API (which would reject
        # check_rep on new jax / accept it on old)
        assert _shard_map.shard_map.__module__ == _shard_map.__name__
    else:
        from jax.experimental.shard_map import shard_map as exp

        assert _shard_map.shard_map is exp
    _run_psum_program(_shard_map.shard_map)


def test_experimental_fallback_branch(monkeypatch):
    """Reload the shim with ``from jax import shard_map`` forced to
    ImportError: the module must fall back to
    ``jax.experimental.shard_map.shard_map`` and still run the psum
    program (the older-jax branch, unreachable on this image without
    the forced failure)."""
    real_import = builtins.__import__

    def no_new_api(name, globals=None, locals=None, fromlist=(), level=0):
        if name == "jax" and fromlist and "shard_map" in fromlist:
            raise ImportError("forced: no jax.shard_map")
        return real_import(name, globals, locals, fromlist, level)

    monkeypatch.setattr(builtins, "__import__", no_new_api)
    try:
        mod = importlib.reload(_shard_map)
        from jax.experimental.shard_map import shard_map as exp

        assert mod.shard_map is exp
        _run_psum_program(mod.shard_map)
    finally:
        monkeypatch.setattr(builtins, "__import__", real_import)
        importlib.reload(_shard_map)


def test_psum_replication_assumption_pinned():
    """The documented assumption itself: with replication checking off,
    a psum-merged out_specs=P() result equals the full reduction on
    every device — run on 2 AND 8 chips so a regrouping regression
    would show."""
    for n in (2, 8):
        _run_psum_program(_shard_map.shard_map, n=n)
