"""Two-PROCESS jax.distributed bring-up through multihost.initialize.

The reference's only cross-host runtime is an SQS queue; this framework
additionally supports one jax program spanning hosts (SURVEY §5.8). Round-1
verdict: multihost was "helpers-only, tested in a single process". This
test runs a REAL two-process jax.distributed runtime on the CPU backend:
coordinator bring-up, a cross-process allgather, and a jit'ed collective
over an 8-device global mesh layered exactly like a pod slice — 2
processes (DCN axis) x 4 local virtual devices each (ICI axis).
"""
import os
import socket
import time
import subprocess
import sys

WORKER = r"""
import sys
sys.path.insert(0, {repo!r})

from chunkflow_tpu.parallel import multihost

multihost.initialize(
    coordinator_address={coord!r},
    num_processes=2,
    process_id={pid},
)
import jax
import numpy as np

assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == {pid}
assert multihost.is_coordinator() == ({pid} == 0)
assert jax.local_device_count() == 4
assert jax.device_count() == 8

# transport-agnostic cross-process exchange: device collectives where
# the backend has them, the coordination-service KV store where it does
# not (the CPU backend cannot run one computation across processes —
# the XlaRuntimeError this suite used to die on)
gathered = multihost.allgather_bytes(b"proc-%d" % {pid})
assert gathered == [b"proc-0", b"proc-1"], gathered

# the task-stream primitive the crosshost CLI loop rides: coordinator
# publishes, every peer receives; None is the stop sentinel
got = multihost.broadcast_string("bbox-task-1" if {pid} == 0 else None)
assert got == "bbox-task-1", got
assert multihost.broadcast_string(None) is None

if multihost.backend_supports_collectives():
    # a collective over the full 8-device global mesh: each process
    # feeds its local 4-row shard; the jit'ed sum reduces across
    # processes + devices (real pod slices only — the CPU backend
    # cannot run multiprocess computations)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = multihost.global_mesh()
    assert mesh.devices.size == 8
    sharding = NamedSharding(mesh, PartitionSpec("data"))
    local_rows = (np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
                  + 100 * {pid})
    garr = jax.make_array_from_process_local_data(
        sharding, local_rows, (8, 3))
    total = jax.jit(
        lambda x: jnp.sum(x),
        out_shardings=NamedSharding(mesh, PartitionSpec()),
    )(garr)
    expected = float(sum(
        (np.arange(12, dtype=np.float32) + 100 * p).sum()
        for p in (0, 1)
    ))
    np.testing.assert_allclose(float(total), expected)

# the full cross-host inference program, identity-engine oracle (the
# blended overlap-add of identity patches must reproduce the input
# chunk). On collective backends this is ONE program over the global
# mesh; on the CPU backend each process computes over its local mesh
# behind the host-side consistency guard — same call, same contract.
from chunkflow_tpu.inference import engines

pin = (4, 16, 16)
engine = engines.create_identity_engine(
    input_patch_size=pin, output_patch_size=pin,
    num_input_channels=1, num_output_channels=3,
)
rng = np.random.default_rng(42)  # same seed everywhere: identical chunks
chunk = rng.random((8, 32, 32)).astype(np.float32)
out = multihost.sharded_inference_global(
    chunk, engine,
    input_patch_size=pin, output_patch_size=pin,
    output_patch_overlap=(2, 8, 8), batch_size=1,
)
assert out.shape == (3, 8, 32, 32), out.shape
np.testing.assert_allclose(out, np.broadcast_to(chunk, out.shape),
                           atol=1e-5)

# replica agreement across processes: on a collective backend each
# host's copy of the "replicated" psum output may differ in the LAST
# ULP (all-reduce rounding is per-rank — which is exactly why the CLI
# publishes only the coordinator's copy); on the CPU fallback the
# unified engine's replayed accumulation is deterministic, so replicas
# agree BITWISE. Exchange digests host-side either way.
dig = np.asarray(multihost._chunk_digest(out), np.float64)
rows = multihost.allgather_bytes(dig.tobytes())
peers = [np.frombuffer(r, np.float64) for r in rows]
if multihost.backend_supports_collectives():
    np.testing.assert_allclose(peers[0][0], peers[1][0], rtol=1e-6)
else:
    assert (peers[0] == peers[1]).all(), peers

# the production surface: Inferencer(sharding='patch') routes through
# the same multi-process recipe whenever the runtime spans processes
from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.inference.inferencer import Inferencer

inferencer = Inferencer(
    input_patch_size=pin,
    output_patch_overlap=(2, 8, 8),
    num_output_channels=3,
    framework="identity",
    batch_size=1,
    sharding="patch",
    crop_output_margin=False,
)
out2 = np.asarray(inferencer(Chunk(chunk)).array)
assert out2.shape == (3, 8, 32, 32), out2.shape
np.testing.assert_allclose(out2, np.broadcast_to(chunk, out2.shape),
                           atol=1e-5)
print("WORKER_OK", {pid})
"""


DIVERGENT_WORKER = r"""
import sys
sys.path.insert(0, {repo!r})

from chunkflow_tpu.parallel import multihost

multihost.initialize(
    coordinator_address={coord!r},
    num_processes=2,
    process_id={pid},
)
import jax
import numpy as np

# a silent single-process bring-up (the documented sitecustomize failure
# mode) would skip the guard's process_count gate entirely — fail here
# with the real diagnosis instead of a bogus "guard did not fire"
assert jax.process_count() == 2, jax.process_count()

from chunkflow_tpu.inference import engines

pin = (4, 16, 16)
engine = engines.create_identity_engine(
    input_patch_size=pin, output_patch_size=pin,
    num_input_channels=1, num_output_channels=3,
)
# DIFFERENT chunk per process — but a PERMUTATION of the same values, so
# the plain float64 sums agree exactly and only the strengthened digest
# (strided-sample crc, ADVICE r4) can tell them apart. The guard must
# abort loudly on every host instead of psum-ing silently corrupt output.
rng = np.random.default_rng(100)  # same seed: same value multiset
chunk = rng.random((8, 32, 32)).astype(np.float32)
if {pid} == 1:
    chunk = np.ascontiguousarray(chunk[::-1])
try:
    multihost.sharded_inference_global(
        chunk, engine,
        input_patch_size=pin, output_patch_size=pin,
        output_patch_overlap=(2, 8, 8), batch_size=1,
    )
except ValueError as e:
    assert "checksums differ" in str(e), e
    print("GUARD_FIRED", {pid})
else:
    raise AssertionError("divergent inputs were not rejected")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env() -> dict:
    """CPU-pinned env for the spawned workers, scrubbed BEFORE interpreter
    start: this image's sitecustomize registers the tunneled TPU plugin at
    startup whenever PALLAS_AXON*/AXON* vars are present, which leaves the
    process in a state where jax.distributed.initialize silently fails to
    apply (process_count stays 1) — and in-worker os.environ surgery runs
    too late to stop it. Four virtual chips per host: the global mesh
    spans DCN (processes) x ICI (local devices) like a real pod slice."""
    import re

    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith(("PALLAS_AXON", "AXON"))
    }
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"
    return env


def _run_two_workers(tmp_path, template, ok_marker):
    import chunkflow_tpu

    repo = str(next(iter(chunkflow_tpu.__path__)).rsplit("/", 1)[0])
    coord = f"127.0.0.1:{_free_port()}"
    # worker output goes to files, not PIPEs: nobody drains a pipe while
    # polling, so a verbose worker would block in write() and be
    # misreported as timed out
    logs = [tmp_path / f"worker{pid}.log" for pid in range(2)]
    procs = []
    for pid in range(2):
        with open(logs[pid], "w") as log:
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 template.format(repo=repo, coord=coord, pid=pid)],
                stdout=log, stderr=subprocess.STDOUT, env=_worker_env(),
            ))
    try:
        # poll both: a worker that dies before the coordinator barrier
        # must surface ITS traceback, not a timeout on the healthy peer
        # (which blocks inside jax.distributed.initialize waiting for it)
        deadline = time.monotonic() + 180
        pending = dict(enumerate(procs))
        while pending and time.monotonic() < deadline:
            for pid, p in list(pending.items()):
                if p.poll() is not None:
                    out = logs[pid].read_text()
                    assert p.returncode == 0, f"worker {pid} failed:\n{out}"
                    assert f"{ok_marker} {pid}" in out
                    del pending[pid]
            time.sleep(0.2)
        assert not pending, f"workers {sorted(pending)} timed out"
    finally:
        # a failed/hung worker must not leave its peer blocked at the
        # coordinator holding the port
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_consistency_guard_rejects_divergent_inputs(tmp_path):
    """Two processes feed DIFFERENT chunks into one collective — value
    permutations with IDENTICAL plain sums: the strengthened digest
    allgather must raise on every host (silent cross-host psum
    corruption is the failure mode this guards)."""
    _run_two_workers(tmp_path, DIVERGENT_WORKER, "GUARD_FIRED")


def test_chunk_digest_distinguishes_permutations():
    """ADVICE r4: a permutation (or sign-cancelling rearrangement) of the
    same values keeps the plain sum equal; the digest must still differ,
    while identical arrays and NaN-masked copies must agree."""
    import numpy as np

    from chunkflow_tpu.parallel.multihost import _chunk_digest

    rng = np.random.default_rng(0)
    a = rng.random((4, 8, 8)).astype(np.float32)
    b = np.ascontiguousarray(a[::-1])
    assert np.isclose(_chunk_digest(a)[0], _chunk_digest(b)[0])  # sums tie
    assert _chunk_digest(a) != _chunk_digest(b)
    assert _chunk_digest(a) == _chunk_digest(a.copy())
    # sign-cancelling divergence: add +x to one voxel, -x to another
    c = a.copy()
    c[0, 0, 0] += 0.25
    c[1, 1, 1] -= 0.25
    assert _chunk_digest(a) != _chunk_digest(c)
    # different shape, same bytes
    assert _chunk_digest(a) != _chunk_digest(a.reshape(8, 4, 8))
    # NaN-masked chunks: equal copies agree under the NaN-aware compare
    # run_global applies (the sum entry is NaN, so plain == would differ)
    d = a.copy()
    d[2, 2, 2] = np.nan
    da, db = _chunk_digest(d), _chunk_digest(d.copy())
    assert all(
        x == y or (np.isnan(x) and np.isnan(y)) for x, y in zip(da, db)
    )


def test_params_fingerprint_detects_inplace_reload():
    """ADVICE r4: reloading weights INTO the same pytree object must
    change the fingerprint so run_global's caches re-transfer instead of
    serving stale device params behind a passing digest."""
    import numpy as np

    from chunkflow_tpu.parallel.multihost import _params_fingerprint

    params = {"dense": {"kernel": np.ones((8, 8), np.float32),
                        "bias": np.zeros((8,), np.float32)}}
    fp0 = _params_fingerprint(params)
    assert fp0 == _params_fingerprint(params)
    params["dense"]["kernel"][3, 3] = 7.0  # in-place mutation, same id()
    assert _params_fingerprint(params) != fp0


def test_two_process_distributed_bringup(tmp_path):
    _run_two_workers(tmp_path, WORKER, "WORKER_OK")
