"""TreeTaskSource: the SpatialTaskTree as a LIVE task source feeding
the ordinary queue/ledger machinery (ISSUE 20). Covers ready-set
ordering, parent unlock strictly after BOTH children's ledger commits,
mid-job serialize/restore resume, and a two-worker run where the tree
is the only coordinator."""
import threading

import pytest

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.parallel.lifecycle import MemoryLedger, open_ledger
from chunkflow_tpu.parallel.queues import open_queue
from chunkflow_tpu.parallel.task_tree import SpatialTaskTree
from chunkflow_tpu.parallel.tree_source import TreeTaskSource


def _tree(stop=(16, 8, 8), block=(4, 4, 4)):
    return SpatialTaskTree(BoundingBox((0, 0, 0), stop), block)


def _drain(queue):
    bodies = []
    while True:
        got = queue.receive()
        if got is None:
            return bodies
        handle, body = got
        bodies.append(body)
        queue.delete(handle)


def test_requires_a_ledger():
    with pytest.raises(ValueError):
        TreeTaskSource(_tree(), open_queue("memory://ts-noledger"), None)


def test_first_sync_enqueues_exactly_the_leaves_in_preorder():
    tree = _tree()
    queue = open_queue("memory://ts-leaves")
    source = TreeTaskSource(tree, queue, MemoryLedger())
    assert source.sync() == len(tree.leaf_list)
    # pre-order claim => leaves go out left-to-right along the walk,
    # and no interior node leaks into the first wave
    expected = [n.bbox.string for n in tree.walk() if n.is_leaf]
    assert _drain(queue) == expected
    assert source.sync() == 0  # nothing committed yet: no new work


def test_parent_unlocks_only_after_both_children_commit():
    tree = _tree(stop=(8, 4, 4))  # two leaves, one root merge
    queue = open_queue("memory://ts-unlock")
    ledger = MemoryLedger()
    source = TreeTaskSource(tree, queue, ledger)
    source.sync()
    left, right = tree.left, tree.right
    assert _drain(queue) == [left.bbox.string, right.bbox.string]

    ledger.mark_done(left.bbox.string)
    assert source.sync() == 0          # one child is NOT enough
    assert _drain(queue) == []
    assert not tree.is_done

    ledger.mark_done(right.bbox.string)
    assert source.sync() == 1          # ...both commits are
    assert _drain(queue) == [tree.bbox.string]
    ledger.mark_done(tree.bbox.string)
    source.sync()
    assert source.all_done and source.pending() == 0


def test_interior_nodes_enqueue_strictly_after_their_subtrees():
    tree = _tree()
    queue = open_queue("memory://ts-order")
    ledger = MemoryLedger()
    source = TreeTaskSource(tree, queue, ledger)
    seen = []
    while not source.all_done:
        source.sync()
        for body in _drain(queue):
            node = tree.find(body)
            if not node.is_leaf:  # children must already be in `seen`
                assert node.left.bbox.string in seen
                assert node.right.bbox.string in seen
            seen.append(body)
            ledger.mark_done(body)
    assert len(seen) == sum(1 for _ in tree.walk())
    assert seen[-1] == tree.bbox.string  # the root merge goes last


def test_custom_body_is_both_queue_body_and_ledger_key():
    tree = _tree(stop=(8, 4, 4))
    queue = open_queue("memory://ts-body")
    ledger = MemoryLedger()
    source = TreeTaskSource(
        tree, queue, ledger, body=lambda n: f"merge_{n.bbox.string}"
    )
    source.sync()
    bodies = _drain(queue)
    assert all(b.startswith("merge_") for b in bodies)
    for body in bodies:
        ledger.mark_done(body)
    source.sync()
    assert _drain(queue) == [f"merge_{tree.bbox.string}"]


def test_serialize_restore_mid_job_keeps_working_nodes_in_flight():
    tree = _tree(stop=(8, 4, 4))
    queue = open_queue("memory://ts-serialize")
    ledger = MemoryLedger()
    source = TreeTaskSource(tree, queue, ledger)
    source.sync()
    # left leaf committed; right leaf's message still IN FLIGHT
    ledger.mark_done(tree.left.bbox.string)

    restored = SpatialTaskTree.from_dict(tree.to_dict())
    assert [n.state for n in restored.walk()] == [
        n.state for n in tree.walk()
    ]
    resumed = TreeTaskSource(restored, queue, ledger)
    # restored WORKING nodes are NOT re-enqueued: their messages are
    # still in the queue; only the ledger fold advances state
    assert resumed.sync() == 0
    assert restored.left.is_done and not restored.right.is_done

    # the in-flight message completes -> the root unlocks on resume
    ledger.mark_done(tree.right.bbox.string)
    assert resumed.sync() == 1
    ledger.mark_done(tree.bbox.string)
    resumed.sync()
    assert resumed.all_done


def test_coordinator_crash_rebuild_from_plan_plus_ledger():
    """The harder crash: the coordinator dies losing ALL tree state.
    A fresh tree + ledger fold re-claims the frontier; duplicates of
    messages still sitting in the queue are absorbed downstream by the
    worker's ledger-skip, so re-enqueueing them is safe — the tree
    must still converge."""
    tree = _tree()
    queue = open_queue("memory://ts-rebuild")
    ledger = MemoryLedger()
    TreeTaskSource(tree, queue, ledger).sync()
    bodies = _drain(queue)
    for body in bodies[: len(bodies) // 2]:
        ledger.mark_done(body)

    rebuilt = TreeTaskSource(_tree(), queue, ledger)  # fresh READY tree
    rebuilt.sync()
    dup = _drain(queue)
    # committed leaves were folded to done and NOT re-sent; every
    # uncommitted leaf was; any extra bodies are interior merges whose
    # subtrees completed before the crash (a legal frontier)
    assert set(dup).isdisjoint(bodies[: len(bodies) // 2])
    assert set(bodies[len(bodies) // 2:]) <= set(dup)
    for body in set(dup) - set(bodies):
        node = rebuilt.tree.find(body)
        assert not node.is_leaf
        assert node.left.is_done and node.right.is_done
    for body in dup:
        ledger.mark_done(body)
    while not rebuilt.all_done:
        if rebuilt.sync() == 0:
            break
        for body in _drain(queue):
            ledger.mark_done(body)
    assert rebuilt.all_done


def test_two_workers_with_the_tree_as_only_coordinator():
    """End to end with REAL concurrency: two worker threads drain the
    queue and write ledger commits; the only scheduling authority is
    TreeTaskSource.run() in the main thread."""
    tree = _tree(stop=(16, 16, 8), block=(4, 4, 4))
    queue = open_queue("memory://ts-two-workers")
    ledger = open_ledger("memory://ts-two-workers-ledger")
    source = TreeTaskSource(tree, queue, ledger)
    stop = threading.Event()
    done_by = {}

    def worker(name):
        while not stop.is_set():
            got = queue.receive()
            if got is None:
                stop.wait(0.005)
                continue
            handle, body = got
            done_by[body] = name  # last writer wins; keys are what matter
            ledger.mark_done(body)
            queue.delete(handle)

    threads = [
        threading.Thread(target=worker, args=(f"w{i}",), daemon=True)
        for i in range(2)
    ]
    for t in threads:
        t.start()
    try:
        enqueued = source.run(poll_interval=0.005, timeout=30)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)

    total = sum(1 for _ in tree.walk())
    assert source.all_done
    assert enqueued == total
    assert set(done_by) == {n.bbox.string for n in tree.walk()}
    assert queue.stats()["pending"] == 0
    assert queue.stats()["inflight"] == 0


def test_run_times_out_with_no_workers():
    source = TreeTaskSource(
        _tree(stop=(8, 8, 8)), open_queue("memory://ts-timeout"),
        MemoryLedger(),
    )
    with pytest.raises(TimeoutError, match="outstanding"):
        source.run(poll_interval=0.01, timeout=0.05)
