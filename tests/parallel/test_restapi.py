"""Coordination service: ID ranges + hierarchical task scheduling over HTTP."""
import json
import threading
import urllib.request

import pytest

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.parallel.restapi import CoordinationService, serve
from chunkflow_tpu.parallel.task_tree import SpatialTaskTree


def make_tree():
    return SpatialTaskTree(BoundingBox((0, 0, 0), (4, 8, 8)), (4, 4, 4))


def test_handle_objids_and_tasks():
    svc = CoordinationService(id_start=100, task_tree=make_tree())
    status, payload = svc.handle("GET", "/objids/10")
    assert status == 200 and payload["base_id"] == 100
    status, payload = svc.handle("GET", "/objids/5")
    assert payload["base_id"] == 110

    # drain leaves, completing each; parents become ready then complete
    done = 0
    while True:
        status, payload = svc.handle("GET", "/task")
        if status == 204:
            break
        assert status == 200
        status, result = svc.handle("POST", f"/task/{payload['bbox']}/done")
        assert status == 200
        done += 1
        if result["all_done"]:
            break
    assert done >= 4  # 4 leaves + internal nodes


def test_handle_unknown_and_unclaimed():
    svc = CoordinationService(task_tree=make_tree())
    assert svc.handle("GET", "/nope")[0] == 404
    assert svc.handle("POST", "/task/0-4_0-4_0-4/done")[0] == 404


def test_http_server_roundtrip():
    svc = CoordinationService(id_start=0, task_tree=make_tree())
    server, _thread = serve(svc, host="127.0.0.1", port=0, background=True)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/objids/7"
        ) as resp:
            assert json.loads(resp.read())["base_id"] == 0
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/task") as resp:
            body = json.loads(resp.read())
            assert "bbox" in body
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/task/{body['bbox']}/done", method="POST"
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
    finally:
        server.shutdown()
