"""Coordination service: ID ranges, task scheduling, live /metrics."""
import json
import re
import threading
import urllib.error
import urllib.request

import pytest

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.parallel.restapi import (
    CoordinationService,
    parse_prometheus,
    prometheus_name,
    render_prometheus,
    scrape_worker,
    serve,
    shutdown_server,
    start_metrics_exporter,
)
from chunkflow_tpu.parallel.task_tree import SpatialTaskTree


def make_tree():
    return SpatialTaskTree(BoundingBox((0, 0, 0), (4, 8, 8)), (4, 4, 4))


def test_handle_objids_and_tasks():
    svc = CoordinationService(id_start=100, task_tree=make_tree())
    status, payload = svc.handle("GET", "/objids/10")
    assert status == 200 and payload["base_id"] == 100
    status, payload = svc.handle("GET", "/objids/5")
    assert payload["base_id"] == 110

    # drain leaves, completing each; parents become ready then complete
    done = 0
    while True:
        status, payload = svc.handle("GET", "/task")
        if status == 204:
            break
        assert status == 200
        status, result = svc.handle("POST", f"/task/{payload['bbox']}/done")
        assert status == 200
        done += 1
        if result["all_done"]:
            break
    assert done >= 4  # 4 leaves + internal nodes


def test_handle_unknown_and_unclaimed():
    svc = CoordinationService(task_tree=make_tree())
    assert svc.handle("GET", "/nope")[0] == 404
    assert svc.handle("POST", "/task/0-4_0-4_0-4/done")[0] == 404


# ---------------------------------------------------------------------------
# Prometheus text exposition (ISSUE 6)
# ---------------------------------------------------------------------------
@pytest.fixture
def clean_telemetry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def test_prometheus_name_mapping():
    assert prometheus_name("pipeline/ring_occupancy") \
        == "chunkflow_pipeline_ring_occupancy"
    assert prometheus_name("tasks/dead_lettered") \
        == "chunkflow_tasks_dead_lettered"
    assert prometheus_name("op/save-h5") == "chunkflow_op_save_h5"


def test_render_prometheus_golden():
    """Exact exposition for a hand-built snapshot: counter/gauge/summary
    typing, name mapping, label escaping, derived stall shares."""
    snap = {
        "counters": {"tasks/committed": 3},
        "gauges": {"scheduler/depth/prefetch": 4},
        "hists": {
            "pipeline/drain": {"count": 2, "total": 1.5, "min": 0.5,
                               "max": 1.0, "mean": 0.75},
            "pipeline/compute": {"count": 2, "total": 0.5, "min": 0.1,
                                 "max": 0.4, "mean": 0.25},
        },
    }
    text = render_prometheus(snap, worker='host"1\\a\nb')
    esc = 'host\\"1\\\\a\\nb'
    assert text == (
        "# TYPE chunkflow_tasks_committed_total counter\n"
        f'chunkflow_tasks_committed_total{{worker="{esc}"}} 3\n'
        "# TYPE chunkflow_scheduler_depth_prefetch gauge\n"
        f'chunkflow_scheduler_depth_prefetch{{worker="{esc}"}} 4\n'
        "# TYPE chunkflow_pipeline_compute summary\n"
        f'chunkflow_pipeline_compute_count{{worker="{esc}"}} 2\n'
        f'chunkflow_pipeline_compute_sum{{worker="{esc}"}} 0.5\n'
        "# TYPE chunkflow_pipeline_drain summary\n"
        f'chunkflow_pipeline_drain_count{{worker="{esc}"}} 2\n'
        f'chunkflow_pipeline_drain_sum{{worker="{esc}"}} 1.5\n'
        "# TYPE chunkflow_stall_share gauge\n"
        f'chunkflow_stall_share{{worker="{esc}",phase="pipeline/compute"}}'
        " 0.250000\n"
        f'chunkflow_stall_share{{worker="{esc}",phase="pipeline/drain"}}'
        " 0.750000\n"
        "# TYPE chunkflow_stall_dominant_share gauge\n"
        f'chunkflow_stall_dominant_share{{worker="{esc}",'
        'phase="pipeline/drain"} 0.750000\n'
    )


def test_render_prometheus_folds_chip_labels():
    """ISSUE 18: device/chip/<i>/* and shard/chip/<i>/* gauges fold the
    chip index out of the metric name into a ``chip`` label, with every
    chip's sample contiguous under ONE TYPE line (strict exposition:
    all samples of a metric must be grouped)."""
    snap = {
        "counters": {},
        "gauges": {
            "device/chip/0/bytes_in_use": 100.0,
            "device/chip/1/bytes_in_use": 700.0,
            "device/chip/0/hbm_headroom": 900.0,
            "device/chip/1/hbm_headroom": 300.0,
            "shard/chip/0/voxels": 2048.0,
            "device/bytes_in_use": 800.0,
        },
        "hists": {},
    }
    text = render_prometheus(snap, worker="w1")
    assert (
        "# TYPE chunkflow_device_chip_bytes_in_use gauge\n"
        'chunkflow_device_chip_bytes_in_use{worker="w1",chip="0"} 100\n'
        'chunkflow_device_chip_bytes_in_use{worker="w1",chip="1"} 700\n'
    ) in text
    assert 'chunkflow_device_chip_hbm_headroom{worker="w1",chip="1"} 300' \
        in text
    assert 'chunkflow_shard_chip_voxels{worker="w1",chip="0"} 2048' in text
    # the aggregate keeps its unlabeled name, and each folded metric
    # declares its TYPE exactly once
    assert 'chunkflow_device_bytes_in_use{worker="w1"} 800' in text
    assert text.count("# TYPE chunkflow_device_chip_bytes_in_use") == 1
    assert text.count("# TYPE chunkflow_device_chip_hbm_headroom") == 1
    parse_prometheus(text)  # grammar holds with the extra label


def test_rendered_exposition_parses(clean_telemetry):
    """Every sample line of a live-registry rendering must match the
    Prometheus exposition grammar (metric names, label syntax, float
    values) — parsed in-test, per the acceptance criteria."""
    telemetry.inc("tasks/committed", 5)
    telemetry.gauge("pipeline/ring_occupancy", 2)
    with telemetry.span("pipeline/drain"):
        pass
    text = render_prometheus()
    parsed = parse_prometheus(text)  # raises on any malformed line
    assert parsed["chunkflow_tasks_committed_total"] == 5
    assert parsed["chunkflow_pipeline_ring_occupancy"] == 2
    assert parsed["chunkflow_pipeline_drain_count"] == 1
    # strict grammar sweep over the raw text
    for line in text.splitlines():
        if line.startswith("#"):
            assert re.fullmatch(
                r"# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                r"(counter|gauge|summary)", line)
        else:
            assert re.fullmatch(
                r'[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+na-]+',
                line), line


def test_metrics_and_healthz_roundtrip(clean_telemetry):
    """/metrics + /healthz over real HTTP from the exporter thread."""
    telemetry.inc("tasks/committed", 2)
    server = start_metrics_exporter(0, host="127.0.0.1")
    assert server is not None
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            parsed = parse_prometheus(resp.read().decode())
        assert parsed["chunkflow_tasks_committed_total"] == 2
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz"
        ) as resp:
            health = json.loads(resp.read())
        assert health["status"] == "ok"
        assert health["worker"] == telemetry.worker_id()
        assert health["inflight_leases"] == 0
        # the scrape helper fleet-status uses sees the same thing
        sample = scrape_worker(f"127.0.0.1:{port}")
        assert sample["error"] is None
        assert sample["healthz"]["worker"] == telemetry.worker_id()
        assert sample["metrics"]["chunkflow_tasks_committed_total"] == 2
    finally:
        server.shutdown()
        server.server_close()


def test_worker_health_marks_truncated_handle_list(monkeypatch):
    """Past the 64-handle cap, /healthz must say the list is truncated
    so the fleet supervisor knows the excess leases will ride out the
    visibility timeout instead of being force-nacked."""
    from chunkflow_tpu.parallel import lifecycle, restapi

    class FakeLease:
        def __init__(self, i):
            self.handle = f"h{i}"

    monkeypatch.setattr(
        lifecycle, "inflight", lambda: [FakeLease(i) for i in range(70)])
    health = restapi.worker_health()
    assert health["inflight_leases"] == 70
    assert len(health["inflight_handles"]) == 64
    assert health["inflight_handles_truncated"] is True

    monkeypatch.setattr(
        lifecycle, "inflight", lambda: [FakeLease(0)])
    health = restapi.worker_health()
    assert health["inflight_handles"] == ["h0"]
    assert health["inflight_handles_truncated"] is False


def test_kill_switch_creates_no_listener(monkeypatch):
    """CHUNKFLOW_TELEMETRY=0 means no socket at all — the same
    creates-nothing discipline as the JSONL sink."""
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    assert start_metrics_exporter(0) is None


def test_scrape_worker_reports_unreachable():
    sample = scrape_worker("127.0.0.1:1", timeout=0.2)  # nothing listens
    assert sample["error"] is not None
    assert sample["healthz"] is None and sample["metrics"] is None


def test_http_server_roundtrip():
    svc = CoordinationService(id_start=0, task_tree=make_tree())
    server, _thread = serve(svc, host="127.0.0.1", port=0, background=True)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/objids/7"
        ) as resp:
            assert json.loads(resp.read())["base_id"] == 0
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/task") as resp:
            body = json.loads(resp.read())
            assert "bbox" in body
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/task/{body['bbox']}/done", method="POST"
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# POST /profile (ISSUE 8: operator-requested bounded capture)
# ---------------------------------------------------------------------------
def test_profile_route_captures_bounded_window(clean_telemetry, tmp_path):
    import glob

    telemetry.configure(str(tmp_path))
    svc = CoordinationService()
    status, payload = svc.handle("POST", "/profile?seconds=0.1")
    assert status == 200, payload
    assert payload["trace_dir"].startswith(str(tmp_path))
    assert payload["seconds"] == 0.1
    assert glob.glob(payload["trace_dir"] + "/**/*.trace.json.gz",
                     recursive=True)


def test_profile_route_rejects_malformed_seconds(clean_telemetry,
                                                 tmp_path):
    telemetry.configure(str(tmp_path))
    status, payload = CoordinationService().handle(
        "POST", "/profile?seconds=soon")
    assert status == 400


def test_profile_route_refuses_concurrent_session(clean_telemetry,
                                                  tmp_path, monkeypatch):
    from chunkflow_tpu.core import profiling

    telemetry.configure(str(tmp_path))
    monkeypatch.setattr(profiling, "_TRACE_ACTIVE", True)
    status, payload = CoordinationService().handle(
        "POST", "/profile?seconds=0.1")
    assert status == 409
    assert "already active" in payload["error"]


def test_profile_route_gone_under_kill_switch(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    status, payload = CoordinationService().handle(
        "POST", "/profile?seconds=1")
    assert status == 404


def test_profile_route_over_http(clean_telemetry, tmp_path):
    telemetry.configure(str(tmp_path))
    server, _thread = serve(CoordinationService(), host="127.0.0.1",
                            port=0, background=True)
    try:
        port = server.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/profile?seconds=0.1", method="POST")
        with urllib.request.urlopen(req) as resp:
            payload = json.loads(resp.read())
        assert payload["trace_dir"].startswith(str(tmp_path))
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# achieved Mvox/s derivation (fleet-status satellite)
# ---------------------------------------------------------------------------
def test_achieved_mvox_s_derivation():
    from chunkflow_tpu.parallel.restapi import achieved_mvox_s

    # serial path: inference/infer carries the seconds
    assert achieved_mvox_s({
        "chunkflow_inference_voxels_total": 4e6,
        "chunkflow_inference_infer_sum": 2.0,
    }) == pytest.approx(2.0)
    # pipelined path: dispatch + compute + drain carry them
    assert achieved_mvox_s({
        "chunkflow_inference_voxels_total": 3e6,
        "chunkflow_pipeline_dispatch_sum": 0.5,
        "chunkflow_pipeline_compute_sum": 0.25,
        "chunkflow_pipeline_drain_sum": 0.25,
    }) == pytest.approx(3.0)
    # no voxel count yet: the figure is simply absent
    assert achieved_mvox_s({"chunkflow_pipeline_compute_sum": 1.0}) is None
    assert achieved_mvox_s({}) is None


def test_shutdown_server_joins_listener_thread(clean_telemetry):
    """Regression (GL013 audit): callers holding only the server object
    (start_metrics_exporter, start_serving) used to have no way to join
    the listener thread — shutdown() left the handle dropped, a thread
    leak per start/stop cycle. The thread now rides on the server and
    shutdown_server joins it."""
    server = start_metrics_exporter(0, host="127.0.0.1")
    thread = server._serve_thread
    assert thread.is_alive()
    shutdown_server(server)
    assert not thread.is_alive()
    shutdown_server(None)  # telemetry-off exporter returns None: no-op


# ---------------------------------------------------------------------------
# /alerts route + SLO gauges (ISSUE 12)
# ---------------------------------------------------------------------------
def test_alerts_route_without_evaluator(clean_telemetry):
    svc = CoordinationService()
    status, payload = svc.handle("GET", "/alerts")
    assert status == 200
    assert payload["enabled"] is False
    assert payload["firing"] == [] and payload["objectives"] == []


def test_alerts_route_reports_burn_and_firing(clean_telemetry):
    from chunkflow_tpu.core import slo

    class Clock:
        t = 1000.0

    traffic = {"serving/requests": 0.0, "serving/errors": 0.0}
    ev = slo.SLOEvaluator(
        objectives=[slo.Objective("availability", target=0.9,
                                  total=("serving/requests",),
                                  bad=("serving/errors",))],
        rules=[slo.BurnRule("fast", short_s=2.0, long_s=6.0, burn=2.0,
                            severity="page")],
        period_s=120.0, clock=lambda: Clock.t,
        source=lambda: {"counters": dict(traffic), "qhists": {}},
    )
    slo._EVALUATOR = ev
    try:
        svc = CoordinationService()
        for _ in range(8):
            Clock.t += 1.0
            traffic["serving/requests"] += 10
            traffic["serving/errors"] += 8
            ev.tick()
        status, payload = svc.handle("GET", "/alerts")
        assert status == 200 and payload["enabled"] is True
        assert payload["firing"] == ["availability:fast"]
        obj = payload["objectives"][0]
        assert obj["name"] == "availability"
        assert obj["burn_rate"] >= 2.0
        assert obj["budget_remaining"] < 1.0
        assert obj["rules"][0]["firing"] is True
        # the same state renders as chunkflow_slo_* gauges on /metrics
        from chunkflow_tpu.parallel.restapi import firing_alerts

        metrics = parse_prometheus(render_prometheus())
        assert metrics["chunkflow_slo_availability_firing"] == 1.0
        assert metrics["chunkflow_slo_availability_burn_rate"] >= 2.0
        assert firing_alerts(metrics) == ["availability"]
    finally:
        slo._EVALUATOR = None


def test_alerts_route_gone_under_kill_switch(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    svc = CoordinationService()
    status, payload = svc.handle("GET", "/alerts")
    assert status == 404


def test_firing_alerts_parses_only_firing_gauges():
    from chunkflow_tpu.parallel.restapi import firing_alerts

    metrics = {
        "chunkflow_slo_availability_firing": 1.0,
        "chunkflow_slo_latency_firing": 0.0,
        "chunkflow_slo_deadline_firing": 1.0,
        "chunkflow_slo_latency_burn_rate": 99.0,  # not a firing gauge
        "chunkflow_other_total": 1.0,
    }
    assert firing_alerts(metrics) == ["availability", "deadline"]
    assert firing_alerts({}) == []
    assert firing_alerts(None) == []
