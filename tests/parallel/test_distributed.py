"""Multi-chip sharded inference on the 8-device virtual CPU mesh.

The identity oracle (reference tests/flow/divid_conquer/test_inferencer.py)
must hold through the shard_map + psum path exactly as it does single-chip:
identity forward + bump blend + reciprocal normalization reproduces the
input chunk.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.inference import engines
from chunkflow_tpu.inference.inferencer import Inferencer
from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.parallel.distributed import make_mesh, sharded_inference


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (see tests/conftest.py)")
    return make_mesh(8)


def test_sharded_identity_oracle(mesh):
    rng = np.random.default_rng(0)
    chunk = rng.random((12, 40, 40)).astype(np.float32)
    input_patch = (4, 16, 16)
    engine = engines.create_identity_engine(
        input_patch_size=input_patch,
        output_patch_size=input_patch,
        num_input_channels=1,
        num_output_channels=1,
    )
    out = sharded_inference(
        chunk,
        engine,
        input_patch_size=input_patch,
        output_patch_size=input_patch,
        output_patch_overlap=(2, 8, 8),
        batch_size=1,
        mesh=mesh,
    )
    arr = np.asarray(out)
    assert arr.shape == (1, 12, 40, 40)
    np.testing.assert_allclose(arr[0], chunk, atol=1e-5)


def test_sharded_matches_single_device(mesh):
    """Multi-chip psum-merged output == single-device fused program."""
    rng = np.random.default_rng(1)
    chunk = rng.random((8, 32, 32)).astype(np.float32)
    input_patch = (4, 16, 16)
    overlap = (2, 8, 8)

    engine = engines.create_flax_engine(
        "", None, input_patch,
        num_input_channels=1, num_output_channels=3,
    )
    sharded = np.asarray(
        sharded_inference(
            chunk,
            engine,
            input_patch_size=input_patch,
            output_patch_size=input_patch,
            output_patch_overlap=overlap,
            batch_size=1,
            mesh=mesh,
        )
    )

    inferencer = Inferencer(
        input_patch_size=input_patch,
        output_patch_overlap=overlap,
        num_output_channels=3,
        framework="flax",
        batch_size=1,
        crop_output_margin=False,
    )
    # reuse the same random init so the two paths share weights
    inferencer.engine = engine
    single = inferencer(Chunk(chunk)).array

    np.testing.assert_allclose(sharded, np.asarray(single), atol=1e-4)
