"""Task lifecycle layer: ledger, leases, retries, dead-letter, resume.

Covers the supervision units (parallel/lifecycle.py), the crash-recovery
contract (a claimed-but-unacked task reappears exactly once and is
ledger-skipped on replay), and the acceptance chaos run: with seeded
fault injection killing every lifecycle stage at least once over a
12-task queue, the drained volume is bit-identical to a fault-free run,
the ledger holds exactly one done-marker per bbox, and the poison task
lands in the dead-letter store with its failure reason.
"""
import os
import signal
import threading
import time

import numpy as np
import pytest
from click.testing import CliRunner

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.parallel import lifecycle
from chunkflow_tpu.parallel.lifecycle import (
    FileLedger,
    LeaseRenewer,
    LifecycleSupervisor,
    MemoryLedger,
    PermanentTaskError,
    TransientTaskError,
    backoff_delay,
    classify_error,
    open_ledger,
)
from chunkflow_tpu.parallel.queues import FileQueue, MemoryQueue, QueueBase
from chunkflow_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _clean_state():
    """Chaos plans and telemetry are process-global; never leak them."""
    chaos.reset()
    telemetry.reset()
    yield
    chaos.reset()
    telemetry.reset()
    # a test that errors mid-claim must not leave in-flight registrations
    for lc in lifecycle.inflight():
        lifecycle._unregister(lc)


# ---------------------------------------------------------------------------
# classification + backoff
# ---------------------------------------------------------------------------
def test_classify_error():
    assert classify_error(ValueError("bad bbox")) == "permanent"
    assert classify_error(PermanentTaskError("poison")) == "permanent"
    assert classify_error(OSError("storage blip")) == "transient"
    assert classify_error(RuntimeError("flake")) == "transient"
    assert classify_error(TransientTaskError("throttled")) == "transient"
    assert classify_error(chaos.ChaosError("injected")) == "transient"


def test_backoff_delay_bounds_and_determinism():
    import random

    rng = random.Random(7)
    delays = [backoff_delay(a, base=0.5, cap=4.0, rng=rng)
              for a in range(1, 8)]
    for attempt, delay in enumerate(delays, start=1):
        assert 0.0 <= delay <= min(4.0, 0.5 * 2 ** (attempt - 1))
    # seeded: the whole fleet's jitter is reproducible in tests
    rng2 = random.Random(7)
    assert delays == [backoff_delay(a, base=0.5, cap=4.0, rng=rng2)
                      for a in range(1, 8)]


# ---------------------------------------------------------------------------
# completion ledger
# ---------------------------------------------------------------------------
def test_memory_ledger_registry_and_idempotence():
    led = MemoryLedger.open("ml-test")
    assert MemoryLedger.open("ml-test") is led
    assert not led.is_done("0-4_0-4_0-4")
    led.mark_done("0-4_0-4_0-4")
    led.mark_done("0-4_0-4_0-4")  # idempotent
    assert led.is_done("0-4_0-4_0-4")
    assert led.keys() == ["0-4_0-4_0-4"]
    assert "0-4_0-4_0-4" in led and len(led) == 1


def test_file_ledger_durable_and_idempotent(tmp_path):
    led = FileLedger(str(tmp_path / "ledger"))
    led.mark_done("0-4_0-4_0-4")
    led.mark_done("0-4_0-4_0-4")
    # a fresh handle on the same dir (a new process resuming) sees it
    led2 = FileLedger(str(tmp_path / "ledger"))
    assert led2.is_done("0-4_0-4_0-4")
    assert led2.keys() == ["0-4_0-4_0-4"]
    # exactly one marker file per key
    done = [n for n in os.listdir(led.dir) if n.endswith(".done")]
    assert len(done) == 1


def test_open_ledger_specs(tmp_path):
    assert isinstance(open_ledger("memory://x"), MemoryLedger)
    assert isinstance(open_ledger(str(tmp_path / "ld")), FileLedger)
    assert isinstance(open_ledger("file://" + str(tmp_path / "ld2")),
                      FileLedger)


# ---------------------------------------------------------------------------
# lease heartbeats
# ---------------------------------------------------------------------------
def test_lease_renewer_keeps_slow_task_claimed():
    q = MemoryQueue("lease-slow", visibility_timeout=0.15)
    q.send_messages(["task"])
    handle, _ = q.receive()
    renewer = LeaseRenewer(q, handle, interval=0.05).start()
    try:
        time.sleep(0.4)  # well past the static visibility timeout
        assert q.receive() is None  # heartbeat held the lease
        assert renewer.renewals >= 3
    finally:
        renewer.stop()
    time.sleep(0.2)
    assert q.receive() is not None  # no heartbeat: lease expires again
    assert telemetry.snapshot()["counters"]["lease/renewals"] >= 3


def test_supervisor_heartbeat_holds_in_flight_leases():
    """The supervisor runs ONE heartbeat thread for all of its in-flight
    claims (not a thread per task): a slow task outliving the static
    visibility timeout stays leased until commit."""
    q = MemoryQueue("hb-sup", visibility_timeout=0.15)
    q.send_messages(["slow-task"])
    sup = LifecycleSupervisor(q, lease_renew=0.05)
    gen = sup.tasks(num=1)
    lc = next(gen)
    try:
        time.sleep(0.4)  # "compute" well past the visibility timeout
        assert q.receive() is None  # heartbeat held the lease
        assert telemetry.snapshot()["counters"]["lease/renewals"] >= 3
        lc.commit()
        assert len(q) == 0
    finally:
        gen.close()  # retires the heartbeat + restores SIGTERM


def test_lease_renewer_survives_renew_failure():
    class BrokenQueue(QueueBase):
        def renew(self, handle, timeout=None):
            raise OSError("queue gone")

    renewer = LeaseRenewer(BrokenQueue(), "h", interval=0.05).start()
    time.sleep(0.3)
    renewer.stop()  # must not have died with an unhandled exception
    assert renewer.renewals == 0
    counters = telemetry.snapshot()["counters"]
    assert counters["lease/renew_failures"] >= 1
    # every failed attempt (3 per give-up) is individually counted
    assert counters["lifecycle/renew_errors"] \
        >= 3 * counters["lease/renew_failures"]


def test_renew_retry_recovers_from_transient_error():
    """A throttled/blipped renew is retried in place with backoff: two
    transient failures then success must still land the renewal — the
    heartbeat loses nothing — with the attempts visible in
    ``lifecycle/renew_errors`` and no ``lease/renew_failures``."""
    from chunkflow_tpu.parallel.lifecycle import _renew_with_retry

    class FlakyQueue(QueueBase):
        def __init__(self):
            self.calls = 0

        def renew(self, handle, timeout=None):
            self.calls += 1
            if self.calls <= 2:
                raise IOError("SQS throttle")

    q = FlakyQueue()
    assert _renew_with_retry(q, "h", base=0.001) is True
    assert q.calls == 3
    counters = telemetry.snapshot()["counters"]
    assert counters["lifecycle/renew_errors"] == 2
    assert counters["lease/renewals"] == 1
    assert "lease/renew_failures" not in counters


def test_heartbeat_thread_survives_registry_error(monkeypatch):
    """Nothing may kill the supervisor's single heartbeat thread: even
    an error OUTSIDE the per-lease renew (registry iteration blowing
    up) is swallowed and counted, and the thread keeps renewing on the
    next tick."""
    import chunkflow_tpu.parallel.lifecycle as lifecycle_mod

    q = MemoryQueue("hb-survive", visibility_timeout=0.15)
    q.send_messages(["t"])
    sup = LifecycleSupervisor(q, lease_renew=0.05)
    blown = {"n": 0}
    real_inflight = lifecycle_mod.inflight

    def exploding_inflight():
        if blown["n"] < 2:
            blown["n"] += 1
            raise RuntimeError("registry iteration exploded")
        return real_inflight()

    monkeypatch.setattr(lifecycle_mod, "inflight", exploding_inflight)
    gen = sup.tasks(num=1)
    lc = next(gen)
    try:
        time.sleep(0.5)  # two exploding ticks, then renewals resume
        assert q.receive() is None  # lease still held past the timeout
        counters = telemetry.snapshot()["counters"]
        assert counters["lifecycle/renew_errors"] >= 2
        assert counters["lease/renewals"] >= 1
        lc.commit()
    finally:
        gen.close()


# ---------------------------------------------------------------------------
# supervisor claim/commit/release
# ---------------------------------------------------------------------------
def test_claim_skips_ledgered_task_idempotently():
    q = MemoryQueue("claim-skip", visibility_timeout=100)
    led = MemoryLedger("claim-skip-ledger")
    led.mark_done("0-4_0-4_0-4")
    q.send_messages(["0-4_0-4_0-4"])
    sup = LifecycleSupervisor(q, ledger=led)
    handle, body = q.receive()
    assert sup.claim(handle, body) is None
    assert len(q) == 0 and not q.invisible  # acked, not redelivered
    assert telemetry.snapshot()["counters"]["ledger/skips"] == 1


def test_claim_dead_letters_crash_loop():
    """Redelivered past the retry budget with no recorded failure: the
    worker died mid-compute every time — dead-letter at claim. Crash
    deliveries are modeled as lease EXPIRY (a dead worker never nacks);
    a polite nack is a handback and does not burn the budget."""
    q = MemoryQueue("claim-loop", visibility_timeout=100)
    q.send_messages(["0-4_0-4_0-4"])
    sup = LifecycleSupervisor(q, max_retries=2)
    for _ in range(2):  # two crashed deliveries
        handle, body = q.receive()
        wire, _deadline = q.invisible[handle]
        q.invisible[handle] = (wire, 0.0)  # worker died: lease runs out
    handle, body = q.receive()  # third delivery: receives=3 > 2
    assert sup.claim(handle, body) is None
    assert len(q) == 0
    dead = q.dead_letters()
    assert len(dead) == 1 and "crash loop" in dead[0]["reason"]


def test_commit_acks_and_marks_ledger():
    q = MemoryQueue("commit", visibility_timeout=100)
    led = MemoryLedger("commit-ledger")
    q.send_messages(["0-4_0-4_0-4"])
    sup = LifecycleSupervisor(q, ledger=led)
    handle, body = q.receive()
    lc = sup.claim(handle, body)
    lc.task = {"log": {"timer": {}}}
    lc.commit()
    assert led.is_done(body)
    assert len(q) == 0 and not q.invisible
    assert lifecycle.inflight() == []
    lc.commit()  # terminal transitions are idempotent


def test_release_transient_retries_with_backoff():
    q = MemoryQueue("release-retry", visibility_timeout=100)
    q.send_messages(["0-4_0-4_0-4"])
    sup = LifecycleSupervisor(q, max_retries=3, backoff_base=0.02,
                              backoff_cap=0.05, seed=1)
    handle, body = q.receive()
    lc = sup.claim(handle, body)
    assert lc.release(OSError("storage blip")) == "retried"
    assert lifecycle.inflight() == []
    time.sleep(0.1)  # > the backoff cap
    handle2, body2 = q.receive()
    assert body2 == body
    assert q.receive_count(handle2) == 2


def test_release_permanent_dead_letters_immediately():
    q = MemoryQueue("release-perm", visibility_timeout=100)
    q.send_messages(["NOT_A_BBOX"])
    sup = LifecycleSupervisor(q, max_retries=5)
    handle, body = q.receive()
    lc = sup.claim(handle, body)
    assert lc.release(ValueError("cannot parse")) == "dead"
    dead = q.dead_letters()
    assert len(dead) == 1
    assert "ValueError" in dead[0]["reason"]
    assert "cannot parse" in dead[0]["reason"]


def test_release_exhausted_budget_dead_letters():
    """A task that fails --max-retries times lands in the dead-letter
    store (acceptance criterion)."""
    q = MemoryQueue("release-budget", visibility_timeout=100)
    q.send_messages(["0-4_0-4_0-4"])
    sup = LifecycleSupervisor(q, max_retries=2, backoff_base=0.01,
                              backoff_cap=0.02, seed=3)
    outcomes = []
    while True:
        item = q.receive()
        if item is None:
            time.sleep(0.03)
            item = q.receive()
            if item is None:
                break
        lc = sup.claim(*item)
        if lc is None:
            break
        outcomes.append(lc.release(RuntimeError("flaky op")))
        if outcomes[-1] == "dead":
            break
    assert outcomes == ["retried", "dead"]  # fails max_retries=2 times
    dead = q.dead_letters()
    assert len(dead) == 1 and "flaky op" in dead[0]["reason"]
    snap = telemetry.snapshot()["counters"]
    assert snap["tasks/retried"] == 1
    assert snap["tasks/dead_lettered"] == 1


def test_release_preemption_nacks_and_flushes_writes():
    from concurrent.futures import ThreadPoolExecutor

    q = MemoryQueue("release-preempt", visibility_timeout=100)
    q.send_messages(["0-4_0-4_0-4"])
    sup = LifecycleSupervisor(q)
    handle, body = q.receive()
    lc = sup.claim(handle, body)
    flushed = threading.Event()
    with ThreadPoolExecutor(1) as pool:
        lc.task = {"log": {"timer": {}},
                   "pending_writes": [pool.submit(flushed.set)]}
        assert lc.release(SystemExit(143)) == "preempted"
    assert flushed.is_set()  # pending writes flushed before exit
    handle2, body2 = q.receive()  # immediately visible again
    assert body2 == body


def test_handle_failure_charges_culprit_surrenders_bystanders():
    """One task's failure must not burn the retry budget of every task
    in the pipelined in-flight window: the tagged culprit is released
    (retried/dead-lettered), the bystanders surrender (immediate nack,
    no failure recorded)."""
    q = MemoryQueue("culprit", visibility_timeout=100)
    q.send_messages(["a", "b", "c"])
    sup = LifecycleSupervisor(q, max_retries=3, backoff_base=0.01,
                              backoff_cap=0.01, seed=0)
    lcs = [sup.claim(*q.receive()) for _ in range(3)]
    exc = RuntimeError("op died on b")
    lifecycle.tag_culprit(exc, lcs[1])
    lifecycle.tag_culprit(exc, lcs[2])  # first tag wins
    assert lifecycle.handle_failure(exc) is True
    snap = telemetry.snapshot()["counters"]
    assert snap["tasks/retried"] == 1  # only the culprit
    assert snap["tasks/surrendered"] == 2
    # bystanders redeliverable immediately; culprit after its backoff
    assert len(q) == 2
    time.sleep(0.05)
    assert len(q) == 3


def test_tag_culprit_via_task_dict():
    q = MemoryQueue("culprit-dict", visibility_timeout=100)
    q.send_messages(["a", "b"])
    sup = LifecycleSupervisor(q, backoff_base=0.01, backoff_cap=0.01)
    lc_a = sup.claim(*q.receive())
    lc_b = sup.claim(*q.receive())
    lc_a.task = {"log": {"timer": {}}, "lifecycle": lc_a}
    exc = OSError("storage blip")
    lifecycle.tag_culprit(exc, lc_a.task)  # operators tag the task dict
    assert lifecycle.handle_failure(exc) is True
    snap = telemetry.snapshot()["counters"]
    assert snap["tasks/retried"] == 1
    assert snap["tasks/surrendered"] == 1


def test_handle_failure_contains_task_errors_only():
    assert lifecycle.handle_failure(RuntimeError("x")) is False  # no inflight
    q = MemoryQueue("handle-fail", visibility_timeout=100)
    q.send_messages(["a", "b"])
    sup = LifecycleSupervisor(q, max_retries=3, backoff_base=0.01,
                              backoff_cap=0.01, seed=0)
    lcs = [sup.claim(*q.receive()) for _ in range(2)]
    assert len(lifecycle.inflight()) == 2
    # task failure: every in-flight task released, worker continues
    assert lifecycle.handle_failure(RuntimeError("op died")) is True
    assert lifecycle.inflight() == []
    time.sleep(0.05)
    assert len(q) == 2  # both back after backoff
    # preemption: released (nacked) but the worker must exit
    lcs = [sup.claim(*q.receive()) for _ in range(2)]
    assert lifecycle.handle_failure(SystemExit(143)) is False
    assert len(q) == 2  # nacked immediately, no backoff


def test_preemption_handler_routes_sigterm():
    restore = lifecycle.install_preemption_handler()
    try:
        with pytest.raises(SystemExit) as exc_info:
            os.kill(os.getpid(), signal.SIGTERM)
            # the signal is delivered on the next bytecode boundary
            time.sleep(0.5)
        assert exc_info.value.code == 143
    finally:
        restore()
    assert signal.getsignal(signal.SIGTERM) is not None


# ---------------------------------------------------------------------------
# crash recovery: exactly-once effects from at-least-once delivery
# ---------------------------------------------------------------------------
def test_crash_recovery_exactly_once(tmp_path):
    """A claimed task whose worker dies (no ack) reappears exactly once
    after the visibility timeout, completes on retry, and is
    ledger-skipped when the whole queue is replayed."""
    q = FileQueue(str(tmp_path / "q"), visibility_timeout=0.2)
    ledger = FileLedger(str(tmp_path / "ledger"))
    q.send_messages(["0-4_0-4_0-4"])
    sup = LifecycleSupervisor(q, ledger=ledger, max_retries=3)

    # worker 1 claims and dies: no ack, no recorded failure
    handle, body = q.receive()
    lc = sup.claim(handle, body)
    assert lc is not None
    lifecycle._unregister(lc)  # simulated process death

    assert q.receive() is None  # invisible while "in compute"
    time.sleep(0.3)
    item = q.receive()  # reappears after the timeout...
    assert item is not None and item[1] == body
    assert q.receive() is None  # ...exactly once

    # worker 2 completes the retry
    lc2 = sup.claim(*item)
    assert lc2 is not None and lc2.receives == 2
    lc2.commit()
    assert ledger.is_done(body)
    assert len(q) == 0

    # replay the entire queue (operator re-seeds after an interruption):
    # the committed task is skipped idempotently, no recompute
    q.send_messages([body])
    item = q.receive()
    assert sup.claim(*item) is None  # ledger skip acks it
    assert len(q) == 0 and q.receive() is None
    assert telemetry.snapshot()["counters"]["ledger/skips"] == 1


# ---------------------------------------------------------------------------
# acceptance: seeded chaos over a 12-task volume through the full CLI
# ---------------------------------------------------------------------------
LIFECYCLE_POINTS = (
    "lifecycle/claim",       # task claimed, before compute
    "op/load-h5",            # upstream load operator
    "op/save-h5",            # storage write operator
    "lifecycle/pre_ledger",  # writes durable, ledger not yet marked
    "lifecycle/pre_ack",     # ledger marked, queue not yet acked
)
SCHEDULER_POINTS = (
    "scheduler/dispatch",    # adaptive scheduler device dispatch
    "scheduler/post",        # adaptive scheduler host post stage
)


def _run_worker(tmp_path, tag, qdir, in_dir, ledger=None):
    out_dir = tmp_path / f"out-{tag}"
    out_dir.mkdir()
    from chunkflow_tpu.flow.cli import main

    # the retry budget is receive-count based (SQS semantics): innocent
    # bystander redeliveries — surrendered claims when ANOTHER in-flight
    # task's failure tears down the shared chain — also count a receive,
    # so the budget must exceed (pipeline depth x injected kills); 10
    # covers the 7-kill plan with margin. The tight-budget dead-letter
    # path is covered by test_release_exhausted_budget_dead_letters.
    args = [
        "fetch-task-from-queue", "-q", qdir, "-r", "20",
        "--max-retries", "10", "--lease-renew", "0.25",
        "--backoff-base", "0.01", "--backoff-cap", "0.05",
    ]
    if ledger:
        args += ["--ledger", ledger]
    args += [
        "load-h5", "-f", str(in_dir) + "/",
        "inference", "-s", "4", "8", "8", "-v", "1", "2", "2",
        "-c", "1", "-f", "identity", "--no-crop-output-margin",
        "--async-depth", "2",
        "save-h5", "--file-name", str(out_dir) + "/",
        "delete-task-in-queue",
    ]
    result = CliRunner().invoke(main, args, catch_exceptions=False)
    assert result.exit_code == 0, result.output
    return out_dir


def _seed_volume(tmp_path, tag):
    """12 distinct random input chunks + a queue holding their bboxes."""
    import itertools

    from chunkflow_tpu.chunk import Chunk
    from chunkflow_tpu.parallel.queues import open_queue

    in_dir = tmp_path / f"in-{tag}"
    in_dir.mkdir()
    rng = np.random.default_rng(11)
    bodies = []
    for zi, yi, xi in itertools.product(range(3), range(2), range(2)):
        off = (zi * 8, yi * 16, xi * 16)
        c = Chunk(rng.random((8, 16, 16)).astype(np.float32),
                  voxel_offset=off)
        c.to_h5(str(in_dir) + "/")
        bodies.append(c.bbox.string)
    qdir = str(tmp_path / f"q-{tag}")
    open_queue(qdir).send_messages(bodies)
    return qdir, in_dir, bodies


def _load_outputs(out_dir):
    import h5py

    outputs = {}
    for path in sorted(out_dir.iterdir()):
        with h5py.File(path, "r") as f:
            outputs[path.name] = np.asarray(f["main"][:])
    return outputs


@pytest.mark.parametrize("sched", ["adaptive", "static"])
def test_chaos_run_converges_bit_identical(tmp_path, monkeypatch, sched):
    """The acceptance run: every lifecycle stage killed at least once
    across a 12-task queue + one poison task; the drained volume is
    bit-identical to the fault-free leg, the ledger holds exactly one
    done-marker per bbox, no task lost or double-committed, and the
    poison task is dead-lettered with its reason and requeueable via
    the CLI. Both scheduler modes: the static (PR 2) pipeline has no
    scheduler/* stages, so those kill points only apply to adaptive."""
    points = LIFECYCLE_POINTS + (
        SCHEDULER_POINTS if sched == "adaptive" else ()
    )
    monkeypatch.setenv("CHUNKFLOW_SCHED", sched)
    monkeypatch.setattr(QueueBase, "retry_sleep", 0.02)

    # fault-free reference leg
    qdir, in_dir, bodies = _seed_volume(tmp_path, "ref")
    ref_out = _run_worker(tmp_path, "ref", qdir, in_dir)
    reference = _load_outputs(ref_out)
    assert len(reference) == 12

    # chaos leg: same inputs, seeded kills at every stage + a poison task
    qdir, in_dir, bodies = _seed_volume(tmp_path, "chaos")
    from chunkflow_tpu.parallel.queues import open_queue

    open_queue(qdir).send_messages(["NOT_A_BBOX"])
    ledger_dir = str(tmp_path / "ledger")
    chaos.configure("once=" + ",".join(points))
    try:
        chaos_out = _run_worker(
            tmp_path, "chaos", qdir, in_dir, ledger=ledger_dir
        )
        injected = chaos.injections()
    finally:
        chaos.reset()

    # every lifecycle stage died at least once
    for point in points:
        assert injected.get(point, 0) >= 1, (point, injected)

    # bit-identical convergence
    faulty = _load_outputs(chaos_out)
    assert sorted(faulty) == sorted(reference)
    for name in reference:
        assert np.array_equal(faulty[name], reference[name]), name

    # exactly one done-marker per bbox; no task lost or double-committed
    ledger = FileLedger(ledger_dir)
    assert sorted(ledger.keys()) == sorted(bodies)

    # the poison task — and ONLY the poison task — is dead-lettered,
    # with its failure reason (innocent bystanders of injected kills
    # must not be falsely dead-lettered)
    queue = open_queue(qdir)
    assert len(queue) == 0
    dead = queue.dead_letters()
    assert len(dead) == 1, dead
    assert dead[0]["body"] == "NOT_A_BBOX"
    assert "ValueError" in dead[0]["reason"]

    # ...and requeueable via the CLI
    from chunkflow_tpu.flow.cli import main

    result = CliRunner().invoke(
        main, ["dead-letter", "-q", qdir, "--requeue"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0
    assert "requeued 1" in result.output
    assert len(queue) == 1 and queue.dead_letters() == []


def test_supervised_resume_after_interrupted_run(tmp_path, monkeypatch):
    """Kill a run partway (SystemExit mid-stream), then rerun the SAME
    queue replay: already-committed tasks ledger-skip, the rest
    complete, and the output set is whole."""
    monkeypatch.setattr(QueueBase, "retry_sleep", 0.02)
    qdir, in_dir, bodies = _seed_volume(tmp_path, "resume")
    ledger_dir = str(tmp_path / "resume-ledger")

    from chunkflow_tpu.flow.cli import main

    out_dir = tmp_path / "out-resume"
    out_dir.mkdir()

    def worker_args(num):
        args = [
            "fetch-task-from-queue", "-q", qdir, "-r", "3",
            "--ledger", ledger_dir, "--max-retries", "2",
            "--backoff-base", "0.01",
        ]
        if num is not None:
            args += ["--num", str(num)]
        return args + [
            "load-h5", "-f", str(in_dir) + "/",
            "save-h5", "--file-name", str(out_dir) + "/",
            "delete-task-in-queue",
        ]

    # first worker processes 5 tasks, then "the VM is reclaimed"
    result = CliRunner().invoke(main, worker_args(5), catch_exceptions=False)
    assert result.exit_code == 0, result.output
    ledger = FileLedger(ledger_dir)
    assert len(ledger.keys()) == 5

    # operator replays the WHOLE task grid into the queue (the standard
    # resume move: no bookkeeping of which tasks remain)
    from chunkflow_tpu.parallel.queues import open_queue

    queue = open_queue(qdir)
    queue.send_messages(bodies)

    telemetry.reset()
    result = CliRunner().invoke(main, worker_args(None),
                                catch_exceptions=False)
    assert result.exit_code == 0, result.output
    assert sorted(FileLedger(ledger_dir).keys()) == sorted(bodies)
    assert len(queue) == 0
    assert len(_load_outputs(out_dir)) == 12
    # the 5 committed tasks were skipped, not recomputed
    assert telemetry.snapshot()["counters"]["ledger/skips"] >= 5
