"""Trace-id propagation through the queue lifecycle (ISSUE 6).

A trace id is minted once, at submission, and must survive every hop a
task can take: claim, nack (preemption hand-back), re-claim by a second
worker, dead-letter, and operator requeue — for all three queue
backends. The wire envelope is invisible to consumers: bodies come back
exactly as submitted.
"""
import pytest

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.parallel.queues import (
    FileQueue,
    MemoryQueue,
    SQSQueue,
    pack_task,
    unpack_task,
)
from tests.parallel.test_queues import FakeSQSClient


@pytest.fixture(autouse=True)
def clean_telemetry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def make_queue(backend, tmp_path):
    """A fresh queue plus a factory for a 'second worker' view of the
    same queue (same storage, new client object where that's
    meaningful)."""
    if backend == "memory":
        MemoryQueue._registry.pop("trace-test", None)
        q = MemoryQueue.open("trace-test", visibility_timeout=600)
        return q, lambda: MemoryQueue.open("trace-test")
    if backend == "file":
        path = str(tmp_path / "q")
        return FileQueue(path, visibility_timeout=600), \
            lambda: FileQueue(path, visibility_timeout=600)
    client = FakeSQSClient()
    q = SQSQueue("trace-test", client=client)
    return q, lambda: SQSQueue("trace-test", client=client)


def test_pack_unpack_roundtrip_and_idempotence():
    wire = pack_task("0-4_0-4_0-4")
    body, trace = unpack_task(wire)
    assert body == "0-4_0-4_0-4"
    assert trace is not None and len(trace) == 32
    # idempotent: re-packing an envelope keeps its original trace id
    assert pack_task(wire) == wire
    # pre-envelope payloads (an old queue on disk) unwrap to themselves
    assert unpack_task("plain-bbox") == ("plain-bbox", None)
    assert unpack_task('{"not": "ours"}') == ('{"not": "ours"}', None)


@pytest.mark.parametrize("backend", ["memory", "file", "sqs"])
def test_trace_survives_nack_reclaim_dead_letter(backend, tmp_path):
    """claim → nack → re-claim on a second worker → dead-letter: one
    trace id throughout, and the listed dead-letter entry carries it."""
    q, second_worker = make_queue(backend, tmp_path)
    q.send_messages(["0-4_0-4_0-4"])

    handle, body = q.receive()
    assert body == "0-4_0-4_0-4"  # envelope is wire-only
    trace = q.trace_id(handle)
    assert trace is not None and len(trace) == 32

    q.nack(handle)  # preempted worker hands the claim back

    q2 = second_worker()
    item = q2.receive()
    assert item is not None
    handle2, body2 = item
    assert body2 == "0-4_0-4_0-4"
    assert q2.trace_id(handle2) == trace  # the hop kept the identity

    q2.dead_letter(handle2, reason="poison")
    dead = q2.dead_letters()
    assert len(dead) == 1
    assert dead[0]["body"] == "0-4_0-4_0-4"
    assert dead[0]["trace_id"] == trace
    assert dead[0]["reason"] == "poison"


@pytest.mark.parametrize("backend", ["memory", "file", "sqs"])
def test_trace_survives_dead_letter_requeue(backend, tmp_path):
    """An operator requeue (`chunkflow dead-letter --requeue`) must not
    mint a new identity: the task's history stays one timeline."""
    q, second_worker = make_queue(backend, tmp_path)
    q.send_messages(["8-12_0-4_0-4"])
    handle, _ = q.receive()
    trace = q.trace_id(handle)
    q.dead_letter(handle, reason="transient outage")
    assert q.requeue_dead() == 1

    q2 = second_worker()
    item = q2.receive()
    assert item is not None
    handle2, body2 = item
    assert body2 == "8-12_0-4_0-4"
    assert q2.trace_id(handle2) == trace


@pytest.mark.parametrize("backend", ["memory", "file"])
def test_trace_survives_visibility_expiry(backend, tmp_path):
    """The crashed-worker path: a claim that expires (no nack, no ack)
    reappears with the same trace id — receive-side bookkeeping rides
    the wire envelope, not worker memory."""
    q, _ = make_queue(backend, tmp_path)
    q.visibility_timeout = 0.05
    q.send_messages(["16-20_0-4_0-4"])
    handle, _ = q.receive()
    trace = q.trace_id(handle)
    import time

    time.sleep(0.1)  # the worker "crashed"; the janitor requeues
    item = q.receive()
    assert item is not None
    handle2, body = item
    assert body == "16-20_0-4_0-4"
    assert q.trace_id(handle2) == trace


def test_submit_event_anchors_the_timeline(tmp_path):
    """send_messages emits one queue/submit event per task (when a sink
    is configured) carrying the minted trace id — the first entry of
    every per-trace timeline."""
    import json

    path = telemetry.configure(str(tmp_path / "metrics"))
    q = FileQueue(str(tmp_path / "q"))
    q.send_messages(["0-4_0-4_0-4", "4-8_0-4_0-4"])
    telemetry.flush()
    events = [json.loads(line) for line in open(path) if line.strip()]
    submits = [e for e in events if e.get("name") == "queue/submit"]
    assert len(submits) == 2
    assert {e["body"] for e in submits} == {"0-4_0-4_0-4", "4-8_0-4_0-4"}
    for e in submits:
        assert len(e["trace_id"]) == 32
        assert e["worker"] == telemetry.worker_id()
    # the claimed trace matches the submitted one
    handle, body = q.receive()
    submitted = {e["body"]: e["trace_id"] for e in submits}
    assert q.trace_id(handle) == submitted[body]


def test_queue_counters_ride_the_registry():
    MemoryQueue._registry.pop("counter-test", None)
    q = MemoryQueue.open("counter-test")
    q.send_messages(["a", "b"])
    q.receive()
    snap = telemetry.snapshot()
    assert snap["counters"]["queue/sent"] == 2
    assert snap["counters"]["queue/receives"] == 1


def test_stats_surface(tmp_path):
    """queue.stats() is the fleet-status substrate: pending / in-flight
    / dead / receives for every backend."""
    # memory
    MemoryQueue._registry.pop("stats-test", None)
    q = MemoryQueue.open("stats-test")
    q.send_messages(["a", "b", "c"])
    h, _ = q.receive()
    q.dead_letter(h, reason="x")
    h2, _ = q.receive()
    # receives tracks live handles only: the dead-lettered task's count
    # moved into its dead-letter entry
    assert q.stats() == {"pending": 1, "inflight": 1, "dead": 1,
                         "receives": 1}
    # file
    fq = FileQueue(str(tmp_path / "statsq"))
    fq.send_messages(["a", "b"])
    fq.receive()
    s = fq.stats()
    assert (s["pending"], s["inflight"], s["dead"], s["receives"]) \
        == (1, 1, 0, 1)
    # sqs (fake client reports approximate depths)
    sq = SQSQueue("stats-test", client=FakeSQSClient())
    sq.send_messages(["a", "b"])
    sq.receive()
    s = sq.stats()
    assert s["pending"] == 1 and s["inflight"] == 1 and s["receives"] == 1
