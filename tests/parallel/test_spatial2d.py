"""2D spatially-sharded inference: identity oracle across BOTH chip-
boundary directions (y and x), incl. corner spill paths, on the 8-device
virtual CPU mesh."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")


def _run(shape, mesh_shape, batch_size=2):
    from chunkflow_tpu.chunk.base import Chunk  # noqa: F401 (jax init order)
    from chunkflow_tpu.inference import engines
    from chunkflow_tpu.parallel.spatial2d import (
        make_mesh_2d,
        spatial2d_sharded_inference,
    )

    pin = (4, 16, 16)
    pout = (4, 16, 16)
    overlap = (2, 8, 8)
    engine = engines.create_identity_engine(
        input_patch_size=pin, output_patch_size=pout,
        num_input_channels=1, num_output_channels=2,
    )
    mesh = make_mesh_2d(mesh_shape)
    rng = np.random.default_rng(3)
    chunk = rng.random(shape).astype(np.float32)
    out = spatial2d_sharded_inference(
        chunk, engine, pin, pout, overlap,
        batch_size=batch_size, mesh=mesh,
    )
    return chunk, np.asarray(out)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2)])
def test_identity_oracle_across_2d_boundaries(mesh_shape):
    chunk, out = _run((8, 64, 64), mesh_shape)
    assert out.shape == (2, 8, 64, 64)
    for c in range(2):
        np.testing.assert_allclose(out[c], chunk, atol=1e-5)


def test_identity_oracle_non_divisible_extent():
    # 50x46 on a (2,4) mesh: both axes pad to slab multiples and crop back
    chunk, out = _run((8, 50, 46), (2, 4))
    assert out.shape == (2, 8, 50, 46)
    for c in range(2):
        np.testing.assert_allclose(out[c], chunk, atol=1e-5)


def test_matches_single_device_program():
    """The 2D-sharded result equals the plain single-device fused program
    bit-for-bit-ish on the same chunk."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    pin, overlap = (4, 16, 16), (2, 8, 8)
    chunk, out2d = _run((8, 64, 48), (2, 4))
    inferencer = Inferencer(
        input_patch_size=pin, output_patch_overlap=overlap,
        num_output_channels=2, framework="identity", batch_size=2,
        crop_output_margin=False,
    )
    ref = np.asarray(inferencer(Chunk(chunk)).array)
    np.testing.assert_allclose(out2d, ref, atol=1e-5)
