"""Unified multi-chip engine: ONE parametrized parity matrix.

Replaces the per-variant test trios (test_distributed / test_spatial /
test_spatial2d): every mesh shape the spec grammar can express runs the
same traffic — plain, ragged, uint8, crop-margin, packed-serve — against
the single-device reference program and must match **bitwise** (the
engine's contract: forward sharded, reference accumulation replayed;
chunkflow_tpu/parallel/engine.py). Runs on the 8-device virtual CPU mesh
(tests/conftest.py)."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.inference import engines
from chunkflow_tpu.inference.inferencer import Inferencer
from chunkflow_tpu.parallel.engine import (
    MeshSpec,
    parse_mesh_spec,
    sharded_inference,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs 8 virtual devices (see tests/conftest.py)",
)

PIN = (4, 16, 16)
OVERLAP = (2, 8, 8)

# the matrix: every engine kind and several shapes of each — mesh
# shapes 1 (kill switch) / 2 / 4 / 8 on the data axis plus 1D and 2D
# spatial layouts, per the ISSUE 13 acceptance grid; ISSUE 19 adds the
# pipeline (stage-parallel) kind — the identity engines declare the
# stage protocol, so the whole traffic grid covers it too. The
# sharded (slab) blend replay is the DEFAULT, so every row below
# exercises it; the replicated flip is pinned separately.
MESHES = ["1", "data=2", "data=4", "data=8", "y=2", "y=4", "y=8",
          "y=2,x=2", "y=4,x=2", "y=2,x=4", "pipeline=4", "pipeline=8"]


@pytest.fixture(scope="module")
def conv_engine():
    """A real conv engine (not identity): bitwise parity must hold for
    arbitrary float math, not just the identity oracle."""
    return engines.create_flax_engine(
        "", None, PIN, num_input_channels=1, num_output_channels=3,
    )


@pytest.fixture(scope="module")
def id_engine():
    """The identity engine drives the wide matrix: its programs compile
    in milliseconds on the virtual CPU mesh, so 10 mesh shapes x 4
    traffic classes stay inside the tier-1 wall-clock budget; the
    conv-engine spot checks below pin the arbitrary-float-math case."""
    return engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=PIN,
        num_input_channels=1, num_output_channels=3,
    )


def make_inferencer(engine, **kw):
    kw.setdefault("crop_output_margin", False)
    return Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=OVERLAP,
        num_output_channels=3,
        framework="prebuilt",
        batch_size=2,
        engine=engine,
        **kw,
    )


# one single-device reference inferencer and one mesh inferencer per
# (mesh, crop) config, shared across the whole matrix — a fresh
# Inferencer per case would recompile every program 40 times. The crop
# config uses a central-crop identity engine (pout < pin) so the margin
# crop is a REAL (1, 4, 4) crop, not a zero-width no-op.
@pytest.fixture(scope="module")
def shared(id_engine):
    crop_engine = engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=(2, 8, 8),
        num_input_channels=1, num_output_channels=3,
    )
    cache: dict = {}

    def get(mesh=None, crop=False):
        key = (mesh, crop)
        if key not in cache:
            if crop:
                cache[key] = Inferencer(
                    input_patch_size=PIN,
                    output_patch_size=(2, 8, 8),
                    output_patch_overlap=(1, 4, 4),
                    num_output_channels=3,
                    framework="prebuilt",
                    batch_size=2,
                    engine=crop_engine,
                    mesh=mesh,
                    crop_output_margin=True,
                )
            else:
                cache[key] = make_inferencer(id_engine, mesh=mesh)
        return cache[key]

    return get


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------
def test_spec_grammar():
    assert parse_mesh_spec(None).kind == "single"
    assert parse_mesh_spec("1").kind == "single"
    assert parse_mesh_spec("off").kind == "single"
    assert parse_mesh_spec("auto", 8) == MeshSpec("data", (8,))
    assert parse_mesh_spec("auto", 1).kind == "single"
    assert parse_mesh_spec("8") == MeshSpec("data", (8,))
    assert parse_mesh_spec("data=4") == MeshSpec("data", (4,))
    assert parse_mesh_spec("y=4") == MeshSpec("spatial", (4, 1))
    assert parse_mesh_spec("x=4") == MeshSpec("spatial", (1, 4))
    assert parse_mesh_spec("y=4,x=2") == MeshSpec("spatial", (4, 2))
    assert parse_mesh_spec("y=1,x=1").kind == "single"
    assert parse_mesh_spec("data=8").describe() == "data=8"
    assert parse_mesh_spec("y=4,x=2").describe() == "y=4,x=2"
    assert parse_mesh_spec("pipeline=4") == MeshSpec("pipeline", (4,))
    assert parse_mesh_spec("pipeline=4").describe() == "pipeline=4"
    assert parse_mesh_spec("pipeline=1").kind == "single"
    with pytest.raises(ValueError, match="bad mesh spec"):
        parse_mesh_spec("z=4")
    with pytest.raises(ValueError, match="does not compose"):
        parse_mesh_spec("data=4,y=2")
    with pytest.raises(ValueError, match="does not compose"):
        parse_mesh_spec("pipeline=2,y=2")
    with pytest.raises(ValueError, match="devices"):
        parse_mesh_spec("pipeline=16", 8)
    with pytest.raises(ValueError, match="duplicate"):
        parse_mesh_spec("y=2,y=4")
    with pytest.raises(ValueError, match="devices"):
        parse_mesh_spec("data=16", 8)


# ---------------------------------------------------------------------------
# the parity matrix
# ---------------------------------------------------------------------------
def _traffic_chunk(traffic: str, seed: int):
    rng = np.random.default_rng(seed)
    if traffic == "ragged":
        # non-divisible extents: edge snapping + uneven slab buckets
        return Chunk(rng.random((6, 37, 45)).astype(np.float32))
    if traffic == "uint8":
        # narrow-input device normalization path
        return Chunk(rng.integers(0, 256, (8, 40, 48), dtype=np.uint8))
    return Chunk(rng.random((8, 40, 48)).astype(np.float32))


@pytest.mark.parametrize("mesh", [m for m in MESHES if m != "1"])
@pytest.mark.parametrize(
    "traffic", ["plain", "ragged", "uint8", "crop_margin"]
)
def test_mesh_bitwise_parity_matrix(shared, mesh, traffic):
    """Every mesh shape x every traffic class == the single-device
    program, bitwise ('crop_margin' additionally exercises the
    post-blend margin crop). Identity engine: its programs compile in
    milliseconds, which is what lets a 36-case matrix live in tier-1;
    the conv spot checks below cover arbitrary float forward math."""
    crop = traffic == "crop_margin"
    chunk = _traffic_chunk(traffic, seed=abs(hash(traffic)) % 2**31)
    ref = np.asarray(shared(crop=crop)(chunk).array)
    out = np.asarray(shared(mesh=mesh, crop=crop)(chunk).array)
    assert out.dtype == ref.dtype
    assert out.shape == ref.shape
    assert np.array_equal(out, ref), (
        f"mesh {mesh} diverged from the single-device reference "
        f"(max abs diff "
        f"{np.abs(out.astype(np.float64) - ref.astype(np.float64)).max():.3e})"
    )


def test_kill_switch_spec_is_single(shared):
    """Mesh '1' (the kill-switch row of the matrix) resolves to NO
    engine at all — covered in depth by test_env_spec_and_kill_switch."""
    assert shared(mesh="1").shard_engine() is None


@pytest.mark.parametrize("mesh", ["data=8", "y=4,x=2"])
def test_conv_engine_bitwise_spot_checks(conv_engine, mesh):
    """The bit-identity contract on REAL conv forward math (per-row
    independence of batched convs is the property the replay design
    rests on) — two representative mesh kinds."""
    rng = np.random.default_rng(11)
    chunk = Chunk(rng.random((6, 37, 45)).astype(np.float32))
    ref = np.asarray(make_inferencer(conv_engine)(chunk).array)
    out = np.asarray(
        make_inferencer(conv_engine, mesh=mesh)(chunk).array
    )
    assert np.array_equal(out, ref)


def test_identity_oracle_through_mesh():
    """The identity oracle (blended overlap-add of identity patches
    reproduces the input) holds through the sharded path — the same
    oracle the reference's single-GPU tests pin."""
    rng = np.random.default_rng(0)
    chunk = rng.random((8, 32, 48)).astype(np.float32)
    engine = engines.create_identity_engine(
        input_patch_size=PIN, output_patch_size=PIN,
        num_input_channels=1, num_output_channels=3,
    )
    for spec in ("data=8", "y=4,x=2"):
        out = np.asarray(sharded_inference(
            chunk, engine, PIN, None, OVERLAP, batch_size=1,
            spec=parse_mesh_spec(spec, 8),
        ))
        np.testing.assert_allclose(
            out, np.broadcast_to(chunk, out.shape), atol=1e-5
        )


def test_uint8_output_dtype_through_mesh(id_engine):
    """The on-device quantized output path survives sharding bitwise."""
    rng = np.random.default_rng(3)
    chunk = Chunk(rng.random((8, 40, 48)).astype(np.float32))
    ref = np.asarray(
        make_inferencer(id_engine, output_dtype="uint8")(chunk).array
    )
    out = np.asarray(
        make_inferencer(id_engine, output_dtype="uint8",
                        mesh="y=2,x=2")(chunk).array
    )
    assert out.dtype == np.uint8
    assert np.array_equal(out, ref)


# ---------------------------------------------------------------------------
# kill switch + env resolution
# ---------------------------------------------------------------------------
def test_env_spec_and_kill_switch(id_engine, monkeypatch):
    """CHUNKFLOW_MESH is re-read per chunk: flipping the kill switch on
    a live inferencer restores the single-device program (the engine
    resolves to None and the ('scatter',) family runs), bit-identically."""
    rng = np.random.default_rng(1)
    chunk = Chunk(rng.random((8, 40, 48)).astype(np.float32))
    ref = np.asarray(make_inferencer(id_engine)(chunk).array)

    inf = make_inferencer(id_engine)
    monkeypatch.setenv("CHUNKFLOW_MESH", "data=4")
    assert inf.shard_engine() is not None
    out = np.asarray(inf(chunk).array)
    assert np.array_equal(out, ref)
    assert any(k[0] == "shard" for k, _ in inf._programs.items())

    monkeypatch.setenv("CHUNKFLOW_MESH", "1")
    assert inf.shard_engine() is None
    out = np.asarray(inf(chunk).array)
    assert np.array_equal(out, ref)
    assert inf._programs.peek(("scatter",)) is not None


def test_explicit_mesh_overrides_env(id_engine, monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_MESH", "data=8")
    inf = make_inferencer(id_engine, mesh="y=2")
    assert inf.shard_engine().spec == MeshSpec("spatial", (2, 1))
    monkeypatch.setenv("CHUNKFLOW_MESH", "1")
    # explicit argument still wins — the env kill switch governs only
    # env-resolved meshes
    assert inf.shard_engine() is not None


def test_mesh_and_legacy_sharding_conflict(id_engine):
    with pytest.raises(ValueError, match="does not compose"):
        make_inferencer(id_engine, mesh="data=4", sharding="patch")


@pytest.mark.parametrize("legacy,kind,shape", [
    ("patch", "data", (8,)),
    ("spatial", "spatial", (8, 1)),
    ("spatial2d", "spatial", (2, 4)),
])
def test_legacy_sharding_aliases(id_engine, legacy, kind, shape):
    """The legacy sharding names map onto the unified engine layouts."""
    inf = make_inferencer(id_engine, sharding=legacy)
    spec = inf.shard_engine().spec
    assert spec.kind == kind
    assert spec.shape == shape


# ---------------------------------------------------------------------------
# legacy wrapper delegation (the subsumed modules)
# ---------------------------------------------------------------------------
def test_legacy_wrappers_delegate_bitwise(id_engine):
    from chunkflow_tpu.parallel.distributed import sharded_inference as d
    from chunkflow_tpu.parallel.spatial import spatial_sharded_inference
    from chunkflow_tpu.parallel.spatial2d import (
        spatial2d_sharded_inference,
    )

    rng = np.random.default_rng(2)
    chunk = rng.random((8, 40, 48)).astype(np.float32)
    ref = np.asarray(
        make_inferencer(id_engine)(Chunk(chunk.copy())).array
    )
    for fn in (d, spatial_sharded_inference, spatial2d_sharded_inference):
        out = np.asarray(fn(
            chunk, id_engine, PIN, PIN, OVERLAP, batch_size=2,
        ))
        assert np.array_equal(out, ref), fn.__name__


# ---------------------------------------------------------------------------
# seams: scheduler stream, serving packer, telemetry/roofline
# ---------------------------------------------------------------------------
def test_scheduled_stream_bitwise_through_mesh(id_engine, monkeypatch):
    """The adaptive scheduler seam: Inferencer.stream over a mesh-active
    inferencer is bit-identical to the serial single-device loop, and
    the stream announces its mesh (scheduler/mesh event)."""
    from chunkflow_tpu.core import telemetry

    rng = np.random.default_rng(4)
    chunks = [
        Chunk(rng.random((8, 40, 48)).astype(np.float32),
              voxel_offset=(8 * i, 0, 0))
        for i in range(4)
    ]
    refs = [
        np.asarray(make_inferencer(id_engine)(c).array) for c in chunks
    ]
    monkeypatch.setenv("CHUNKFLOW_MESH", "y=2,x=2")
    events = []
    monkeypatch.setattr(
        telemetry, "event",
        lambda kind, name, **attrs: events.append((kind, name, attrs)),
    )
    inf = make_inferencer(id_engine)
    outs = [np.asarray(c.array) for c in inf.stream(iter(chunks))]
    for ref, out in zip(refs, outs):
        assert np.array_equal(out, ref)
    assert any(
        k == "scheduler" and n == "mesh" and a.get("mesh") == "y=2,x=2"
        for k, n, a in events
    ), events


def test_packed_serving_shards_across_chips(id_engine, monkeypatch):
    """The serving seam: packed batches span the slice (B * n_chips
    slots), stay bit-identical to the per-chunk path, and feed the
    occupancy gauge per chip."""
    from chunkflow_tpu.core import telemetry
    from chunkflow_tpu.serve.packer import PatchPacker

    rng = np.random.default_rng(5)
    inf = Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=(0, 0, 0),
        num_output_channels=3,
        framework="prebuilt",
        batch_size=2,
        engine=id_engine,
        crop_output_margin=False,
    )
    chunks = [
        Chunk(rng.random((4, 16, 48)).astype(np.float32),
              voxel_offset=(4 * i, 0, 0))
        for i in range(8)
    ]
    monkeypatch.setenv("CHUNKFLOW_MESH", "1")
    refs = [np.asarray(inf(c).array) for c in chunks]

    monkeypatch.setenv("CHUNKFLOW_MESH", "data=4")
    telemetry.reset()
    packer = PatchPacker(inf, max_wait_ms=25.0)
    try:
        handles = [packer.submit(c) for c in chunks]
        outs = [np.asarray(h.result(timeout=120).array) for h in handles]
    finally:
        packer.close()
    for ref, out in zip(refs, outs):
        assert np.array_equal(out, ref)
    snap = telemetry.snapshot()
    assert snap["gauges"].get("serving/chips") == 4.0
    # 8 requests x 3 patches over 8-slot (2 x 4 chips) dispatches: the
    # packer must have packed across requests, not one per dispatch
    batches = snap["counters"]["serving/batches"]
    assert batches <= 4, snap["counters"]
    telemetry.reset()


def test_shard_telemetry_and_roofline_ledger(id_engine, tmp_path,
                                             monkeypatch):
    """Sharded programs ride the ProgramCache, so they land in the PR 8
    roofline ledger (programs.json) with shard/* gauges alongside."""
    import json

    from chunkflow_tpu.core import telemetry

    monkeypatch.setenv("CHUNKFLOW_MESH", "data=4")
    telemetry.reset()
    telemetry.configure(str(tmp_path))
    try:
        inf = make_inferencer(id_engine)
        rng = np.random.default_rng(6)
        np.asarray(inf(Chunk(rng.random((8, 40, 48)).astype(
            np.float32))).array)
        snap = telemetry.snapshot()
        assert snap["gauges"].get("shard/mesh_devices") == 4.0
        assert snap["gauges"].get("shard/per_chip_voxels") == float(
            8 * 40 * 48)
        assert snap["counters"].get("shard/chunks") == 1
        telemetry.flush()
    finally:
        telemetry.configure(None)
        telemetry.reset()
    catalog = json.loads((tmp_path / "programs.json").read_text())
    entries = catalog["programs"]
    shard_entries = [
        e for e in entries
        if e.get("family") == "shard" or "shard" in str(e.get("key"))
    ]
    assert shard_entries, entries
    # the ledger carries real cost accounting for the sharded program
    assert shard_entries[0].get("compile_s") is not None


def test_per_chip_attribution_gauges(id_engine, monkeypatch):
    """ISSUE 18: every sharded dispatch attributes its work per chip —
    shard/chip/<i>/voxels load gauges, a sampled readiness probe
    (shard/chip/<i>/ready_s + shard/chip_skew_s), and analytic
    collective byte counters with the compute-vs-collective split."""
    from chunkflow_tpu.core import telemetry

    monkeypatch.setenv("CHUNKFLOW_MESH", "data=8")
    monkeypatch.setenv("CHUNKFLOW_CHIP_PROBE_EVERY", "1")
    telemetry.reset()
    try:
        inf = make_inferencer(id_engine)
        rng = np.random.default_rng(11)
        np.asarray(inf(Chunk(rng.random((8, 40, 48)).astype(
            np.float32))).array)
        gauges = telemetry.snapshot()["gauges"]
        counters = telemetry.snapshot()["counters"]
    finally:
        telemetry.reset()
    chip_vox = {int(m.group("chip")): v for name, v in gauges.items()
                for m in [telemetry.CHIP_METRIC_RE.match(name)]
                if m and m.group("plane") == "shard"
                and m.group("metric") == "voxels"}
    assert sorted(chip_vox) == list(range(8))
    # attribution is real, not degenerate: whole patches only, covering
    # at least the chunk (overlap re-visits voxels), unevenly spread
    # because the padded grid does not divide 8 ways
    pvox = float(np.prod(PIN))
    total = sum(chip_vox.values())
    assert total % pvox == 0 and total >= 8 * 40 * 48
    assert len(set(chip_vox.values())) > 1
    # the readiness probe stamped every chip, cumulative hence monotone
    readies = [gauges[f"shard/chip/{i}/ready_s"] for i in range(8)]
    assert readies == sorted(readies)
    assert gauges["shard/chip_skew_s"] == pytest.approx(
        readies[-1] - readies[0])
    # analytic collective plane: the data axis all-gathers the output
    # rows, and the split estimate rides with it
    assert counters["shard/gather_bytes"] > 0
    assert gauges["shard/gather_bytes_per_chunk"] == pytest.approx(
        counters["shard/gather_bytes"])
    assert 0.0 < gauges["shard/collective_share_est"] <= 1.0


def test_spatial_mesh_stamps_halo_bytes(id_engine, monkeypatch):
    """A 2D spatial mesh exchanges halos on both axes: the analytic
    halo counter is non-zero and separate from the replay planes. The
    sharded replay default ships fringe windows (replay_strip_bytes)
    instead of the full-stack all_gather; the replicated flip restores
    the gather plane (ISSUE 19)."""
    from chunkflow_tpu.core import telemetry

    monkeypatch.setenv("CHUNKFLOW_MESH", "y=2,x=2")
    telemetry.reset()
    try:
        inf = make_inferencer(id_engine)
        rng = np.random.default_rng(12)
        np.asarray(inf(Chunk(rng.random((8, 40, 48)).astype(
            np.float32))).array)
        snap = telemetry.snapshot()
    finally:
        telemetry.reset()
    assert snap["counters"]["shard/halo_bytes"] > 0
    assert snap["counters"]["shard/replay_strip_bytes"] > 0
    assert "shard/gather_bytes" not in snap["counters"]
    # the analytic slab+margin blend-buffer plane, per chip too
    assert snap["gauges"]["shard/replay_buffer_bytes"] > 0
    assert all(snap["gauges"].get(f"shard/chip/{i}/replay_buffer_bytes")
               for i in range(4))
    chip_vox = [snap["gauges"].get(f"shard/chip/{i}/voxels")
                for i in range(4)]
    assert all(v is not None for v in chip_vox)

    monkeypatch.setenv("CHUNKFLOW_SHARD_REPLAY", "replicated")
    telemetry.reset()
    try:
        inf = make_inferencer(id_engine)
        rng = np.random.default_rng(12)
        np.asarray(inf(Chunk(rng.random((8, 40, 48)).astype(
            np.float32))).array)
        snap = telemetry.snapshot()
    finally:
        telemetry.reset()
    assert snap["counters"]["shard/gather_bytes"] > 0
    assert "shard/replay_strip_bytes" not in snap["counters"]


def test_telemetry_off_means_no_chip_probes(id_engine, monkeypatch):
    """CHUNKFLOW_TELEMETRY=0 acceptance: the sharded path emits no
    per-chip gauges and never runs the readiness probe (no extra
    block_until_ready on the dispatch path) — and stays bitwise
    identical to the telemetry-on run."""
    from chunkflow_tpu.core import telemetry
    from chunkflow_tpu.parallel import engine as engine_mod

    monkeypatch.setenv("CHUNKFLOW_MESH", "data=8")
    monkeypatch.setenv("CHUNKFLOW_CHIP_PROBE_EVERY", "1")
    rng = np.random.default_rng(13)
    chunk = rng.random((8, 40, 48)).astype(np.float32)
    telemetry.reset()
    inf_on = make_inferencer(id_engine)
    out_on = np.asarray(inf_on(Chunk(chunk.copy())).array)
    telemetry.reset()

    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    inf_off = make_inferencer(id_engine)
    out_off = np.asarray(inf_off(Chunk(chunk.copy())).array)
    snap = telemetry.snapshot()
    telemetry.reset()
    assert not any(telemetry.CHIP_METRIC_RE.match(name)
                   for name in snap["gauges"])
    assert "shard/gather_bytes" not in snap["counters"]
    np.testing.assert_array_equal(out_on, out_off)

    # and the probe itself is a free return: with telemetry off it must
    # never touch the result (no block_until_ready on the dispatch path)
    class Untouchable:
        @property
        def addressable_shards(self):
            raise AssertionError("probe touched the result while off")

    shard_engine = inf_off.shard_engine()
    assert isinstance(shard_engine, engine_mod.ShardedEngine)
    for _ in range(3):
        shard_engine._probe_chip_readiness(Untouchable())


def _bare_sharded_engine(spec):
    from chunkflow_tpu.parallel.engine import ShardedEngine

    return ShardedEngine(
        forward=lambda x: x, num_input_channels=1, num_output_channels=3,
        input_patch_size=PIN, output_patch_size=PIN, batch_size=2,
        spec=spec,
    )


def test_probe_cadence_is_sampled(monkeypatch):
    """The readiness probe fires on dispatch 0 and then every
    CHUNKFLOW_CHIP_PROBE_EVERY dispatches, not per chunk."""
    from chunkflow_tpu.core import telemetry

    monkeypatch.setenv("CHUNKFLOW_CHIP_PROBE_EVERY", "4")
    engine = _bare_sharded_engine(MeshSpec("data", (8,)))
    probed = []

    class FakeShard:
        def __init__(self):
            self.device = type("D", (), {"id": 0})()
            self.data = type("A", (), {
                "block_until_ready": lambda self: None})()

    class FakeResult:
        @property
        def addressable_shards(self):
            probed.append(True)
            return [FakeShard()]

    telemetry.reset()
    try:
        for _ in range(9):
            engine._probe_chip_readiness(FakeResult())
        assert len(probed) == 3  # dispatches 0, 4, 8
        assert "shard/chip_skew_s" in telemetry.snapshot()["gauges"]
    finally:
        telemetry.reset()


def test_program_reuse_across_same_shape_chunks(id_engine, monkeypatch):
    """Two same-shape chunks share ONE sharded program build (the
    compile-cache invariant every other family holds)."""
    monkeypatch.setenv("CHUNKFLOW_MESH", "y=4")
    inf = make_inferencer(id_engine)
    rng = np.random.default_rng(7)
    for _ in range(3):
        np.asarray(inf(Chunk(rng.random((8, 40, 48)).astype(
            np.float32))).array)
    shard_builds = [k for k, _ in inf._programs.items()
                    if k[0] == "shard"]
    assert len(shard_builds) == 1, shard_builds
    assert inf._programs.hits >= 2


def test_engine_is_graftlint_clean():
    """ISSUE 13 acceptance: GL001-GL014 clean over parallel/engine.py
    and the modules it reworked, asserted in-suite (the whole-repo gate
    in tests/tools/test_graftlint_gate.py covers them too; this pins
    the specific modules so a future baseline regeneration cannot
    quietly grandfather a finding here)."""
    from pathlib import Path

    from tools.graftlint.config import load_config
    from tools.graftlint.engine import lint_paths

    repo_root = Path(__file__).resolve().parents[2]
    config = load_config(repo_root / "pyproject.toml")
    findings, _ = lint_paths(
        [
            "chunkflow_tpu/parallel/engine.py",
            "chunkflow_tpu/parallel/pipeline.py",
            "chunkflow_tpu/parallel/distributed.py",
            "chunkflow_tpu/parallel/spatial.py",
            "chunkflow_tpu/parallel/spatial2d.py",
            "chunkflow_tpu/parallel/multihost.py",
            "chunkflow_tpu/serve/packer.py",
            "chunkflow_tpu/inference/precision.py",
            "chunkflow_tpu/ops/blend.py",
        ],
        config, repo_root=repo_root,
    )
    assert not findings, [
        f"{f.path}:{f.line}: {f.code} {f.message}" for f in findings
    ]


# ---------------------------------------------------------------------------
# ISSUE 19: sharded blend replay + the pipeline kind
# ---------------------------------------------------------------------------
def test_replay_mode_flip_bitwise_and_distinct_keys(id_engine,
                                                    monkeypatch):
    """CHUNKFLOW_SHARD_REPLAY is re-read per chunk: flipping a live
    inferencer between the sharded default and the replicated replay
    rebuilds the program (distinct cache keys — the 'replay-replicated'
    tag) and stays bit-identical."""
    rng = np.random.default_rng(21)
    chunk = rng.random((6, 37, 45)).astype(np.float32)
    ref = np.asarray(make_inferencer(id_engine)(Chunk(chunk.copy()))
                     .array)
    monkeypatch.setenv("CHUNKFLOW_MESH", "y=2,x=2")
    inf = make_inferencer(id_engine)
    out_sharded = np.asarray(inf(Chunk(chunk.copy())).array)
    monkeypatch.setenv("CHUNKFLOW_SHARD_REPLAY", "replicated")
    out_replicated = np.asarray(inf(Chunk(chunk.copy())).array)
    assert np.array_equal(out_sharded, ref)
    assert np.array_equal(out_replicated, ref)
    shard_keys = [k for k, _ in inf._programs.items() if k[0] == "shard"]
    assert len(shard_keys) == 2, shard_keys
    assert sum("replay-replicated" in k for k in shard_keys) == 1, \
        shard_keys


def test_pipeline_mesh_needs_staged_engine(conv_engine):
    """A pipeline mesh over an engine that never declared the stage
    protocol fails loudly (no silent fallback to an unpipelined
    program): the flax conv engine is opaque."""
    rng = np.random.default_rng(22)
    chunk = Chunk(rng.random((6, 37, 45)).astype(np.float32))
    inf = make_inferencer(conv_engine, mesh="pipeline=4")
    with pytest.raises(ValueError, match="stage protocol"):
        inf(chunk)


def test_stage_groups_contiguous_and_balanced():
    """parallel/pipeline.stage_groups: contiguous balanced groups,
    later stages absorb the remainder, composition order preserved."""
    from chunkflow_tpu.parallel.pipeline import (
        require_stages,
        stage_groups,
    )

    trace = []

    def body(tag):
        def run(params, x):
            trace.append(tag)
            return x + 1

        return run

    groups = stage_groups(tuple(body(i) for i in range(5)), 3)
    assert len(groups) == 3
    x = 0
    for g in groups:
        x = g(None, x)
    assert x == 5
    # contiguous order, remainder on the LATER stages: 1 + 2 + 2
    assert trace == [0, 1, 2, 3, 4]
    trace.clear()
    groups[0](None, 0)
    assert trace == [0]
    trace.clear()
    groups[2](None, 0)
    assert trace == [3, 4]
    # more stages than bodies: the extra stages are the identity
    groups = stage_groups((body("only"),), 4)
    assert len(groups) == 4 and groups[0](None, 7) == 7
    with pytest.raises(ValueError, match="stage protocol"):
        require_stages(None, None, "test context")


def test_pipeline_packed_serving_bitwise(id_engine, monkeypatch):
    """The serving seam over a pipeline mesh: packed batches stream
    through the staged ring and stay bit-identical to the per-chunk
    path (the serving acceptance row of ISSUE 19)."""
    from chunkflow_tpu.serve.packer import PatchPacker

    rng = np.random.default_rng(23)
    inf = Inferencer(
        input_patch_size=PIN,
        output_patch_overlap=(0, 0, 0),
        num_output_channels=3,
        framework="prebuilt",
        batch_size=2,
        engine=id_engine,
        crop_output_margin=False,
    )
    chunks = [
        Chunk(rng.random((4, 16, 48)).astype(np.float32),
              voxel_offset=(4 * i, 0, 0))
        for i in range(6)
    ]
    monkeypatch.setenv("CHUNKFLOW_MESH", "1")
    refs = [np.asarray(inf(c).array) for c in chunks]

    monkeypatch.setenv("CHUNKFLOW_MESH", "pipeline=4")
    packer = PatchPacker(inf, max_wait_ms=25.0)
    try:
        handles = [packer.submit(c) for c in chunks]
        outs = [np.asarray(h.result(timeout=120).array)
                for h in handles]
    finally:
        packer.close()
    for ref, out in zip(refs, outs):
        assert np.array_equal(out, ref)
    serve_keys = [k for k, _ in inf._programs.items()
                  if k[0] == "serve_forward"]
    assert any("pipeline" in k for k in serve_keys), serve_keys


def test_sharded_replay_under_pallas_interpret(id_engine, monkeypatch):
    """The kernelcheck/interpret leg covers the sharded replay path:
    with CHUNKFLOW_PALLAS=interpret the slab+margin replay runs the
    fused Pallas accumulation kernel (interpreted) and still matches
    the interpreted single-device program bitwise."""
    monkeypatch.setenv("CHUNKFLOW_PALLAS", "interpret")
    rng = np.random.default_rng(24)
    chunk = rng.random((6, 37, 45)).astype(np.float32)
    ref = np.asarray(make_inferencer(id_engine)(Chunk(chunk.copy()))
                     .array)
    for mesh in ("y=2,x=2", "pipeline=4"):
        out = np.asarray(
            make_inferencer(id_engine, mesh=mesh)(Chunk(chunk.copy()))
            .array
        )
        assert np.array_equal(out, ref), mesh


def test_replay_buffer_hbm_shrinks_to_slab_plus_halo(id_engine,
                                                     monkeypatch):
    """The HBM acceptance criterion: the sharded replay's per-chip
    blend buffer is slab+margin, not full-chunk. The analytic plane
    (shard/replay_buffer_bytes + the per-chip mirror) must match the
    slab+margin formula exactly and undercut the full-chunk figure;
    when the backend's memory_stats watermark plane reports (PR 18),
    the measured per-chip peak must also stay under the replicated
    run's peak-plus-full-buffer bound — guarded, since CPU backends
    may not report."""
    from chunkflow_tpu.core import telemetry
    from chunkflow_tpu.parallel.engine import axis_geometry

    monkeypatch.setenv("CHUNKFLOW_MESH", "y=4,x=2")
    # big enough that slab+margin genuinely undercuts the full chunk
    # (the margins are a fixed two output patches per sharded axis)
    z, y, x = 8, 120, 96
    telemetry.reset()
    try:
        inf = make_inferencer(id_engine)
        rng = np.random.default_rng(25)
        np.asarray(inf(Chunk(rng.random((z, y, x)).astype(
            np.float32))).array)
        gauges = telemetry.snapshot()["gauges"]
    finally:
        telemetry.reset()
    co = 3
    yslab = axis_geometry(y, 4, PIN[1], PIN[1])[0]
    xslab = axis_geometry(x, 2, PIN[2], PIN[2])[0]
    # margins are one output patch on each boundary-facing side
    expected = (co + 1) * z * (yslab + 2 * PIN[1]) \
        * (xslab + 2 * PIN[2]) * 4
    full_chunk = (co + 1) * z * y * x * 4
    assert gauges["shard/replay_buffer_bytes"] == float(expected)
    assert expected < full_chunk
    for i in range(8):
        assert gauges[f"shard/chip/{i}/replay_buffer_bytes"] == float(
            expected)
    # guarded watermark cross-check: when the backend reports
    # memory_stats, the per-chip measured peak exists alongside
    try:
        import jax as _jax

        stats = _jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("peak_bytes_in_use"):
        from chunkflow_tpu.flow import scheduler

        telemetry.reset()
        try:
            scheduler.sample_device_memory()
            g = telemetry.snapshot()["gauges"]
            assert g.get("device/chip/0/peak_bytes", 0) > 0
        finally:
            telemetry.reset()
