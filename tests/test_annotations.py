import numpy as np
import pytest

from chunkflow_tpu.annotations.point_cloud import PointCloud
from chunkflow_tpu.annotations.skeleton import Skeleton
from chunkflow_tpu.annotations.synapses import Synapses
from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.bbox import BoundingBox


@pytest.fixture
def synapses():
    pre = np.array([[10, 10, 10], [50, 50, 50], [90, 90, 90]], dtype=np.int32)
    post = np.array(
        [
            [0, 12, 10, 10],
            [0, 10, 14, 10],
            [1, 52, 50, 50],
        ],
        dtype=np.int32,
    )
    return Synapses(pre, post=post, resolution=(40, 4, 4))


class TestSynapses:
    def test_basic_counts(self, synapses):
        assert synapses.pre_num == 3
        assert synapses.post_num == 3
        assert synapses.pre_with_post_num == 2
        assert synapses.post_indices_of_pre(0).tolist() == [0, 1]

    def test_distances(self, synapses):
        d = synapses.distances_from_pre_to_post()
        assert d.shape == (3,)
        np.testing.assert_allclose(d[0], 2 * 40)  # z offset of 2
        np.testing.assert_allclose(d[1], 4 * 4)   # y offset of 4

    def test_json_h5_roundtrip(self, synapses, tmp_path):
        for suffix in ("json", "h5"):
            path = str(tmp_path / f"syn.{suffix}")
            synapses.to_file(path)
            loaded = Synapses.from_file(path)
            assert loaded == synapses
            assert loaded.resolution == synapses.resolution

    def test_dvid_roundtrip(self, synapses):
        """Synapses -> DVID element list -> Synapses preserves geometry
        (reference synapses.py:128-224,364-...)."""
        elements = synapses.to_dvid_list_of_dict(user="tester")
        # 3 post + 3 pre elements, xyz positions
        kinds = [e["Kind"] for e in elements]
        assert kinds.count("PostSyn") == 3 and kinds.count("PreSyn") == 3
        pre0 = next(e for e in elements if e["Kind"] == "PreSyn")
        assert pre0["Pos"] == [10, 10, 10]  # zyx (10,10,10) -> xyz
        assert {r["Rel"] for r in pre0["Rels"]} == {"PreSynTo"}

        back = Synapses.from_dvid_list(elements, resolution=(40, 4, 4))
        assert back == synapses

    def test_dvid_list_drops_orphan_posts(self):
        elements = [
            {"Kind": "PreSyn", "Pos": [1, 2, 3], "Prop": {}, "Rels": []},
            # post pointing at a deleted presynapse
            {"Kind": "PostSyn", "Pos": [9, 9, 9], "Prop": {},
             "Rels": [{"Rel": "PostSynTo", "To": [7, 7, 7]}]},
            # post with no relation at all
            {"Kind": "PostSyn", "Pos": [8, 8, 8], "Prop": {}, "Rels": []},
        ]
        syn = Synapses.from_dvid_list(elements)
        assert syn.pre_num == 1 and syn.post_num == 0

    def test_neutu_task_export(self, synapses, tmp_path):
        import json

        path = str(tmp_path / "task.json")
        synapses.to_neutu_task(path, body_id=77)
        with open(path) as f:
            task = json.load(f)
        assert task["metadata"]["coordinate system"] == "dvid"
        assert len(task["data"]) == synapses.pre_num
        assert task["data"][0] == {"body ID": 77, "location": [10, 10, 10]}
        with pytest.raises(ValueError):
            synapses.to_neutu_task(str(tmp_path / "task.txt"))

    def test_filter_by_bbox_remaps_indices(self, synapses):
        cropped = synapses.filter_by_bbox(BoundingBox((40, 40, 40), (100, 100, 100)))
        assert cropped.pre_num == 2
        # only pre index 1 (now 0) kept its post
        assert cropped.post_num == 1
        assert cropped.post[0, 0] == 0
        np.testing.assert_array_equal(cropped.post[0, 1:], [52, 50, 50])

    def test_remove_pre_without_post(self, synapses):
        trimmed = synapses.remove_pre_without_post()
        assert trimmed.pre_num == 2
        assert trimmed.post_num == 3
        assert trimmed.post[:, 0].max() <= 1

    def test_redundant_post(self):
        pre = np.array([[0, 0, 0]], dtype=np.int32)
        post = np.array(
            [[0, 0, 0, 10], [0, 0, 0, 12], [0, 0, 0, 50]], dtype=np.int32
        )
        syn = Synapses(pre, post=post, resolution=(1, 1, 1))
        redundant = syn.find_redundant_post(5.0)
        assert redundant.tolist() == [1]

    def test_duplicate_on_same_neuron(self):
        seg_arr = np.zeros((4, 4, 4), dtype=np.uint32)
        seg_arr[:, :, :2] = 7
        seg = Chunk(seg_arr)
        pre = np.array([[0, 0, 0]], dtype=np.int32)
        post = np.array(
            [[0, 1, 1, 0], [0, 2, 2, 1], [0, 3, 3, 3]], dtype=np.int32
        )
        syn = Synapses(pre, post=post)
        dups = syn.find_duplicate_post_on_same_neuron(seg)
        assert dups.tolist() == [1]  # second post on the same id-7 neuron

    def test_validation(self):
        with pytest.raises(ValueError):
            Synapses(np.zeros((2, 2), dtype=np.int32))
        with pytest.raises(ValueError):
            Synapses(
                np.zeros((1, 3), dtype=np.int32),
                post=np.array([[5, 0, 0, 0]], dtype=np.int32),
            )


class TestPointCloud:
    def test_basics_and_io(self, tmp_path):
        points = np.array([[1, 2, 3], [7, 8, 9]], dtype=np.int64)
        pc = PointCloud(points, voxel_size=(40, 4, 4))
        assert len(pc) == 2
        assert pc.bbox == BoundingBox((1, 2, 3), (8, 9, 10))
        np.testing.assert_array_equal(pc.physical[0], [40, 8, 12])
        path = str(tmp_path / "points.h5")
        pc.to_h5(path)
        loaded = PointCloud.from_h5(path)
        np.testing.assert_array_equal(loaded.points, points)

    def test_filter(self):
        pc = PointCloud(np.array([[0, 0, 0], [5, 5, 5], [9, 9, 9]]))
        kept = pc.filter_by_bbox(BoundingBox((1, 1, 1), (8, 8, 8)))
        assert len(kept) == 1


class TestSkeleton:
    def _y_skeleton(self):
        # a Y shape: 0-1-2 trunk, 3-4 branch from node 1
        nodes = np.array(
            [[0, 0, 0], [0, 10, 0], [0, 20, 0], [0, 15, 5], [0, 20, 10]],
            dtype=np.float32,
        )
        parents = np.array([-1, 0, 1, 1, 3])
        return Skeleton(nodes, parents)

    def test_edges_and_length(self):
        skel = self._y_skeleton()
        assert skel.edges.shape == (4, 2)
        assert skel.cable_length() > 0

    def test_swc_roundtrip(self, tmp_path):
        skel = self._y_skeleton()
        path = str(tmp_path / "skel.swc")
        skel.to_swc(path)
        loaded = Skeleton.from_swc(path)
        np.testing.assert_allclose(loaded.nodes, skel.nodes)
        np.testing.assert_array_equal(loaded.parents, skel.parents)

    def test_precomputed_roundtrip(self):
        skel = self._y_skeleton()
        blob = skel.to_precomputed_bytes()
        loaded = Skeleton.from_precomputed_bytes(blob)
        assert len(loaded) == len(skel)
        np.testing.assert_allclose(loaded.nodes, skel.nodes)
        # same edge set regardless of parent orientation
        orig = {tuple(sorted(e)) for e in skel.edges.tolist()}
        back = {tuple(sorted(e)) for e in loaded.edges.tolist()}
        assert orig == back


class TestSynapsePlugins:
    def test_detect_pre_and_post(self):
        from chunkflow_tpu.flow.plugin import load_plugin

        prob = np.zeros((8, 32, 32), dtype=np.float32)
        prob[4, 8, 8] = 1.0
        prob[4, 24, 24] = 0.9
        chunk = Chunk(prob)
        detect_pre = load_plugin("detect_pre_synapses")
        synapses = detect_pre(chunk, min_distance=3)
        assert synapses.pre_num == 2

        post_prob = np.zeros((8, 32, 32), dtype=np.float32)
        post_prob[4, 10, 10] = 1.0
        detect_post = load_plugin("detect_post_synapses")
        with_post = detect_post(
            synapses, Chunk(post_prob), search_radius=5, min_distance=2
        )
        assert with_post.post_num == 1
        assert with_post.post[0, 0] in (0, 1)

    def test_find_tbar_object(self):
        from chunkflow_tpu.flow.plugin import load_plugin

        seg_arr = np.zeros((8, 8, 8), dtype=np.uint32)
        seg_arr[2, 2, 2] = 42
        syn = Synapses(np.array([[2, 2, 2], [5, 5, 5]], dtype=np.int32))
        find = load_plugin("find_tbar_object")
        ids = find(syn, Chunk(seg_arr))
        assert ids.tolist() == [42, 0]

    def test_adjust_pre(self):
        from chunkflow_tpu.flow.plugin import load_plugin

        prob = np.zeros((8, 8, 8), dtype=np.float32)
        prob[3, 3, 3] = 1.0
        syn = Synapses(np.array([[2, 2, 2]], dtype=np.int32))
        adjust = load_plugin("adjust_pre")
        moved = adjust(syn, Chunk(prob), window=2)
        np.testing.assert_array_equal(moved.pre[0], [3, 3, 3])


def test_skeletonize_plugin(tmp_path):
    from chunkflow_tpu.flow.plugin import load_plugin

    # a thick horizontal bar: skeleton should run along its length
    arr = np.zeros((8, 8, 32), dtype=np.uint32)
    arr[2:6, 2:6, 2:30] = 1
    seg = Chunk(arr, voxel_size=(1, 1, 1))
    skeletonize = load_plugin("skeletonize")
    out_dir = str(tmp_path / "skel")
    skeletons = skeletonize(seg, voxel_num_threshold=10, output_path=out_dir)
    assert 1 in skeletons
    skel = skeletons[1]
    assert len(skel) > 3
    # spans most of the bar length
    span = skel.nodes[:, 2].max() - skel.nodes[:, 2].min()
    assert span > 15

    import os

    frags = os.listdir(out_dir)
    assert len(frags) == 1

    aggregate = load_plugin("aggregate_skeleton_fragments")
    assert aggregate(out_dir, str(tmp_path / "agg")) == 1


def test_skeleton_precomputed_undirected_edges():
    """Precomputed edge pairs carry no orientation; any orientation must
    round-trip into a valid single tree (child->parent rebuild by BFS)."""
    import numpy as np
    from chunkflow_tpu.annotations.skeleton import Skeleton

    nodes = np.arange(12, dtype=np.float32).reshape(4, 3)
    # path 1-0-2-3 stored as unordered pairs (0,1), (2,3), (0,2)
    import struct
    blob = struct.pack("<II", 4, 3)
    blob += nodes[:, ::-1].astype("<f4").tobytes()
    blob += np.asarray([[0, 1], [2, 3], [0, 2]], dtype="<u4").tobytes()
    skel = Skeleton.from_precomputed_bytes(blob)
    assert len(skel) == 4
    # exactly one root, all 3 edges present, every node reaches the root
    assert int((skel.parents == -1).sum()) == 1
    assert skel.edges.shape[0] == 3
    root = int(np.nonzero(skel.parents == -1)[0][0])
    for i in range(4):
        seen = set()
        j = i
        while skel.parents[j] != -1:
            assert j not in seen
            seen.add(j)
            j = int(skel.parents[j])
        assert j == root


def test_empty_synapses_json_roundtrip(tmp_path):
    import numpy as np
    from chunkflow_tpu.annotations.synapses import Synapses

    empty = Synapses(np.zeros((0, 3), np.int32), np.zeros((0, 4), np.int32))
    path = str(tmp_path / "empty.json")
    empty.to_json(path)
    back = Synapses.from_json(path)
    assert back.pre_num == 0 and back.post_num == 0


def test_duplicate_post_4d_segmentation():
    import numpy as np
    from chunkflow_tpu.annotations.synapses import Synapses
    from chunkflow_tpu.chunk.segmentation import Segmentation

    seg = Segmentation(np.ones((1, 4, 4, 4), np.uint32))
    syn = Synapses(
        np.asarray([[1, 1, 1]], np.int32),
        np.asarray([[0, 1, 1, 2], [0, 2, 2, 2]], np.int32),
    )
    dup = syn.find_duplicate_post_on_same_neuron(seg)
    assert dup.tolist() == [1]


def test_skeleton_precomputed_radii_roundtrip():
    import numpy as np
    from chunkflow_tpu.annotations.skeleton import Skeleton

    nodes = np.arange(9, dtype=np.float32).reshape(3, 3)
    skel = Skeleton(nodes, [-1, 0, 1], radii=[3.0, 2.0, 1.0])
    back = Skeleton.from_precomputed_bytes(skel.to_precomputed_bytes())
    np.testing.assert_allclose(back.radii, [3.0, 2.0, 1.0])


def test_synapses_reference_api_surface():
    """Reference drop-in spellings (reference synapses.py:461-700):
    bounding boxes, physical coordinates, point clouds, per-pre post
    buckets, in-place editors, transpose, json dict round trip."""
    import numpy as np

    from chunkflow_tpu.annotations.synapses import Synapses

    pre = np.array([[1, 2, 3], [4, 5, 6], [7, 8, 9]], np.int32)
    post = np.array([[0, 1, 2, 4], [0, 1, 3, 3], [2, 7, 9, 9]], np.int32)
    s = Synapses(pre, post=post, resolution=(40, 4, 4))

    assert s.pre_bounding_box == s.pre_bbox
    assert s.bounding_box.contains((7, 9, 9))
    assert np.array_equal(s.post_coordinates, post[:, 1:])
    assert np.allclose(s.pre_with_physical_coordinate[0], [40, 8, 12])
    assert np.allclose(s.post_with_physical_coordinate[0, 1:], [40, 8, 16])
    assert s.pre_point_cloud.points.shape == (3, 3)
    assert s.post_point_cloud.points.shape == (3, 3)
    assert s.pre_index2post_indices == [[0, 1], [], [2]]
    assert s.post_synapse_num_list == [2, 0, 1]
    assert s.pre_indices_without_post == [1]

    # json dict round trip
    s2 = Synapses.from_dict(s.json_dict)
    assert s2 == s

    # in-place editing: remove pre 0 -> posts remap
    s3 = Synapses.from_dict(s.json_dict)
    s3.remove_pre([0])
    assert s3.pre_num == 2 and s3.post_num == 1
    assert s3.post[0, 0] == 1  # old pre 2 -> new pre 1

    s4 = Synapses.from_dict(s.json_dict)
    s4.remove_synapses_without_post()
    assert s4.pre_num == 2 and s4.post_num == 3

    s5 = Synapses.from_dict(s.json_dict)
    from chunkflow_tpu.core.bbox import BoundingBox

    s5.remove_synapses_outside_bounding_box(BoundingBox((0, 0, 0), (5, 6, 7)))
    assert s5.pre_num == 2

    s6 = Synapses.from_dict(s.json_dict)
    s6.add_pre(np.array([[1, 2, 3]], np.int32))
    assert s6.pre_num == 4
    s6.remove_pre_duplicates()
    assert s6.pre_num == 3
    assert s6.post_num == 3  # posts survive, re-attached to kept T-bars

    # pre-only sets: remove_synapses_without_post is a no-op, not a wipe
    s8 = Synapses(np.array([[1, 2, 3]], np.int32))
    s8.remove_synapses_without_post()
    assert s8.pre_num == 1

    s7 = Synapses.from_dict(s.json_dict)
    s7.transpose_axis()
    assert tuple(s7.pre[0]) == (3, 2, 1)
    assert tuple(s7.resolution) == (4, 4, 40)
    assert tuple(s7.post[0, 1:]) == (4, 2, 1)

    # reference signature: posts farther than distance_threshold VOXELS
    # from their pre are flagged (every post here is exactly 1 voxel from
    # its T-bar)
    assert s.find_redundent_post(distance_threshold=0.5) == {0, 1, 2}
    assert s.find_redundent_post(distance_threshold=1.0) == set()
    assert s.find_redundent_post(num_threshold=1,
                                 distance_threshold=100.0) == {1}
