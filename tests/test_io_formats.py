"""Native NRRD/MRC codecs + their plugins (parity: reference save-nrrd
command and load_nrrd/load_mrc plugins, without pynrrd/mrcfile)."""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.volume.io_mrc import load_mrc, save_mrc
from chunkflow_tpu.volume.io_nrrd import load_nrrd, save_nrrd


@pytest.mark.parametrize("dtype", ["uint8", "uint16", "float32", "uint32"])
def test_nrrd_roundtrip(tmp_path, dtype):
    rng = np.random.default_rng(0)
    arr = (rng.random((4, 8, 6)) * 100).astype(dtype)
    path = str(tmp_path / "c.nrrd")
    save_nrrd(path, arr, voxel_size=(40, 4, 4), voxel_offset=(1, 2, 3))
    back, header = load_nrrd(path)
    np.testing.assert_array_equal(back, arr)
    assert header["type"] == np.dtype(dtype).name
    assert header["chunkflow voxel offset"] == "1 2 3"


def test_nrrd_gzip_roundtrip(tmp_path):
    arr = np.arange(64, dtype=np.uint8).reshape(4, 4, 4)
    path = str(tmp_path / "c.nrrd")
    save_nrrd(path, arr, encoding="gzip")
    back, header = load_nrrd(path)
    np.testing.assert_array_equal(back, arr)
    assert header["encoding"] == "gzip"


def test_nrrd_plugin_roundtrip(tmp_path):
    from chunkflow_tpu.plugins import load_nrrd as load_plugin
    from chunkflow_tpu.plugins import save_nrrd as save_plugin

    chunk = Chunk.create(size=(4, 8, 8), dtype="uint8", voxel_offset=(5, 6, 7))
    path = str(tmp_path / "p.nrrd")
    save_plugin.execute(chunk, file_name=path)
    back = load_plugin.execute(path)
    np.testing.assert_array_equal(np.asarray(back.array), np.asarray(chunk.array))
    assert tuple(back.voxel_offset) == (5, 6, 7)


@pytest.mark.parametrize("dtype", ["int8", "int16", "float32", "uint16"])
def test_mrc_roundtrip(tmp_path, dtype):
    rng = np.random.default_rng(1)
    arr = (rng.random((3, 5, 7)) * 50).astype(dtype)
    path = str(tmp_path / "c.mrc")
    save_mrc(path, arr, voxel_size_nm=(40.0, 4.0, 4.0))
    back, header = load_mrc(path)
    np.testing.assert_array_equal(back, arr)
    np.testing.assert_allclose(header["voxel_size_nm"], (40.0, 4.0, 4.0), rtol=1e-5)


def test_mrc_plugin(tmp_path):
    from chunkflow_tpu.plugins import load_mrc as plugin

    arr = np.zeros((2, 4, 4), dtype=np.float32)
    path = str(tmp_path / "p.mrc")
    save_mrc(path, arr, voxel_size_nm=(40.0, 4.0, 4.0))
    img = plugin.execute(path)
    assert img.shape == (2, 4, 4)
    assert tuple(img.voxel_size) == (40, 4, 4)


def test_load_tensorstore_plugin(tmp_path):
    pytest.importorskip("tensorstore")
    import tensorstore as ts

    from chunkflow_tpu.core.bbox import BoundingBox
    from chunkflow_tpu.plugins import load_tensorstore as plugin

    store_path = str(tmp_path / "store.zarr")
    rng = np.random.default_rng(2)
    data = rng.integers(0, 255, size=(8, 8, 8), dtype=np.uint8)
    store = ts.open(
        {"driver": "zarr", "kvstore": {"driver": "file", "path": store_path}},
        create=True, dtype="uint8", shape=(8, 8, 8),
    ).result()
    store[...] = data

    bbox = BoundingBox((2, 2, 2), (6, 6, 6))
    chunk = plugin.execute(bbox, driver="zarr", kvstore=f"file://{store_path}")
    np.testing.assert_array_equal(np.asarray(chunk.array), data[2:6, 2:6, 2:6])
    assert tuple(chunk.voxel_offset) == (2, 2, 2)


def test_save_pngs_affinity_and_float_and_bf16(tmp_path):
    """PNG export: float [0,1] scales to uint8; 3-channel affinity maps
    export the yx mean (reference save_pngs.py:33-38) without uint8
    overflow; bfloat16 payloads export instead of crashing."""
    import ml_dtypes
    import numpy as np
    from PIL import Image

    from chunkflow_tpu.chunk.base import Chunk, LayerType
    from chunkflow_tpu.volume.io_png import save_pngs

    rng = np.random.default_rng(0)
    # float affinity
    aff = Chunk(rng.random((3, 2, 8, 8)).astype(np.float32),
                layer_type=LayerType.AFFINITY_MAP)
    d = tmp_path / "aff_f32"
    save_pngs(aff, str(d))
    sections = sorted(d.iterdir())
    assert len(sections) == 2
    got = np.asarray(Image.open(sections[0]))
    arr = np.asarray(aff.array)
    want = np.clip((arr[1, 0] + arr[2, 0]) / 2.0, 0, 1) * 255.0
    np.testing.assert_allclose(got, want.astype(np.uint8), atol=1)
    # uint8 affinity: no wraparound in the channel mean
    u8 = Chunk(np.full((3, 2, 8, 8), 200, np.uint8),
               layer_type=LayerType.AFFINITY_MAP)
    d2 = tmp_path / "aff_u8"
    save_pngs(u8, str(d2))
    got = np.asarray(Image.open(sorted(d2.iterdir())[0]))
    assert (got == 200).all(), got.max()
    # bfloat16 single channel
    bf = Chunk(rng.random((2, 8, 8)).astype(ml_dtypes.bfloat16))
    d3 = tmp_path / "bf16"
    save_pngs(bf, str(d3))
    assert len(list(d3.iterdir())) == 2
