"""Native NRRD/MRC codecs + their plugins (parity: reference save-nrrd
command and load_nrrd/load_mrc plugins, without pynrrd/mrcfile)."""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.volume.io_mrc import load_mrc, save_mrc
from chunkflow_tpu.volume.io_nrrd import load_nrrd, save_nrrd


@pytest.mark.parametrize("dtype", ["uint8", "uint16", "float32", "uint32"])
def test_nrrd_roundtrip(tmp_path, dtype):
    rng = np.random.default_rng(0)
    arr = (rng.random((4, 8, 6)) * 100).astype(dtype)
    path = str(tmp_path / "c.nrrd")
    save_nrrd(path, arr, voxel_size=(40, 4, 4), voxel_offset=(1, 2, 3))
    back, header = load_nrrd(path)
    np.testing.assert_array_equal(back, arr)
    assert header["type"] == np.dtype(dtype).name
    assert header["chunkflow voxel offset"] == "1 2 3"


def test_nrrd_gzip_roundtrip(tmp_path):
    arr = np.arange(64, dtype=np.uint8).reshape(4, 4, 4)
    path = str(tmp_path / "c.nrrd")
    save_nrrd(path, arr, encoding="gzip")
    back, header = load_nrrd(path)
    np.testing.assert_array_equal(back, arr)
    assert header["encoding"] == "gzip"


def test_nrrd_plugin_roundtrip(tmp_path):
    from chunkflow_tpu.plugins import load_nrrd as load_plugin
    from chunkflow_tpu.plugins import save_nrrd as save_plugin

    chunk = Chunk.create(size=(4, 8, 8), dtype="uint8", voxel_offset=(5, 6, 7))
    path = str(tmp_path / "p.nrrd")
    save_plugin.execute(chunk, file_name=path)
    back = load_plugin.execute(path)
    np.testing.assert_array_equal(np.asarray(back.array), np.asarray(chunk.array))
    assert tuple(back.voxel_offset) == (5, 6, 7)


@pytest.mark.parametrize("dtype", ["int8", "int16", "float32", "uint16"])
def test_mrc_roundtrip(tmp_path, dtype):
    rng = np.random.default_rng(1)
    arr = (rng.random((3, 5, 7)) * 50).astype(dtype)
    path = str(tmp_path / "c.mrc")
    save_mrc(path, arr, voxel_size_nm=(40.0, 4.0, 4.0))
    back, header = load_mrc(path)
    np.testing.assert_array_equal(back, arr)
    np.testing.assert_allclose(header["voxel_size_nm"], (40.0, 4.0, 4.0), rtol=1e-5)


def test_mrc_plugin(tmp_path):
    from chunkflow_tpu.plugins import load_mrc as plugin

    arr = np.zeros((2, 4, 4), dtype=np.float32)
    path = str(tmp_path / "p.mrc")
    save_mrc(path, arr, voxel_size_nm=(40.0, 4.0, 4.0))
    img = plugin.execute(path)
    assert img.shape == (2, 4, 4)
    assert tuple(img.voxel_size) == (40, 4, 4)


def test_load_tensorstore_plugin(tmp_path):
    pytest.importorskip("tensorstore")
    import tensorstore as ts

    from chunkflow_tpu.core.bbox import BoundingBox
    from chunkflow_tpu.plugins import load_tensorstore as plugin

    store_path = str(tmp_path / "store.zarr")
    rng = np.random.default_rng(2)
    data = rng.integers(0, 255, size=(8, 8, 8), dtype=np.uint8)
    store = ts.open(
        {"driver": "zarr", "kvstore": {"driver": "file", "path": store_path}},
        create=True, dtype="uint8", shape=(8, 8, 8),
    ).result()
    store[...] = data

    bbox = BoundingBox((2, 2, 2), (6, 6, 6))
    chunk = plugin.execute(bbox, driver="zarr", kvstore=f"file://{store_path}")
    np.testing.assert_array_equal(np.asarray(chunk.array), data[2:6, 2:6, 2:6])
    assert tuple(chunk.voxel_offset) == (2, 2, 2)
