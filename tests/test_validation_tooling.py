"""Unit tests for the battery's provenance machinery
(tools/tpu_validation.py): every recorded row must carry the measuring
commit, the jax backend, and — under geometry env overrides — a
geometry_note, so a rehearsal number can never masquerade as a
production on-chip measurement (ROUND4.md §2)."""
import importlib.util
import json
import sys
from pathlib import Path

import pytest

_TV_PATH = Path(__file__).resolve().parents[1] / "tools" / "tpu_validation.py"


@pytest.fixture()
def tv(tmp_path, monkeypatch):
    """Import tools/tpu_validation.py with a redirected results file."""
    results = tmp_path / "results.json"
    monkeypatch.setenv("CHUNKFLOW_VALIDATION_RESULTS", str(results))
    monkeypatch.setenv("CHUNKFLOW_REVALIDATE", "1")
    spec = importlib.util.spec_from_file_location(
        "tv_under_test", str(_TV_PATH)
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["tv_under_test"] = mod
    try:
        spec.loader.exec_module(mod)
        yield mod, results
    finally:
        sys.modules.pop("tv_under_test", None)


def test_env_geometry_note_empty_without_overrides(tv, monkeypatch):
    mod, _ = tv
    for name in ("CHUNKFLOW_BENCH_CHUNK", "CHUNKFLOW_BENCH_PATCH",
                 "CHUNKFLOW_BENCH_OVERLAP", "CHUNKFLOW_BENCH_JUMBO"):
        monkeypatch.delenv(name, raising=False)
    assert mod._env_geometry_note() == ""


def test_env_geometry_note_lists_overrides(tv, monkeypatch):
    mod, _ = tv
    monkeypatch.setenv("CHUNKFLOW_BENCH_CHUNK", "16,64,64")
    monkeypatch.setenv("CHUNKFLOW_BENCH_JUMBO", "24,128,128")
    note = mod._env_geometry_note()
    assert "chunk=16,64,64" in note
    assert "jumbo=24,128,128" in note


def test_step_stamps_commit_platform_and_geometry(tv, monkeypatch):
    mod, results = tv
    monkeypatch.setenv("CHUNKFLOW_BENCH_CHUNK", "16,64,64")

    @mod.step("bench_fake")
    def fake():
        return {"mvox_s": 1.0}

    assert fake()
    row = json.loads(results.read_text())["bench_fake"]
    assert row["ok"] is True
    assert row["commit"] and row["commit"] != "unknown"
    # conftest pins the cpu backend and jax is already imported, so the
    # platform stamp must be exactly "cpu" — "" would mean stamping broke
    assert row["platform"] == "cpu"
    assert "geometry_note" in row["value"]


def test_step_records_failure_with_provenance(tv):
    mod, results = tv

    @mod.step("bench_boom")
    def boom():
        raise RuntimeError("deliberate")

    assert not boom()
    row = json.loads(results.read_text())["bench_boom"]
    assert row["ok"] is False
    assert "deliberate" in row["error"]
    # failure rows carry provenance too: a failed row in the resume cache
    # must be attributable to the commit/platform it failed on
    assert row["commit"] and row["commit"] != "unknown"
    assert row["platform"] == "cpu"


def test_step_resume_skips_prior_success(tv):
    mod, results = tv
    calls = []

    @mod.step("bench_once")
    def once():
        calls.append(1)
        return {"mvox_s": 2.0}

    assert once()
    assert once()  # second call skips (prior ok)
    assert len(calls) == 1


def test_tunnel_step_never_resume_skipped(tv):
    mod, _ = tv
    calls = []

    @mod.step("tunnel")
    def fake_tunnel():
        calls.append(1)
        return "devices"

    assert fake_tunnel()
    assert fake_tunnel()
    assert len(calls) == 2  # liveness gate re-runs every attempt


def test_record_writes_trailing_newline(tv):
    """ADVICE r5: frozen snapshots are committed text files — every
    results write must end with a newline."""
    mod, results = tv
    mod.record("some_step", {"ok": True})
    assert results.read_text().endswith("\n")


def test_failed_tunnel_retry_preserves_banked_tunnel_row(tv):
    """ADVICE r5: a failed tunnel retry must not overwrite the ok tunnel
    row from the attempt that banked the measurements — it banks under
    tunnel_last_retry instead, so a frozen snapshot stays internally
    consistent."""
    mod, results = tv
    mod.record("tunnel", {"ok": True, "value": "live", "commit": "aaaa111",
                          "platform": "tpu"})
    mod.RESULTS["tunnel"] = json.loads(results.read_text())["tunnel"]

    @mod.step("tunnel")
    def dead_tunnel():
        raise RuntimeError("Connection refused")

    assert not dead_tunnel()
    data = json.loads(results.read_text())
    assert data["tunnel"]["ok"] is True  # the banked row survived
    assert data["tunnel"]["commit"] == "aaaa111"
    retry = data["tunnel_last_retry"]
    assert retry["ok"] is False
    assert "Connection refused" in retry["error"]


def test_failed_tunnel_with_no_prior_success_records_failure(tv):
    mod, results = tv

    @mod.step("tunnel")
    def dead_tunnel():
        raise RuntimeError("Connection refused")

    assert not dead_tunnel()
    data = json.loads(results.read_text())
    assert data["tunnel"]["ok"] is False


def test_freeze_snapshot_stamps_tunnel_retry_note(tv, tmp_path):
    """A freeze whose tunnel row is a later failed retry (the r05
    inconsistency) must say so in _meta and end with a newline."""
    mod, results = tv
    live = {
        "tunnel": {"ok": False, "commit": "bbbb222", "platform": "",
                   "error": "Connection refused"},
        "bench_flagship": {"ok": True, "commit": "aaaa111",
                           "platform": "tpu", "value": {"mvox_s": 2.0}},
    }
    results.write_text(json.dumps(live))
    dest = tmp_path / "frozen.json"
    mod.freeze_snapshot(str(dest))
    text = dest.read_text()
    assert text.endswith("\n")
    frozen = json.loads(text)
    note = frozen["_meta"]["tunnel_row_note"]
    assert "LAST RETRY" in note
    assert "bbbb222" in note and "aaaa111" in note
    # the data rows themselves are untouched
    assert frozen["tunnel"] == live["tunnel"]
    assert frozen["bench_flagship"] == live["bench_flagship"]


def test_freeze_snapshot_consistent_run_gets_no_note(tv, tmp_path):
    mod, results = tv
    live = {
        "tunnel": {"ok": True, "commit": "aaaa111", "platform": "tpu",
                   "value": "live"},
        "bench_flagship": {"ok": True, "commit": "aaaa111",
                           "platform": "tpu", "value": {"mvox_s": 2.0}},
    }
    results.write_text(json.dumps(live))
    dest = tmp_path / "frozen.json"
    mod.freeze_snapshot(str(dest))
    frozen = json.loads(dest.read_text())
    assert "tunnel_row_note" not in frozen["_meta"]
    assert frozen["_meta"]["measured_at_commit"]  # provenance stamped
    assert dest.read_text().endswith("\n")


def test_committed_r05_snapshot_is_consistent():
    """The r05 snapshot this advisory was about: now carries the
    tunnel-row note and a trailing newline."""
    path = _TV_PATH.parent / "tpu_validation_r05.json"
    text = path.read_text()
    assert text.endswith("\n")
    data = json.loads(text)
    assert "tunnel_row_note" in data["_meta"]
