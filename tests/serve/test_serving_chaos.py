"""Serving chaos acceptance (ISSUE 9): a REAL worker process SIGKILLed
mid-request. The request rides the PR 5 lifecycle — lease expiry,
redelivery to a fresh worker, exactly-once commit through the ledger —
and the client still gets one correct response."""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.inference import Inferencer
from chunkflow_tpu.serve.frontend import (
    AdmissionController,
    ServingService,
    SpoolBackend,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture
def clean(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield monkeypatch
    telemetry.reset()


def _spawn_worker(spool: str, slow_plugin: str, log_path: str):
    """One external serving worker: the standard supervised
    fetch/compute/save/ack chain over the spool queue — exactly the
    chain a fleet-run would spawn."""
    cmd = [
        sys.executable, "-m", "chunkflow_tpu.flow.cli",
        "fetch-task-from-queue", "-q", os.path.join(spool, "queue"),
        "-v", "3", "-r", "60", "--poll-interval", "0.25",
        "--max-retries", "20", "--lease-renew", "1.0",
        "--backoff-base", "0.01", "--backoff-cap", "0.1",
        "--ledger", os.path.join(spool, "ledger"),
        "load-h5", "-f", os.path.join(spool, "in") + os.sep,
        "plugin", "--name", slow_plugin,
        "inference", "-s", "4", "8", "8", "-v", "1", "2", "2",
        "-c", "1", "-f", "identity", "--no-crop-output-margin",
        "save-h5", "--file-name", os.path.join(spool, "out") + os.sep,
        "delete-task-in-queue",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="",
               PYTHONPATH=REPO_ROOT)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    log = open(log_path, "ab")
    try:
        return subprocess.Popen(cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
    finally:
        log.close()


def test_worker_sigkill_mid_request_completes_exactly_once(
        clean, tmp_path):
    """POST-shaped request -> spool queue -> worker A claims it ->
    SIGKILL worker A mid-compute -> the lease expires, worker B claims
    the redelivery, completes, commits -> the front-end answers 200
    with the bit-exact result; exactly one ledger marker, one output
    file, a clean queue."""
    spool = str(tmp_path / "spool")
    slow = str(tmp_path / "slow.py")
    with open(slow, "w") as f:
        # a wide, honest kill window on any box
        f.write("import time\n\n\ndef execute(chunk):\n"
                "    time.sleep(1.0)\n    return chunk\n")

    backend = SpoolBackend(spool, visibility_timeout=3.0, poll_s=0.05)
    service = ServingService(
        backend, admission=AdmissionController(max_inflight=4),
        default_deadline_s=120.0,
    )
    rng = np.random.default_rng(6)
    arr = rng.random((8, 16, 16)).astype(np.float32)
    reference = Inferencer(
        input_patch_size=(4, 8, 8), output_patch_overlap=(1, 2, 2),
        num_output_channels=1, framework="identity",
        crop_output_margin=False, batch_size=1,
    )
    ref = np.asarray(reference(Chunk(arr)).array)

    import base64

    body = json.dumps({
        "shape": list(arr.shape),
        "dtype": "float32",
        "data_b64": base64.b64encode(arr.tobytes()).decode(),
        "deadline_s": 110.0,
    }).encode()

    response = {}

    def post():
        response["status"], response["payload"] = service.handle(
            "POST", "/infer", body)

    worker_a = _spawn_worker(spool, slow, str(tmp_path / "worker-a.log"))
    worker_b = None
    client = threading.Thread(target=post, daemon=True)
    try:
        client.start()
        # wait until worker A actually CLAIMS the request (in-flight on
        # the queue), then kill it inside the 1 s slow-plugin window
        deadline = time.time() + 60
        while time.time() < deadline:
            stats = backend.queue.stats()
            if stats.get("inflight"):
                break
            time.sleep(0.02)
        else:
            raise AssertionError("worker A never claimed the request")
        os.kill(worker_a.pid, signal.SIGKILL)  # crash-shaped death
        assert worker_a.wait(timeout=10) == -signal.SIGKILL
        # the claim is now a dead lease; a fresh worker must recover it
        worker_b = _spawn_worker(spool, slow,
                                 str(tmp_path / "worker-b.log"))
        client.join(timeout=120)
        assert not client.is_alive(), "request never completed"
        assert response["status"] == 200, response
        got = np.frombuffer(
            base64.b64decode(response["payload"]["data_b64"]),
            dtype=response["payload"]["dtype"],
        ).reshape(response["payload"]["shape"])
        assert np.array_equal(got, ref), "recovered result diverged"
        # exactly once: one ledger marker, one output file
        ledger_dir = os.path.join(spool, "ledger")
        marks = [n for n in os.listdir(ledger_dir)
                 if n.endswith(".done")]
        assert len(marks) == 1, marks
        outs = [n for n in os.listdir(os.path.join(spool, "out"))
                if n.endswith(".h5")]
        assert len(outs) == 1, outs
        # queue clean: nothing pending/in-flight/dead-lettered
        for _ in range(100):
            stats = backend.queue.stats()
            if not stats.get("pending") and not stats.get("inflight"):
                break
            time.sleep(0.1)
        assert not stats.get("pending"), stats
        assert not backend.queue.dead_letters()
    finally:
        for proc in (worker_a, worker_b):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        backend.close()
