"""ISSUE 12 end-to-end SLO acceptance: a served workload with an
injected regression drives the fast-burn window over threshold —
exactly one alert event with correct burn/budget attributes, reported
by /alerts live AND by log-summary --slo from merged JSONL alone after
the worker is SIGKILLed; one profiler capture fires and a second alert
inside the cooldown triggers none; a healthy run of the same workload
fires nothing; CHUNKFLOW_TELEMETRY=0 creates no sampler thread, no
events, and no /alerts route."""
import base64
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import slo, telemetry
from chunkflow_tpu.inference import Inferencer
from chunkflow_tpu.serve.frontend import LocalBackend, ServingService
from chunkflow_tpu.testing import chaos


@pytest.fixture
def clean(monkeypatch):
    for var in ("CHUNKFLOW_TELEMETRY", "CHUNKFLOW_SLO", "CHUNKFLOW_SERVE",
                "CHUNKFLOW_TS_INTERVAL", "CHUNKFLOW_CHAOS",
                "CHUNKFLOW_SCHED_MEM_GB"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    chaos.reset()
    yield monkeypatch
    chaos.reset()
    telemetry.reset()


def make_inferencer():
    return Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=4,
        crop_output_margin=False,
    )


def infer_body(arr, deadline_s=20.0):
    return json.dumps({
        "shape": list(arr.shape),
        "dtype": arr.dtype.name,
        "data_b64": base64.b64encode(
            np.ascontiguousarray(arr).tobytes()).decode(),
        "deadline_s": deadline_s,
    }).encode()


#: fast-burn-only test config: tiny windows so days compress to a
#: second, only the serving objectives armed (the dead_letter objective
#: would double-fire on the injected failures — this test asserts
#: EXACTLY one alert per regression)
SLO_TOML = """
period_s = 600
[objective.availability]
target = 0.9
[objective.deadline]
target = 0.9
[objective.latency]
enabled = false
[objective.dead_letter]
enabled = false
[objective.storage_hit]
enabled = false
[rule.fast]
short_s = 0.4
long_s = 1.6
burn = 2.0
severity = "page"
[rule.slow]
enabled = false
"""


def write_config(tmp_path):
    path = tmp_path / "slo.toml"
    path.write_text(SLO_TOML)
    return str(path)


def drive_requests(service, body, n, pause=0.04):
    statuses = []
    for _ in range(n):
        status, _payload = service.handle("POST", "/infer", body)
        statuses.append(status)
        time.sleep(pause)
    return statuses


def test_regression_fires_one_alert_one_capture_cooldown_blocks_second(
    clean, tmp_path
):
    """The core acceptance run: chaos-injected compute failures burn
    the availability budget -> exactly one page alert with burn/budget
    attributes, /alerts reports it, one bounded profiler capture lands;
    a second regression (deadline misses) pages inside the cooldown and
    captures nothing more."""
    from chunkflow_tpu.core import profiling

    clean.setenv("CHUNKFLOW_TS_INTERVAL", "0.05")
    clean.setenv("CHUNKFLOW_PROFILE_ON_ANOMALY", "1")
    clean.setenv("CHUNKFLOW_PROFILE_SECONDS", "0.1")
    clean.setenv("CHUNKFLOW_PROFILE_COOLDOWN", "600")
    metrics_dir = tmp_path / "metrics"
    telemetry.configure(str(metrics_dir))
    evaluator = slo.start_slo(write_config(tmp_path),
                              pyproject="/nonexistent")
    assert evaluator is not None

    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1, max_retries=0,
                           backoff_base=0.01)
    service = ServingService(backend, default_deadline_s=10.0,
                             max_body_mb=16)
    rng = np.random.default_rng(0)
    arr = (rng.random((4, 16, 16)) * 255).astype(np.uint8)
    try:
        # --- phase 1: every compute fails (a poisoned model push) ----
        chaos.configure("seed=1:rate=1.0:points=serving/compute")
        deadline = time.time() + 20
        while time.time() < deadline and not evaluator.firing():
            status, _ = service.handle("POST", "/infer", infer_body(arr))
            assert status in (500, 504)
            time.sleep(0.04)
        assert evaluator.firing() == ["availability:fast"]
        status, payload = service.handle("GET", "/alerts")
        assert status == 200
        assert payload["firing"] == ["availability:fast"]
        avail = next(o for o in payload["objectives"]
                     if o["name"] == "availability")
        assert avail["burn_rate"] >= 2.0
        assert avail["budget_remaining"] < 1.0
        # /serving carries the firing list too
        assert service.serving_stats()["slo_firing"] == \
            ["availability:fast"]
        profiling.wait_for_captures()
        # exactly one capture, for the paging objective (the capture
        # sequence number is process-global: other tests bump it)
        captures = [p.name for p in metrics_dir.iterdir()
                    if p.name.startswith("profile-slo-")]
        assert len(captures) == 1, captures
        assert captures[0].startswith("profile-slo-availability-")

        # --- phase 2: compute healthy again, but deadlines impossible -
        chaos.reset()
        deadline = time.time() + 20
        while time.time() < deadline and \
                "deadline:fast" not in evaluator.firing():
            status, _ = service.handle(
                "POST", "/infer", infer_body(arr, deadline_s=0.001))
            assert status == 504
            time.sleep(0.04)
        assert "deadline:fast" in evaluator.firing()
        profiling.wait_for_captures()
        captures = [p.name for p in metrics_dir.iterdir()
                    if p.name.startswith("profile-slo-")]
        assert len(captures) == 1, captures  # cooldown blocked #2
        assert captures[0].startswith("profile-slo-availability-")
        assert telemetry.snapshot()["counters"]["profile/captures"] == 1
    finally:
        chaos.reset()
        backend.close()

    # exactly one firing alert event per regression, attributes intact
    telemetry.flush()
    path = telemetry.configured_path()
    events = [json.loads(line) for line in open(path)]
    fired = [e for e in events if e.get("kind") == "alert"
             and e.get("state") == "firing"]
    by_alert = {}
    for e in fired:
        by_alert.setdefault(e["alert"], []).append(e)
    assert sorted(by_alert) == ["availability:fast", "deadline:fast"]
    assert all(len(v) == 1 for v in by_alert.values())
    first = by_alert["availability:fast"][0]
    assert first["severity"] == "page"
    assert first["burn_short"] >= 2.0 and first["burn_long"] >= 2.0
    assert first["budget_remaining"] < 1.0
    assert first["target"] == 0.9


def test_healthy_run_of_same_workload_fires_nothing(clean, tmp_path):
    clean.setenv("CHUNKFLOW_TS_INTERVAL", "0.05")
    metrics_dir = tmp_path / "metrics"
    telemetry.configure(str(metrics_dir))
    evaluator = slo.start_slo(write_config(tmp_path),
                              pyproject="/nonexistent")
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend, default_deadline_s=30.0,
                             max_body_mb=16)
    rng = np.random.default_rng(0)
    arr = (rng.random((4, 16, 16)) * 255).astype(np.uint8)
    try:
        for _ in range(8):
            status, _ = service.handle("POST", "/infer", infer_body(arr))
            assert status == 200
        time.sleep(0.8)  # several evaluation ticks past both windows
        assert evaluator.firing() == []
        status, payload = service.handle("GET", "/alerts")
        assert status == 200 and payload["firing"] == []
    finally:
        backend.close()
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.configured_path())]
    assert not [e for e in events if e.get("kind") == "alert"]
    assert [e for e in events if e.get("kind") == "timeseries"]


_VICTIM_SCRIPT = r"""
import base64, json, os, sys, time
import numpy as np
from chunkflow_tpu.core import slo, telemetry
from chunkflow_tpu.inference import Inferencer
from chunkflow_tpu.serve.frontend import LocalBackend, ServingService
from chunkflow_tpu.testing import chaos

metrics_dir, cfg = sys.argv[1], sys.argv[2]
telemetry.configure(metrics_dir)
evaluator = slo.start_slo(cfg, pyproject="/nonexistent")
inferencer = Inferencer(
    input_patch_size=(4, 16, 16), output_patch_overlap=(2, 8, 8),
    num_output_channels=3, framework="identity", batch_size=4,
    crop_output_margin=False)
backend = LocalBackend(inferencer, workers=1, max_retries=0,
                       backoff_base=0.01)
service = ServingService(backend, default_deadline_s=10.0)
chaos.configure("seed=1:rate=1.0:points=serving/compute")
rng = np.random.default_rng(0)
arr = (rng.random((4, 16, 16)) * 255).astype(np.uint8)
body = json.dumps({
    "shape": list(arr.shape), "dtype": "uint8",
    "data_b64": base64.b64encode(arr.tobytes()).decode(),
    "deadline_s": 10.0,
}).encode()
deadline = time.time() + 30
while time.time() < deadline and not evaluator.firing():
    service.handle("POST", "/infer", body)
    time.sleep(0.04)
print("ALERTED" if evaluator.firing() else "NOALERT", flush=True)
time.sleep(600)  # hold claims + sink open until the SIGKILL lands
"""


def test_alert_survives_worker_sigkill_via_log_summary(clean, tmp_path):
    """The crash half of the acceptance: the worker process is
    SIGKILLed (no flush, no atexit) right after alerting — the
    line-buffered JSONL still carries the alert + timeseries history,
    and `log-summary --slo` reconstructs the report from the dir
    alone."""
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main

    metrics_dir = tmp_path / "metrics"
    metrics_dir.mkdir()
    script = tmp_path / "victim.py"
    script.write_text(_VICTIM_SCRIPT)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "CHUNKFLOW_WORKER_ID": "slo-victim",
        "CHUNKFLOW_TS_INTERVAL": "0.05",
        "CHUNKFLOW_PROFILE_ON_ANOMALY": "0",
        "PYTHONPATH": repo_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else ""),
    })
    env.pop("XLA_FLAGS", None)  # the 8-device mesh slows child startup
    proc = subprocess.Popen(
        [sys.executable, str(script), str(metrics_dir),
         write_config(tmp_path)],
        env=env, stdout=subprocess.PIPE, text=True,
    )
    try:
        line = ""
        timer = threading.Timer(120.0, proc.kill)
        timer.start()
        try:
            line = proc.stdout.readline().strip()
        finally:
            timer.cancel()
        assert line == "ALERTED", f"victim said {line!r}"
        # SIGKILL: nothing unwinds, nothing flushes
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    result = CliRunner().invoke(
        main, ["log-summary", "--metrics-dir", str(metrics_dir), "--slo"])
    assert result.exit_code == 0, result.output
    assert "alerts fired: 1" in result.output
    assert "availability:fast page" in result.output
    assert "FIRING (slo-victim)" in result.output
    assert "rate:serving/requests" in result.output  # sparkline history


def test_kill_switch_no_sampler_no_events_no_alerts_route(
    clean, tmp_path
):
    clean.setenv("CHUNKFLOW_TELEMETRY", "0")
    clean.setenv("CHUNKFLOW_TS_INTERVAL", "0.05")
    metrics_dir = tmp_path / "off"
    assert telemetry.configure(str(metrics_dir)) is None
    assert telemetry.start_timeseries() is None
    assert slo.start_slo(write_config(tmp_path),
                         pyproject="/nonexistent") is None
    assert not any(t.name == "chunkflow-timeseries"
                   for t in threading.enumerate())
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend, max_body_mb=16)
    try:
        status, _ = service.handle("GET", "/alerts")
        assert status == 404  # the route does not exist when off
        rng = np.random.default_rng(0)
        arr = (rng.random((4, 16, 16)) * 255).astype(np.uint8)
        status, _ = service.handle("POST", "/infer", infer_body(arr))
        assert status == 200  # serving itself still works
    finally:
        backend.close()
    assert not metrics_dir.exists()  # an off run leaves no trace
