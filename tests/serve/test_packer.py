"""Cross-task patch packer: bit-identical parity with the per-chunk
fused path on ragged mixed-size traffic, kill-switch equivalence,
occupancy telemetry, deadline drops, and the graftlint gate over the
serve modules (ISSUE 9)."""
import threading
import time

import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.inference import Inferencer
from chunkflow_tpu.serve.packer import (
    PackerClosed,
    PatchPacker,
    RequestExpired,
    serve_enabled,
)


@pytest.fixture
def clean(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    monkeypatch.delenv("CHUNKFLOW_SERVE", raising=False)
    telemetry.reset()
    yield monkeypatch
    telemetry.reset()


def make_inferencer(**kwargs):
    defaults = dict(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=4,
        crop_output_margin=False,
    )
    defaults.update(kwargs)
    return Inferencer(**defaults)


#: deliberately ragged: exact patch size, mid-size, and shapes that snap
#: their last patch flush against the boundary
RAGGED_SHAPES = [(8, 32, 32), (6, 20, 28), (4, 16, 16), (9, 33, 35),
                 (5, 17, 30)]


def _parity_check(inferencer, chunks, max_wait_ms=1.0):
    """refs through the fused per-chunk program, then the same chunks
    through the packer concurrently; assert bitwise equality."""
    refs = [np.asarray(inferencer(c).array) for c in chunks]
    packer = PatchPacker(inferencer, max_wait_ms=max_wait_ms)
    try:
        handles = [packer.submit(c) for c in chunks]
        outs = [h.result(timeout=60) for h in handles]
    finally:
        packer.close()
    for i, (ref, out) in enumerate(zip(refs, outs)):
        got = np.asarray(out.array)
        assert got.dtype == ref.dtype, chunks[i].shape
        assert np.array_equal(got, ref), (
            f"chunk {tuple(chunks[i].shape)}: packed output diverged "
            f"(max |d| = {np.abs(got.astype(np.float64) - ref.astype(np.float64)).max()})"
        )
    return refs, outs


def test_packed_bit_identical_ragged_float32(clean):
    inferencer = make_inferencer()
    rng = np.random.default_rng(7)
    chunks = [
        Chunk(rng.random(s).astype(np.float32), voxel_offset=(64 * i, 0, 0))
        for i, s in enumerate(RAGGED_SHAPES)
    ]
    _parity_check(inferencer, chunks)


def test_packed_bit_identical_uint8_and_bucket_boundaries(clean):
    """uint8 input (the narrow EM-image wire path) + uint8 on-device
    quantization + shape bucketing, including shapes exactly ON the
    bucket boundary and one voxel past it."""
    inferencer = make_inferencer(
        output_patch_size=(2, 8, 8),
        output_patch_overlap=(1, 2, 2),
        num_output_channels=2,
        output_dtype="uint8",
        shape_bucket=(4, 16, 16),
    )
    rng = np.random.default_rng(3)
    shapes = [
        (8, 32, 32),   # exact multiple of the bucket
        (7, 31, 30),   # ragged: pads into the SAME (8,32,32) bucket
        (4, 16, 16),   # exactly one bucket
        (5, 17, 16),   # one voxel past a boundary on two axes
    ]
    chunks = [
        Chunk((rng.random(s) * 255).astype(np.uint8),
              voxel_offset=(64 * i, 0, 0))
        for i, s in enumerate(shapes)
    ]
    refs, _ = _parity_check(inferencer, chunks)
    assert refs[0].dtype == np.uint8
    # bucketing must have collapsed the serve scatter programs: at most
    # one per distinct bucketed run shape, not one per raw shape
    scatter_keys = {
        key for key, _ in inferencer._programs.items()
        if key[0] == "serve_scatter"
    }
    assert len(scatter_keys) < len(shapes)


def test_packed_bit_identical_with_crop_margin(clean):
    inferencer = make_inferencer(
        output_patch_size=(2, 8, 8),
        output_patch_overlap=(1, 4, 4),
        crop_output_margin=True,
    )
    rng = np.random.default_rng(11)
    chunks = [
        Chunk(rng.random(s).astype(np.float32), voxel_offset=(64 * i, 0, 0))
        for i, s in enumerate([(8, 32, 32), (6, 24, 28)])
    ]
    _parity_check(inferencer, chunks)


def test_packed_bit_identical_under_concurrent_submitters(clean):
    """Mixed-size requests racing in from many threads — the serving
    shape — still scatter back to the right task, bitwise."""
    inferencer = make_inferencer()
    rng = np.random.default_rng(5)
    chunks = [
        Chunk(rng.random(RAGGED_SHAPES[i % len(RAGGED_SHAPES)])
              .astype(np.float32), voxel_offset=(64 * i, 0, 0))
        for i in range(12)
    ]
    refs = [np.asarray(inferencer(c).array) for c in chunks]
    packer = PatchPacker(inferencer, max_wait_ms=1.0)
    results = [None] * len(chunks)

    def submit(i):
        results[i] = packer.submit(chunks[i]).result(timeout=60)

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(len(chunks))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    packer.close()
    for ref, out in zip(refs, results):
        assert out is not None
        assert np.array_equal(np.asarray(out.array), ref)


def test_all_zero_chunk_takes_blank_path(clean):
    inferencer = make_inferencer()
    chunk = Chunk(np.zeros((8, 32, 32), dtype=np.float32))
    ref = inferencer(chunk)
    packer = PatchPacker(inferencer)
    out = packer.infer(chunk, timeout=30)
    packer.close()
    assert np.array_equal(np.asarray(out.array), np.asarray(ref.array))
    assert out.array.dtype == ref.array.dtype


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------
def test_kill_switch_restores_per_chunk_path(clean):
    """CHUNKFLOW_SERVE=0: submit() routes through the untouched
    per-chunk program — bit-identical by construction — and builds NO
    serve program at all (the repo's kill-switch convention)."""
    clean.setenv("CHUNKFLOW_SERVE", "0")
    assert not serve_enabled()
    inferencer = make_inferencer()
    rng = np.random.default_rng(9)
    chunks = [
        Chunk(rng.random(s).astype(np.float32), voxel_offset=(64 * i, 0, 0))
        for i, s in enumerate(RAGGED_SHAPES[:3])
    ]
    refs = [np.asarray(inferencer(c).array) for c in chunks]
    packer = PatchPacker(inferencer, max_wait_ms=1.0)
    outs = [packer.submit(c).result(timeout=30) for c in chunks]
    packer.close()
    for ref, out in zip(refs, outs):
        assert np.array_equal(np.asarray(out.array), ref)
    keys = {key[0] for key, _ in inferencer._programs.items()}
    assert "serve_forward" not in keys
    assert "serve_scatter" not in keys
    snap = telemetry.snapshot()
    assert snap["counters"].get("serving/fallbacks", 0) == len(chunks)
    assert "serving/batches" not in snap["counters"]


def test_sharded_and_fold_inferencers_fall_back(clean):
    inferencer = make_inferencer(blend="fold")
    rng = np.random.default_rng(2)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    ref = np.asarray(inferencer(chunk).array)
    packer = PatchPacker(inferencer)
    out = packer.infer(chunk, timeout=60)
    packer.close()
    assert np.array_equal(np.asarray(out.array), ref)
    assert telemetry.snapshot()["counters"].get("serving/fallbacks") == 1


# ---------------------------------------------------------------------------
# occupancy telemetry + compile-cache reuse
# ---------------------------------------------------------------------------
def test_occupancy_telemetry_and_single_forward_trace(clean):
    """Same-size 3-patch requests against batch 8: the packed plane
    must account every batch slot (real + filler = batches * B), fill
    past per-chunk occupancy on concurrent traffic, and trace the
    forward program exactly once."""
    inferencer = make_inferencer(
        input_patch_size=(4, 16, 16), output_patch_overlap=(0, 0, 0),
        batch_size=8,
    )
    rng = np.random.default_rng(1)
    chunks = [
        Chunk(rng.random((4, 16, 48)).astype(np.float32),
              voxel_offset=(8 * i, 0, 0))
        for i in range(8)  # 8 requests x 3 patches = 24 = 3 full batches
    ]
    packer = PatchPacker(inferencer, max_wait_ms=20.0)
    handles = [packer.submit(c) for c in chunks]
    for h in handles:
        h.result(timeout=60)
    packer.close()
    snap = telemetry.snapshot()
    counters = snap["counters"]
    batches = counters["serving/batches"]
    assert counters["serving/packed_patches"] == 24
    assert (counters["serving/packed_patches"]
            + counters.get("serving/filler_slots", 0)) == batches * 8
    # the per-chunk path would have dispatched 8 one-per-request
    # batches; packing crosses requests, so strictly fewer
    assert batches < len(chunks)
    occupancy = counters["serving/packed_patches"] / (batches * 8)
    assert occupancy > 0.5
    # ONE forward trace serves all traffic (compile-cache reuse)
    forward_keys = [key for key, _ in inferencer._programs.items()
                    if key[0] == "serve_forward"]
    assert len(forward_keys) == 1
    assert "serving/queue_age" in snap["hists"]
    assert "serving/occupancy" in snap["gauges"]


# ---------------------------------------------------------------------------
# deadlines + teardown
# ---------------------------------------------------------------------------
def test_expired_request_fails_with_request_expired(clean):
    inferencer = make_inferencer()
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    packer = PatchPacker(inferencer, max_wait_ms=1.0)
    handle = packer.submit(chunk, deadline=time.time() - 1.0)
    with pytest.raises(RequestExpired):
        handle.result(timeout=30)
    # the packer stays healthy for later traffic
    ref = np.asarray(inferencer(chunk).array)
    out = packer.infer(chunk, timeout=30)
    packer.close()
    assert np.array_equal(np.asarray(out.array), ref)


def test_close_without_drain_fails_queued_requests(clean):
    inferencer = make_inferencer()
    rng = np.random.default_rng(0)
    # a huge wait window so the queued request is still pending at close
    packer = PatchPacker(inferencer, max_wait_ms=60_000.0)
    handle = packer.submit(Chunk(rng.random((4, 16, 16))
                                 .astype(np.float32)))
    packer.close(drain=False)
    with pytest.raises((PackerClosed, RequestExpired)):
        handle.result(timeout=10)
    # a submit after close fails cleanly too
    late = packer.submit(Chunk(rng.random((4, 16, 16))
                               .astype(np.float32)))
    with pytest.raises(PackerClosed):
        late.result(timeout=10)


# ---------------------------------------------------------------------------
# lint gate: the serve modules are GL001-GL007 clean
# ---------------------------------------------------------------------------
def test_serve_modules_are_graftlint_clean():
    from pathlib import Path

    from tools.graftlint.config import load_config
    from tools.graftlint.engine import lint_paths

    repo_root = Path(__file__).resolve().parents[2]
    config = load_config(repo_root / "pyproject.toml")
    findings, _ = lint_paths(
        [
            "chunkflow_tpu/serve/__init__.py",
            "chunkflow_tpu/serve/packer.py",
            "chunkflow_tpu/serve/frontend.py",
        ],
        config, repo_root=repo_root,
    )
    assert not findings, [
        f"{f.path}:{f.line}: {f.code} {f.message}" for f in findings
    ]


def test_request_larger_than_queue_bound_completes(clean):
    """Regression (GL014 audit): a request with more patches than
    ``max_queue_patches`` used to spin forever in submit's backpressure
    loop — the predicate ``len(items) + n <= bound`` can never become
    true when ``n > bound``. Oversized requests are now admitted once
    the queue has drained."""
    inferencer = make_inferencer()
    rng = np.random.default_rng(11)
    chunk = Chunk(rng.random((8, 32, 32)).astype(np.float32))
    ref = np.asarray(inferencer(chunk).array)
    packer = PatchPacker(inferencer, max_wait_ms=1.0, max_queue_patches=2)
    done = threading.Event()
    out = {}

    def go():
        out["chunk"] = packer.submit(chunk).result(timeout=30)
        done.set()

    thread = threading.Thread(target=go, daemon=True)
    thread.start()
    assert done.wait(30), "submit hung: oversized-request livelock is back"
    packer.close()
    assert np.array_equal(np.asarray(out["chunk"].array), ref)
