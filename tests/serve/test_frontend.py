"""Serving front-end: POST /infer over real HTTP, admission-reject and
deadline-expiry paths, lifecycle retry containment, port-0 ephemeral
listeners + endpoint files, the Prometheus serving histogram, and the
SERVING blocks in log-summary (ISSUE 9)."""
import base64
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.inference import Inferencer
from chunkflow_tpu.serve.frontend import (
    AdmissionController,
    AdmissionRejected,
    LocalBackend,
    ServingRequest,
    ServingService,
    start_serving,
)
from chunkflow_tpu.serve.packer import RequestExpired
from chunkflow_tpu.testing import chaos


@pytest.fixture
def clean(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    monkeypatch.delenv("CHUNKFLOW_SERVE", raising=False)
    monkeypatch.delenv("CHUNKFLOW_SCHED_MEM_GB", raising=False)
    telemetry.reset()
    chaos.reset()
    yield monkeypatch
    chaos.reset()
    telemetry.reset()


def make_inferencer():
    return Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=4,
        crop_output_margin=False,
    )


def infer_body(arr, deadline_s=20.0, **extra):
    payload = {
        "shape": list(arr.shape),
        "dtype": arr.dtype.name,
        "data_b64": base64.b64encode(
            np.ascontiguousarray(arr).tobytes()).decode(),
        "deadline_s": deadline_s,
    }
    payload.update(extra)
    return json.dumps(payload).encode()


def decode_response(payload):
    return np.frombuffer(
        base64.b64decode(payload["data_b64"]), dtype=payload["dtype"]
    ).reshape(payload["shape"])


# ---------------------------------------------------------------------------
# the full HTTP path
# ---------------------------------------------------------------------------
def test_post_infer_end_to_end_http(clean):
    """Real sockets end to end: port 0 binds ephemeral, POST /infer
    returns the bit-exact per-chunk result with a trace id, /serving
    reports the latency quantiles, and the request committed exactly
    once through the lifecycle layer."""
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=2)
    service = ServingService(backend, default_deadline_s=30.0)
    server = start_serving(service, host="127.0.0.1", port=0)
    port = server.server_address[1]
    assert port > 0
    try:
        rng = np.random.default_rng(0)
        arr = rng.random((6, 20, 28)).astype(np.float32)
        ref = np.asarray(inferencer(Chunk(arr)).array)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/infer",
            data=infer_body(arr), method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read())
        assert np.array_equal(decode_response(payload), ref)
        assert payload["trace_id"]
        assert payload["latency_s"] > 0
        # /serving rides the same listener
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/serving", timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["requests"] == 1
        assert stats["completed"] == 1
        assert stats["latency_p50_s"] > 0
        # /metrics renders the latency histogram + serving counters
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "chunkflow_serving_latency_bucket" in text
        from chunkflow_tpu.parallel.restapi import serving_stats

        parsed = serving_stats(text)
        assert parsed["completed"] == 1
        assert parsed["p50_s"] is not None
        # exactly-once commit through the lifecycle layer
        snap = telemetry.snapshot()
        assert snap["counters"].get("tasks/committed") == 1
        assert len(backend.ledger) == 1
    finally:
        backend.close()
        server.shutdown()
        server.server_close()


def test_uint8_request_round_trip(clean):
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend)
    try:
        rng = np.random.default_rng(4)
        arr = (rng.random((8, 32, 32)) * 255).astype(np.uint8)
        ref = np.asarray(inferencer(Chunk(arr)).array)
        status, payload = service.handle("POST", "/infer", infer_body(arr))
        assert status == 200
        assert np.array_equal(decode_response(payload), ref)
    finally:
        backend.close()


# ---------------------------------------------------------------------------
# admission control + backpressure
# ---------------------------------------------------------------------------
def test_admission_rejects_past_max_inflight(clean):
    admission = AdmissionController(max_inflight=0)
    with pytest.raises(AdmissionRejected) as err:
        admission.admit(1024)
    assert err.value.reason == "inflight"
    assert telemetry.snapshot()["counters"][
        "serving/rejected_admission"] == 1


def test_admission_reject_is_clean_429_not_worker_death(clean):
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(
        backend, admission=AdmissionController(max_inflight=0))
    try:
        arr = np.zeros((4, 16, 16), dtype=np.float32)
        status, payload = service.handle("POST", "/infer", infer_body(arr))
        assert status == 429
        assert payload["reason"] == "inflight"
        # the server still works once capacity exists
        service.admission.max_inflight = 4
        rng = np.random.default_rng(1)
        arr = rng.random((8, 32, 32)).astype(np.float32)
        status, payload = service.handle("POST", "/infer", infer_body(arr))
        assert status == 200
    finally:
        backend.close()


def test_memory_watermark_backpressure(clean):
    """Admission shares the adaptive scheduler's host-memory watermark:
    a tiny CHUNKFLOW_SCHED_MEM_GB rejects the request with reason
    'memory' instead of admitting it into an OOM."""
    clean.setenv("CHUNKFLOW_SCHED_MEM_GB", "0.000001")  # ~1 KB
    admission = AdmissionController(max_inflight=8)
    with pytest.raises(AdmissionRejected) as err:
        admission.admit(1 << 20)
    assert err.value.reason == "memory"
    assert telemetry.snapshot()["counters"]["serving/rejected_memory"] == 1
    # and the depth controller sees serving reservations too
    clean.setenv("CHUNKFLOW_SCHED_MEM_GB", "4")
    from chunkflow_tpu.flow.scheduler import (
        DepthController,
        release_host_bytes,
        reserve_host_bytes,
    )

    ctl = DepthController(watermark_bytes=1 << 20)
    ctl.note_slot_bytes(1 << 10)
    assert ctl._would_fit()
    assert reserve_host_bytes(1 << 20)  # hog the whole watermark
    try:
        assert not ctl._would_fit()
    finally:
        release_host_bytes(1 << 20)
    assert ctl._would_fit()


def test_malformed_requests_get_400(clean):
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend)
    try:
        for body in (
            None,
            b"not json",
            json.dumps({"shape": [4, 16, 16]}).encode(),  # no data
            json.dumps({"shape": [0, 16, 16], "dtype": "uint8",
                        "data_b64": ""}).encode(),
            json.dumps({"shape": [4, 16, 16], "dtype": "float64",
                        "data_b64": ""}).encode(),
            json.dumps({"shape": [4, 16, 16], "dtype": "uint8",
                        "data_b64": "AAAA"}).encode(),  # size mismatch
        ):
            status, payload = service.handle("POST", "/infer", body)
            assert status == 400, body
            assert "error" in payload
    finally:
        backend.close()


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------
class _StallBackend:
    """A backend that never completes anything: the deadline clock is
    the only way out."""

    def submit(self, record):
        pass

    def wait(self, record, timeout):
        return record.wait(timeout)

    def close(self):
        pass


def test_deadline_miss_is_clean_504(clean):
    service = ServingService(_StallBackend(), default_deadline_s=0.2)
    arr = np.zeros((4, 16, 16), dtype=np.float32)
    t0 = time.time()
    status, payload = service.handle(
        "POST", "/infer", infer_body(arr, deadline_s=0.2))
    assert status == 504
    assert time.time() - t0 < 5.0
    counters = telemetry.snapshot()["counters"]
    assert counters["serving/deadline_missed"] == 1
    # a miss is shed load, not an error
    assert counters.get("serving/errors", 0) == 0


def test_serving_request_outcome_is_first_wins_and_counted_once(clean):
    record = ServingRequest(None, deadline=time.time() + 10)
    assert record.fail(RequestExpired("late"))
    assert not record.fail(RequestExpired("later"))
    assert not record.complete("result")
    counters = telemetry.snapshot()["counters"]
    assert counters["serving/deadline_missed"] == 1
    assert counters.get("serving/completed", 0) == 0


# ---------------------------------------------------------------------------
# lifecycle containment: transient failures retry, requests complete once
# ---------------------------------------------------------------------------
def test_transient_compute_failure_retries_via_lifecycle(clean):
    """A chaos kill at the serving compute boundary is contained by the
    lifecycle layer: the request retries with backoff and completes
    exactly once — the worker does not die, the client sees one 200."""
    chaos.configure("once=serving/compute")
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1, max_retries=3,
                           backoff_base=0.01, backoff_cap=0.05)
    service = ServingService(backend, default_deadline_s=30.0)
    try:
        rng = np.random.default_rng(8)
        arr = rng.random((8, 32, 32)).astype(np.float32)
        ref = np.asarray(inferencer(Chunk(arr)).array)
        status, payload = service.handle("POST", "/infer", infer_body(arr))
        assert status == 200
        assert np.array_equal(decode_response(payload), ref)
        counters = telemetry.snapshot()["counters"]
        assert counters.get("chaos/injected", 0) == 1
        assert counters.get("tasks/retried", 0) == 1
        assert counters.get("serving/completed") == 1
        assert counters.get("tasks/committed") == 1
        assert len(backend.ledger) == 1  # exactly one commit marker
    finally:
        backend.close()


def test_poison_request_dead_letters_and_fails_cleanly(clean):
    """A request that fails permanently every time exhausts its retry
    budget and dead-letters; the client gets a clean error, the server
    keeps serving."""
    chaos.configure("seed=1:rate=1.0:points=serving/compute")
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1, max_retries=1,
                           backoff_base=0.01, backoff_cap=0.02)
    service = ServingService(backend, default_deadline_s=15.0)
    try:
        arr = np.random.default_rng(0).random((4, 16, 16)) \
            .astype(np.float32)
        status, payload = service.handle("POST", "/infer", infer_body(arr))
        assert status in (500, 504)
        chaos.reset()
        status, payload = service.handle("POST", "/infer", infer_body(arr))
        assert status == 200
    finally:
        backend.close()


# ---------------------------------------------------------------------------
# port 0 + endpoint files (the fleet-supervisor discovery path)
# ---------------------------------------------------------------------------
def test_metrics_exporter_port0_reports_bound_port(clean):
    from chunkflow_tpu.parallel.restapi import (
        bound_port,
        start_metrics_exporter,
    )

    server = start_metrics_exporter(0, host="127.0.0.1")
    try:
        port = bound_port(server)
        assert port and port > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert json.loads(resp.read())["status"] == "ok"
    finally:
        server.shutdown()
        server.server_close()


def test_endpoint_file_write_read_merge(clean, tmp_path):
    from chunkflow_tpu.parallel.restapi import (
        read_endpoint_file,
        write_endpoint_file,
    )

    clean.setenv("CHUNKFLOW_WORKER_ID", "fleet-w007")
    telemetry.reset()  # drop the cached worker id
    path = write_endpoint_file(str(tmp_path), metrics_port=18080)
    assert path is not None
    record = read_endpoint_file(str(tmp_path), "fleet-w007")
    assert record["metrics_port"] == 18080
    assert record["worker"] == "fleet-w007"
    # a later write (the serving listener) merges, not clobbers
    write_endpoint_file(str(tmp_path), serving_port=18081)
    record = read_endpoint_file(str(tmp_path), "fleet-w007")
    assert record["metrics_port"] == 18080
    assert record["serving_port"] == 18081
    assert read_endpoint_file(str(tmp_path), "nobody") is None


def test_endpoint_file_respects_kill_switch(clean, tmp_path):
    from chunkflow_tpu.parallel.restapi import write_endpoint_file

    clean.setenv("CHUNKFLOW_TELEMETRY", "0")
    assert write_endpoint_file(str(tmp_path), metrics_port=1) is None
    assert not list(tmp_path.iterdir())


def test_fleet_discovers_port_from_endpoint_file(clean, tmp_path):
    """The supervisor resolves an ephemeral-spawned worker's bound port
    from its endpoint file instead of pre-picking (racy) ports."""
    from chunkflow_tpu.parallel.fleet import FleetSupervisor, WorkerHandle
    from chunkflow_tpu.parallel.restapi import write_endpoint_file

    clean.setenv("CHUNKFLOW_WORKER_ID", "fleet-w001")
    telemetry.reset()
    write_endpoint_file(str(tmp_path), metrics_port=23456)
    clean.delenv("CHUNKFLOW_WORKER_ID")
    telemetry.reset()

    supervisor = FleetSupervisor.__new__(FleetSupervisor)
    supervisor.metrics_dir = str(tmp_path)

    class _Proc:
        pid = 4242

        def poll(self):
            return None

    worker = WorkerHandle("fleet-w001", None, _Proc(), [])
    assert worker.to_record()["endpoint"] is None
    assert supervisor._discover_port(worker) == 23456
    assert worker.port == 23456
    assert worker.to_record()["endpoint"] == "127.0.0.1:23456"
    # unknown worker: stays undiscovered (probation handles it)
    other = WorkerHandle("fleet-w999", None, _Proc(), [])
    assert supervisor._discover_port(other) is None


# ---------------------------------------------------------------------------
# SERVING blocks: log-summary + fleet summary
# ---------------------------------------------------------------------------
def test_log_summary_serving_block(clean, tmp_path, capsys):
    from chunkflow_tpu.flow.log_summary import (
        print_fleet_summary,
        print_telemetry_summary,
    )

    telemetry.configure(str(tmp_path))
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend)
    try:
        rng = np.random.default_rng(2)
        for i in range(3):
            arr = rng.random((6, 20, 28)).astype(np.float32)
            status, _ = service.handle("POST", "/infer", infer_body(arr))
            assert status == 200
    finally:
        backend.close()
    telemetry.flush()
    telemetry.configure(None)
    agg = print_telemetry_summary(str(tmp_path))
    out = capsys.readouterr().out
    assert "serving (docs/serving.md):" in out
    assert "serving/requests" in out
    assert "latency p50" in out
    assert agg["counters"]["serving/completed"] == 3
    assert agg["qhists"]["serving/latency"]["count"] == 3
    # per-worker SERVING line in the fleet view
    print_fleet_summary(str(tmp_path))
    out = capsys.readouterr().out
    assert "serving: requests=3" in out


def test_cli_serve_end_to_end(clean, tmp_path):
    """The `chunkflow serve` entry point: ephemeral port published via
    the endpoint file, a live POST /infer answered, graceful drain at
    --max-runtime with the summary line."""
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main as cli_main
    from chunkflow_tpu.parallel.restapi import read_endpoint_file

    clean.setenv("CHUNKFLOW_WORKER_ID", "serve-cli-test")
    metrics_dir = tmp_path / "metrics"
    runner = CliRunner()
    result = {}

    def run_cli():
        result["run"] = runner.invoke(
            cli_main,
            [
                "--metrics-dir", str(metrics_dir),
                "serve", "--port", "0", "--host", "127.0.0.1",
                "-p", "4", "16", "16",
                "--framework", "identity", "-c", "1",
                "--batch-size", "2", "--serve-workers", "1",
                "--max-runtime", "15",
            ],
            catch_exceptions=False,
        )

    thread = threading.Thread(target=run_cli, daemon=True)
    thread.start()
    port = None
    deadline = time.time() + 12
    while time.time() < deadline:
        record = read_endpoint_file(str(metrics_dir), "serve-cli-test")
        if record and record.get("serving_port"):
            port = record["serving_port"]
            break
        time.sleep(0.1)
    assert port, "serve never published its bound port"
    arr = np.random.default_rng(0).random((8, 32, 32)) \
        .astype(np.float32)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/infer",
        data=infer_body(arr, deadline_s=10.0), method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
        payload = json.loads(resp.read())
    assert payload["shape"] == [1, 8, 32, 32]
    thread.join(timeout=30)
    assert not thread.is_alive(), "serve did not exit at --max-runtime"
    out = result["run"].output
    assert "serving: http://127.0.0.1:" in out
    assert "serve drained:" in out
    assert result["run"].exit_code == 0


# ---------------------------------------------------------------------------
# volume-reference requests ride the shared BlockCache (ISSUE 15 satellite)
# ---------------------------------------------------------------------------
def test_volume_reference_request_rides_block_cache(clean, tmp_path):
    """A request naming a precomputed volume + bbox instead of inline
    data: the serving plane cuts the chunk out itself through
    PrecomputedVolume.cutout — block-decomposed reads riding the shared
    hot-block LRU (docs/storage.md) — and the result is bit-exact with
    the same region posted inline. A second overlapping request hits the
    cache instead of the store."""
    pytest.importorskip("tensorstore")
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume
    from chunkflow_tpu.volume.storage import reset_shared_cache

    clean.delenv("CHUNKFLOW_STORAGE_CACHE_MB", raising=False)
    reset_shared_cache()
    vol = PrecomputedVolume.create(
        str(tmp_path / "vol"),
        volume_size=(16, 48, 48),
        voxel_size=(40, 4, 4),
        voxel_offset=(0, 0, 0),
        dtype="uint8",
        block_size=(8, 16, 16),
    )
    source = Chunk.create((16, 48, 48), dtype=np.uint8,
                          voxel_size=(40, 4, 4))
    vol.save(source)
    # drop the write-through-populated cache so the FIRST serving load
    # demonstrably reads the store (misses), and the second hits
    reset_shared_cache()

    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend)
    try:
        body = json.dumps({
            "volume_path": str(tmp_path / "vol"),
            "bbox_start": [0, 0, 0],
            "bbox_size": [8, 32, 48],
            "deadline_s": 30.0,
        }).encode()
        status, payload = service.handle("POST", "/infer", body)
        assert status == 200, payload
        inline = np.asarray(source.array)[:8, :32, :48]
        ref_status, ref_payload = service.handle(
            "POST", "/infer", infer_body(inline))
        assert ref_status == 200
        assert np.array_equal(decode_response(payload),
                              decode_response(ref_payload))

        misses_before = telemetry.snapshot()["counters"].get(
            "storage/misses", 0)
        assert misses_before > 0  # the first load really hit the store
        status, _ = service.handle("POST", "/infer", body)
        assert status == 200
        counters = telemetry.snapshot()["counters"]
        # the repeat load is served from the shared hot-block LRU: hits
        # accrue, misses do not
        assert counters.get("storage/hits", 0) > 0
        assert counters.get("storage/misses", 0) == misses_before
        # one cached volume handle, reused across requests
        assert len(service._volumes) == 1
    finally:
        backend.close()
        reset_shared_cache()


def test_volume_reference_request_validation(clean, tmp_path):
    """Volume-reference request validation is a clean 400: bad bbox,
    mixing inline data with a volume ref, an unreadable dataset, and an
    over-bound bbox all fail without touching the worker pool."""
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend, max_body_mb=1.0)
    try:
        def post(payload):
            return service.handle(
                "POST", "/infer", json.dumps(payload).encode())

        status, payload = post({"volume_path": str(tmp_path / "nope"),
                                "bbox_start": [0, 0, 0],
                                "bbox_size": [8, 16, 16]})
        assert status == 400 and "cannot open volume" in payload["error"]
        status, payload = post({"volume_path": "x", "bbox_start": [0, 0],
                                "bbox_size": [8, 16, 16]})
        assert status == 400 and "bbox_start" in payload["error"]
        status, payload = post({"volume_path": "x",
                                "bbox_start": [0, 0, 0],
                                "bbox_size": [8, 16, 0]})
        assert status == 400 and "bbox_size" in payload["error"]
        status, payload = post({"volume_path": "x",
                                "bbox_start": [0, 0, 0],
                                "bbox_size": [8, 16, 16],
                                "data_b64": "AAAA"})
        assert status == 400 and "mutually exclusive" in payload["error"]
        status, payload = post({"volume_path": "x",
                                "bbox_start": [0, 0, 0],
                                "bbox_size": [8, 16, 16],
                                "mip": -1})
        assert status == 400 and "mip" in payload["error"]
    finally:
        backend.close()


def test_volume_reference_over_bound_bbox_rejected(clean, tmp_path):
    """A bbox implying more bytes than the request bound is refused
    BEFORE any store read."""
    pytest.importorskip("tensorstore")
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    PrecomputedVolume.create(
        str(tmp_path / "vol"),
        volume_size=(16, 48, 48),
        voxel_size=(40, 4, 4),
        voxel_offset=(0, 0, 0),
        dtype="uint8",
        block_size=(8, 16, 16),
    )
    inferencer = make_inferencer()
    backend = LocalBackend(inferencer, workers=1)
    service = ServingService(backend, max_body_mb=0.00001)
    try:
        status, payload = service.handle("POST", "/infer", json.dumps({
            "volume_path": str(tmp_path / "vol"),
            "bbox_start": [0, 0, 0],
            "bbox_size": [8, 32, 48],
        }).encode())
        assert status == 400
        assert "over the" in payload["error"]
    finally:
        backend.close()
