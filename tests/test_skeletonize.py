"""TEASAR-lite skeletonize plugin: topology correctness on synthetic shapes."""
import numpy as np

from chunkflow_tpu.chunk.segmentation import Segmentation
from chunkflow_tpu.plugins import skeletonize


def _tree_is_valid(skel):
    n = len(skel)
    roots = np.nonzero(skel.parents == -1)[0]
    assert len(roots) == 1
    for i in range(n):
        j, hops = i, 0
        while skel.parents[j] != -1:
            j = int(skel.parents[j])
            hops += 1
            assert hops <= n
    return True


def test_skeletonize_branching_object_topology():
    # T-shaped tube: horizontal bar + vertical stem in one z-plane slab
    seg = np.zeros((3, 40, 40), dtype=np.uint32)
    seg[:, 18:22, 4:36] = 1          # bar along x
    seg[:, 4:30, 18:22] = 1          # stem along y, crossing the bar
    chunk = Segmentation(seg, voxel_size=(1, 1, 1))
    skels = skeletonize.execute(chunk, voxel_num_threshold=10)
    assert 1 in skels
    skel = skels[1]
    assert _tree_is_valid(skel)
    # no spurious giant edge: every edge should be short (neighbors in a
    # 26-connected voxel grid are <= sqrt(3) apart; allow path joins a bit
    # more slack)
    edges = skel.edges
    lengths = np.linalg.norm(
        skel.nodes[edges[:, 0]] - skel.nodes[edges[:, 1]], axis=1
    )
    assert lengths.max() <= 2.0, (
        f"misattached branch: edge of length {lengths.max()}"
    )
    # the skeleton should span all three arms of the T: total cable length
    # must be a reasonable fraction of bar+stem extents (32 + 26)
    assert skel.cable_length() > 35.0


def test_skeletonize_cylinder_centerline_accuracy():
    """A straight tube's skeleton must hug the medial axis (kimimaro-class
    behavior): nodes near the (y,x) center, spanning ~the full z extent,
    with radii ~ the tube radius away from the ends."""
    Z, R, CY, CX = 40, 5, 16, 16
    seg = np.zeros((Z, 32, 32), dtype=np.uint32)
    yy, xx = np.mgrid[0:32, 0:32]
    disk = (yy - CY) ** 2 + (xx - CX) ** 2 <= R ** 2
    seg[:, disk] = 7
    chunk = Segmentation(seg, voxel_size=(1, 1, 1))
    skels = skeletonize.execute(chunk, voxel_num_threshold=10)
    skel = skels[7]
    assert _tree_is_valid(skel)
    # interior nodes within 2 voxels of the axis (TEASAR penalty keeps
    # paths on the medial axis; endpoints legitimately climb to the
    # end-cap rim — the path target is the furthest voxel, as in
    # kimimaro — so only judge z in [R, Z-R))
    interior_z = (skel.nodes[:, 0] >= R) & (skel.nodes[:, 0] < Z - R)
    off_axis = np.linalg.norm(skel.nodes[interior_z, 1:] - [CY, CX], axis=1)
    assert off_axis.max() <= 2.0, off_axis.max()
    # spans (almost) the whole cylinder
    zspan = skel.nodes[:, 0].max() - skel.nodes[:, 0].min()
    assert zspan >= Z - 4, zspan
    # interior radii estimate the tube radius
    interior = (skel.nodes[:, 0] > 8) & (skel.nodes[:, 0] < Z - 8)
    assert interior.any()
    assert np.all(np.abs(skel.radii[interior] - R) <= 2.0)


def test_skeletonize_anisotropic_voxels():
    """Physical coordinates honor anisotropic voxel size (EM stacks are
    typically (40, 4, 4) nm-ish)."""
    seg = np.zeros((20, 12, 12), dtype=np.uint32)
    seg[:, 4:8, 4:8] = 3
    chunk = Segmentation(seg, voxel_size=(40, 4, 4))
    skels = skeletonize.execute(chunk, voxel_num_threshold=10)
    skel = skels[3]
    assert _tree_is_valid(skel)
    # cable runs along z: length in nm ~ 19 * 40
    assert skel.cable_length() >= 15 * 40
    # nodes are in nm: y/x coordinates sit inside [16, 32) nm
    assert skel.nodes[:, 1].max() < 8 * 4
    assert skel.nodes[:, 2].max() < 8 * 4


def test_skeletonize_disjoint_objects_and_threshold():
    seg = np.zeros((6, 30, 30), dtype=np.uint32)
    seg[:, 2:6, 2:28] = 1          # big tube
    seg[:, 20:24, 2:28] = 2        # second big tube
    seg[0, 28, 28] = 5             # dust: below threshold
    chunk = Segmentation(seg, voxel_size=(1, 1, 1))
    skels = skeletonize.execute(chunk, voxel_num_threshold=10)
    assert set(skels) == {1, 2}
    for skel in skels.values():
        assert _tree_is_valid(skel)
        assert skel.cable_length() > 20.0
