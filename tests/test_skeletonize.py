"""TEASAR-lite skeletonize plugin: topology correctness on synthetic shapes."""
import numpy as np

from chunkflow_tpu.chunk.segmentation import Segmentation
from chunkflow_tpu.plugins import skeletonize


def _tree_is_valid(skel):
    n = len(skel)
    roots = np.nonzero(skel.parents == -1)[0]
    assert len(roots) == 1
    for i in range(n):
        j, hops = i, 0
        while skel.parents[j] != -1:
            j = int(skel.parents[j])
            hops += 1
            assert hops <= n
    return True


def test_skeletonize_branching_object_topology():
    # T-shaped tube: horizontal bar + vertical stem in one z-plane slab
    seg = np.zeros((3, 40, 40), dtype=np.uint32)
    seg[:, 18:22, 4:36] = 1          # bar along x
    seg[:, 4:30, 18:22] = 1          # stem along y, crossing the bar
    chunk = Segmentation(seg, voxel_size=(1, 1, 1))
    skels = skeletonize.execute(chunk, voxel_num_threshold=10)
    assert 1 in skels
    skel = skels[1]
    assert _tree_is_valid(skel)
    # no spurious giant edge: every edge should be short (neighbors in a
    # 26-connected voxel grid are <= sqrt(3) apart; allow path joins a bit
    # more slack)
    edges = skel.edges
    lengths = np.linalg.norm(
        skel.nodes[edges[:, 0]] - skel.nodes[edges[:, 1]], axis=1
    )
    assert lengths.max() <= 2.0, (
        f"misattached branch: edge of length {lengths.max()}"
    )
    # the skeleton should span all three arms of the T: total cable length
    # must be a reasonable fraction of bar+stem extents (32 + 26)
    assert skel.cable_length() > 35.0
