import numpy as np
import pytest

from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian


def test_construction_and_fields():
    c = Cartesian(1, 2, 3)
    assert c.z == 1 and c.y == 2 and c.x == 3
    assert tuple(c) == (1, 2, 3)
    assert Cartesian.from_collection([4, 5, 6]) == Cartesian(4, 5, 6)
    assert Cartesian.from_collection(np.array([4, 5, 6])) == Cartesian(4, 5, 6)


def test_arithmetic_with_scalar():
    c = Cartesian(2, 4, 6)
    assert c + 1 == Cartesian(3, 5, 7)
    assert c - 1 == Cartesian(1, 3, 5)
    assert c * 2 == Cartesian(4, 8, 12)
    assert c // 2 == Cartesian(1, 2, 3)
    assert c / 2 == Cartesian(1.0, 2.0, 3.0)
    assert c % 4 == Cartesian(2, 0, 2)
    assert 1 + c == Cartesian(3, 5, 7)
    assert 10 - c == Cartesian(8, 6, 4)


def test_arithmetic_with_triple():
    a = Cartesian(1, 2, 3)
    b = Cartesian(10, 20, 30)
    assert a + b == Cartesian(11, 22, 33)
    assert b - a == Cartesian(9, 18, 27)
    assert a * b == Cartesian(10, 40, 90)
    assert b // a == Cartesian(10, 10, 10)
    assert a + (1, 1, 1) == Cartesian(2, 3, 4)


def test_negation_and_inverse():
    c = Cartesian(1, 2, 4)
    assert -c == Cartesian(-1, -2, -4)
    assert ~c == Cartesian(1.0, 0.5, 0.25)


def test_comparisons_are_elementwise_all():
    assert Cartesian(1, 1, 1) < Cartesian(2, 2, 2)
    assert not (Cartesian(1, 3, 1) < Cartesian(2, 2, 2))
    assert Cartesian(2, 2, 2) <= Cartesian(2, 2, 2)
    assert Cartesian(3, 3, 3) > Cartesian(2, 2, 2)
    assert Cartesian(1, 2, 3) == Cartesian(1, 2, 3)
    assert Cartesian(1, 2, 3) != Cartesian(1, 2, 4)


def test_rounding_and_ceildiv():
    c = Cartesian(1.2, 2.5, 3.9)
    assert c.ceil() == Cartesian(2, 3, 4)
    assert c.floor() == Cartesian(1, 2, 3)
    assert Cartesian(10, 11, 12).ceildiv(4) == Cartesian(3, 3, 3)
    assert Cartesian(8, 8, 8).ceildiv(4) == Cartesian(2, 2, 2)


def test_min_max_prod():
    a = Cartesian(1, 5, 3)
    b = Cartesian(2, 4, 3)
    assert a.maximum(b) == Cartesian(2, 5, 3)
    assert a.minimum(b) == Cartesian(1, 4, 3)
    assert a.prod() == 15
    assert a.all_positive()
    assert not Cartesian(0, 1, 1).all_positive()


def test_numpy_bridge():
    c = Cartesian(1, 2, 3)
    np.testing.assert_array_equal(c.vec, np.array([1, 2, 3]))
    # NamedTuple indexes like a sequence
    assert c[0] == 1


def test_to_cartesian():
    assert to_cartesian(None) is None
    assert to_cartesian((1, 2, 3)) == Cartesian(1, 2, 3)
    c = Cartesian(1, 2, 3)
    assert to_cartesian(c) is c
    with pytest.raises(ValueError):
        Cartesian.from_collection([1, 2])
