import numpy as np
import pytest

from chunkflow_tpu.core.bbox import (
    BoundingBox,
    BoundingBoxes,
    PhysicalBoundingBox,
)
from chunkflow_tpu.core.cartesian import Cartesian


def test_basic_properties():
    b = BoundingBox((0, 0, 0), (4, 8, 16))
    assert b.shape == Cartesian(4, 8, 16)
    assert b.voxel_count == 4 * 8 * 16
    assert b.is_valid()
    assert b.slices == (slice(0, 4), slice(0, 8), slice(0, 16))
    assert b.string == "0-4_0-8_0-16"


def test_string_roundtrip():
    b = BoundingBox((16384, 86294, 121142), (16492, 88342, 123190))
    assert BoundingBox.from_string(b.string) == b
    # log-file style with channel prefix and extension
    parsed = BoundingBox.from_string(
        "0-3_16384-16492_86294-88342_121142-123190.json"
    )
    assert parsed == b
    with pytest.raises(ValueError):
        BoundingBox.from_string("nonsense")


def test_from_delta_and_slices():
    b = BoundingBox.from_delta((1, 2, 3), (10, 10, 10))
    assert b.stop == Cartesian(11, 12, 13)
    assert BoundingBox.from_slices(b.slices) == b


def test_union_intersection_contains():
    a = BoundingBox((0, 0, 0), (10, 10, 10))
    b = BoundingBox((5, 5, 5), (15, 15, 15))
    assert a.union(b) == BoundingBox((0, 0, 0), (15, 15, 15))
    assert a.intersection(b) == BoundingBox((5, 5, 5), (10, 10, 10))
    assert a.overlaps(b)
    assert not a.overlaps(BoundingBox((20, 20, 20), (30, 30, 30)))
    assert a.contains(BoundingBox((1, 1, 1), (9, 9, 9)))
    assert not a.contains(b)
    assert a.contains_point((9, 9, 9))
    assert not a.contains_point((10, 0, 0))


def test_adjust_and_translate():
    b = BoundingBox((10, 10, 10), (20, 20, 20))
    grown = b.adjust(2)
    assert grown == BoundingBox((8, 8, 8), (22, 22, 22))
    assert grown.adjust((-2, -2, -2)) == b
    assert b.translate((1, 2, 3)) == BoundingBox((11, 12, 13), (21, 22, 23))


def test_alignment():
    b = BoundingBox((0, 64, 128), (64, 128, 192))
    assert b.is_aligned_with((64, 64, 64))
    assert not b.is_aligned_with((64, 64, 60))
    unaligned = BoundingBox((1, 65, 127), (63, 130, 200))
    snapped = unaligned.snap_to_blocks((64, 64, 64), outward=True)
    assert snapped == BoundingBox((0, 64, 64), (64, 192, 256))
    assert snapped.is_aligned_with((64, 64, 64))


def test_decompose():
    b = BoundingBox((0, 0, 0), (4, 4, 8))
    blocks = b.decompose((2, 4, 4))
    assert len(blocks) == 4
    # blocks tile the box exactly
    assert sum(blk.voxel_count for blk in blocks) == b.voxel_count
    union = blocks[0]
    for blk in blocks[1:]:
        union = union.union(blk)
    assert union == b
    with pytest.raises(ValueError):
        b.decompose((3, 3, 3))


def test_array_roundtrip():
    b = BoundingBox((1, 2, 3), (4, 5, 6))
    assert BoundingBox.from_array(b.to_array()) == b


class TestBoundingBoxes:
    def test_grid_no_overlap(self):
        bboxes = BoundingBoxes.from_manual_setup(
            chunk_size=(4, 4, 4), roi_start=(0, 0, 0), roi_stop=(8, 8, 8)
        )
        assert len(bboxes) == 8
        assert bboxes.grid_size == Cartesian(2, 2, 2)
        starts = {b.start for b in bboxes}
        assert Cartesian(0, 0, 0) in starts and Cartesian(4, 4, 4) in starts

    def test_grid_with_overlap(self):
        bboxes = BoundingBoxes.from_manual_setup(
            chunk_size=(4, 4, 4),
            overlap=(2, 2, 2),
            roi_start=(0, 0, 0),
            roi_stop=(8, 8, 8),
        )
        # stride 2: need ceil((8-2)/2)=3 per axis
        assert bboxes.grid_size == Cartesian(3, 3, 3)
        assert len(bboxes) == 27
        # chunks cover the ROI
        union = bboxes[0]
        for b in bboxes:
            union = union.union(b)
        assert union.contains(BoundingBox((0, 0, 0), (8, 8, 8)))

    def test_grid_size_override(self):
        bboxes = BoundingBoxes.from_manual_setup(
            chunk_size=(4, 4, 4), grid_size=(1, 2, 3), roi_start=(0, 0, 0)
        )
        assert len(bboxes) == 6

    def test_bounded_clamps_to_roi(self):
        bboxes = BoundingBoxes.from_manual_setup(
            chunk_size=(4, 4, 4),
            roi_start=(0, 0, 0),
            roi_stop=(6, 6, 6),
            bounded=True,
        )
        roi = BoundingBox((0, 0, 0), (6, 6, 6))
        for b in bboxes:
            assert roi.contains(b)

    def test_aligned_block_size_snaps_roi(self):
        bboxes = BoundingBoxes.from_manual_setup(
            chunk_size=(4, 4, 4),
            roi_start=(1, 1, 1),
            roi_stop=(7, 7, 7),
            aligned_block_size=(4, 4, 4),
        )
        assert bboxes.roi == BoundingBox((0, 0, 0), (8, 8, 8))

    def test_file_roundtrip(self, tmp_path):
        bboxes = BoundingBoxes.from_manual_setup(
            chunk_size=(4, 4, 4), roi_start=(0, 0, 0), roi_stop=(8, 8, 8)
        )
        npy = tmp_path / "tasks.npy"
        txt = tmp_path / "tasks.txt"
        bboxes.to_file(str(npy))
        bboxes.to_file(str(txt))
        assert BoundingBoxes.from_file(str(npy)) == bboxes
        assert BoundingBoxes.from_file(str(txt)) == bboxes


def test_physical_bbox_rescale():
    pb = PhysicalBoundingBox((0, 0, 0), (8, 8, 8), voxel_size=(40, 4, 4))
    # downsample xy by 2 -> coords halve in xy
    other = pb.to_voxel_size((40, 8, 8))
    assert other.start == Cartesian(0, 0, 0)
    assert other.stop == Cartesian(8, 4, 4)
    assert pb.physical_stop == Cartesian(320, 32, 32)


def test_reference_geometry_surface():
    """Drop-in reference spellings (cartesian_coordinate.py:236-724)."""
    import numpy as np

    from chunkflow_tpu.core.bbox import BoundingBox, PhysicalBoundingBox
    from chunkflow_tpu.core.cartesian import Cartesian

    b = BoundingBox(Cartesian(0, 4, 8), Cartesian(8, 12, 24))
    assert b.minpt == b.start and b.maxpt == b.stop
    assert BoundingBox.from_list([0, 4, 8, 8, 12, 24]) == b
    pts = np.array([[0, 4, 8], [7, 11, 23]])
    assert BoundingBox.from_points(pts) == b
    c = b.random_coordinate
    assert b.contains(c)
    assert b.inverse_order() == BoundingBox(Cartesian(8, 4, 0), Cartesian(24, 12, 8))
    assert b.adjust_corner((1, 1, 1, -1, -1, -1)) == BoundingBox(
        Cartesian(1, 5, 9), Cartesian(7, 11, 23)
    )
    nz, ny, nx = b.left_neighbors
    assert nz == BoundingBox(Cartesian(-8, 4, 8), Cartesian(0, 12, 24))
    assert nx.shape == b.shape

    blocks = b.decompose_to_aligned_block_bounding_boxes((8, 8, 8))
    assert len(blocks) == 1 * 1 * 2 and all(
        tuple(bb.shape) == (8, 8, 8) for bb in blocks
    )
    # unbounded: grid extends past stop when not divisible (the
    # reference formula ranges to stop+block-1 per axis, over-generating
    # exactly like this)
    b2 = BoundingBox(Cartesian(0, 0, 0), Cartesian(8, 8, 20))
    over = b2.decompose_to_aligned_block_bounding_boxes((8, 8, 8), bounded=False)
    assert len(over) == 2 * 2 * 4
    assert max(bb.stop.x for bb in over) >= 20  # covers the stop corner
    clipped = b2.decompose_to_unaligned_block_bounding_boxes((8, 8, 8))
    assert clipped[-1].stop.x == 20  # trailing block clipped

    p = PhysicalBoundingBox(Cartesian(0, 0, 0), Cartesian(8, 16, 16),
                            voxel_size=(40, 4, 4))
    assert p.to_other_voxel_size((40, 8, 8)).stop == Cartesian(8, 8, 8)
    assert p.voxel_bounding_box == BoundingBox(Cartesian(0, 0, 0),
                                               Cartesian(8, 16, 16))
    assert Cartesian(1, 2, 3).inverse == Cartesian(3, 2, 1)
