"""RegionOfInterest + ROITree (reference lib/region_of_interest.py; the
reference's from_roi is an empty prototype — ours must actually decompose)."""
import numpy as np

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.roi import RegionOfInterest, ROITree


def test_roi_physical_size_and_scale_slices():
    roi = RegionOfInterest((0, 0, 0), (4, 8, 8), voxel_size=(40, 4, 4))
    assert tuple(roi.physical_size) == (160, 32, 32)
    slices = roi.slices_in_scale((40, 8, 8))
    assert slices == (slice(0, 4), slice(0, 4), slice(0, 4))


def test_roi_from_bbox_clone():
    roi = RegionOfInterest.from_bbox(
        BoundingBox((1, 2, 3), (4, 5, 6)), (40, 4, 4)
    )
    other = roi.clone()
    assert other == roi or (
        tuple(other.start) == (1, 2, 3) and tuple(other.voxel_size) == (40, 4, 4)
    )


def test_roitree_decomposes_to_atomic_blocks():
    roi = RegionOfInterest((0, 0, 0), (4, 64, 96), voxel_size=(40, 4, 4))
    tree = ROITree.from_roi(roi, (4, 32, 32))
    leaves = list(tree.leaves())
    assert len(tree) == len(leaves) == 2 * 3
    # leaves tile the roi exactly
    total = sum(int(np.prod(tuple(l.shape))) for l in leaves)
    assert total == 4 * 64 * 96
    for leaf in leaves:
        assert all(s <= b for s, b in zip(leaf.shape, (4, 32, 32)))


def test_roitree_unaligned_roi():
    roi = RegionOfInterest((0, 0, 0), (4, 40, 40), voxel_size=(1, 1, 1))
    tree = ROITree.from_roi(roi, (4, 32, 32))
    leaves = list(tree.leaves())
    total = sum(int(np.prod(tuple(l.shape))) for l in leaves)
    assert total == 4 * 40 * 40
