"""core/telemetry.py: registry, spans, JSONL sink, kill switch, summary."""
import json
import os
import threading

import pytest

from chunkflow_tpu.core import telemetry


@pytest.fixture(autouse=True)
def clean_registry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def test_counters_gauges_histograms():
    telemetry.inc("a/count")
    telemetry.inc("a/count", 2)
    telemetry.gauge("a/level", 3)
    telemetry.gauge("a/level", 1)
    telemetry.observe("a/dur", 0.5)
    telemetry.observe("a/dur", 1.5)
    snap = telemetry.snapshot()
    assert snap["counters"]["a/count"] == 3
    assert snap["gauges"]["a/level"] == 1  # last value
    h = snap["hists"]["a/dur"]
    assert h["count"] == 2
    assert h["total"] == pytest.approx(2.0)
    assert h["mean"] == pytest.approx(1.0)
    assert h["min"] == 0.5 and h["max"] == 1.5
    # gauges also fold into a histogram so mean occupancy is queryable
    assert snap["hists"]["a/level"]["mean"] == pytest.approx(2.0)


def test_span_records_duration_and_exposes_it():
    with telemetry.span("phase/x") as sp:
        pass
    assert sp.duration >= 0
    snap = telemetry.snapshot()
    assert snap["hists"]["phase/x"]["count"] == 1


def test_span_survives_exceptions():
    with pytest.raises(ValueError):
        with telemetry.span("phase/err"):
            raise ValueError("boom")
    assert telemetry.snapshot()["hists"]["phase/err"]["count"] == 1


def test_jsonl_emission_and_snapshot_event(tmp_path):
    path = telemetry.configure(str(tmp_path))
    assert path is not None and str(tmp_path) in path
    with telemetry.span("pipeline/stage", chunk=3):
        pass
    telemetry.gauge("pipeline/ring_occupancy", 2)
    telemetry.inc("compile_cache/builds")
    telemetry.flush()
    events = [
        json.loads(line)
        for line in open(path).read().splitlines() if line
    ]
    kinds = [e["kind"] for e in events]
    assert kinds == ["span", "gauge", "snapshot"]
    span_event = events[0]
    assert span_event["name"] == "pipeline/stage"
    assert span_event["chunk"] == 3  # attrs ride the event
    assert span_event["dur_s"] >= 0
    assert events[2]["counters"]["compile_cache/builds"] == 1


def test_kill_switch_emits_nothing_and_creates_nothing(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    target = tmp_path / "metrics"
    assert telemetry.configure(str(target)) is None
    assert not target.exists()  # an off run leaves no trace on disk
    telemetry.inc("x")
    telemetry.gauge("g", 1)
    telemetry.observe("h", 1)
    with telemetry.span("s"):
        pass
    telemetry.event("custom", "e")
    telemetry.flush()
    snap = telemetry.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "hists": {}}
    assert telemetry.summary_table() == ""


def test_kill_switch_mid_run(tmp_path, monkeypatch):
    path = telemetry.configure(str(tmp_path))
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    with telemetry.span("late"):
        pass
    telemetry.flush()
    # sink was open, but disabled spans never reach it
    assert open(path).read() == ""


def test_disabled_span_is_cheap():
    # the whole point of the kill switch: ~free when off. 100k no-op
    # spans in well under a second leaves 10x margin on a loaded CI box.
    import time as _time

    os.environ["CHUNKFLOW_TELEMETRY"] = "0"
    try:
        t0 = _time.perf_counter()
        for _ in range(100_000):
            with telemetry.span("x"):
                pass
        assert _time.perf_counter() - t0 < 1.0
    finally:
        del os.environ["CHUNKFLOW_TELEMETRY"]


def test_thread_safety_smoke(tmp_path):
    telemetry.configure(str(tmp_path))

    def work():
        for _ in range(500):
            telemetry.inc("t/count")
            with telemetry.span("t/span"):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = telemetry.snapshot()
    assert snap["counters"]["t/count"] == 2000
    assert snap["hists"]["t/span"]["count"] == 2000


def test_summary_table_lists_everything():
    with telemetry.span("op/inference"):
        pass
    telemetry.inc("pipeline/tasks", 4)
    telemetry.gauge("pipeline/ring_occupancy", 2)
    table = telemetry.summary_table()
    assert "op/inference" in table
    assert "pipeline/tasks" in table
    assert "pipeline/ring_occupancy" in table


def test_configure_reconfigure_closes_previous(tmp_path):
    first = telemetry.configure(str(tmp_path / "a"))
    second = telemetry.configure(str(tmp_path / "b"))
    assert first != second
    assert telemetry.configured_path() == second
    with telemetry.span("x"):
        pass
    telemetry.flush()
    assert open(first).read() == ""
    assert "span" in open(second).read()
