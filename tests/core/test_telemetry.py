"""core/telemetry.py: registry, spans, JSONL sink, kill switch, summary."""
import json
import os
import threading

import pytest

from chunkflow_tpu.core import telemetry


@pytest.fixture(autouse=True)
def clean_registry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def test_counters_gauges_histograms():
    telemetry.inc("a/count")
    telemetry.inc("a/count", 2)
    telemetry.gauge("a/level", 3)
    telemetry.gauge("a/level", 1)
    telemetry.observe("a/dur", 0.5)
    telemetry.observe("a/dur", 1.5)
    snap = telemetry.snapshot()
    assert snap["counters"]["a/count"] == 3
    assert snap["gauges"]["a/level"] == 1  # last value
    h = snap["hists"]["a/dur"]
    assert h["count"] == 2
    assert h["total"] == pytest.approx(2.0)
    assert h["mean"] == pytest.approx(1.0)
    assert h["min"] == 0.5 and h["max"] == 1.5
    # gauges also fold into a histogram so mean occupancy is queryable
    assert snap["hists"]["a/level"]["mean"] == pytest.approx(2.0)


def test_span_records_duration_and_exposes_it():
    with telemetry.span("phase/x") as sp:
        pass
    assert sp.duration >= 0
    snap = telemetry.snapshot()
    assert snap["hists"]["phase/x"]["count"] == 1


def test_span_survives_exceptions():
    with pytest.raises(ValueError):
        with telemetry.span("phase/err"):
            raise ValueError("boom")
    assert telemetry.snapshot()["hists"]["phase/err"]["count"] == 1


def test_jsonl_emission_and_snapshot_event(tmp_path):
    path = telemetry.configure(str(tmp_path))
    assert path is not None and str(tmp_path) in path
    with telemetry.span("pipeline/stage", chunk=3):
        pass
    telemetry.gauge("pipeline/ring_occupancy", 2)
    telemetry.inc("compile_cache/builds")
    telemetry.flush()
    events = [
        json.loads(line)
        for line in open(path).read().splitlines() if line
    ]
    kinds = [e["kind"] for e in events]
    assert kinds == ["span", "gauge", "snapshot"]
    span_event = events[0]
    assert span_event["name"] == "pipeline/stage"
    assert span_event["chunk"] == 3  # attrs ride the event
    assert span_event["dur_s"] >= 0
    assert events[2]["counters"]["compile_cache/builds"] == 1


def test_kill_switch_emits_nothing_and_creates_nothing(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    target = tmp_path / "metrics"
    assert telemetry.configure(str(target)) is None
    assert not target.exists()  # an off run leaves no trace on disk
    telemetry.inc("x")
    telemetry.gauge("g", 1)
    telemetry.observe("h", 1)
    with telemetry.span("s"):
        pass
    telemetry.event("custom", "e")
    telemetry.flush()
    snap = telemetry.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "hists": {}}
    assert telemetry.summary_table() == ""


def test_kill_switch_mid_run(tmp_path, monkeypatch):
    path = telemetry.configure(str(tmp_path))
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    with telemetry.span("late"):
        pass
    telemetry.flush()
    # sink was open, but disabled spans never reach it
    assert open(path).read() == ""


def test_disabled_span_is_cheap():
    # the whole point of the kill switch: ~free when off. 100k no-op
    # spans in well under a second leaves 10x margin on a loaded CI box.
    import time as _time

    os.environ["CHUNKFLOW_TELEMETRY"] = "0"
    try:
        t0 = _time.perf_counter()
        for _ in range(100_000):
            with telemetry.span("x"):
                pass
        assert _time.perf_counter() - t0 < 1.0
    finally:
        del os.environ["CHUNKFLOW_TELEMETRY"]


def test_thread_safety_smoke(tmp_path):
    telemetry.configure(str(tmp_path))

    def work():
        for _ in range(500):
            telemetry.inc("t/count")
            with telemetry.span("t/span"):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = telemetry.snapshot()
    assert snap["counters"]["t/count"] == 2000
    assert snap["hists"]["t/span"]["count"] == 2000


def test_summary_table_lists_everything():
    with telemetry.span("op/inference"):
        pass
    telemetry.inc("pipeline/tasks", 4)
    telemetry.gauge("pipeline/ring_occupancy", 2)
    table = telemetry.summary_table()
    assert "op/inference" in table
    assert "pipeline/tasks" in table
    assert "pipeline/ring_occupancy" in table


def test_configure_reconfigure_closes_previous(tmp_path):
    first = telemetry.configure(str(tmp_path / "a"))
    second = telemetry.configure(str(tmp_path / "b"))
    assert first != second
    assert telemetry.configured_path() == second
    with telemetry.span("x"):
        pass
    telemetry.flush()
    assert open(first).read() == ""
    assert "span" in open(second).read()


# ---------------------------------------------------------------------------
# fleet identity + task trace context (ISSUE 6)
# ---------------------------------------------------------------------------
def test_worker_id_stable_and_overridable(monkeypatch):
    first = telemetry.worker_id()
    assert str(os.getpid()) in first
    assert telemetry.worker_id() == first  # cached, stable within a run
    monkeypatch.setenv("CHUNKFLOW_WORKER_ID", "fleet-worker-7")
    assert telemetry.worker_id() == first  # env read only at first use...
    telemetry.reset()
    assert telemetry.worker_id() == "fleet-worker-7"  # ...or after reset


def test_sink_file_named_by_worker_id(tmp_path, monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_WORKER_ID", "worker a/b")
    telemetry.reset()
    path = telemetry.configure(str(tmp_path))
    # unsafe characters sanitized, telemetry-*.jsonl contract preserved
    assert os.path.basename(path) == "telemetry-worker_a_b.jsonl"


def test_events_stamped_with_worker_and_trace(tmp_path):
    path = telemetry.configure(str(tmp_path))
    with telemetry.task_context("trace-123"):
        with telemetry.span("op/x"):
            pass
        telemetry.gauge("g", 1)
        telemetry.event("task", "lifecycle/claimed", body="b")
    with telemetry.span("op/outside"):
        pass
    telemetry.flush()
    events = [json.loads(line) for line in open(path) if line.strip()]
    inside = [e for e in events if e.get("trace_id") == "trace-123"]
    assert {e["kind"] for e in inside} == {"span", "gauge", "task"}
    for e in events:
        assert e["worker"] == telemetry.worker_id()
    outside = next(e for e in events if e.get("name") == "op/outside")
    assert "trace_id" not in outside  # context did not leak past exit
    snap_event = next(e for e in events if e["kind"] == "snapshot")
    assert snap_event["worker"] == telemetry.worker_id()


def test_task_context_nesting_and_none(tmp_path):
    assert telemetry.current_trace_id() is None
    with telemetry.task_context("outer"):
        assert telemetry.current_trace_id() == "outer"
        with telemetry.task_context(None):  # no-op: keeps the outer id
            assert telemetry.current_trace_id() == "outer"
        with telemetry.task_context("inner"):
            assert telemetry.current_trace_id() == "inner"
        assert telemetry.current_trace_id() == "outer"
    assert telemetry.current_trace_id() is None


def test_task_context_is_thread_local(tmp_path):
    seen = {}

    def work(tid):
        with telemetry.task_context(tid):
            import time as _time

            _time.sleep(0.01)
            seen[tid] = telemetry.current_trace_id()

    threads = [
        threading.Thread(target=work, args=(f"t{i}",)) for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen == {f"t{i}": f"t{i}" for i in range(4)}


# ---------------------------------------------------------------------------
# JSONL rotation (ISSUE 6: long-lived workers must not grow unbounded)
# ---------------------------------------------------------------------------
def test_jsonl_rotation_caps_size(tmp_path, monkeypatch):
    # ~1 KB cap: a few hundred spans must rotate at least once
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_MAX_MB", "0.001")
    path = telemetry.configure(str(tmp_path))
    for _ in range(200):
        with telemetry.span("op/rotate"):
            pass
    telemetry.flush()
    rotated = path + ".1"
    assert os.path.exists(rotated)
    assert os.path.getsize(path) <= 4096  # live file stays near the cap
    # at most two generations on disk, both valid JSONL
    files = sorted(os.listdir(tmp_path))
    assert files == [os.path.basename(path), os.path.basename(rotated)]
    for name in files:
        for line in open(tmp_path / name):
            json.loads(line)


def test_rotation_off_without_sink_and_when_disabled(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_MAX_MB", "0.001")
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    assert telemetry.configure(str(tmp_path / "off")) is None
    for _ in range(200):
        with telemetry.span("op/none"):
            pass
    telemetry.flush()
    # kill switch: no files at all, rotated or otherwise
    assert not (tmp_path / "off").exists()


# ---------------------------------------------------------------------------
# quantile histograms (the serving p50/p99 substrate, ISSUE 9)
# ---------------------------------------------------------------------------
def test_quantile_histogram_estimates_and_snapshot_schema():
    telemetry.reset()
    try:
        for v in [0.004] * 50 + [0.02] * 40 + [0.8] * 10:
            telemetry.observe_quantile("serving/latency", v)
        p50 = telemetry.quantile("serving/latency", 0.5)
        p99 = telemetry.quantile("serving/latency", 0.99)
        # 50th sample sits in the (0.0025, 0.005] bucket, 99th in
        # (0.5, 1.0] — the log-bucket estimate must land inside them
        assert 0.0025 <= p50 <= 0.005, p50
        assert 0.5 <= p99 <= 1.0, p99
        snap = telemetry.snapshot()
        h = snap["qhists"]["serving/latency"]
        assert h["count"] == 100
        assert len(h["buckets"]) == len(telemetry.QUANTILE_BOUNDS) + 1
        assert sum(h["buckets"]) == 100
        # fixed bounds mean per-worker buckets sum exactly: merging two
        # copies doubles every estimate's weight but moves no quantile
        merged = {"count": 2 * h["count"],
                  "buckets": [2 * n for n in h["buckets"]]}
        assert telemetry.quantile_from_buckets(merged, 0.5) == \
            pytest.approx(p50)
    finally:
        telemetry.reset()


def test_quantile_histogram_edge_cases():
    telemetry.reset()
    try:
        assert telemetry.quantile("missing", 0.5) is None
        assert telemetry.quantile_from_buckets(
            {"count": 0, "buckets": []}, 0.5) is None
        # an overflow-only histogram saturates at the top bound
        telemetry.observe_quantile("serving/huge", 9999.0)
        assert telemetry.quantile("serving/huge", 0.5) == \
            telemetry.QUANTILE_BOUNDS[-1]
    finally:
        telemetry.reset()


def test_quantile_histogram_respects_kill_switch(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    telemetry.observe_quantile("serving/latency", 0.1)
    assert telemetry.quantile("serving/latency", 0.5) is None
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY")
    telemetry.reset()
    assert "qhists" not in telemetry.snapshot()


# ---------------------------------------------------------------------------
# quantile_from_buckets edge cases (ISSUE 12: it feeds alerting now)
# ---------------------------------------------------------------------------
def test_quantile_from_buckets_empty_and_none_shapes():
    qfb = telemetry.quantile_from_buckets
    assert qfb({}, 0.5) is None                      # empty dict
    assert qfb({"count": 0, "buckets": []}, 0.5) is None
    assert qfb({"count": 5, "buckets": None}, 0.5) is None  # None buckets
    # count claims samples but the bucket list is empty: no estimate,
    # not an IndexError — a torn snapshot must not crash alerting
    assert qfb({"count": 5, "buckets": []}, 0.99) is None


def test_quantile_from_buckets_q0_and_q1():
    buckets = [0] * (len(telemetry.QUANTILE_BOUNDS) + 1)
    buckets[3] = 10  # all samples in (0.005, 0.01]
    h = {"count": 10, "buckets": buckets}
    q0 = telemetry.quantile_from_buckets(h, 0.0)
    q1 = telemetry.quantile_from_buckets(h, 1.0)
    # both land inside the one occupied bucket, ordered
    assert 0.005 <= q0 <= 0.01
    assert 0.005 <= q1 <= 0.01
    assert q0 <= q1
    assert q1 == pytest.approx(0.01)  # q=1 is the bucket's upper bound


def test_quantile_from_buckets_single_bucket_and_overflow_only():
    bounds = telemetry.QUANTILE_BOUNDS
    single = [0] * (len(bounds) + 1)
    single[0] = 7  # everything under the first bound
    h = {"count": 7, "buckets": single}
    for q in (0.01, 0.5, 0.99):
        est = telemetry.quantile_from_buckets(h, q)
        assert 0.0 <= est <= bounds[0]
    overflow = [0] * (len(bounds) + 1)
    overflow[-1] = 3  # only samples past the largest tracked bound
    h = {"count": 3, "buckets": overflow}
    # the estimate saturates at the largest bound instead of inventing
    # a number past the tracked range
    assert telemetry.quantile_from_buckets(h, 0.5) == bounds[-1]


def test_quantile_from_buckets_short_bucket_list():
    # a stream from an older schema may carry fewer buckets than
    # bounds: the reader pads conceptually, never IndexErrors
    h = {"count": 4, "buckets": [4]}
    est = telemetry.quantile_from_buckets(h, 0.5)
    assert 0.0 <= est <= telemetry.QUANTILE_BOUNDS[0]


# ---------------------------------------------------------------------------
# rotation generations (ISSUE 12: CHUNKFLOW_TELEMETRY_KEEP)
# ---------------------------------------------------------------------------
def _spam_spans(n):
    for _ in range(n):
        with telemetry.span("op/rotate"):
            pass


def test_rotation_keeps_configured_generations(tmp_path, monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_MAX_MB", "0.001")
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_KEEP", "3")
    path = telemetry.configure(str(tmp_path))
    _spam_spans(800)
    telemetry.flush()
    base = os.path.basename(path)
    files = sorted(os.listdir(tmp_path))
    assert files == [base, f"{base}.1", f"{base}.2"]
    for name in files:  # every generation is valid JSONL
        for line in open(tmp_path / name):
            json.loads(line)


def test_rotation_sweeps_stale_generations_when_keep_drops(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_MAX_MB", "0.001")
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_KEEP", "4")
    path = telemetry.configure(str(tmp_path))
    _spam_spans(1200)
    base = os.path.basename(path)
    assert f"{base}.3" in os.listdir(tmp_path)
    # KEEP lowered on a live worker: the next rotation sweeps the tail
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_KEEP", "2")
    _spam_spans(400)
    files = sorted(os.listdir(tmp_path))
    assert files == [base, f"{base}.1"]


def test_load_telemetry_dir_reads_all_generations_in_order(
    tmp_path, monkeypatch
):
    from chunkflow_tpu.flow.log_summary import load_telemetry_dir

    monkeypatch.setenv("CHUNKFLOW_WORKER_ID", "w-rot")
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_MAX_MB", "0.001")
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY_KEEP", "3")
    telemetry.configure(str(tmp_path))
    for i in range(900):
        telemetry.event("probe", "order/check", seq=i)
    telemetry.flush()
    assert len([n for n in os.listdir(tmp_path)
                if ".jsonl" in n]) == 3  # live + .1 + .2
    events = load_telemetry_dir(str(tmp_path))
    seqs = [e["seq"] for e in events if e.get("name") == "order/check"]
    # every surviving generation was read, oldest first: the tail of
    # the sequence is contiguous and spans more than the live file
    assert seqs == list(range(seqs[0], 900))
    assert len(seqs) > 12  # more events than one capped file holds


# ---------------------------------------------------------------------------
# time-series ring sampler (ISSUE 12)
# ---------------------------------------------------------------------------
def test_timeseries_sampler_rates_gauges_quantiles(tmp_path):
    path = telemetry.configure(str(tmp_path))
    sampler = telemetry.start_timeseries(interval=60.0)  # manual ticks
    assert telemetry.start_timeseries() is sampler  # idempotent
    telemetry.inc("serving/requests", 10)
    telemetry.gauge("serving/inflight", 3)
    telemetry.observe_quantile("serving/latency", 0.01)
    sampler.sample(now=1000.0)
    telemetry.inc("serving/requests", 20)
    sampler.sample(now=1002.0)
    series = telemetry.timeseries()
    # counter rate against the previous tick: 20 events / 2 s
    assert series["rate:serving/requests"][-1] == (1002.0, 10.0)
    assert series["gauge:serving/inflight"][-1][1] == 3.0
    assert 0.005 <= series["p50:serving/latency"][-1][1] <= 0.01
    telemetry.flush()
    events = [json.loads(line) for line in open(path)]
    ts = [e for e in events if e["kind"] == "timeseries"]
    assert len(ts) >= 2
    # the event carries raw cumulative buckets (fleet-summable)
    assert ts[-1]["qhists"]["serving/latency"]["count"] == 1
    assert ts[-1]["values"]["gauge:serving/inflight"] == 3.0


def test_timeseries_ring_is_bounded():
    sampler = telemetry.start_timeseries(interval=60.0, points=5)
    telemetry.inc("x/count")
    for i in range(20):
        sampler.sample(now=1000.0 + i)
    series = telemetry.timeseries()
    assert len(series["rate:x/count"]) == 5  # ring, not a log
    assert series["rate:x/count"][-1][0] == 1019.0


def test_timeseries_knobs_and_kill_switch(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_TS_INTERVAL", "0")
    assert telemetry.start_timeseries() is None  # interval 0: disabled
    monkeypatch.setenv("CHUNKFLOW_TS_INTERVAL", "2.5")
    monkeypatch.setenv("CHUNKFLOW_TS_POINTS", "77")
    assert telemetry.ts_interval() == 2.5
    assert telemetry.ts_points() == 77
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    assert telemetry.start_timeseries() is None
    assert not any(t.name == "chunkflow-timeseries"
                   for t in threading.enumerate())


def test_timeseries_tick_hooks_run_and_clear_on_reset():
    ticks = []
    sampler = telemetry.start_timeseries(interval=60.0)
    telemetry.add_tick_hook(ticks.append)
    telemetry.add_tick_hook(ticks.append)  # idempotent by identity
    telemetry.inc("x/count")
    sampler.sample(now=1000.0)
    assert ticks == [1000.0]

    def explode(now):
        raise RuntimeError("hook down")

    telemetry.add_tick_hook(explode)  # a raising hook never kills a tick
    sampler.sample(now=1001.0)
    assert ticks == [1000.0, 1001.0]
    telemetry.reset()
    assert not telemetry.timeseries_running()
    sampler2 = telemetry.start_timeseries(interval=60.0)
    telemetry.inc("x/count")
    sampler2.sample(now=2000.0)
    assert ticks == [1000.0, 1001.0]  # reset cleared the hooks


def test_flush_takes_a_final_sample(tmp_path):
    path = telemetry.configure(str(tmp_path))
    telemetry.start_timeseries(interval=3600.0)  # would never self-tick
    telemetry.inc("serving/requests", 4)
    telemetry.flush()
    events = [json.loads(line) for line in open(path)]
    assert any(e["kind"] == "timeseries" for e in events)
