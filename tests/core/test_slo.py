"""SLO plane units (ISSUE 12): burn-rate math on synthetic traffic,
edge-triggered alerting, config loading/overrides, page-alert profiler
capture through the cooldown, kill switches, and the time-series tick
wiring."""
import json
import os
import threading
import time

import pytest

from chunkflow_tpu.core import slo, telemetry


@pytest.fixture
def clean(monkeypatch):
    for var in ("CHUNKFLOW_TELEMETRY", "CHUNKFLOW_SLO",
                "CHUNKFLOW_TS_INTERVAL", "CHUNKFLOW_TS_POINTS"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    yield monkeypatch
    telemetry.reset()


class SyntheticTraffic:
    """A fake clock + registry source: tests drive time and counters by
    hand, so burn-rate math is asserted on exact numbers."""

    def __init__(self):
        self.t = 1000.0
        self.counters = {}
        self.qhists = {}

    def clock(self):
        return self.t

    def source(self):
        return {"counters": dict(self.counters),
                "qhists": {k: dict(v) for k, v in self.qhists.items()}}

    def advance(self, dt, **deltas):
        self.t += dt
        for name, n in deltas.items():
            key = name.replace("__", "/")
            self.counters[key] = self.counters.get(key, 0) + n


def make_evaluator(traffic, target=0.9, short_s=2.0, long_s=10.0,
                   burn=2.0, severity="page", period_s=120.0):
    obj = slo.Objective("availability", target=target,
                        total=("serving/requests",),
                        bad=("serving/errors",))
    rule = slo.BurnRule("fast", short_s=short_s, long_s=long_s,
                        burn=burn, severity=severity)
    return slo.SLOEvaluator(objectives=[obj], rules=[rule],
                            period_s=period_s, clock=traffic.clock,
                            source=traffic.source)


# ---------------------------------------------------------------------------
# burn-rate math + alert edges
# ---------------------------------------------------------------------------
def test_healthy_traffic_never_fires(clean):
    traffic = SyntheticTraffic()
    ev = make_evaluator(traffic)
    alerts = []
    for _ in range(30):
        traffic.advance(1.0, serving__requests=10)
        alerts += ev.tick()
    assert alerts == []
    assert ev.firing() == []
    status = ev.status()["objectives"][0]
    assert status["burn_rate"] == 0.0
    assert status["budget_remaining"] == 1.0


def test_regression_fires_exactly_once_with_attributes(clean):
    traffic = SyntheticTraffic()
    ev = make_evaluator(traffic, target=0.9, burn=2.0)
    for _ in range(15):
        traffic.advance(1.0, serving__requests=10)
        ev.tick()
    alerts = []
    # 50% errors: bad_frac 0.5 / budget 0.1 = burn 5 >= 2 on both
    # windows once the long window accumulates enough bad share
    for _ in range(10):
        traffic.advance(1.0, serving__requests=10, serving__errors=5)
        alerts += ev.tick()
    assert len(alerts) == 1  # edge-triggered: one event, not one/tick
    alert = alerts[0]
    assert alert["alert"] == "availability:fast"
    assert alert["severity"] == "page"
    assert alert["burn_short"] >= 2.0
    assert alert["burn_long"] >= 2.0
    assert alert["budget_remaining"] < 1.0
    assert ev.firing() == ["availability:fast"]
    # counters + the firing gauge reached the registry
    snap = telemetry.snapshot()
    assert snap["counters"]["slo/alerts"] == 1
    assert snap["gauges"]["slo/availability/firing"] == 1.0


def test_alert_resolves_and_rearms(clean):
    traffic = SyntheticTraffic()
    ev = make_evaluator(traffic, target=0.9, short_s=2.0, long_s=6.0,
                        burn=2.0)
    for _ in range(10):
        traffic.advance(1.0, serving__requests=10)
        ev.tick()
    fired = []
    for _ in range(6):
        traffic.advance(1.0, serving__requests=10, serving__errors=5)
        fired += ev.tick()
    assert len(fired) == 1
    # clean traffic drains the short window first, then the long one
    for _ in range(10):
        traffic.advance(1.0, serving__requests=10)
        ev.tick()
    assert ev.firing() == []
    snap = telemetry.snapshot()
    assert snap["counters"]["slo/alerts_resolved"] == 1
    assert snap["gauges"]["slo/availability/firing"] == 0.0
    # a NEW regression re-fires (the pair re-armed at resolve)
    again = []
    for _ in range(6):
        traffic.advance(1.0, serving__requests=10, serving__errors=5)
        again += ev.tick()
    assert len(again) == 1


def test_short_window_gates_stale_regressions(clean):
    """Multi-window contract: a burst that ended longer than short_s
    ago must NOT page, even while the long window still remembers it."""
    traffic = SyntheticTraffic()
    ev = make_evaluator(traffic, target=0.9, short_s=2.0, long_s=30.0,
                        burn=2.0)
    traffic.advance(1.0, serving__requests=10)
    ev.tick()
    # a 2-second error burst...
    fired = []
    for _ in range(2):
        traffic.advance(1.0, serving__requests=10, serving__errors=8)
        fired += ev.tick()
    assert fired  # burning NOW: pages
    # ...then 10 clean seconds: long window still sees the burst, the
    # short window does not -> resolved, and it stays resolved
    for _ in range(10):
        traffic.advance(1.0, serving__requests=10)
        ev.tick()
    assert ev.firing() == []
    status = ev.status()["objectives"][0]
    assert status["rules"][0]["burn_long"] > 0  # memory is still there


def test_no_traffic_burns_nothing(clean):
    traffic = SyntheticTraffic()
    ev = make_evaluator(traffic)
    for _ in range(20):
        traffic.advance(1.0)  # no requests at all
        assert ev.tick() == []
    assert ev.status()["objectives"][0]["budget_remaining"] == 1.0


def test_latency_objective_counts_buckets_above_threshold(clean):
    traffic = SyntheticTraffic()
    obj = slo.Objective("latency", target=0.9, kind="latency",
                        qhist="serving/latency", threshold_s=0.05)
    rule = slo.BurnRule("fast", short_s=2.0, long_s=6.0, burn=2.0)
    ev = slo.SLOEvaluator(objectives=[obj], rules=[rule], period_s=120.0,
                          clock=traffic.clock, source=traffic.source)

    def observe(n_fast, n_slow):
        h = traffic.qhists.setdefault("serving/latency", {
            "count": 0,
            "buckets": [0] * (len(telemetry.QUANTILE_BOUNDS) + 1),
        })
        h["count"] += n_fast + n_slow
        buckets = list(h["buckets"])
        buckets[3] += n_fast   # 0.01 s <= 0.05 threshold: good
        buckets[8] += n_slow   # 0.5 s  >  0.05 threshold: bad
        h["buckets"] = buckets

    for _ in range(5):
        traffic.advance(1.0)
        observe(10, 0)
        assert ev.tick() == []
    fired = []
    for _ in range(6):
        traffic.advance(1.0)
        observe(5, 5)  # half the requests blow the latency threshold
        fired += ev.tick()
    assert len(fired) == 1
    assert fired[0]["objective"] == "latency"


def test_page_alert_triggers_one_capture_cooldown_blocks_second(
    clean, tmp_path, monkeypatch
):
    """ISSUE 12 acceptance (capture half): the first page-severity
    alert triggers exactly one bounded profiler capture through the
    PR 8 cooldown machinery; a second alert inside the cooldown
    triggers none."""
    from chunkflow_tpu.core import profiling

    monkeypatch.setenv("CHUNKFLOW_PROFILE_ON_ANOMALY", "1")
    monkeypatch.setenv("CHUNKFLOW_PROFILE_SECONDS", "0.1")
    monkeypatch.setenv("CHUNKFLOW_PROFILE_COOLDOWN", "600")
    telemetry.configure(str(tmp_path))
    traffic = SyntheticTraffic()
    obj_a = slo.Objective("availability", target=0.9,
                          total=("serving/requests",),
                          bad=("serving/errors",))
    obj_b = slo.Objective("deadline", target=0.9,
                          total=("serving/requests",),
                          bad=("serving/deadline_missed",))
    rule = slo.BurnRule("fast", short_s=2.0, long_s=6.0, burn=2.0,
                        severity="page")
    ev = slo.SLOEvaluator(objectives=[obj_a, obj_b], rules=[rule],
                          period_s=120.0, clock=traffic.clock,
                          source=traffic.source)
    traffic.advance(1.0, serving__requests=10)
    ev.tick()
    # first regression: availability pages -> one capture
    for _ in range(4):
        traffic.advance(1.0, serving__requests=10, serving__errors=8)
        ev.tick()
    profiling.wait_for_captures()
    captures = sorted(p.name for p in tmp_path.iterdir()
                      if p.name.startswith("profile-slo-"))
    assert len(captures) == 1 and "availability" in captures[0]
    # second alert (different objective) inside the cooldown: no capture
    for _ in range(4):
        traffic.advance(1.0, serving__requests=10,
                        serving__deadline_missed=8)
        ev.tick()
    profiling.wait_for_captures()
    assert "deadline:fast" in ev.firing()
    captures = [p.name for p in tmp_path.iterdir()
                if p.name.startswith("profile-slo-")]
    assert len(captures) == 1
    assert telemetry.snapshot()["counters"].get("profile/captures") == 1


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------
SLO_TOML = """
period_s = 240
scale = 0.5
[objective.availability]
target = 0.95
[objective.storage_hit]
enabled = false
[objective.custom]
total = ["serving/requests"]
bad = ["serving/oom"]
target = 0.99
[rule.fast]
short_s = 4
long_s = 16
burn = 3.0
severity = "page"
[rule.slow]
enabled = false
"""


def test_minimal_toml_parser_shapes():
    parsed = slo._parse_toml_minimal(
        'a = 1\nb = 2.5\nc = "x"  # comment\nd = true\n'
        '[s.t]\ne = ["p", "q"]\n')
    assert parsed["a"] == 1 and parsed["b"] == 2.5 and parsed["c"] == "x"
    assert parsed["d"] is True
    assert parsed["s"]["t"]["e"] == ["p", "q"]
    with pytest.raises(ValueError):
        slo._parse_toml_minimal("not a key value line\n")


def test_config_file_overrides_defaults(clean, tmp_path):
    path = tmp_path / "slo.toml"
    path.write_text(SLO_TOML)
    config = slo.load_slo_config(str(path), pyproject="/nonexistent")
    ev = slo.evaluator_from_config(config)
    names = [o.name for o in ev.objectives]
    assert "storage_hit" not in names          # disabled
    assert "custom" in names                   # config-only objective
    avail = next(o for o in ev.objectives if o.name == "availability")
    assert avail.target == 0.95                # overridden
    latency = next(o for o in ev.objectives if o.name == "latency")
    assert latency.target == 0.99              # untouched default
    assert [r.name for r in ev.rules] == ["fast"]  # slow disabled
    fast = ev.rules[0]
    # scale=0.5 compresses windows AND the period
    assert fast.short_s == pytest.approx(2.0)
    assert fast.long_s == pytest.approx(8.0)
    assert ev.period_s == pytest.approx(120.0)


def test_pyproject_section_applies_and_file_wins(clean, tmp_path):
    pyproject = tmp_path / "pyproject.toml"
    pyproject.write_text(
        "[tool.chunkflow.slo]\nperiod_s = 100\n"
        "[tool.chunkflow.slo.objective.availability]\ntarget = 0.5\n")
    config = slo.load_slo_config(None, pyproject=str(pyproject))
    assert config["period_s"] == 100
    assert config["objective"]["availability"]["target"] == 0.5
    override = tmp_path / "slo.toml"
    override.write_text("[objective.availability]\ntarget = 0.75\n")
    config = slo.load_slo_config(str(override), pyproject=str(pyproject))
    assert config["period_s"] == 100              # pyproject survives
    assert config["objective"]["availability"]["target"] == 0.75


def test_malformed_config_raises(clean, tmp_path):
    bad = tmp_path / "bad.toml"
    bad.write_text("this is not toml at all\n")
    with pytest.raises(ValueError):
        slo.load_slo_config(str(bad), pyproject="/nonexistent")


def test_objective_validation():
    with pytest.raises(ValueError):
        slo.Objective("x", target=1.5)
    with pytest.raises(ValueError):
        slo.Objective("x", target=0.9, kind="latency")  # no qhist
    with pytest.raises(ValueError):
        slo.BurnRule("x", short_s=10.0, long_s=5.0, burn=1.0)


# ---------------------------------------------------------------------------
# lifecycle + kill switches
# ---------------------------------------------------------------------------
def test_start_slo_rides_the_timeseries_tick(clean, tmp_path, monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_TS_INTERVAL", "0.05")
    telemetry.configure(str(tmp_path))
    ev = slo.start_slo(pyproject="/nonexistent")
    assert ev is not None and slo.current() is ev
    assert slo.start_slo() is ev  # idempotent
    assert telemetry.timeseries_running()
    for _ in range(8):
        telemetry.inc("serving/requests")
        time.sleep(0.05)
    assert len(ev._samples) >= 2  # the sampler thread ticked it
    telemetry.reset()  # reset hook tears the evaluator down
    assert slo.current() is None
    assert not telemetry.timeseries_running()


def test_kill_switches_create_nothing(clean, monkeypatch, tmp_path):
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    assert not slo.slo_enabled()
    assert slo.start_slo() is None
    assert telemetry.start_timeseries() is None
    assert not any(t.name == "chunkflow-timeseries"
                   for t in threading.enumerate())
    assert telemetry.timeseries() == {}
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY")
    monkeypatch.setenv("CHUNKFLOW_SLO", "0")
    assert slo.start_slo() is None  # evaluator off, telemetry may run


def test_alert_events_reach_the_jsonl_stream(clean, tmp_path):
    telemetry.configure(str(tmp_path))
    traffic = SyntheticTraffic()
    ev = make_evaluator(traffic, target=0.9, short_s=2.0, long_s=6.0)
    traffic.advance(1.0, serving__requests=10)
    ev.tick()
    for _ in range(6):
        traffic.advance(1.0, serving__requests=10, serving__errors=8)
        ev.tick()
    for _ in range(10):
        traffic.advance(1.0, serving__requests=10)
        ev.tick()
    telemetry.flush()
    path = telemetry.configured_path()
    events = [json.loads(line) for line in open(path)]
    alerts = [e for e in events if e.get("kind") == "alert"]
    states = [e.get("state") for e in alerts]
    assert states == ["firing", "resolved"]
    assert alerts[0]["alert"] == "availability:fast"
    assert alerts[0]["burn_short"] >= 2.0
    assert "worker" in alerts[0]  # fleet-stamped like every event


def test_slo_plane_is_graftlint_clean():
    """ISSUE 12 satellite: GL001-GL014 clean over core/slo.py and the
    reworked telemetry/profiling modules, pinned in-suite so a future
    baseline regeneration cannot quietly grandfather a finding here."""
    from pathlib import Path

    from tools.graftlint.config import load_config
    from tools.graftlint.engine import lint_paths

    repo_root = Path(__file__).resolve().parents[2]
    config = load_config(repo_root / "pyproject.toml")
    findings, _ = lint_paths(
        [
            "chunkflow_tpu/core/slo.py",
            "chunkflow_tpu/core/telemetry.py",
            "chunkflow_tpu/core/profiling.py",
        ],
        config, repo_root=repo_root,
    )
    assert not findings, [
        f"{f.path}:{f.line}: {f.code} {f.message}" for f in findings
    ]
