"""Device-performance plane (core/profiling.py, ISSUE 8): program cost
ledger + roofline accounting, anomaly-triggered bounded profiler
capture, kill-switch compliance, and the GL007 lint gate over the new
module."""
import glob
import json
import os
import warnings

import numpy as np
import pytest

from chunkflow_tpu.core import profiling, telemetry
from chunkflow_tpu.core.compile_cache import ProgramCache, RetraceWarning


@pytest.fixture
def clean_plane(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()  # reset hook clears the ledger + capture state
    yield monkeypatch
    telemetry.reset()


# ---------------------------------------------------------------------------
# cost ledger
# ---------------------------------------------------------------------------
def test_program_cache_build_records_cost_ledger_entry(clean_plane,
                                                       tmp_path):
    """Acceptance: every ProgramCache build records compile seconds
    (always) and FLOPs/bytes (cost_analysis available on CPU), visible
    in the catalog, programs.json, the JSONL stream, and /metrics."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    telemetry.configure(str(tmp_path))
    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    for _ in range(2):
        np.asarray(inferencer(Chunk(
            rng.random((8, 32, 32), dtype=np.float32))).array)

    entries = profiling.catalog()
    assert len(entries) == 1
    entry = entries[0]
    assert entry["family"] == "scatter"
    assert entry["compile_s"] > 0  # first call paid trace + XLA compile
    assert entry["flops"] > 0  # CPU backend exposes cost_analysis
    assert entry["bytes_accessed"] > 0
    assert entry["calls"] == 2
    # roofline derivation against the peak table (CPU fallback row)
    assert entry["roofline_s"] > 0
    assert entry["roofline_util"] is not None
    assert entry["peak_source"].startswith(("table:", "env"))

    counters = telemetry.snapshot()["counters"]
    assert counters["program/builds"] == 1
    assert counters["program/compile_seconds"] > 0
    assert counters["program/flops_total"] == entry["flops"]

    # flush writes programs.json (flush hook) + emits the catalog event
    telemetry.flush()
    catalog_path = tmp_path / "programs.json"
    assert catalog_path.exists()
    payload = json.loads(catalog_path.read_text())
    assert payload["programs"][0]["family"] == "scatter"

    kinds = {}
    with open(telemetry.configured_path()) as f:
        for line in f:
            record = json.loads(line)
            kinds.setdefault(record["kind"], []).append(record)
    assert len(kinds["compile"]) == 1
    compile_ev = kinds["compile"][0]
    assert compile_ev["name"] == "program/scatter"
    assert compile_ev["compile_s"] > 0
    assert kinds["programs"][0]["programs"]

    # the program/* counters ride /metrics with zero new mapping code
    from chunkflow_tpu.parallel.restapi import (
        parse_prometheus,
        render_prometheus,
    )

    metrics = parse_prometheus(render_prometheus())
    assert metrics["chunkflow_program_builds_total"] == 1
    assert metrics["chunkflow_program_compile_seconds_total"] > 0
    assert metrics["chunkflow_program_flops_total_total"] == entry["flops"]


def test_instrument_program_passthrough_for_non_programs(clean_plane):
    """Cache entries that are not lowerable jit programs (tests cache
    plain sentinels) pass through untouched."""
    assert profiling.instrument_program("tag", ("k",)) == "tag"
    fn = lambda: 1  # noqa: E731 — callable but no .lower
    assert profiling.instrument_program(fn, ("k",)) is fn
    assert profiling.catalog() == []


def test_instrumented_program_forwards_attributes(clean_plane):
    import jax
    import jax.numpy as jnp

    program = profiling.instrument_program(
        jax.jit(lambda x: x * 2), ("fold", (8, 16, 16)), label="t")
    out = program(jnp.ones((4, 4)))
    assert float(out[0, 0]) == 2.0
    assert program._cache_size() == 1  # PjitFunction API forwards
    entry = profiling.catalog()[0]
    assert entry["family"] == "fold"
    assert entry["key"] == "(8, 16, 16)"


def test_device_peaks_env_override_and_table(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("CHUNKFLOW_PEAK_BW", raising=False)
    v5e = profiling.device_peaks("TPU v5 lite")
    assert v5e["flops_per_s"] == 197e12 and v5e["bytes_per_s"] == 819e9
    assert v5e["source"] == "table:tpu v5 lite"
    assert profiling.device_peaks("weird accelerator")["source"] \
        == "fallback"
    monkeypatch.setenv("CHUNKFLOW_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("CHUNKFLOW_PEAK_BW", "2e11")
    got = profiling.device_peaks("TPU v5 lite")
    assert got == {"flops_per_s": 1e12, "bytes_per_s": 2e11,
                   "source": "env"}


# ---------------------------------------------------------------------------
# anomaly-triggered bounded capture
# ---------------------------------------------------------------------------
def test_retrace_fire_captures_exactly_once(clean_plane, tmp_path):
    """Acceptance: an induced retrace-watchdog fire produces exactly ONE
    bounded capture that tools/analyze_trace.py can summarise; a second
    anomaly within the cooldown does not capture again."""
    import jax
    import jax.numpy as jnp

    monkeypatch = clean_plane
    monkeypatch.setenv("CHUNKFLOW_PROFILE_ON_ANOMALY", "1")
    monkeypatch.setenv("CHUNKFLOW_PROFILE_SECONDS", "0.3")
    monkeypatch.setenv("CHUNKFLOW_PROFILE_COOLDOWN", "300")
    telemetry.configure(str(tmp_path))

    cache = ProgramCache(expected_builds=1, label="anomaly")
    cache.get(("a",), lambda: jax.jit(lambda x: x + 1))(jnp.ones((8, 8)))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RetraceWarning)
        program = cache.get(("b",), lambda: jax.jit(lambda x: x * 2))
    # run device work while the window is open so the trace has events
    for _ in range(5):
        program(jnp.ones((16, 16))).block_until_ready()
    profiling.wait_for_captures(30)

    capture_dirs = sorted(glob.glob(str(tmp_path / "profile-*")))
    assert len(capture_dirs) == 1
    assert "retrace-anomaly" in os.path.basename(capture_dirs[0])

    from tools.analyze_trace import summarize_trace_dir

    summary = summarize_trace_dir(capture_dirs[0])
    assert summary["files"] >= 1

    # second anomaly inside the cooldown: no new capture
    profiling.note_retrace("again")
    profiling.wait_for_captures(10)
    assert len(glob.glob(str(tmp_path / "profile-*"))) == 1
    assert telemetry.snapshot()["counters"]["profile/captures"] == 1


def test_stall_streak_triggers_capture(clean_plane, monkeypatch):
    """K consecutive controller ticks with the SAME dominant phase at or
    above the share threshold trigger one capture; dipping below or
    switching phase resets the streak."""
    captured = []
    monkeypatch.setattr(profiling, "maybe_capture",
                        lambda reason: captured.append(reason) or True)
    monkeypatch.setenv("CHUNKFLOW_PROFILE_STALL_SHARE", "0.8")
    monkeypatch.setenv("CHUNKFLOW_PROFILE_STALL_TICKS", "3")

    profiling.note_stall("scheduler/load", 0.9)
    profiling.note_stall("scheduler/load", 0.5)  # dip resets
    profiling.note_stall("scheduler/load", 0.9)
    profiling.note_stall("pipeline/drain", 0.9)  # phase switch resets
    profiling.note_stall("pipeline/drain", 0.9)
    assert captured == []
    profiling.note_stall("pipeline/drain", 0.9)  # third consecutive
    assert captured == ["stall-pipeline-drain"]
    # streak reset after firing: the cooldown owns repeat suppression
    profiling.note_stall("pipeline/drain", 0.9)
    profiling.note_stall("pipeline/drain", 0.9)
    assert len(captured) == 1


def test_scheduler_tick_feeds_stall_anomaly(clean_plane, monkeypatch):
    """The depth controller reports every tick's dominant share to the
    profiling plane (flow/scheduler.py wiring)."""
    from chunkflow_tpu.flow.scheduler import DepthController

    seen = []
    monkeypatch.setattr(profiling, "note_stall",
                        lambda phase, share: seen.append((phase, share)))
    ctl = DepthController(interval=1, watermark_bytes=1 << 40)
    ctl.tick({"scheduler/load": 10.0})
    assert seen == [("scheduler/load", 1.0)]


# ---------------------------------------------------------------------------
# kill switch: CHUNKFLOW_TELEMETRY=0 means the plane does not exist
# ---------------------------------------------------------------------------
def test_kill_switch_creates_nothing(tmp_path, monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    telemetry.reset()
    # no instrumentation wrapper...
    program = jax.jit(lambda x: x + 1)
    assert profiling.instrument_program(program, ("a",)) is program
    cached = ProgramCache().get(("a",), lambda: program)
    assert cached is program
    cached(jnp.ones((4, 4)))
    assert profiling.catalog() == []
    # ...no catalog file...
    assert profiling.write_catalog(str(tmp_path)) is None
    # ...no capture threads or files...
    assert profiling.maybe_capture("retrace-x") is False
    target, err = profiling.capture(0.1, "operator", force=True)
    assert target is None and "disabled" in err
    # ...no task window...
    assert profiling.start_task_window(str(tmp_path / "w")) is None
    # ...and no /profile route
    from chunkflow_tpu.parallel.restapi import CoordinationService

    status, payload = CoordinationService().handle(
        "POST", "/profile?seconds=0.1")
    assert status == 404
    assert list(tmp_path.iterdir()) == []
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY")
    telemetry.reset()


def test_capture_requires_a_destination(clean_plane, monkeypatch):
    """No metrics sink and no CHUNKFLOW_PROFILE_DIR: captures refuse
    rather than writing somewhere surprising."""
    monkeypatch.delenv("CHUNKFLOW_PROFILE_DIR", raising=False)
    target, err = profiling.capture(0.1, "operator", force=True)
    assert target is None and "no capture dir" in err


# ---------------------------------------------------------------------------
# lint compliance: no instrumentation inside traced functions (GL007)
# ---------------------------------------------------------------------------
def test_profiling_module_is_gl007_clean():
    from pathlib import Path

    from tools.graftlint.config import load_config
    from tools.graftlint.engine import lint_paths

    repo_root = Path(__file__).resolve().parents[2]
    config = load_config(repo_root / "pyproject.toml")
    findings, _ = lint_paths(
        ["chunkflow_tpu/core/profiling.py"], config, repo_root=repo_root)
    gl007 = [f for f in findings if f.code == "GL007"]
    assert not gl007, [f"{f.path}:{f.line}: {f.message}" for f in gl007]
    assert not findings, [
        f"{f.path}:{f.line}: {f.code} {f.message}" for f in findings
    ]


# ---------------------------------------------------------------------------
# log-summary DEVICE PROGRAMS table + cloud watch pickup
# ---------------------------------------------------------------------------
def test_log_summary_renders_device_programs_table(clean_plane, tmp_path,
                                                   capsys):
    from chunkflow_tpu.flow.log_summary import (
        print_telemetry_summary,
        summarize_programs,
    )

    events = [
        {"kind": "compile", "name": "program/fold", "family": "fold",
         "key": "(8, 32, 32)", "compile_s": 0.5, "flops": 2e9,
         "bytes_accessed": 3e8, "device": "cpu", "worker": "w1",
         "t": 1.0},
        {"kind": "programs", "name": "program/catalog", "worker": "w1",
         "t": 2.0, "programs": [
             {"family": "fold", "key": "(8, 32, 32)", "compile_s": 0.5,
              "flops": 2e9, "bytes_accessed": 3e8, "exec_mean_s": 0.01,
              "roofline_util": 0.42, "device_kind": "cpu"},
             {"family": "scatter", "key": "", "compile_s": 0.2,
              "flops": 1e9, "bytes_accessed": 1e8, "exec_mean_s": 0.02,
              "roofline_util": 0.04, "device_kind": "cpu"},
         ]},
    ]
    programs = summarize_programs(events)
    # the catalog event wins over raw compile events for the same worker
    assert len(programs) == 2
    assert programs[0]["family"] == "fold"  # sorted by compile_s
    assert programs[0]["roofline_util"] == 0.42

    path = tmp_path / "telemetry-w1.jsonl"
    with open(path, "w") as f:
        for record in events:
            f.write(json.dumps(record) + "\n")
    print_telemetry_summary(str(tmp_path))
    out = capsys.readouterr().out
    assert "device programs" in out
    assert "fold" in out and "scatter" in out
    assert "42.0%" in out


def test_device_programs_rank_by_lost_seconds(clean_plane):
    """ISSUE 14 satellite: the DEVICE PROGRAMS ranking key is lost
    seconds ((dispatch_wall − roofline_s) × calls) — the family
    furthest above its cost-model floor leads, regardless of compile
    time; entries without a roofline fall back behind, by compile
    time."""
    from chunkflow_tpu.flow.log_summary import summarize_programs

    events = [
        {"kind": "programs", "name": "program/catalog", "worker": "w1",
         "t": 2.0, "programs": [
             # slow compile but NEAR its floor: little to win
             {"family": "fold", "key": "", "compile_s": 9.0,
              "exec_mean_s": 0.010, "roofline_s": 0.009,
              "lost_s": 0.01, "roofline_util": 0.9},
             # fast compile but far above its floor over many calls:
             # the fusion target
             {"family": "scatter", "key": "", "compile_s": 0.2,
              "exec_mean_s": 0.050, "roofline_s": 0.005,
              "lost_s": 4.5, "roofline_util": 0.1},
             # no roofline figure at all: ranks behind both
             {"family": "mystery", "key": "", "compile_s": 1.0},
         ]},
    ]
    programs = summarize_programs(events)
    assert [p["family"] for p in programs] == \
        ["scatter", "fold", "mystery"]


def test_stamp_cost_wins_over_xla_cost_analysis(clean_plane, tmp_path):
    """profiling.stamp_cost: an analytic cost model attached to a
    program (Pallas custom calls / loop bodies are opaque to XLA's
    cost_analysis) is what the ledger scores — and lost_s derives from
    it."""
    import jax

    from chunkflow_tpu.core import telemetry
    from chunkflow_tpu.core.compile_cache import ProgramCache

    telemetry.configure(str(tmp_path))
    try:
        cache = ProgramCache(label="stamped")
        program = cache.get(
            ("stamped_family",),
            lambda: profiling.stamp_cost(
                jax.jit(lambda x: x * 2.0), flops=123.0,
                bytes_accessed=4.5e8),
        )
        import jax.numpy as jnp

        out = program(jnp.ones((4,)))
        out.block_until_ready()
        program(jnp.ones((4,))).block_until_ready()
        entry = {e["family"]: e for e in profiling.catalog()}[
            "stamped_family"]
        assert entry["flops"] == 123.0
        assert entry["bytes_accessed"] == 4.5e8
        assert entry["roofline_s"] is not None
        assert entry["lost_s"] is not None and entry["lost_s"] >= 0.0
    finally:
        telemetry.flush()
        telemetry.configure(None)


def test_program_counters_reach_cloud_watch(clean_plane):
    """Satellite: program_* counters flow through the CloudWatch
    publisher with no new mapping code (and the seconds counter gets a
    real unit)."""
    from chunkflow_tpu.plugins.aws.cloud_watch import snapshot_metric_data

    telemetry.inc("program/builds", 2)
    telemetry.inc("program/compile_seconds", 1.5)
    data = {d["MetricName"]: d for d in snapshot_metric_data()}
    assert data["program/builds"]["Value"] == 2
    assert data["program/builds"]["Unit"] == "Count"
    assert data["program/compile_seconds"]["Unit"] == "Seconds"


def test_task_window_stops_after_n_tasks(clean_plane, tmp_path):
    """--profile-dir windowed capture: the trace closes itself once its
    task budget is spent and releases the profiler session."""
    import jax
    import jax.numpy as jnp

    telemetry.configure(str(tmp_path))
    trace_dir = tmp_path / "win"
    window = profiling.start_task_window(str(trace_dir), tasks=2)
    assert window is not None and window.active
    jax.jit(lambda x: x + 1)(jnp.ones((8, 8))).block_until_ready()
    profiling.note_task_done()
    assert window.active  # 1 of 2
    profiling.note_task_done()
    assert not window.active  # budget spent: trace stopped
    assert glob.glob(str(trace_dir / "**" / "*.trace.json.gz"),
                     recursive=True)
    # the session flag is released: a capture can start again
    assert profiling._TRACE_ACTIVE is False
    profiling.note_task_done()  # past-budget tasks are a no-op
    window.close()  # idempotent
