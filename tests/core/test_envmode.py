"""core/envmode.py: the shared warn-once env-mode parser (ISSUE 16
satellite). The three callers' own warn-once tests (pallas_mode,
gather_mode, resolve_precision) keep covering their ends of the seam;
these tests pin the helper's contract directly so the fused patch
program's future knob can rely on it without growing copy #4."""
import pytest

from chunkflow_tpu.core import envmode

CHOICES = {
    "off": ("", "0", "off"),
    "on": ("1", "on", "force"),
    "interpret": ("interpret",),
}


@pytest.fixture
def clean_var(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_ENVMODE_TEST", raising=False)
    monkeypatch.setattr(envmode, "_WARNED_BY_VAR", {})
    return "CHUNKFLOW_ENVMODE_TEST"


def resolve(warned=None):
    return envmode.resolve(
        "CHUNKFLOW_ENVMODE_TEST", CHOICES, default="off",
        note="treating it as OFF", warned=warned,
    )


def test_recognized_values_resolve_without_warning(
        clean_var, monkeypatch, capsys):
    for value, expected in [("", "off"), ("0", "off"), ("off", "off"),
                            ("1", "on"), ("force", "on"),
                            ("interpret", "interpret"),
                            ("INTERPRET", "interpret")]:
        monkeypatch.setenv(clean_var, value)
        assert resolve() == expected
    monkeypatch.delenv(clean_var)
    assert resolve() == "off"  # unset -> the ""-bearing choice
    assert capsys.readouterr().err == ""


def test_unrecognized_warns_once_per_value(clean_var, monkeypatch, capsys):
    warned = set()
    monkeypatch.setenv(clean_var, "ture")
    assert resolve(warned) == "off"
    err = capsys.readouterr().err
    assert "ture" in err and "not a recognized value" in err
    assert "treating it as OFF" in err
    # same typo again: silent
    assert resolve(warned) == "off"
    assert capsys.readouterr().err == ""
    # a different typo warns again
    monkeypatch.setenv(clean_var, "yes please")
    assert resolve(warned) == "off"
    assert "yes please" in capsys.readouterr().err
    assert warned == {"ture", "yes please"}


def test_warning_lists_recognized_values(clean_var, monkeypatch, capsys):
    monkeypatch.setenv(clean_var, "bogus")
    resolve(set())
    err = capsys.readouterr().err
    # every non-empty recognized value group is named in the warning
    assert "0/off" in err and "1/on/force" in err and "interpret" in err


def test_internal_warned_sets_are_per_variable(monkeypatch, capsys):
    monkeypatch.setattr(envmode, "_WARNED_BY_VAR", {})
    monkeypatch.setenv("CHUNKFLOW_ENVMODE_A", "oops")
    monkeypatch.setenv("CHUNKFLOW_ENVMODE_B", "oops")
    envmode.resolve("CHUNKFLOW_ENVMODE_A", CHOICES, "off", "note a")
    # the same typo on a DIFFERENT variable still warns: per-var sets
    envmode.resolve("CHUNKFLOW_ENVMODE_B", CHOICES, "off", "note b")
    err = capsys.readouterr().err
    assert "CHUNKFLOW_ENVMODE_A" in err and "CHUNKFLOW_ENVMODE_B" in err
    # and each variable's second hit is silent
    envmode.resolve("CHUNKFLOW_ENVMODE_A", CHOICES, "off", "note a")
    envmode.resolve("CHUNKFLOW_ENVMODE_B", CHOICES, "off", "note b")
    assert capsys.readouterr().err == ""


def test_normalize_folds_aliases_before_matching(
        clean_var, monkeypatch, capsys):
    aliases = {"fast": "on"}
    monkeypatch.setenv(clean_var, "FAST")
    got = envmode.resolve(
        clean_var, CHOICES, "off", "note",
        warned=set(), normalize=lambda env: aliases.get(env, env),
    )
    assert got == "on"
    assert capsys.readouterr().err == ""


def test_recognized_values_enumeration():
    assert envmode.recognized_values(CHOICES) == (
        "", "0", "off", "1", "on", "force", "interpret"
    )
