"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Sharding tests run against 8 virtual CPU devices so multi-chip layouts are
validated without TPU pod hardware; the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip.
"""
import os

# Hard override: the driver env pins JAX_PLATFORMS=axon (the real TPU) and a
# sitecustomize hook registers that PJRT plugin in every interpreter, so env
# vars alone cannot switch platforms. Unit tests must run on the virtual CPU
# mesh — full-precision convs for the torch-parity oracle and no per-test TPU
# compile latency — so force it through jax.config before any test imports
# jax. bench.py and __graft_entry__ do not import this file, so they still
# see the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
# Scrub the TPU-tunnel trigger for every SUBPROCESS tests spawn (pod-sim
# workers, bench.py's probe/child): with PALLAS_AXON_POOL_IPS set, the
# sitecustomize hook registers the single-client tunnel in each fresh
# interpreter before any user code runs — in-process jax.config fixes
# (below) cannot reach those children, and a probe against a dead tunnel
# hangs ~25 min. Scrubbing here, in the parent, is the only early-enough
# place.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Anomaly-triggered profiler capture (core/profiling.py) is ON by
# default in production, but a background jax.profiler window starting
# mid-suite (many tests deliberately drive 100%-dominant stalls and
# retraces with a sink configured) would race the tests that own the
# one-session-at-a-time profiler. Default it off for the suite; the
# dedicated profiling tests opt back in with monkeypatch.
os.environ.setdefault("CHUNKFLOW_PROFILE_ON_ANOMALY", "0")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Locksmith concurrency sanitizer (chunkflow_tpu/testing/locksmith.py):
# proxy every Lock/RLock/Condition this codebase creates and raise on
# lock-order cycles, so the whole tier-1 suite doubles as a concurrency
# test. Installed BEFORE any chunkflow module import so module-level
# locks (scheduler watermark, profiling state, telemetry registry) are
# covered too. Default ON for the suite; CHUNKFLOW_LOCKSMITH=0 disables
# (and then install() is a strict no-op — no proxies, no files).
os.environ.setdefault("CHUNKFLOW_LOCKSMITH", "1")
from chunkflow_tpu.testing import locksmith  # noqa: E402

locksmith.install()

# Kernelcheck Pallas sanitizer (chunkflow_tpu/testing/kernelcheck.py):
# poison VMEM scratch, assert DMA windows in-bounds and verify the RMW
# grid order on every interpret-mode kernel run, so the tier-1 parity
# suites double as kernel sanitizer runs. Default ON for the suite;
# CHUNKFLOW_KERNELCHECK=0 disables (a strict no-op — no callbacks, no
# poison, byte-identical traces).
os.environ.setdefault("CHUNKFLOW_KERNELCHECK", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
