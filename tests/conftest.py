"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Sharding tests run against 8 virtual CPU devices so multi-chip layouts are
validated without TPU pod hardware; the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
