"""Double-buffered chunk executor (flow/pipeline.py): the pipelined path
must be a pure wall-time optimization — bit-identical outputs, input
order, same failure semantics as the serial loop — with the donation
ownership contract honored at every boundary (staged ring slots are
consumed; caller-owned buffers never are)."""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.flow.pipeline import (
    pipeline_chunks,
    pipelined_inference_stage,
)
from chunkflow_tpu.inference import Inferencer


def _inferencer(**kwargs):
    defaults = dict(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    defaults.update(kwargs)
    return Inferencer(**defaults)


def _chunks(shapes, seed=0):
    rng = np.random.default_rng(seed)
    return [
        Chunk(rng.random(s).astype(np.float32), voxel_offset=(8 * i, 0, 0))
        for i, s in enumerate(shapes)
    ]


# mixed aligned + ragged-edge shapes: the regime where retrace/donation
# bugs hide (a ragged chunk pads, runs a different geometry, crops back)
RAGGED_SHAPES = [(8, 32, 32), (5, 17, 18), (8, 32, 32), (7, 30, 20)]


@pytest.mark.parametrize("ring", [1, 2, 3])
def test_pipeline_bit_identical_to_serial(ring):
    inferencer = _inferencer(shape_bucket=(8, 16, 16))
    chunks = _chunks(RAGGED_SHAPES)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    piped = list(pipeline_chunks(inferencer, iter(chunks), ring=ring))
    assert len(piped) == len(chunks)
    for src, ref, out in zip(chunks, serial, piped):
        assert not out.is_on_device
        assert tuple(out.voxel_offset) == tuple(src.voxel_offset)
        # bit-identical, not allclose: both paths run the SAME compiled
        # program; donation must not perturb a single ulp
        np.testing.assert_array_equal(np.asarray(out.array), ref)


def test_pipeline_bit_identical_uint8_output():
    inferencer = _inferencer(output_dtype="uint8")
    chunks = _chunks(RAGGED_SHAPES, seed=3)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    piped = list(pipeline_chunks(inferencer, iter(chunks)))
    for ref, out in zip(serial, piped):
        assert np.asarray(out.array).dtype == np.uint8
        np.testing.assert_array_equal(np.asarray(out.array), ref)


def test_donation_back_to_back_same_program():
    """The same donating program invoked back-to-back (same shape, fresh
    buffers) must not corrupt results: XLA recycles the donated input
    into the output, so a stale aliasing bug would show as run-to-run
    divergence."""
    inferencer = _inferencer()
    chunk = _chunks([(8, 32, 32)])[0]
    first = np.asarray(inferencer(chunk).array)
    for _ in range(3):
        np.testing.assert_array_equal(
            np.asarray(inferencer(chunk).array), first
        )


def test_caller_device_chunk_survives_inference():
    """A device-resident chunk the CALLER staged is not pipeline-owned:
    inference must copy rather than donate it, leaving the caller's
    buffer alive (prefetch --to-device hands such chunks to the
    inference stage, which may re-read them under another name)."""
    inferencer = _inferencer()
    host = _chunks([(8, 32, 32)])[0]
    dev = host.device()
    out1 = list(pipeline_chunks(inferencer, iter([dev])))[0]
    # the caller's buffer must still be readable after the program ran
    np.testing.assert_array_equal(
        np.asarray(dev.array), np.asarray(host.array)
    )
    out2 = np.asarray(inferencer(host).array)
    np.testing.assert_array_equal(np.asarray(out1.array), out2)


def test_pipeline_postprocess_order_and_results():
    inferencer = _inferencer()
    chunks = _chunks(RAGGED_SHAPES[:3], seed=5)
    serial = [float(np.asarray(inferencer(c).array).sum()) for c in chunks]
    piped = list(
        pipeline_chunks(
            inferencer, iter(chunks),
            postprocess=lambda c: float(np.asarray(c.array).sum()),
        )
    )
    assert piped == pytest.approx(serial)


def _task(chunk, i):
    return {"log": {"timer": {}, "compute_device": ""}, "i": i,
            "chunk": chunk}


def test_pipelined_stage_order_skip_markers_and_timers():
    inferencer = _inferencer()
    chunks = _chunks(RAGGED_SHAPES, seed=7)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    tasks = [_task(c, i) for i, c in enumerate(chunks)]
    tasks.insert(2, None)  # skip marker mid-stream
    stage = pipelined_inference_stage(inferencer, depth=2, op_name="inf")
    out = list(stage(iter(tasks)))
    assert [t["i"] if t else None for t in out] == [0, 1, None, 2, 3]
    for task in out:
        if task is None:
            continue
        np.testing.assert_array_equal(
            np.asarray(task["chunk"].array), serial[task["i"]]
        )
        assert not task["chunk"].is_on_device
        assert task["log"]["timer"]["inf"] >= 0
        assert task["log"]["compute_device"]


def test_pipelined_stage_flushes_dispatched_on_error():
    """A mid-stream failure must not drop tasks that were already
    dispatched — the synchronous path would have completed them."""
    inferencer = _inferencer()
    chunks = _chunks([(8, 32, 32)] * 3, seed=9)

    def check(chunk):
        if tuple(chunk.voxel_offset)[0] == 16:  # third task
            raise RuntimeError("bad grid")

    stage = pipelined_inference_stage(
        inferencer, depth=2, op_name="inf", check=check
    )
    got = []
    with pytest.raises(RuntimeError, match="bad grid"):
        for task in stage(iter(_task(c, i) for i, c in enumerate(chunks))):
            got.append(task["i"])
    assert got == [0, 1]


def test_prefetch_then_pipelined_inference_compose():
    """The full streaming sandwich: prefetch --to-device staging feeding
    the double-buffered inference stage (the production worker wiring)."""
    from chunkflow_tpu.flow.runtime import prefetch_stage

    inferencer = _inferencer()
    chunks = _chunks(RAGGED_SHAPES, seed=11)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    stages = [
        prefetch_stage(depth=2, to_device=True),
        pipelined_inference_stage(inferencer, depth=2, op_name="inf"),
    ]
    stream = iter([_task(c, i) for i, c in enumerate(chunks)])
    for s in stages:
        stream = s(stream)
    out = list(stream)
    assert [t["i"] for t in out] == [0, 1, 2, 3]
    for task in out:
        np.testing.assert_array_equal(
            np.asarray(task["chunk"].array), serial[task["i"]]
        )
