import numpy as np
import pytest
from click.testing import CliRunner

from chunkflow_tpu.chunk import Chunk
from chunkflow_tpu.flow.cli import main


@pytest.fixture
def runner():
    return CliRunner()


def run_ok(runner, args):
    result = runner.invoke(main, args, catch_exceptions=False)
    assert result.exit_code == 0, result.output
    return result


def test_create_save_load_h5(runner, tmp_path):
    path = str(tmp_path / "c.h5")
    run_ok(runner, ["create-chunk", "--size", "8", "8", "8", "save-h5", "-f", path])
    loaded = Chunk.from_h5(path)
    assert loaded.shape == (8, 8, 8)
    out = str(tmp_path / "c2.h5")
    run_ok(runner, ["load-h5", "-f", path, "save-h5", "-f", out])
    reloaded = Chunk.from_h5(out)
    np.testing.assert_array_equal(np.asarray(reloaded.array), np.asarray(loaded.array))


def test_tif_roundtrip(runner, tmp_path):
    path = str(tmp_path / "c.tif")
    run_ok(runner, ["create-chunk", "--size", "4", "8", "8", "save-tif", "-f", path])
    loaded = Chunk.from_tif(path)
    assert loaded.shape == (4, 8, 8)


def test_pipeline_compute(runner, tmp_path):
    out = str(tmp_path / "seg.h5")
    run_ok(
        runner,
        [
            "create-chunk", "--size", "8", "16", "16", "--dtype", "float32",
            "--pattern", "random",
            "threshold", "-t", "0.5",
            "connected-components",
            "save-h5", "-f", out,
        ],
    )
    seg = Chunk.from_h5(out)
    assert np.dtype(seg.dtype).kind in "iu"


def test_skip_all_zero_short_circuits(runner, tmp_path):
    out = str(tmp_path / "never.h5")
    run_ok(
        runner,
        [
            "create-chunk", "--pattern", "zero", "--size", "4", "4", "4",
            "skip-all-zero",
            "save-h5", "-f", out,
        ],
    )
    import os

    assert not os.path.exists(out)


def test_generate_tasks_stream_and_file(runner, tmp_path):
    task_file = str(tmp_path / "tasks.txt")
    run_ok(
        runner,
        [
            "generate-tasks", "-c", "4", "4", "4",
            "--roi-start", "0", "0", "0", "--roi-stop", "8", "8", "8",
            "--task-file", task_file,
        ],
    )
    lines = open(task_file).read().splitlines()
    assert len(lines) == 8

    # streamed tasks drive downstream ops once per bbox
    result = run_ok(
        runner,
        [
            "-v",
            "generate-tasks", "-c", "4", "4", "4",
            "--roi-start", "0", "0", "0", "--roi-stop", "8", "8", "8",
        ],
    )
    assert "8 task" in result.output


def test_disbatch_protocol(runner, tmp_path, monkeypatch):
    """$DISBATCH_REPEAT_INDEX selects a single task (reference
    flow/flow.py:151-156) in both generate-tasks and fetch-task-from-file."""
    monkeypatch.setenv("DISBATCH_REPEAT_INDEX", "3")
    result = run_ok(
        runner,
        [
            "-v",
            "generate-tasks", "-c", "4", "4", "4",
            "--roi-start", "0", "0", "0", "--roi-stop", "8", "8", "8",
            "--disbatch",
        ],
    )
    assert "1 task" in result.output

    task_file = str(tmp_path / "tasks.npy")
    run_ok(
        runner,
        [
            "generate-tasks", "-c", "4", "4", "4",
            "--roi-start", "0", "0", "0", "--roi-stop", "8", "8", "8",
            "--task-file", task_file,
        ],
    )
    result = run_ok(
        runner,
        ["-v", "fetch-task-from-file", "-f", task_file, "--disbatch"],
    )
    assert "1 task" in result.output

    # out-of-range index fails loudly
    monkeypatch.setenv("DISBATCH_REPEAT_INDEX", "99")
    result = runner.invoke(main, [
        "generate-tasks", "-c", "4", "4", "4",
        "--roi-start", "0", "0", "0", "--roi-stop", "8", "8", "8",
        "--disbatch",
    ])
    assert result.exit_code != 0


def test_queue_workflow(runner, tmp_path):
    qdir = str(tmp_path / "queue")
    run_ok(
        runner,
        [
            "generate-tasks", "-c", "4", "4", "4",
            "--roi-start", "0", "0", "0", "--roi-stop", "8", "8", "8",
            "--queue-name", qdir,
        ],
    )
    from chunkflow_tpu.parallel.queues import open_queue

    assert len(open_queue(qdir)) == 8

    # consume and ack every task
    run_ok(
        runner,
        ["fetch-task-from-queue", "-q", qdir, "delete-task-in-queue"],
    )
    queue = open_queue(qdir)
    assert len(queue) == 0
    import os

    assert not os.listdir(os.path.join(qdir, "claimed"))


def test_delete_and_copy_var(runner, tmp_path):
    out = str(tmp_path / "copy.h5")
    run_ok(
        runner,
        [
            "create-chunk", "--size", "4", "4", "4",
            "copy-var", "-f", "chunk", "-t", "backup",
            "delete-var", "-v", "chunk",
            "save-h5", "-f", out, "-i", "backup",
        ],
    )
    assert Chunk.from_h5(out).shape == (4, 4, 4)


def test_normalize_intensity(runner, tmp_path):
    src = str(tmp_path / "u8.h5")
    out = str(tmp_path / "norm.h5")
    run_ok(
        runner,
        [
            "create-chunk", "--size", "4", "8", "8", "--dtype", "uint8",
            "--pattern", "random",
            "save-h5", "-f", src,
        ],
    )
    run_ok(
        runner,
        ["load-h5", "-f", src, "normalize-intensity", "save-h5", "-f", out],
    )
    norm = Chunk.from_h5(out)
    arr = np.asarray(norm.array)
    assert arr.dtype == np.float32
    assert arr.min() >= -1.0 and arr.max() <= 1.0


def test_normalize_section_shang(runner, tmp_path):
    out = str(tmp_path / "shang.h5")
    run_ok(
        runner,
        [
            "create-chunk", "--size", "4", "8", "8", "--dtype", "uint8",
            "--pattern", "random",
            "normalize-section-shang", "--nominalmax", "1.0",
            "--clipvalues", "true",
            "save-h5", "-f", out,
        ],
    )
    arr = np.asarray(Chunk.from_h5(out).array)
    assert arr.dtype == np.float32
    assert arr.max() <= 1.0


def test_save_zarr_nonzero_offset(runner, tmp_path):
    pytest.importorskip("tensorstore")
    store = str(tmp_path / "store.zarr")
    run_ok(
        runner,
        [
            "create-chunk", "--size", "4", "8", "8",
            "--voxel-offset", "2", "4", "4",
            "save-zarr", "-p", store,
        ],
    )
    import tensorstore as ts

    arr = ts.open(
        {"driver": "zarr", "kvstore": {"driver": "file", "path": store}}
    ).result()
    assert tuple(arr.shape) == (6, 12, 12)


def test_save_zarr_into_existing_larger_store(runner, tmp_path):
    pytest.importorskip("tensorstore")
    store = str(tmp_path / "big.zarr")
    # create the store with an explicit volume size via the corner chunk
    run_ok(
        runner,
        [
            "create-chunk", "--size", "4", "8", "8",
            "save-zarr", "-p", store, "--volume-size", "8", "16", "16",
        ],
    )
    # then write an interior chunk without repeating --volume-size
    run_ok(
        runner,
        [
            "create-chunk", "--size", "4", "8", "8",
            "--voxel-offset", "4", "8", "8",
            "save-zarr", "-p", store,
        ],
    )
    import tensorstore as ts

    arr = ts.open(
        {"driver": "zarr", "kvstore": {"driver": "file", "path": store}}
    ).result()
    assert tuple(arr.shape) == (8, 16, 16)


def test_save_nrrd_cli(runner, tmp_path):
    path = str(tmp_path / "c.nrrd")
    run_ok(runner, ["create-chunk", "--size", "4", "8", "8", "save-nrrd", "-f", path])
    from chunkflow_tpu.volume.io_nrrd import load_nrrd

    arr, header = load_nrrd(path)
    assert arr.shape == (4, 8, 8)


def test_mesh_download_mesh_cli(runner, tmp_path):
    mesh_dir = str(tmp_path / "mesh")
    out_pre = str(tmp_path / "m_")
    # two touching cubes of one object meshed from a random-ish seg
    run_ok(
        runner,
        [
            "create-chunk", "--size", "8", "16", "16", "--pattern", "zero",
            "--dtype", "uint32",
            "plugin", "-f", "print_max_id",
            "mesh", "-o", mesh_dir, "--output-format", "precomputed",
        ],
    )
    # meshing a zero chunk produces no fragments; now a real object
    import numpy as np

    from chunkflow_tpu.chunk.segmentation import Segmentation
    from chunkflow_tpu.flow.mesh import MeshOperator, write_manifests

    seg = np.zeros((8, 16, 16), np.uint32)
    seg[2:6, 2:14, 2:8] = 7
    seg[2:6, 2:14, 8:14] = 7
    op = MeshOperator(mesh_dir, output_format="precomputed")
    op(Segmentation(seg, voxel_size=(1, 1, 1)))
    write_manifests(mesh_dir)
    run_ok(
        runner,
        [
            "create-chunk", "--size", "2", "2", "2",
            "download-mesh", "-v", mesh_dir, "-i", "7",
            "-o", out_pre, "-f", "obj",
        ],
    )
    import os

    assert os.path.exists(out_pre + "7.obj")


def test_view_screenshot(runner, tmp_path):
    shot = str(tmp_path / "view.png")
    run_ok(
        runner,
        [
            "create-chunk", "--size", "4", "16", "16", "--pattern", "sin",
            "view", "--screenshot", shot,
        ],
    )
    import os

    assert os.path.exists(shot)


def test_load_precomputed_blackout_and_validate(runner, tmp_path):
    """blackout_section_ids.json zeroes sections; cross-mip validation runs."""
    import json

    from chunkflow_tpu.chunk import Chunk
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "vol"
    chunk = Chunk.create((8, 16, 16), dtype=np.uint8, pattern="sin")
    vol = PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(40, 4, 4),
    )
    vol.save(chunk, mip=0)
    (root / "blackout_section_ids.json").write_text(
        json.dumps({"section_ids": [2, 5]})
    )

    out = tmp_path / "out.h5"
    result = runner.invoke(main, [
        "generate-tasks", "-c", "8", "16", "16",
        "--roi-stop", "8", "16", "16",
        "load-precomputed", "-v", str(root), "--blackout-sections",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    loaded = np.asarray(Chunk.from_h5(str(out)).array)
    assert loaded[2].sum() == 0 and loaded[5].sum() == 0
    assert loaded[0].sum() > 0


def test_load_precomputed_cross_mip_validation(runner, tmp_path, capsys):
    """--validate-mip re-downloads at the coarse mip and compares."""
    from chunkflow_tpu.chunk import Chunk
    from chunkflow_tpu.ops.downsample import downsample_average
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "vol2"
    chunk = Chunk.create((8, 16, 16), dtype=np.uint8, pattern="sin")
    vol = PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(40, 4, 4), num_mips=2, block_size=(8, 8, 8),
    )
    vol.save(chunk, mip=0)
    vol.save(downsample_average(chunk, factor=(1, 2, 2)), mip=1)

    out = tmp_path / "out2.h5"
    result = runner.invoke(main, [
        "generate-tasks", "-c", "8", "16", "16",
        "--roi-stop", "8", "16", "16",
        "load-precomputed", "-v", str(root), "--validate-mip", "1",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    assert "cross-mip validation mismatch" not in result.output

    # corrupt the coarse mip: validation must now FAIL the task (the
    # reference asserts equality, load_precomputed.py:115-182)
    zero = Chunk.create((8, 8, 8), dtype=np.uint8, pattern="zero")
    vol.save(zero, mip=1)
    result = runner.invoke(main, [
        "generate-tasks", "-c", "8", "16", "16",
        "--roi-stop", "8", "16", "16",
        "load-precomputed", "-v", str(root), "--validate-mip", "1",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code != 0
    assert "cross-mip validation mismatch" in str(result.exception)


def test_profile_dir_writes_trace(runner, tmp_path):
    trace_dir = tmp_path / "trace"
    result = runner.invoke(main, [
        "--profile-dir", str(trace_dir),
        "create-chunk", "--size", "4", "8", "8",
        "threshold", "--threshold", "0.5",
    ])
    assert result.exit_code == 0, result.output
    assert trace_dir.exists() and any(trace_dir.rglob("*"))


def test_mesh_simplification():
    """Vertex clustering cuts vertex count, preserves manifoldness basics."""
    from chunkflow_tpu.flow.mesh import mesh_chunk, simplify_mesh
    from chunkflow_tpu.chunk import Segmentation

    seg = np.zeros((16, 16, 16), dtype=np.uint32)
    seg[2:14, 2:14, 2:14] = 1
    meshes = mesh_chunk(Segmentation(seg, voxel_size=(1, 1, 1)))
    vertices, faces = meshes[1]
    sv, sf = simplify_mesh(vertices, faces, cell_size=4.0)
    assert sv.shape[0] < vertices.shape[0]
    assert sf.shape[0] < faces.shape[0]
    assert sf.max() < sv.shape[0]
    # bounding box roughly preserved (within one cell)
    assert np.allclose(sv.min(0), vertices.min(0), atol=4.0)
    assert np.allclose(sv.max(0), vertices.max(0), atol=4.0)
    # no-op when cell_size=0
    v0, f0 = simplify_mesh(vertices, faces, cell_size=0.0)
    assert v0.shape == vertices.shape and f0.shape == faces.shape


def test_save_precomputed_with_thumbnail_and_log(runner, tmp_path):
    """save-precomputed writes data + timing-log sidecar; thumbnail pyramid
    lands in the sibling thumbnail volume (reference save_precomputed.py
    :104-150)."""
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "outvol"
    PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(40, 4, 4), block_size=(8, 8, 8),
    )
    result = runner.invoke(main, [
        "generate-tasks", "-c", "8", "16", "16",
        "--roi-stop", "8", "16", "16",
        "create-chunk", "--size", "8", "16", "16", "--pattern", "sin",
        "save-precomputed", "-v", str(root),
    ])
    assert result.exit_code == 0, result.output
    log_dir = root / "log"
    assert log_dir.exists() and any(log_dir.iterdir())
    import json

    record = json.loads(next(log_dir.iterdir()).read_text())
    assert "timer" in record and "compute_device" in record


def test_inference_reference_migration_options(runner, tmp_path):
    """Reference spellings work verbatim: -s/-v/-c short flags, --name
    timer key, --patch-num grid assertion, --dtype float16 (mapped to
    bfloat16), --output-crop-margin explicit crop
    (reference flow/flow.py:1852-1894)."""
    out = tmp_path / "o.h5"
    result = runner.invoke(main, [
        "--verbose",
        "create-chunk", "-s", "16", "48", "48", "--pattern", "sin",
        "inference", "--name", "my-inference",
        "-s", "8", "24", "24", "-v", "2", "8", "8", "-c", "1",
        "-f", "identity", "-b", "2", "--bump", "wu",
        "--patch-num", "3", "3", "3",
        "--dtype", "float16",
        "--output-crop-margin", "2", "4", "4",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    assert "my-inference" in result.output  # custom timer key
    import h5py

    with h5py.File(out, "r") as f:
        key = [k for k in f if "voxel" not in k and "layer" not in k][0]
        # 16,48,48 minus 2*(2,4,4) crop
        assert f[key].shape == (1, 12, 40, 40)


def test_inference_patch_num_mismatch_errors(runner):
    result = runner.invoke(main, [
        "create-chunk", "-s", "16", "48", "48",
        "inference", "-s", "8", "24", "24", "-v", "2", "8", "8",
        "-c", "1", "-f", "identity", "--patch-num", "2", "2", "2",
        "--no-crop-output-margin",
    ])
    assert result.exit_code != 0
    assert "decomposes into (3, 3, 3)" in result.output


def test_generate_tasks_reference_forms(runner, tmp_path):
    """Reference generate-tasks forms (flow/flow.py:73-183): roi from a
    volume's metadata (-v, with block-size snapping), a canonical
    bounding-box string (-b), and --roi-size with --bounded."""
    pytest.importorskip("tensorstore")
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "vol"
    PrecomputedVolume.create(
        str(root), volume_size=(32, 64, 64), dtype="uint8",
        voxel_size=(40, 4, 4), block_size=(16, 32, 32),
        voxel_offset=(8, 16, 16),
    )
    tf = tmp_path / "tasks.txt"
    result = runner.invoke(main, [
        "generate-tasks", "-v", str(root), "-c", "16", "32", "32",
        "--task-file", str(tf),
    ])
    assert result.exit_code == 0, result.output
    tasks = tf.read_text().split()
    # roi (8,16,16)-(40,80,80) snapped to (16,32,32) blocks ANCHORED at the
    # volume's voxel_offset (storage blocks start there) -> exact 2^3 grid
    assert len(tasks) == 8 and tasks[0] == "8-24_16-48_16-48"

    result = runner.invoke(main, [
        "generate-tasks", "-b", "0-32_0-64_0-64", "-c", "16", "32", "32",
        "--task-file", str(tf),
    ])
    assert result.exit_code == 0, result.output
    assert len(tf.read_text().split()) == 8

    result = runner.invoke(main, [
        "generate-tasks", "-s", "0", "0", "0", "-z", "20", "40", "40",
        "-c", "16", "32", "32", "--bounded", "--task-file", str(tf),
    ])
    assert result.exit_code == 0, result.output
    # bounded: nothing spills past the roi stop
    assert all(
        int(s.split("_")[0].split("-")[1]) <= 20 for s in tf.read_text().split()
    )


def test_load_save_precomputed_reference_options(runner, tmp_path):
    """--chunk-start/--chunk-size explicit boxes on load;
    --intensity-threshold save skip (reference flow.py:1185-1191,
    :2286-2309)."""
    pytest.importorskip("tensorstore")
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "vol"
    PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(40, 4, 4), block_size=(8, 8, 8),
    )
    out = tmp_path / "o.h5"
    # write constant-1 data, then explicit-box load without any task bbox
    result = runner.invoke(main, [
        "create-chunk", "-s", "8", "16", "16", "--pattern", "sin",
        "save-precomputed", "-v", str(root), "--intensity-threshold", "300",
    ])
    assert result.exit_code == 0, result.output
    assert "skip save" in result.output  # uint8 max < 300

    result = runner.invoke(main, [
        "create-chunk", "-s", "8", "16", "16", "--pattern", "sin",
        "save-precomputed", "-v", str(root), "--intensity-threshold", "10",
    ])
    assert result.exit_code == 0, result.output
    assert "skip save" not in result.output

    result = runner.invoke(main, [
        "load-precomputed", "-v", str(root),
        "--chunk-start", "0", "0", "8", "--chunk-size", "8", "16", "8",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    import h5py

    with h5py.File(out, "r") as f:
        key = [k for k in f if "voxel" not in k and "layer" not in k][0]
        assert f[key].shape[-3:] == (8, 16, 8)


def test_intensity_threshold_rescales_for_uint8(runner, tmp_path):
    """Thresholds tuned for [0,1] float probabilities keep working when
    the chunk is uint8 (0-255): values <= 1.0 are rescaled by 255,
    loudly. Without the rescale a 0.99 threshold would never skip —
    every nonzero uint8 chunk has max >= 1."""
    pytest.importorskip("tensorstore")
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "vol"
    PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(40, 4, 4), block_size=(8, 8, 8),
    )
    # this sin chunk peaks at 250: rescaled 0.9 -> 229.5 < 250 -> saves
    result = runner.invoke(main, [
        "create-chunk", "-s", "8", "16", "16", "--pattern", "sin",
        "save-precomputed", "-v", str(root), "--intensity-threshold", "0.9",
    ])
    assert result.exit_code == 0, result.output
    assert "rescaled to 229.5" in result.output
    assert "skip save" not in result.output

    # all-zero chunk: rescaled 0.5 -> 127.5 > 0 -> skips
    result = runner.invoke(main, [
        "create-chunk", "-s", "8", "16", "16", "--pattern", "zero",
        "save-precomputed", "-v", str(root), "--intensity-threshold", "0.5",
    ])
    assert result.exit_code == 0, result.output
    assert "skip save" in result.output

    # exactly 1.0 is an ABSOLUTE threshold (ADVICE r3): skip only
    # all-zero uint8 chunks, do not reinterpret as 255
    result = runner.invoke(main, [
        "create-chunk", "-s", "8", "16", "16", "--pattern", "sin",
        "save-precomputed", "-v", str(root), "--intensity-threshold", "1.0",
    ])
    assert result.exit_code == 0, result.output
    assert "rescaled" not in result.output
    assert "skip save" not in result.output  # sin peaks at 250 >= 1.0


def test_downsample_upload_chunk_mip_semantics(runner, tmp_path):
    """Pyramid levels count from --chunk-mip; --start-mip at or below the
    chunk mip fails fast (reference downsample_upload.py asserts
    start_mip > chunk_mip)."""
    pytest.importorskip("tensorstore")
    import json

    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "vol"
    PrecomputedVolume.create(
        str(root), volume_size=(8, 32, 32), dtype="uint8",
        voxel_size=(40, 4, 4), block_size=(8, 8, 8), num_mips=3,
        downsample_factor=(1, 2, 2),
    )
    result = runner.invoke(main, [
        "generate-tasks", "-c", "8", "32", "32", "--roi-stop", "8", "32", "32",
        "create-chunk", "-s", "8", "32", "32", "--pattern", "sin",
        "downsample-upload", "-v", str(root), "--factor", "1", "2", "2",
    ])
    assert result.exit_code == 0, result.output
    vol = PrecomputedVolume(str(root))
    # levels 1 and 2 written, shapes halved in yx
    assert np.asarray(vol.cutout(vol.bounds(1), mip=1).array).shape[-2:] == (16, 16)
    assert np.asarray(vol.cutout(vol.bounds(2), mip=2).array).shape[-2:] == (8, 8)

    result = runner.invoke(main, [
        "create-chunk", "-s", "8", "32", "32",
        "downsample-upload", "-v", str(root), "--chunk-mip", "1",
        "--start-mip", "1",
    ])
    assert result.exit_code != 0
    assert "must be above the chunk mip" in str(result.output) + str(result.exception)


def test_load_precomputed_task_bbox_wins_over_explicit(runner, tmp_path):
    """Reference precedence (flow.py:1228-1243): the task's own bbox wins;
    --chunk-start/--chunk-size is the no-task fallback, and a lone
    --chunk-size defaults its start from the volume bounds."""
    pytest.importorskip("tensorstore")
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "vol"
    PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(40, 4, 4), block_size=(8, 8, 8),
    )
    out = tmp_path / "o.h5"
    # task bbox (from generate-tasks) wins over the explicit box
    result = runner.invoke(main, [
        "generate-tasks", "-c", "8", "8", "8", "--roi-start", "0", "8", "8",
        "--roi-stop", "8", "16", "16",
        "load-precomputed", "-v", str(root),
        "--chunk-start", "0", "0", "0", "--chunk-size", "8", "16", "16",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    import h5py

    with h5py.File(out, "r") as f:
        key = [k for k in f if "voxel" not in k and "layer" not in k][0]
        assert f[key].shape[-3:] == (8, 8, 8)  # the task's box, not the explicit one

    # lone --chunk-size: start defaults from the volume bounds
    result = runner.invoke(main, [
        "load-precomputed", "-v", str(root), "--chunk-size", "8", "16", "8",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    with h5py.File(out, "r") as f:
        key = [k for k in f if "voxel" not in k and "layer" not in k][0]
        assert f[key].shape[-3:] == (8, 16, 8)


def test_inference_async_depth_pipelines_tasks(runner, tmp_path):
    """--async-depth N holds dispatched tasks in flight and yields them
    in order with identical results to the synchronous path (identity
    oracle per task)."""
    import h5py

    outs = [tmp_path / f"o{i}.h5" for i in range(2)]
    for depth, out in (("1", outs[0]), ("2", outs[1])):
        result = runner.invoke(main, [
            "generate-tasks", "-c", "16", "48", "48",
            "--roi-stop", "16", "96", "48",
            "create-chunk", "--size", "16", "48", "48", "--pattern", "sin",
            "inference", "-s", "8", "24", "24", "-v", "2", "8", "8",
            "-c", "1", "-f", "identity", "--no-crop-output-margin",
            "--async-depth", depth,
            "save-h5", "--file-name", str(out),
        ])
        assert result.exit_code == 0, result.output
    # both runs write the (same) last task's chunk; results must agree
    with h5py.File(outs[0], "r") as a, h5py.File(outs[1], "r") as b:
        key = [k for k in a if "voxel" not in k and "layer" not in k][0]
        np.testing.assert_allclose(a[key][:], b[key][:], atol=1e-6)


def test_inference_output_dtype_bfloat16(runner, tmp_path):
    import h5py

    out = tmp_path / "bf16.h5"
    result = runner.invoke(main, [
        "create-chunk", "-s", "16", "48", "48", "--pattern", "sin",
        "inference", "-s", "8", "24", "24", "-v", "2", "8", "8",
        "-c", "1", "-f", "identity", "--no-crop-output-margin",
        "--output-dtype", "bfloat16",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    with h5py.File(out, "r") as f:
        key = [k for k in f if "voxel" not in k and "layer" not in k][0]
        arr = f[key][:]
    assert arr.shape == (1, 16, 48, 48)
    # h5 has no bfloat16: the writer must store a readable float, not
    # opaque |V2 bytes
    assert arr.dtype.kind == "f", arr.dtype
    from chunkflow_tpu.chunk.base import Chunk

    # identity oracle: uint8 input normalizes to [0,1] inside inference
    ref = np.asarray(Chunk.create(size=(16, 48, 48), pattern="sin").array)
    np.testing.assert_allclose(arr[0], ref / 255.0, atol=0.01)


def test_inference_async_depth_preserves_task_output_pairing(
        runner, tmp_path):
    """Distinct random inputs per task, loaded via <prefix><bbox>.h5 and
    saved the same way: a pipelining bug that swapped, dropped, or
    duplicated the (task, in-flight output) pairing would mismatch a
    per-task identity oracle on DISTINCT data (unlike same-data smoke
    tests, which cannot see a swap)."""
    import h5py

    in_dir = tmp_path / "in"
    out_dir = tmp_path / "out"
    in_dir.mkdir()
    out_dir.mkdir()
    rng = np.random.default_rng(5)
    offsets = [(0, 0, 0), (0, 48, 0)]
    inputs = {}
    for off in offsets:
        c = Chunk(
            rng.random((16, 48, 48)).astype(np.float32), voxel_offset=off)
        c.to_h5(str(in_dir) + "/")
        inputs[off] = np.asarray(c.array)
    result = runner.invoke(main, [
        "generate-tasks", "-c", "16", "48", "48",
        "--roi-stop", "16", "96", "48",
        "load-h5", "-f", str(in_dir) + "/",
        "inference", "-s", "8", "24", "24", "-v", "2", "8", "8",
        "-c", "1", "-f", "identity", "--no-crop-output-margin",
        "--async-depth", "2",
        "save-h5", "--file-name", str(out_dir) + "/",
    ])
    assert result.exit_code == 0, result.output
    outs = sorted(out_dir.iterdir())
    assert len(outs) == 2, [p.name for p in outs]
    for path in outs:
        with h5py.File(path, "r") as f:
            arr = f["main"][:]
            off = tuple(int(v) for v in f["voxel_offset"][:])
        np.testing.assert_allclose(
            arr[0], inputs[off], atol=1e-5,
            err_msg=f"task at offset {off} got another task's output")


def test_inference_async_depth_with_explicit_crop(runner, tmp_path):
    """--async-depth + --output-crop-margin crops ON DEVICE before the
    async copy; results must match the synchronous cropped path."""
    import h5py

    outs = [tmp_path / f"c{i}.h5" for i in range(2)]
    for depth, out in (("1", outs[0]), ("2", outs[1])):
        result = runner.invoke(main, [
            "create-chunk", "-s", "16", "48", "48", "--pattern", "sin",
            "inference", "-s", "8", "24", "24", "-v", "2", "8", "8",
            "-c", "1", "-f", "identity",
            "--output-crop-margin", "2", "4", "4",
            "--async-depth", depth,
            "save-h5", "--file-name", str(out),
        ])
        assert result.exit_code == 0, result.output
    with h5py.File(outs[0], "r") as a, h5py.File(outs[1], "r") as b:
        key = [k for k in a if "voxel" not in k and "layer" not in k][0]
        assert a[key].shape == (1, 12, 40, 40)
        np.testing.assert_allclose(a[key][:], b[key][:], atol=1e-6)
        # cropped offset must be preserved through the async path
        np.testing.assert_array_equal(
            a["voxel_offset"][:], b["voxel_offset"][:])


def test_save_precomputed_async_write_pipeline(runner, tmp_path):
    """--async-write: futures drain at the pipeline-end barrier, and the
    stored bytes match a sync run."""
    pytest.importorskip("tensorstore")
    import numpy as np

    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    roots = []
    for mode in ("--sync-write", "--async-write"):
        root = tmp_path / f"vol{mode}"
        PrecomputedVolume.create(
            str(root), volume_size=(8, 16, 16), dtype="uint8",
            voxel_size=(1, 1, 1), block_size=(8, 8, 8),
        )
        result = runner.invoke(main, [
            "generate-tasks", "-c", "8", "16", "16",
            "--roi-stop", "8", "16", "16",
            "create-chunk", "--size", "8", "16", "16", "--pattern", "sin",
            "save-precomputed", "-v", str(root), mode,
        ])
        assert result.exit_code == 0, result.output
        roots.append(root)
    from chunkflow_tpu.core.bbox import BoundingBox as BB

    a = PrecomputedVolume(str(roots[0])).cutout(
        BB.from_delta((0, 0, 0), (8, 16, 16)))
    b = PrecomputedVolume(str(roots[1])).cutout(
        BB.from_delta((0, 0, 0), (8, 16, 16)))
    np.testing.assert_array_equal(np.asarray(a.array), np.asarray(b.array))
    assert np.asarray(b.array).any()


def test_async_write_drained_before_queue_ack(runner, tmp_path):
    pytest.importorskip("tensorstore")
    import numpy as np

    from chunkflow_tpu.parallel.queues import open_queue
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "qvol"
    PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    qdir = str(tmp_path / "queue")
    run_ok(runner, [
        "generate-tasks", "-c", "8", "16", "16",
        "--roi-stop", "8", "16", "16", "--queue-name", qdir,
    ])
    run_ok(runner, [
        "fetch-task-from-queue", "-q", qdir,
        "create-chunk", "--size", "8", "16", "16", "--pattern", "sin",
        "save-precomputed", "-v", str(root), "--async-write",
        "delete-task-in-queue",
    ])
    assert len(open_queue(qdir)) == 0  # acked
    from chunkflow_tpu.core.bbox import BoundingBox as BB

    out = PrecomputedVolume(str(root)).cutout(
        BB.from_delta((0, 0, 0), (8, 16, 16)))
    assert np.asarray(out.array).any()  # durable before/at ack


def test_async_write_drained_when_task_skipped(runner, tmp_path):
    """A downstream skip (task -> None) must not abandon async write
    futures: the operator wrapper drains them."""
    pytest.importorskip("tensorstore")
    from chunkflow_tpu.core.bbox import BoundingBox as BB
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    root = tmp_path / "skipvol"
    PrecomputedVolume.create(
        str(root), volume_size=(8, 16, 16), dtype="uint8",
        voxel_size=(1, 1, 1), block_size=(8, 8, 8),
    )
    # save async, then delete the chunk and skip-none nulls the task
    run_ok(runner, [
        "generate-tasks", "-c", "8", "16", "16",
        "--roi-stop", "8", "16", "16",
        "create-chunk", "--size", "8", "16", "16", "--pattern", "sin",
        "save-precomputed", "-v", str(root), "--async-write",
        "delete-var", "-v", "chunk",
        "skip-none",
    ])
    out = PrecomputedVolume(str(root)).cutout(
        BB.from_delta((0, 0, 0), (8, 16, 16)))
    assert np.asarray(out.array).any()
