"""Capacity planner (parity: reference flow/setup_env.py semantics)."""
import json
import os

import numpy as np
import pytest
from click.testing import CliRunner

from chunkflow_tpu.flow.setup_env import get_optimized_block_size, setup_environment


def test_optimized_block_size_divisibility():
    patch_num, out_chunk, in_chunk, block, factor = get_optimized_block_size(
        output_patch_size=(16, 192, 192),
        output_patch_overlap=(2, 32, 32),
        max_ram_size=15.0,
        channel_num=3,
        max_mip=5,
        crop_chunk_margin=(2, 32, 32),
        input_patch_size=(20, 256, 256),
        mip=0,
        thumbnail_mip=6,
    )
    # xy divisible by 2**max_mip after margin removal
    assert out_chunk[1] % 2 ** 5 == 0
    assert out_chunk[2] % 2 ** 5 == 0
    assert factor == 1
    # output buffer fits in half of 15 GB at float32 x 3 channels
    ram = np.prod(out_chunk) * 4 * 3 / 1e9
    assert ram <= 15.0 * 0.75, f"output buffer {ram} GB blows the budget"
    # input chunk = output chunk + 2*margin + (in_patch - out_patch)
    assert in_chunk[0] == out_chunk[0] + 4 + 4
    assert in_chunk[1] == out_chunk[1] + 64 + 64


def test_optimized_block_size_infeasible_raises():
    with pytest.raises(ValueError):
        get_optimized_block_size(
            output_patch_size=(16, 13, 13),   # xy stride 13, odd prime
            output_patch_overlap=(2, 0, 0),
            max_ram_size=0.001,
            channel_num=1,
            max_mip=10,                        # 1024-divisibility: impossible
            crop_chunk_margin=(0, 0, 0),
            input_patch_size=(16, 13, 13),
            mip=0,
            thumbnail_mip=6,
        )


def test_setup_environment_creates_infos_and_tasks(tmp_path):
    volume_path = str(tmp_path / "vol")
    plan = setup_environment(
        dry_run=False,
        volume_start=(0, 0, 0),
        volume_stop=None,
        volume_size=(128, 2048, 2048),
        volume_path=volume_path,
        max_ram_size=2.0,
        output_patch_size=(16, 192, 192),
        input_patch_size=(20, 256, 256),
        channel_num=3,
        dtype="float32",
        output_patch_overlap=(2, 32, 32),
        crop_chunk_margin=(2, 32, 32),
        mip=0,
        thumbnail_mip=6,
        max_mip=5,
        thumbnail=True,
        encoding="raw",
        voxel_size=(40, 4, 4),
        overwrite_info=True,
    )
    assert os.path.exists(os.path.join(volume_path, "info"))
    assert os.path.exists(os.path.join(volume_path, "thumbnail", "info"))
    with open(os.path.join(volume_path, "info")) as f:
        info = json.load(f)
    assert info["num_channels"] == 3
    assert len(plan.bboxes) > 0
    # every task chunk is the planned output chunk size
    first = plan.bboxes[0]
    assert tuple(first.shape) == tuple(plan.output_chunk_size)


def test_setup_env_cli_dry_run(tmp_path):
    from chunkflow_tpu.flow.cli import main

    runner = CliRunner()
    result = runner.invoke(
        main,
        [
            "--dry-run",
            "setup-env",
            "--volume-start", "0", "0", "0",
            "--volume-size", "64", "1024", "1024",
            "-l", str(tmp_path / "v"),
            "-r", "1",
            "--output-patch-size", "16", "192", "192",
            "--input-patch-size", "20", "256", "256",
            "--output-patch-overlap", "2", "32", "32",
            "--crop-chunk-margin", "2", "32", "32",
            "skip-none",
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "--patch-num" in result.output
    assert not os.path.exists(str(tmp_path / "v"))


def test_fetch_task_from_file(tmp_path, monkeypatch):
    from chunkflow_tpu.core.bbox import BoundingBoxes
    from chunkflow_tpu.flow.cli import main

    boxes = BoundingBoxes.from_manual_setup(
        chunk_size=(8, 8, 8), roi_start=(0, 0, 0), roi_stop=(8, 16, 16)
    )
    task_file = str(tmp_path / "tasks.txt")
    boxes.to_file(task_file)

    monkeypatch.setenv("SLURM_ARRAY_TASK_ID", "1")
    out = str(tmp_path / "got.h5")
    runner = CliRunner()
    result = runner.invoke(
        main,
        [
            "fetch-task-from-file", "-f", task_file,
            "create-chunk", "--size", "8", "8", "8",
            "save-h5", "-f", out,
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert os.path.exists(out)


def test_setup_env_explicit_zero_overlap_respected(tmp_path):
    """--output-patch-overlap 0 0 0 must mean ZERO, not the half-patch
    default (regression: all-zero tuples were treated as unset)."""
    from chunkflow_tpu.flow.cli import main

    runner = CliRunner()
    result = runner.invoke(
        main,
        [
            "--dry-run",
            "setup-env",
            "--volume-start", "0", "0", "0",
            "--volume-size", "64", "1024", "1024",
            "-l", str(tmp_path / "v"),
            "-r", "1",
            "--output-patch-size", "16", "192", "192",
            "--output-patch-overlap", "0", "0", "0",
            "--crop-chunk-margin", "0", "0", "0",
            "skip-none",
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "--expand-margin-size 0 0 0" in result.output
