"""Viewer-path smoke tests against stubbed neuroglancer / napari modules.

The reference has no viewer tests at all; these exercise the full layer
dispatch (reference flow/neuroglancer.py:340-423) without a browser or the
real packages.
"""
import sys
import types

import numpy as np
import pytest

from chunkflow_tpu.annotations.point_cloud import PointCloud
from chunkflow_tpu.annotations.synapses import Synapses
from chunkflow_tpu.chunk.base import Chunk, LayerType


class _Record:
    """Generic stand-in that just records its constructor kwargs."""

    def __init__(self, *args, **kwargs):
        self.args = args
        self.kwargs = kwargs


class _Layers:
    def __init__(self):
        self.entries = []

    def append(self, name=None, layer=None, **kwargs):
        assert name is not None and layer is not None
        self.entries.append({"name": name, "layer": layer, **kwargs})


class _Txn:
    def __init__(self):
        self.layers = _Layers()


@pytest.fixture
def stub_ng(monkeypatch):
    ng = types.ModuleType("neuroglancer")
    for cls in (
        "CoordinateSpace",
        "LocalVolume",
        "LocalAnnotationLayer",
        "AnnotationPropertySpec",
        "PointAnnotation",
        "LineAnnotation",
    ):
        setattr(ng, cls, type(cls, (_Record,), {}))
    monkeypatch.setitem(sys.modules, "neuroglancer", ng)
    return ng


def _chunk(layer_type, dtype=np.float32, nchan=None):
    shape = (4, 8, 8) if nchan is None else (nchan, 4, 8, 8)
    arr = np.arange(np.prod(shape), dtype=np.float64).reshape(shape)
    if np.issubdtype(np.dtype(dtype), np.integer):
        arr = arr.astype(dtype)
    else:
        arr = (arr / arr.max()).astype(dtype)
    return Chunk(arr, voxel_offset=(1, 2, 3), voxel_size=(40, 8, 8),
                 layer_type=layer_type)


def test_build_layers_every_chunk_type(stub_ng):
    from chunkflow_tpu.flow.viewers import build_layers

    txn = _Txn()
    n = build_layers(
        txn,
        {
            "img": _chunk(LayerType.IMAGE),
            "seg": _chunk(LayerType.SEGMENTATION, dtype=np.uint32),
            "aff": _chunk(LayerType.AFFINITY_MAP, nchan=3),
            "prob": _chunk(LayerType.PROBABILITY_MAP, nchan=1),
        },
    )
    assert n == 4
    by_name = {e["name"]: e for e in txn.layers.entries}
    assert set(by_name) == {"img", "seg", "aff", "prob"}
    # image gets the grayscale shader, affinity the multichannel shader
    assert "emitGrayscale" in by_name["img"]["shader"]
    assert "emitRGB" in by_name["aff"]["shader"]
    assert "getDataValue(0)" in by_name["prob"]["shader"]
    # segmentation layers carry no shader
    assert "shader" not in by_name["seg"]
    # data was transposed to xyz for neuroglancer
    assert by_name["img"]["layer"].kwargs["data"].shape == (8, 8, 4)


def test_build_layers_segmentation_dtypes(stub_ng):
    from chunkflow_tpu.flow.viewers import build_layers

    for dtype, expected in (
        (bool, np.uint32),  # bool -> uint8 -> uint32, as in the reference
        (np.int64, np.uint64),
        (np.uint8, np.uint32),
        (np.uint32, np.uint32),
    ):
        txn = _Txn()
        build_layers(
            txn, {"seg": _chunk(LayerType.SEGMENTATION, dtype=dtype)}
        )
        data = txn.layers.entries[0]["layer"].kwargs["data"]
        assert data.dtype == expected, dtype


def test_build_layers_annotations(stub_ng):
    from chunkflow_tpu.flow.viewers import build_layers

    pre = np.array([[1, 2, 3], [4, 5, 6]], dtype=np.int32)
    post = np.array([[0, 1, 2, 4], [1, 4, 6, 7]], dtype=np.int32)
    syn = Synapses(pre, post, resolution=(40, 8, 8))
    points = PointCloud(np.array([[0, 1, 2]]), voxel_size=(40, 8, 8))

    class _Skel:
        vertices = np.array([[0.0, 1.0, 2.0], [3.0, 4.0, 5.0]])
        edges = np.array([[0, 1]])

    txn = _Txn()
    n = build_layers(
        txn,
        {
            "syn": syn,
            "pts": points,
            "raw_pts": np.array([[7, 8, 9]]),
            "skel": {42: _Skel()},
        },
    )
    assert n == 4
    names = [e["name"] for e in txn.layers.entries]
    # synapses produce a line layer plus a <name>_pre T-bar point layer
    assert "syn" in names and "syn_pre" in names
    assert "pts" in names and "raw_pts" in names and "skel" in names
    syn_layer = next(e for e in txn.layers.entries if e["name"] == "syn")
    lines = syn_layer["layer"].kwargs["annotations"]
    assert len(lines) == 2
    # physical (nm) coordinates, xyz order: pre[0]=(1,2,3)*res -> (24,16,40)
    assert lines[0].kwargs["pointA"] == [24.0, 16.0, 40.0]


def test_build_layers_skips_none_and_rejects_unknown(stub_ng):
    from chunkflow_tpu.flow.viewers import build_layers

    txn = _Txn()
    assert build_layers(txn, {"x": None}) == 0
    with pytest.raises(ValueError, match="cannot render"):
        build_layers(txn, {"bad": object()})
    # an empty skeleton dict renders an empty annotation layer, not a crash
    txn = _Txn()
    assert build_layers(txn, {"skel": {}}) == 1
    assert txn.layers.entries[0]["layer"].kwargs["annotations"] == []


def test_napari_layer_dispatch():
    from chunkflow_tpu.flow.viewers import add_napari_layers

    calls = []

    class _Viewer:
        def add_labels(self, arr, name=None):
            calls.append(("labels", name))

        def add_image(self, arr, name=None):
            calls.append(("image", name))

    n = add_napari_layers(
        _Viewer(),
        {
            "seg": _chunk(LayerType.SEGMENTATION, dtype=np.uint32),
            "img": _chunk(LayerType.IMAGE),
            "none": None,
        },
    )
    assert n == 2
    assert ("labels", "seg") in calls and ("image", "img") in calls


def test_neuroglancer_cli_command(stub_ng, monkeypatch, tmp_path):
    """The CLI command path up to serve_neuroglancer with a stubbed server."""
    served = {}

    class _Viewer:
        def txn(self):
            import contextlib

            @contextlib.contextmanager
            def cm():
                yield _Txn()

            return cm()

        def get_viewer_url(self):
            return "http://stub"

    stub_ng.set_server_bind_address = lambda **kw: served.update(kw)
    stub_ng.Viewer = _Viewer

    from chunkflow_tpu.flow.viewers import serve_neuroglancer

    serve_neuroglancer(
        {"img": _chunk(LayerType.IMAGE)}, port=0, blocking=False
    )
    assert served["bind_address"] == "0.0.0.0"
