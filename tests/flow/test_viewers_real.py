"""Real-viewer layer construction (VERDICT r2 item 8).

The stub tests in test_viewers.py exercise the dispatch logic; this module
runs the SAME build_layers path against the real ``neuroglancer`` /
``napari`` packages when they are importable. Neither ships in this image
and installs are not possible here, so the tests gate with importorskip —
in an environment with the viewers installed (e.g. the reference's own
deployment image) they run as genuine layer-construction smoke tests
against reference flow/neuroglancer.py:212-320 semantics.
"""
import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk, LayerType


def _datas():
    img = Chunk(
        (np.random.default_rng(0).random((4, 16, 16)) * 255).astype(np.uint8),
        voxel_offset=(0, 0, 0),
        voxel_size=(40, 8, 8),
    )
    img.layer_type = LayerType.IMAGE
    seg = Chunk(
        np.arange(4 * 16 * 16, dtype=np.uint32).reshape(4, 16, 16) % 7,
        voxel_offset=(0, 0, 0),
        voxel_size=(40, 8, 8),
    )
    seg.layer_type = LayerType.SEGMENTATION
    return {"img": img, "seg": seg}


def test_real_neuroglancer_layer_construction():
    ng = pytest.importorskip("neuroglancer")

    from chunkflow_tpu.flow.viewers import build_layers

    viewer = ng.Viewer()
    with viewer.txn() as txn:
        n = build_layers(txn, _datas())
    assert n == 2
    state = viewer.state
    assert {layer.name for layer in state.layers} == {"img", "seg"}


def test_real_napari_layer_construction():
    napari = pytest.importorskip("napari")

    from chunkflow_tpu.flow.viewers import add_napari_layers

    try:
        viewer = napari.Viewer(show=False)
    except Exception as e:  # headless box: Qt platform plugin missing
        pytest.skip(f"napari importable but no display backend: {e}")
    try:
        n = add_napari_layers(viewer, _datas())
        assert n == 2
        assert len(viewer.layers) == 2
    finally:
        viewer.close()
