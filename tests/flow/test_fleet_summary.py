"""Fleet aggregation (ISSUE 6): per-worker summaries, trace timelines,
rotated-file loading, the fleet-status CLI, device-memory gauges, and
the CloudWatch snapshot publisher.
"""
import json
import os

import pytest
from click.testing import CliRunner

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.flow.log_summary import (
    load_telemetry_dir,
    summarize_fleet,
    trace_timeline,
)


@pytest.fixture(autouse=True)
def clean_telemetry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def _write_events(path, events):
    with open(path, "w") as f:
        for event in events:
            f.write(json.dumps(event) + "\n")


def _two_worker_dir(tmp_path):
    """Synthesized two-worker stream: worker-a is drain-bound and
    retried a task; worker-b is load-bound and finished the trace."""
    a = [
        {"kind": "span", "name": "pipeline/drain", "t": 1.0, "dur_s": 3.0,
         "worker": "worker-a", "pid": 1},
        {"kind": "span", "name": "pipeline/compute", "t": 1.1, "dur_s": 1.0,
         "worker": "worker-a", "pid": 1},
        {"kind": "task", "name": "lifecycle/claimed", "t": 1.2,
         "worker": "worker-a", "trace_id": "t1", "body": "bbox-1"},
        {"kind": "task_retry", "name": "lifecycle/retry", "t": 1.3,
         "worker": "worker-a", "trace_id": "t1", "body": "bbox-1"},
        {"kind": "snapshot", "t": 2.0, "worker": "worker-a", "pid": 1,
         "counters": {"tasks/retried": 1, "tasks/committed": 4,
                      "compile_cache/builds": 1, "compile_cache/hits": 3},
         "gauges": {"device/bytes_in_use": 1048576.0}, "hists": {}},
    ]
    b = [
        {"kind": "span", "name": "scheduler/load", "t": 3.0, "dur_s": 5.0,
         "worker": "worker-b", "pid": 1},
        {"kind": "span", "name": "pipeline/compute", "t": 3.1, "dur_s": 1.0,
         "worker": "worker-b", "pid": 1},
        {"kind": "task", "name": "lifecycle/claimed", "t": 3.2,
         "worker": "worker-b", "trace_id": "t1", "body": "bbox-1"},
        {"kind": "task", "name": "lifecycle/committed", "t": 3.4,
         "worker": "worker-b", "trace_id": "t1", "body": "bbox-1"},
        {"kind": "snapshot", "t": 4.0, "worker": "worker-b", "pid": 1,
         "counters": {"tasks/committed": 5, "ledger/skips": 2},
         "gauges": {}, "hists": {}},
    ]
    _write_events(tmp_path / "telemetry-worker-a.jsonl", a)
    _write_events(tmp_path / "telemetry-worker-b.jsonl", b)
    return a + b


def test_summarize_fleet_per_worker(tmp_path):
    _two_worker_dir(tmp_path)
    fleet = summarize_fleet(load_telemetry_dir(str(tmp_path)))
    assert sorted(fleet) == ["worker-a", "worker-b"]
    a, b = fleet["worker-a"], fleet["worker-b"]
    assert a["dominant"] == "pipeline/drain"
    assert a["stall"]["pipeline/drain"]["share"] == pytest.approx(0.75)
    assert a["retries"] == 1 and a["committed"] == 4
    assert a["cache_hit_rate"] == pytest.approx(0.75)
    assert a["device_bytes_in_use"] == pytest.approx(1048576.0)
    assert b["dominant"] == "scheduler/load"
    assert b["retries"] == 0 and b["committed"] == 5
    assert b["ledger_skips"] == 2
    assert b["cache_hit_rate"] is None  # no cache traffic on b


def test_trace_timeline_merges_workers(tmp_path):
    events = _two_worker_dir(tmp_path)
    timeline = trace_timeline(events, "t1")
    assert [e["name"] for e in timeline] == [
        "lifecycle/claimed", "lifecycle/retry",
        "lifecycle/claimed", "lifecycle/committed",
    ]
    assert [e["worker"] for e in timeline] == [
        "worker-a", "worker-a", "worker-b", "worker-b",
    ]


def test_load_telemetry_dir_reads_rotations(tmp_path):
    """Rotated ``.jsonl.1`` files load, and before their live file so a
    worker's stream stays in order."""
    _write_events(tmp_path / "telemetry-w.jsonl.1",
                  [{"kind": "span", "name": "old", "t": 1.0, "dur_s": 1}])
    _write_events(tmp_path / "telemetry-w.jsonl",
                  [{"kind": "span", "name": "new", "t": 2.0, "dur_s": 1}])
    events = load_telemetry_dir(str(tmp_path))
    assert [e["name"] for e in events] == ["old", "new"]


def test_cli_fleet_and_trace_report(tmp_path):
    _two_worker_dir(tmp_path)
    from chunkflow_tpu.flow.cli import main

    result = CliRunner().invoke(
        main,
        ["log-summary", "--metrics-dir", str(tmp_path), "--fleet",
         "--trace-id", "t1"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "fleet: 2 worker(s)" in result.output
    assert "worker worker-a:" in result.output
    assert "retries=1" in result.output
    assert "dominant phase: pipeline/drain" in result.output
    assert "dominant phase: scheduler/load" in result.output
    assert "trace t1: 4 event(s)" in result.output
    assert "lifecycle/committed" in result.output


def test_cli_fleet_requires_metrics_dir(tmp_path):
    from chunkflow_tpu.flow.cli import main

    result = CliRunner().invoke(
        main, ["log-summary", "--log-dir", str(tmp_path), "--fleet"]
    )
    assert result.exit_code != 0
    assert "--fleet/--trace-id/--slo/--export-trace needs " \
        "--metrics-dir" in result.output


# ---------------------------------------------------------------------------
# fleet-status CLI
# ---------------------------------------------------------------------------
def test_fleet_status_against_seeded_file_queue(tmp_path):
    from chunkflow_tpu.flow.cli import main
    from chunkflow_tpu.parallel.queues import open_queue

    qdir = str(tmp_path / "q")
    queue = open_queue(qdir)
    queue.send_messages(["0-4_0-4_0-4", "4-8_0-4_0-4", "8-12_0-4_0-4"])
    handle, _ = queue.receive()  # one task in flight
    queue.dead_letter(handle, reason="poison")  # ...now dead-lettered
    queue.receive()  # a second in flight

    result = CliRunner().invoke(
        main, ["fleet-status", "-q", qdir], catch_exceptions=False
    )
    assert result.exit_code == 0, result.output
    assert "pending=1" in result.output
    assert "in-flight=1" in result.output
    assert "dead=1" in result.output
    assert "receives=1" in result.output
    assert "dead-letter tasks pending triage" in result.output


def test_fleet_status_samples_live_worker(tmp_path):
    from chunkflow_tpu.flow.cli import main
    from chunkflow_tpu.parallel.queues import open_queue
    from chunkflow_tpu.parallel.restapi import start_metrics_exporter

    qdir = str(tmp_path / "q")
    open_queue(qdir).send_messages(["0-4_0-4_0-4"])
    server = start_metrics_exporter(0, host="127.0.0.1")
    port = server.server_address[1]
    try:
        # NB: the CLI invocation resets the registry (one invocation =
        # one run), so the exporter serves zeroed counters here — the
        # counter round trip itself is covered in test_restapi.py; this
        # test pins the dashboard wiring: scrape, format, dead-endpoint
        # handling
        result = CliRunner().invoke(
            main,
            ["fleet-status", "-q", qdir,
             "-w", f"127.0.0.1:{port},127.0.0.1:1"],
            catch_exceptions=False,
        )
    finally:
        server.shutdown()
        server.server_close()
    assert result.exit_code == 0, result.output
    assert f"worker http://127.0.0.1:{port}:" in result.output
    assert "committed=0" in result.output
    assert "leases=0" in result.output
    assert telemetry.worker_id() in result.output
    # the dead endpoint renders as unreachable instead of crashing
    assert "worker http://127.0.0.1:1: unreachable" in result.output


# ---------------------------------------------------------------------------
# device-memory gauges (satellite: sampled at drain time)
# ---------------------------------------------------------------------------
def test_device_memory_gauges_sampled(monkeypatch):
    import jax

    from chunkflow_tpu.flow import scheduler

    class FakeDevice:
        def __init__(self, in_use, peak):
            self._stats = {"bytes_in_use": in_use,
                           "peak_bytes_in_use": peak}

        def memory_stats(self):
            return self._stats

    monkeypatch.setattr(
        jax, "local_devices",
        lambda: [FakeDevice(100, 150), FakeDevice(50, 60)],
    )
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_UNSUPPORTED", False)
    scheduler.sample_device_memory()
    snap = telemetry.snapshot()
    assert snap["gauges"]["device/bytes_in_use"] == 150
    assert snap["gauges"]["device/peak_bytes"] == 210


def test_device_memory_unsupported_backend_is_noop(monkeypatch):
    import jax

    from chunkflow_tpu.flow import scheduler

    class NoStats:
        def memory_stats(self):
            return None

    monkeypatch.setattr(jax, "local_devices", lambda: [NoStats()])
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_UNSUPPORTED", False)
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_FAILURES", 0)
    scheduler.sample_device_memory()
    assert "device/bytes_in_use" not in telemetry.snapshot()["gauges"]
    # the probe marked itself unsupported: later calls are free no-ops
    assert scheduler._DEVICE_MEM_UNSUPPORTED is True


def test_device_memory_per_chip_watermarks_and_headroom(monkeypatch):
    """ISSUE 18: per-chip bytes/peak/headroom gauges under the
    device/chip/<i>/* convention, plus device/hbm_headroom = the WORST
    chip's headroom (the distance to the next OOM)."""
    import jax

    from chunkflow_tpu.flow import scheduler

    class FakeDevice:
        def __init__(self, in_use, peak, limit):
            self._stats = {"bytes_in_use": in_use,
                           "peak_bytes_in_use": peak,
                           "bytes_limit": limit}

        def memory_stats(self):
            return self._stats

    monkeypatch.setattr(
        jax, "local_devices",
        lambda: [FakeDevice(100, 150, 1000), FakeDevice(700, 800, 1000)],
    )
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_UNSUPPORTED", False)
    scheduler.sample_device_memory()
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["device/chip/0/bytes_in_use"] == 100
    assert gauges["device/chip/1/bytes_in_use"] == 700
    assert gauges["device/chip/0/peak_bytes"] == 150
    assert gauges["device/chip/0/hbm_headroom"] == 900
    assert gauges["device/chip/1/hbm_headroom"] == 300
    # aggregates: sums, and headroom = the worst chip (chip 1)
    assert gauges["device/bytes_in_use"] == 800
    assert gauges["device/peak_bytes"] == 950
    assert gauges["device/hbm_headroom"] == 300


def test_device_memory_partial_results_stand(monkeypatch):
    """One chip failing to report must not blank the others — and a
    partial probe counts as a SUCCESS (no backoff latch)."""
    import jax

    from chunkflow_tpu.flow import scheduler

    class Good:
        def memory_stats(self):
            return {"bytes_in_use": 64, "peak_bytes_in_use": 64}

    class Flaky:
        def memory_stats(self):
            raise RuntimeError("transient runtime stutter")

    monkeypatch.setattr(jax, "local_devices", lambda: [Good(), Flaky()])
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_UNSUPPORTED", False)
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_FAILURES", 3)
    scheduler.sample_device_memory()
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["device/chip/0/bytes_in_use"] == 64
    assert "device/chip/1/bytes_in_use" not in gauges
    assert gauges["device/bytes_in_use"] == 64
    assert scheduler._DEVICE_MEM_UNSUPPORTED is False
    assert scheduler._DEVICE_MEM_FAILURES == 0


def test_device_memory_backoff_reprobes(monkeypatch):
    """ISSUE 18 satellite: a failed probe no longer latches the plane
    off for the process lifetime — it backs off (8 skips, doubling per
    consecutive failure up to CHUNKFLOW_DEVICE_MEM_REPROBE) and then
    re-probes, so a backend whose runtime stuttered once recovers."""
    import jax

    from chunkflow_tpu.flow import scheduler

    probes = []

    def failing_devices():
        probes.append("probe")
        raise RuntimeError("runtime not ready")

    monkeypatch.setattr(jax, "local_devices", failing_devices)
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_UNSUPPORTED", False)
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_FAILURES", 0)
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_SKIPS_LEFT", 0)
    scheduler.sample_device_memory()  # fails -> back off 8 drains
    assert len(probes) == 1
    assert scheduler._DEVICE_MEM_UNSUPPORTED is True
    for _ in range(8):
        scheduler.sample_device_memory()  # free no-ops, no probe
    assert len(probes) == 1
    scheduler.sample_device_memory()  # window drained: re-probe
    assert len(probes) == 2
    # second consecutive failure doubles the window
    assert scheduler._DEVICE_MEM_SKIPS_LEFT == 16

    class Healthy:
        def memory_stats(self):
            return {"bytes_in_use": 7, "peak_bytes_in_use": 7}

    monkeypatch.setattr(scheduler, "_DEVICE_MEM_SKIPS_LEFT", 0)
    monkeypatch.setattr(jax, "local_devices", lambda: [Healthy()])
    scheduler.sample_device_memory()  # recovery resets the backoff
    assert scheduler._DEVICE_MEM_UNSUPPORTED is False
    assert scheduler._DEVICE_MEM_FAILURES == 0
    assert telemetry.snapshot()["gauges"]["device/bytes_in_use"] == 7


def test_device_memory_backoff_window_is_capped(monkeypatch):
    from chunkflow_tpu.flow import scheduler

    monkeypatch.setenv("CHUNKFLOW_DEVICE_MEM_REPROBE", "10")
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_FAILURES", 6)
    scheduler._note_device_mem_failure()
    assert scheduler._DEVICE_MEM_SKIPS_LEFT == 10  # capped, not 512
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_UNSUPPORTED", False)
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_FAILURES", 0)
    monkeypatch.setattr(scheduler, "_DEVICE_MEM_SKIPS_LEFT", 0)


# ---------------------------------------------------------------------------
# CloudWatch snapshot publisher (satellite: registry, not just timers)
# ---------------------------------------------------------------------------
class FakeCloudWatch:
    def __init__(self):
        self.calls = []

    def put_metric_data(self, Namespace, MetricData):
        self.calls.append((Namespace, MetricData))


def test_cloud_watch_publishes_registry_snapshot():
    from chunkflow_tpu.plugins.aws import cloud_watch

    telemetry.inc("tasks/committed", 4)
    telemetry.inc("queue/receives", 9)
    telemetry.gauge("device/bytes_in_use", 2048)
    with telemetry.task_context(None):
        with telemetry.span("pipeline/drain"):
            pass
    client = FakeCloudWatch()
    cloud_watch.execute(log={"timer": {"inference": 1.5}}, client=client)
    assert client.calls
    data = [d for _, batch in client.calls for d in batch]
    by_name = {d["MetricName"]: d for d in data}
    assert by_name["tasks/committed"]["Value"] == 4
    assert by_name["tasks/committed"]["Unit"] == "Count"
    assert by_name["queue/receives"]["Value"] == 9
    assert by_name["device/bytes_in_use"]["Unit"] == "Bytes"
    assert by_name["pipeline/drain-total"]["Unit"] == "Seconds"
    # derived dominant-stall share rides along (the autoscaling signal)
    assert by_name["stall/dominant_share"]["Value"] == pytest.approx(1.0)
    # legacy timer dict still published for existing dashboards
    assert by_name["inference-time"]["Value"] == 1.5
    for d in data:
        assert d["Dimensions"] == [
            {"Name": "worker", "Value": telemetry.worker_id()}
        ]
    # CloudWatch caps batches at 20
    for _, batch in client.calls:
        assert len(batch) <= 20


def test_cloud_watch_fleet_gauges_ride_with_count_unit():
    """ISSUE 7: the fleet supervisor's counters/gauges flow to
    CloudWatch like every other subsystem, with sizing gauges as Count
    (a fleet-size alarm needs a sane unit, not None)."""
    from chunkflow_tpu.plugins.aws import cloud_watch

    telemetry.inc("fleet/spawns", 5)
    telemetry.inc("fleet/evictions", 1)
    telemetry.gauge("fleet/workers", 3)
    telemetry.gauge("fleet/target", 3)
    telemetry.gauge("fleet/pending", 17)
    client = FakeCloudWatch()
    cloud_watch.execute(client=client)
    data = [d for _, batch in client.calls for d in batch]
    by_name = {d["MetricName"]: d for d in data}
    assert by_name["fleet/spawns"]["Unit"] == "Count"
    assert by_name["fleet/workers"]["Unit"] == "Count"
    assert by_name["fleet/workers"]["Value"] == 3
    assert by_name["fleet/target"]["Unit"] == "Count"
    assert by_name["fleet/pending"]["Unit"] == "Count"
    assert by_name["fleet/pending"]["Value"] == 17


def test_log_summary_prints_fleet_block(tmp_path, capsys):
    """ISSUE 7: fleet/* counters get their own log-summary block."""
    from chunkflow_tpu.flow.log_summary import print_telemetry_summary

    telemetry.configure(str(tmp_path))
    telemetry.inc("fleet/spawns", 4)
    telemetry.inc("fleet/evictions", 1)
    telemetry.inc("fleet/drill_preemptions", 2)
    telemetry.gauge("fleet/workers", 2)
    telemetry.gauge("fleet/target", 2)
    telemetry.flush()
    agg = print_telemetry_summary(str(tmp_path))
    out = capsys.readouterr().out
    assert "fleet supervisor" in out
    assert "fleet/spawns" in out and "fleet/drill_preemptions" in out
    assert "final size: 2 worker(s), target 2" in out
    assert agg["counters"]["fleet/spawns"] == 4


def test_cloud_watch_batches_over_twenty():
    from chunkflow_tpu.plugins.aws import cloud_watch

    for i in range(25):
        telemetry.inc(f"c/{i}")
    client = FakeCloudWatch()
    cloud_watch.execute(client=client)
    assert len(client.calls) >= 2
    assert sum(len(batch) for _, batch in client.calls) >= 25


def test_cloud_watch_publishes_quantile_histograms_as_milliseconds():
    """ISSUE 12 satellite: the PR 9 quantile histograms (serving
    p50/p99) go out to CloudWatch as Milliseconds with the worker
    dimension, through the one shared bucket estimator."""
    from chunkflow_tpu.plugins.aws import cloud_watch

    for v in [0.004] * 50 + [0.02] * 40 + [0.8] * 10:
        telemetry.observe_quantile("serving/latency", v)
    client = FakeCloudWatch()
    cloud_watch.execute(client=client)
    data = [d for _, batch in client.calls for d in batch]
    by_name = {d["MetricName"]: d for d in data}
    p50 = by_name["serving/latency-p50"]
    p99 = by_name["serving/latency-p99"]
    assert p50["Unit"] == "Milliseconds"
    assert p99["Unit"] == "Milliseconds"
    # same estimator as /metrics and log-summary, in milliseconds
    assert p50["Value"] == pytest.approx(
        telemetry.quantile("serving/latency", 0.5) * 1000.0)
    assert 2.5 <= p50["Value"] <= 5.0      # (0.0025, 0.005] bucket
    assert 500.0 <= p99["Value"] <= 1000.0  # (0.5, 1.0] bucket
    for name in ("serving/latency-p50", "serving/latency-p99"):
        assert by_name[name]["Dimensions"] == [
            {"Name": "worker", "Value": telemetry.worker_id()}
        ]


def test_cloud_watch_skips_empty_quantile_histograms(monkeypatch):
    from chunkflow_tpu.plugins.aws import cloud_watch

    data = cloud_watch.snapshot_metric_data(
        snap={"counters": {}, "gauges": {}, "hists": {},
              "qhists": {"serving/latency": {"count": 0, "total": 0.0,
                                             "buckets": []}}})
    assert data == []


def test_fleet_status_prints_slo_firing(tmp_path, monkeypatch):
    """ISSUE 12: out-of-spec workers lead with their firing SLO
    objectives in fleet-status (scraped from chunkflow_slo_*_firing)."""
    from chunkflow_tpu.flow.cli import main
    from chunkflow_tpu.parallel import restapi
    from chunkflow_tpu.parallel.queues import open_queue

    def fake_scrape(endpoint, timeout=1.0):
        return {"endpoint": f"http://{endpoint}",
                "healthz": {"worker": "w1", "inflight_leases": 0},
                "metrics": {}, "dominant_stall": None, "serving": None,
                "slo_firing": ["availability", "latency"], "error": None}

    monkeypatch.setattr(restapi, "scrape_worker", fake_scrape)
    qdir = str(tmp_path / "q")
    open_queue(qdir).send_messages(["0-4_0-4_0-4"])
    result = CliRunner().invoke(
        main, ["fleet-status", "-q", qdir, "-w", "127.0.0.1:9"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert "SLO-FIRING: availability,latency" in result.output
