import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.flow.plugin import (
    find_plugin,
    load_plugin,
    str_to_dict,
    wrap_outputs,
)


def test_str_to_dict():
    assert str_to_dict(None) == {}
    assert str_to_dict("a=3;b=2.5;c=hello") == {"a": 3, "b": 2.5, "c": "hello"}
    assert str_to_dict("t=(1,2,3)") == {"t": (1, 2, 3)}
    assert str_to_dict("l=[1,2]") == {"l": [1, 2]}
    assert str_to_dict("flag=true") == {"flag": True}


def test_find_bundled_plugin():
    path = find_plugin("median_filter")
    assert path.endswith("median_filter.py")
    with pytest.raises(FileNotFoundError):
        find_plugin("no_such_plugin_xyz")


def test_load_and_run_bundled_plugins():
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.integers(0, 255, (4, 8, 8)).astype(np.uint8))

    inverse = load_plugin("inverse")
    out = inverse(chunk)
    np.testing.assert_array_equal(out, 255 - np.asarray(chunk.array))

    mapto01 = load_plugin("mapto01")
    out = mapto01(chunk)
    assert out.dtype == np.float32
    assert out.max() <= 1.0

    median = load_plugin("median_filter")
    out = median(chunk, size=3)
    assert out.shape == chunk.shape


def test_custom_plugin_dir(tmp_path, monkeypatch):
    plugin = tmp_path / "myplug.py"
    plugin.write_text("def execute(chunk, scale=2):\n    return chunk.array * scale\n")
    monkeypatch.setenv("CHUNKFLOW_PLUGIN_DIR", str(tmp_path))
    execute = load_plugin("myplug")
    chunk = Chunk(np.ones((2, 2, 2), dtype=np.float32))
    out = execute(chunk, scale=3)
    assert np.all(out == 3)


def test_wrap_outputs_symmetric_crop_fixup():
    chunk = Chunk(np.ones((8, 8, 8), dtype=np.float32), voxel_offset=(10, 10, 10))
    shrunk = np.ones((4, 4, 4), dtype=np.float32)
    wrapped = wrap_outputs(shrunk, [chunk])
    assert len(wrapped) == 1
    assert wrapped[0].voxel_offset.tuple == (12, 12, 12)

    same = wrap_outputs(np.ones((8, 8, 8), dtype=np.float32), [chunk])
    assert same[0].voxel_offset.tuple == (10, 10, 10)
    # non-array output passes through
    assert wrap_outputs("hello", [chunk]) == ["hello"]
