"""Unified adaptive pipeline scheduler (flow/scheduler.py, ISSUE 4):
the scheduled path must be a pure wall-time optimization — bit-identical
outputs, input order, same failure semantics as the serial and static
paths — with depth growth driven by the telemetry stall signal, bounded
by the host-memory watermark, and fully disabled by the
``CHUNKFLOW_SCHED=static`` kill switch."""
import time

import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.flow import scheduler
from chunkflow_tpu.flow.runtime import drain_pending_writes, new_task
from chunkflow_tpu.flow.scheduler import (
    DEFAULT_DEPTHS,
    DepthController,
    schedule_chunks,
    scheduled_inference_stage,
    scheduler_mode,
    write_behind_stage,
)
from chunkflow_tpu.inference import Inferencer


@pytest.fixture(autouse=True)
def clean_registry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    monkeypatch.delenv("CHUNKFLOW_SCHED", raising=False)
    monkeypatch.delenv("CHUNKFLOW_SCHED_MEM_GB", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


def _inferencer(**kwargs):
    defaults = dict(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    defaults.update(kwargs)
    return Inferencer(**defaults)


def _chunks(shapes, seed=0):
    rng = np.random.default_rng(seed)
    return [
        Chunk(rng.random(s).astype(np.float32), voxel_offset=(8 * i, 0, 0))
        for i, s in enumerate(shapes)
    ]


# mixed aligned + ragged-edge shapes: the regime where retrace/donation
# bugs hide (same fixture philosophy as test_pipeline_executor.py)
RAGGED_SHAPES = [(8, 32, 32), (5, 17, 18), (8, 32, 32), (7, 30, 20)]


def _task(chunk, i):
    task = new_task()
    task["chunk"] = chunk
    task["i"] = i
    return task


# ---------------------------------------------------------------------------
# bit-identical output contract
# ---------------------------------------------------------------------------
def test_schedule_chunks_bit_identical_to_serial_ragged():
    inferencer = _inferencer(shape_bucket=(8, 16, 16))
    chunks = _chunks(RAGGED_SHAPES)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    scheduled = list(schedule_chunks(inferencer, iter(chunks)))
    assert len(scheduled) == len(chunks)
    for src, ref, out in zip(chunks, serial, scheduled):
        assert not out.is_on_device
        assert tuple(out.voxel_offset) == tuple(src.voxel_offset)
        # bit-identical, not allclose: both paths run the SAME compiled
        # program; scheduling must not perturb a single ulp
        np.testing.assert_array_equal(np.asarray(out.array), ref)


def test_schedule_chunks_bit_identical_uint8_output():
    inferencer = _inferencer(output_dtype="uint8")
    chunks = _chunks(RAGGED_SHAPES, seed=3)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    scheduled = list(schedule_chunks(inferencer, iter(chunks)))
    for ref, out in zip(serial, scheduled):
        assert np.asarray(out.array).dtype == np.uint8
        np.testing.assert_array_equal(np.asarray(out.array), ref)


def test_stream_adaptive_vs_static_bit_identical(monkeypatch):
    """Inferencer.stream must yield byte-for-byte the same chunks whether
    it routes through the adaptive scheduler or (CHUNKFLOW_SCHED=static)
    the PR 2 double-buffered executor."""
    inferencer = _inferencer(shape_bucket=(8, 16, 16))
    chunks = _chunks(RAGGED_SHAPES, seed=5)
    adaptive = [np.asarray(o.array) for o in inferencer.stream(iter(chunks))]
    monkeypatch.setenv("CHUNKFLOW_SCHED", "static")
    assert scheduler_mode() == "static"
    static = [np.asarray(o.array) for o in inferencer.stream(iter(chunks))]
    for a, b in zip(adaptive, static):
        np.testing.assert_array_equal(a, b)


def test_stream_static_mode_bypasses_scheduler(monkeypatch):
    """The kill switch must remove the scheduler from the hot path
    entirely, not just pin its depths."""
    monkeypatch.setenv("CHUNKFLOW_SCHED", "static")

    def boom(*args, **kwargs):
        raise AssertionError("static mode must not touch schedule_chunks")

    monkeypatch.setattr(scheduler, "schedule_chunks", boom)
    inferencer = _inferencer()
    chunks = _chunks([(8, 32, 32)])
    out = list(inferencer.stream(iter(chunks)))
    assert len(out) == 1


# ---------------------------------------------------------------------------
# task-level stage: order, skip markers, failure semantics
# ---------------------------------------------------------------------------
def test_scheduled_stage_order_skip_markers_and_timers():
    inferencer = _inferencer()
    chunks = _chunks(RAGGED_SHAPES, seed=7)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    tasks = [_task(c, i) for i, c in enumerate(chunks)]
    tasks.insert(2, None)  # skip marker mid-stream
    stage = scheduled_inference_stage(inferencer, depth=2, op_name="inf")
    out = list(stage(iter(tasks)))
    assert [t["i"] if t else None for t in out] == [0, 1, None, 2, 3]
    for task in out:
        if task is None:
            continue
        np.testing.assert_array_equal(
            np.asarray(task["chunk"].array), serial[task["i"]]
        )
        assert not task["chunk"].is_on_device
        assert task["log"]["timer"]["inf"] >= 0
        assert task["log"]["compute_device"]


def test_scheduled_stage_flushes_dispatched_on_error():
    """Same contract as the static stage: a mid-stream failure must not
    drop tasks that were already dispatched."""
    inferencer = _inferencer()
    chunks = _chunks([(8, 32, 32)] * 3, seed=9)

    def check(chunk):
        if tuple(chunk.voxel_offset)[0] == 16:  # third task
            raise RuntimeError("bad grid")

    stage = scheduled_inference_stage(
        inferencer, depth=2, op_name="inf", check=check
    )
    got = []
    with pytest.raises(RuntimeError, match="bad grid"):
        for task in stage(iter(_task(c, i) for i, c in enumerate(chunks))):
            got.append(task["i"])
    assert got == [0, 1]


def test_scheduled_stage_failing_post_op_flushes_survivors():
    """A failing post op must not strand staged device buffers or other
    tasks' results: the surviving in-flight tasks flush downstream, then
    the post failure re-raises."""
    inferencer = _inferencer()
    chunks = _chunks([(8, 32, 32)] * 4, seed=11)

    def post(chunk):
        if tuple(chunk.voxel_offset)[0] == 8:  # second task's output
            raise RuntimeError("poisoned post")
        return chunk

    stage = scheduled_inference_stage(
        inferencer, depth=1, ring=1, op_name="inf", postprocess=post,
    )
    got = []
    with pytest.raises(RuntimeError, match="poisoned post"):
        for task in stage(iter(_task(c, i) for i, c in enumerate(chunks))):
            got.append(task["i"])
    # task 0 completed before the poison; tasks 2..3 were in flight when
    # the failure surfaced and must still come out (the synchronous path
    # would have finished them); task 1 is the failure itself
    assert 1 not in got
    assert got == sorted(got)
    assert 0 in got


def test_scheduled_stage_upstream_exception_propagates():
    inferencer = _inferencer()

    def source():
        yield _task(_chunks([(8, 32, 32)])[0], 0)
        raise RuntimeError("upstream boom")

    stage = scheduled_inference_stage(inferencer, depth=2, op_name="inf")
    got = []
    with pytest.raises(RuntimeError, match="upstream boom"):
        for task in stage(source()):
            got.append(task["i"])
    assert got == [0]


def test_scheduler_smoke_full_stage_chain():
    """Tier-1 smoke (ISSUE 4 satellite): 3 synthetic tasks through the
    FULL chain — source → scheduled inference (+post pool) → async write
    attach → write-behind — with order, results, and durable writes all
    checked."""
    from concurrent.futures import ThreadPoolExecutor

    inferencer = _inferencer()
    chunks = _chunks([(8, 32, 32)] * 3, seed=13)
    serial = [np.asarray(inferencer(c).array) for c in chunks]
    committed = []
    pool = ThreadPoolExecutor(max_workers=2)

    def source(stream):
        for _seed in stream:
            for i, c in enumerate(chunks):
                yield _task(c, i)

    def attach_write(stream):
        for task in stream:
            task.setdefault("pending_writes", []).append(
                pool.submit(lambda i=task["i"]: committed.append(i)))
            yield task

    stages = [
        source,
        scheduled_inference_stage(inferencer, depth=2, op_name="inf"),
        attach_write,
        write_behind_stage(window=1),
    ]
    stream = iter([new_task()])
    for s in stages:
        stream = s(stream)
    out = list(stream)
    assert [t["i"] for t in out] == [0, 1, 2]
    for task in out:
        assert not task.get("pending_writes")  # durable before yield
        np.testing.assert_array_equal(
            np.asarray(task["chunk"].array), serial[task["i"]]
        )
    assert sorted(committed) == [0, 1, 2]


# ---------------------------------------------------------------------------
# adaptive controller
# ---------------------------------------------------------------------------
def _drive(ctl, phase, n_tasks=10, stall_s=0.05):
    """Feed ``n_tasks`` synthetic tasks whose stall stream is dominated
    by ``phase`` through the real telemetry registry."""
    for _ in range(n_tasks):
        telemetry.observe(phase, stall_s)
        telemetry.observe("pipeline/compute", stall_s / 20)
        ctl.observe_task()


def test_controller_stage_dominant_raises_prefetch_within_10_tasks():
    ctl = DepthController(watermark_bytes=1 << 40)
    _drive(ctl, "pipeline/stage", n_tasks=10)
    assert ctl.depths["prefetch"] > DEFAULT_DEPTHS["prefetch"]
    assert ctl.changes, "controller never adapted"
    first_change_task = ctl.changes[0][0]
    assert first_change_task <= 10


def test_controller_load_dominant_raises_prefetch():
    ctl = DepthController(watermark_bytes=1 << 40)
    _drive(ctl, "scheduler/load", n_tasks=10)
    assert ctl.depths["prefetch"] > DEFAULT_DEPTHS["prefetch"]


def test_controller_drain_dominant_grows_write_pool():
    ctl = DepthController(watermark_bytes=1 << 40)
    _drive(ctl, "pipeline/drain", n_tasks=10)
    assert ctl.depths["write"] > DEFAULT_DEPTHS["write"]
    assert ctl.depths["post"] > DEFAULT_DEPTHS["post"]


def test_controller_compute_dominant_stands_pat():
    """Device-bound is the design goal: no knob to turn."""
    ctl = DepthController(watermark_bytes=1 << 40)
    _drive(ctl, "pipeline/compute", n_tasks=12)
    assert ctl.depths == ctl.initial
    assert not ctl.changes


def test_controller_balanced_stream_stands_pat():
    """No phase above min_share: depths are matched, nothing widens."""
    ctl = DepthController(watermark_bytes=1 << 40)
    for _ in range(12):
        for phase in ("pipeline/stage", "pipeline/compute",
                      "pipeline/drain", "scheduler/post"):
            telemetry.observe(phase, 0.01)
        ctl.observe_task()
    assert ctl.depths == ctl.initial


def test_controller_respects_memory_watermark():
    """Backpressure: under a tiny watermark no depth ever rises past the
    static initials — the documented graceful fallback."""
    ctl = DepthController(watermark_bytes=1024)
    ctl.note_slot_bytes(64 << 20)  # one 64 MB chunk seen
    _drive(ctl, "pipeline/stage", n_tasks=20)
    assert ctl.depths == ctl.initial
    assert not ctl.changes


def test_controller_env_watermark(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_SCHED_MEM_GB", "0.000001")
    ctl = DepthController()
    assert ctl.watermark_bytes == int(0.000001 * (1 << 30))
    ctl.note_slot_bytes(1 << 20)
    _drive(ctl, "pipeline/stage", n_tasks=8)
    assert ctl.depths == ctl.initial


def test_controller_respects_depth_ceilings():
    ctl = DepthController(interval=1, watermark_bytes=1 << 40)
    _drive(ctl, "pipeline/stage", n_tasks=50)
    assert ctl.depths["prefetch"] == ctl.limits["prefetch"]


def test_controller_static_when_telemetry_off(monkeypatch):
    """CHUNKFLOW_TELEMETRY=0 removes the stall signal; depths must stay
    static rather than adapt on garbage."""
    monkeypatch.setenv("CHUNKFLOW_TELEMETRY", "0")
    ctl = DepthController(watermark_bytes=1 << 40)
    _drive(ctl, "pipeline/stage", n_tasks=12)
    assert ctl.depths == ctl.initial


def test_controller_emits_depth_change_events(tmp_path):
    telemetry.configure(str(tmp_path))
    ctl = DepthController(watermark_bytes=1 << 40)
    _drive(ctl, "pipeline/stage", n_tasks=8)
    telemetry.flush()
    from chunkflow_tpu.flow.log_summary import (
        load_telemetry_dir,
        summarize_telemetry,
    )

    agg = summarize_telemetry(load_telemetry_dir(str(tmp_path)))
    assert agg["depth_changes"], "no depth_change events in the stream"
    change = agg["depth_changes"][0]
    assert change["name"] == "scheduler/prefetch"
    assert change["new"] == change["old"] + 1
    assert agg["gauges"]["scheduler/depth/prefetch"]["last"] >= change["new"]


def test_queue_capacity_widens_live():
    q = scheduler._AdaptiveQueue(1)
    assert q.put("a")
    q.set_capacity(3)
    assert q.put("b")
    assert q.put("c")
    assert [q.get(), q.get(), q.get()] == ["a", "b", "c"]
    q.close()
    assert not q.put("d")  # closed queue refuses new work


# ---------------------------------------------------------------------------
# write-behind + drain hardening
# ---------------------------------------------------------------------------
def test_drain_pending_writes_drains_every_future_and_reraises_first():
    """ISSUE 4 satellite: an exception mid-drain must not abandon the
    remaining futures — all drained, first error re-raised."""
    drained = []

    class _Write:
        def __init__(self, tag, exc=None):
            self.tag = tag
            self.exc = exc

        def result(self):
            drained.append(self.tag)
            if self.exc is not None:
                raise self.exc

    task = {"pending_writes": [
        _Write("w0"),
        _Write("w1", RuntimeError("first poison")),
        _Write("w2", ValueError("second poison")),
        _Write("w3"),
    ]}
    with pytest.raises(RuntimeError, match="first poison"):
        drain_pending_writes(task)
    assert drained == ["w0", "w1", "w2", "w3"]  # every future drained
    assert "pending_writes" not in task


def test_write_behind_overlaps_and_preserves_order():
    """With a window of 2, task k's commit must not block task k+1's
    arrival; tasks yield in order with writes durable."""
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=4)
    log = []

    def tasks():
        for i in range(5):
            t = new_task()
            t["i"] = i
            t["pending_writes"] = [pool.submit(time.sleep, 0.01)]
            log.append(("in", i))
            yield t

    out = []
    for task in write_behind_stage(window=2)(tasks()):
        log.append(("out", task["i"]))
        out.append(task["i"])
        assert not task.get("pending_writes")
    assert out == [0, 1, 2, 3, 4]
    # write-behind: tasks 0..2 all arrived (writes riding) before task
    # 0's commit was forced — the serial path would interleave strictly
    assert log.index(("out", 0)) > log.index(("in", 2))


def test_write_behind_passes_markers_and_unwritten_tasks_through():
    t0 = new_task()
    t0["i"] = 0
    out = list(write_behind_stage(window=2)(iter([t0, None])))
    assert out[0] is t0 and out[1] is None


def test_write_behind_drains_buffered_writes_on_downstream_abandon():
    """Closing the consumer mid-stream must still commit buffered writes
    (ack-after-durable-write holds on every exit path)."""
    committed = []

    class _Write:
        def __init__(self, i):
            self.i = i

        def result(self):
            committed.append(self.i)

    def tasks():
        for i in range(4):
            t = new_task()
            t["i"] = i
            t["pending_writes"] = [_Write(i)]
            yield t

    gen = write_behind_stage(window=3)(tasks())
    next(gen)  # pulls several tasks into the window
    gen.close()
    assert committed == sorted(committed)
    assert len(committed) >= 2  # the buffered tasks' writes committed


def test_write_behind_drains_remaining_on_upstream_error():
    committed = []

    class _Write:
        def __init__(self, i):
            self.i = i

        def result(self):
            committed.append(self.i)

    def tasks():
        for i in range(3):
            t = new_task()
            t["i"] = i
            t["pending_writes"] = [_Write(i)]
            yield t
        raise RuntimeError("upstream died")

    with pytest.raises(RuntimeError, match="upstream died"):
        list(write_behind_stage(window=8)(tasks()))
    assert sorted(committed) == [0, 1, 2]


def test_process_stream_adaptive_appends_write_behind(monkeypatch):
    """End-of-pipeline commit protocol under the adaptive default: tasks
    reach the drain barrier already durable, and static mode behaves
    identically from the outside."""
    from chunkflow_tpu.flow.runtime import process_stream

    for mode in ("adaptive", "static"):
        monkeypatch.setenv("CHUNKFLOW_SCHED", mode)
        committed = []

        class _Write:
            def result(self):
                committed.append(True)

        def source(stream):
            for _seed in stream:
                for _ in range(3):
                    t = new_task()
                    t["pending_writes"] = [_Write()]
                    yield t

        count = process_stream([source])
        assert count == 3, mode
        assert len(committed) == 3, mode


# ---------------------------------------------------------------------------
# CLI integration: static kill switch is the legacy composition
# ---------------------------------------------------------------------------
def test_cli_inference_static_vs_adaptive_bit_identical(monkeypatch, tmp_path):
    import h5py
    from click.testing import CliRunner

    from chunkflow_tpu.flow.cli import main

    runner = CliRunner()
    outs = {}
    for mode in ("adaptive", "static"):
        monkeypatch.setenv("CHUNKFLOW_SCHED", mode)
        out = tmp_path / f"{mode}.h5"
        result = runner.invoke(main, [
            "generate-tasks", "-c", "16", "48", "48",
            "--roi-stop", "16", "96", "48",
            "create-chunk", "--size", "16", "48", "48", "--pattern", "sin",
            "inference", "-s", "8", "24", "24", "-v", "2", "8", "8",
            "-c", "1", "-f", "identity", "--no-crop-output-margin",
            "--async-depth", "2", "--prefetch-depth", "2",
            "save-h5", "--file-name", str(out),
        ], catch_exceptions=False)
        assert result.exit_code == 0, result.output
        with h5py.File(out, "r") as f:
            key = [k for k in f if "voxel" not in k and "layer" not in k][0]
            outs[mode] = f[key][:]
    np.testing.assert_array_equal(outs["adaptive"], outs["static"])


def test_scheduler_mode_env_values(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_SCHED", raising=False)
    assert scheduler_mode() == "adaptive"
    for value in ("static", "0", "off", "STATIC"):
        monkeypatch.setenv("CHUNKFLOW_SCHED", value)
        assert scheduler_mode() == "static", value
    monkeypatch.setenv("CHUNKFLOW_SCHED", "adaptive")
    assert scheduler_mode() == "adaptive"


def test_mem_watermark_malformed_falls_back(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_SCHED_MEM_GB", "not-a-number")
    assert scheduler.mem_watermark_bytes() == 4 << 30


# ---------------------------------------------------------------------------
# lease-leak guard: tasks dropped during chain teardown are surrendered
# ---------------------------------------------------------------------------
class _FakeLifecycle:
    def __init__(self):
        self.surrenders = 0

    def surrender(self):
        self.surrenders += 1
        return "surrendered"


def test_pump_drop_and_close_surrender_claimed_tasks():
    """The chain-rebuild race (observed in the lifecycle chaos
    acceptance run): after a contained failure resolves the in-flight
    set, the prefetch pump can claim ONE more task before noticing the
    consumer closed, and tasks buffered in the handoff queue may have
    been claimed after the snapshot too. Both must be surrendered —
    dropped-on-the-floor claims leak their lease until the visibility
    timeout and lose the task for the run."""
    from chunkflow_tpu.flow.scheduler import _AdaptiveQueue, _pump

    buffered, in_hand, never_pulled = (
        _FakeLifecycle(), _FakeLifecycle(), _FakeLifecycle(),
    )
    q = _AdaptiveQueue(1)

    def source():
        yield {"lifecycle": buffered}    # fills the queue
        q.close()                        # consumer dies between pulls
        yield {"lifecycle": in_hand}     # put() refused -> surrender
        yield {"lifecycle": never_pulled}  # pump must have stopped

    _pump(iter(source()), q)
    assert buffered.surrenders == 1     # drained + surrendered at close
    assert in_hand.surrenders == 1      # refused put -> surrendered
    assert never_pulled.surrenders == 0  # never claimed, never touched


def test_prefetch_stage_surrenders_buffered_tasks_on_early_close():
    """Same guard for the static-path prefetch stage (runtime.py)."""
    from chunkflow_tpu.flow.runtime import prefetch_stage

    lcs = [_FakeLifecycle() for _ in range(4)]

    def source():
        for lc in lcs:
            yield {"lifecycle": lc, "log": {"timer": {}}}

    stage = prefetch_stage(depth=2)
    stream = stage(source())
    first = next(stream)        # one task delivered downstream
    stream.close()              # downstream dies; buffered tasks remain
    delivered = first["lifecycle"]
    assert delivered.surrenders == 0  # delivered tasks are NOT touched
    surrendered = sum(lc.surrenders for lc in lcs if lc is not delivered)
    # whatever the worker managed to buffer before close was handed back
    assert surrendered >= 1
    assert all(lc.surrenders <= 1 for lc in lcs)
