"""Pipeline stall attribution (ISSUE 3): a synthetic slow-stage /
slow-compute / slow-drain pipeline must attribute >80% of the injected
delay to the correct phase, telemetry-off runs must be bit-identical to
telemetry-on runs, and telemetry must cost ~nothing on the pipelined
path (the overhead gate)."""
import time

import numpy as np
import pytest

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.flow.pipeline import pipeline_chunks


@pytest.fixture(autouse=True)
def clean_registry(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_TELEMETRY", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


class FakeArray:
    """Mimics a jax array's drain-side surface: block_until_ready is the
    compute wait, nothing else is touched."""

    def __init__(self, compute_s):
        self.compute_s = compute_s

    def block_until_ready(self):
        time.sleep(self.compute_s)


class FakeOut:
    def __init__(self, payload, compute_s, drain_s):
        self.array = FakeArray(compute_s)
        self.payload = payload
        self.drain_s = drain_s

    def host(self):
        time.sleep(self.drain_s)
        return self.payload


class FakeInferencer:
    """Injects a controlled delay into exactly one pipeline phase."""

    def __init__(self, stage_s=0.0, compute_s=0.0, drain_s=0.0):
        self.stage_s = stage_s
        self.compute_s = compute_s
        self.drain_s = drain_s

    def stage(self, chunk):
        time.sleep(self.stage_s)
        return ("staged", chunk)  # distinct object -> pipeline-owned

    def infer_async(self, slot, crop=None, consume=False):
        _, chunk = slot
        return FakeOut(chunk, self.compute_s, self.drain_s)


N_CHUNKS = 5
DELAY_S = 0.03


def _run(inferencer):
    return list(pipeline_chunks(inferencer, list(range(N_CHUNKS)), ring=2))


def _phase_totals():
    hists = telemetry.snapshot()["hists"]
    return {
        phase: hists.get(f"pipeline/{phase}", {}).get("total", 0.0)
        for phase in ("stage", "dispatch", "compute", "drain")
    }


@pytest.mark.parametrize("slow_phase", ["stage", "compute", "drain"])
def test_injected_delay_lands_in_the_right_phase(slow_phase):
    injected = N_CHUNKS * DELAY_S
    inferencer = FakeInferencer(**{f"{slow_phase}_s": DELAY_S})
    out = _run(inferencer)
    assert out == list(range(N_CHUNKS))  # order preserved
    totals = _phase_totals()
    # >80% of the injected delay attributed to the right phase, and no
    # other phase absorbs a comparable share
    assert totals[slow_phase] >= 0.8 * injected, totals
    for phase, total in totals.items():
        if phase != slow_phase:
            assert total <= 0.2 * injected, totals


def test_ring_occupancy_gauge_recorded():
    _run(FakeInferencer())
    snap = telemetry.snapshot()
    occ = snap["hists"]["pipeline/ring_occupancy"]
    assert occ["count"] == N_CHUNKS
    assert 1 <= occ["max"] <= 2  # ring=2 bounds staged-ahead inputs
    assert snap["hists"]["pipeline/inflight"]["max"] <= 2


def test_summary_reports_drain_bound(tmp_path):
    """End to end: JSONL from a drain-bound run must say so."""
    from chunkflow_tpu.flow.log_summary import (
        load_telemetry_dir,
        summarize_telemetry,
    )

    telemetry.configure(str(tmp_path))
    _run(FakeInferencer(drain_s=DELAY_S))
    telemetry.flush()
    agg = summarize_telemetry(load_telemetry_dir(str(tmp_path)))
    stall = agg["stall"]
    assert stall["pipeline/drain"]["share"] > 0.5
    dominant = max(stall, key=lambda p: stall[p]["share"])
    assert dominant == "pipeline/drain"
    assert agg["gauges"]["pipeline/ring_occupancy"]["mean"] >= 1


def test_telemetry_off_run_is_bit_identical():
    """The real executor over the real identity engine: telemetry on vs
    off must produce byte-for-byte the same outputs (telemetry never
    touches data, only clocks)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    chunks = [
        Chunk(rng.random((8, 32, 32), dtype=np.float32)) for _ in range(3)
    ]

    def run_all():
        return [
            np.asarray(out.array)
            for out in pipeline_chunks(inferencer, iter(chunks), ring=2)
        ]

    on = run_all()
    import os

    os.environ["CHUNKFLOW_TELEMETRY"] = "0"
    try:
        off = run_all()
    finally:
        del os.environ["CHUNKFLOW_TELEMETRY"]
    for a, b in zip(on, off):
        assert np.array_equal(a, b)


def test_overhead_gate():
    """Telemetry-on wall time within noise of telemetry-off on a
    sleep-calibrated synthetic pipeline (the CPU-safe stand-in for the
    pipeline_overlap micro-benchmark; bench.py telemetry_overhead runs
    the real thing). 25% is a deliberately loose CI bound — the
    acceptance target of <2% is asserted on the calibrated benchmark,
    not on a shared test box."""
    import os

    def timed_run():
        t0 = time.perf_counter()
        _run(FakeInferencer(stage_s=0.01, compute_s=0.005, drain_s=0.005))
        return time.perf_counter() - t0

    timed_run()  # warm both paths
    on = min(timed_run() for _ in range(2))
    os.environ["CHUNKFLOW_TELEMETRY"] = "0"
    try:
        off = min(timed_run() for _ in range(2))
    finally:
        del os.environ["CHUNKFLOW_TELEMETRY"]
    assert on <= off * 1.25, (on, off)
