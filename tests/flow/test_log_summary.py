"""log-summary: per-device aggregation + Mvoxel/s (reference
flow/log_summary.py:57-75 semantics)."""
import json

import numpy as np
import pytest

from chunkflow_tpu.flow import log_summary


@pytest.fixture
def log_dir(tmp_path):
    d = tmp_path / "log"
    d.mkdir()
    # two tasks on one device, one on another; bbox-coded filenames
    specs = [
        ("0-8_0-16_0-16.json", "tpu:v5e", {"load": 1.0, "inference": 3.0}),
        ("8-16_0-16_0-16.json", "tpu:v5e", {"load": 2.0, "inference": 5.0}),
        ("16-24_0-16_0-16.json", "cpu:x86", {"load": 4.0, "inference": 16.0}),
    ]
    for name, device, timer in specs:
        (d / name).write_text(json.dumps({
            "timer": timer, "compute_device": device,
        }))
    return str(d)


def test_load_and_summarize(log_dir):
    records = log_summary.load_log_dir(log_dir)
    assert len(records) == 3
    assert all(r["_bbox"] is not None for r in records)

    frame = log_summary.summarize(records)
    # grouped by device: v5e mean total = (4 + 7) / 2 = 5.5; cpu total = 20
    v5e = frame.loc["tpu:v5e"]
    cpu = frame.loc["cpu:x86"]
    assert v5e[("_total", "mean")] == pytest.approx(5.5)
    assert cpu[("_total", "mean")] == pytest.approx(20.0)
    # Mvoxel/s = voxels / mean_seconds / 1e6; bbox voxels = 8*16*16 = 2048
    assert v5e[("_mvoxel_per_s", "mean")] == pytest.approx(
        np.mean([2048 / 4 / 1e6, 2048 / 7 / 1e6])
    )


def test_summarize_empty_returns_empty_summary(tmp_path, capsys):
    """An empty log dir (or one with no usable records) must yield an
    empty summary with a warning, not a pandas KeyError (ISSUE 3)."""
    empty = tmp_path / "log"
    empty.mkdir()
    records = log_summary.load_log_dir(str(empty))
    assert records == []
    frame = log_summary.summarize(records)
    assert len(frame) == 0
    assert "no usable task records" in capsys.readouterr().err
    # print_summary end to end on the empty dir
    log_summary.print_summary(str(empty))
    assert "no task logs found" in capsys.readouterr().out


def test_load_log_dir_missing_dir_warns(tmp_path, capsys):
    records = log_summary.load_log_dir(str(tmp_path / "nope"))
    assert records == []
    assert "no such log dir" in capsys.readouterr().err


def test_summarize_tolerates_missing_compute_device(tmp_path):
    d = tmp_path / "log"
    d.mkdir()
    (d / "0-8_0-16_0-16.json").write_text(json.dumps({
        "timer": {"inference": 2.0},  # no compute_device key at all
    }))
    frame = log_summary.summarize(log_summary.load_log_dir(str(d)))
    assert frame.loc[""][("_total", "mean")] == pytest.approx(2.0)


def _write_events(path, events):
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n")


def test_telemetry_aggregation(tmp_path):
    _write_events(tmp_path / "telemetry-1.jsonl", [
        {"kind": "span", "name": "pipeline/stage", "dur_s": 1.0},
        {"kind": "span", "name": "pipeline/drain", "dur_s": 3.0},
        {"kind": "span", "name": "pipeline/drain", "dur_s": 5.0},
        {"kind": "gauge", "name": "pipeline/ring_occupancy", "value": 2},
        {"kind": "gauge", "name": "pipeline/ring_occupancy", "value": 1},
        {"kind": "snapshot", "pid": 1,
         "counters": {"compile_cache/builds": 2, "compile_cache/hits": 7}},
    ])
    _write_events(tmp_path / "telemetry-2.jsonl", [
        {"kind": "span", "name": "pipeline/stage", "dur_s": 1.0},
        {"kind": "snapshot", "pid": 2,
         "counters": {"compile_cache/builds": 1}},
    ])
    (tmp_path / "ignored.txt").write_text("not jsonl")

    agg = log_summary.summarize_telemetry(
        log_summary.load_telemetry_dir(str(tmp_path))
    )
    assert agg["spans"]["pipeline/drain"]["count"] == 2
    assert agg["spans"]["pipeline/drain"]["total_s"] == pytest.approx(8.0)
    assert agg["spans"]["pipeline/drain"]["mean_s"] == pytest.approx(4.0)
    # counters sum across per-pid snapshots
    assert agg["counters"]["compile_cache/builds"] == 3
    assert agg["counters"]["compile_cache/hits"] == 7
    assert agg["gauges"]["pipeline/ring_occupancy"]["mean"] == \
        pytest.approx(1.5)
    # stall shares: stage 2s of 10s, drain 8s of 10s
    assert agg["stall"]["pipeline/stage"]["share"] == pytest.approx(0.2)
    assert agg["stall"]["pipeline/drain"]["share"] == pytest.approx(0.8)


def test_telemetry_snapshot_fills_span_holes_without_double_count(tmp_path):
    # a stream recorded with a late-configured sink: spans only in the
    # snapshot hists; gauges in the snapshot must not become spans
    _write_events(tmp_path / "telemetry-1.jsonl", [
        {"kind": "span", "name": "pipeline/drain", "dur_s": 2.0},
        {"kind": "snapshot", "pid": 1,
         "gauges": {"pipeline/ring_occupancy": 2},
         "hists": {
             "pipeline/drain": {"count": 9, "total": 9.0, "max": 2.0},
             "pipeline/stage": {"count": 4, "total": 1.0, "max": 0.5},
             "pipeline/ring_occupancy": {"count": 4, "total": 8.0,
                                         "max": 2},
         }},
    ])
    agg = log_summary.summarize_telemetry(
        log_summary.load_telemetry_dir(str(tmp_path))
    )
    # live span events win over the snapshot copy (no double count)
    assert agg["spans"]["pipeline/drain"]["count"] == 1
    # hole filled from the snapshot
    assert agg["spans"]["pipeline/stage"]["count"] == 4
    # the gauge's histogram is occupancy, not a span
    assert "pipeline/ring_occupancy" not in agg["spans"]


def test_print_telemetry_summary(tmp_path, capsys):
    assert log_summary.print_telemetry_summary(str(tmp_path)) is None
    assert "no telemetry events" in capsys.readouterr().out
    _write_events(tmp_path / "telemetry-1.jsonl", [
        {"kind": "span", "name": "pipeline/stage", "dur_s": 1.0},
        {"kind": "span", "name": "pipeline/drain", "dur_s": 9.0},
        {"kind": "gauge", "name": "pipeline/ring_occupancy", "value": 2},
        {"kind": "snapshot", "pid": 1,
         "counters": {"compile_cache/builds": 1,
                      "compile_cache/hits": 5}},
    ])
    agg = log_summary.print_telemetry_summary(str(tmp_path))
    out = capsys.readouterr().out
    assert agg["stall"]["pipeline/drain"]["share"] == pytest.approx(0.9)
    assert "dominant phase: pipeline/drain" in out
    assert "ring occupancy" in out
    assert "1 build(s), 5 hit(s)" in out


def test_print_mesh_block_renders_per_chip_table(tmp_path, capsys):
    """ISSUE 18: the MESH block folds shard/chip and device/chip gauges
    into one per-chip table with skew, analytic collective traffic, and
    the compute-vs-collective split verdict."""
    _write_events(tmp_path / "telemetry-1.jsonl", [
        {"kind": "gauge", "name": "shard/mesh_devices", "value": 2},
        {"kind": "gauge", "name": "shard/chip/0/voxels", "value": 2048.0},
        {"kind": "gauge", "name": "shard/chip/1/voxels", "value": 1024.0},
        {"kind": "gauge", "name": "shard/chip/0/ready_s",
         "value": 0.000004},
        {"kind": "gauge", "name": "shard/chip/1/ready_s",
         "value": 0.000010},
        {"kind": "gauge", "name": "shard/chip_skew_s", "value": 0.000006},
        {"kind": "gauge", "name": "device/chip/0/bytes_in_use",
         "value": 2.0 * 2**20},
        {"kind": "gauge", "name": "device/chip/0/hbm_headroom",
         "value": 14.0 * 2**20},
        {"kind": "gauge", "name": "device/hbm_headroom",
         "value": 14.0 * 2**20},
        {"kind": "gauge", "name": "device/bytes_in_use",
         "value": 2.0 * 2**20},
        {"kind": "gauge", "name": "shard/collective_share_est",
         "value": 0.93},
        {"kind": "gauge", "name": "shard/compute_s_est", "value": 0.0001},
        {"kind": "gauge", "name": "shard/collective_s_est",
         "value": 0.0015},
        {"kind": "snapshot", "pid": 1,
         "counters": {"shard/chunks": 3, "shard/halo_bytes": 1048576.0,
                      "shard/gather_bytes": 2097152.0}},
    ])
    agg = log_summary.print_telemetry_summary(str(tmp_path))
    out = capsys.readouterr().out
    assert "mesh (docs/multichip.md):" in out
    assert "shape data=2 (2 chip(s)), 3 sharded dispatch(es)" in out
    # per-chip rows: chip 0 carries load, HBM and headroom; chip 1 has
    # no watermark samples and renders dashes instead of zeros
    assert "0     " in out and "2048" in out and "1024" in out
    assert "2.0" in out and "14.0" in out
    assert "chip skew (last ready − first ready)" in out
    assert "halo 1.00 MiB, gather 2.00 MiB" in out
    assert "share 93% — collective-bound" in out
    assert "headroom 14.0 MiB (worst chip)" in out
    assert agg["counters"]["shard/gather_bytes"] == 2097152.0


def test_print_mesh_block_spatial_shape_and_quiet_default(capsys):
    from chunkflow_tpu.flow.log_summary import print_mesh_block

    # no sharded engine ever built: quiet
    assert print_mesh_block(
        {"gauges": {}, "counters": {}}) is False
    assert capsys.readouterr().out == ""
    # a 2D spatial mesh renders its y/x shape, not data=N
    agg = {"gauges": {
        "shard/mesh_devices": {"last": 4.0, "mean": 4.0},
        "shard/mesh_y": {"last": 2.0, "mean": 2.0},
        "shard/mesh_x": {"last": 2.0, "mean": 2.0},
    }, "counters": {"shard/chunks": 1}}
    assert print_mesh_block(agg) is True
    out = capsys.readouterr().out
    assert "shape y=2,x=2 (4 chip(s)), 1 sharded dispatch(es)" in out


def test_print_mesh_block_pipeline_shape_and_traffic_planes(capsys):
    """ISSUE 19: a pipeline mesh labels itself pipeline=N (not data=N),
    the traffic line carries the replay-strip and stage-handoff planes,
    and the collective verdict turns into a recommended-shape hint."""
    from chunkflow_tpu.flow.log_summary import print_mesh_block

    agg = {"gauges": {
        "shard/mesh_devices": {"last": 4.0, "mean": 4.0},
        "shard/mesh_y": {"last": 1.0, "mean": 1.0},
        "shard/mesh_x": {"last": 1.0, "mean": 1.0},
        "shard/mesh_pipeline": {"last": 4.0, "mean": 4.0},
        "shard/collective_share_est": {"last": 0.8, "mean": 0.8},
        "shard/compute_s_est": {"last": 0.0001, "mean": 0.0001},
        "shard/collective_s_est": {"last": 0.0004, "mean": 0.0004},
    }, "counters": {"shard/chunks": 2,
                    "shard/halo_bytes": 1048576.0,
                    "shard/replay_strip_bytes": 524288.0,
                    "shard/handoff_bytes": 2097152.0}}
    assert print_mesh_block(agg) is True
    out = capsys.readouterr().out
    assert "shape pipeline=4 (4 chip(s)), 2 sharded dispatch(es)" in out
    assert "replay strips 0.50 MiB" in out
    assert "stage handoffs 2.00 MiB" in out
    # handoffs dominate a collective-bound pipeline: the hint says so
    assert "shape hint: stage handoffs dominate" in out


def test_print_mesh_block_hints_replicated_replay_and_tight_hbm(capsys):
    """The two other hint arms: a collective-bound mesh whose gather
    plane has no replay strips points at CHUNKFLOW_SHARD_REPLAY; a
    compute-bound mesh with a tight chip points at the shapes that
    shrink per-chip footprints."""
    from chunkflow_tpu.flow.log_summary import print_mesh_block

    agg = {"gauges": {
        "shard/mesh_devices": {"last": 2.0, "mean": 2.0},
        "shard/collective_share_est": {"last": 0.9, "mean": 0.9},
    }, "counters": {"shard/chunks": 1,
                    "shard/gather_bytes": 2097152.0}}
    assert print_mesh_block(agg) is True
    out = capsys.readouterr().out
    assert ("shape hint: replicated replay dominates — flip "
            "CHUNKFLOW_SHARD_REPLAY=sharded") in out

    agg = {"gauges": {
        "shard/mesh_devices": {"last": 2.0, "mean": 2.0},
        "shard/collective_share_est": {"last": 0.1, "mean": 0.1},
        "device/chip/1/hbm_headroom": {"last": 2.0 * 2**20,
                                       "mean": 2.0 * 2**20},
    }, "counters": {"shard/chunks": 1}}
    assert print_mesh_block(agg) is True
    out = capsys.readouterr().out
    assert "compute-bound but chip(s) [1]" in out
    assert "sharded replay" in out


def test_log_summary_sweeps_profile_captures(tmp_path, capsys):
    """ISSUE 8: log-summary summarizes every profile-* capture dir under
    the metrics dir through tools/analyze_trace.py."""
    import gzip

    from chunkflow_tpu.flow.log_summary import print_profile_summaries

    capture = tmp_path / "profile-retrace-x-1" / "plugins" / "run"
    capture.mkdir(parents=True)
    with gzip.open(capture / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 7, "name": "fusion.1", "dur": 800},
            {"ph": "X", "pid": 7, "name": "convolution.2", "dur": 200},
        ]}, f)
    (tmp_path / "profile-empty-2").mkdir()
    print_profile_summaries(str(tmp_path))
    out = capsys.readouterr().out
    assert "profile-retrace-x-1" in out
    assert "fusion 80%" in out
    assert "profile-empty-2: no trace files" in out


# ---------------------------------------------------------------------------
# SLO view: sparklines + fleet-merged timeseries + the SLO block (ISSUE 12)
# ---------------------------------------------------------------------------
def test_sparkline_shapes():
    assert log_summary.sparkline([]) == ""
    flat = log_summary.sparkline([(0, 5.0), (1, 5.0), (2, 5.0)])
    assert len(flat) == 3 and len(set(flat)) == 1  # constant: one glyph
    ramp = log_summary.sparkline([(i, float(i)) for i in range(8)])
    assert len(ramp) == 8
    assert ramp[0] == log_summary._SPARK_BLOCKS[0]
    assert ramp[-1] == log_summary._SPARK_BLOCKS[-1]
    wide = log_summary.sparkline([(i, float(i)) for i in range(500)],
                                 width=40)
    assert len(wide) == 40  # resampled, not truncated


def _ts_event(worker, t, values=None, qhists=None, interval=1.0):
    return {"kind": "timeseries", "worker": worker, "t": t,
            "interval_s": interval, "values": values or {},
            "qhists": qhists or {}}


def test_summarize_timeseries_sums_rates_across_workers():
    events = [
        _ts_event("w1", 10.2, {"rate:serving/requests": 5.0}),
        _ts_event("w2", 10.4, {"rate:serving/requests": 7.0}),
        _ts_event("w1", 11.2, {"rate:serving/requests": 6.0}),
        _ts_event("w2", 11.4, {"rate:serving/requests": 8.0}),
    ]
    merged = log_summary.summarize_timeseries(events)
    series = merged["series"]["rate:serving/requests"]
    # fleet rate = sum across workers, per time bin
    assert [v for _, v in series] == [12.0, 14.0]


def test_summarize_timeseries_fleet_p99_from_bucket_deltas():
    from chunkflow_tpu.core import telemetry

    n = len(telemetry.QUANTILE_BOUNDS) + 1

    def buckets(**at):
        b = [0] * n
        for idx, count in at.items():
            b[int(idx[1:])] = count
        return b

    # worker 1 serves fast (bucket 3 ~ 10 ms), worker 2 slow (bucket 9
    # ~ 1 s); cumulative counts grow between ticks
    events = [
        _ts_event("w1", 10.0, qhists={"serving/latency": {
            "count": 10, "buckets": buckets(i3=10)}}),
        _ts_event("w2", 10.1, qhists={"serving/latency": {
            "count": 10, "buckets": buckets(i9=10)}}),
        _ts_event("w1", 11.0, qhists={"serving/latency": {
            "count": 30, "buckets": buckets(i3=30)}}),
        _ts_event("w2", 11.1, qhists={"serving/latency": {
            "count": 30, "buckets": buckets(i9=30)}}),
    ]
    merged = log_summary.summarize_timeseries(events)
    p99 = dict(merged["series"]["fleet_p99:serving/latency"])
    p50 = dict(merged["series"]["fleet_p50:serving/latency"])
    # second bin: 20 fast + 20 slow deltas -> p50 mid-range, p99 in the
    # slow worker's (0.5, 1.0] bucket — only bucket SUMS can say this
    (bin_t,) = p99.keys()
    assert 0.5 <= p99[bin_t] <= 1.0
    assert p50[bin_t] <= 0.5


def test_print_slo_block_renders_alerts_state_and_timelines(capsys):
    events = [
        {"kind": "alert", "state": "firing", "worker": "w1", "t": 5.0,
         "alert": "availability:fast", "objective": "availability",
         "rule": "fast", "severity": "page", "burn_short": 5.0,
         "burn_long": 3.0, "budget_remaining": 0.4},
        {"kind": "gauge", "worker": "w1", "t": 6.0,
         "name": "slo/availability/firing", "value": 1.0},
        {"kind": "gauge", "worker": "w1", "t": 6.0,
         "name": "slo/availability/budget_remaining", "value": 0.4},
        {"kind": "gauge", "worker": "w2", "t": 6.0,
         "name": "slo/availability/budget_remaining", "value": 0.9},
        _ts_event("w1", 5.5, {"rate:serving/requests": 5.0}),
        _ts_event("w1", 6.5, {"rate:serving/requests": 9.0}),
    ]
    assert log_summary.print_slo_block(events) is True
    out = capsys.readouterr().out
    assert "alerts fired: 1 (0 resolved)" in out
    assert "availability:fast page" in out
    assert "burn_short=5" in out and "budget_remaining=0.4" in out
    # worst (minimum) budget across workers + who is firing
    assert "objective availability:" in out
    assert "budget remaining 40.0%" in out
    assert "FIRING (w1)" in out
    assert "rate:serving/requests" in out  # a sparkline timeline


def test_print_slo_block_quiet_without_slo_plane(capsys):
    events = [{"kind": "span", "name": "op/x", "t": 1.0, "dur_s": 0.5,
               "worker": "w1"}]
    assert log_summary.print_slo_block(events) is False
    assert capsys.readouterr().out == ""


def test_cli_log_summary_slo(tmp_path, capsys):
    """`log-summary --slo` over a real recorded stream — and the stream
    survives the recording process: only JSONL is read."""
    from click.testing import CliRunner

    from chunkflow_tpu.core import telemetry
    from chunkflow_tpu.flow.cli import main

    d = tmp_path / "metrics"
    telemetry.reset()
    telemetry.configure(str(d))
    sampler = telemetry.start_timeseries(interval=3600.0)
    telemetry.inc("serving/requests", 10)
    sampler.sample(now=100.0)
    telemetry.inc("serving/requests", 30)
    sampler.sample(now=101.0)
    telemetry.event("alert", "slo/availability", state="firing",
                    alert="availability:fast", objective="availability",
                    rule="fast", severity="page", burn_short=9.0,
                    burn_long=4.0, budget_remaining=0.2)
    telemetry.flush()
    telemetry.reset()
    result = CliRunner().invoke(
        main, ["log-summary", "--metrics-dir", str(d), "--slo"])
    assert result.exit_code == 0, result.output
    assert "alerts fired: 1" in result.output
    assert "availability:fast page" in result.output
    assert "rate:serving/requests" in result.output
