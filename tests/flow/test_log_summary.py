"""log-summary: per-device aggregation + Mvoxel/s (reference
flow/log_summary.py:57-75 semantics)."""
import json

import numpy as np
import pytest

from chunkflow_tpu.flow import log_summary


@pytest.fixture
def log_dir(tmp_path):
    d = tmp_path / "log"
    d.mkdir()
    # two tasks on one device, one on another; bbox-coded filenames
    specs = [
        ("0-8_0-16_0-16.json", "tpu:v5e", {"load": 1.0, "inference": 3.0}),
        ("8-16_0-16_0-16.json", "tpu:v5e", {"load": 2.0, "inference": 5.0}),
        ("16-24_0-16_0-16.json", "cpu:x86", {"load": 4.0, "inference": 16.0}),
    ]
    for name, device, timer in specs:
        (d / name).write_text(json.dumps({
            "timer": timer, "compute_device": device,
        }))
    return str(d)


def test_load_and_summarize(log_dir):
    records = log_summary.load_log_dir(log_dir)
    assert len(records) == 3
    assert all(r["_bbox"] is not None for r in records)

    frame = log_summary.summarize(records)
    # grouped by device: v5e mean total = (4 + 7) / 2 = 5.5; cpu total = 20
    v5e = frame.loc["tpu:v5e"]
    cpu = frame.loc["cpu:x86"]
    assert v5e[("_total", "mean")] == pytest.approx(5.5)
    assert cpu[("_total", "mean")] == pytest.approx(20.0)
    # Mvoxel/s = voxels / mean_seconds / 1e6; bbox voxels = 8*16*16 = 2048
    assert v5e[("_mvoxel_per_s", "mean")] == pytest.approx(
        np.mean([2048 / 4 / 1e6, 2048 / 7 / 1e6])
    )


def test_summarize_empty_returns_empty_summary(tmp_path, capsys):
    """An empty log dir (or one with no usable records) must yield an
    empty summary with a warning, not a pandas KeyError (ISSUE 3)."""
    empty = tmp_path / "log"
    empty.mkdir()
    records = log_summary.load_log_dir(str(empty))
    assert records == []
    frame = log_summary.summarize(records)
    assert len(frame) == 0
    assert "no usable task records" in capsys.readouterr().err
    # print_summary end to end on the empty dir
    log_summary.print_summary(str(empty))
    assert "no task logs found" in capsys.readouterr().out


def test_load_log_dir_missing_dir_warns(tmp_path, capsys):
    records = log_summary.load_log_dir(str(tmp_path / "nope"))
    assert records == []
    assert "no such log dir" in capsys.readouterr().err


def test_summarize_tolerates_missing_compute_device(tmp_path):
    d = tmp_path / "log"
    d.mkdir()
    (d / "0-8_0-16_0-16.json").write_text(json.dumps({
        "timer": {"inference": 2.0},  # no compute_device key at all
    }))
    frame = log_summary.summarize(log_summary.load_log_dir(str(d)))
    assert frame.loc[""][("_total", "mean")] == pytest.approx(2.0)


def _write_events(path, events):
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n")


def test_telemetry_aggregation(tmp_path):
    _write_events(tmp_path / "telemetry-1.jsonl", [
        {"kind": "span", "name": "pipeline/stage", "dur_s": 1.0},
        {"kind": "span", "name": "pipeline/drain", "dur_s": 3.0},
        {"kind": "span", "name": "pipeline/drain", "dur_s": 5.0},
        {"kind": "gauge", "name": "pipeline/ring_occupancy", "value": 2},
        {"kind": "gauge", "name": "pipeline/ring_occupancy", "value": 1},
        {"kind": "snapshot", "pid": 1,
         "counters": {"compile_cache/builds": 2, "compile_cache/hits": 7}},
    ])
    _write_events(tmp_path / "telemetry-2.jsonl", [
        {"kind": "span", "name": "pipeline/stage", "dur_s": 1.0},
        {"kind": "snapshot", "pid": 2,
         "counters": {"compile_cache/builds": 1}},
    ])
    (tmp_path / "ignored.txt").write_text("not jsonl")

    agg = log_summary.summarize_telemetry(
        log_summary.load_telemetry_dir(str(tmp_path))
    )
    assert agg["spans"]["pipeline/drain"]["count"] == 2
    assert agg["spans"]["pipeline/drain"]["total_s"] == pytest.approx(8.0)
    assert agg["spans"]["pipeline/drain"]["mean_s"] == pytest.approx(4.0)
    # counters sum across per-pid snapshots
    assert agg["counters"]["compile_cache/builds"] == 3
    assert agg["counters"]["compile_cache/hits"] == 7
    assert agg["gauges"]["pipeline/ring_occupancy"]["mean"] == \
        pytest.approx(1.5)
    # stall shares: stage 2s of 10s, drain 8s of 10s
    assert agg["stall"]["pipeline/stage"]["share"] == pytest.approx(0.2)
    assert agg["stall"]["pipeline/drain"]["share"] == pytest.approx(0.8)


def test_telemetry_snapshot_fills_span_holes_without_double_count(tmp_path):
    # a stream recorded with a late-configured sink: spans only in the
    # snapshot hists; gauges in the snapshot must not become spans
    _write_events(tmp_path / "telemetry-1.jsonl", [
        {"kind": "span", "name": "pipeline/drain", "dur_s": 2.0},
        {"kind": "snapshot", "pid": 1,
         "gauges": {"pipeline/ring_occupancy": 2},
         "hists": {
             "pipeline/drain": {"count": 9, "total": 9.0, "max": 2.0},
             "pipeline/stage": {"count": 4, "total": 1.0, "max": 0.5},
             "pipeline/ring_occupancy": {"count": 4, "total": 8.0,
                                         "max": 2},
         }},
    ])
    agg = log_summary.summarize_telemetry(
        log_summary.load_telemetry_dir(str(tmp_path))
    )
    # live span events win over the snapshot copy (no double count)
    assert agg["spans"]["pipeline/drain"]["count"] == 1
    # hole filled from the snapshot
    assert agg["spans"]["pipeline/stage"]["count"] == 4
    # the gauge's histogram is occupancy, not a span
    assert "pipeline/ring_occupancy" not in agg["spans"]


def test_print_telemetry_summary(tmp_path, capsys):
    assert log_summary.print_telemetry_summary(str(tmp_path)) is None
    assert "no telemetry events" in capsys.readouterr().out
    _write_events(tmp_path / "telemetry-1.jsonl", [
        {"kind": "span", "name": "pipeline/stage", "dur_s": 1.0},
        {"kind": "span", "name": "pipeline/drain", "dur_s": 9.0},
        {"kind": "gauge", "name": "pipeline/ring_occupancy", "value": 2},
        {"kind": "snapshot", "pid": 1,
         "counters": {"compile_cache/builds": 1,
                      "compile_cache/hits": 5}},
    ])
    agg = log_summary.print_telemetry_summary(str(tmp_path))
    out = capsys.readouterr().out
    assert agg["stall"]["pipeline/drain"]["share"] == pytest.approx(0.9)
    assert "dominant phase: pipeline/drain" in out
    assert "ring occupancy" in out
    assert "1 build(s), 5 hit(s)" in out


def test_log_summary_sweeps_profile_captures(tmp_path, capsys):
    """ISSUE 8: log-summary summarizes every profile-* capture dir under
    the metrics dir through tools/analyze_trace.py."""
    import gzip

    from chunkflow_tpu.flow.log_summary import print_profile_summaries

    capture = tmp_path / "profile-retrace-x-1" / "plugins" / "run"
    capture.mkdir(parents=True)
    with gzip.open(capture / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 7, "name": "fusion.1", "dur": 800},
            {"ph": "X", "pid": 7, "name": "convolution.2", "dur": 200},
        ]}, f)
    (tmp_path / "profile-empty-2").mkdir()
    print_profile_summaries(str(tmp_path))
    out = capsys.readouterr().out
    assert "profile-retrace-x-1" in out
    assert "fusion 80%" in out
    assert "profile-empty-2: no trace files" in out
