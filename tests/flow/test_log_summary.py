"""log-summary: per-device aggregation + Mvoxel/s (reference
flow/log_summary.py:57-75 semantics)."""
import json

import numpy as np
import pytest

from chunkflow_tpu.flow import log_summary


@pytest.fixture
def log_dir(tmp_path):
    d = tmp_path / "log"
    d.mkdir()
    # two tasks on one device, one on another; bbox-coded filenames
    specs = [
        ("0-8_0-16_0-16.json", "tpu:v5e", {"load": 1.0, "inference": 3.0}),
        ("8-16_0-16_0-16.json", "tpu:v5e", {"load": 2.0, "inference": 5.0}),
        ("16-24_0-16_0-16.json", "cpu:x86", {"load": 4.0, "inference": 16.0}),
    ]
    for name, device, timer in specs:
        (d / name).write_text(json.dumps({
            "timer": timer, "compute_device": device,
        }))
    return str(d)


def test_load_and_summarize(log_dir):
    records = log_summary.load_log_dir(log_dir)
    assert len(records) == 3
    assert all(r["_bbox"] is not None for r in records)

    frame = log_summary.summarize(records)
    # grouped by device: v5e mean total = (4 + 7) / 2 = 5.5; cpu total = 20
    v5e = frame.loc["tpu:v5e"]
    cpu = frame.loc["cpu:x86"]
    assert v5e[("_total", "mean")] == pytest.approx(5.5)
    assert cpu[("_total", "mean")] == pytest.approx(20.0)
    # Mvoxel/s = voxels / mean_seconds / 1e6; bbox voxels = 8*16*16 = 2048
    assert v5e[("_mvoxel_per_s", "mean")] == pytest.approx(
        np.mean([2048 / 4 / 1e6, 2048 / 7 / 1e6])
    )
