"""The shipped examples/ files run through the real CLI."""
import os

import numpy as np
import pytest
from click.testing import CliRunner

from chunkflow_tpu.chunk import Chunk
from chunkflow_tpu.flow.cli import main

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "examples", "inference",
)


@pytest.fixture
def runner():
    return CliRunner()


def test_custom_flax_model_example(runner, tmp_path):
    out = tmp_path / "out.h5"
    result = runner.invoke(main, [
        "create-chunk", "--size", "8", "32", "32", "--pattern", "random",
        "inference", "--framework", "flax",
        "--model-path", os.path.join(EXAMPLES, "custom_flax_model.py"),
        "--input-patch-size", "4", "16", "16",
        "--output-patch-overlap", "2", "8", "8",
        "--num-output-channels", "3", "--no-crop-output-margin",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    arr = np.asarray(Chunk.from_h5(str(out)).array)
    assert arr.shape == (3, 8, 32, 32)
    assert np.isfinite(arr).all() and arr.std() > 0


def test_universal_engine_example(runner, tmp_path):
    out = tmp_path / "out.h5"
    result = runner.invoke(main, [
        "create-chunk", "--size", "8", "32", "32", "--pattern", "random",
        "--dtype", "float32",
        "inference", "--framework", "universal",
        "--model-path", os.path.join(EXAMPLES, "universal_engine.py"),
        "--input-patch-size", "4", "16", "16",
        "--output-patch-overlap", "2", "8", "8",
        "--num-output-channels", "1", "--no-crop-output-margin",
        "save-h5", "--file-name", str(out),
    ])
    assert result.exit_code == 0, result.output
    arr = np.asarray(Chunk.from_h5(str(out)).array)
    assert arr.shape == (1, 8, 32, 32)
    assert np.isfinite(arr).all()
