"""Flagship integration test: the full production worker pipeline against
local-disk precomputed volumes (analog of the reference's
tests/flow/test_flow.py::test_inference_pipeline).

Builds input volume, coarse input mask (mip 1), output volume, coarse
output mask, runs:
    fetch-task -> load-precomputed(+margin) -> mask(in) -> inference
    (identity) -> crop-margin -> mask(out) -> save-precomputed
and asserts masked regions are zero and unmasked output ~= input.
"""
import numpy as np
import pytest
from click.testing import CliRunner

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.flow.cli import main
from chunkflow_tpu.volume.precomputed import PrecomputedVolume


@pytest.fixture
def world(tmp_path):
    rng = np.random.default_rng(0)
    size = (32, 64, 64)
    image = Chunk(
        rng.integers(1, 255, size).astype(np.uint8), voxel_size=(1, 1, 1)
    )
    input_vol = PrecomputedVolume.from_chunk(
        image, str(tmp_path / "img"), block_size=(16, 16, 16)
    )

    # input mask at mip 1 (2x coarser in yx): zero out a corner
    mask_arr = np.ones((32, 32, 32), dtype=np.uint8)
    mask_arr[:, :8, :8] = 0  # masks yx < 16 at mip 0
    mask_vol = PrecomputedVolume.from_chunk(
        Chunk(mask_arr, voxel_size=(1, 2, 2)),
        str(tmp_path / "mask"),
        block_size=(16, 16, 16),
    )

    output_vol = PrecomputedVolume.create(
        str(tmp_path / "out"),
        volume_size=size,
        voxel_size=(1, 1, 1),
        dtype="float32",
        layer_type="image",
        block_size=(16, 16, 16),
    )
    return dict(
        tmp_path=tmp_path,
        image=image,
        input_vol=input_vol,
        mask_vol=mask_vol,
        output_vol=output_vol,
    )


def test_full_worker_pipeline(world):
    qdir = str(world["tmp_path"] / "queue")
    runner = CliRunner()

    # enqueue one interior task
    result = runner.invoke(
        main,
        [
            "generate-tasks", "-c", "16", "32", "32",
            "--roi-start", "8", "16", "16",
            "--grid-size", "1", "1", "1",
            "--queue-name", qdir,
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0

    result = runner.invoke(
        main,
        [
            "fetch-task-from-queue", "-q", qdir,
            "load-precomputed", "-v", world["input_vol"].path,
            "--expand-margin-size", "4", "8", "8",
            "mask", "-v", world["mask_vol"].path,
            "inference",
            "--framework", "identity",
            "--input-patch-size", "12", "24", "24",
            "--output-patch-size", "8", "16", "16",
            "--output-patch-overlap", "4", "8", "8",
            "--num-output-channels", "1",
            "--batch-size", "2",
            "crop-margin",
            "mask", "-v", world["mask_vol"].path,
            "save-precomputed", "-v", world["output_vol"].path,
            "delete-task-in-queue",
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output

    bbox = BoundingBox((8, 16, 16), (24, 48, 48))
    out = world["output_vol"].cutout(bbox)
    got = np.asarray(out.array).squeeze()
    expected = (
        np.asarray(world["image"].cutout(bbox).array).astype(np.float32) / 255.0
    )

    # masked corner (y<16 and x<16 at mip0... here the corner yx<16) is zero
    # the task bbox starts at y=16, x=16, so nothing in it is masked; check
    # output matches input
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-3)

    # timing log uploaded next to the volume
    import os

    log_dir = os.path.join(str(world["tmp_path"] / "out"), "log")
    logs = os.listdir(log_dir)
    assert len(logs) == 1 and logs[0].endswith(".json")


def test_masked_region_zeroed(world):
    """Task overlapping the masked corner: masked voxels must be zero."""
    runner = CliRunner()
    result = runner.invoke(
        main,
        [
            "generate-tasks", "-c", "16", "32", "32",
            "--roi-start", "0", "0", "0",
            "--grid-size", "1", "1", "1",
            "load-precomputed", "-v", world["input_vol"].path,
            "mask", "-v", world["mask_vol"].path,
            "save-precomputed", "-v", world["output_vol"].path,
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    out = world["output_vol"].cutout(BoundingBox((0, 0, 0), (16, 32, 32)))
    got = np.asarray(out.array).squeeze()
    assert np.all(got[:, :16, :16] == 0)  # masked corner
    assert np.any(got[:, 16:, 16:] != 0)  # rest survived


def test_skip_by_blocks_resume(world):
    """Second run of the same task skips via has_all_blocks."""
    runner = CliRunner()
    args = [
        "-v",
        "generate-tasks", "-c", "16", "16", "16",
        "--roi-start", "0", "0", "0", "--grid-size", "1", "1", "1",
        "skip-task-by-blocks-in-volume", "-v", world["output_vol"].path,
        "load-precomputed", "-v", world["input_vol"].path,
        "save-precomputed", "-v", world["output_vol"].path,
    ]
    r1 = runner.invoke(main, args, catch_exceptions=False)
    assert r1.exit_code == 0
    assert "save-precomputed" in r1.output
    r2 = runner.invoke(main, args, catch_exceptions=False)
    # second run: task skipped before load
    assert "save-precomputed" not in r2.output
