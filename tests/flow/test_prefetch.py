"""Prefetch stage: ordering, completeness, exception propagation, overlap."""
import time

import pytest

from chunkflow_tpu.flow.runtime import prefetch_stage


def test_prefetch_preserves_order_and_count():
    tasks = [{"log": {"timer": {}}, "i": i} for i in range(20)]
    out = list(prefetch_stage(depth=3)(iter(tasks)))
    assert [t["i"] for t in out] == list(range(20))


def test_prefetch_propagates_exceptions():
    def source():
        yield {"i": 0}
        raise RuntimeError("boom")

    stage = prefetch_stage(depth=1)
    it = stage(source())
    assert next(it)["i"] == 0
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_prefetch_overlaps_producer_and_consumer():
    """With prefetch, slow-produce + slow-consume take ~max, not ~sum."""
    n, delay = 6, 0.05

    def source():
        for i in range(n):
            time.sleep(delay)  # pretend host IO
            yield {"i": i}

    start = time.perf_counter()
    for _ in prefetch_stage(depth=2)(source()):
        time.sleep(delay)  # pretend device compute
    elapsed = time.perf_counter() - start
    # sequential would be ~2*n*delay; pipelined ~(n+1)*delay
    assert elapsed < 1.7 * n * delay, elapsed


def test_prefetch_cli_registered():
    from chunkflow_tpu.flow.cli import main

    assert "prefetch" in main.commands


def test_prefetch_stops_upstream_on_early_exit():
    """Closing the consumer retires the worker; upstream stops being pulled."""
    pulled = []

    def source():
        for i in range(100):
            pulled.append(i)
            yield {"i": i}

    stage = prefetch_stage(depth=1)
    it = stage(source())
    assert next(it)["i"] == 0
    it.close()  # simulates a downstream exception unwinding the pipeline
    time.sleep(0.3)
    n = len(pulled)
    assert n <= 4, f"worker kept pulling after close: {n}"
    time.sleep(0.2)
    assert len(pulled) == n, "worker still running after close"


def test_prefetch_to_device():
    from chunkflow_tpu.chunk.base import Chunk
    import numpy as np

    tasks = [
        {"log": {"timer": {}}, "chunk": Chunk(np.ones((2, 2, 2), np.float32))}
        for _ in range(3)
    ]
    out = list(prefetch_stage(depth=2, to_device=True)(iter(tasks)))
    assert len(out) == 3
    assert all(t["chunk"].is_on_device for t in out)
