"""Unit tests for the interpret-mode kernel sanitizer
(chunkflow_tpu/testing/kernelcheck.py): switch semantics, registry
mechanics, the three host-side checks, and end-to-end runs through the
SHIPPING kernels — clean data must pass with zero violations (and
bit-identical results), bad data must trip the right violation kind.
"""
import numpy as np
import pytest

from chunkflow_tpu.testing import kernelcheck


@pytest.fixture(autouse=True)
def _clean_registry():
    kernelcheck.reset_state()
    yield
    kernelcheck.reset_state()


# ---------------------------------------------------------------------------
# switch semantics
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("value", ["", "0", "off", "false", "no",
                                   "OFF", "False", "No"])
def test_off_values(monkeypatch, value):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", value)
    assert not kernelcheck.enabled()
    assert kernelcheck.key_suffix() == ""
    assert not kernelcheck.active(True)


@pytest.mark.parametrize("value", ["1", "on", "yes", "raise"])
def test_on_values(monkeypatch, value):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", value)
    assert kernelcheck.enabled()
    assert kernelcheck.key_suffix() == "+kc"


def test_unset_is_off(monkeypatch):
    monkeypatch.delenv("CHUNKFLOW_KERNELCHECK", raising=False)
    assert not kernelcheck.enabled()


def test_active_requires_interpret(monkeypatch):
    # compiled Mosaic legs are never instrumented, whatever the env says
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    assert kernelcheck.active(True)
    assert not kernelcheck.active(False)


# ---------------------------------------------------------------------------
# registry mechanics
# ---------------------------------------------------------------------------
def test_report_and_reset(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    kernelcheck._registry.count_check()
    kernelcheck._registry.violation("oob-slice", "synthetic")
    snap = kernelcheck.report()
    assert snap["enabled"] and snap["checks"] == 1
    assert [v["kind"] for v in snap["violations"]] == ["oob-slice"]
    kernelcheck.reset_state()
    snap = kernelcheck.report()
    assert snap["checks"] == 0 and snap["violations"] == []


def test_violation_raises_in_raise_mode(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "raise")
    with pytest.raises(kernelcheck.KernelCheckError, match="synthetic"):
        kernelcheck._registry.violation("oob-slice", "synthetic detail")
    # recorded even when it raises
    assert len(kernelcheck.report()["violations"]) == 1


def test_grid_trace_only_records_when_armed():
    kernelcheck._record_visit(0, label="k")
    kernelcheck._record_visit(1, label="k")
    assert kernelcheck._registry.take_trace("k") == []
    kernelcheck.arm_grid_trace("k")
    kernelcheck._record_visit(0, label="k")
    kernelcheck._record_visit(1, label="k")
    assert kernelcheck._registry.take_trace("k") == [0, 1]
    # take_trace consumed it
    assert kernelcheck._registry.take_trace("k") == []


def test_rmw_order_violation_from_descending_walk(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    kernelcheck.arm_grid_trace("k")
    for idx in (0, 2, 1):
        kernelcheck._record_visit(idx, label="k")
    kernelcheck._host_check_result(False, label="k")
    kinds = [v["kind"] for v in kernelcheck.report()["violations"]]
    assert kinds == ["rmw-order"]


def test_ascending_walk_passes(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    kernelcheck.arm_grid_trace("k")
    for idx in (0, 0, 1, 2):  # repeats are fine (multi-channel grids)
        kernelcheck._record_visit(idx, label="k")
    kernelcheck._host_check_result(False, label="k")
    assert kernelcheck.report()["violations"] == []


def test_nan_canary_violation(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    kernelcheck._host_check_result(True, label="k")
    kinds = [v["kind"] for v in kernelcheck.report()["violations"]]
    assert kinds == ["scratch-canary"]


def test_host_check_bounds(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    starts = np.array([[0, 0], [8, 128]], np.int32)
    kernelcheck._host_check_bounds(
        starts, window=(8, 128), extent=(16, 256), label="k")
    assert kernelcheck.report()["violations"] == []
    kernelcheck._host_check_bounds(
        starts, window=(8, 256), extent=(16, 256), label="k")
    viols = kernelcheck.report()["violations"]
    assert [v["kind"] for v in viols] == ["oob-slice"]
    assert "batch 1 dim 1" in viols[0]["detail"]


def test_host_check_bounds_negative_start(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    starts = np.array([[-8, 0]], np.int32)
    kernelcheck._host_check_bounds(
        starts, window=(8, 128), extent=(16, 256), label="k")
    assert [v["kind"] for v in kernelcheck.report()["violations"]] == [
        "oob-slice"]


def test_publish_gauges(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    from chunkflow_tpu.core import telemetry

    kernelcheck._registry.count_check()
    kernelcheck.publish()
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["kernelcheck/checks"] == 1
    assert gauges["kernelcheck/violations"] == 0


# ---------------------------------------------------------------------------
# end-to-end through the shipping kernels (interpret mode on CPU)
# ---------------------------------------------------------------------------
def _gather_args(starts_rows):
    import jax.numpy as jnp

    from chunkflow_tpu.ops import pallas_gather

    ci, shape, pin = 2, (9, 40, 50), (3, 12, 18)
    raw = np.ones((ci,) + shape, np.float32)
    pad_y, pad_x = pallas_gather.gather_buffer_padding(pin, raw.dtype)
    padded = np.pad(raw, [(0, 0), (0, 0), (0, pad_y), (0, pad_x)])
    return (jnp.asarray(padded),
            jnp.asarray(np.array(starts_rows, np.int32)), pin)


def test_gather_patches_clean_run_counts_checks(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    from chunkflow_tpu.ops import pallas_gather

    chunk, starts, pin = _gather_args([[0, 0, 0], [6, 28, 32]])
    pallas_gather.gather_patches(
        chunk, starts, pin, interpret=True).block_until_ready()
    snap = kernelcheck.report()
    assert snap["violations"] == []
    assert snap["checks"] >= 2  # bounds + result sweep both fired


def test_gather_patches_oob_starts_detected(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK_MODE", "log")
    from chunkflow_tpu.ops import pallas_gather

    # z start 8 + window 3 runs past the 9-deep chunk
    chunk, starts, pin = _gather_args([[8, 0, 0]])
    pallas_gather.gather_patches(
        chunk, starts, pin, interpret=True).block_until_ready()
    kinds = [v["kind"] for v in kernelcheck.report()["violations"]]
    assert "oob-slice" in kinds


def test_fused_blend_armed_walk_is_ascending(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "1")
    import jax.numpy as jnp

    from chunkflow_tpu.ops import pallas_blend

    kernelcheck.arm_grid_trace("fused_blend")
    co, Z, Y, X, B, pz, py, px = 2, 5, 32, 40, 3, 3, 12, 16
    pad_y, pad_x = pallas_blend.buffer_padding((pz, py, px))
    out = jnp.zeros((co, Z, Y + pad_y, X + pad_x), jnp.float32)
    weight = jnp.zeros((Z, Y + pad_y, X + pad_x), jnp.float32)
    preds = jnp.ones((B, co, pz, py, px), jnp.float32)
    valid = jnp.ones((B,), jnp.float32)
    bump = jnp.ones((pz, py, px), jnp.float32)
    starts = jnp.asarray(
        np.array([[0, 0, 0], [1, 6, 8], [2, 12, 16]], np.int32))
    res_out, _ = pallas_blend.fused_accumulate_patches(
        out, weight, preds, valid, bump, starts, interpret=True)
    res_out.block_until_ready()
    snap = kernelcheck.report()
    assert snap["violations"] == []
    # check_result consumed the trace; nothing left behind
    assert kernelcheck._registry.take_trace("fused_blend") == []


def test_disabled_is_strict_noop(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_KERNELCHECK", "0")
    from chunkflow_tpu.ops import pallas_gather

    chunk, starts, pin = _gather_args([[8, 0, 0]])  # OOB — must NOT trip
    pallas_gather.gather_patches(
        chunk, starts, pin, interpret=True).block_until_ready()
    snap = kernelcheck.report()
    assert snap == {"enabled": False, "checks": 0, "violations": []}
