"""The deterministic fault-injection harness (testing/chaos.py)."""
import pytest

from chunkflow_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _clean():
    chaos.reset()
    yield
    chaos.reset()


def test_inactive_is_noop():
    assert not chaos.active()
    chaos.chaos_point("lifecycle/claim")  # must not raise
    assert chaos.injections() == {}


def test_once_kills_each_point_exactly_once():
    chaos.configure("once=a/b,c/d")
    with pytest.raises(chaos.ChaosError):
        chaos.chaos_point("a/b")
    chaos.chaos_point("a/b")  # second hit survives
    with pytest.raises(chaos.ChaosError):
        chaos.chaos_point("c/d")
    chaos.chaos_point("c/d")
    assert chaos.injections() == {"a/b": 1, "c/d": 1}


def test_rate_sequence_is_seed_deterministic():
    def kill_sequence():
        chaos.configure("seed=42:rate=0.5:points=op/*")
        seq = []
        for _ in range(32):
            try:
                chaos.chaos_point("op/load-h5")
                seq.append(False)
            except chaos.ChaosError:
                seq.append(True)
        return seq

    first = kill_sequence()
    assert any(first) and not all(first)  # actually Bernoulli at 0.5
    assert kill_sequence() == first  # pure function of (seed, hit order)


def test_fnmatch_patterns_and_nonmatching_points():
    chaos.configure("seed=1:rate=1.0:points=op/*")
    chaos.chaos_point("lifecycle/claim")  # no match: survives
    with pytest.raises(chaos.ChaosError):
        chaos.chaos_point("op/save-h5")


def test_max_kills_bounds_total_injections():
    chaos.configure("seed=1:rate=1.0:points=op/*:max=2")
    for _ in range(2):
        with pytest.raises(chaos.ChaosError):
            chaos.chaos_point("op/x")
    chaos.chaos_point("op/x")  # budget spent: no more kills
    assert sum(chaos.injections().values()) == 2


def test_env_var_pickup_and_change(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_CHAOS", "once=env/point")
    assert chaos.active()
    with pytest.raises(chaos.ChaosError):
        chaos.chaos_point("env/point")
    monkeypatch.setenv("CHUNKFLOW_CHAOS", "")
    assert not chaos.active()  # re-read: plan dropped with the env var


def test_configure_overrides_env(monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_CHAOS", "once=env/point")
    chaos.configure(None)  # explicit off wins over the env until reset()
    assert not chaos.active()
    chaos.chaos_point("env/point")
    chaos.reset()
    assert chaos.active()


def test_bad_spec_raises():
    with pytest.raises(ValueError, match="bad CHUNKFLOW_CHAOS field"):
        chaos.configure("bogus=1")


def test_bad_action_raises():
    with pytest.raises(ValueError, match="bad CHUNKFLOW_CHAOS action"):
        chaos.configure("once=a/b:action=explode")


def test_kill_action_parses_and_defaults_to_raise():
    chaos.configure("once=a/b:action=kill")
    assert chaos._current_plan().action == "kill"
    chaos.configure("once=a/b")
    assert chaos._current_plan().action == "raise"


def test_kill_action_dies_by_sigkill():
    """``action=kill`` must be TRUE process death: no exception
    unwinding, no finally blocks — the child is SIGKILLed on the spot
    (exit by signal 9), and a non-matching point leaves it alive."""
    import subprocess
    import sys

    prog = (
        "from chunkflow_tpu.testing import chaos\n"
        "chaos.configure('once=op/x:action=kill')\n"
        "chaos.chaos_point('op/other')\n"  # no match: survives
        "try:\n"
        "    chaos.chaos_point('op/x')\n"
        "finally:\n"
        "    print('FINALLY RAN')\n"  # must never appear
        "print('SURVIVED')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        timeout=60,
    )
    assert proc.returncode in (-9, 137), (proc.returncode, proc.stderr)
    assert "FINALLY RAN" not in proc.stdout
    assert "SURVIVED" not in proc.stdout


def test_chaos_error_is_transient():
    from chunkflow_tpu.parallel.lifecycle import classify_error

    assert classify_error(chaos.ChaosError("injected")) == "transient"
