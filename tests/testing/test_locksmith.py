"""Locksmith runtime sanitizer tests: seeded AB/BA inversion detection,
RLock reentrancy, proxy transparency, the kill switch, hold ceilings.

These tests manage the sanitizer's global state themselves (uninstall +
reset around each) because conftest enables locksmith for the whole
tier-1 suite — the fixture hands each test a clean graph.
"""
import queue
import threading
import time

import pytest

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.testing import locksmith


def own_violations(rep):
    """Violations whose lock sites live in THIS file — daemon threads
    from earlier suite tests (packer dispatchers, heartbeats) may still
    be recording into the global graph while these tests run."""
    return [v for v in rep["violations"]
            if "test_locksmith" in v.get("detail", "")]


@pytest.fixture
def fresh(monkeypatch):
    was_installed = locksmith.installed()
    locksmith.uninstall()
    locksmith.reset_state()
    monkeypatch.setenv("CHUNKFLOW_LOCKSMITH", "1")
    monkeypatch.delenv("CHUNKFLOW_LOCKSMITH_MODE", raising=False)
    monkeypatch.delenv("CHUNKFLOW_LOCKSMITH_HOLD_MS", raising=False)
    yield locksmith
    locksmith.uninstall()
    locksmith.reset_state()
    if was_installed:
        locksmith.install()


def test_detects_seeded_ab_ba_inversion(fresh):
    """The acceptance fixture: thread 1 takes A then B, thread 2 takes
    B then A — deterministic (sequential threads), no real contention,
    and the second thread's inner acquire must raise BEFORE acquiring."""
    assert fresh.install()
    a = threading.Lock()
    b = threading.Lock()
    caught = []

    def t1():
        with a:
            with b:
                pass

    def t2():
        try:
            with b:
                with a:  # closes the cycle: must raise here
                    pytest.fail("inverted acquire went through")
        except locksmith.LockOrderError as exc:
            caught.append(exc)

    th1 = threading.Thread(target=t1)
    th1.start()
    th1.join(timeout=10)
    th2 = threading.Thread(target=t2)
    th2.start()
    th2.join(timeout=10)
    assert len(caught) == 1
    assert "cycle" in str(caught[0])
    assert not a.locked() and not b.locked()  # clean unwinding
    mine = own_violations(fresh.report())
    assert mine and mine[0]["kind"] == "lock-order-cycle"


def test_transitive_cycle_through_three_locks(fresh):
    fresh.install()
    a, b, c = threading.Lock(), threading.Lock(), threading.Lock()
    caught = []

    def run(first, second, expect_raise=False):
        def body():
            try:
                with first:
                    with second:
                        pass
            except locksmith.LockOrderError as exc:
                caught.append(exc)
        t = threading.Thread(target=body)
        t.start()
        t.join(timeout=10)

    run(a, b)
    run(b, c)
    run(c, a)  # a -> b -> c -> a
    assert len(caught) == 1


def test_single_thread_both_orders_not_flagged(fresh):
    """One thread running A->B then B->A sequentially cannot deadlock
    against itself — the diversity criterion keeps tier-1 false-positive
    free."""
    fresh.install()
    a, b = threading.Lock(), threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert own_violations(fresh.report()) == []


def test_rlock_reentrancy_not_flagged(fresh):
    fresh.install()
    r = threading.RLock()
    with r:
        with r:
            with r:
                pass
    assert own_violations(fresh.report()) == []


def test_plain_lock_self_deadlock_detected(fresh):
    fresh.install()
    lk = threading.Lock()
    lk.acquire()
    with pytest.raises(locksmith.LockOrderError, match="re-acquires"):
        lk.acquire()
    lk.release()


def test_proxy_transparency(fresh):
    fresh.install()
    lk = threading.Lock()
    assert lk.acquire(timeout=0.5) is True
    assert lk.locked()
    lk.release()
    assert not lk.locked()
    assert lk.acquire(False) is True  # non-blocking
    lk.release()
    with lk:
        assert lk.locked()
    assert not lk.locked()
    r = threading.RLock()
    with r:
        assert r.acquire(timeout=0.1) is True
        r.release()
    assert own_violations(fresh.report()) == []


def test_condition_wait_notify_across_threads(fresh):
    """Condition over a proxied lock: wait shows as release+reacquire,
    the handoff works, and no violation is recorded."""
    fresh.install()
    cv = threading.Condition()
    shared_cv = threading.Condition(threading.Lock())
    items = []

    def consumer():
        with cv:
            while not items:
                cv.wait(timeout=5)
            items.pop()

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    with cv:
        items.append(1)
        cv.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    with shared_cv:  # plain-lock condition path
        assert shared_cv.wait(timeout=0.01) is False
    assert own_violations(fresh.report()) == []


def test_kill_switch_creates_no_proxies(fresh, monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_LOCKSMITH", "0")
    assert locksmith.install() is False
    assert threading.Lock is locksmith._ORIG_LOCK
    assert threading.Condition is locksmith._ORIG_CONDITION
    lk = threading.Lock()
    assert not hasattr(lk, "_ls_id")
    assert not locksmith.installed()


def test_out_of_scope_construction_gets_real_locks(fresh):
    # stdlib frames (queue.Queue internals) must never be proxied
    fresh.install()
    q = queue.Queue()
    assert not hasattr(q.mutex, "_ls_id")
    lk = threading.Lock()  # this file IS in scope (tests/)
    assert hasattr(lk, "_ls_id")


def test_hold_ceiling_records_violation(fresh, monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_LOCKSMITH_HOLD_MS", "10")
    fresh.install()
    lk = threading.Lock()
    with lk:
        time.sleep(0.05)
    mine = [h for h in fresh.report()["hold_violations"]
            if "test_locksmith" in h["lock"]]
    assert mine
    assert mine[0]["held_s"] >= 0.01


def test_log_mode_records_without_raising(fresh, monkeypatch):
    monkeypatch.setenv("CHUNKFLOW_LOCKSMITH_MODE", "log")
    fresh.install()
    a, b = threading.Lock(), threading.Lock()

    def order(first, second):
        def body():
            with first:
                with second:
                    pass
        t = threading.Thread(target=body)
        t.start()
        t.join(timeout=10)

    order(a, b)
    order(b, a)  # would raise in raise mode; log mode records
    assert len(own_violations(fresh.report())) == 1


def test_report_and_publish_counters(fresh):
    fresh.install()
    telemetry.reset()
    lk = threading.Lock()
    with lk:
        pass
    rep = fresh.report()
    assert rep["enabled"] and rep["locks"] >= 1 and rep["acquires"] >= 1
    locksmith.publish()
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["locksmith/locks"] >= 1
    assert gauges["locksmith/acquires"] >= 1
    telemetry.reset()


def test_thread_tokens_never_reused(fresh):
    """Regression: threading.get_ident() is recycled after a thread
    exits, which made two sequential threads look like one and
    suppressed a genuine AB/BA inversion mid-suite. The registry's own
    tokens are monotonic and never reused."""
    fresh.install()
    tokens = []

    def grab():
        tokens.append(locksmith._registry._thread_token())

    for _ in range(3):
        t = threading.Thread(target=grab)
        t.start()
        t.join(timeout=10)
    assert len(set(tokens)) == 3
