import numpy as np

from chunkflow_tpu.chunk import AffinityMap, Image, ProbabilityMap, Segmentation
from chunkflow_tpu.chunk.base import Chunk


def test_image_normalize_contrast():
    rng = np.random.default_rng(0)
    arr = (rng.random((4, 32, 32)) * 100 + 50).astype(np.uint8)
    img = Image(arr)
    normed = img.normalize_contrast()
    out = np.asarray(normed.array)
    assert out.max() > 200  # stretched up
    assert out.min() >= 1


def test_affinity_quantize():
    aff = AffinityMap(np.random.default_rng(0).random((3, 4, 8, 8)).astype(np.float32))
    q = aff.quantize()
    assert q.shape == (4, 8, 8)
    assert q.dtype == np.uint8
    qz = aff.quantize(mode="z")
    np.testing.assert_array_equal(
        np.asarray(qz.array),
        np.clip(np.asarray(aff.array)[0] * 255, 0, 255).astype(np.uint8),
    )


def test_segmentation_evaluate_self_is_perfect():
    rng = np.random.default_rng(0)
    seg = Segmentation(rng.integers(0, 5, (8, 8, 8)).astype(np.uint32))
    scores = seg.evaluate(seg)
    assert scores["rand_index"] == 1.0
    assert scores["adjusted_rand_index"] == 1.0
    assert abs(scores["voi_split"]) < 1e-9
    assert abs(scores["voi_merge"]) < 1e-9


def test_segmentation_evaluate_different():
    rng = np.random.default_rng(0)
    a = Segmentation(rng.integers(1, 5, (8, 8, 8)).astype(np.uint32))
    b = Segmentation(rng.integers(1, 5, (8, 8, 8)).astype(np.uint32))
    scores = a.evaluate(b)
    assert scores["rand_index"] < 1.0
    assert scores["voi_split"] > 0


def test_segmentation_renumber_and_masks():
    arr = np.array([[[0, 5], [5, 9]], [[9, 9], [0, 2]]], dtype=np.uint32)
    seg = Segmentation(arr)
    renum = seg.renumber()
    ids = set(np.unique(np.asarray(renum.array)).tolist())
    assert ids == {0, 1, 2, 3}
    offset = seg.renumber(base_id=100)
    assert set(np.unique(np.asarray(offset.array)).tolist()) == {0, 101, 102, 103}

    dusted = seg.mask_fragments(2)
    assert 2 not in np.unique(np.asarray(dusted.array))  # id 2 has 1 voxel
    assert 9 in np.unique(np.asarray(dusted.array))

    kept = seg.mask_except([5])
    assert set(np.unique(np.asarray(kept.array)).tolist()) == {0, 5}


def test_probability_detect_points():
    arr = np.zeros((8, 16, 16), dtype=np.float32)
    arr[4, 4, 4] = 1.0
    arr[4, 12, 12] = 0.9
    pm = ProbabilityMap(arr, voxel_offset=(100, 0, 0))
    points, conf = pm.detect_points(min_distance=2, threshold_rel=0.3)
    assert points.shape[0] == 2
    assert [104, 4, 4] in points.tolist()
    assert conf.max() == 1.0


def test_channel_voting():
    arr = np.zeros((3, 2, 2, 2), dtype=np.float32)
    arr[1] = 1.0  # channel 1 wins everywhere
    c = Chunk(arr)
    voted = c.channel_voting()
    assert voted.shape == (2, 2, 2)
    assert np.all(np.asarray(voted.array) == 2)


def test_mask_using_last_channel():
    arr = np.ones((3, 2, 2, 2), dtype=np.float32)
    arr[-1, 0] = 0.0  # below threshold -> kept
    c = Chunk(arr)
    masked = c.mask_using_last_channel(threshold=0.3)
    assert masked.shape == (2, 2, 2, 2)
    out = np.asarray(masked.array)
    assert np.all(out[:, 0] == 1.0)
    assert np.all(out[:, 1] == 0.0)


def test_connected_components():
    arr = np.zeros((4, 8, 8), dtype=np.float32)
    arr[0:2, 0:2, 0:2] = 0.9
    arr[2:4, 6:8, 6:8] = 0.9
    c = Chunk(arr)
    seg = c.connected_component(threshold=0.5)
    labels = np.asarray(seg.array)
    assert seg.is_segmentation
    assert labels.max() == 2
    assert labels[0, 0, 0] != labels[3, 7, 7]
    assert labels[0, 0, 0] != 0


def test_maskout_multiresolution():
    chunk = Chunk(
        np.ones((4, 8, 8), dtype=np.float32),
        voxel_offset=(0, 0, 0),
        voxel_size=(1, 1, 1),
    )
    # mask at 2x coarser in y/x
    mask_arr = np.ones((4, 4, 4), dtype=np.uint8)
    mask_arr[:, 0, 0] = 0
    mask = Chunk(mask_arr, voxel_size=(1, 2, 2))
    out = chunk.maskout(mask)
    arr = np.asarray(out.array)
    assert arr[0, 0, 0] == 0 and arr[0, 1, 1] == 0
    assert arr[0, 2, 2] == 1


def test_normalize_contrast_on_device_matches_host():
    import numpy as np

    from chunkflow_tpu.chunk.image import Image

    rng = np.random.default_rng(0)
    arr = rng.integers(10, 240, (4, 16, 16)).astype(np.uint8)
    host_out = Image(arr).normalize_contrast()
    dev_img = Image(arr).device()
    dev_out = dev_img.normalize_contrast()
    assert dev_out.is_on_device
    np.testing.assert_allclose(
        np.asarray(dev_out.array).astype(np.int32),
        np.asarray(host_out.array).astype(np.int32),
        atol=1,  # percentile interpolation may differ by 1 grey level
    )


def test_affinity_from_segmentation():
    """Ground-truth affinity generation: same nonzero label -> inside,
    different labels or background -> boundary, leading planes inside
    (self-edge); metadata carries over from a Chunk input."""
    import numpy as np

    from chunkflow_tpu.chunk import AffinityMap, Segmentation

    seg = np.zeros((2, 3, 3), np.uint32)
    seg[:, :, 0] = 1
    seg[:, :, 2] = 2  # column x=1 stays background 0
    aff = AffinityMap.from_segmentation(seg, inside=0.9, boundary=0.1)
    arr = np.asarray(aff.array)
    assert arr.shape == (3, 2, 3, 3)
    # x-channel: edge (x=1 -> x=0) touches background -> boundary;
    # leading plane x=0 -> inside
    assert arr[2, 0, 0, 0] == np.float32(0.9)
    assert arr[2, 0, 0, 1] == np.float32(0.1)
    assert arr[2, 0, 0, 2] == np.float32(0.1)
    # z-channel within label 1: inside
    assert arr[0, 1, 0, 0] == np.float32(0.9)
    # background-background z edge (x=1 column): never connects
    assert arr[0, 1, 0, 1] == np.float32(0.1)
    # metadata from a Chunk input
    chunk = Segmentation(seg, voxel_offset=(5, 6, 7), voxel_size=(40, 8, 8))
    aff2 = AffinityMap.from_segmentation(chunk)
    assert tuple(aff2.voxel_offset) == (5, 6, 7)
    assert tuple(aff2.voxel_size) == (40, 8, 8)
