"""Black-box detector (reference chunk/validate.py + native NCC)."""
import numpy as np

from chunkflow_tpu.chunk.validate import (
    match_template_ncc,
    validate_by_template_matching,
)


def test_ncc_perfect_match_scores_one():
    rng = np.random.default_rng(0)
    img = rng.random((10, 12, 14))
    template = img[2:4, 3:10, 4:11].copy()
    score = match_template_ncc(img, template)
    assert abs(score[2, 3, 4] - 1.0) < 1e-6
    assert score.max() <= 1.0 + 1e-6


def test_validate_clean_image_passes():
    rng = np.random.default_rng(1)
    img = rng.integers(1, 255, size=(16, 64, 64), dtype=np.uint8)
    assert validate_by_template_matching(img)


def test_validate_black_box_fails():
    rng = np.random.default_rng(2)
    img = rng.integers(1, 255, size=(32, 128, 128), dtype=np.uint8)
    img[8:24, 32:96, 32:96] = 0  # the black box
    assert not validate_by_template_matching(img)


def test_validate_float_skipped():
    img = np.zeros((16, 32, 32), dtype=np.float32)
    assert validate_by_template_matching(img)
