"""Grey adjustment library (parity: reference tests/chunk/image/test_adjust_grey.py
semantics + the adjust_grey.py function contracts)."""
import numpy as np
import pytest

from chunkflow_tpu.chunk import adjust_grey
from chunkflow_tpu.chunk.image import Image


def test_clip_percentile_stretches_to_full_range():
    rng = np.random.default_rng(0)
    img = rng.integers(50, 200, size=(4, 32, 32), dtype=np.uint8)
    out = adjust_grey.clip_percentile(img, 0.01, 0.01)
    assert out.dtype == np.uint8
    assert out.min() < 10
    assert out.max() > 245


def test_clip_percentile_noop_range_preserved_shape():
    img = np.zeros((2, 8, 8), dtype=np.uint8)
    out = adjust_grey.clip_percentile(img)
    assert out.shape == img.shape


def test_window_level_maps_edges_to_unit():
    img = np.array([0.0, 0.5, 1.0], dtype=np.float32)
    out = adjust_grey.window_level(img.copy(), half_window=0.5, level=0.5)
    np.testing.assert_allclose(out, [-1.0, 0.0, 1.0], atol=1e-6)
    with pytest.raises(ValueError):
        adjust_grey.window_level(img, half_window=0.0, level=0.5)


def test_rescale_linear_map():
    img = np.array([0.0, 0.5, 1.0], dtype=np.float32)
    out = adjust_grey.rescale(img.copy(), (0, 1), (-1, 1))
    np.testing.assert_allclose(out, [-1.0, 0.0, 1.0], atol=1e-6)
    same = adjust_grey.rescale(img.copy(), (0, 1), (0, 1))
    np.testing.assert_allclose(same, img)


def test_normalize_meanstd_excludes_extremes():
    rng = np.random.default_rng(1)
    img = rng.random((16, 16)).astype(np.float32)
    img[0, 0] = 0.0   # invalid min
    img[0, 1] = 1.0   # invalid max
    out = adjust_grey.normalize(img, "meanstd")
    # the valid voxels are z-scored
    got = out[(img != 0.0) & (img != 1.0)]
    np.testing.assert_allclose(got.mean(), 0.0, atol=1e-5)
    np.testing.assert_allclose(got.std(), 1.0, atol=1e-4)


def test_normalize_fill_hits_target_range():
    rng = np.random.default_rng(2)
    img = rng.random((8, 8)).astype(np.float32) * 100
    out = adjust_grey.normalize(img, "fill", target_scale=(-1, 1),
                                min_max_invalid=(False, False))
    np.testing.assert_allclose(out.min(), -1.0, atol=1e-5)
    np.testing.assert_allclose(out.max(), 1.0, atol=1e-5)


def test_adjust_gamma_identity_and_clip():
    img = np.linspace(0, 1, 11, dtype=np.float32)
    out = adjust_grey.adjust_gamma(img.copy(), 1.0)
    np.testing.assert_allclose(out, img, atol=1e-6)
    out2 = adjust_grey.adjust_gamma(np.array([-0.5, 2.0], np.float32), 2.0)
    np.testing.assert_allclose(out2, [0.0, 1.0])


def test_grey_augment_stays_in_range():
    rng = np.random.default_rng(3)
    img = (rng.random((4, 16, 16), dtype=np.float32) * 2 - 1)
    out = adjust_grey.grey_augment(img, rng=np.random.default_rng(4))
    assert out.shape == img.shape
    assert out.min() >= -1.0 - 1e-5
    assert out.max() <= 1.0 + 1e-5


def test_normalize_shang_per_slice_fill():
    rng = np.random.default_rng(5)
    img = (rng.random((3, 16, 16)) * 100).astype(np.float32)
    out = adjust_grey.normalize_shang(img, 0.0, 1.0, clipvalues=True)
    assert out.dtype == np.float32
    assert out.min() >= 0.0 and out.max() <= 1.0
    # slice-wise: each slice's valid voxels span the target range
    for zz in range(3):
        assert out[zz].max() > 0.9


def test_image_normalize_shang_method():
    rng = np.random.default_rng(6)
    img = Image(
        (rng.random((3, 8, 8)) * 255).astype(np.uint8),
        voxel_offset=(1, 2, 3),
    )
    out = img.normalize_shang(0.0, 1.0, clipvalues=True)
    assert out.dtype == np.float32
    assert tuple(out.voxel_offset) == (1, 2, 3)


def test_normalize_shang_blank_slice_still_clipped():
    img = (np.ones((2, 8, 8)) * 255).astype(np.float32)
    img[1] = np.random.default_rng(7).random((8, 8)) * 255
    out = adjust_grey.normalize_shang(img, 0.0, 1.0, clipvalues=True)
    # the constant slice cannot be rescaled, but the [0, 1] output
    # contract must still hold
    assert out.max() <= 1.0
