import numpy as np
import pytest

from chunkflow_tpu.chunk.base import Chunk, LayerType
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian


def test_create_patterns():
    sin = Chunk.create((8, 8, 8), dtype=np.uint8, pattern="sin")
    assert sin.shape == (8, 8, 8)
    assert sin.dtype == np.uint8
    zero = Chunk.create((4, 4, 4), pattern="zero")
    assert zero.all_zero()
    rand = Chunk.create((4, 4, 4), dtype=np.float32, pattern="random")
    assert not rand.all_zero()
    multi = Chunk.create((4, 4, 4), dtype=np.float32, nchannels=3)
    assert multi.shape == (3, 4, 4, 4)
    assert multi.nchannels == 3


def test_layer_type_inference():
    assert Chunk(np.zeros((4, 4, 4), dtype=np.uint8)).is_image
    assert Chunk(np.zeros((4, 4, 4), dtype=np.uint32)).is_segmentation
    assert Chunk(np.zeros((3, 4, 4, 4), dtype=np.float32)).is_affinity_map
    assert Chunk(np.zeros((4, 4, 4), dtype=np.float32)).is_probability_map


def test_bbox_and_geometry():
    c = Chunk.create((8, 8, 8), voxel_offset=(10, 20, 30))
    assert c.voxel_offset == Cartesian(10, 20, 30)
    assert c.bbox == BoundingBox((10, 20, 30), (18, 28, 38))
    sub = c.cutout(BoundingBox((12, 22, 32), (14, 24, 34)))
    assert sub.shape == (2, 2, 2)
    assert sub.voxel_offset == Cartesian(12, 22, 32)
    np.testing.assert_array_equal(sub.array, np.asarray(c.array)[2:4, 2:4, 2:4])
    with pytest.raises(ValueError):
        c.cutout(BoundingBox((0, 0, 0), (4, 4, 4)))


def test_save_and_blend():
    base = Chunk(np.zeros((8, 8, 8), dtype=np.float32))
    patch = Chunk(
        np.ones((4, 4, 4), dtype=np.float32), voxel_offset=(2, 2, 2)
    )
    base.save(patch)
    assert base.array[2:6, 2:6, 2:6].sum() == 64
    base.blend(patch)
    assert base.array[3, 3, 3] == 2.0
    assert base.array[0, 0, 0] == 0.0


def test_crop_margin():
    c = Chunk.create((8, 8, 8), voxel_offset=(0, 0, 0))
    cropped = c.crop_margin((2, 2, 2))
    assert cropped.shape == (4, 4, 4)
    assert cropped.voxel_offset == Cartesian(2, 2, 2)
    # 4d
    c4 = Chunk.create((8, 8, 8), dtype=np.float32, nchannels=2)
    cropped4 = c4.crop_margin((1, 2, 3))
    assert cropped4.shape == (2, 6, 4, 2)


def test_ufunc_keeps_metadata():
    c = Chunk.create((4, 4, 4), dtype=np.float32, voxel_offset=(1, 2, 3))
    doubled = c * 2.0
    assert isinstance(doubled, Chunk)
    assert doubled.voxel_offset == Cartesian(1, 2, 3)
    np.testing.assert_allclose(np.asarray(doubled.array), np.asarray(c.array) * 2)
    summed = c + c
    assert isinstance(summed, Chunk)
    # reduction escapes the wrapper
    assert isinstance(np.sum(c), (np.floating, float, np.ndarray))


def test_inplace_ufunc():
    c = Chunk(np.full((4, 4, 4), 4.0, dtype=np.float32))
    c /= 2.0
    assert isinstance(c, Chunk)
    assert float(np.asarray(c.array)[0, 0, 0]) == 2.0


def test_transpose():
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    c = Chunk(arr, voxel_offset=(1, 2, 3), voxel_size=(40, 4, 4))
    t = c.transpose()
    assert t.shape == (4, 3, 2)
    assert t.voxel_offset == Cartesian(3, 2, 1)
    assert t.voxel_size == Cartesian(4, 4, 40)
    np.testing.assert_array_equal(np.asarray(t.array), arr.transpose(2, 1, 0))


def test_h5_roundtrip(tmp_path):
    c = Chunk.create(
        (6, 6, 6), dtype=np.float32, voxel_offset=(4, 5, 6), voxel_size=(40, 4, 4)
    )
    path = str(tmp_path / "chunk.h5")
    c.to_h5(path)
    loaded = Chunk.from_h5(path)
    assert loaded.voxel_offset == c.voxel_offset
    assert loaded.voxel_size == c.voxel_size
    np.testing.assert_array_equal(np.asarray(loaded.array), np.asarray(c.array))
    # windowed read
    window = BoundingBox((5, 6, 7), (7, 8, 9))
    sub = Chunk.from_h5(path, bbox=window)
    assert sub.voxel_offset == Cartesian(5, 6, 7)
    np.testing.assert_array_equal(
        np.asarray(sub.array), np.asarray(c.cutout(window).array)
    )


def test_tif_roundtrip(tmp_path):
    c = Chunk.create((4, 8, 8), dtype=np.uint8)
    path = str(tmp_path / "chunk.tif")
    c.to_tif(path)
    loaded = Chunk.from_tif(path)
    np.testing.assert_array_equal(np.asarray(loaded.array), np.asarray(c.array))


def test_device_roundtrip():
    c = Chunk.create((4, 4, 4), dtype=np.float32)
    d = c.device()
    assert d.is_on_device
    back = d.host()
    assert not back.is_on_device
    np.testing.assert_array_equal(np.asarray(back.array), np.asarray(c.array))


def test_pad_to():
    c = Chunk.create((3, 5, 7), dtype=np.float32)
    p = c.pad_to((4, 8, 8))
    assert p.shape == (4, 8, 8)
    np.testing.assert_array_equal(np.asarray(p.array)[:3, :5, :7], np.asarray(c.array))


def test_shrink():
    c = Chunk.create(size=(8, 8, 8), voxel_offset=(1, 2, 3))
    s = c.shrink((1, 2, 3))
    assert s.shape == (6, 4, 2)
    assert tuple(s.voxel_offset) == (2, 4, 6)
    s6 = c.shrink((1, 1, 1, 2, 2, 2))
    assert s6.shape == (5, 5, 5)
    assert tuple(s6.voxel_offset) == (2, 3, 4)


def test_add_overlap():
    a = Chunk(np.ones((4, 4, 4), np.float32), voxel_offset=(0, 0, 0))
    b = Chunk(np.ones((4, 4, 4), np.float32), voxel_offset=(0, 0, 2))
    a.add_overlap(b)
    assert np.asarray(a.array)[:, :, :2].sum() == 32  # untouched
    assert np.asarray(a.array)[:, :, 2:].sum() == 64  # overlap doubled


def test_from_array():
    from chunkflow_tpu.core.bbox import BoundingBox

    bbox = BoundingBox.from_delta((2, 3, 4), (4, 4, 4))
    c = Chunk.from_array(np.zeros((4, 4, 4), np.uint8), bbox)
    assert tuple(c.voxel_offset) == (2, 3, 4)


def test_segmentation_remap():
    from chunkflow_tpu.chunk.segmentation import Segmentation

    arr = np.array([[[0, 7, 7], [9, 9, 0], [0, 0, 42]]], dtype=np.uint32)
    seg = Segmentation(arr)
    out, new_base = seg.remap(base_id=100)
    assert isinstance(out, Segmentation)
    assert out.dtype == np.uint64
    vals = np.unique(np.asarray(out.array))
    assert set(vals.tolist()) == {0, 101, 102, 103}
    assert new_base == 103


def test_segmentation_remap_overflow_and_empty():
    from chunkflow_tpu.chunk.segmentation import Segmentation

    # base_id near uint32 max must not wrap (offset applies after uint64 cast)
    seg = Segmentation(np.array([[[0, 1, 2, 3]]], dtype=np.uint32))
    out, base = seg.remap(base_id=2**32 - 2)
    vals = set(np.unique(np.asarray(out.array)).tolist())
    assert vals == {0, 2**32 - 1, 2**32, 2**32 + 1}
    assert base == 2**32 + 1

    # empty chunk must preserve the accumulated base id
    empty = Segmentation(np.zeros((1, 2, 2), dtype=np.uint32))
    _, base = empty.remap(base_id=100)
    assert base == 100


def test_shrink_rejects_negative():
    c = Chunk.create(size=(8, 8, 8))
    with pytest.raises(ValueError):
        c.shrink((-1, 0, 0))


def test_from_array_shape_mismatch():
    from chunkflow_tpu.core.bbox import BoundingBox

    bbox = BoundingBox.from_delta((0, 0, 0), (8, 8, 8))
    with pytest.raises(ValueError):
        Chunk.from_array(np.zeros((4, 4, 4), np.uint8), bbox)


def test_shrink_rejects_overconsume():
    c = Chunk.create(size=(8, 8, 8))
    with pytest.raises(ValueError):
        c.shrink((0, 0, 0, 9, 0, 0))
    with pytest.raises(ValueError):
        c.shrink((4, 0, 0, 4, 0, 0))


def test_renumber_base_id_no_wrap():
    from chunkflow_tpu.chunk.segmentation import Segmentation

    seg = Segmentation(np.array([[[0, 1, 2]]], dtype=np.uint32))
    out = seg.renumber(base_id=2**32 - 2)
    vals = set(np.unique(np.asarray(out.array)).tolist())
    assert vals == {0, 2**32 - 1, 2**32}


def test_reference_api_surface():
    """Drop-in reference spellings (reference chunk/base.py:517-760):
    bounding_box/start/stop/size/ndoffset/slices/properties/fill/where."""
    from chunkflow_tpu.chunk.base import Chunk

    c = Chunk(np.zeros((2, 4, 6, 8), np.float32), voxel_offset=(1, 2, 3),
              voxel_size=(40, 4, 4))
    assert c.bounding_box == c.bbox
    assert tuple(c.start) == (1, 2, 3) and tuple(c.stop) == (5, 8, 11)
    assert c.size == 2 * 4 * 6 * 8
    assert c.ndoffset == (0, 1, 2, 3)
    assert c.slices == (slice(0, 2), slice(1, 5), slice(2, 8), slice(3, 11))
    props = c.properties
    assert tuple(props["voxel_size"]) == (40, 4, 4)
    c2 = Chunk(np.zeros((4, 6, 8), np.uint8))
    c2.properties = props  # reference setter spelling
    assert tuple(c2.voxel_offset) == (1, 2, 3)
    assert c2.layer_type == c.layer_type

    c2.fill(7)
    assert (np.asarray(c2.array) == 7).all()
    mask = np.zeros((4, 6, 8), bool)
    mask[0, 0, 0] = True
    z, y, x = c2.where(mask)
    assert (z[0], y[0], x[0]) == (1, 2, 3)
    assert c2.ascontiguousarray() is c2
