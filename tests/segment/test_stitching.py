"""Stitching correctness: the map->reduce->map output must be exactly
label-isomorphic to one monolithic labeling pass — across connectivity,
ragged grids, multi-valued inputs, the device leg, nonzero-offset
domains — and every stage must replay idempotently (ISSUE 20)."""
import numpy as np
import pytest

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.ops import connected_components as cc
from chunkflow_tpu.segment import labels_isomorphic, segment_volume
from chunkflow_tpu.segment.driver import run_local
from chunkflow_tpu.segment.plan import SegmentPlan
from chunkflow_tpu.segment.stages import (
    LABEL_DTYPE,
    SegmentStore,
    label_chunk,
    merge_node,
    relabel_chunk,
)
from chunkflow_tpu.volume.storage import (
    KVArrayBackend,
    MemoryBackend,
    MemoryKV,
    blockwise_cutout,
    blockwise_save,
)


def _monolithic(arr, connectivity, multivalue=False, threshold=0.5):
    if multivalue:
        return cc.label_multivalue(arr, connectivity=connectivity)
    if np.dtype(arr.dtype).kind == "f":
        return cc.label_binary(arr > threshold, connectivity=connectivity)
    return cc.label_binary(arr != 0, connectivity=connectivity)


# ---------------------------------------------------------------------------
# isomorphism across connectivity / grid shape / input kind
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("connectivity", [6, 18, 26])
@pytest.mark.parametrize(
    "shape,chunk",
    [
        ((24, 24, 24), (8, 8, 8)),    # even grid
        ((13, 17, 9), (5, 6, 4)),     # ragged on every axis
        ((7, 20, 6), (7, 6, 6)),      # single-chunk axes + ragged axis
    ],
)
def test_binary_stitch_isomorphic(connectivity, shape, chunk):
    rng = np.random.default_rng(connectivity * 100 + shape[0])
    dense = (rng.random(shape) > 0.62).astype(np.float32)
    out = segment_volume(
        dense, chunk, connectivity=connectivity, workers=3
    )
    assert labels_isomorphic(out, _monolithic(dense, connectivity))


@pytest.mark.parametrize("connectivity", [6, 26])
def test_multivalue_stitch_isomorphic(connectivity):
    rng = np.random.default_rng(connectivity)
    # dense multi-id field: different ids touch everywhere, so the
    # equal-value edge mask is load-bearing, not incidental
    ids = rng.integers(0, 4, size=(14, 11, 10)).astype(np.uint32)
    out = segment_volume(
        ids, (6, 5, 4), connectivity=connectivity,
        multivalue=True, workers=2,
    )
    assert labels_isomorphic(
        out, _monolithic(ids, connectivity, multivalue=True)
    )


def test_single_chunk_grid_degenerates_cleanly():
    rng = np.random.default_rng(0)
    dense = (rng.random((6, 6, 6)) > 0.5).astype(np.float32)
    out = segment_volume(dense, (8, 8, 8), connectivity=26)
    assert labels_isomorphic(out, _monolithic(dense, 26))


def test_device_leg_stitch_isomorphic():
    rng = np.random.default_rng(2)
    dense = (rng.random((12, 12, 12)) > 0.6).astype(np.float32)
    out = segment_volume(
        dense, (6, 6, 6), connectivity=26, device=True, workers=1
    )
    assert labels_isomorphic(out, _monolithic(dense, 26))


def test_empty_and_full_volumes():
    zeros = np.zeros((9, 9, 9), dtype=np.uint8)
    assert not segment_volume(zeros, (4, 4, 4)).any()
    ones = np.ones((9, 9, 9), dtype=np.uint8)
    out = segment_volume(ones, (4, 4, 4), connectivity=6)
    assert labels_isomorphic(out, _monolithic(ones, 6))
    assert out.all() and np.unique(out).size == 1  # one object, no bg


def _kv_store(arr, start, chunk, connectivity=26):
    """A store over a nonzero-offset domain, input and labels both held
    in KVArrayBackends (the multi-process layout, in memory)."""
    stop = tuple(s + d for s, d in zip(start, arr.shape))
    plan = SegmentPlan(BoundingBox(start, stop), chunk)
    input_b = KVArrayBackend(
        MemoryKV(), domain=(start, stop), dtype=arr.dtype,
        block_shape=chunk, prefix="in",
    )
    blockwise_save(input_b, start, arr)
    seg_b = KVArrayBackend(
        MemoryKV(), domain=(start, stop), dtype=LABEL_DTYPE,
        block_shape=chunk, prefix="seg",
    )
    return SegmentStore(
        plan, input_b, seg_b, MemoryKV(), connectivity=connectivity
    )


def test_nonzero_offset_domain():
    rng = np.random.default_rng(5)
    arr = (rng.random((13, 10, 11)) > 0.6).astype(np.uint8)
    start = (32, 7, 129)
    store = _kv_store(arr, start, (5, 4, 6))
    run_local(store, workers=2)
    stop = tuple(s + d for s, d in zip(start, arr.shape))
    out = blockwise_cutout(store.seg_backend, start, stop)
    assert labels_isomorphic(out, _monolithic(arr, 26))


# ---------------------------------------------------------------------------
# replay idempotence (the exactly-once argument, docs/segmentation.md)
# ---------------------------------------------------------------------------
def test_every_stage_replays_identically():
    """A SIGKILLed worker's task is redelivered and re-executed in full;
    each stage must rewrite byte-identical state. Replays happen within
    a stage's own phase — once a task's ledger marker exists the
    lifecycle skips it, so a label task can never replay after the
    merge wave consumed its faces."""
    rng = np.random.default_rng(6)
    arr = (rng.random((12, 10, 8)) > 0.55).astype(np.uint8)
    store = _kv_store(arr, (0, 0, 0), (6, 5, 4))
    plan = store.plan

    def snapshot():
        return (
            dict(store.kv._data),
            blockwise_cutout(
                store.seg_backend, plan.bbox.start, plan.bbox.stop
            ),
        )

    def assert_unchanged(before):
        kv_before, seg_before = before
        kv_after, seg_after = snapshot()
        assert np.array_equal(seg_before, seg_after)
        assert kv_after.keys() == kv_before.keys()
        for key, data in kv_before.items():
            assert kv_after[key] == data, key

    for chunk in plan.chunks:
        label_chunk(store, chunk)
    before = snapshot()
    label_chunk(store, plan.chunks[0])  # mid-phase replay
    assert_unchanged(before)

    interior = [
        n.bbox for n in plan.make_tree().post_order() if not n.is_leaf
    ]
    for bbox in interior:
        merge_node(store, bbox)
    before = snapshot()
    merge_node(store, interior[0])
    merge_node(store, interior[-1])  # the root: rewrites the remap too
    assert_unchanged(before)

    for chunk in plan.chunks:
        relabel_chunk(store, chunk)
    before = snapshot()
    relabel_chunk(store, plan.chunks[-1])  # fixpoint: a no-op rewrite
    assert_unchanged(before)


# ---------------------------------------------------------------------------
# plan geometry invariants
# ---------------------------------------------------------------------------
def test_every_grid_interface_is_covered_exactly_once():
    """The merge reduce's coverage invariant: for every internal grid
    interface (axis, coordinate), the interior nodes splitting there
    tile the full ROI cross-section exactly once — no voxel-to-voxel
    contact is compared twice or missed."""
    roi = BoundingBox((0, 0, 0), (13, 17, 9))
    plan = SegmentPlan(roi, (5, 6, 4))
    internal = set()
    for axis in range(3):
        for chunk in plan.chunks:
            coord = int(chunk.stop[axis])
            if coord < int(roi.stop[axis]):
                internal.add((axis, coord))
    shape = tuple(int(s) for s in roi.shape)
    coverage = {
        key: np.zeros(
            tuple(shape[d] for d in range(3) if d != key[0]), dtype=int
        )
        for key in internal
    }
    for node in plan.make_tree().walk():
        if node.is_leaf:
            continue
        axis = plan.split_axis(node)
        split = int(node.left.bbox.stop[axis])
        low, high = plan.plane_chunks(node)[2:]
        # the node's plane is tiled exactly by its low/high chunk faces
        assert low and high
        inplane = [d for d in range(3) if d != axis]
        window = tuple(
            slice(int(node.bbox.start[d]), int(node.bbox.stop[d]))
            for d in inplane
        )
        coverage[(axis, split)][window] += 1
        for side in (low, high):
            area = sum(
                np.prod([
                    int(c.stop[d]) - int(c.start[d]) for d in inplane
                ]) for c in side
            )
            assert area == np.prod([
                int(node.bbox.stop[d]) - int(node.bbox.start[d])
                for d in inplane
            ]), (axis, split)
    for key, plane in coverage.items():
        assert (plane == 1).all(), key  # exactly once, everywhere


def test_global_id_ranges_are_collision_free():
    plan = SegmentPlan(BoundingBox((0, 0, 0), (13, 17, 9)), (5, 6, 4))
    offsets = sorted(plan.id_offset(c) for c in plan.chunks)
    assert len(set(offsets)) == len(plan.chunks)
    for a, b in zip(offsets, offsets[1:]):
        assert b - a >= plan.id_stride
    # the stride bounds the per-chunk label count for both legs: host
    # labels are consecutive 1..n (n <= voxels), device labels are
    # linear-index+1 (<= voxels)
    assert plan.id_stride == 5 * 6 * 4


def test_task_bodies_round_trip():
    plan = SegmentPlan(BoundingBox((0, 0, 0), (12, 12, 12)), (6, 6, 6))
    chunk = plan.chunks[3]
    for body, kind in (
        (plan.label_body(chunk), "label"),
        (plan.merge_body(plan.bbox), "merge"),
        (plan.relabel_body(chunk), "relabel"),
    ):
        parsed = SegmentPlan.parse_body(body)
        assert parsed is not None
        assert parsed[0] == kind
    assert SegmentPlan.parse_body(chunk.string) is None  # plain traffic
    assert SegmentPlan.parse_body("unrelated") is None


def test_store_rejects_bad_connectivity():
    plan = SegmentPlan(BoundingBox((0, 0, 0), (8, 8, 8)), (4, 4, 4))
    with pytest.raises(ValueError):
        SegmentStore(
            plan,
            MemoryBackend(np.zeros((8, 8, 8), np.uint8)),
            MemoryBackend(np.zeros((8, 8, 8), LABEL_DTYPE)),
            MemoryKV(),
            connectivity=4,
        )


def test_relabel_before_root_merge_raises():
    rng = np.random.default_rng(8)
    arr = (rng.random((8, 8, 8)) > 0.5).astype(np.uint8)
    store = _kv_store(arr, (0, 0, 0), (4, 4, 4))
    label_chunk(store, store.plan.chunks[0])
    with pytest.raises(RuntimeError, match="remap table"):
        relabel_chunk(store, store.plan.chunks[0])
