"""Chaos acceptance of the stitching job (ISSUE 20): a REAL worker
subprocess is SIGKILLed inside a merge task (the ``segment/merge``
chaos point fires after the inputs are read and before the table is
written — mid-merge by construction), its lease expires, and the
surviving worker replays the merge. The final segmentation must be
label-isomorphic to a fault-free monolithic labeling, with exactly one
ledger marker per tree node and per relabel chunk."""
import os
import subprocess
import sys
import time

import numpy as np

from chunkflow_tpu.ops import connected_components as cc
from chunkflow_tpu.parallel.lifecycle import FileLedger
from chunkflow_tpu.parallel.queues import open_queue
from chunkflow_tpu.segment import labels_isomorphic, open_store
from chunkflow_tpu.segment.driver import export_segmentation

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _worker_cmd(qdir, ledger, seg_dir, vis=3):
    return [
        sys.executable, "-m", "chunkflow_tpu.flow.cli",
        "fetch-task-from-queue", "-q", str(qdir), "-v", str(vis),
        "-r", "400", "--poll-interval", "0.05", "--max-retries", "3",
        "--ledger", str(ledger),
        "label-chunk", "-d", str(seg_dir),
        "merge-seg", "-d", str(seg_dir),
        "relabel", "-d", str(seg_dir),
        "delete-task-in-queue",
    ]


def test_sigkill_mid_merge_replays_to_isomorphic_result(tmp_path):
    rng = np.random.default_rng(11)
    arr = (rng.random((14, 12, 10)) > 0.6).astype(np.float32)
    input_npy = tmp_path / "input.npy"
    np.save(input_npy, arr)
    seg_dir = tmp_path / "job"
    qdir = tmp_path / "queue"
    ledger = tmp_path / "ledger"

    base_env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        PYTHONPATH=_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    base_env.pop("XLA_FLAGS", None)

    coordinator = subprocess.Popen(
        [sys.executable, "-m", "chunkflow_tpu.flow.cli", "segment-volume",
         "-i", str(input_npy), "-d", str(seg_dir), "-c", "6", "6", "6",
         "--connectivity", "26", "-q", str(qdir), "--ledger", str(ledger),
         "--timeout", "150"],
        env=base_env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        # let spec.json land before the workers open the store
        deadline = time.monotonic() + 30
        while not (seg_dir / "spec.json").exists():
            assert coordinator.poll() is None, coordinator.communicate()[0]
            assert time.monotonic() < deadline
            time.sleep(0.05)

        # worker A self-SIGKILLs inside its first merge: the task is
        # claimed, the faces/child tables are read, the output is not
        # yet written — true process death, nothing unwinds
        env_a = dict(base_env,
                     CHUNKFLOW_CHAOS="once=segment/merge:action=kill")
        proc_a = subprocess.Popen(
            _worker_cmd(qdir, ledger, seg_dir), env=env_a,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        # worker B: clean; drains everything A dropped once the lease
        # expires (visibility 3s -> janitored back to pending)
        proc_b = subprocess.Popen(
            _worker_cmd(qdir, ledger, seg_dir), env=base_env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

        out, _ = coordinator.communicate(timeout=180)
        assert coordinator.returncode == 0, out[-3000:]
        rc_a = proc_a.wait(timeout=60)
        assert rc_a in (-9, 137), (rc_a, proc_a.communicate()[0][-2000:])
        rc_b = proc_b.wait(timeout=60)
        assert rc_b == 0, proc_b.communicate()[0][-2000:]
    finally:
        for proc in (coordinator,):
            if proc.poll() is None:
                proc.kill()

    store = open_store(str(seg_dir))
    seg = export_segmentation(store)
    mono = cc.label_binary(arr > 0.5, connectivity=26)
    assert labels_isomorphic(seg, mono)

    # exactly one ledger marker per tree node body + per relabel body
    plan = store.plan
    expected = {plan.node_body(n) for n in plan.make_tree().walk()}
    expected |= {plan.relabel_body(c) for c in plan.chunks}
    assert sorted(FileLedger(str(ledger)).keys()) == sorted(expected)

    # the queue drained clean: nothing pending, in flight or poisoned
    queue = open_queue(str(qdir))
    assert queue.stats()["pending"] == 0
    assert queue.stats()["inflight"] == 0
    assert queue.dead_letters() == []
