"""Unit tests of the reduce algebra: face-pair edges, union-find and
the label-isomorphism oracle (chunkflow_tpu/segment/merge_table.py)."""
import numpy as np
import pytest

from chunkflow_tpu.segment.merge_table import (
    face_pair_edges,
    labels_isomorphic,
    merge_edge_sets,
    merge_table,
    union_find,
)


# ---------------------------------------------------------------------------
# face_pair_edges
# ---------------------------------------------------------------------------
def test_face_edges_direct_contact():
    low = np.array([[1, 0], [0, 2]], dtype=np.uint64)
    high = np.array([[5, 0], [0, 0]], dtype=np.uint64)
    edges = face_pair_edges(low, high, connectivity=6)
    assert edges.tolist() == [[1, 5]]


def test_face_edges_diagonal_only_visible_at_26():
    # the two nonzero voxels touch only corner-to-corner across the face
    low = np.zeros((3, 3), dtype=np.uint64)
    high = np.zeros((3, 3), dtype=np.uint64)
    low[0, 0] = 7
    high[1, 1] = 9
    assert face_pair_edges(low, high, connectivity=6).size == 0
    assert face_pair_edges(low, high, connectivity=18).size == 0
    edges = face_pair_edges(low, high, connectivity=26)
    assert edges.tolist() == [[7, 9]]


def test_face_edges_inplane_offset_at_18():
    # offset by one along a single in-plane axis: an edge-contact, which
    # 18-connectivity sees but 6 does not
    low = np.zeros((3, 3), dtype=np.uint64)
    high = np.zeros((3, 3), dtype=np.uint64)
    low[1, 1] = 3
    high[1, 2] = 4
    assert face_pair_edges(low, high, connectivity=6).size == 0
    assert face_pair_edges(low, high, connectivity=18).tolist() == [[3, 4]]
    assert face_pair_edges(low, high, connectivity=26).tolist() == [[3, 4]]


def test_face_edges_dedupe_and_zero_dropped():
    low = np.full((4, 4), 2, dtype=np.uint64)
    high = np.full((4, 4), 8, dtype=np.uint64)
    high[0, :] = 0
    edges = face_pair_edges(low, high, connectivity=26)
    assert edges.tolist() == [[2, 8]]


def test_face_edges_value_mask():
    # multivalue mode: equal labels but DIFFERENT input values on the
    # two sides must not merge
    low = np.array([[1, 1]], dtype=np.uint64)
    high = np.array([[2, 2]], dtype=np.uint64)
    low_vals = np.array([[5, 6]], dtype=np.uint64)
    high_vals = np.array([[5, 7]], dtype=np.uint64)
    edges = face_pair_edges(
        low, high, connectivity=6,
        low_values=low_vals, high_values=high_vals,
    )
    assert edges.tolist() == [[1, 2]]  # only the value-5 column
    with pytest.raises(ValueError):
        face_pair_edges(low, high, connectivity=6, low_values=low_vals)


def test_face_edges_shape_mismatch_raises():
    with pytest.raises(ValueError):
        face_pair_edges(
            np.zeros((2, 2), np.uint64), np.zeros((2, 3), np.uint64)
        )
    with pytest.raises(ValueError):
        face_pair_edges(
            np.zeros((2, 2), np.uint64), np.zeros((2, 2), np.uint64),
            connectivity=4,
        )


# ---------------------------------------------------------------------------
# union_find / merge_table
# ---------------------------------------------------------------------------
def test_union_find_chain_compresses_to_min():
    edges = np.array([[2, 3], [3, 4], [4, 5]], dtype=np.uint64)
    ids, roots = union_find(edges)
    assert ids.tolist() == [2, 3, 4, 5]
    assert roots.tolist() == [2, 2, 2, 2]


def test_union_find_disjoint_components():
    edges = np.array([[10, 11], [20, 21], [21, 22]], dtype=np.uint64)
    ids, roots = union_find(edges)
    assert dict(zip(ids.tolist(), roots.tolist())) == {
        10: 10, 11: 10, 20: 20, 21: 20, 22: 20,
    }


def test_union_find_random_against_scipy():
    rng = np.random.default_rng(0)
    n = 200
    edges = rng.integers(1, 60, size=(n, 2)).astype(np.uint64)
    ids, roots = union_find(edges)
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components as sp_cc

    idx = np.searchsorted(ids, edges)
    graph = coo_matrix(
        (np.ones(n), (idx[:, 0], idx[:, 1])), shape=(ids.size, ids.size)
    )
    _, comp = sp_cc(graph, directed=False)
    # same partition, and each root is the min id of its component
    for c in np.unique(comp):
        members = ids[comp == c]
        assert (roots[comp == c] == members.min()).all()


def test_union_find_empty():
    ids, roots = union_find(np.empty((0, 2), dtype=np.uint64))
    assert ids.size == 0 and roots.size == 0


def test_merge_table_is_fixpoint():
    table = merge_table([np.array([[5, 9], [9, 12], [3, 4]], np.uint64)])
    keys, values = table[:, 0], table[:, 1]
    # non-identity rows only, and no value ever appears as a key: the
    # table is a fixpoint, so applying it twice equals applying it once
    # (the idempotent-relabel property, docs/segmentation.md)
    assert (keys != values).all()
    assert not np.isin(values, keys).any()


def test_merge_edge_sets_combines_tables_and_edges():
    a = np.array([[1, 2]], dtype=np.uint64)
    b = np.array([[2, 3], [1, 2]], dtype=np.uint64)
    merged = merge_edge_sets([a, b, np.empty((0, 2), np.uint64)])
    assert merged.tolist() == [[1, 2], [2, 3]]


# ---------------------------------------------------------------------------
# labels_isomorphic
# ---------------------------------------------------------------------------
def test_isomorphic_accepts_renamed_labels():
    a = np.array([[0, 1, 1], [2, 2, 0]])
    b = np.array([[0, 9, 9], [4, 4, 0]])
    assert labels_isomorphic(a, b)


def test_isomorphic_rejects_split_and_merge():
    a = np.array([[1, 1, 2]])
    merged = np.array([[7, 7, 7]])   # two objects fused
    split = np.array([[1, 3, 2]])    # one object split
    assert not labels_isomorphic(a, merged)
    assert not labels_isomorphic(merged, a)
    assert not labels_isomorphic(a, split)


def test_isomorphic_rejects_background_mismatch_and_shape():
    a = np.array([[0, 1]])
    assert not labels_isomorphic(a, np.array([[1, 1]]))
    assert not labels_isomorphic(a, np.array([[0, 1, 0]]))
