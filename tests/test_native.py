import numpy as np
import pytest

from chunkflow_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


class TestConnectedComponents:
    def test_multivalue_and_counts(self):
        arr = np.zeros((8, 8, 8), np.uint32)
        arr[:2, :2, :2] = 5
        arr[6:, 6:, 6:] = 5
        arr[4, 4, 4] = 9
        labels, count = native.connected_components(arr)
        assert count == 3
        assert labels[0, 0, 0] != labels[7, 7, 7]
        assert labels[4, 4, 4] not in (labels[0, 0, 0], labels[7, 7, 7])
        assert labels[3, 3, 3] == 0

    def test_connectivity_semantics(self):
        diag = np.zeros((2, 2, 2), np.uint8)
        diag[0, 0, 0] = diag[1, 1, 1] = 1
        assert native.connected_components(diag, 26)[1] == 1
        assert native.connected_components(diag, 18)[1] == 2
        assert native.connected_components(diag, 6)[1] == 2
        edge = np.zeros((1, 2, 2), np.uint8)
        edge[0, 0, 0] = edge[0, 1, 1] = 1
        assert native.connected_components(edge, 18)[1] == 1
        assert native.connected_components(edge, 6)[1] == 2

    def test_matches_scipy_on_binary(self):
        from scipy import ndimage

        rng = np.random.default_rng(0)
        binary = (rng.random((16, 16, 16)) > 0.7).astype(np.uint8)
        ours, n_ours = native.connected_components(binary, 26)
        ref, n_ref = ndimage.label(
            binary, structure=ndimage.generate_binary_structure(3, 3)
        )
        assert n_ours == n_ref
        # same partition (label values may differ): check bijection
        pairs = set(zip(ours.ravel().tolist(), ref.ravel().tolist()))
        assert len(pairs) == n_ref + 1

    def test_uint64_input(self):
        arr = np.zeros((4, 4, 4), np.uint64)
        arr[0, 0, 0] = 2 ** 40
        labels, count = native.connected_components(arr)
        assert count == 1


class TestWatershed:
    def test_split_by_low_affinity_plane(self):
        aff = np.ones((3, 4, 8, 8), np.float32)
        aff[:, :, :, 4] = 0.05
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.2, 0.5)
        assert count == 2
        assert seg[0, 0, 0] != seg[0, 0, 7]
        assert (seg > 0).all()

    def test_agglomeration_merges_strong_boundary(self):
        aff = np.ones((3, 2, 4, 4), np.float32)
        aff[:, :, :, 2] = 0.8  # boundary below t_high but high mean affinity
        # low merge threshold: regions merge back into one
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.2, 0.5)
        assert count == 1
        # merge threshold above boundary score: stays split
        seg2, count2 = native.watershed_agglomerate(aff, 0.9, 0.2, 0.9)
        assert count2 == 2

    def test_background_stays_zero(self):
        aff = np.full((3, 2, 4, 4), 0.01, np.float32)
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.2, 0.5)
        assert count == 0
        assert (seg == 0).all()


class TestMesher:
    def test_cube_is_closed_surface(self):
        seg = np.zeros((6, 6, 6), np.uint32)
        seg[2:4, 2:4, 2:4] = 1
        vertices, faces = native.mesh_object(seg, 1)
        assert vertices.shape[0] > 0
        # closed genus-0 surface: V - E + F == 2
        edges = set()
        for tri in faces:
            for a, b in ((0, 1), (1, 2), (2, 0)):
                edges.add(tuple(sorted((int(tri[a]), int(tri[b])))))
        assert vertices.shape[0] - len(edges) + faces.shape[0] == 2
        # vertices surround the object (voxel units, 0.5-centered)
        assert vertices.min() >= 1.0 and vertices.max() <= 4.0

    def test_absent_object_empty(self):
        seg = np.zeros((4, 4, 4), np.uint32)
        vertices, faces = native.mesh_object(seg, 7)
        assert vertices.shape[0] == 0 and faces.shape[0] == 0


def test_agglomerate_plugin():
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.flow.plugin import load_plugin

    aff_arr = np.ones((3, 4, 8, 8), np.float32)
    aff_arr[:, :, :, 4] = 0.05
    chunk = Chunk(aff_arr, voxel_offset=(10, 0, 0))
    execute = load_plugin("agglomerate")
    seg = execute(chunk, threshold=0.7)
    assert seg.is_segmentation
    assert seg.voxel_offset.tuple == (10, 0, 0)
    assert np.unique(np.asarray(seg.array)).size == 2


def test_mesh_operator_and_manifest(tmp_path):
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.flow.mesh import MeshOperator, write_manifests

    arr = np.zeros((8, 8, 8), np.uint32)
    arr[1:4, 1:4, 1:4] = 1
    arr[5:7, 5:7, 5:7] = 2
    seg = Chunk(arr, voxel_offset=(0, 0, 0), voxel_size=(40, 4, 4))

    out = str(tmp_path / "mesh")
    op = MeshOperator(out, output_format="precomputed")
    count = op(seg)
    assert count == 2

    import os

    frags = [f for f in os.listdir(out) if f.count(":") == 2]
    assert len(frags) == 2
    assert write_manifests(out) == 2
    import json

    manifest = json.load(open(os.path.join(out, "1:0")))
    assert manifest["fragments"] == [f for f in sorted(frags) if f.startswith("1:")]

    # fragment binary sanity: vertex count header matches payload size
    import struct

    frag_path = os.path.join(out, frags[0])
    blob = open(frag_path, "rb").read()
    (nv,) = struct.unpack("<I", blob[:4])
    assert nv > 0
    assert (len(blob) - 4 - nv * 12) % 12 == 0  # remaining = uint32 faces

    # obj writer
    op2 = MeshOperator(str(tmp_path / "obj"), output_format="obj")
    assert op2(seg) == 2
    obj_files = os.listdir(str(tmp_path / "obj"))
    assert any(f.endswith(".obj") for f in obj_files)
