import numpy as np
import pytest

from chunkflow_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


class TestConnectedComponents:
    def test_multivalue_and_counts(self):
        arr = np.zeros((8, 8, 8), np.uint32)
        arr[:2, :2, :2] = 5
        arr[6:, 6:, 6:] = 5
        arr[4, 4, 4] = 9
        labels, count = native.connected_components(arr)
        assert count == 3
        assert labels[0, 0, 0] != labels[7, 7, 7]
        assert labels[4, 4, 4] not in (labels[0, 0, 0], labels[7, 7, 7])
        assert labels[3, 3, 3] == 0

    def test_connectivity_semantics(self):
        diag = np.zeros((2, 2, 2), np.uint8)
        diag[0, 0, 0] = diag[1, 1, 1] = 1
        assert native.connected_components(diag, 26)[1] == 1
        assert native.connected_components(diag, 18)[1] == 2
        assert native.connected_components(diag, 6)[1] == 2
        edge = np.zeros((1, 2, 2), np.uint8)
        edge[0, 0, 0] = edge[0, 1, 1] = 1
        assert native.connected_components(edge, 18)[1] == 1
        assert native.connected_components(edge, 6)[1] == 2

    def test_matches_scipy_on_binary(self):
        from scipy import ndimage

        rng = np.random.default_rng(0)
        binary = (rng.random((16, 16, 16)) > 0.7).astype(np.uint8)
        ours, n_ours = native.connected_components(binary, 26)
        ref, n_ref = ndimage.label(
            binary, structure=ndimage.generate_binary_structure(3, 3)
        )
        assert n_ours == n_ref
        # same partition (label values may differ): check bijection
        pairs = set(zip(ours.ravel().tolist(), ref.ravel().tolist()))
        assert len(pairs) == n_ref + 1

    def test_uint64_input(self):
        arr = np.zeros((4, 4, 4), np.uint64)
        arr[0, 0, 0] = 2 ** 40
        labels, count = native.connected_components(arr)
        assert count == 1


class TestWatershed:
    def test_split_by_low_affinity_plane(self):
        aff = np.ones((3, 4, 8, 8), np.float32)
        aff[:, :, :, 4] = 0.05
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.2, 0.5)
        assert count == 2
        assert seg[0, 0, 0] != seg[0, 0, 7]
        assert (seg > 0).all()

    def test_agglomeration_merges_strong_boundary(self):
        aff = np.ones((3, 2, 4, 4), np.float32)
        aff[:, :, :, 2] = 0.8  # boundary below t_high but high mean affinity
        # low merge threshold: regions merge back into one
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.2, 0.5)
        assert count == 1
        # merge threshold above boundary score: stays split
        seg2, count2 = native.watershed_agglomerate(aff, 0.9, 0.2, 0.9)
        assert count2 == 2

    def test_background_stays_zero(self):
        aff = np.full((3, 2, 4, 4), 0.01, np.float32)
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.2, 0.5)
        assert count == 0
        assert (seg == 0).all()


class TestMesher:
    def test_cube_is_closed_surface(self):
        seg = np.zeros((6, 6, 6), np.uint32)
        seg[2:4, 2:4, 2:4] = 1
        vertices, faces = native.mesh_object(seg, 1)
        assert vertices.shape[0] > 0
        # closed genus-0 surface: V - E + F == 2
        edges = set()
        for tri in faces:
            for a, b in ((0, 1), (1, 2), (2, 0)):
                edges.add(tuple(sorted((int(tri[a]), int(tri[b])))))
        assert vertices.shape[0] - len(edges) + faces.shape[0] == 2
        # vertices surround the object (voxel units, 0.5-centered)
        assert vertices.min() >= 1.0 and vertices.max() <= 4.0

    def test_absent_object_empty(self):
        seg = np.zeros((4, 4, 4), np.uint32)
        vertices, faces = native.mesh_object(seg, 7)
        assert vertices.shape[0] == 0 and faces.shape[0] == 0


def test_agglomerate_plugin():
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.flow.plugin import load_plugin

    aff_arr = np.ones((3, 4, 8, 8), np.float32)
    aff_arr[:, :, :, 4] = 0.05
    chunk = Chunk(aff_arr, voxel_offset=(10, 0, 0))
    execute = load_plugin("agglomerate")
    seg = execute(chunk, threshold=0.7)
    assert seg.is_segmentation
    assert seg.voxel_offset.tuple == (10, 0, 0)
    assert np.unique(np.asarray(seg.array)).size == 2


def test_mesh_operator_and_manifest(tmp_path):
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.flow.mesh import MeshOperator, write_manifests

    arr = np.zeros((8, 8, 8), np.uint32)
    arr[1:4, 1:4, 1:4] = 1
    arr[5:7, 5:7, 5:7] = 2
    seg = Chunk(arr, voxel_offset=(0, 0, 0), voxel_size=(40, 4, 4))

    out = str(tmp_path / "mesh")
    op = MeshOperator(out, output_format="precomputed")
    count = op(seg)
    assert count == 2

    import os

    frags = [f for f in os.listdir(out) if f.count(":") == 2]
    assert len(frags) == 2
    assert write_manifests(out) == 2
    import json

    manifest = json.load(open(os.path.join(out, "1:0")))
    assert manifest["fragments"] == [f for f in sorted(frags) if f.startswith("1:")]

    # fragment binary sanity: vertex count header matches payload size
    import struct

    frag_path = os.path.join(out, frags[0])
    blob = open(frag_path, "rb").read()
    (nv,) = struct.unpack("<I", blob[:4])
    assert nv > 0
    assert (len(blob) - 4 - nv * 12) % 12 == 0  # remaining = uint32 faces

    # obj writer
    op2 = MeshOperator(str(tmp_path / "obj"), output_format="obj")
    assert op2(seg) == 2
    obj_files = os.listdir(str(tmp_path / "obj"))
    assert any(f.endswith(".obj") for f in obj_files)


# ---------------------------------------------------------------------------
# Mesh quality-parity harness (VERDICT r2 item 5): the reference meshes via
# zmesh marching cubes + quadric simplification (reference flow/mesh.py:78-92);
# this repo substitutes surface-nets + vertex clustering. These tests bound
# the substitution quantitatively against analytic ground truth: two-sided
# Hausdorff distance, enclosed volume, topology (Euler characteristic,
# closedness), and the simplification error at production-style tolerances.
# ---------------------------------------------------------------------------


def _edge_counts(faces):
    from collections import Counter

    edges = Counter()
    for tri in faces:
        for a, b in ((0, 1), (1, 2), (2, 0)):
            edges[tuple(sorted((int(tri[a]), int(tri[b]))))] += 1
    return edges


def _euler_characteristic(vertices, faces):
    return vertices.shape[0] - len(_edge_counts(faces)) + faces.shape[0]


def _is_closed(faces):
    """Every edge shared by exactly two faces (watertight, no borders)."""
    return all(c == 2 for c in _edge_counts(faces).values())


def _signed_volume(vertices, faces):
    v = vertices[faces]  # [F, 3, 3]
    return float(
        np.abs(np.einsum("ij,ij->i", v[:, 0], np.cross(v[:, 1], v[:, 2])).sum())
        / 6.0
    )


def _ball(shape, center, radius):
    zz, yy, xx = np.meshgrid(*(np.arange(s) for s in shape), indexing="ij")
    d2 = (zz - center[0]) ** 2 + (yy - center[1]) ** 2 + (xx - center[2]) ** 2
    return (d2 <= radius**2).astype(np.uint32)


class TestMeshQuality:
    def test_sphere_hausdorff_volume_topology(self):
        from scipy.spatial import cKDTree

        R, c = 20.0, 31.5
        seg = _ball((64, 64, 64), (c, c, c), R)
        vertices, faces = native.mesh_object(seg, 1)  # xyz voxel coords
        assert vertices.shape[0] > 0 and _is_closed(faces)
        assert _euler_characteristic(vertices, faces) == 2

        # one-sided Hausdorff: every mesh vertex within 1 voxel of the
        # analytic sphere (surface nets localize the boundary sub-voxel)
        # vertex coords: voxel center == integer index (probe: a
        # single voxel at index 3 meshes to the cube [2.5, 3.5]^3)
        center_xyz = np.array([c, c, c])
        radial = np.linalg.norm(vertices - center_xyz, axis=1)
        assert np.abs(radial - R).max() <= 1.0, np.abs(radial - R).max()

        # other side: every analytic-surface sample has a mesh vertex
        # within 1.75 voxels (vertex spacing on the dual grid is ~1)
        rng = np.random.default_rng(0)
        pts = rng.normal(size=(512, 3))
        pts = center_xyz + R * pts / np.linalg.norm(pts, axis=1, keepdims=True)
        d, _ = cKDTree(vertices).query(pts)
        assert d.max() <= 1.75, d.max()

        # enclosed volume within 10% of (4/3) pi R^3
        vol = _signed_volume(vertices, faces)
        true = 4.0 / 3.0 * np.pi * R**3
        assert abs(vol - true) / true <= 0.10, (vol, true)

    def test_torus_topology_and_hausdorff(self):
        Rmaj, rmin = 14.0, 5.0
        shape = (24, 48, 48)
        cz, cy, cx = 11.5, 23.5, 23.5
        zz, yy, xx = np.meshgrid(*(np.arange(s) for s in shape), indexing="ij")
        ring = np.sqrt((yy - cy) ** 2 + (xx - cx) ** 2) - Rmaj
        seg = ((ring**2 + (zz - cz) ** 2) <= rmin**2).astype(np.uint32)
        vertices, faces = native.mesh_object(seg, 1)
        assert vertices.shape[0] > 0 and _is_closed(faces)
        # genus-1: V - E + F == 0
        assert _euler_characteristic(vertices, faces) == 0
        # Hausdorff (mesh -> analytic surface): distance from each vertex
        # to the torus surface, in xyz coords (vertices are xyz!)
        vx, vy, vz = vertices[:, 0], vertices[:, 1], vertices[:, 2]
        ring_v = np.sqrt((vy - cy) ** 2 + (vx - cx) ** 2) - Rmaj
        dist = np.abs(np.sqrt(ring_v**2 + (vz - cz) ** 2) - rmin)
        assert dist.max() <= 1.0, dist.max()

    def test_touching_blobs_stay_separate_and_closed(self):
        # two labels sharing a planar interface: each mesh closed, neither
        # bleeding into the other's half-space by more than the sub-voxel
        # localization bound
        seg = np.zeros((16, 16, 16), np.uint32)
        ball = _ball((16, 16, 16), (7.5, 7.5, 7.5), 6.0)
        seg[:8] = ball[:8]
        seg[8:] = ball[8:] * 2
        v1, f1 = native.mesh_object(seg, 1)
        v2, f2 = native.mesh_object(seg, 2)
        assert v1.shape[0] > 0 and v2.shape[0] > 0
        assert _is_closed(f1) and _is_closed(f2)
        # z is the third xyz component; interface plane at z=8.0
        assert v1[:, 2].max() <= 8.0 + 0.5
        assert v2[:, 2].min() >= 8.0 - 0.5

    def test_simplification_error_at_production_tolerance(self):
        from chunkflow_tpu.flow.mesh import simplify_mesh
        from scipy.spatial import cKDTree

        # production framing: 4 nm isotropic voxels, 8 nm simplification
        # cell (reference max_simplification_error class of tolerances)
        R, c, nm = 20.0, 31.5, 4.0
        seg = _ball((64, 64, 64), (c, c, c), R)
        vertices, faces = native.mesh_object(seg, 1)
        vertices_nm = vertices * nm
        cell = 8.0
        sv, sf = simplify_mesh(vertices_nm, faces, cell)
        # real reduction at this tolerance
        assert sv.shape[0] <= 0.7 * vertices_nm.shape[0], (
            sv.shape[0], vertices_nm.shape[0],
        )
        assert sf.shape[0] > 0
        # error bound: pre-simplification Hausdorff (1 voxel = 4 nm) plus
        # the clustering cell diagonal
        center_nm = np.array([c] * 3) * nm
        radial = np.linalg.norm(sv - center_nm, axis=1)
        bound = 1.0 * nm + cell * np.sqrt(3.0)
        assert np.abs(radial - R * nm).max() <= bound, (
            np.abs(radial - R * nm).max(), bound,
        )
        # coverage survives simplification: analytic samples still have a
        # nearby simplified vertex (cell-scale resolution)
        rng = np.random.default_rng(1)
        pts = rng.normal(size=(256, 3))
        pts = center_nm + R * nm * pts / np.linalg.norm(
            pts, axis=1, keepdims=True
        )
        d, _ = cKDTree(sv).query(pts)
        assert d.max() <= 2 * cell, d.max()


# ---------------------------------------------------------------------------
# Agglomeration quality-parity harness (VERDICT r2 item 6): the reference
# agglomerates via waterz (reference plugins/agglomerate.py:35-43); this repo
# substitutes native/src/watershed.cpp. Instead of a committed fixture
# segmentation, ground truth is ANALYTIC (a deterministic Voronoi partition)
# and the affinity map is derived from it — the floors below are therefore
# absolute quality numbers, not self-comparisons.
# ---------------------------------------------------------------------------


def _voronoi_affinity_fixture(noise, inside, boundary, seed=0):
    from chunkflow_tpu.chunk import AffinityMap

    rng = np.random.default_rng(seed)
    shape = (32, 64, 64)
    n_objects = 12
    seeds = np.stack([rng.uniform(0, s, n_objects) for s in shape], axis=1)
    zz, yy, xx = np.meshgrid(*(np.arange(s) for s in shape), indexing="ij")
    pts = np.stack([zz, yy, xx], -1).reshape(-1, 3)
    d2 = ((pts[:, None, :] - seeds[None]) ** 2).sum(-1)
    gt = (d2.argmin(1) + 1).reshape(shape).astype(np.uint32)
    aff = np.asarray(
        AffinityMap.from_segmentation(gt, inside=inside, boundary=boundary)
        .array
    )
    aff = aff + rng.normal(0, noise, aff.shape).astype(np.float32)
    return np.clip(aff, 0, 1).astype(np.float32), gt


class TestAgglomerationQuality:
    def test_clean_affinities_exact_recovery(self):
        from chunkflow_tpu.chunk.segmentation import Segmentation

        aff, gt = _voronoi_affinity_fixture(0.05, 0.9, 0.1)
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.3, 0.5)
        assert count == 12
        m = Segmentation(seg).evaluate(gt)
        assert m["adjusted_rand_index"] >= 0.99, m
        assert m["voi_split"] + m["voi_merge"] <= 0.02, m

    def test_noisy_affinities_quality_floor(self):
        from chunkflow_tpu.chunk.segmentation import Segmentation

        # sigma-0.15 noise on 0.85/0.15 affinities; measured 2026-07-30
        # (hierarchical rescoring agglomeration): 12/12 objects, ARI 1.0,
        # VOI 0.0 — floors set with margin so a regression fails while the
        # exact numbers stay on record here. (The pre-rescoring
        # single-shot scoring measured ARI 0.775 on this fixture.)
        aff, gt = _voronoi_affinity_fixture(0.15, 0.85, 0.15)
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.3, 0.5)
        assert 10 <= count <= 14, count
        m = Segmentation(seg).evaluate(gt)
        assert m["rand_index"] >= 0.99, m
        assert m["adjusted_rand_index"] >= 0.95, m
        assert m["voi_split"] + m["voi_merge"] <= 0.10, m

    def test_dropout_noise_quality_floor(self):
        """Random low-affinity dropout inside objects — the fixture that
        collapsed single-shot scoring (ARI 0.03, everything chain-merged
        into 2 objects). With waterz-style rescoring after every merge:
        measured ARI 0.9999, VOI 0.0006 (2026-07-30)."""
        from chunkflow_tpu.chunk.segmentation import Segmentation

        rng = np.random.default_rng(0)
        aff, gt = _voronoi_affinity_fixture(0.0, 0.85, 0.15)
        drop = rng.random(aff.shape) < 0.05
        aff = np.where(drop, np.float32(0.3), aff)
        aff += rng.normal(0, 0.15, aff.shape).astype(np.float32)
        aff = np.clip(aff, 0, 1).astype(np.float32)
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.3, 0.5)
        assert 10 <= count <= 24, count
        m = Segmentation(seg).evaluate(gt)
        assert m["adjusted_rand_index"] >= 0.95, m
        assert m["voi_split"] + m["voi_merge"] <= 0.10, m

    def test_quantized_affinities_quality_floor(self):
        """uint8-quantized affinities (save-precomputed then agglomerate)
        make exact ties ubiquitous; the steepest-ascent tie rule (ALL
        tied maximal edges contract) must not degrade quality. Measured
        ARI 1.0 on both fixtures (2026-07-30)."""
        from chunkflow_tpu.chunk.segmentation import Segmentation

        for fixture, params in [
            (_voronoi_affinity_fixture(0.05, 0.9, 0.1), (0.9, 0.3, 0.5)),
            (_voronoi_affinity_fixture(0.15, 0.85, 0.15, seed=1),
             (0.9, 0.2, 0.6)),
        ]:
            aff, gt = fixture
            q = (np.round(aff * 255) / 255).astype(np.float32)
            seg, count = native.watershed_agglomerate(q, *params)
            assert count == 12, count
            m = Segmentation(seg).evaluate(gt)
            assert m["adjusted_rand_index"] >= 0.95, m

    def test_plateau_merges_as_one(self):
        """Documented steepest-ascent tie semantics (canonical
        zwatershed): a constant-affinity plateau is one fragment and
        bridges the seed cores it touches. Real affinity maps never hold
        an exactly-constant plateau spanning two true objects; the
        quantized-fixture test above shows realistic ties are harmless."""
        aff = np.full((3, 8, 16, 32), 0.5, np.float32)
        aff[:, :, :, :6] = 0.995
        aff[:, :, :, 26:] = 0.995
        seg, count = native.watershed_agglomerate(
            aff, 0.99, 0.3, 2.0)  # merge_threshold 2.0: no agglomeration
        assert count == 1, count
        assert seg[0, 0, 0] == seg[0, 0, -1]


class TestAgglomerationThinProcesses:
    def test_parallel_tubes_do_not_merge(self):
        """EM's classic failure mode: thin elongated processes running in
        parallel with weak boundaries between them. Four 4-voxel-wide
        tubes along x, separated by 1-voxel boundaries: agglomeration
        must keep them apart while healing internal noise."""
        from chunkflow_tpu.chunk.segmentation import Segmentation

        rng = np.random.default_rng(2)
        shape = (8, 20, 64)
        gt = np.zeros(shape, np.uint32)
        for i in range(4):
            gt[:, i * 5: i * 5 + 4, :] = i + 1  # rows i*5+4 stay 0 (gap)
        aff = np.empty((3,) + shape, np.float32)
        for c, ax in enumerate((0, 1, 2)):
            same = np.ones(shape, bool)
            sl_a = [slice(None)] * 3
            sl_b = [slice(None)] * 3
            sl_a[ax] = slice(1, None)
            sl_b[ax] = slice(0, -1)
            both = (gt[tuple(sl_a)] == gt[tuple(sl_b)]) & (gt[tuple(sl_a)] > 0)
            same[tuple(sl_a)] = both
            aff[c] = np.where(same & (gt > 0), 0.85, 0.12)
        aff += rng.normal(0, 0.1, aff.shape).astype(np.float32)
        aff = np.clip(aff, 0, 1).astype(np.float32)
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.3, 0.5)
        m = Segmentation(seg).evaluate(gt)
        # no cross-tube merging: VOI-merge stays near zero
        assert m["voi_merge"] <= 0.05, m
        assert m["adjusted_rand_index"] >= 0.95, m


def test_mesh_chunk_anisotropic_nm_scaling():
    """mesh_chunk output is in global nanometers: an isotropic voxel-space
    ball meshed with anisotropic voxel_size must become the matching
    ellipsoid in nm, offset into global coordinates (reference
    flow/mesh.py:95 vertex-offset semantics)."""
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.flow.mesh import mesh_chunk

    R, c = 10.0, 15.5
    seg_arr = _ball((32, 32, 32), (c, c, c), R)
    seg = Chunk(seg_arr, voxel_offset=(100, 200, 300), voxel_size=(40, 8, 8))
    meshes = mesh_chunk(seg)
    assert set(meshes) == {1}
    vertices, faces = meshes[1]
    # xyz in nm; normalize back to voxel units per axis and check the
    # radial bound against the analytic sphere
    center_nm = np.array([(300 + c) * 8.0, (200 + c) * 8.0, (100 + c) * 40.0])
    scale = np.array([8.0, 8.0, 40.0])
    radial = np.linalg.norm((vertices - center_nm) / scale, axis=1)
    assert np.abs(radial - R).max() <= 1.0, np.abs(radial - R).max()


class TestWatershedThreading:
    """z-slab threading (VERDICT r4 #3) must be a pure implementation
    detail: the partition produced with N worker threads equals the
    sequential one (seam z-edges are stitched after the parallel join,
    and per-pair RAG sums merge in slab order)."""

    def test_threaded_matches_sequential(self, monkeypatch):
        """Guarantees under test: (a) a fixed thread count is bit-exact
        deterministic; (b) across thread counts the partition is
        near-identical — per-pair RAG double sums combine in slab order,
        so fp non-associativity may flip a score by an ulp, but any
        union-find race would corrupt whole components and crater ARI."""
        from chunkflow_tpu.chunk.segmentation import Segmentation

        rng = np.random.default_rng(11)
        aff = np.clip(
            rng.normal(0.5, 0.25, (3, 16, 48, 48)), 0, 1
        ).astype(np.float32)
        monkeypatch.setenv("CHUNKFLOW_NATIVE_THREADS", "1")
        seg1, n1 = native.watershed_agglomerate(aff, 0.95, 0.2, 0.6)
        for nt in ("2", "4", "7"):
            monkeypatch.setenv("CHUNKFLOW_NATIVE_THREADS", nt)
            segn, nn = native.watershed_agglomerate(aff, 0.95, 0.2, 0.6)
            rerun, _ = native.watershed_agglomerate(aff, 0.95, 0.2, 0.6)
            np.testing.assert_array_equal(segn, rerun)  # fixed-nt exact
            assert abs(nn - n1) <= max(2, n1 // 100), (nt, nn, n1)
            m = Segmentation(segn).evaluate(seg1)
            assert m["adjusted_rand_index"] >= 0.9999, (nt, m)

    def test_thread_count_exceeding_depth(self, monkeypatch):
        # more workers than z-planes/2: must clamp, not crash or distort
        aff, gt = _voronoi_affinity_fixture(0.05, 0.9, 0.1)
        monkeypatch.setenv("CHUNKFLOW_NATIVE_THREADS", "64")
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.3, 0.5)
        assert count == 12


class TestCC3DThreading:
    def test_threaded_matches_sequential(self, monkeypatch):
        """cc3d z-slab threading is invisible: identical labels (values,
        not just partition — first-encounter raster numbering is
        sequential) for every thread count, all connectivities."""
        rng = np.random.default_rng(4)
        arr = rng.integers(0, 3, (16, 32, 32)).astype(np.uint32)
        for conn in (6, 18, 26):
            monkeypatch.setenv("CHUNKFLOW_NATIVE_THREADS", "1")
            seq, n_seq = native.connected_components(arr, connectivity=conn)
            for nt in ("2", "5"):
                monkeypatch.setenv("CHUNKFLOW_NATIVE_THREADS", nt)
                par, n_par = native.connected_components(
                    arr, connectivity=conn)
                assert n_par == n_seq, (conn, nt)
                np.testing.assert_array_equal(par, seq)

    def test_component_spanning_all_seams(self, monkeypatch):
        # one thin column through every slab plus per-slab islands: the
        # seam stitch must fuse the column into ONE component
        monkeypatch.setenv("CHUNKFLOW_NATIVE_THREADS", "4")
        arr = np.zeros((16, 8, 8), np.uint8)
        arr[:, 4, 4] = 1  # column crossing all 3 seams
        arr[3, 0, 0] = arr[7, 0, 0] = arr[12, 0, 0] = 1  # isolated islands
        labels, count = native.connected_components(arr, connectivity=6)
        assert count == 4, count
        assert len(np.unique(labels[:, 4, 4])) == 1


class TestScoringAndFragments:
    """waterz-parity agglomeration options (reference
    plugins/agglomerate.py: scoring_function, fragments)."""

    def test_max_min_scoring_semantics(self):
        # two blocks; boundary affinities mixed 0.9 / 0.1 -> mean 0.5
        aff = np.ones((3, 2, 4, 8), np.float32)
        aff[:, :, :, 4] = 0.1
        aff[2, 0, 0, 4] = 0.9  # one strong edge on the boundary
        # mean ~ 0.15-0.2 < 0.6: stays split
        _, n_mean = native.watershed_agglomerate(
            aff, 0.95, 0.01, 0.6, scoring="mean")
        assert n_mean == 2
        # max = 0.9 >= 0.6: merges
        _, n_max = native.watershed_agglomerate(
            aff, 0.95, 0.01, 0.6, scoring="max")
        assert n_max == 1
        # min = 0.1 < 0.6: stays split even with threshold below mean
        aff2 = np.ones((3, 2, 4, 8), np.float32)
        aff2[:, :, :, 4] = 0.7
        aff2[2, 0, 0, 4] = 0.1
        _, n_min = native.watershed_agglomerate(
            aff2, 0.95, 0.01, 0.5, scoring="min")
        assert n_min == 2
        _, n_mean2 = native.watershed_agglomerate(
            aff2, 0.95, 0.01, 0.5, scoring="mean")
        assert n_mean2 == 1

    def test_fragments_input_matches_full_run(self):
        """merge_threshold=0 returns raw fragments; feeding them back via
        fragments= must reproduce the full run bit-for-bit (the fragment
        ids are already first-encounter-compact, so the RAG sums match)."""
        rng = np.random.default_rng(21)
        aff = np.clip(
            rng.normal(0.6, 0.2, (3, 8, 32, 32)), 0, 1
        ).astype(np.float32)
        frag_seg, n_frag = native.watershed_agglomerate(aff, 0.9, 0.2, 0.0)
        assert n_frag > 1
        full, n_full = native.watershed_agglomerate(aff, 0.9, 0.2, 0.55)
        via_frags, n_via = native.watershed_agglomerate(
            aff, merge_threshold=0.55, fragments=frag_seg)
        assert n_via == n_full
        np.testing.assert_array_equal(via_frags, full)

    def test_fragments_arbitrary_labels(self):
        # non-compact labels (e.g. global supervoxel ids) compact by
        # first raster encounter; background 0 stays 0
        aff = np.ones((3, 2, 4, 8), np.float32)
        aff[2, :, :, 4] = 0.9  # x-edges crossing the fragment boundary
        frags = np.zeros((2, 4, 8), np.uint32)
        frags[:, 1:, :4] = 7_000_001  # touching fragments at x=3|4,
        frags[:, 1:, 4:] = 123        # row y=0 stays background
        seg, count = native.watershed_agglomerate(
            aff, merge_threshold=0.8, fragments=frags)
        assert count == 1  # mean boundary 0.9 >= 0.8 merges them
        assert (seg[:, 0, :] == 0).all()  # background preserved
        seg2, count2 = native.watershed_agglomerate(
            aff, merge_threshold=0.95, fragments=frags)
        assert count2 == 2
        assert seg2[0, 1, 0] == 1 and seg2[0, 1, 7] == 2  # raster order

    def test_bad_scoring_rejected(self):
        aff = np.ones((3, 2, 4, 4), np.float32)
        with pytest.raises(ValueError, match="scoring"):
            native.watershed_agglomerate(aff, scoring="median")

    def test_fragments_label_overflow_rejected(self):
        # int64 supervoxel ids beyond uint32 must be rejected, not
        # silently wrapped onto each other (silent fusion)
        aff = np.ones((3, 2, 4, 4), np.float32)
        frags = np.zeros((2, 4, 4), np.int64)
        frags[:, :, :2] = 5
        frags[:, :, 2:] = (1 << 32) + 5
        with pytest.raises(ValueError, match="uint32"):
            native.watershed_agglomerate(
                aff, merge_threshold=0.5, fragments=frags)
        with pytest.raises(TypeError, match="integer"):
            native.watershed_agglomerate(
                aff, merge_threshold=0.5,
                fragments=frags.astype(np.float32))

    def test_plugin_scoring_function_and_flip(self):
        from chunkflow_tpu.chunk.base import Chunk
        from chunkflow_tpu.flow.plugin import load_plugin

        execute = load_plugin("agglomerate")
        aff_zyx = np.ones((3, 4, 8, 8), np.float32)
        aff_zyx[:, :, :, 4] = 0.05
        chunk = Chunk(aff_zyx.copy())
        # waterz spelling parses to mean
        seg = execute(
            chunk, threshold=0.7,
            scoring_function="OneMinus<MeanAffinity<RegionGraphType, ScoreValue>>",
        )
        assert np.unique(np.asarray(seg.array)).size == 2
        # the reference's xyz channel order + flip_channel=True must
        # match the zyx run
        chunk_xyz = Chunk(np.ascontiguousarray(aff_zyx[::-1]))
        seg_flip = execute(chunk_xyz, threshold=0.7, flip_channel=True)
        np.testing.assert_array_equal(
            np.asarray(seg_flip.array), np.asarray(seg.array))
        with pytest.raises(ValueError, match="scoring_function"):
            execute(chunk, scoring_function="Quantile<50>")


class TestQuantileScoring:
    def test_median_vs_mean_semantics(self):
        # boundary: 3 weak edges (0.1) + 7 strong (0.9) -> mean 0.66,
        # median ~0.9: a threshold of 0.8 merges only under quantile50
        aff = np.ones((3, 2, 4, 8), np.float32)
        aff[2, :, :, 4] = 0.9
        aff[2, 0, :3, 4] = 0.1  # 3 of 8 boundary edges weak... 2*4=8 edges
        _, n_mean = native.watershed_agglomerate(
            aff, 0.95, 0.01, 0.8, scoring="mean")
        assert n_mean == 2
        _, n_q50 = native.watershed_agglomerate(
            aff, 0.95, 0.01, 0.8, scoring="quantile50")
        assert n_q50 == 1
        # quantile0 ~ min: the weakest edge (0.1) governs
        _, n_q0 = native.watershed_agglomerate(
            aff, 0.95, 0.01, 0.5, scoring="quantile0")
        assert n_q0 == 2

    def test_quantile_matches_full_run_via_fragments(self):
        rng = np.random.default_rng(33)
        aff = np.clip(rng.normal(0.6, 0.2, (3, 8, 24, 24)), 0, 1
                      ).astype(np.float32)
        frag_seg, _ = native.watershed_agglomerate(aff, 0.9, 0.2, 0.0)
        full, n_full = native.watershed_agglomerate(
            aff, 0.9, 0.2, 0.6, scoring="quantile50")
        via, n_via = native.watershed_agglomerate(
            aff, merge_threshold=0.6, scoring="quantile50",
            fragments=frag_seg)
        assert n_via == n_full
        np.testing.assert_array_equal(via, full)

    def test_plugin_waterz_quantile_spelling(self):
        from chunkflow_tpu.chunk.base import Chunk
        from chunkflow_tpu.flow.plugin import load_plugin

        execute = load_plugin("agglomerate")
        aff = np.ones((3, 4, 8, 8), np.float32)
        aff[:, :, :, 4] = 0.05
        seg = execute(
            Chunk(aff), threshold=0.7,
            scoring_function=(
                "OneMinus<QuantileAffinity<RegionGraphType, "
                "ScoreValue, 50, false>>"),
        )
        assert np.unique(np.asarray(seg.array)).size == 2

    def test_bad_quantile_rejected(self):
        aff = np.ones((3, 2, 4, 4), np.float32)
        with pytest.raises(ValueError, match="scoring"):
            native.watershed_agglomerate(aff, scoring="quantile101")
