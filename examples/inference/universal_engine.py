"""Example --model-path file for the universal engine.

Usage:
    chunkflow ... inference --framework universal \
        --model-path examples/inference/universal_engine.py ...

Contract (chunkflow_tpu/inference/engines.py:create_universal_engine,
reference patch/universal.py): expose
``create_engine(weight_path, input_patch_size, output_patch_size,
num_input_channels, num_output_channels) -> (params, apply)`` where
``apply(params, batch)`` maps [B, Ci, *pin] -> [B, Co, *pout] in jax.
This one inverts intensities and center-crops — any jax-traceable code
works, including wrapping models from other ecosystems.
"""
import jax.numpy as jnp


def create_engine(weight_path, input_patch_size, output_patch_size,
                  num_input_channels, num_output_channels):
    del weight_path
    margin = tuple(
        (i - o) // 2 for i, o in zip(input_patch_size, output_patch_size)
    )

    def apply(params, batch):
        sl = (slice(None), slice(0, 1)) + tuple(
            slice(m, m + o) for m, o in zip(margin, output_patch_size)
        )
        center = 1.0 - batch[sl]
        return jnp.broadcast_to(
            center, (batch.shape[0], num_output_channels) + tuple(output_patch_size)
        )

    return (), apply
