"""Example --model-path file: a custom Flax model for the flax engine.

Usage:
    chunkflow ... inference --framework flax \
        --model-path examples/inference/custom_flax_model.py \
        --input-patch-size 16 128 128 ...

The file must expose ``create_model(num_input_channels,
num_output_channels)`` returning a Flax module mapping NDHWC -> NDHWC.
(Engine contract: chunkflow_tpu/inference/engines.py:create_flax_engine.)
"""
import flax.linen as nn
import jax


class TinyNet(nn.Module):
    out_channels: int

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(16, (3, 3, 3), padding="SAME")(x)
        x = nn.elu(x)
        x = nn.Conv(self.out_channels, (3, 3, 3), padding="SAME")(x)
        return jax.nn.sigmoid(x)


def create_model(num_input_channels, num_output_channels):
    del num_input_channels  # inferred from the input by flax
    return TinyNet(out_channels=num_output_channels)
