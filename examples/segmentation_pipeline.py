"""End-to-end segmentation at toy scale: affinity inference -> native
watershed + mean-affinity agglomeration -> connected components -> mesh.

The library-API version of BASELINE config 3 (the CLI spelling is
`... inference ... plugin -f agglomerate connected-components mesh`).
Runs anywhere:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu PYTHONPATH=. \
        python examples/segmentation_pipeline.py
"""
import numpy as np

from chunkflow_tpu import native
from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.chunk.segmentation import Segmentation
from chunkflow_tpu.inference import Inferencer


def main():
    # 1) affinity inference (identity engine keeps the example fast and
    #    deterministic; swap framework="flax", model_variant="tpu" and a
    #    --dtype bfloat16 for the real model)
    rng = np.random.default_rng(0)
    image = rng.random((16, 64, 64)).astype(np.float32)
    inferencer = Inferencer(
        input_patch_size=(8, 32, 32),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    affs = np.asarray(inferencer(Chunk(image)).array, dtype=np.float32)
    print(f"affinities: {affs.shape} in [{affs.min():.2f}, {affs.max():.2f}]")

    # 2) watershed fragments + hierarchical agglomeration (host C++)
    seg, n_seg = native.watershed_agglomerate(
        affs, t_high=0.9999, t_low=0.2, merge_threshold=0.7
    )
    print(f"agglomeration: {n_seg} segments")

    # 2b) production spelling for MANY chunks: stream(postprocess=...)
    #     runs the host watershed of chunk i in a worker thread while
    #     chunk i+1's program executes on device, so the CPU stage the
    #     reference ships to separate fleets hides behind chip time
    tasks = [Chunk(rng.random((16, 64, 64)).astype(np.float32),
                   voxel_offset=(16 * i, 0, 0)) for i in range(3)]

    def agglomerate(out_chunk):
        arr = np.asarray(out_chunk.array, dtype=np.float32)
        return native.watershed_agglomerate(
            arr, t_high=0.9999, t_low=0.2, merge_threshold=0.7
        )
    for (seg_i, n_i), task in zip(
        inferencer.stream(iter(tasks), postprocess=agglomerate), tasks
    ):
        print(f"  streamed task z={task.voxel_offset[0]}: {n_i} segments")

    # 3) connected components split spatially-disconnected labels
    cc, n_cc = native.connected_components(seg)
    print(f"connected components: {n_cc}")

    # 4) quality metrics against any ground truth (here: itself — 1.0)
    metrics = Segmentation(cc).evaluate(cc)
    print(f"self-ARI sanity: {metrics['adjusted_rand_index']:.3f}")

    # 5) mesh the largest object (surface nets, host C++)
    if n_cc:
        ids, counts = np.unique(cc[cc > 0], return_counts=True)
        obj = int(ids[counts.argmax()])
        verts, faces = native.mesh_object(cc, obj)
        print(f"mesh of object {obj}: {len(verts)} vertices, "
              f"{len(faces)} faces")


if __name__ == "__main__":
    main()
