"""Multi-chip and multi-host patch-parallel inference.

On a real TPU slice the mesh covers the local chips automatically; on a
laptop, emulate 8 chips with the virtual CPU mesh:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu PYTHONPATH=. \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/multichip_inference.py

For a multi-HOST pod slice, call `multihost.initialize()` first (one
process per host); `Inferencer(sharding="patch")` then automatically
routes through global arrays — see docs/distributed.md.
"""
import numpy as np

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.inference import Inferencer
from chunkflow_tpu.parallel.distributed import make_mesh


def main():
    import jax

    mesh = make_mesh()
    print(f"mesh: {mesh.devices.size} x {jax.devices()[0].platform}")

    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random((16, 64, 64)).astype(np.float32))

    # unified mesh engine (docs/multichip.md): patch-parallel — chunk
    # replicated, each chip forwards its share of patch batches, the
    # reference blend accumulation replays verbatim (bitwise identical
    # to the single-device path; CHUNKFLOW_MESH=auto does the same)
    sharded = Inferencer(
        input_patch_size=(8, 32, 32),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=1,
        mesh=f"data={mesh.devices.size}" if mesh.devices.size > 1 else "1",
        crop_output_margin=False,
    )
    out = np.asarray(sharded(chunk).array)

    # bitwise parity with the single-device path (same weights)
    single = Inferencer(
        input_patch_size=(8, 32, 32),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=1,
        crop_output_margin=False,
    )
    ref = np.asarray(single(chunk).array)
    diff = float(np.abs(out - ref).max())
    print(f"sharded vs single-device max-abs-diff: {diff:.2e}")
    assert np.array_equal(out, ref), "mesh output diverged bitwise"


if __name__ == "__main__":
    main()
